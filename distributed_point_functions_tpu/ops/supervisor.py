"""Resilient job supervisor: dispatch deadlines, chunk-journal
checkpoint/resume, and full-surface mode-aware degradation.

ISSUE 7 closes the three failure modes the integrity/degradation stack of
PR 1 did not reach:

* **Hangs.** The tunnel this repo measures through has been *dead* (not
  erroring — silent) since round 5; a hung ``block_until_ready`` today
  wedges the executor forever. The **dispatch-deadline watchdog** here
  bounds every per-chunk launch and finalize wait (``DPF_TPU_DEADLINE``
  env / ``DegradationPolicy.deadline_seconds``) and classifies an expiry
  as ``UnavailableError`` — hangs enter the existing retry→degrade path.
  Disabled, the guard is one ``None`` check per chunk and zero device
  programs.

* **Mid-run death.** The 128-level heavy-hitters advance runs ~27 min in
  the acceptance suite; a killed job used to restart from zero. The
  **chunk journal** (:class:`ChunkJournal`) is a crash-safe append-only
  JSONL file: one line per *verified* chunk (the sentinel/spot check ran
  before the append), a job fingerprint (keys digest + params + mode) so
  a stale journal can never feed a different job, and an atomic ``done``
  marker on completion. A restarted ``full_domain_evaluate_robust(...,
  journal=path)`` / ``evaluate_levels_fused_robust`` re-dispatches only
  the unverified chunks — pinned by dispatch-audit program counts.

* **Mode blindness.** The PR 1 chain walked flat backends
  (pallas→jax→numpy); the megakernel modes of PRs 3-5 sat outside it, so
  a Mosaic miscompile in the slab kernel skipped straight off the device.
  The chain (ops/degrade.py ``_run_chain``) now walks **(mode, backend)
  rungs** and this module composes the per-op chains::

      full-domain fold / PIR   megakernel → fold/pallas → fold/jax → numpy
      EvaluateAt / DCF / MIC   walkkernel → walk/pallas → walk/jax → numpy
      hierarchical             hierkernel → fused/pallas → fused/jax → numpy

  plus the four robust wrappers PR 1 never had: ``batch_evaluate_robust``
  (DCF), ``mic_batch_eval_robust`` / ``gate_batch_eval_robust`` (the
  whole FSS gate family rides the DCF chain through its shared
  ``GatePlan`` flatten, ISSUE 9), ``evaluate_levels_fused_robust``
  (resuming from the exported ``BatchedContext`` state rather than
  re-walking verified prefix windows), and ``pir_query_batch_robust``
  (re-preparing the ``PreparedPirDatabase`` when a mode downgrade
  invalidates its ``order=`` layout). Every rung transition emits the
  PR 6 ``decision(source="degrade")`` record.

Verification: the full-domain / EvaluateAt / PIR wrappers keep their
wire-riding sentinel probes (utils/integrity.py). DCF, MIC and the
hierarchical wrapper — whose entry points have no probe seam — use
**host-oracle spot checks**: the last key row of every device-rung result
is recomputed on the host engine (the sentinel cost profile: one key's
worth of oracle work per call), and a mismatch raises
``DataCorruptionError`` into the chain. ``DegradationPolicy.verify=False``
disables both forms.

``tools/chaos_soak.py`` drives seeded fault schedules (corruption, OOM,
unavailable, device_hang) across all six entry points against these
wrappers and asserts bit-exact recovery plus telemetry completeness;
``ci.sh faults`` runs a short deterministic pass.
"""

from __future__ import annotations

import base64
import contextlib
import hashlib
import json
import os
import threading
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Optional, Sequence, Tuple

import numpy as np

from ..utils import envflags, faultinject, integrity
from ..utils import telemetry as _tm
from ..utils.errors import (
    DataCorruptionError,
    InvalidArgumentError,
    UnavailableError,
)
from . import degrade
from .degrade import (  # noqa: F401  (re-exported: the one-stop surface)
    DEFAULT_POLICY,
    DegradationPolicy,
    RungUnsupported,
    Rung,
    evaluate_at_robust,
    rung_label,
)

# ---------------------------------------------------------------------------
# Dispatch-deadline watchdog
# ---------------------------------------------------------------------------

_tls = threading.local()
_UNSET = object()


def deadline_default() -> Optional[float]:
    """DPF_TPU_DEADLINE seconds (float), None/unset/<=0 = no deadline."""
    seconds = envflags.env_float("DPF_TPU_DEADLINE", None)
    if seconds is None:
        return None
    return seconds if seconds > 0 else None


def current_deadline() -> Optional[float]:
    """The deadline bounding device waits on THIS thread: a
    `deadline_scope` override when inside one (how
    ``DegradationPolicy.deadline_seconds`` arms the chain walk), else the
    process env default. None = unbounded (the disabled fast path — one
    TLS read and one env lookup per chunk, no threads, no programs)."""
    val = getattr(_tls, "deadline", _UNSET)
    if val is not _UNSET:
        return val
    return deadline_default()


@contextlib.contextmanager
def deadline_scope(seconds: Optional[float]):
    """Arms (or explicitly disables, seconds=0) the dispatch deadline for
    the with-block. seconds=None is a pass-through: the env default keeps
    ruling — the DegradationPolicy convention."""
    if seconds is None:
        yield
        return
    prev = getattr(_tls, "deadline", _UNSET)
    _tls.deadline = float(seconds) if seconds > 0 else None
    try:
        yield
    finally:
        if prev is _UNSET:
            del _tls.deadline
        else:
            _tls.deadline = prev


def _deadline_expired(what: str, seconds: float, op, backend) -> None:
    _tm.counter("supervisor.deadline_expired", op=op)
    integrity.emit_event(
        "deadline-expired",
        f"{what} did not complete within the {seconds:g}s dispatch "
        "deadline — treating the device as unavailable "
        "(the hung wait continues on a daemon thread)",
        backend or "",
        op=op,
        what=what,
        deadline_seconds=seconds,
    )
    raise UnavailableError(
        f"DEADLINE_EXCEEDED: {what} did not complete within {seconds:g}s "
        "(DPF_TPU_DEADLINE / DegradationPolicy.deadline_seconds)"
    )


def work_abandoned() -> bool:
    """True on a watchdog thread whose `deadline_call` already gave up.

    A hung *blocking* call cannot be cancelled, but injected hangs (and
    real ones that eventually return) leave a zombie thread that would
    otherwise proceed with real device work behind the retry — racing the
    recovered execution and keeping runtime state alive into interpreter
    teardown. Guarded code paths (the pipelined executor's launch/finalize
    bodies, the hierarchical attempt) poll this after each potential hang
    point and abort with ``UnavailableError`` instead."""
    evt = getattr(_tls, "abandoned", None)
    return evt is not None and evt.is_set()


def check_abandoned() -> None:
    if work_abandoned():
        raise UnavailableError(
            "UNAVAILABLE: watchdog abandoned this attempt after its "
            "dispatch deadline expired"
        )


def deadline_call(fn, what: str, op=None, backend=None):
    """Runs `fn` bounded by the current deadline. Unarmed: a direct call
    (the production fast path). Armed: `fn` runs on a daemon watchdog
    thread and an expiry raises ``UnavailableError`` — the hung call
    cannot be cancelled (a blocked device wait holds the GIL only between
    C calls), but the *caller* is released into the retry→degrade path,
    which is the property that matters: a hang becomes an error instead
    of wedging the executor. The abandoned thread sees
    :func:`work_abandoned` and aborts at its next checkpoint."""
    seconds = current_deadline()
    if not seconds:
        return fn()
    box: dict = {}
    done = threading.Event()
    abandoned = threading.Event()

    def _run():
        _tls.abandoned = abandoned
        try:
            box["value"] = fn()
        except BaseException as exc:  # noqa: BLE001 — re-raised on caller
            box["error"] = exc
        finally:
            done.set()

    thread = threading.Thread(
        target=_run, name="dpf-supervisor-watchdog", daemon=True
    )
    thread.start()
    if not done.wait(seconds):
        abandoned.set()
        _deadline_expired(what, seconds, op, backend)
    if "error" in box:
        raise box["error"]
    return box["value"]


def deadline_result(future, what: str, op=None, backend=None):
    """The pipelined-executor form of :func:`deadline_call`: bounds a
    worker-thread finalize future's ``result()`` wait. The future's
    finalize is already running when the consumer pops it (one worker,
    strict order), so the timeout bounds the remaining pull time."""
    seconds = current_deadline()
    if not seconds:
        return future.result()
    try:
        return future.result(timeout=seconds)
    except _FutureTimeout:
        _deadline_expired(what, seconds, op, backend)


# ---------------------------------------------------------------------------
# Per-op (mode, backend) chains
# ---------------------------------------------------------------------------


def _walk_rungs(
    walkkernel_ok: bool, mode: Optional[str], explicit: bool
) -> Tuple[Rung, ...]:
    from . import evaluator

    resolved = mode if mode is not None else evaluator._walk_mode_default()
    if resolved not in ("walk", "walkkernel"):
        raise InvalidArgumentError(
            f"mode must be 'walk' or 'walkkernel', got {resolved!r}"
        )
    rungs = []
    if resolved == "walkkernel" and (walkkernel_ok or explicit):
        # An inexpressible EXPLICIT walkkernel stays in the chain so the
        # entry point raises the caller's error; the env default quietly
        # starts at the shipped walk shape (the resolver contract).
        rungs.append(("walkkernel", "pallas"))
    if evaluator._pallas_default():
        rungs.append(("walk", "pallas"))
    rungs.append(("walk", "jax"))
    rungs.append((None, "numpy"))
    return tuple(rungs)


def walk_chain(
    dpf, hierarchy_level: int, mode: Optional[str], op: str = ""
) -> Tuple[Rung, ...]:
    """The point-walk chain for `dpf` at `hierarchy_level`:
    walkkernel → walk/pallas → walk/jax → numpy, with the kernel rung
    present only when the resolved strategy is "walkkernel" and the value
    type / tree shape can express it."""
    del op
    from ..core.value_types import Int, XorWrapper

    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    vt = v.parameters[hierarchy_level].value_type
    scalar = isinstance(vt, (Int, XorWrapper))
    ok = (
        scalar
        and vt.bitsize % 32 == 0
        and v.hierarchy_to_tree[hierarchy_level] >= 1
    )
    return _walk_rungs(ok, mode, explicit=mode is not None)


def dcf_chain(dcf, mode: Optional[str]) -> Tuple[Rung, ...]:
    """walk_chain for a DistributedComparisonFunction (its DPF's final
    hierarchy level drives the walk)."""
    from . import evaluator

    bits, _, n_elems = evaluator._payload_kind(dcf.value_type)
    v = dcf.dpf.validator
    ok = (
        n_elems == 1
        and bits % 32 == 0
        and v.hierarchy_to_tree[v.num_hierarchy_levels - 1] >= 1
    )
    return _walk_rungs(ok, mode, explicit=mode is not None)


def fold_chain(mode: Optional[str]) -> Tuple[Rung, ...]:
    """The full-domain-fold / PIR chain: sharded-megakernel (PIR only,
    needs a mesh) → megakernel → fold/pallas → fold/jax → numpy (host
    fold). 'sharded-megakernel' never resolves from the env default — it
    only enters the chain when the caller asked for the mesh path
    (pir_query_batch_robust mode=/mesh=), and its first downgrade rung is
    the SAME kernel on one device, so a mesh-layer fault (collective
    timeout, device loss) sheds to single-chip before shedding engines."""
    from . import evaluator

    resolved = mode if mode is not None else evaluator._fold_mode_default()
    if resolved not in ("fold", "megakernel", "sharded-megakernel"):
        raise InvalidArgumentError(
            f"mode must be 'fold', 'megakernel' or 'sharded-megakernel', "
            f"got {resolved!r}"
        )
    rungs = []
    if resolved == "sharded-megakernel":
        rungs.append(("sharded-megakernel", "pallas"))
    if resolved in ("megakernel", "sharded-megakernel"):
        rungs.append(("megakernel", "pallas"))
    if evaluator._pallas_default():
        rungs.append(("fold", "pallas"))
    rungs.append(("fold", "jax"))
    rungs.append((None, "numpy"))
    return tuple(rungs)


def hier_chain(mode: Optional[str]) -> Tuple[Rung, ...]:
    """The hierarchical-advance chain: hierkernel → fused/pallas →
    fused/jax → numpy (the native host engine)."""
    from . import evaluator

    resolved = mode if mode is not None else evaluator._hier_mode_default()
    if resolved not in ("fused", "hierkernel"):
        raise InvalidArgumentError(
            f"mode must be 'fused' or 'hierkernel', got {resolved!r}"
        )
    rungs = []
    if resolved == "hierkernel":
        rungs.append(("hierkernel", "pallas"))
    if evaluator._pallas_default():
        rungs.append(("fused", "pallas"))
    rungs.append(("fused", "jax"))
    rungs.append((None, "numpy"))
    return tuple(rungs)


def full_domain_chain() -> Tuple[Rung, ...]:
    """The flat full-domain values chain (one execution shape per
    backend): pallas → jax → numpy, pallas only on Mosaic platforms."""
    return tuple((None, b) for b in degrade.fallback_chain())


def keygen_chain(mode: Optional[str]) -> Tuple[Rung, ...]:
    """The batched-keygen chain (ISSUE 13, megakernel rung ISSUE 19):
    keygen/megakernel → keygen/pallas → keygen/jax →
    keygen/numpy-threaded → keygen/numpy (the vectorized host batch) →
    numpy — the rung of last resort being the SCALAR per-key oracle
    loop, the one keygen implementation that shares no code with the
    batched paths. The resolved mode decides the entry rung; every rung
    generates the same bytes from the same seeds, so degradation is
    invisible to callers."""
    from . import keygen_batch

    resolved = keygen_batch.validated_mode(mode)
    order = keygen_batch.KEYGEN_RUNG_ORDER
    # ROADMAP: a mode present in KEYGEN_MODES but missing from the rung
    # ladder would make `order.index` miss (explicit modes) or silently
    # start the chain at the wrong rung (prefix slicing) — assert
    # set-agreement of the two tuples HERE, where the slice happens, so
    # any drift fails the first chain build of the process.
    assert set(order) == set(keygen_batch.KEYGEN_MODES), (
        "keygen rung ladder out of sync with KEYGEN_MODES: "
        f"{order} vs {keygen_batch.KEYGEN_MODES}"
    )
    rungs = [("keygen", b) for b in order[order.index(resolved):]]
    rungs.append((None, "numpy"))
    return tuple(rungs)


# ---------------------------------------------------------------------------
# Chunk journal: crash-safe checkpoint/resume
# ---------------------------------------------------------------------------


def _encode_array(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    dtype = a.dtype.descr if a.dtype.names else a.dtype.str
    return {
        "shape": list(a.shape),
        "dtype": dtype,
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _decode_array(d: dict) -> np.ndarray:
    spec = d["dtype"]
    if isinstance(spec, list):  # structured (e.g. the U128 prefix dtype)
        dtype = np.dtype([(str(name), str(fmt)) for name, fmt in spec])
    else:
        dtype = np.dtype(spec)
    raw = base64.b64decode(d["b64"])
    return np.frombuffer(raw, dtype=dtype).reshape(d["shape"]).copy()


class ChunkJournal:
    """Append-only JSONL checkpoint of one robust bulk job.

    Layout::

        {"kind": "job", "fingerprint": "...", "op": "..."}   # header
        {"kind": "chunk", "index": 0, "sha": "...", ...payload}
        ...
        {"kind": "done", "chunks": N}                        # finalize

    Crash safety is structural: every append is one line, flushed and
    fsync'd before the writer moves on, so a kill leaves at most one torn
    *tail* line, which the loader discards (JSON decode failure ends the
    replay — everything before it is intact). Each chunk line carries a
    sha256 of its decoded payload bytes, so a corrupted-but-parseable
    line is rejected rather than replayed. The header fingerprint (keys
    digest + params + mode, :func:`job_fingerprint`) must match the
    resuming job exactly; a mismatch discards the file — a journal can
    never feed a different job's chunks. ``finalize`` appends the
    ``done`` marker (atomic at the line level: a torn marker simply
    means "not finalized", and every chunk is still individually
    replayable)."""

    def __init__(self, path: str, fingerprint: str, op: str = ""):
        self.path = path
        self.fingerprint = fingerprint
        self.op = op
        self._chunks: dict = {}
        self._valid_lines: list = []  # raw good lines (header first)
        self._header_ok = False
        self._rewrite = False  # file holds garbage past the good prefix
        self._finalized = False
        self._f = None
        self._load()

    # -- loading ----------------------------------------------------------
    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path, "r") as f:
                lines = f.read().splitlines()
        except OSError:
            return
        header_seen = False
        good: list = []
        torn = False
        for line in lines:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                torn = True
                break  # torn tail from a mid-append kill: stop here
            kind = rec.get("kind")
            if not header_seen:
                if kind != "job" or rec.get("fingerprint") != self.fingerprint:
                    # A different job's journal (or a pre-crash file from
                    # changed inputs): never replay it.
                    integrity.emit_event(
                        "journal-discarded",
                        f"chunk journal {self.path}: fingerprint mismatch — "
                        "starting fresh",
                        "",
                        op=self.op,
                    )
                    return
                header_seen = True
                good.append(line)
                continue
            if kind == "chunk":
                payload = {
                    k: v
                    for k, v in rec.items()
                    if k not in ("kind", "index", "sha")
                }
                if _payload_sha(payload) != rec.get("sha"):
                    torn = True
                    break  # corrupted line: trust nothing at or after it
                self._chunks[int(rec["index"])] = payload
                good.append(line)
            elif kind == "done":
                self._finalized = True
                good.append(line)
        self._header_ok = header_seen
        self._valid_lines = good
        # Appending after a torn tail would weld new lines onto garbage;
        # rewrite the good prefix first instead.
        self._rewrite = torn and header_seen

    # -- writing ----------------------------------------------------------
    def _writer(self):
        if self._f is None:
            if self._header_ok and not self._rewrite:
                self._f = open(self.path, "a")
            else:
                self._f = open(self.path, "w")
                if self._header_ok:
                    for line in self._valid_lines:
                        self._f.write(line + "\n")
                    self._f.flush()
                    self._rewrite = False
                else:
                    self._append(
                        {"kind": "job", "fingerprint": self.fingerprint,
                         "op": self.op}
                    )
                    self._header_ok = True
        return self._f

    def _append(self, rec: dict) -> None:
        f = self._f
        line = json.dumps(rec)
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())
        if _tm.enabled():
            _tm.observe("journal.append_bytes", len(line) + 1, op=self.op)

    def completed(self, index: int) -> Optional[dict]:
        """The stored payload of a verified chunk, or None (must run)."""
        payload = self._chunks.get(index)
        if payload is not None:
            _tm.counter("journal.chunks_skipped", op=self.op)
        return payload

    def record(self, index: int, payload: dict) -> None:
        """Appends one VERIFIED chunk (call only after the sentinel/spot
        check passed — the journal's whole value is that replayed chunks
        need no re-verification)."""
        self._writer()
        self._append(
            {"kind": "chunk", "index": index, "sha": _payload_sha(payload),
             **payload}
        )
        self._chunks[index] = payload
        _tm.counter("journal.chunks_recorded", op=self.op)

    def finalize(self) -> None:
        if self._finalized:
            return
        self._writer()
        self._append({"kind": "done", "chunks": len(self._chunks)})
        self._finalized = True
        self.close()

    @property
    def finalized(self) -> bool:
        """True once the ``done`` marker is durable — the journal's
        atomic completion bit (the streaming tier reads it as a window's
        durable *closed* marker, ISSUE 15)."""
        return self._finalized

    def completed_indices(self) -> list:
        """Sorted indices of every verified chunk on record (no
        counters; the resume loaders iterate this before `completed`)."""
        return sorted(self._chunks)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def unlink(self) -> None:
        """Closes and removes the journal file — the rotation hook for
        long-lived servers (ISSUE 15): a finalized window journal has
        done its job once the window's result is durable elsewhere, and
        keeping one result-sized file per window grows disk without
        bound (the PR 10 fingerprint-derived journal lesson)."""
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass
        if _tm.enabled():
            _tm.counter("journal.rotated", op=self.op)


def _payload_sha(payload: dict) -> str:
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()
    ).hexdigest()


def _prefix_bytes(prefixes) -> bytes:
    if isinstance(prefixes, np.ndarray):
        return np.ascontiguousarray(prefixes).tobytes()
    return repr([int(x) for x in prefixes]).encode()


def job_fingerprint(
    op: str,
    dpf,
    keys: Sequence,
    hierarchy_level: int = -1,
    mode: Optional[str] = None,
    extra: tuple = (),
) -> str:
    """sha256 over (op, DPF parameter signature, execution mode, party,
    key material digest, extras) — the identity a journal line must match
    before its chunks replay. Key material goes in via the packed
    KeyBatch arrays (root seeds + correction words + value corrections),
    so two jobs over byte-identical keys fingerprint identically across
    processes."""
    from . import evaluator

    batch = evaluator.KeyBatch.from_keys(dpf, keys, hierarchy_level)
    h = hashlib.sha256()
    h.update(
        repr(
            (
                op,
                integrity._params_signature(dpf.validator),
                mode,
                batch.party,
                len(keys),
                extra,
            )
        ).encode()
    )
    for arr in (
        batch.seeds,
        batch.cw_seeds,
        batch.cw_left,
        batch.cw_right,
        batch.value_corrections,
    ):
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Host-oracle helpers (spot checks + numpy rungs)
# ---------------------------------------------------------------------------


def _ints_to_limbs(vals, bits: int) -> np.ndarray:
    """Python-int host values -> uint32[..., lpe] limbs."""
    from ..core import uint128

    lpe = max(bits // 32, 1)
    vals = np.asarray(vals, dtype=object)
    out = np.zeros(vals.shape + (lpe,), dtype=np.uint32)
    for idx in np.ndindex(vals.shape):
        out[idx] = uint128.to_limbs(int(vals[idx]))[:lpe]
    return out


def _dcf_host_limbs(
    dcf, keys, xs, bits: int, cap: Optional[int] = None
) -> Tuple[np.ndarray, int]:
    """Host-oracle DCF values as uint32[K, P', lpe] limbs plus the number
    of points covered. The native engine covers all P; without it the
    reference-parity python path runs — all points by default (the chain's
    rung of last resort must SERVE, however slowly), or a `cap`-bounded
    prefix for spot checks."""
    from .. import native
    from ..core import host_eval
    from ..dcf import batch as dcf_batch
    from . import evaluator

    _, _, n_elems = evaluator._payload_kind(dcf.value_type)
    with integrity._faults_suspended():
        # Tuple payloads run the fused host walk regardless of the native
        # build: its backend_numpy primitives carry their own numpy
        # fallback, so it IS the rung of last resort.
        if native.available() or n_elems > 1:
            raw = dcf_batch.batch_evaluate_host(dcf, keys, xs)
            if raw.ndim >= 3 and raw.dtype == np.uint64 and raw.shape[-1] == 2:
                # uint64 (lo, hi) pairs: [K, P(, n_elems), 2]. Tuple
                # payloads keep the full 4-limb lane (the device contract
                # zero-pads narrow elements to 4 limbs); scalars slice to
                # the value width's limbs.
                limbs = np.zeros(raw.shape[:-1] + (4,), np.uint32)
                limbs[..., 0] = raw[..., 0] & np.uint64(0xFFFFFFFF)
                limbs[..., 1] = raw[..., 0] >> np.uint64(32)
                limbs[..., 2] = raw[..., 1] & np.uint64(0xFFFFFFFF)
                limbs[..., 3] = raw[..., 1] >> np.uint64(32)
                if n_elems > 1:
                    return limbs, len(xs)
                return limbs[..., : max(bits // 32, 1)], len(xs)
            return host_eval.values_to_limbs(raw, bits), len(xs)
        covered = len(xs) if cap is None else min(len(xs), cap)
        vals = [
            [dcf.evaluate(k, int(x)) for x in xs[:covered]] for k in keys
        ]
        return _ints_to_limbs(vals, bits), covered


def _spot_check(
    op: str, got_row: np.ndarray, want_row: np.ndarray, backend: str,
    key_index: int,
) -> None:
    """Host-oracle spot verification of one key row (the sentinel-probe
    analog for entry points with no probe seam). Raises on mismatch."""
    got = np.asarray(got_row)[: want_row.shape[0]]
    if got.shape == want_row.shape and np.array_equal(got, want_row):
        integrity.emit_event(
            "sentinel-ok",
            f"{op}: host-oracle spot check verified key row {key_index} "
            f"over {want_row.shape[0]} positions",
            backend,
            op=op,
        )
        return
    bad = (
        np.nonzero((got != want_row).reshape(want_row.shape[0], -1).any(axis=1))[0]
        if got.shape == want_row.shape
        else np.arange(min(8, want_row.shape[0]))
    )
    raise DataCorruptionError(
        f"host-oracle spot check failed on {op} (backend {backend!r}): key "
        f"row {key_index} disagrees at {bad.shape[0]} of "
        f"{want_row.shape[0]} checked positions",
        key_index=key_index,
        lanes=bad[:32].tolist(),
        pattern=integrity.diagnose_lanes(bad, want_row.shape[0]),
        backend=backend,
    )


def _host_pir_fold(dpf, keys, db_nat: np.ndarray, bits: int) -> np.ndarray:
    """Numpy rung of the PIR chain: the host oracle's full-domain values
    AND-masked against the natural-order DB and XOR-folded — the same
    arithmetic `integrity.verify_probe_fold` checks device responses
    against, here serving the whole batch."""
    from ..core import host_eval

    with integrity._faults_suspended():
        raw = host_eval.full_domain_evaluate_host(dpf, keys)
    vals = host_eval.values_to_limbs(raw, bits)
    masked = vals & np.asarray(db_nat, dtype=np.uint32)[None]
    return np.bitwise_xor.reduce(masked, axis=1).astype(np.uint32)


# ---------------------------------------------------------------------------
# Robust wrappers: the four entry points PR 1 never covered
# ---------------------------------------------------------------------------


def batch_evaluate_robust(
    dcf,
    keys: Sequence,
    xs: Sequence[int],
    key_chunk: Optional[int] = None,
    policy: DegradationPolicy = DEFAULT_POLICY,
    pipeline: Optional[bool] = None,
    mode: Optional[str] = None,
) -> np.ndarray:
    """`dcf.batch.batch_evaluate` behind the supervisor: the chain walks
    walkkernel → walk/pallas → walk/jax → numpy (the host engine), each
    device rung spot-verified against the host oracle on the last key row
    (DCF has no sentinel-probe seam — a probe key's comparison values
    would not ride the same capture tables). Returns uint32[K, P, lpe]
    limbs on every rung, including the host one."""
    from . import evaluator

    bits, _xor, _n_elems = evaluator._payload_kind(dcf.value_type)
    chain = dcf_chain(dcf, mode)
    verify = policy.verify is not False

    def attempt(mode_r: Optional[str], backend: str, chunk: Optional[int]):
        if backend == "numpy":
            # Rung of last resort: with the native engine missing this is
            # the O(n^2)-per-point reference path — slow but it SERVES.
            limbs, _covered = _dcf_host_limbs(dcf, keys, xs, bits)
            return limbs
        ck = chunk if chunk is not None else key_chunk
        out = dcf.batch_evaluate(
            keys, xs,
            mode=mode_r or "walk",
            use_pallas=(backend == "pallas"),
            key_chunk=ck,
            pipeline=pipeline,
        )
        if verify:
            want, _ = _dcf_host_limbs(dcf, [keys[-1]], xs, bits, cap=64)
            _spot_check(
                "dcf.batch_evaluate", out[-1], want[0], backend,
                key_index=len(keys) - 1,
            )
        return out

    attempt.default_chunk = len(keys) if keys else 1
    return degrade._run_chain("dcf.batch_evaluate", policy, attempt, chain=chain)


def gate_batch_eval_robust(
    gate,
    key,
    xs: Sequence[int],
    policy: DegradationPolicy = DEFAULT_POLICY,
    key_chunk: Optional[int] = None,
    pipeline: Optional[bool] = None,
    mode: Optional[str] = None,
) -> np.ndarray:
    """Any framework gate's ``batch_eval`` (gates/framework.MaskedGate —
    MIC, DReLU/ReLU, splines, bit decomposition) behind the supervisor:
    the gate's single fused DCF pass (its :class:`GatePlan` flatten) runs
    through :func:`batch_evaluate_robust` — inheriting the
    walkkernel → walk/pallas → walk/jax → numpy chain and its host-oracle
    spot checks — and the exact-int mask combine stays on the host.
    Returns the same object ndarray [len(xs), num_outputs] of share
    values the direct ``gate.batch_eval`` produces."""
    from ..gates import framework as gate_framework
    from . import evaluator

    plan = gate_framework.GatePlan.build(gate, xs)
    dcf_keys, _ = gate._key_parts(key)
    evals = batch_evaluate_robust(
        gate.dcf, list(dcf_keys), plan.points,
        key_chunk=key_chunk, policy=policy, pipeline=pipeline, mode=mode,
    )
    return plan.combine(key, evaluator.values_to_numpy(evals, 128))


def mic_batch_eval_robust(
    gate,
    key,
    xs: Sequence[int],
    policy: DegradationPolicy = DEFAULT_POLICY,
    key_chunk: Optional[int] = None,
    pipeline: Optional[bool] = None,
    mode: Optional[str] = None,
) -> np.ndarray:
    """`gates.mic.MultipleIntervalContainmentGate.batch_eval` behind the
    supervisor — the MIC-shaped alias of :func:`gate_batch_eval_robust`
    (the gate framework made the generic form possible; this name stays
    for the serving layer and chaos suites that grew up on it)."""
    return gate_batch_eval_robust(
        gate, key, xs,
        policy=policy, key_chunk=key_chunk, pipeline=pipeline, mode=mode,
    )


def _keygen_spot_check(
    dpf, keys_0, keys_1, alphas, per_key_betas, seeds, backend: str
) -> None:
    """Serialized-bytes spot verification of batched keygen: the LAST key
    pair is regenerated through the scalar per-key oracle (the one path
    sharing no code with the batched level loop) from the same seeds, and
    both parties' wire bytes must match exactly. One key's worth of
    oracle work per call — the keygen analog of `_spot_check`."""
    from ..core import uint128
    from ..protos import serialization

    i = len(alphas) - 1
    with integrity._faults_suspended():
        want_0, want_1 = dpf.generate_keys_incremental(
            alphas[i], per_key_betas[i],
            seeds=(
                uint128.from_limbs(seeds[i, 0]),
                uint128.from_limbs(seeds[i, 1]),
            ),
        )
    params = dpf.validator.parameters
    for party, got, want in ((0, keys_0[i], want_0), (1, keys_1[i], want_1)):
        got_b = serialization.serialize_dpf_key(got, params)
        want_b = serialization.serialize_dpf_key(want, params)
        if got_b != want_b:
            bad = [
                j for j in range(min(len(got_b), len(want_b)))
                if got_b[j] != want_b[j]
            ]
            raise DataCorruptionError(
                f"keygen spot check failed (backend {backend!r}): key "
                f"{i} party {party} serialized bytes disagree at "
                f"{len(bad) or abs(len(got_b) - len(want_b))} positions "
                f"vs the scalar oracle",
                key_index=i,
                lanes=bad[:32],
                backend=backend,
            )
    integrity.emit_event(
        "sentinel-ok",
        f"generate_keys: scalar-oracle spot check verified key pair {i} "
        "byte-exact (both parties)",
        backend,
        op="generate_keys",
    )


def generate_keys_robust(
    dpf,
    alphas: Sequence[int],
    betas: Sequence,
    mode: Optional[str] = None,
    seeds: Optional[np.ndarray] = None,
    policy: DegradationPolicy = DEFAULT_POLICY,
) -> Tuple[list, list]:
    """Batched two-party keygen behind the supervisor (ISSUE 13): the
    chain walks keygen/megakernel → keygen/pallas → keygen/jax →
    keygen/numpy-threaded → keygen/numpy → numpy (the scalar per-key
    oracle). The CSPRNG seeds are drawn ONCE up front and
    handed to every rung, so rungs are interchangeable — a degraded
    retry produces the SAME key pairs, and each non-oracle rung is
    spot-verified by regenerating the last key pair through the scalar
    oracle and comparing serialized bytes. Resource exhaustion halves
    the key chunk (the batch is seeded level-major per slice; slicing
    changes nothing — each key's tree walk is independent).

    Args match ``ops.keygen_batch.generate_keys_batch``. Returns
    (keys_0, keys_1) lists of ``DpfKey``."""
    import secrets as _secrets

    from ..core import uint128
    from . import keygen_batch

    k = len(alphas)
    if k == 0:
        return [], []
    if seeds is None:
        raw = _secrets.token_bytes(16 * 2 * k)
        seeds = np.frombuffer(raw, dtype=np.uint32).reshape(k, 2, 4).copy()
    else:
        seeds = np.array(seeds, dtype=np.uint32).reshape(k, 2, 4)
    from ..core import keygen as core_keygen

    v = dpf.validator
    beta_cols = core_keygen.normalize_beta_cols(
        betas, k, v.num_hierarchy_levels
    )
    per_key_betas = [[col[i] for col in beta_cols] for i in range(k)]
    chain = keygen_chain(mode)
    verify = policy.verify is not False

    def attempt(mode_r: Optional[str], backend: str, chunk: Optional[int]):
        if mode_r is None:
            # Scalar oracle of last resort: the per-key reference loop.
            out_0, out_1 = [], []
            for i in range(k):
                a, b = dpf.generate_keys_incremental(
                    alphas[i], per_key_betas[i],
                    seeds=(
                        uint128.from_limbs(seeds[i, 0]),
                        uint128.from_limbs(seeds[i, 1]),
                    ),
                )
                out_0.append(a)
                out_1.append(b)
            return out_0, out_1
        ck = chunk if chunk is not None else k
        # Direct engine dispatch (run_resolved), NOT the resolve_mode
        # entry point: a rung is the chain's choice — its
        # decision(source="degrade") stream is the record — and a
        # per-attempt decision(source="explicit") would inflate and
        # mislabel the telemetry consumers count engines by.
        out_0, out_1 = [], []
        for s in range(0, k, ck):
            part_0, part_1 = keygen_batch.run_resolved(
                dpf, backend,
                alphas[s : s + ck],
                [col[s : s + ck] for col in beta_cols],
                seeds=seeds[s : s + ck],
            )
            out_0.extend(part_0)
            out_1.extend(part_1)
        if verify:
            _keygen_spot_check(
                dpf, out_0, out_1, alphas, per_key_betas, seeds, backend
            )
        return out_0, out_1

    attempt.default_chunk = k
    return degrade._run_chain("generate_keys", policy, attempt, chain=chain)


def _ctx_snapshot(ctx) -> tuple:
    return (
        ctx.previous_hierarchy_level,
        None if ctx.parent_tree is None else np.array(ctx.parent_tree),
        ctx.child_levels,
        ctx.seeds,
        ctx.control,
    )


def _ctx_restore(ctx, snap: tuple) -> None:
    (
        ctx.previous_hierarchy_level,
        ctx.parent_tree,
        ctx.child_levels,
        ctx.seeds,
        ctx.control,
    ) = snap


def _ctx_record(ctx) -> dict:
    """Journal payload of a BatchedContext's resumable state (the state
    the hierarchical megakernel exports at every window boundary)."""
    rec: dict = {
        "prev_level": ctx.previous_hierarchy_level,
        "child_levels": ctx.child_levels,
    }
    if ctx.parent_tree is not None:
        rec["parent_tree"] = _encode_array(np.asarray(ctx.parent_tree))
    if ctx.seeds is not None:
        rec["seeds"] = _encode_array(np.asarray(ctx.seeds))
        rec["control"] = _encode_array(
            np.asarray(ctx.control).astype(np.uint32)
        )
    return rec


def _ctx_apply(ctx, rec: dict) -> None:
    ctx.previous_hierarchy_level = int(rec["prev_level"])
    ctx.child_levels = int(rec["child_levels"])
    ctx.parent_tree = (
        _decode_array(rec["parent_tree"]) if "parent_tree" in rec else None
    )
    if "seeds" in rec:
        ctx.seeds = _decode_array(rec["seeds"])
        ctx.control = _decode_array(rec["control"]).astype(bool)
    else:
        ctx.seeds = None
        ctx.control = None


#: Public journal hooks (ISSUE 15): the streaming window manager
#: checkpoints a window's resumable BatchedContext state per advanced
#: level through exactly the encoding the hierarchical journal already
#: uses — one state format, one loader.
ctx_record = _ctx_record
ctx_apply = _ctx_apply


def advance_level_robust(
    ctx,
    hierarchy_level: int,
    prefixes,
    group: int = 16,
    policy: DegradationPolicy = DEFAULT_POLICY,
    mode: Optional[str] = None,
    key_chunk: Optional[int] = None,
    pipeline: Optional[bool] = None,
) -> np.ndarray:
    """ONE incremental window advance behind the supervisor (ISSUE 15):
    the single-entry plan form of :func:`evaluate_levels_fused_robust` —
    the streaming heavy-hitters tier advances each rolling window level
    by level as survivor prefixes arrive, so the one-entry shape IS its
    natural call. Inherits the full hierkernel → fused/pallas →
    fused/jax → numpy chain, host-oracle spot checks, and the resumable
    BatchedContext commit discipline (a failed rung never leaves `ctx`
    advanced). Returns uint32[K, n_outputs, lpe] limbs."""
    return evaluate_levels_fused_robust(
        ctx, [(int(hierarchy_level), list(prefixes))], group=group,
        policy=policy, mode=mode, key_chunk=key_chunk, pipeline=pipeline,
    )[0]


def evaluate_levels_fused_robust(
    ctx,
    plan,
    group: int = 16,
    policy: DegradationPolicy = DEFAULT_POLICY,
    mode: Optional[str] = None,
    key_chunk: Optional[int] = None,
    pipeline: Optional[bool] = None,
    journal: Optional[str] = None,
) -> list:
    """`hierarchical.evaluate_levels_fused` behind the supervisor, one
    plan entry at a time (each entry is one resumable advance — the
    documented equivalence with calling `evaluate_until_batch` per
    entry). Per entry the chain walks hierkernel → fused/pallas →
    fused/jax → numpy (the native host engine via
    ``evaluate_until_batch(engine="host")``); a failed rung restores the
    entry's entry-state snapshot and the next rung resumes **from the
    exported BatchedContext state** — verified prefix windows are never
    re-walked. Device rungs are spot-verified on the last key row against
    a one-key host shadow context (sentinel cost profile).

    `journal` (a file path) checkpoints every verified entry's outputs
    AND post-entry context state: a killed job restarted over the same
    keys/plan/mode replays verified entries from the journal, applies
    the stored context state, and re-dispatches only the rest. Returns
    per-entry uint32[K, n_outputs, lpe] limb arrays (every rung
    normalizes to the device limb format). Scalar plans only (raw
    (level, prefixes) lists — prepared plans carry mode-specific tables
    the chain could not re-target)."""
    from ..core import host_eval
    from . import evaluator, hierarchical

    if not isinstance(plan, (list, tuple)) or not plan:
        raise InvalidArgumentError(
            "evaluate_levels_fused_robust takes a non-empty raw plan "
            "(list of (hierarchy_level, prefixes)); prepared plans are "
            "mode-specific and cannot ride the degradation chain"
        )
    dpf, v = ctx.dpf, ctx.dpf.validator
    chain = hier_chain(mode)
    verify = policy.verify is not False
    jr = None
    if journal is not None:
        fp = job_fingerprint(
            "evaluate_levels_fused", dpf, ctx.keys, -1, mode,
            extra=(
                group,
                tuple(
                    (int(h), hashlib.sha256(_prefix_bytes(p)).hexdigest())
                    for h, p in plan
                ),
            ),
        )
        jr = ChunkJournal(journal, fp, op="evaluate_levels_fused")

    shadow = None
    if verify:
        shadow = hierarchical.BatchedContext.create(dpf, [ctx.keys[-1]])
        if ctx.previous_hierarchy_level >= 0 or ctx.seeds is not None:
            # The caller's context is already advanced (the adaptive
            # per-level shape: heavy-hitters pruning feeds each level's
            # survivors into the next call). Fast-forward the one-key
            # shadow from the context's state — direct numpy copies with
            # the last key's seed/control row sliced out, NOT the
            # _ctx_record round-trip (which would base64-encode all K
            # keys' planes once per robust call just to keep 1/K).
            shadow.previous_hierarchy_level = ctx.previous_hierarchy_level
            shadow.child_levels = ctx.child_levels
            shadow.parent_tree = (
                None if ctx.parent_tree is None else np.copy(ctx.parent_tree)
            )
            if ctx.seeds is not None:
                shadow.seeds = np.copy(np.asarray(ctx.seeds)[-1:])
                shadow.control = np.copy(np.asarray(ctx.control)[-1:])
            else:
                shadow.seeds = None
                shadow.control = None

    outs: list = []
    try:
        for ei, (h, prefixes) in enumerate(plan):
            bits, _ = evaluator._value_kind(v.parameters[h].value_type)
            stored = jr.completed(ei) if jr is not None else None
            if stored is not None:
                outs.append(_decode_array(stored["values"]))
                _ctx_apply(ctx, stored["state"])
                if shadow is not None:
                    # The shadow context's per-key state is the last row
                    # of the journaled batch state — fast-forward it
                    # without re-running the host engine.
                    _ctx_apply(shadow, stored["state"])
                    if shadow.seeds is not None:
                        shadow.seeds = shadow.seeds[-1:]
                        shadow.control = shadow.control[-1:]
                continue

            want_row = None
            if shadow is not None:
                with integrity._faults_suspended():
                    ref = hierarchical.evaluate_until_batch(
                        shadow, h, prefixes, engine="host"
                    )
                want_row = host_eval.values_to_limbs(np.asarray(ref), bits)[0]

            snap = _ctx_snapshot(ctx)

            def attempt(
                mode_r, backend, chunk, h=h, prefixes=prefixes,
                want_row=want_row, snap=snap, bits=bits,
            ):
                # Entry precondition: every attempt resumes from the
                # entry's own state snapshot — verified earlier entries
                # are never re-walked, and a prior failed rung cannot
                # leave the context advanced behind the retry.
                _ctx_restore(ctx, snap)
                if backend == "numpy":
                    ref = hierarchical.evaluate_until_batch(
                        ctx, h, prefixes, engine="host"
                    )
                    return host_eval.values_to_limbs(np.asarray(ref), bits)
                ck = chunk if chunk is not None else key_chunk
                # Device rungs advance a DETACHED context: when the
                # deadline watchdog abandons a hung advance, the zombie
                # thread may still finish and update its context much
                # later — on the detached copy that is harmless, and the
                # caller's context only ever commits an in-deadline,
                # spot-verified advance.
                work = hierarchical.BatchedContext(
                    dpf=ctx.dpf, keys=ctx.keys,
                    previous_hierarchy_level=snap[0], parent_tree=snap[1],
                    child_levels=snap[2], seeds=snap[3], control=snap[4],
                )

                def _device_entry():
                    # The fused path never crosses the pipelined executor,
                    # so it gets its own hang seams (both stage points, so
                    # any hang schedule reaches it) + deadline guard here:
                    # one watchdog per advance (the hierkernel mode's
                    # per-chunk waits are additionally bounded inside the
                    # executor).
                    faultinject.device_hang("launch", backend=backend)
                    check_abandoned()
                    entry_out = hierarchical.evaluate_levels_fused(
                        work, [(h, prefixes)], group=group, mode=mode_r,
                        use_pallas=(backend == "pallas"),
                        key_chunk=ck, pipeline=pipeline,
                    )[0]
                    faultinject.device_hang("finalize", backend=backend)
                    check_abandoned()
                    return entry_out

                try:
                    out = deadline_call(
                        _device_entry, "evaluate_levels_fused",
                        op="evaluate_levels_fused", backend=backend,
                    )
                except NotImplementedError as exc:
                    raise RungUnsupported(str(exc), exc)
                if want_row is not None:
                    _spot_check(
                        "evaluate_levels_fused", out[-1], want_row, backend,
                        key_index=len(ctx.keys) - 1,
                    )
                _ctx_restore(ctx, _ctx_snapshot(work))
                return out

            attempt.default_chunk = len(ctx.keys)
            out = degrade._run_chain(
                "evaluate_levels_fused", policy, attempt, chain=chain
            )
            outs.append(np.asarray(out))
            if jr is not None:
                jr.record(
                    ei,
                    {"values": _encode_array(np.asarray(out)),
                     "state": _ctx_record(ctx)},
                )
        if jr is not None:
            jr.finalize()
    finally:
        if jr is not None:
            jr.close()
    return outs


def pir_query_batch_robust(
    dpf,
    keys: Sequence,
    db_limbs,
    key_chunk: int = 64,
    host_levels: Optional[int] = None,
    policy: DegradationPolicy = DEFAULT_POLICY,
    pipeline: Optional[bool] = None,
    mode: Optional[str] = None,
    mesh=None,
) -> np.ndarray:
    """`parallel.sharded.pir_query_batch_chunked` behind the supervisor:
    sharded-megakernel (mesh) → megakernel → fold/pallas → fold/jax →
    numpy (host fold), sentinel-verified per rung via the existing probe
    machinery. A mode downgrade that invalidates the prepared database's
    ``order=``/mesh row layout (megakernel's streaming tiles vs the lane
    permutation; one mesh's column blocks vs another's) re-prepares it
    from the cached natural-order host copy — served queries keep their
    answers bit-exact across the transition. `db_limbs` is a host
    uint32[D, lpe] array or any-order ``PreparedPirDatabase``.

    `mesh` (a sharded.make_mesh / multihost.local_mesh (keys, domain)
    mesh; default: the DPF_TPU_PIR_MESH env via
    sharded.pir_mesh_from_env when mode='sharded-megakernel' asks for
    one) puts the pod-scale rung on top of the chain: the sharded
    megakernel's first downgrade is the SAME kernel on one device, so a
    mesh-layer fault sheds to single-chip before shedding engines."""
    from ..parallel import sharded
    from . import evaluator

    if mesh is not None and mode is None:
        mode = "sharded-megakernel"
    if mode == "sharded-megakernel" and mesh is None:
        mesh = sharded.pir_mesh_from_env()
        if mesh is None:
            raise InvalidArgumentError(
                "mode='sharded-megakernel' needs a mesh: pass mesh= (see "
                "sharded.make_mesh / multihost.local_mesh) or set "
                "DPF_TPU_PIR_MESH=KxD"
            )
    v = dpf.validator
    bits, _xor = evaluator._value_kind(v.parameters[-1].value_type)
    chain = fold_chain(mode)
    nat_cache: dict = {}
    prepared_cache: dict = {}

    def _nat_db() -> np.ndarray:
        if "nat" not in nat_cache:
            nat_cache["nat"] = (
                db_limbs.natural_host(dpf)
                if isinstance(db_limbs, sharded.PreparedPirDatabase)
                else np.asarray(db_limbs)
            )
        return nat_cache["nat"]

    def _db_for(want_order: str, want_mesh=None):
        if (
            isinstance(db_limbs, sharded.PreparedPirDatabase)
            and db_limbs.order == want_order
            and db_limbs.mesh == want_mesh
        ):
            return db_limbs
        cache_key = (want_order, want_mesh)
        if cache_key not in prepared_cache:
            prepared_cache[cache_key] = sharded.prepare_pir_database(
                dpf, _nat_db(), host_levels, order=want_order,
                mesh=want_mesh,
            )
            if isinstance(db_limbs, sharded.PreparedPirDatabase):
                integrity.emit_event(
                    "pir-db-reprepared",
                    "pir_query_batch_robust: mode rung needs a "
                    f"{want_order!r}-order (mesh "
                    f"{sharded._mesh_desc(want_mesh)}) database; "
                    "re-prepared from the "
                    f"{db_limbs.order!r}-order (mesh "
                    f"{sharded._mesh_desc(db_limbs.mesh)}) original's "
                    "natural-order host copy (one upload per downgrade, "
                    "not per query)",
                    "",
                    op="pir_query_batch",
                    from_order=db_limbs.order,
                    to_order=want_order,
                )
                _tm.counter("supervisor.pir_db_reprepared", op="pir_query_batch")
        return prepared_cache[cache_key]

    def attempt(mode_r: Optional[str], backend: str, chunk: Optional[int]):
        ck = chunk if chunk is not None else key_chunk
        if backend == "numpy":
            return _host_pir_fold(dpf, keys, _nat_db(), bits)
        sharded_rung = mode_r == "sharded-megakernel"
        want_order = (
            "megakernel" if mode_r in ("megakernel", "sharded-megakernel")
            else "lane"
        )
        try:
            pdb = _db_for(want_order, mesh if sharded_rung else None)
            return sharded.pir_query_batch_chunked(
                dpf, keys, pdb,
                key_chunk=ck,
                host_levels=host_levels,
                mode="megakernel" if sharded_rung else (mode_r or "fold"),
                mesh=mesh if sharded_rung else None,
                integrity=True if policy.verify is None else policy.verify,
                pipeline=pipeline,
                use_pallas=(
                    None
                    if mode_r in ("megakernel", "sharded-megakernel")
                    else backend == "pallas"
                ),
            )
        except NotImplementedError as exc:
            raise RungUnsupported(str(exc), exc)

    attempt.default_chunk = key_chunk
    return degrade._run_chain("pir_query_batch", policy, attempt, chain=chain)


def full_domain_evaluate_robust(
    dpf,
    keys: Sequence,
    hierarchy_level: int = -1,
    key_chunk: int = 32,
    host_levels: Optional[int] = None,
    policy: DegradationPolicy = DEFAULT_POLICY,
    pipeline: Optional[bool] = None,
    journal: Optional[str] = None,
    journal_dir: Optional[str] = None,
) -> np.ndarray:
    """`degrade.full_domain_evaluate_robust` plus chunk-journal
    checkpoint/resume: with `journal` (a file path), keys run in
    `key_chunk` groups, each group's verified limbs append to the journal
    as one chunk, and a restarted job with the same fingerprint (keys
    digest + params + chunking) re-dispatches only unjournaled chunks —
    dispatch-audit pinned. `journal_dir` names a directory instead and
    derives the file name FROM the job fingerprint — the RPC server's
    form (ISSUE 10): a SIGKILLed server restarted over the same journal
    directory resumes any re-sent job past its verified chunks without
    either end tracking file names. Without both, this delegates
    untouched (zero added programs, zero overhead)."""
    if journal is None and journal_dir is None:
        return degrade.full_domain_evaluate_robust(
            dpf, keys, hierarchy_level, key_chunk=key_chunk,
            host_levels=host_levels, policy=policy, pipeline=pipeline,
        )
    key_chunk = max(1, key_chunk)
    fp = job_fingerprint(
        "full_domain_evaluate", dpf, keys, hierarchy_level, None,
        extra=(key_chunk, host_levels),
    )
    derived = journal is None
    if derived:
        os.makedirs(journal_dir, exist_ok=True)
        journal = os.path.join(journal_dir, f"fd-{fp[:32]}.journal")
    jr = ChunkJournal(journal, fp, op="full_domain_evaluate")
    outs = []
    try:
        for ci, start in enumerate(range(0, len(keys), key_chunk)):
            stored = jr.completed(ci)
            if stored is not None:
                outs.append(_decode_array(stored["values"]))
                continue
            out = degrade.full_domain_evaluate_robust(
                dpf, keys[start : start + key_chunk], hierarchy_level,
                key_chunk=key_chunk, host_levels=host_levels, policy=policy,
                pipeline=pipeline,
            )
            jr.record(ci, {"values": _encode_array(np.asarray(out))})
            outs.append(out)
        jr.finalize()
    finally:
        jr.close()
    if derived:
        # The fingerprint-derived form is the RPC server's: every
        # distinct client batch is a new file holding the job's whole
        # encoded result, so a long-lived server would grow disk without
        # bound. The journal exists to survive a crash DURING the job —
        # once the result is in hand it has done that job; worst case a
        # crash after this unlink but before the response delivers costs
        # one recompute, never correctness. Caller-named `journal=` paths
        # stay, replayable at zero programs (tests pin that).
        try:
            os.unlink(journal)
        except OSError:
            pass
    return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
