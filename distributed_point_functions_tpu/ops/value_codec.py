"""Device lowering of the ValueType system.

Maps the host value types (core/value_types.py — the re-implementation of
/root/reference/dpf/internal/value_type_helpers.h:42-651) onto TPU-friendly
u32-limb kernels:

* ``Int`` / ``XorWrapper``   — bit-slot extraction + add/xor mod 2^bits.
* ``IntModN``                — the 128-bit hash block reduced mod N by a
  bit-serial ``lax.fori_loop`` (TPU has no wide divide; 128 shift/compare/
  subtract steps on little-endian u32 limbs), then mod-N group ops.
  Mirrors IntModNImpl::UnsafeSampleFromBytes
  (/root/reference/dpf/int_mod_n.h:154-177).
* ``TupleType``              — struct-of-arrays: one limb array per LEAF
  element, with arbitrary nesting flattened in leaf order (the spec records
  the nesting tree to rebuild host values).
  Directly-convertible tuples extract each component at its static byte
  offset; tuples containing IntModN replay the sequential sampling chain
  (running 128-bit block, divmod by N, refill low bits from the byte
  stream) with static offsets — vectorized across lanes, sequential only in
  the (static, small) component count, exactly like the reference's
  SampleAndUpdateBytes chain
  (/root/reference/dpf/internal/value_type_helpers.h:341-437).

The public entry points are ``build_spec`` (host: ValueType -> hashable
``ValueSpec`` usable as a jit static argument), ``correction_limbs`` (host:
key correction values -> per-component limb arrays) and ``correct_values``
(device: hashed blocks + control bits + corrections -> per-component limb
arrays, applying `value += correction if control; value = -value if party 1`
as in EvaluateUntil, /root/reference/dpf/distributed_point_function.h:776-808).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.value_types import Int, IntModN, TupleType, ValueType, XorWrapper

_U32 = jnp.uint32


# ---------------------------------------------------------------------------
# Specs (hashable; jit static arguments)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ComponentSpec:
    """One tuple component (or the sole component of a scalar type)."""

    kind: str  # "int" | "xor" | "modn"
    bits: int  # bitsize (int/xor) or base integer bitsize (modn)
    modulus: int = 0  # modn only
    offset_bits: int = 0  # bit offset within one element slot (direct specs)

    @property
    def lpe(self) -> int:
        """Output limbs per element for this component."""
        if self.kind == "modn":
            return max(((self.modulus - 1).bit_length() + 31) // 32, 1)
        return max(self.bits // 32, 1)


@dataclasses.dataclass(frozen=True)
class ValueSpec:
    """Device lowering plan for one ValueType."""

    components: Tuple[ComponentSpec, ...]
    epb: int  # elements per 128-bit block
    stride_bits: int  # spacing of element slots within the block (direct)
    blocks_needed: int
    direct: bool  # True: offset extraction; False: sampling chain
    is_tuple: bool
    # Nesting shape for tuples: a tree of leaf indices into `components`
    # (int = leaf, tuple = nested tuple), e.g. Tuple<u32, Tuple<u32,u32>>
    # -> (0, (1, 2)). None for scalar types. Hashable (jit static arg).
    structure: object = None

    @property
    def is_scalar_direct(self) -> bool:
        return self.direct and not self.is_tuple


def build_spec(value_type: ValueType, blocks_needed: int) -> ValueSpec:
    """Lowers a host ValueType to a device ValueSpec."""
    if isinstance(value_type, (Int, XorWrapper)):
        kind = "xor" if isinstance(value_type, XorWrapper) else "int"
        bits = value_type.bitsize
        return ValueSpec(
            components=(ComponentSpec(kind, bits),),
            epb=128 // bits,
            stride_bits=bits,
            blocks_needed=blocks_needed,
            direct=True,
            is_tuple=False,
        )
    if isinstance(value_type, IntModN):
        return ValueSpec(
            components=(
                ComponentSpec("modn", value_type.base_bitsize, value_type.modulus),
            ),
            epb=1,
            stride_bits=0,
            blocks_needed=blocks_needed,
            direct=False,
            is_tuple=False,
        )
    if isinstance(value_type, TupleType):
        # Flatten arbitrary nesting into the leaf list, recording the tree
        # of leaf indices. The reference's recursive TupleHelper
        # (/root/reference/dpf/internal/value_type_helpers.h:341-437)
        # consumes the byte stream in leaf order — DirectlyFromBytes
        # advances by each element's byte size (all leaf bitsizes are byte
        # multiples, so cumulative bit offsets coincide), and
        # SampleAndUpdateBytes's update2 = update || (not last element)
        # resolves, through the recursion, to "update after every leaf but
        # the flattened-order last" — exactly the flat chain below.
        comps = []

        def _flatten(t):
            if isinstance(t, TupleType):
                return tuple(_flatten(e) for e in t.elements)
            if isinstance(t, Int):
                comps.append(("int", t.bitsize, 0))
            elif isinstance(t, XorWrapper):
                comps.append(("xor", t.bitsize, 0))
            elif isinstance(t, IntModN):
                comps.append(("modn", t.base_bitsize, t.modulus))
            else:
                raise NotImplementedError(
                    f"no device lowering for tuple element {t}"
                )
            return len(comps) - 1

        structure = _flatten(value_type)
        direct = value_type.can_convert_directly()
        if direct:
            tbs = value_type.total_bit_size()
            offset = 0
            specs = []
            for kind, bits, mod in comps:
                specs.append(ComponentSpec(kind, bits, mod, offset))
                offset += bits
            epb = 128 // tbs if tbs <= 128 else 1
            return ValueSpec(
                components=tuple(specs),
                epb=epb,
                stride_bits=tbs,
                blocks_needed=blocks_needed,
                direct=True,
                is_tuple=True,
                structure=structure,
            )
        return ValueSpec(
            components=tuple(ComponentSpec(k, b, m) for k, b, m in comps),
            epb=1,
            stride_bits=0,
            blocks_needed=blocks_needed,
            direct=False,
            is_tuple=True,
            structure=structure,
        )
    raise NotImplementedError(f"no device lowering for value type {value_type}")


# ---------------------------------------------------------------------------
# Host-side correction preparation
# ---------------------------------------------------------------------------


def _int_to_limbs(x: int, n: int) -> np.ndarray:
    return np.array([(x >> (32 * i)) & 0xFFFFFFFF for i in range(n)], dtype=np.uint32)


def _leaf_values(value, structure):
    """Yields a (possibly nested) tuple value's leaves in flattened order."""
    if isinstance(structure, int):
        yield value
    else:
        for v, s in zip(value, structure):
            yield from _leaf_values(v, s)


def _build_nested(structure, leaves):
    """Inverse of _leaf_values: leaf list -> nested tuple value."""
    if isinstance(structure, int):
        return leaves[structure]
    return tuple(_build_nested(s, leaves) for s in structure)


def correction_limbs(spec: ValueSpec, corrections: Sequence) -> Tuple[np.ndarray, ...]:
    """Key correction values (epb host values) -> per-component limb arrays.

    Returns, per component c, uint32[epb, lpe_c].
    """
    out = [
        np.zeros((spec.epb, comp.lpe), dtype=np.uint32)
        for comp in spec.components
    ]
    for j, value in enumerate(corrections):
        if spec.is_tuple:
            flat = list(_leaf_values(value, spec.structure))
        else:
            flat = [value]
        for c, comp in enumerate(spec.components):
            out[c][j] = _int_to_limbs(int(flat[c]), comp.lpe)
    return tuple(out)


# ---------------------------------------------------------------------------
# Limb arithmetic primitives (static limb counts, unrolled)
# ---------------------------------------------------------------------------


def extract_bits(stream: jnp.ndarray, offset: int, width: int) -> jnp.ndarray:
    """uint32[..., S] little-endian limb stream -> uint32[..., lpe] value of
    `width` bits starting at static bit `offset`."""
    s = stream.shape[-1]
    lpe = (width + 31) // 32
    outs = []
    for l in range(lpe):
        bitoff = offset + 32 * l
        limb, sh = bitoff // 32, bitoff % 32
        lo = stream[..., limb] if limb < s else jnp.zeros_like(stream[..., 0])
        if sh:
            lo = lo >> _U32(sh)
            if limb + 1 < s:
                lo = lo | (stream[..., limb + 1] << _U32(32 - sh))
        outs.append(lo)
    rem = width - 32 * (lpe - 1)
    if rem < 32:
        outs[-1] = outs[-1] & _U32((1 << rem) - 1)
    return jnp.stack(outs, axis=-1)


def _shl1(a: jnp.ndarray) -> jnp.ndarray:
    """Limb-wise left shift by one bit over the last axis."""
    parts = [a[..., 0] << _U32(1)]
    for l in range(1, a.shape[-1]):
        parts.append((a[..., l] << _U32(1)) | (a[..., l - 1] >> _U32(31)))
    return jnp.stack(parts, axis=-1)


def _shl_const(a: jnp.ndarray, k: int, out_limbs: int) -> jnp.ndarray:
    """a << k truncated to out_limbs limbs; k static."""
    word, bit = k // 32, k % 32
    parts = []
    for l in range(out_limbs):
        src = l - word
        lo = a[..., src] if 0 <= src < a.shape[-1] else jnp.zeros_like(a[..., 0])
        if bit:
            lo = lo << _U32(bit)
            if 0 <= src - 1 < a.shape[-1]:
                lo = lo | (a[..., src - 1] >> _U32(32 - bit))
        parts.append(lo)
    return jnp.stack(parts, axis=-1)


def _ge_const(a: jnp.ndarray, c: np.ndarray) -> jnp.ndarray:
    """a >= c (elementwise over leading axes); c: uint32[n] host constant."""
    n = a.shape[-1]
    gt = jnp.zeros(a.shape[:-1], dtype=bool)
    eq = jnp.ones(a.shape[:-1], dtype=bool)
    for l in range(n - 1, -1, -1):
        cl = _U32(c[l]) if l < len(c) else _U32(0)
        gt = gt | (eq & (a[..., l] > cl))
        eq = eq & (a[..., l] == cl)
    return gt | eq


def _sub_const(a: jnp.ndarray, c: np.ndarray) -> jnp.ndarray:
    """a - c mod 2^(32n); c: uint32 host constant limbs."""
    n = a.shape[-1]
    parts = []
    borrow = jnp.zeros(a.shape[:-1], dtype=_U32)
    for l in range(n):
        cl = _U32(c[l]) if l < len(c) else _U32(0)
        t = a[..., l] - cl
        b1 = (t > a[..., l]).astype(_U32)
        d = t - borrow
        b2 = (d > t).astype(_U32)
        parts.append(d)
        borrow = b1 | b2
    return jnp.stack(parts, axis=-1)


def _rsub_const(c: np.ndarray, a: jnp.ndarray) -> jnp.ndarray:
    """c - a mod 2^(32n); c: uint32 host constant limbs."""
    n = a.shape[-1]
    parts = []
    borrow = jnp.zeros(a.shape[:-1], dtype=_U32)
    for l in range(n):
        cl = _U32(c[l]) if l < len(c) else _U32(0)
        t = cl - a[..., l]
        b1 = (t > cl).astype(_U32)
        d = t - borrow
        b2 = (d > t).astype(_U32)
        parts.append(d)
        borrow = b1 | b2
    return jnp.stack(parts, axis=-1)


def _add_wide(a: jnp.ndarray, b: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """a + b over out_limbs limbs (inputs zero-extended)."""
    parts = []
    carry = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), dtype=_U32)
    for l in range(out_limbs):
        al = a[..., l] if l < a.shape[-1] else jnp.zeros_like(carry)
        bl = b[..., l] if l < b.shape[-1] else jnp.zeros_like(carry)
        t = al + bl
        c1 = (t < al).astype(_U32)
        s = t + carry
        c2 = (s < t).astype(_U32)
        parts.append(s)
        carry = c1 | c2
    return jnp.stack(parts, axis=-1)


def _mask_low_bits(a: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Keeps the low `bits` bits of a limb array (static)."""
    n = a.shape[-1]
    parts = []
    for l in range(n):
        lo, hi = 32 * l, 32 * (l + 1)
        if hi <= bits:
            parts.append(a[..., l])
        elif lo >= bits:
            parts.append(jnp.zeros_like(a[..., l]))
        else:
            parts.append(a[..., l] & _U32((1 << (bits - lo)) - 1))
    return jnp.stack(parts, axis=-1)


def _clear_low_bits(a: jnp.ndarray, bits: int) -> jnp.ndarray:
    n = a.shape[-1]
    parts = []
    for l in range(n):
        lo, hi = 32 * l, 32 * (l + 1)
        if hi <= bits:
            parts.append(jnp.zeros_like(a[..., l]))
        elif lo >= bits:
            parts.append(a[..., l])
        else:
            parts.append(a[..., l] & _U32(~((1 << (bits - lo)) - 1) & 0xFFFFFFFF))
    return jnp.stack(parts, axis=-1)


# ---------------------------------------------------------------------------
# Mod-N arithmetic (modulus is a static Python int)
# ---------------------------------------------------------------------------


def _mul32x32(a: jnp.ndarray, b: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact u32 x u32 -> (lo, hi) u32 via 16-bit splits (no u64 needed —
    works with jax_enable_x64 off, and XLA:TPU lowers u32 natively)."""
    mask = _U32(0xFFFF)
    a0, a1 = a & mask, a >> _U32(16)
    b0, b1 = b & mask, b >> _U32(16)
    ll = a0 * b0
    lh = a0 * b1
    hl = a1 * b0
    hh = a1 * b1
    mid = (ll >> _U32(16)) + (lh & mask) + (hl & mask)
    lo = (ll & mask) | ((mid & mask) << _U32(16))
    hi = hh + (lh >> _U32(16)) + (hl >> _U32(16)) + (mid >> _U32(16))
    return lo, hi


def _mul_const_wide(v: jnp.ndarray, c: int, out_limbs: int) -> jnp.ndarray:
    """u32[..., L] limb vector x host constant c -> u32[..., out_limbs]
    (low out_limbs limbs of the exact product), schoolbook with carries."""
    L = v.shape[-1]
    c_limbs = [(c >> (32 * i)) & 0xFFFFFFFF for i in range(out_limbs)]
    acc = [jnp.zeros(v.shape[:-1], _U32) for _ in range(out_limbs)]

    def add_into(k, x):
        # acc[k:] += x with carry propagation (x: u32 array).
        carry = x
        for i in range(k, out_limbs):
            s = acc[i] + carry
            carry = (s < acc[i]).astype(_U32)
            acc[i] = s

    for i in range(L):
        for j, cl in enumerate(c_limbs):
            if cl == 0 or i + j >= out_limbs:
                continue
            lo, hi = _mul32x32(v[..., i], _U32(cl))
            add_into(i + j, lo)
            if i + j + 1 < out_limbs:
                add_into(i + j + 1, hi)
    return jnp.stack(acc, axis=-1)


def _sub_wide_vec(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b mod 2^(32n) for equal-limb u32 vectors."""
    n = a.shape[-1]
    parts = []
    borrow = jnp.zeros(a.shape[:-1], dtype=_U32)
    for l in range(n):
        t = a[..., l] - b[..., l]
        b1 = (t > a[..., l]).astype(_U32)
        d = t - borrow
        b2 = (d > t).astype(_U32)
        parts.append(d)
        borrow = b1 | b2
    return jnp.stack(parts, axis=-1)


@functools.lru_cache(maxsize=None)
def _mod_fold_plan(modulus: int, in_limbs: int = 4):
    """Host-side plan for folding a 32*in_limbs-bit value mod `modulus`.

    Returns (folds, final_shifts, work_limbs) where folds is a tuple of
    (split_limbs, C, prod_limbs) steps replacing v with
    (v >> 32*split) * C + (v mod 2^(32*split)), C = 2^(32*split) mod N —
    value preserved mod N, bound tracked exactly with Python ints — and
    final_shifts is the descending list of k for the ending
    "if v >= N << k: v -= N << k" chain. None when folding cannot beat the
    bit-serial loop (modulus far below a power of 2^32, so C stays large).
    """
    rl = max((modulus.bit_length() + 31) // 32, 1)
    C = ((1 << (32 * rl)) % modulus)
    bound = 1 << (32 * in_limbs)  # exclusive upper bound on the value
    folds = []
    for _ in range(32):
        if bound <= (modulus << 8):
            break
        hi_bound = (bound - 1) >> (32 * rl)
        if hi_bound == 0:
            break
        new_bound = hi_bound * C + (1 << (32 * rl))
        if new_bound >= bound:  # stalled (lo term dominates): finish by chain
            break
        prod_limbs = max(((hi_bound * C).bit_length() + 31) // 32, rl)
        work = max(prod_limbs, rl + 1)
        folds.append((rl, C, work))
        bound = new_bound
    if bound > (modulus << 33):  # ending chain would be too long
        return None
    final_shifts = []
    k = 0
    while (modulus << k) < bound:
        k += 1
    for s in range(k - 1, -1, -1):
        final_shifts.append(s)
    work_limbs = max((bound.bit_length() + 31) // 32, rl)
    return tuple(folds), tuple(final_shifts), work_limbs


def _mod_by_const_folded(block: jnp.ndarray, modulus: int, plan) -> jnp.ndarray:
    """Applies a _mod_fold_plan: returns block % modulus as u32 limbs
    (ceil(nbits/32) limbs), fully vectorized — no 128-step serial loop."""
    folds, final_shifts, work_limbs = plan
    v = block
    for split, C, prod_limbs in folds:
        lo = v[..., :split]
        hi = v[..., split:]
        if hi.shape[-1] == 0:
            break
        prod = _mul_const_wide(hi, C, prod_limbs)
        width = max(prod_limbs, split) + 1
        v = _add_wide(prod, lo, width)
    # Trim to the plan's working width (bound-safe).
    if v.shape[-1] > work_limbs:
        v = v[..., :work_limbs]
    elif v.shape[-1] < work_limbs:
        v = jnp.concatenate(
            [v, jnp.zeros(v.shape[:-1] + (work_limbs - v.shape[-1],), _U32)],
            axis=-1,
        )
    for s in final_shifts:
        ns = _int_to_limbs(modulus << s, work_limbs)
        ge = _ge_const(v, ns)
        v = jnp.where(ge[..., None], _sub_const(v, ns), v)
    lpe = max(((modulus - 1).bit_length() + 31) // 32, 1)
    return v[..., :lpe]


def divmod_by_const(
    block: jnp.ndarray, modulus: int, need_quotient: bool
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(block // modulus, block % modulus) for uint32[..., 4] 128-bit blocks.

    Fast path (every practical IntModN modulus — 2^32-5, 2^64-59, 2^80-65
    style primes sit just below a power of 2^32): residue folding
    v -> (v >> 32r) * (2^(32r) mod N) + (v mod 2^(32r)) with host-tracked
    exact bounds, finished by a short shift-subtract chain — ~10^2 fully
    vectorized u32 ops instead of 128 serial loop iterations. The quotient,
    needed only for the IntModN refill chain (int_mod_n.h:165-170), comes
    from one exact identity: block - r = q*N, so q = (block - r) * N^{-1}
    mod 2^128 for odd N (the Montgomery inverse is a host constant).

    Fallback (even non-power-of-2 N, or N so far below a power of 2^32 that
    folding diverges): bit-serial restoring division via ``lax.fori_loop``
    — 128 iterations of shift/compare/conditional-subtract; TPU has no
    128-bit (or even 64x64) integer divide.

    Returns (quotient uint32[..., 4], remainder uint32[..., rl]).
    """
    nbits = max(modulus.bit_length(), 1)
    if modulus & (modulus - 1) == 0:
        # Power of two: plain masking/shifting.
        shift = nbits - 1  # modulus == 2^shift
        rl = max((shift + 31) // 32, 1)
        if shift == 0:
            return block, jnp.zeros(block.shape[:-1] + (1,), _U32)
        r = _mask_low_bits(block, shift)[..., :rl]
        if shift >= 128:
            q = jnp.zeros_like(block)
        else:
            qv = extract_bits(block, shift, 128 - shift)
            pad = 4 - qv.shape[-1]
            q = jnp.concatenate(
                [qv, jnp.zeros(block.shape[:-1] + (pad,), _U32)], axis=-1
            )
        return q, r
    plan = _mod_fold_plan(modulus, block.shape[-1])
    if plan is not None and (not need_quotient or modulus % 2 == 1):
        r = _mod_by_const_folded(block, modulus, plan)
        if not need_quotient:
            return jnp.zeros(block.shape[:-1] + (4,), _U32), r
        # q = (block - r) * N^{-1} mod 2^128: block - r is exactly q*N and
        # q < 2^128, so the low-128-bit product with the odd modulus's
        # inverse recovers q exactly.
        inv = pow(modulus, -1, 1 << 128)
        pad = block.shape[-1] - r.shape[-1]
        r_pad = (
            jnp.concatenate(
                [r, jnp.zeros(r.shape[:-1] + (pad,), _U32)], axis=-1
            )
            if pad
            else r
        )
        diff = _sub_wide_vec(block, r_pad)
        q = _mul_const_wide(diff, inv, 4)
        return q, r
    rl = (nbits + 1 + 31) // 32  # remainder register holds values < 2N
    n_limbs = _int_to_limbs(modulus, rl)

    def body(i, carry):
        q, r = carry
        bit_index = _U32(127) - jnp.asarray(i, _U32)
        limb = jnp.take(block, bit_index // _U32(32), axis=-1)
        bit = (limb >> (bit_index % _U32(32))) & _U32(1)
        r = _shl1(r)
        r = r.at[..., 0].set(r[..., 0] | bit)
        ge = _ge_const(r, n_limbs)
        r = jnp.where(ge[..., None], _sub_const(r, n_limbs), r)
        if need_quotient:
            q = _shl1(q)
            q = q.at[..., 0].set(q[..., 0] | ge.astype(_U32))
        return q, r

    q0 = jnp.zeros(block.shape[:-1] + (4,), _U32)
    r0 = jnp.zeros(block.shape[:-1] + (rl,), _U32)
    q, r = jax.lax.fori_loop(0, 128, body, (q0, r0))
    lpe = max(((modulus - 1).bit_length() + 31) // 32, 1)
    return q, r[..., :lpe]


def modn_add(a: jnp.ndarray, b: jnp.ndarray, modulus: int) -> jnp.ndarray:
    """(a + b) mod modulus for limb values a, b < modulus."""
    lpe = a.shape[-1]
    wide = lpe + 1
    s = _add_wide(a, b, wide)
    n_wide = _int_to_limbs(modulus, wide)
    ge = _ge_const(s, n_wide)
    s = jnp.where(ge[..., None], _sub_const(s, n_wide), s)
    return s[..., :lpe]


def modn_neg(a: jnp.ndarray, modulus: int) -> jnp.ndarray:
    """(-a) mod modulus for limb values a < modulus."""
    n_limbs = _int_to_limbs(modulus, a.shape[-1])
    nz = jnp.zeros(a.shape[:-1], dtype=bool)
    for l in range(a.shape[-1]):
        nz = nz | (a[..., l] != 0)
    return jnp.where(nz[..., None], _rsub_const(n_limbs, a), jnp.zeros_like(a))


# ---------------------------------------------------------------------------
# Power-of-two group ops (shared with the scalar fast path)
# ---------------------------------------------------------------------------


def limb_add_pow2(a: jnp.ndarray, b: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Elementwise addition mod 2^bits on uint32[..., lpe] limb arrays."""
    if bits <= 32:
        mask = _U32((1 << bits) - 1) if bits < 32 else _U32(0xFFFFFFFF)
        return (a + b) & mask
    return _add_wide(a, b, bits // 32)


def limb_neg_pow2(a: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Two's-complement negation mod 2^bits on uint32[..., lpe] limbs."""
    if bits <= 32:
        mask = _U32((1 << bits) - 1) if bits < 32 else _U32(0xFFFFFFFF)
        return (_U32(0) - a) & mask
    out = []
    carry = _U32(1)  # ~a + 1
    for l in range(bits // 32):
        s = (~a[..., l]) + carry
        carry = jnp.where((s == 0) & (carry == 1), _U32(1), _U32(0))
        out.append(s)
    return jnp.stack(out, axis=-1)


# ---------------------------------------------------------------------------
# Sampling (device replay of FromBytes / SampleAndUpdateBytes)
# ---------------------------------------------------------------------------


def _sample_chain(stream: jnp.ndarray, spec: ValueSpec) -> Tuple[jnp.ndarray, ...]:
    """Non-direct sampling: running 128-bit block + static-offset refills.

    stream: uint32[..., 4*blocks_needed]. Returns per-component limb arrays
    uint32[..., lpe_c] (one element per block: epb == 1).
    """
    block = stream[..., :4]
    cursor = 16  # bytes; refills start after the first block
    results = []
    n = len(spec.components)
    for i, comp in enumerate(spec.components):
        update = i + 1 < n  # eval-side FromBytes: update all but the last
        if comp.kind in ("int", "xor"):
            lpe = comp.lpe
            results.append(_mask_low_bits(block[..., :lpe], comp.bits)[..., :lpe])
            if update:
                size = comp.bits // 8
                fresh = extract_bits(stream, 8 * cursor, comp.bits)
                kept = _clear_low_bits(block, comp.bits)
                padded = jnp.concatenate(
                    [fresh, jnp.zeros(block.shape[:-1] + (4 - fresh.shape[-1],), _U32)],
                    axis=-1,
                )
                block = kept | padded
                cursor += size
        else:  # modn
            q, r = divmod_by_const(block, comp.modulus, need_quotient=update)
            results.append(r)
            if update:
                size = comp.bits // 8
                shifted = (
                    jnp.zeros_like(block)
                    if comp.bits >= 128
                    else _shl_const(q, comp.bits, 4)
                )
                fresh = extract_bits(stream, 8 * cursor, comp.bits)
                padded = jnp.concatenate(
                    [fresh, jnp.zeros(block.shape[:-1] + (4 - fresh.shape[-1],), _U32)],
                    axis=-1,
                )
                block = shifted | padded
                cursor += size
    return tuple(results)


# ---------------------------------------------------------------------------
# Correction (device)
# ---------------------------------------------------------------------------


def correct_values(
    stream: jnp.ndarray,  # uint32[..., 4*blocks_needed] hashed byte stream
    control: jnp.ndarray,  # bool/uint32[...] control bits (1 = corrected)
    corrections: Tuple[jnp.ndarray, ...],  # per component uint32[epb, lpe_c]
    spec: ValueSpec,
    party: int,
) -> Tuple[jnp.ndarray, ...]:
    """hash -> elements -> += correction if control -> negate if party 1.

    Returns per-component uint32[..., epb, lpe_c] limb arrays (struct of
    arrays). Mirrors the per-element correction loop in EvaluateUntil
    (/root/reference/dpf/distributed_point_function.h:776-808).
    """
    ctrl = control.astype(_U32)[..., None, None]  # [..., 1, 1]
    if spec.direct:
        sampled = []
        for comp in spec.components:
            elems = [
                extract_bits(stream, j * spec.stride_bits + comp.offset_bits, comp.bits)
                for j in range(spec.epb)
            ]
            sampled.append(jnp.stack(elems, axis=-2))  # [..., epb, lpe]
    else:
        sampled = [v[..., None, :] for v in _sample_chain(stream, spec)]

    out = []
    for comp, elems, corr in zip(spec.components, sampled, corrections):
        c = corr * ctrl  # zero where control unset (corr < group order)
        if comp.kind == "xor":
            out.append(elems ^ c)
        elif comp.kind == "int":
            v = limb_add_pow2(elems, c, comp.bits)
            if party == 1:
                v = limb_neg_pow2(v, comp.bits)
            out.append(v)
        else:  # modn
            v = modn_add(elems, c, comp.modulus)
            if party == 1:
                v = modn_neg(v, comp.modulus)
            out.append(v)
    return tuple(out)


# ---------------------------------------------------------------------------
# In-kernel (Mosaic row) correction — the megakernel's value codec
# ---------------------------------------------------------------------------


def rows_correct_element(
    limbs, ctrl_mask, corr, bits: int, party: int, xor_group: bool
):
    """Value correction for ONE element of a hashed block, in Mosaic row
    form: every operand is a uint32 vector row (or a scalar broadcast), so
    the whole computation stays elementwise inside a Pallas kernel — the
    in-kernel twin of `_correct_values`/`correct_values` for the direct
    power-of-two codecs (Int(64)/Int(32)/u128 and their Xor wrappers; the
    multi-limb carry chain mirrors `limb_add_pow2`/`limb_neg_pow2`).

    Args:
      limbs: list of bits//32 uint32 rows — the element's hash limbs, one
        vector per limb (lane = one evaluation).
      ctrl_mask: uint32 row, 0 / ~0 per lane (1 = apply correction).
      corr: list of bits//32 uint32 scalars — this key's correction limbs.
      bits: element width; must be a multiple of 32 (sub-word codecs keep
        to the XLA paths).
      party: 0 or 1 (party 1 negates additive groups).
      xor_group: XOR group (XorWrapper) vs additive (Int).
    Returns the corrected limb rows (list of bits//32 uint32 rows).
    """
    if bits % 32:
        raise NotImplementedError(
            f"rows_correct_element handles 32-bit-multiple widths, got {bits}"
        )
    lpe = bits // 32
    gated = [corr[l] & ctrl_mask for l in range(lpe)]
    if xor_group:
        return [limbs[l] ^ gated[l] for l in range(lpe)]
    out = rows_limb_add(limbs, gated, bits)
    if party == 1:
        out = rows_limb_neg(out, bits)
    return out


def rows_limb_add(a, b, bits: int):
    """Addition mod 2^bits on two lpe-limb row lists (uint32 rows, lane =
    one evaluation) — the Mosaic-row twin of `limb_add_pow2` /
    `evaluator._limb_add`, shared by `rows_correct_element` and the walk
    megakernel's per-depth DCF accumulate (the carry chain must match the
    XLA paths bit-for-bit or the accumulated comparison shares drift)."""
    if bits % 32:
        raise NotImplementedError(
            f"rows_limb_add handles 32-bit-multiple widths, got {bits}"
        )
    out = []
    carry = None
    for l in range(bits // 32):
        s = a[l] + b[l]
        c1 = (s < a[l]).astype(_U32)
        if carry is None:
            carry = c1
        else:
            s2 = s + carry
            c2 = (s2 < s).astype(_U32)
            s, carry = s2, c1 | c2
        out.append(s)
    return out


def rows_limb_neg(a, bits: int):
    """Two's-complement negation mod 2^bits on an lpe-limb row list — the
    Mosaic-row twin of `limb_neg_pow2` / `evaluator._limb_neg` (party-1
    negation of additive shares, applied once at the end of a DCF walk)."""
    if bits % 32:
        raise NotImplementedError(
            f"rows_limb_neg handles 32-bit-multiple widths, got {bits}"
        )
    out = []
    carry = _U32(1)  # ~a + 1
    for l in range(bits // 32):
        s = (~a[l]) + carry
        carry = jnp.where((s == 0) & (carry == 1), _U32(1), _U32(0))
        out.append(s)
    return out


# ---------------------------------------------------------------------------
# Tile-padding accounting (host-side)
# ---------------------------------------------------------------------------


def tile_padded_bytes(
    shape, itemsize: int = 4, sublane: int = 8, lane: int = 128
) -> int:
    """(sublane, lane)-tile-padded byte size of an array shape — the
    host-side accounting behind the PERF.md IntModN-finalize open item
    (small trailing dims vs 8x128 tiles). TPU tiles the LAST TWO dims to
    (8, 128); every leading dim multiplies whole tiles. Used by the layout
    tests to pin that folding `lpe` into the lane dimension actually
    shrinks the padded footprint (the device's real layout choice is
    XLA's, but the logical trailing dims are what it tiles)."""
    shape = tuple(int(s) for s in shape)
    if not shape:
        return itemsize
    if len(shape) == 1:
        shape = (1,) + shape
    lead = 1
    for s in shape[:-2]:
        lead *= s
    s, l = shape[-2], shape[-1]
    return (
        lead
        * (-(-s // sublane) * sublane)
        * (-(-l // lane) * lane)
        * itemsize
    )


# ---------------------------------------------------------------------------
# Host-side views
# ---------------------------------------------------------------------------


def component_to_numpy(values: np.ndarray, comp: ComponentSpec) -> np.ndarray:
    """uint32[..., lpe] limb values of one component -> numpy integers
    (object dtype above 64 bits)."""
    values = np.asarray(values)
    lpe = values.shape[-1]
    if lpe == 1:
        bits = comp.bits if comp.kind != "modn" else 32
        if comp.kind != "modn" and bits < 32:
            return values[..., 0].astype(f"uint{max(bits, 8)}")
        return values[..., 0]
    if lpe == 2:
        return values[..., 0].astype(np.uint64) | (
            values[..., 1].astype(np.uint64) << np.uint64(32)
        )
    out = np.zeros(values.shape[:-1], dtype=object)
    for l in range(lpe):
        out |= values[..., l].astype(object) << (32 * l)
    return out


def values_to_host(arrays: Tuple[np.ndarray, ...], spec: ValueSpec) -> list:
    """Per-component limb arrays [N, lpe_c] -> flat list of host values
    (ints, or — possibly nested — tuples of ints for tuple types)
    comparable with the host path."""
    comps = [
        component_to_numpy(a, c).reshape(-1) for a, c in zip(arrays, spec.components)
    ]
    n = comps[0].shape[0]
    if not spec.is_tuple:
        return [int(v) for v in comps[0]]
    return [
        _build_nested(spec.structure, [int(comps[c][i]) for c in range(len(comps))])
        for i in range(n)
    ]
