"""Multi-host (DCN) scaling: key batches across hosts, ICI within each.

The reference has no communication backend at all — its two "parties" are
organizational, and SURVEY.md §2 fixes the green-field design: the DPF math
has *no cross-key terms*, so the key/query batch is embarrassingly parallel
across hosts. The right multi-host shape is therefore NOT one global
shard_map (which would force every input through cross-process array
construction for zero benefit): each host runs the single-host sharded
paths (parallel/sharded.py) over its OWN chips — a local (keys, domain)
mesh whose 'domain' collectives ride ICI by construction — on its OWN
contiguous slice of the key batch. DCN carries only the application-level
key scatter and the tiny [K_local, lpe] response gather.

Usage on every host of a pod/cluster:

    from distributed_point_functions_tpu.parallel import multihost, sharded
    multihost.initialize()                       # jax.distributed handshake
    mesh = multihost.local_mesh()                # this host's chips
    lo, hi = multihost.local_key_slice(num_keys) # this host's key range
    out = sharded.pir_query_batch(dpf, keys[lo:hi], db, mesh)
    # gather responses across hosts at the application layer, e.g.
    # jax.experimental.multihost_utils.process_allgather(out)

The same program runs unchanged in a single process (initialize is then a
no-op and the slice is the whole batch).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

from ..utils.errors import InvalidArgumentError
from . import sharded

_log = logging.getLogger("distributed_point_functions_tpu")


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed handshake.

    With explicit arguments (or JAX_COORDINATOR / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID), initializes exactly as told and propagates failures.
    With none, attempts jax.distributed's own cluster auto-detection (cloud
    TPU pods need no arguments); environments with no detectable cluster
    (laptops, CI, single chips) log and continue as a single process.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except Exception as e:
        if explicit or _multi_host_markers_present():
            # A detected-but-broken multi-host cluster must fail loudly:
            # proceeding single-process would silently duplicate the whole
            # key batch on every host.
            raise
        _log.info("no distributed cluster detected (%s); single process", e)


def _multi_host_markers_present() -> bool:
    """True only when the environment indicates MORE THAN ONE host/rank —
    single-node SLURM/mpirun/TPU-VM runs (value 1 / one hostname) may
    safely degrade to single-process."""
    def _gt1(name):
        try:
            return int(os.environ[name]) > 1
        except (KeyError, ValueError):
            return False

    def _gt0(name):
        try:
            return int(os.environ[name]) > 0
        except (KeyError, ValueError):
            return False

    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return (
        _gt1("SLURM_JOB_NUM_NODES")
        or _gt1("OMPI_COMM_WORLD_SIZE")
        or len([h for h in hosts.split(",") if h]) > 1
        or "MEGASCALE_COORDINATOR_ADDRESS" in os.environ
        # A nonzero worker/task rank can only come from a multi-worker pod,
        # even when the hostname list is absent or truncated. (Rank 0 is
        # indistinguishable from a single-host TPU VM — which also sets
        # TPU_WORKER_ID=0 — so worker 0 of a hostname-less broken pod still
        # degrades; raising there would break every single-host box.)
        or _gt0("TPU_WORKER_ID")
        or _gt0("CLOUD_TPU_TASK_ID")
    )


def local_mesh(
    n_key_shards: Optional[int] = None,
    n_domain_shards: Optional[int] = None,
    shape: Optional[Tuple[int, int]] = None,
):
    """A (keys, domain) mesh over THIS host's chips only.

    Domain collectives stay on the host's ICI by construction. Defaults to
    all local devices on the domain axis (n_key_shards=1).

    `shape` is the explicit ``(keys, domain)`` pair form (the tuple the
    "KxD" knobs — DPF_TPU_PIR_MESH, BENCH_PIR_MESH — parse to); mutually
    exclusive with the per-axis arguments. A shape whose product is not
    `jax.local_device_count()` raises InvalidArgumentError naming both,
    instead of surfacing as a raw mesh-construction error deep in jax.
    """
    import jax

    if shape is not None:
        if n_key_shards is not None or n_domain_shards is not None:
            raise InvalidArgumentError(
                "pass shape=(keys, domain) OR "
                "n_key_shards/n_domain_shards, not both"
            )
        try:
            n_key_shards, n_domain_shards = (int(s) for s in shape)
        except (TypeError, ValueError):
            raise InvalidArgumentError(
                f"shape must be a (keys, domain) pair, got {shape!r}"
            )
    devices = jax.local_devices()
    n_local = len(devices)
    for name, v in (("n_key_shards", n_key_shards), ("n_domain_shards", n_domain_shards)):
        if v is not None and v < 1:
            raise InvalidArgumentError(f"`{name}` must be positive, got {v}")
    if n_key_shards is None and n_domain_shards is None:
        n_key_shards, n_domain_shards = 1, n_local
    elif n_key_shards is None:
        n_key_shards = n_local // n_domain_shards
    elif n_domain_shards is None:
        n_domain_shards = n_local // n_key_shards
    if n_key_shards * n_domain_shards != n_local:
        raise InvalidArgumentError(
            f"mesh {n_key_shards} x {n_domain_shards} does not match the "
            f"local device count ({n_local})"
        )
    return sharded.make_mesh(n_key_shards, n_domain_shards, devices=devices)


def local_key_slice(num_keys: int) -> Tuple[int, int]:
    """This process's contiguous [start, stop) range of a global key batch.

    Keys are data-parallel across hosts; each host generates/loads only its
    own slice. The remainder spreads over the first hosts.
    """
    import jax

    n_proc = jax.process_count()
    pid = jax.process_index()
    base, extra = divmod(num_keys, n_proc)
    start = pid * base + min(pid, extra)
    stop = start + base + (1 if pid < extra else 0)
    return start, stop
