"""Multi-chip sharded DPF evaluation over a jax.sharding.Mesh.

The reference library has no distributed backend at all — its "distribution"
is protocol-level (two parties hold two keys). On TPU, scale comes from two
mesh axes (this module is green-field design fixed by BASELINE.json
config[4], the v5e-8 two-server PIR workload):

* ``keys``   — data parallelism over independent queries/keys. Embarrassingly
  parallel; no communication (the math has no cross-key terms).
* ``domain`` — the DPF evaluation tree is split at depth log2(n_domain):
  device d owns subtree d, *walks* the first log2(n_domain) levels along the
  path d (one masked-key AES per level), then fully expands only its own
  2^(levels - log2(n_domain)) leaves. This is the sequence-parallel analog:
  the long axis (the domain) is sharded, and only a tiny all-gather of the
  per-device partial inner products crosses the ICI.

The PIR inner product uses the XOR group: with beta = 2^128-1, the two
servers' responses XOR to DB[alpha] (share_a ^ share_b is beta at alpha and 0
elsewhere). XOR has no hardware collective, so the [K, limbs] partials ride
one ``all_gather`` over 'domain' and reduce locally — bytes on the wire:
n_domain * K * 16.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core.dpf import DistributedPointFunction
from ..core.keys import DpfKey
from ..ops import aes_jax, backend_jax, evaluator
from ..utils import errors


def make_mesh(n_key_shards: int, n_domain_shards: int, devices=None) -> Mesh:
    """A (keys, domain) mesh; n_key_shards * n_domain_shards devices."""
    if devices is None:
        devices = jax.devices()
    n = n_key_shards * n_domain_shards
    grid = np.asarray(devices[:n]).reshape(n_key_shards, n_domain_shards)
    return Mesh(grid, axis_names=("keys", "domain"))


def _pack_bits_device(bits: jnp.ndarray) -> jnp.ndarray:
    """bool[..., N] -> uint32[..., N//32] packed lane masks, device-side
    (same lane order as aes_jax.pack_bit_mask)."""
    n = bits.shape[-1]
    w = bits.reshape(bits.shape[:-1] + (n // 32, 32)).astype(jnp.uint32)
    return (w << jnp.arange(32, dtype=jnp.uint32)).sum(axis=-1).astype(jnp.uint32)


def _leaf_path_masks(base: jnp.ndarray, n_leaves: int, num_levels: int) -> jnp.ndarray:
    """Packed per-level path bits for leaves [base, base + n_leaves).

    Level l selects bit (num_levels - 1 - l) of the leaf index, as in
    backend_jax._path_bit_masks. Returns uint32[num_levels, n_leaves//32].
    """
    leaf = base.astype(jnp.uint32) + jnp.arange(n_leaves, dtype=jnp.uint32)
    shifts = (num_levels - 1 - jnp.arange(num_levels, dtype=jnp.uint32))[:, None]
    bits = ((leaf[None, :] >> shifts) & 1).astype(bool)
    return _pack_bits_device(bits)


def _walk_leaves_one_key(
    seed,  # uint32[4]
    cw_planes,  # uint32[L, 128]
    ccl,  # uint32[L]
    ccr,  # uint32[L]
    corrections,  # uint32[epb, lpe]
    leaf_base,  # uint32 traced: first leaf this device owns
    n_leaves: int,
    num_levels: int,
    party: int,
    bits: int,
    xor_group: bool,
):
    """Evaluates one key at its device's contiguous leaf range by walking all
    leaf paths at once (`evaluate_seeds_planes` scan — one traced AES body,
    so it compiles ~8x faster than the unrolled doubling in
    `_walk_and_expand_one_key` at the cost of num_levels/2 x the AES work).
    Returns uint32[n_leaves * epb, lpe] values in leaf order."""
    lanes = max(n_leaves, 32)
    seeds = jnp.broadcast_to(seed[None, :], (lanes, 4))
    planes = aes_jax.pack_to_planes(seeds)
    control = jnp.full(lanes // 32, 0xFFFFFFFF if party else 0, jnp.uint32)
    path_masks = _leaf_path_masks(leaf_base, lanes, num_levels)
    planes, control = backend_jax.evaluate_seeds_planes(
        planes, control, path_masks, cw_planes, ccl, ccr
    )
    hashed = backend_jax.hash_value_planes(planes)
    blocks = aes_jax.unpack_from_planes(hashed)
    ctrl = backend_jax.unpack_mask_device(control)
    values = evaluator._correct_values(
        blocks, ctrl, corrections, bits, party, xor_group
    )[:n_leaves]
    n_blocks, epb, lpe = values.shape
    return values.reshape(n_blocks * epb, lpe)


def _walk_and_expand_one_key(
    seed,  # uint32[4]
    cw_planes,  # uint32[L, 128]
    ccl,  # uint32[L]
    ccr,  # uint32[L]
    corrections,  # uint32[epb, lpe]
    subtree_index,  # int32 traced: which subtree this device owns
    subtree_levels: int,
    expand_levels: int,
    party: int,
    bits: int,
    xor_group: bool,
):
    """Walks `subtree_levels` down along subtree_index, expands the rest,
    hashes and corrects. Returns uint32[2^expand_levels * epb, lpe] values of
    this key restricted to the device's domain slice, in leaf order."""
    lanes = jnp.zeros((32, 4), jnp.uint32).at[0].set(seed)
    planes = aes_jax.pack_to_planes(lanes)
    control = jnp.array([party], dtype=jnp.uint32)  # lane 0 only
    if subtree_levels:
        shifts = subtree_levels - 1 - jnp.arange(subtree_levels, dtype=jnp.int32)
        bits_path = (subtree_index >> shifts) & 1
        path_masks = (jnp.uint32(0) - bits_path.astype(jnp.uint32))[:, None]
        planes, control = backend_jax.evaluate_seeds_planes(
            planes,
            control,
            path_masks,
            cw_planes[:subtree_levels],
            ccl[:subtree_levels],
            ccr[:subtree_levels],
        )
    for l in range(subtree_levels, subtree_levels + expand_levels):
        planes, control = backend_jax.expand_one_level(
            planes, control, cw_planes[l], ccl[l], ccr[l]
        )
    hashed = backend_jax.hash_value_planes(planes)
    blocks = aes_jax.unpack_from_planes(hashed)
    ctrl = backend_jax.unpack_mask_device(control)
    values = evaluator._correct_values(
        blocks, ctrl, corrections, bits, party, xor_group
    )  # [32 << expand_levels, epb, lpe]
    order = jnp.asarray(backend_jax.expansion_output_order(1, 32, expand_levels))
    values = values[order]  # [2^expand_levels, epb, lpe] leaf order
    n_blocks, epb, lpe = values.shape
    return values.reshape(n_blocks * epb, lpe)


@functools.lru_cache(maxsize=None)
def build_pir_step(
    mesh: Mesh,
    num_levels: int,
    party: int,
    bits: int = 128,
    xor_group: bool = True,
    mode: str = "expand",
):
    """Compiles one server's sharded PIR answer step.

    Returns jitted fn(seeds [K,4], cw_planes [K,L,128], ccl [K,L], ccr [K,L],
    corrections [K,epb,lpe], db [D,lpe]) -> responses [K, lpe], with K sharded
    over 'keys', the DB and the evaluation tree sharded over 'domain', and the
    XOR inner-product reduction crossing shards via all_gather.

    mode="expand" (default) uses the unrolled doubling expansion — minimal AES
    work, one traced AES circuit per level. mode="walk" walks every leaf path
    with one `lax.scan` — ~num_levels/2 x the AES work but a near-constant
    trace size, for compile-time-bound settings (tests, CPU dryrun).
    """
    if mode not in ("expand", "walk"):
        raise errors.InvalidArgumentError(
            f"mode must be 'expand' or 'walk', got {mode!r}"
        )
    n_domain = mesh.shape["domain"]
    subtree_levels = int(np.log2(n_domain))
    assert 1 << subtree_levels == n_domain, "domain shards must be a power of 2"
    expand_levels = num_levels - subtree_levels
    assert expand_levels >= 0, "domain smaller than the device mesh"
    leaves_per_shard = 1 << expand_levels

    def device_fn(seeds, cw_planes, ccl, ccr, corrections, db):
        di = jax.lax.axis_index("domain").astype(jnp.int32)
        if mode == "walk":
            fn = functools.partial(
                _walk_leaves_one_key,
                n_leaves=leaves_per_shard,
                num_levels=num_levels,
                party=party,
                bits=bits,
                xor_group=xor_group,
            )
            base = (di * leaves_per_shard).astype(jnp.uint32)
            values = jax.vmap(fn, in_axes=(0, 0, 0, 0, 0, None))(
                seeds, cw_planes, ccl, ccr, corrections, base
            )  # [Kl, elems_local, lpe]
        else:
            fn = functools.partial(
                _walk_and_expand_one_key,
                subtree_levels=subtree_levels,
                expand_levels=expand_levels,
                party=party,
                bits=bits,
                xor_group=xor_group,
            )
            values = jax.vmap(fn, in_axes=(0, 0, 0, 0, 0, None))(
                seeds, cw_planes, ccl, ccr, corrections, di
            )  # [Kl, elems_local, lpe]
        elems_local = db.shape[0]
        partial = jnp.bitwise_xor.reduce(
            values[:, :elems_local] & db[None, :, :], axis=1
        )  # [Kl, lpe]
        gathered = jax.lax.all_gather(partial, "domain")  # [n_domain, Kl, lpe]
        return jnp.bitwise_xor.reduce(gathered, axis=0)

    step = jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(
            P("keys"),  # seeds
            P("keys"),  # cw_planes
            P("keys"),  # ccl
            P("keys"),  # ccr
            P("keys"),  # corrections
            P("domain"),  # db
        ),
        out_specs=P("keys"),
        check_vma=False,
    )
    return jax.jit(step)


def pir_query_batch(
    dpf: DistributedPointFunction,
    keys: Sequence[DpfKey],
    db_limbs: np.ndarray,  # uint32[D, lpe]
    mesh: Mesh,
    mode: str = "expand",
) -> np.ndarray:
    """One server's answers for a batch of PIR queries. Returns uint32[K, lpe].

    Host-side convenience wrapper: prepares correction-word arrays from the
    keys, shards them over `mesh`, runs the compiled step.
    """
    v = dpf.validator
    hierarchy_level = v.num_hierarchy_levels - 1
    value_type = v.parameters[hierarchy_level].value_type
    bits, xor_group = evaluator._value_kind(value_type)
    domain = 1 << v.parameters[hierarchy_level].log_domain_size
    db_limbs = np.asarray(db_limbs)
    if db_limbs.shape[0] != domain:
        raise errors.InvalidArgumentError(
            f"db has {db_limbs.shape[0]} rows; the DPF domain has {domain} "
            "elements — they must match exactly"
        )
    if domain % mesh.shape["domain"]:
        raise errors.InvalidArgumentError(
            f"db rows ({domain}) must be divisible by the 'domain' mesh axis "
            f"({mesh.shape['domain']})"
        )
    backend_jax.log_backend_once()
    batch = evaluator.KeyBatch.from_keys(dpf, keys, hierarchy_level)
    # Pad the key axis to a multiple of the 'keys' mesh axis (shard_map
    # requires even divisibility); padded rows repeat key 0 and are trimmed.
    n_real = batch.seeds.shape[0]
    key_shards = mesh.shape["keys"]
    pad = (-n_real) % key_shards
    if pad:
        batch = batch.take(
            np.concatenate([np.arange(n_real), np.zeros(pad, dtype=np.int64)])
        )
    cw_planes, ccl, ccr = batch.device_cw_arrays()
    corrections = evaluator._correction_limbs(batch.value_corrections, bits)
    step = build_pir_step(
        mesh, batch.num_levels, batch.party, bits=bits, xor_group=xor_group,
        mode=mode,
    )
    out = step(
        jnp.asarray(batch.seeds),
        jnp.asarray(cw_planes),
        jnp.asarray(ccl),
        jnp.asarray(ccr),
        jnp.asarray(corrections),
        jnp.asarray(db_limbs),
    )
    return np.asarray(out)[:n_real]


# ---------------------------------------------------------------------------
# Sharded full-domain / hierarchical expansion (all value types)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def build_sharded_expand_step(
    mesh: Mesh,
    num_levels: int,
    party: int,
    spec,  # value_codec.ValueSpec (hashable)
    keep_per_block: int,
):
    """Compiles a domain-sharded full-domain expansion for one key batch.

    Device d walks log2(n_domain) levels to its subtree, expands the rest,
    hashes and value-corrects through the codec. Returns jitted
    fn(seeds [K,4], cw_planes [K,L,128], ccl, ccr, corrections pytree) ->
    values [K, domain_elems, lpe] (tuple of arrays for Tuple specs), with K
    sharded over 'keys' and the element axis over 'domain'. The analog of
    sharding the long axis in sequence parallelism: the evaluation tree
    splits at depth log2(n_domain) and no communication crosses shards at
    all (outputs stay sharded for the consumer to reduce).
    """
    from ..ops import value_codec

    n_domain = mesh.shape["domain"]
    subtree_levels = int(np.log2(n_domain))
    assert 1 << subtree_levels == n_domain, "domain shards must be a power of 2"
    expand_levels = num_levels - subtree_levels
    assert expand_levels >= 0, "domain smaller than the device mesh"

    def one_key(seed, cw_planes, ccl, ccr, corrections, subtree_index):
        lanes = jnp.zeros((32, 4), jnp.uint32).at[0].set(seed)
        planes = aes_jax.pack_to_planes(lanes)
        control = jnp.array([party], dtype=jnp.uint32)  # lane 0 only
        if subtree_levels:
            shifts = subtree_levels - 1 - jnp.arange(subtree_levels, dtype=jnp.int32)
            bits_path = (subtree_index >> shifts) & 1
            path_masks = (jnp.uint32(0) - bits_path.astype(jnp.uint32))[:, None]
            planes, control = backend_jax.evaluate_seeds_planes(
                planes,
                control,
                path_masks,
                cw_planes[:subtree_levels],
                ccl[:subtree_levels],
                ccr[:subtree_levels],
            )
        for l in range(subtree_levels, num_levels):
            planes, control = backend_jax.expand_one_level(
                planes, control, cw_planes[l], ccl[l], ccr[l]
            )
        stream = backend_jax.hash_value_stream(planes, spec.blocks_needed)
        ctrl = backend_jax.unpack_mask_device(control)
        vals = value_codec.correct_values(stream, ctrl, corrections, spec, party)
        order = jnp.asarray(
            backend_jax.expansion_output_order(1, 32, expand_levels)
        )
        outs = []
        for v in vals:  # [32 << expand_levels, epb, lpe]
            v = v[order][:, :keep_per_block]  # leaf order, trimmed blocks
            n_blocks, kept, lpe = v.shape
            outs.append(v.reshape(n_blocks * kept, lpe))
        return tuple(outs)

    def device_fn(seeds, cw_planes, ccl, ccr, corrections):
        di = jax.lax.axis_index("domain").astype(jnp.int32)
        outs = jax.vmap(
            lambda s, cw, l, r, c: one_key(s, cw, l, r, c, di),
        )(seeds, cw_planes, ccl, ccr, corrections)
        return outs if spec.is_tuple else outs[0]

    out_spec = (
        tuple(P("keys", "domain") for _ in spec.components)
        if spec.is_tuple
        else P("keys", "domain")
    )
    step = jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P("keys"), P("keys"), P("keys"), P("keys"),
                  tuple(P("keys") for _ in spec.components)),
        out_specs=out_spec,
        check_vma=False,
    )
    return jax.jit(step)


def sharded_full_domain_evaluate(
    dpf: DistributedPointFunction,
    keys: Sequence[DpfKey],
    mesh: Mesh,
    hierarchy_level: int = -1,
):
    """Full-domain evaluation sharded over a (keys, domain) mesh.

    Returns a *sharded device array* [K, domain, lpe] (tuple of arrays for
    Tuple outputs) laid out P('keys', 'domain') — downstream on-device
    consumers (PIR reductions, aggregation) keep it sharded; np.asarray
    gathers to the host. Supports every value type via the codec, unlike
    `pir_query_batch` which is specialized to the XOR inner product.
    """
    from ..ops import value_codec

    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    value_type = v.parameters[hierarchy_level].value_type
    spec = value_codec.build_spec(value_type, v.blocks_needed[hierarchy_level])
    lds = v.parameters[hierarchy_level].log_domain_size
    backend_jax.log_backend_once()
    batch = evaluator.KeyBatch.from_keys(dpf, keys, hierarchy_level)
    stop_level = batch.num_levels
    keep_per_block = 1 << (lds - stop_level)
    n_domain = mesh.shape["domain"]
    if (1 << stop_level) < n_domain:
        raise errors.InvalidArgumentError(
            f"domain tree ({1 << stop_level} leaves) smaller than the "
            f"'domain' mesh axis ({n_domain})"
        )
    n_real = batch.seeds.shape[0]
    key_shards = mesh.shape["keys"]
    pad = (-n_real) % key_shards
    idx = np.concatenate([np.arange(n_real), np.zeros(pad, dtype=np.int64)])
    step = build_sharded_expand_step(
        mesh, stop_level, batch.party, spec, keep_per_block
    )
    batch = batch.take(idx)
    cw_planes, ccl, ccr = batch.device_cw_arrays()
    corrections = tuple(jnp.asarray(a) for a in batch.codec_corrections)
    out = step(
        jnp.asarray(batch.seeds),
        jnp.asarray(cw_planes),
        jnp.asarray(ccl),
        jnp.asarray(ccr),
        corrections,
    )
    # Trim padded keys and block-packing overshoot (host-side views; the
    # sharded array itself is what on-device consumers keep).
    domain = 1 << lds
    if spec.is_tuple:
        return tuple(o[:n_real, :domain] for o in out)
    return out[:n_real, :domain]
