"""Multi-chip sharded DPF evaluation over a jax.sharding.Mesh.

The reference library has no distributed backend at all — its "distribution"
is protocol-level (two parties hold two keys). On TPU, scale comes from two
mesh axes (this module is green-field design fixed by BASELINE.json
config[4], the v5e-8 two-server PIR workload):

* ``keys``   — data parallelism over independent queries/keys. Embarrassingly
  parallel; no communication (the math has no cross-key terms).
* ``domain`` — the DPF evaluation tree is split at depth log2(n_domain):
  device d owns subtree d, *walks* the first log2(n_domain) levels along the
  path d (one masked-key AES per level), then fully expands only its own
  2^(levels - log2(n_domain)) leaves. This is the sequence-parallel analog:
  the long axis (the domain) is sharded, and only a tiny all-gather of the
  per-device partial inner products crosses the ICI.

The PIR inner product uses the XOR group: with beta = 2^128-1, the two
servers' responses XOR to DB[alpha] (share_a ^ share_b is beta at alpha and 0
elsewhere). XOR has no hardware collective, so the [K, limbs] partials ride
one ``all_gather`` over 'domain' and reduce locally — bytes on the wire:
n_domain * K * 16.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core.dpf import DistributedPointFunction
from ..core.keys import DpfKey
from ..ops import aes_jax, backend_jax, evaluator
from ..utils import envflags, errors, faultinject
from ..utils import telemetry as _tm


def make_mesh(n_key_shards: int, n_domain_shards: int, devices=None) -> Mesh:
    """A (keys, domain) mesh; n_key_shards * n_domain_shards devices."""
    if devices is None:
        devices = jax.devices()
    n = n_key_shards * n_domain_shards
    grid = np.asarray(devices[:n]).reshape(n_key_shards, n_domain_shards)
    return Mesh(grid, axis_names=("keys", "domain"))


def _pack_bits_device(bits: jnp.ndarray) -> jnp.ndarray:
    """bool[..., N] -> uint32[..., N//32] packed lane masks, device-side
    (same lane order as aes_jax.pack_bit_mask)."""
    n = bits.shape[-1]
    w = bits.reshape(bits.shape[:-1] + (n // 32, 32)).astype(jnp.uint32)
    return (w << jnp.arange(32, dtype=jnp.uint32)).sum(axis=-1).astype(jnp.uint32)


def _leaf_path_masks(base: jnp.ndarray, n_leaves: int, num_levels: int) -> jnp.ndarray:
    """Packed per-level path bits for leaves [base, base + n_leaves).

    Level l selects bit (num_levels - 1 - l) of the leaf index, as in
    backend_jax._path_bit_masks. Returns uint32[num_levels, n_leaves//32].
    """
    leaf = base.astype(jnp.uint32) + jnp.arange(n_leaves, dtype=jnp.uint32)
    shifts = (num_levels - 1 - jnp.arange(num_levels, dtype=jnp.uint32))[:, None]
    bits = ((leaf[None, :] >> shifts) & 1).astype(bool)
    return _pack_bits_device(bits)


def _walk_leaves_one_key(
    seed,  # uint32[4]
    cw_planes,  # uint32[L, 128]
    ccl,  # uint32[L]
    ccr,  # uint32[L]
    corrections,  # uint32[epb, lpe]
    leaf_base,  # uint32 traced: first leaf this device owns
    n_leaves: int,
    num_levels: int,
    party: int,
    bits: int,
    xor_group: bool,
):
    """Evaluates one key at its device's contiguous leaf range by walking all
    leaf paths at once (`evaluate_seeds_planes` scan — one traced AES body,
    so it compiles ~8x faster than the unrolled doubling in
    `_walk_and_expand_one_key` at the cost of num_levels/2 x the AES work).
    Returns uint32[n_leaves * epb, lpe] values in leaf order."""
    lanes = max(n_leaves, 32)
    seeds = jnp.broadcast_to(seed[None, :], (lanes, 4))
    planes = aes_jax.pack_to_planes(seeds)
    control = jnp.full(lanes // 32, 0xFFFFFFFF if party else 0, jnp.uint32)
    path_masks = _leaf_path_masks(leaf_base, lanes, num_levels)
    planes, control = backend_jax.evaluate_seeds_planes(
        planes, control, path_masks, cw_planes, ccl, ccr
    )
    hashed = backend_jax.hash_value_planes(planes)
    blocks = aes_jax.unpack_from_planes(hashed)
    ctrl = backend_jax.unpack_mask_device(control)
    values = evaluator._correct_values(
        blocks, ctrl, corrections, bits, party, xor_group
    )[:n_leaves]
    n_blocks, epb, lpe = values.shape
    return values.reshape(n_blocks * epb, lpe)


def _walk_and_expand_one_key(
    seed,  # uint32[4]
    cw_planes,  # uint32[L, 128]
    ccl,  # uint32[L]
    ccr,  # uint32[L]
    corrections,  # uint32[epb, lpe]
    subtree_index,  # int32 traced: which subtree this device owns
    subtree_levels: int,
    expand_levels: int,
    party: int,
    bits: int,
    xor_group: bool,
):
    """Walks down to the device's subtree, expands the rest, hashes and
    corrects. Returns uint32[2^expand_levels * epb, lpe] values of this key
    restricted to the device's domain slice, in leaf order.

    The walk descends to the 32 (= one packed lane word) subtree nodes at
    depth subtree_levels + min(5, expand_levels), one per lane, so the
    doubling expansion starts with every lane real — expanding a single
    root from a 32-lane word instead costs 32x the AES work and 32x the
    plane memory (the difference between ~1 GB and ~32 GB of temporaries
    per 8 queries at a 2^24 domain)."""
    lane_levels = min(5, expand_levels)
    n_lane = 1 << lane_levels
    walk_levels = subtree_levels + lane_levels
    seeds = jnp.broadcast_to(seed[None, :], (32, 4))
    planes = aes_jax.pack_to_planes(seeds)
    control = jnp.full(1, 0xFFFFFFFF if party else 0, jnp.uint32)
    if walk_levels:
        # Lane l follows the path to subtree node subtree_index * n_lane +
        # (l mod n_lane) at depth walk_levels (lanes >= n_lane duplicate
        # lane l mod n_lane; expansion_output_order dedups below).
        node = subtree_index.astype(jnp.uint32) * jnp.uint32(n_lane) + (
            jnp.arange(32, dtype=jnp.uint32) % jnp.uint32(n_lane)
        )
        shifts = (walk_levels - 1 - jnp.arange(walk_levels, dtype=jnp.uint32))[
            :, None
        ]
        bits_path = ((node[None, :] >> shifts) & 1).astype(bool)
        path_masks = _pack_bits_device(bits_path)  # [walk_levels, 1]
        planes, control = backend_jax.evaluate_seeds_planes(
            planes,
            control,
            path_masks,
            cw_planes[:walk_levels],
            ccl[:walk_levels],
            ccr[:walk_levels],
        )
    for l in range(walk_levels, subtree_levels + expand_levels):
        planes, control = backend_jax.expand_one_level(
            planes, control, cw_planes[l], ccl[l], ccr[l]
        )
    hashed = backend_jax.hash_value_planes(planes)
    blocks = aes_jax.unpack_from_planes(hashed)
    ctrl = backend_jax.unpack_mask_device(control)
    values = evaluator._correct_values(
        blocks, ctrl, corrections, bits, party, xor_group
    )  # [32 << (expand_levels - lane_levels), epb, lpe]
    order = jnp.asarray(
        backend_jax.expansion_output_order(n_lane, 32, expand_levels - lane_levels)
    )
    values = values[order]  # [2^expand_levels, epb, lpe] leaf order
    n_blocks, epb, lpe = values.shape
    return values.reshape(n_blocks * epb, lpe)


@functools.lru_cache(maxsize=None)
def build_pir_step(
    mesh: Mesh,
    num_levels: int,
    party: int,
    bits: int = 128,
    xor_group: bool = True,
    mode: str = "expand",
    slab_levels: int = 0,
):
    """Compiles one server's sharded PIR answer step.

    Returns jitted fn(seeds [K,4], cw_planes [K,L,128], ccl [K,L], ccr [K,L],
    corrections [K,epb,lpe], db [D,lpe]) -> responses [K, lpe], with K sharded
    over 'keys', the DB and the evaluation tree sharded over 'domain', and the
    XOR inner-product reduction crossing shards via all_gather.

    mode="expand" (default) uses the unrolled doubling expansion — minimal AES
    work, one traced AES circuit per level. mode="walk" walks every leaf path
    with one `lax.scan` — ~num_levels/2 x the AES work but a near-constant
    trace size, for compile-time-bound settings (tests, CPU dryrun).

    slab_levels > 0 (expand mode) bounds HBM: each device processes its
    domain slice in 2^slab_levels slabs inside a `lax.fori_loop`, walking
    slab_levels extra levels and XOR-accumulating the partial inner product
    per slab — memory drops 2^slab_levels x for slab_levels extra AES walks
    per slab (a 2^24-domain query on one v5e chip needs ~32 GB of plane
    temporaries unslabbed; 8 slabs fit comfortably).
    """
    if mode not in ("expand", "walk"):
        raise errors.InvalidArgumentError(
            f"mode must be 'expand' or 'walk', got {mode!r}"
        )
    n_domain = mesh.shape["domain"]
    subtree_levels = int(np.log2(n_domain))
    assert 1 << subtree_levels == n_domain, "domain shards must be a power of 2"
    expand_levels = num_levels - subtree_levels
    assert expand_levels >= 0, "domain smaller than the device mesh"
    leaves_per_shard = 1 << expand_levels
    if slab_levels and mode != "expand":
        raise errors.InvalidArgumentError("slab_levels requires mode='expand'")
    if slab_levels > expand_levels:
        slab_levels = expand_levels

    def device_fn(seeds, cw_planes, ccl, ccr, corrections, db):
        di = jax.lax.axis_index("domain").astype(jnp.int32)
        elems_local = db.shape[0]
        if mode == "walk":
            fn = functools.partial(
                _walk_leaves_one_key,
                n_leaves=leaves_per_shard,
                num_levels=num_levels,
                party=party,
                bits=bits,
                xor_group=xor_group,
            )
            base = (di * leaves_per_shard).astype(jnp.uint32)
            values = jax.vmap(fn, in_axes=(0, 0, 0, 0, 0, None))(
                seeds, cw_planes, ccl, ccr, corrections, base
            )  # [Kl, elems_local, lpe]
            partial = jnp.bitwise_xor.reduce(
                values[:, :elems_local] & db[None, :, :], axis=1
            )  # [Kl, lpe]
        else:
            n_slabs = 1 << slab_levels
            elems_slab = elems_local // n_slabs
            fn = functools.partial(
                _walk_and_expand_one_key,
                subtree_levels=subtree_levels + slab_levels,
                expand_levels=expand_levels - slab_levels,
                party=party,
                bits=bits,
                xor_group=xor_group,
            )

            def slab_partial(j):
                sub = di * n_slabs + j.astype(jnp.int32)
                values = jax.vmap(fn, in_axes=(0, 0, 0, 0, 0, None))(
                    seeds, cw_planes, ccl, ccr, corrections, sub
                )  # [Kl, elems_slab, lpe]
                dbj = jax.lax.dynamic_slice_in_dim(
                    db, j.astype(jnp.int32) * elems_slab, elems_slab
                )
                return jnp.bitwise_xor.reduce(
                    values[:, :elems_slab] & dbj[None, :, :], axis=1
                )  # [Kl, lpe]

            if n_slabs == 1:
                partial = slab_partial(jnp.int32(0))
            else:
                partial = jax.lax.fori_loop(
                    0,
                    n_slabs,
                    lambda j, acc: acc ^ slab_partial(jnp.int32(j)),
                    jnp.zeros((seeds.shape[0], db.shape[1]), jnp.uint32),
                )
        gathered = jax.lax.all_gather(partial, "domain")  # [n_domain, Kl, lpe]
        return jnp.bitwise_xor.reduce(gathered, axis=0)

    step = backend_jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(
            P("keys"),  # seeds
            P("keys"),  # cw_planes
            P("keys"),  # ccl
            P("keys"),  # ccr
            P("keys"),  # corrections
            P("domain"),  # db
        ),
        out_specs=P("keys"),
    )
    return jax.jit(step)


def pir_mesh_from_env():
    """Optional serving-default PIR mesh from the DPF_TPU_PIR_MESH env
    ("KxD", e.g. "2x4" — keys x domain shards, utils/envflags). Returns
    None when unset, so single-device deployments pay nothing; a malformed
    value raises InvalidArgumentError rather than silently running
    unsharded."""
    spec = envflags.env_str("DPF_TPU_PIR_MESH", "")
    if not spec:
        return None
    parts = spec.lower().split("x")
    if len(parts) != 2 or not all(p.isdigit() and int(p) > 0 for p in parts):
        raise errors.InvalidArgumentError(
            f"DPF_TPU_PIR_MESH must be 'KxD' (keys x domain shards, e.g. "
            f"'2x4'), got {spec!r}"
        )
    return make_mesh(int(parts[0]), int(parts[1]))


def _mesh_desc(mesh) -> str:
    """'KxD' (or 'none (single-device)') for error messages."""
    if mesh is None:
        return "none (single-device)"
    return f"{mesh.shape['keys']}x{mesh.shape['domain']}"


@functools.lru_cache(maxsize=None)
def build_sharded_megakernel_step(
    mesh: Mesh,
    plan,  # evaluator.MegakernelPlan — the PER-SHARD plan
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    engine: str,  # "pallas" (real Mosaic kernel) | "replay" (XLA reference)
):
    """Compiles one server's mesh-sharded slab-megakernel PIR step.

    Returns jitted fn(seeds [K, M, 4], control_mask [K, M//32],
    cw_planes [K, L, 128], ccl [K, L], ccr [K, L], corrections
    [K, epb, lpe], db_rows [keep*lpe*32, D*shard_words]) -> [K, lpe]:
    keys sharded over 'keys'; the entry-plane tile AND the DB's
    megakernel-order rows sharded over 'domain'; ONE program per call.

    The sharding trick is the entry-plane fast-forward: at level
    host_levels the entry lane index IS the tree node id, and the
    doubling expansion applies the same per-level correction words to
    every lane — so shard d's kernel, run UNCHANGED on its contiguous
    slice of the entry tile with the per-shard plan
    (evaluator.plan_megakernel(domain_shards=D)), computes exactly the
    leaves of domain slice [d*domain/D, (d+1)*domain/D) and ANDs them
    against its own DB tile streamed from its own HBM. Each shard emits a
    [Kl, lpe] partial inner product; XOR has no hardware collective, so
    the partials ride one all_gather over 'domain' and reduce locally
    (the `build_pir_step` tail — bytes on the wire: D * Kl * lpe * 4).

    `engine` picks the per-shard fold program: "pallas" is the real
    Mosaic megakernel (`aes_pallas.megakernel_fold_pallas_batched`,
    kernel body untouched — the Mosaic surface and the dpflint
    mosaic-opset baseline stay frozen); "replay" traces
    `megakernel_reference_rows` as a plain XLA program — the off-TPU
    default, so the forced-host-device mesh tests and dryruns add ZERO
    interpret-pallas compile configs (pallas-inside-shard_map stays
    staged for a hardware window)."""
    if engine not in ("pallas", "replay"):
        raise errors.InvalidArgumentError(
            f"engine must be 'pallas' or 'replay', got {engine!r}"
        )
    from ..ops import aes_pallas

    def device_fn(seeds, control_mask, cw_planes, ccl, ccr, corrections, db_rows):
        # Pack INSIDE the sharded program: the whole per-chunk computation
        # (pack + expand + in-kernel inner product + collective) is one
        # device program — the megakernel's one-dispatch-per-chunk contract
        # survives sharding (tests/test_dispatch_audit.py pins it).
        planes = jax.vmap(aes_jax.pack_to_planes)(seeds)  # [Kl, 128, ew]
        if engine == "pallas":
            folds = aes_pallas.megakernel_fold_pallas_batched(
                planes, control_mask, cw_planes, ccl, ccr, corrections,
                db_rows, plan=plan, bits=bits, party=party,
                xor_group=xor_group, keep=keep,
            )  # [Kl, lpe, fold_words]
            partial = jnp.bitwise_xor.reduce(folds, axis=2)
        else:
            ref = functools.partial(
                aes_pallas.megakernel_reference_rows,
                plan=plan, bits=bits, party=party,
                xor_group=xor_group, keep=keep,
            )
            partial = jax.vmap(ref, in_axes=(0, 0, 0, 0, 0, 0, None))(
                planes, control_mask, cw_planes, ccl, ccr, corrections,
                db_rows,
            )  # [Kl, lpe]
        gathered = jax.lax.all_gather(partial, "domain")  # [D, Kl, lpe]
        return jnp.bitwise_xor.reduce(gathered, axis=0)

    step = backend_jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(
            P("keys", "domain", None),  # seeds: entry lanes follow the tree
            P("keys", "domain"),  # control_mask: whole packed entry words
            P("keys"),  # cw_planes
            P("keys"),  # ccl
            P("keys"),  # ccr
            P("keys"),  # corrections
            P(None, "domain"),  # db_rows: one column block per shard
        ),
        out_specs=P("keys"),
    )
    return jax.jit(step)


def _sharded_megakernel_fold_chunks(
    dpf, keys, pdb, mesh, key_chunk, host_levels, pipeline
):
    """Yields (num_valid_keys, fold [chunk, lpe] sharded P('keys')) per key
    chunk through `build_sharded_megakernel_step` — the mesh twin of
    evaluator.full_domain_fold_chunks(mode='megakernel'). Host chunk prep
    stays on `evaluator._prepare_chunk_host`; every device upload is a
    shard-direct `device_put` onto its NamedSharding (a transfer, never a
    device program — uploading single-device and letting shard_map reshard
    costs extra eager dispatches per chunk, the round-5 audit lesson)."""
    from jax.sharding import NamedSharding

    from ..ops import pipeline as _pl

    v = dpf.validator
    hierarchy_level = v.num_hierarchy_levels - 1
    value_type = v.parameters[hierarchy_level].value_type
    bits, xor_group = evaluator._value_kind(value_type)
    if bits % 32:
        raise NotImplementedError(
            f"megakernel value correction handles 32-bit-multiple widths "
            f"(Int/XorWrapper 32/64/128), got {bits}-bit values"
        )
    batch = evaluator.KeyBatch.from_keys(dpf, keys, hierarchy_level)
    spec = batch.spec
    if not (spec.is_scalar_direct and spec.blocks_needed == 1):
        raise NotImplementedError(
            "the sharded megakernel folds scalar Int/XorWrapper value "
            "types; evaluate IntModN/Tuple outputs via "
            "sharded_full_domain_evaluate"
        )
    stop = batch.num_levels
    lds = v.parameters[hierarchy_level].log_domain_size
    keep = 1 << (lds - stop)
    plan = pdb.plan  # the PER-SHARD plan (prepare validated it)
    hl = plan.host_levels
    evaluator._inject_batch_faults(batch, True)
    backend_jax.log_backend_once()

    # Pad the key axis to a multiple of the 'keys' mesh axis and make the
    # chunk width a shard multiple too, so every chunk's shard_map splits
    # evenly (padded rows repeat key 0; the caller trims).
    n_keys = batch.seeds.shape[0]
    k_shards = mesh.shape["keys"]
    pad = (-n_keys) % k_shards
    if pad:
        batch = batch.take(
            np.concatenate([np.arange(n_keys), np.zeros(pad, dtype=np.int64)])
        )
    n_padded = n_keys + pad
    key_chunk = max(k_shards, -(-int(key_chunk) // k_shards) * k_shards)
    # Off-TPU the per-shard fold runs the XLA replay of the SAME slab
    # computation (zero interpret-pallas configs on the forced-host mesh);
    # on TPU it is the real Mosaic megakernel, unchanged.
    engine = "pallas" if jax.default_backend() == "tpu" else "replay"
    _tm.decision("pir_query_batch_chunked", f"sharded-megakernel/{engine}",
                 "backend-default")
    step = build_sharded_megakernel_step(
        mesh, plan, bits, batch.party, xor_group, keep, engine
    )
    ks_s = NamedSharding(mesh, P("keys"))
    kd3_s = NamedSharding(mesh, P("keys", "domain", None))
    kd2_s = NamedSharding(mesh, P("keys", "domain"))
    db_dev = pdb.lane_db

    def _dispatch(kb, valid):
        seeds_h, control_mask, cw, ccl, ccr, corr, _m = (
            evaluator._prepare_chunk_host(kb, hl, True, bits)
        )
        if _tm.enabled():
            _tm.counter(
                "bytes.h2d",
                _tm.nbytes_of([seeds_h, control_mask, cw, ccl, ccr, corr]),
            )
        return valid, step(
            jax.device_put(seeds_h, kd3_s),
            jax.device_put(control_mask, kd2_s),
            jax.device_put(cw, ks_s),
            jax.device_put(ccl, ks_s),
            jax.device_put(ccr, ks_s),
            jax.device_put(corr, ks_s),
            db_dev,
        )

    def _thunks():
        for kb, valid in evaluator._key_chunks(batch, n_padded, key_chunk):
            yield functools.partial(_dispatch, kb, valid)

    pipe = _pl.resolve(pipeline)
    yield from _pl.prefetch_thunks(
        _thunks(), pipe, backend="pallas", op="pir_query_batch_chunked"
    )


def _pir_probe(dpf, keys, integrity_flag, context: str, backend: str):
    """PIR-side alias of the shared probe setup (utils/integrity.py).
    `backend` is the fault-injection level of the call, so backend-scoped
    wire fault plans keep their scope on the PIR paths."""
    from ..utils import integrity as _integrity

    return _integrity.setup_probe(
        dpf, -1, keys, integrity_flag, context, backend=backend
    )


def _pir_verify_fold(
    probe, responses: np.ndarray, db_natural, context: str, backend: str
):
    """Strips and checks the probe's response row: its XOR fold against the
    natural-order DB is recomputed from the host oracle
    (utils/integrity.verify_probe_fold). Returns responses without the
    probe row; raises DataCorruptionError on mismatch. `backend` is the
    fault-injection level the responses were computed on, so
    backend-scoped plans keep their scope on the PIR paths (the "bit4"
    pattern has no position axis here — corrupt PIR responses with
    pattern="lane")."""
    from ..utils import integrity as _integrity

    responses = faultinject.corrupt_output(
        responses[:, None, :], backend=backend
    )[:, 0, :]
    if probe is None:
        return responses
    _integrity.verify_probe_fold(
        probe,
        responses[-1],
        db_limbs=db_natural,
        context=context,
        key_index=responses.shape[0] - 1,
    )
    return responses[:-1]


def pir_query_batch(
    dpf: DistributedPointFunction,
    keys: Sequence[DpfKey],
    db_limbs: np.ndarray,  # uint32[D, lpe]
    mesh: Mesh,
    mode: str = "expand",
    slab_levels=None,
    integrity=None,
) -> np.ndarray:
    """One server's answers for a batch of PIR queries. Returns uint32[K, lpe].

    Host-side convenience wrapper: prepares correction-word arrays from the
    keys, shards them over `mesh`, runs the compiled step. slab_levels=None
    picks the smallest slab count that keeps each device's expansion
    temporaries under ~DPF_TPU_PIR_SLAB_BUDGET bytes (default 2 GB).

    `integrity` (None = DPF_TPU_INTEGRITY env default) appends one sentinel
    probe key to the batch; its folded response is recomputed on the host
    oracle and a mismatch raises DataCorruptionError — a silently corrupted
    PIR answer is a wrong answer handed to a client (utils/integrity.py).
    With a bare device-resident `db_limbs` the verification fold pulls the
    DB to the host once per call; a natural-order PreparedPirDatabase
    (prepare_pir_database(..., order="natural")) caches that host copy, so
    serving loops pay the pull once at setup.
    """
    import math
    v = dpf.validator
    hierarchy_level = v.num_hierarchy_levels - 1
    keys, probe = _pir_probe(dpf, keys, integrity, "pir_query_batch", "jax")
    value_type = v.parameters[hierarchy_level].value_type
    bits, xor_group = evaluator._value_kind(value_type)
    domain = 1 << v.parameters[hierarchy_level].log_domain_size
    db_prepared = None
    if isinstance(db_limbs, PreparedPirDatabase):
        if db_limbs.order != "natural":
            raise errors.InvalidArgumentError(
                "pir_query_batch folds against the natural-order DB; this "
                "PreparedPirDatabase is lane-ordered (only "
                "pir_query_batch_chunked consumes that order) — prepare "
                "with order='natural'"
            )
        db_prepared = db_limbs
        db_limbs = db_prepared.lane_db
    if not isinstance(db_limbs, jax.Array):  # keep device-resident DBs put
        db_limbs = np.asarray(db_limbs)
    if db_limbs.shape[0] != domain:
        raise errors.InvalidArgumentError(
            f"db has {db_limbs.shape[0]} rows; the DPF domain has {domain} "
            "elements — they must match exactly"
        )
    if domain % mesh.shape["domain"]:
        raise errors.InvalidArgumentError(
            f"db rows ({domain}) must be divisible by the 'domain' mesh axis "
            f"({mesh.shape['domain']})"
        )
    backend_jax.log_backend_once()
    batch = evaluator.KeyBatch.from_keys(dpf, keys, hierarchy_level)
    # Pad the key axis to a multiple of the 'keys' mesh axis (shard_map
    # requires even divisibility); padded rows repeat key 0 and are trimmed.
    n_real = batch.seeds.shape[0]
    key_shards = mesh.shape["keys"]
    pad = (-n_real) % key_shards
    if pad:
        batch = batch.take(
            np.concatenate([np.arange(n_real), np.zeros(pad, dtype=np.int64)])
        )
    cw_planes, ccl, ccr = batch.device_cw_arrays()
    corrections = evaluator._correction_limbs(batch.value_corrections, bits)
    if slab_levels is None:
        slab_levels = 0
        if mode == "expand":
            n_domain = mesh.shape["domain"]
            expand_levels = batch.num_levels - int(np.log2(n_domain))
            keys_local = -(-batch.seeds.shape[0] // mesh.shape["keys"])
            # ~16 B/leaf of plane state, ~4x for fusion temporaries.
            est = keys_local * (1 << max(expand_levels, 0)) * 16 * 4
            budget = envflags.env_int("DPF_TPU_PIR_SLAB_BUDGET", 2 << 30)
            if est > budget:
                slab_levels = min(
                    max(expand_levels, 0), math.ceil(math.log2(est / budget))
                )
    step = build_pir_step(
        mesh, batch.num_levels, batch.party, bits=bits, xor_group=xor_group,
        mode=mode, slab_levels=int(slab_levels),
    )
    # Host inputs go straight onto their shards (a transfer, not a device
    # program): uploaded single-device, the shard_map call resharded every
    # argument with its own eager program — 6 extra dispatches per query
    # batch (round-5 program audit). Device-resident arrays (a prepared
    # DB) pass through untouched.
    from jax.sharding import NamedSharding

    ks = NamedSharding(mesh, P("keys"))

    def put(x, s):
        return x if isinstance(x, jax.Array) else jax.device_put(np.asarray(x), s)

    out = step(
        put(batch.seeds, ks),
        put(cw_planes, ks),
        put(ccl, ks),
        put(ccr, ks),
        put(corrections, ks),
        put(db_limbs, NamedSharding(mesh, P("domain"))),
    )
    res = np.asarray(out)[:n_real]
    db_nat = None
    if probe is not None:
        db_nat = (
            db_prepared.natural_host(dpf)
            if db_prepared is not None
            else np.asarray(db_limbs)
        )
    # The shard_map step is an XLA program on every platform: level "jax".
    return _pir_verify_fold(probe, res, db_nat, "pir_query_batch", "jax")


@jax.jit
def _pir_fold_jit(values, db_lane):
    """XOR inner product of lane-order values against a lane-order DB."""
    return jnp.bitwise_xor.reduce(values & db_lane[None, :, :], axis=1)


@functools.partial(jax.jit, donate_argnums=(0,))
def _pir_fold_jit_donated(values, db_lane):
    """`_pir_fold_jit` DONATING the values buffer: the [chunk, domain, lpe]
    chunk output (100+ MB at serving shapes) is dead after the fold, and
    donation lets XLA reuse it instead of accumulating toward the
    RESOURCE_EXHAUSTED cliff / HBM-eviction stalls (PERF.md). The DB is
    never donated — it is the long-lived prepared buffer."""
    return jnp.bitwise_xor.reduce(values & db_lane[None, :, :], axis=1)


@jax.jit
def _pir_fold_slab_jit(values, db, off):
    """XOR inner product of a leaf-contiguous values piece against rows
    [off, off + piece) of a natural-order DB (one compile for any offset)."""
    piece = jax.lax.dynamic_slice_in_dim(db, off, values.shape[1], axis=0)
    return jnp.bitwise_xor.reduce(values & piece[None, :, :], axis=1)


@functools.partial(jax.jit, donate_argnums=(0,))
def _pir_fold_slab_jit_donated(values, db, off):
    """Donating variant of `_pir_fold_slab_jit` (see _pir_fold_jit_donated)."""
    piece = jax.lax.dynamic_slice_in_dim(db, off, values.shape[1], axis=0)
    return jnp.bitwise_xor.reduce(values & piece[None, :, :], axis=1)


def _pir_fold(values, db_lane):
    """Fold + release of a chunk's value buffer: input-buffer donation on
    backends that implement it (ops/pipeline.donate_default — TPU, or
    DPF_TPU_DONATE=1), the explicit post-dispatch `delete()` elsewhere.
    Either way the 100+ MB buffer is reclaimed before the next chunk's
    expansion temporaries land — a live extra chunk pushes past HBM and
    the runtime starts evicting buffers across the host link (the
    difference between 0.1 s and 5 s per chunk, PERF.md)."""
    from ..ops import pipeline as _pl

    if _pl.donate_default():
        return _pir_fold_jit_donated(values, db_lane)
    out = _pir_fold_jit(values, db_lane)
    values.delete()
    return out


def _pir_fold_slab(values, db, off):
    """Slab-piece analog of `_pir_fold`."""
    from ..ops import pipeline as _pl

    if _pl.donate_default():
        return _pir_fold_slab_jit_donated(values, db, off)
    out = _pir_fold_slab_jit(values, db, off)
    values.delete()
    return out


class PreparedPirDatabase:
    """Device-resident PIR database (prepare_pir_database), in the row
    order of the evaluation mode that will consume it: "lane" (expansion
    lane order, for the per-level mode's gather-free fold), "natural"
    (domain order, for walk mode whose lane i IS leaf i), or "megakernel"
    (the streaming row layout the slab megakernel's in-kernel inner
    product ANDs against — evaluator.megakernel_db_rows).

    A distinct type on purpose: for epb=1 value types the lane-ordered
    array has exactly `domain` rows, so a bare device array would pass
    `pir_query_batch`'s shape check and silently produce XOR inner
    products against a permuted DB."""

    __slots__ = ("lane_db", "order", "host_levels", "plan", "mesh",
                 "_nat_host")

    def __init__(self, lane_db, order: str = "lane", host_levels=None,
                 plan=None, mesh=None):
        self.lane_db = lane_db
        self.order = order
        self.host_levels = host_levels  # the lane permutation's parameter
        self.plan = plan  # megakernel order: the MegakernelPlan it encodes
        self.mesh = mesh  # sharded megakernel: the Mesh the layout targets
        self._nat_host = None

    def natural_host(self, dpf) -> np.ndarray:
        """Natural-order host copy for sentinel verification: one device
        pull (plus, for permuted orders, the inverse of the prepare-time
        permutation), computed on first use and cached — the DB is
        immutable, so serving loops pay this once, not per query batch
        (the host link runs at megabytes/s through this image's tunnel,
        PERF.md)."""
        if self._nat_host is None:
            from ..ops import evaluator as ev

            lane_host = np.asarray(self.lane_db)
            if self.order == "natural":
                self._nat_host = lane_host
            elif self.order == "megakernel":
                # Invert megakernel_db_rows: row (e*lpe + l)*32 + i at
                # word w holds limb l of element e of the block at global
                # lane 32w+i, whose domain row is leaves[g]*keep + e. Mesh
                # layouts concatenate one such tile per domain shard along
                # the word axis; shard d's local leaf g is global leaf
                # g + d * leaves_per_shard (the entry-plane fast-forward:
                # contiguous domain slices per shard).
                v = dpf.validator
                stop = v.hierarchy_to_tree[-1]
                lds = v.parameters[-1].log_domain_size
                keep = 1 << (lds - stop)
                lpe = lane_host.shape[0] // (keep * 32)
                leaves = ev._megakernel_block_leaves(self.plan)
                d_shards = (
                    self.mesh.shape["domain"] if self.mesh is not None else 1
                )
                shard_w = lane_host.shape[1] // d_shards
                nat = np.zeros(((1 << lds), lpe), np.uint32)
                for d in range(d_shards):
                    shard = lane_host[:, d * shard_w : (d + 1) * shard_w]
                    blocks = (leaves + d * leaves.shape[0]).reshape(-1, 32)
                    for e in range(keep):
                        rows = blocks * keep + e
                        for l in range(lpe):
                            nat[rows, l] = shard[
                                (e * lpe + l) * 32 : (e * lpe + l + 1) * 32, :
                            ].T
                self._nat_host = nat
            else:
                # Invert the one-time permutation to recover the
                # natural-order rows the oracle fold masks against (padded
                # lane positions hold zeros and map to no domain row).
                m = ev.lane_order_map(dpf, -1, self.host_levels)
                domain = 1 << dpf.validator.parameters[-1].log_domain_size
                nat = np.zeros((domain, lane_host.shape[1]), np.uint32)
                valid = m >= 0
                nat[m[valid]] = lane_host[valid]
                self._nat_host = nat
        return self._nat_host


def prepare_pir_database(
    dpf: DistributedPointFunction,
    db_limbs: np.ndarray,  # uint32[D, lpe]
    host_levels=None,
    order: str = "lane",
    mesh: Mesh = None,
) -> "PreparedPirDatabase":
    """Uploads a PIR database to the device ONCE, permuted for its consumer:
    order="lane" (default) permutes into the per-level expansion's lane
    order so the fold needs no gather; order="natural" uploads domain order
    as-is (walk-mode output is domain-trimmed) for `pir_query_batch_chunked`
    mode="walk"; order="megakernel" builds the streaming row layout the
    slab megakernel's in-kernel inner product consumes (one contiguous
    [keep*lpe*32, final_words] tile per domain slab, DMA'd into VMEM per
    grid step — evaluator.megakernel_db_rows). A PIR server's DB is
    static: re-uploading it per query batch would put the host link
    (megabytes/s through this image's tunnel) on the query path — prepare
    at setup, query forever after.

    `mesh` (order="megakernel" only) lays the rows out for the
    mesh-sharded megakernel path: the domain splits into
    mesh.shape['domain'] contiguous slices (shard d owns
    [d*D/n, (d+1)*D/n) — at the entry plane the lane index IS the tree
    node id, so each shard's subtree covers exactly its slice), each
    slice gets its OWN megakernel row tile under the per-shard plan
    (evaluator.plan_megakernel(domain_shards=n) — slabs sized against
    per-chip VMEM, so total DB capacity scales linearly with domain
    shards), and the concatenated [keep*lpe*32, n*shard_words] array
    uploads via ONE `device_put` onto NamedSharding(P(None, 'domain')) —
    each column block lands shard-direct on its owning chip as a
    transfer; nothing reshards a 100+MB array post-hoc (the round-5
    dispatch-audit lesson)."""
    from ..ops import evaluator as ev

    v = dpf.validator
    hierarchy_level = v.num_hierarchy_levels - 1
    domain = 1 << v.parameters[hierarchy_level].log_domain_size
    db_limbs = np.asarray(db_limbs)
    if db_limbs.shape[0] != domain:
        raise errors.InvalidArgumentError(
            f"db has {db_limbs.shape[0]} rows; the DPF domain has {domain} "
            "elements — they must match exactly"
        )
    if mesh is not None and order != "megakernel":
        raise errors.InvalidArgumentError(
            f"mesh-sharded preparation exists only for order='megakernel' "
            f"(got order={order!r}); the other orders feed single-device "
            "consumers"
        )
    if order == "natural":
        # Walk-mode output is already trimmed to the domain, so the natural
        # DB uploads as-is.
        return PreparedPirDatabase(jnp.asarray(db_limbs), order="natural")
    if order == "megakernel":
        if mesh is not None:
            from jax.sharding import NamedSharding

            d_shards = mesh.shape["domain"]
            plan = ev.plan_megakernel(
                dpf, hierarchy_level, host_levels, domain_shards=d_shards
            )
            per = domain // d_shards
            rows = np.concatenate(
                [
                    ev.megakernel_db_rows(
                        dpf, db_limbs[d * per : (d + 1) * per], plan,
                        hierarchy_level,
                    )
                    for d in range(d_shards)
                ],
                axis=1,
            )
            lane = jax.device_put(
                rows, NamedSharding(mesh, P(None, "domain"))
            )
            return PreparedPirDatabase(
                lane, order="megakernel",
                host_levels=plan.host_levels, plan=plan, mesh=mesh,
            )
        plan = ev.plan_megakernel(dpf, hierarchy_level, host_levels)
        rows = ev.megakernel_db_rows(dpf, db_limbs, plan, hierarchy_level)
        return PreparedPirDatabase(
            jnp.asarray(rows), order="megakernel",
            host_levels=plan.host_levels, plan=plan,
        )
    if order != "lane":
        raise errors.InvalidArgumentError(
            f"order must be 'lane', 'natural' or 'megakernel', got {order!r}"
        )
    m = ev.lane_order_map(dpf, hierarchy_level, host_levels)
    db_lane = np.zeros((m.shape[0], db_limbs.shape[1]), dtype=np.uint32)
    valid = m >= 0
    db_lane[valid] = db_limbs[m[valid]]
    return PreparedPirDatabase(
        jnp.asarray(db_lane), order="lane", host_levels=host_levels
    )


@_tm.traced("pir_query_batch_chunked")
def pir_query_batch_chunked(
    dpf: DistributedPointFunction,
    keys: Sequence[DpfKey],
    db_limbs: np.ndarray,  # uint32[D, lpe]
    key_chunk: int = 64,
    host_levels=None,
    mode: str = "levels",
    integrity=None,
    pipeline=None,
    use_pallas=None,
    mesh: Mesh = None,
) -> np.ndarray:
    """Single-device PIR answers via the chunked bulk evaluator.

    mode="levels": the headline-bench execution shape (ops/evaluator.
    full_domain_evaluate_chunks: host-driven per-level dispatch, small XLA
    programs) — the database is permuted ONCE into the expansion's lane
    order (`lane_order_map`, so no per-query leaf-order gather exists at
    all) and each key chunk folds against it on device. On one v5e chip
    this runs the 2^24 x 64-query BASELINE config ~60x faster than the
    monolithic walk+expand shard_map program, whose 20+ unrolled AES levels
    in a single program spill (PERF.md). mode="walk": ONE program per chunk
    (every leaf lane walks its own path — see full_domain_evaluate_chunks),
    folding against the NATURAL-order DB. mode="fold" (fastest): the inner
    product runs INSIDE each chunk's program against the lane-order DB
    (evaluator.full_domain_fold_chunks) — values are materialized in HBM
    behind an optimization_barrier and consumed there, so the program
    output is a tiny [chunk, lpe] and the tunnel's large-output miscompute
    never applies. mode="fused": ONE doubling-
    expansion program per dispatch, auto-slabbed by `evaluator.plan_slabs`
    so no single program materializes more output than the platform
    computes correctly (this image's tunnel corrupts >= ~128 MB programs,
    PERF.md) — each leaf-contiguous piece folds against the matching
    NATURAL-order DB rows and pieces XOR into the running answer. This is
    the only correct single-chip mode at 2^24+ domains on the tunnel.
    mode="megakernel": the slab megakernel (evaluator.
    full_domain_fold_chunks mode="megakernel") — the inner product runs
    INSIDE the expansion kernel against database tiles streamed from HBM
    with double-buffered DMA, so the DB is read once per key per batch and
    the expansion itself never touches HBM at all; takes the "megakernel"-
    order PreparedPirDatabase. With `mesh` (a make_mesh/local_mesh
    (keys, domain) mesh), mode="megakernel" runs POD-SCALE: the key batch
    shards over 'keys', each chunk is ONE jitted shard_map program whose
    per-shard body packs + fast-forwards the entry plane of its OWN
    domain slice and runs the slab megakernel UNCHANGED against its OWN
    DB column block (prepare_pir_database(order='megakernel',
    mesh=mesh) — per-shard plans sized against per-chip VMEM/HBM, so DB
    capacity scales linearly with domain shards and throughput with key
    shards), and the [Kl, lpe] partial inner products reduce by one XOR
    all-gather over 'domain'. `mesh` is rejected for every other mode.

    `db_limbs` may be a host uint32[D, lpe] array (permuted + uploaded on
    every call — fine for tests, wrong for serving) or the
    PreparedPirDatabase from `prepare_pir_database` (upload once, query
    many; its order must match the mode: "lane" for levels, "natural" for
    walk/fused).

    `integrity` (None = DPF_TPU_INTEGRITY env default) appends one
    sentinel probe key whose folded response is recomputed on the host
    oracle — see `pir_query_batch`. With a PreparedPirDatabase the
    verification fold reconstructs a natural-order host copy of the DB
    once per *database* (cached on the immutable PreparedPirDatabase), so
    serving loops pay the device pull at setup, not per query batch.

    `pipeline` (None = DPF_TPU_PIPELINE env / platform default,
    ops/pipeline.py) runs the chunked evaluation through the pipelined
    executor: chunk N+1's key pack + upload + dispatch overlap chunk N's
    device program and chunk N-1's response pull (worker thread). The
    per-chunk fold dispatches stay on the main thread in chunk order, so
    answers are deterministic and bit-identical to the serial path.

    `use_pallas` (None = platform default) pins the expansion engine of
    the non-megakernel modes — how the supervisor's degradation chain
    (ops/supervisor.pir_query_batch_robust, ISSUE 7) distinguishes its
    fold/pallas and fold/jax rungs.
    """
    from ..ops import evaluator as ev
    from ..ops import pipeline as _pl

    # The chunk evaluators resolve use_pallas=None to the platform default
    # (an explicit value — the supervisor pinning a degradation rung,
    # ISSUE 7 — passes through); the fault-injection level of this call
    # follows that resolution (the megakernel is a Mosaic program
    # regardless of the use_pallas knob).
    fi_backend = (
        "pallas" if mode == "megakernel"
        else ev._fi_backend(
            ev._pallas_default() if use_pallas is None else use_pallas
        )
    )
    if mesh is not None and mode != "megakernel":
        raise errors.InvalidArgumentError(
            f"mesh sharding exists only for mode='megakernel' (got "
            f"mode={mode!r}); the per-level sharded path is "
            "pir_query_batch"
        )
    keys, probe = _pir_probe(
        dpf, keys, integrity, "pir_query_batch_chunked", fi_backend
    )
    want_order = "natural" if mode in ("walk", "fused") else "lane"
    if mode == "fold":
        # In-program inner product (evaluator.full_domain_fold_chunks):
        # values never leave the program, the fold consumes the lane-order
        # DB, and the program's tiny [chunk, lpe] output sidesteps the
        # tunnel's large-output miscompute at ANY domain size — the fastest
        # AND always-correct single-chip mode (PERF.md "fold-in-program").
        want_order = "lane"
    if mode == "megakernel":
        # In-KERNEL inner product: the megakernel streams DB tiles from
        # HBM into VMEM per slab and accumulates there (ISSUE 3).
        want_order = "megakernel"
    if isinstance(db_limbs, PreparedPirDatabase):
        if db_limbs.order != want_order:
            raise errors.InvalidArgumentError(
                f"mode={mode!r} needs a {want_order!r}-order "
                f"PreparedPirDatabase, got {db_limbs.order!r}"
            )
        if mode == "megakernel":
            # The row layout encodes one slab plan AND one mesh; a
            # budget/host_levels/mesh change between prepare and query
            # would silently AND against mis-ordered tiles, so both are
            # REJECTED — never silently re-laid-out (a re-layout is a
            # 100+MB host round trip hiding on the query path).
            db_mesh = db_limbs.mesh
            if db_mesh != mesh:
                raise errors.InvalidArgumentError(
                    "database prepared for mesh "
                    f"{_mesh_desc(db_mesh)} but the query asked for mesh "
                    f"{_mesh_desc(mesh)}; re-run prepare_pir_database("
                    "order='megakernel', mesh=...) for the query mesh"
                )
            current = ev.plan_megakernel(
                dpf, -1, host_levels or db_limbs.plan.host_levels,
                domain_shards=(mesh.shape["domain"] if mesh is not None
                               else 1),
            )
            if current != db_limbs.plan:
                raise errors.InvalidArgumentError(
                    "megakernel plan changed since the database was "
                    f"prepared ({db_limbs.plan} -> {current}); re-run "
                    "prepare_pir_database(order='megakernel')"
                )
            host_levels = db_limbs.plan.host_levels
        pdb = db_limbs
        db_dev = db_limbs.lane_db
    elif isinstance(db_limbs, jax.Array):
        raise errors.InvalidArgumentError(
            "pass the PreparedPirDatabase from prepare_pir_database (or a "
            "host array); a bare device array's row order is ambiguous"
        )
    else:
        pdb = prepare_pir_database(
            dpf, db_limbs, host_levels, order=want_order, mesh=mesh
        )
        db_dev = pdb.lane_db
    db_nat = None
    if probe is not None:
        if isinstance(db_limbs, PreparedPirDatabase):
            db_nat = db_limbs.natural_host(dpf)
        else:
            db_nat = np.asarray(db_limbs)
    pipe = _pl.resolve(pipeline)

    def _pull(item):
        n_valid, fold = item
        return np.asarray(fold)[:n_valid]

    if mode == "megakernel" and mesh is not None:
        rows = list(
            _pl.consume(
                _sharded_megakernel_fold_chunks(
                    dpf, keys, pdb, mesh, key_chunk=key_chunk,
                    host_levels=host_levels, pipeline=pipeline,
                ),
                _pull,
                pipe,
                backend=fi_backend,
                op="pir_query_batch_chunked",
            )
        )
        # Trim the key-shard padding the sharded generator added so every
        # chunk's shard_map splits evenly over the 'keys' axis.
        res = np.concatenate(rows, axis=0)[: len(keys)]
        return _pir_verify_fold(
            probe, res, db_nat, "pir_query_batch_chunked", fi_backend
        )
    if mode in ("fold", "megakernel"):
        rows = list(
            _pl.consume(
                ev.full_domain_fold_chunks(
                    dpf, keys, key_chunk=key_chunk, host_levels=host_levels,
                    db_lane=db_dev, pipeline=pipeline, mode=mode,
                    use_pallas=use_pallas,
                ),
                _pull,
                pipe,
                backend=fi_backend,
                op="pir_query_batch_chunked",
            )
        )
        return _pir_verify_fold(
            probe, np.concatenate(rows, axis=0), db_nat,
            "pir_query_batch_chunked", fi_backend,
        )
    if mode == "fused":
        h, slab = ev.plan_slabs(
            dpf,
            max(1, min(key_chunk, len(keys))),
            min_host_levels=host_levels or 5,
        )

        def _chunk_folds():
            # Fold dispatches chain on the MAIN thread in piece order (the
            # per-piece value buffer is donated/deleted by _pir_fold_slab);
            # only the tiny [chunk, lpe] per-chunk accumulator crosses to
            # the pull thread.
            acc, off = None, 0
            for n_valid, vals in ev.full_domain_evaluate_chunks(
                dpf, keys, key_chunk=key_chunk, host_levels=h, mode="fused",
                lane_slab=slab, pipeline=pipeline, use_pallas=use_pallas,
            ):
                fold = _pir_fold_slab(vals, db_dev, off)
                acc = fold if acc is None else acc ^ fold
                off += vals.shape[1]
                if off >= db_dev.shape[0]:  # chunk complete
                    yield n_valid, acc
                    acc, off = None, 0

        outs = list(
            _pl.consume(
                _chunk_folds(), _pull, pipe, backend=fi_backend,
                op="pir_query_batch_chunked",
            )
        )
        return _pir_verify_fold(
            probe, np.concatenate(outs, axis=0), db_nat,
            "pir_query_batch_chunked", fi_backend,
        )

    def _folded():
        # The fold frees each chunk's [chunk, domain, lpe] values NOW
        # (donation or explicit delete inside _pir_fold): at large domains
        # a live extra chunk (plus the expansion temporaries of the next
        # one) pushes past HBM and the runtime starts evicting buffers
        # across the host link — the difference between 0.1 s and 5 s per
        # chunk.
        for n_valid, vals in ev.full_domain_evaluate_chunks(
            dpf,
            keys,
            key_chunk=key_chunk,
            host_levels=host_levels if mode == "levels" else None,
            leaf_order=(mode == "walk"),
            mode=mode,
            pipeline=pipeline,
            use_pallas=use_pallas,
        ):
            yield n_valid, _pir_fold(vals, db_dev)

    outs = list(
        _pl.consume(
            _folded(), _pull, pipe, backend=fi_backend,
            op="pir_query_batch_chunked",
        )
    )
    return _pir_verify_fold(
        probe, np.concatenate(outs, axis=0), db_nat,
        "pir_query_batch_chunked", fi_backend,
    )


# ---------------------------------------------------------------------------
# Sharded full-domain / hierarchical expansion (all value types)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def build_sharded_expand_step(
    mesh: Mesh,
    num_levels: int,
    party: int,
    spec,  # value_codec.ValueSpec (hashable)
    keep_per_block: int,
):
    """Compiles a domain-sharded full-domain expansion for one key batch.

    Device d walks log2(n_domain) levels to its subtree, expands the rest,
    hashes and value-corrects through the codec. Returns jitted
    fn(seeds [K,4], cw_planes [K,L,128], ccl, ccr, corrections pytree) ->
    values [K, domain_elems, lpe] (tuple of arrays for Tuple specs), with K
    sharded over 'keys' and the element axis over 'domain'. The analog of
    sharding the long axis in sequence parallelism: the evaluation tree
    splits at depth log2(n_domain) and no communication crosses shards at
    all (outputs stay sharded for the consumer to reduce).
    """
    from ..ops import value_codec

    n_domain = mesh.shape["domain"]
    subtree_levels = int(np.log2(n_domain))
    assert 1 << subtree_levels == n_domain, "domain shards must be a power of 2"
    expand_levels = num_levels - subtree_levels
    assert expand_levels >= 0, "domain smaller than the device mesh"

    def one_key(seed, cw_planes, ccl, ccr, corrections, subtree_index):
        # Walk to the 32 subtree nodes at depth subtree_levels + lane_levels
        # (one per packed lane) so the doubling expansion starts with every
        # lane real — see _walk_and_expand_one_key for why.
        lane_levels = min(5, expand_levels)
        n_lane = 1 << lane_levels
        walk_levels = subtree_levels + lane_levels
        seeds = jnp.broadcast_to(seed[None, :], (32, 4))
        planes = aes_jax.pack_to_planes(seeds)
        control = jnp.full(1, 0xFFFFFFFF if party else 0, jnp.uint32)
        if walk_levels:
            node = subtree_index.astype(jnp.uint32) * jnp.uint32(n_lane) + (
                jnp.arange(32, dtype=jnp.uint32) % jnp.uint32(n_lane)
            )
            shifts = (
                walk_levels - 1 - jnp.arange(walk_levels, dtype=jnp.uint32)
            )[:, None]
            bits_path = ((node[None, :] >> shifts) & 1).astype(bool)
            planes, control = backend_jax.evaluate_seeds_planes(
                planes,
                control,
                _pack_bits_device(bits_path),
                cw_planes[:walk_levels],
                ccl[:walk_levels],
                ccr[:walk_levels],
            )
        for l in range(walk_levels, num_levels):
            planes, control = backend_jax.expand_one_level(
                planes, control, cw_planes[l], ccl[l], ccr[l]
            )
        stream = backend_jax.hash_value_stream(planes, spec.blocks_needed)
        ctrl = backend_jax.unpack_mask_device(control)
        vals = value_codec.correct_values(stream, ctrl, corrections, spec, party)
        order = jnp.asarray(
            backend_jax.expansion_output_order(
                n_lane, 32, expand_levels - lane_levels
            )
        )
        outs = []
        for v in vals:  # [32 << (expand_levels - lane_levels), epb, lpe]
            v = v[order][:, :keep_per_block]  # leaf order, trimmed blocks
            n_blocks, kept, lpe = v.shape
            outs.append(v.reshape(n_blocks * kept, lpe))
        return tuple(outs)

    def device_fn(seeds, cw_planes, ccl, ccr, corrections):
        di = jax.lax.axis_index("domain").astype(jnp.int32)
        outs = jax.vmap(
            lambda s, cw, l, r, c: one_key(s, cw, l, r, c, di),
        )(seeds, cw_planes, ccl, ccr, corrections)
        return outs if spec.is_tuple else outs[0]

    out_spec = (
        tuple(P("keys", "domain") for _ in spec.components)
        if spec.is_tuple
        else P("keys", "domain")
    )
    step = backend_jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P("keys"), P("keys"), P("keys"), P("keys"),
                  tuple(P("keys") for _ in spec.components)),
        out_specs=out_spec,
    )
    return jax.jit(step)


def sharded_full_domain_evaluate(
    dpf: DistributedPointFunction,
    keys: Sequence[DpfKey],
    mesh: Mesh,
    hierarchy_level: int = -1,
):
    """Full-domain evaluation sharded over a (keys, domain) mesh.

    Returns a *sharded device array* [K, domain, lpe] (tuple of arrays for
    Tuple outputs) laid out P('keys', 'domain') — downstream on-device
    consumers (PIR reductions, aggregation) keep it sharded; np.asarray
    gathers to the host. Supports every value type via the codec, unlike
    `pir_query_batch` which is specialized to the XOR inner product.
    """
    from ..ops import value_codec

    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    value_type = v.parameters[hierarchy_level].value_type
    spec = value_codec.build_spec(value_type, v.blocks_needed[hierarchy_level])
    lds = v.parameters[hierarchy_level].log_domain_size
    backend_jax.log_backend_once()
    batch = evaluator.KeyBatch.from_keys(dpf, keys, hierarchy_level)
    stop_level = batch.num_levels
    keep_per_block = 1 << (lds - stop_level)
    n_domain = mesh.shape["domain"]
    if (1 << stop_level) < n_domain:
        raise errors.InvalidArgumentError(
            f"domain tree ({1 << stop_level} leaves) smaller than the "
            f"'domain' mesh axis ({n_domain})"
        )
    n_real = batch.seeds.shape[0]
    key_shards = mesh.shape["keys"]
    pad = (-n_real) % key_shards
    idx = np.concatenate([np.arange(n_real), np.zeros(pad, dtype=np.int64)])
    step = build_sharded_expand_step(
        mesh, stop_level, batch.party, spec, keep_per_block
    )
    batch = batch.take(idx)
    cw_planes, ccl, ccr = batch.device_cw_arrays()
    corrections = tuple(jnp.asarray(a) for a in batch.codec_corrections)
    out = step(
        jnp.asarray(batch.seeds),
        jnp.asarray(cw_planes),
        jnp.asarray(ccl),
        jnp.asarray(ccr),
        corrections,
    )
    # Trim padded keys and block-packing overshoot (host-side views; the
    # sharded array itself is what on-device consumers keep).
    domain = 1 << lds
    if spec.is_tuple:
        return tuple(o[:n_real, :domain] for o in out)
    return out[:n_real, :domain]
