"""Byte-compatible wire format for keys, parameters, and contexts.

See serialization.py for the message codecs (reference schema:
/root/reference/dpf/distributed_point_function.proto and the dcf/fss_gates
protos) and wire.py for the proto3 wire-format primitives.
"""

from .serialization import (  # noqa: F401
    decode_dpf_parameters,
    decode_mic_parameters,
    decode_value,
    decode_value_type,
    encode_dpf_parameters,
    encode_mic_parameters,
    encode_value,
    encode_value_type,
    parse_dcf_key,
    parse_dpf_key,
    parse_evaluation_context,
    parse_mic_key,
    serialize_dcf_key,
    serialize_dpf_key,
    serialize_evaluation_context,
    serialize_mic_key,
)
