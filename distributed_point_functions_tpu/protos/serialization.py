"""Byte-compatible serialization of keys, parameters, and contexts.

Maps the host dataclasses (core/keys.py, core/params.py, core/value_types.py,
dcf/dcf.py, gates/mic.py) onto the reference's protobuf messages:

* ValueType / Value        /root/reference/dpf/distributed_point_function.proto:25-89
* DpfParameters            :92-105   (field 2 reserved; value_type is field 3)
* Block                    :108-111  (high=1, low=2)
* CorrectionWord           :114-126  (field 4 reserved; value_correction=5)
* DpfKey                   :129-140  (field 4 reserved; last_level_value_correction=5)
* PartialEvaluation        :144-152
* EvaluationContext        :156-171
* DcfParameters / DcfKey   /root/reference/dcf/distributed_comparison_function.proto:25-32
* Interval / MicParameters / MicKey
                           /root/reference/dcf/fss_gates/multiple_interval_containment.proto:23-60

Integer values follow the reference's Uint128ToValueInteger rule
(value_type_helpers.cc:134-144): value_uint64 when the high 64 bits are zero,
otherwise a value_uint128 Block. Tested byte-for-byte against the protobuf
runtime in tests/test_serialization.py.
"""

from __future__ import annotations

from typing import List, Sequence

from ..core.keys import CorrectionWord, DpfKey, EvaluationContext, PartialEvaluation
from ..core.params import DpfParameters
from ..core.value_types import Int, IntModN, TupleType, ValueType, XorWrapper
from ..utils.errors import InvalidArgumentError
from . import wire

# ---------------------------------------------------------------------------
# Block (a single 128-bit AES block: high=1, low=2)
# ---------------------------------------------------------------------------


def encode_block(x: int) -> bytes:
    high, low = (x >> 64) & 0xFFFFFFFFFFFFFFFF, x & 0xFFFFFFFFFFFFFFFF
    return wire.uint64_field(1, high) + wire.uint64_field(2, low)


def decode_block(buf: bytes) -> int:
    high = low = 0
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            high = value
        elif field == 2:
            low = value
    return (high << 64) | low


# ---------------------------------------------------------------------------
# ValueType (oneof: integer=1 | tuple=2 | int_mod_n=3 | xor_wrapper=4)
# ---------------------------------------------------------------------------


def _encode_integer_type(bitsize: int) -> bytes:
    return wire.int32_field(1, bitsize)


def encode_value_type(vt: ValueType) -> bytes:
    """Deterministic (ascending-field-order) ValueType serialization — the
    same bytes the reference uses as its value-correction dispatch key
    (/root/reference/dpf/distributed_point_function.cc:526-559)."""
    if isinstance(vt, Int):
        return wire.len_field(1, _encode_integer_type(vt.bitsize))
    if isinstance(vt, TupleType):
        payload = b"".join(
            wire.len_field(1, encode_value_type(e)) for e in vt.elements
        )
        return wire.len_field(2, payload)
    if isinstance(vt, IntModN):
        body = wire.len_field(1, _encode_integer_type(vt.base_bitsize))
        body += wire.len_field(2, _encode_value_integer(vt.modulus))
        return wire.len_field(3, body)
    if isinstance(vt, XorWrapper):
        return wire.len_field(4, _encode_integer_type(vt.bitsize))
    raise InvalidArgumentError(f"unsupported value type {vt!r}")


def decode_value_type(buf: bytes) -> ValueType:
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            return Int(_decode_integer_type(value))
        if field == 2:
            elements = [
                decode_value_type(v)
                for f, _, v in wire.iter_fields(value)
                if f == 1
            ]
            return TupleType(*elements)
        if field == 3:
            base = modulus = None
            for f, _, v in wire.iter_fields(value):
                if f == 1:
                    base = _decode_integer_type(v)
                elif f == 2:
                    modulus = _decode_value_integer(v)
            if base is None or modulus is None:
                raise InvalidArgumentError("IntModN type needs base and modulus")
            return IntModN(base, modulus)
        if field == 4:
            return XorWrapper(_decode_integer_type(value))
    raise InvalidArgumentError("ValueType has no type set")


def _decode_integer_type(buf: bytes) -> int:
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            return wire.decode_int32(value)
    return 0


# ---------------------------------------------------------------------------
# Value (oneof: integer=1 | tuple=2 | int_mod_n=3 | xor_wrapper=4)
# ---------------------------------------------------------------------------


def _encode_value_integer(x: int) -> bytes:
    """Value.Integer per Uint128ToValueInteger: value_uint64 (field 1) when
    high64 == 0, else value_uint128 Block (field 2). Oneof scalars are
    written even when zero (presence)."""
    if x < 0 or x >= 1 << 128:
        raise InvalidArgumentError("integer value out of uint128 range")
    if (x >> 64) == 0:
        return wire.tag(1, wire.VARINT) + wire.encode_varint(x)
    return wire.len_field(2, encode_block(x))


def _decode_value_integer(buf: bytes) -> int:
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            return value
        if field == 2:
            return decode_block(value)
    return 0


def encode_value(vt: ValueType, value) -> bytes:
    """Value message for host `value` of declared type `vt`."""
    if isinstance(vt, Int):
        return wire.len_field(1, _encode_value_integer(int(value)))
    if isinstance(vt, TupleType):
        payload = b"".join(
            wire.len_field(1, encode_value(evt, ev))
            for evt, ev in zip(vt.elements, value)
        )
        return wire.len_field(2, payload)
    if isinstance(vt, IntModN):
        return wire.len_field(3, _encode_value_integer(int(value)))
    if isinstance(vt, XorWrapper):
        return wire.len_field(4, _encode_value_integer(int(value)))
    raise InvalidArgumentError(f"unsupported value type {vt!r}")


def decode_value(buf: bytes):
    """Decodes a Value to its host representation (int or nested tuple).
    The branch taken is recorded in the message itself, so no type context
    is needed; validation against the expected type happens at use sites."""
    for field, _, value in wire.iter_fields(buf):
        if field in (1, 3, 4):
            return _decode_value_integer(value)
        if field == 2:
            return tuple(
                decode_value(v) for f, _, v in wire.iter_fields(value) if f == 1
            )
    raise InvalidArgumentError("Value has no value set")


# ---------------------------------------------------------------------------
# DpfParameters (log_domain_size=1, value_type=3, security_parameter=4)
# ---------------------------------------------------------------------------


def encode_dpf_parameters(p: DpfParameters) -> bytes:
    out = wire.int32_field(1, p.log_domain_size)
    out += wire.len_field(3, encode_value_type(p.value_type))
    out += wire.double_field(4, p.security_parameter)
    return out


def decode_dpf_parameters(buf: bytes) -> DpfParameters:
    log_domain_size = 0
    value_type = None
    security_parameter = 0.0
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            log_domain_size = wire.decode_int32(value)
        elif field == 3:
            value_type = decode_value_type(value)
        elif field == 4:
            security_parameter = wire.decode_double(value)
    if value_type is None:
        raise InvalidArgumentError("`value_type` is required")
    return DpfParameters(log_domain_size, value_type, security_parameter)


# ---------------------------------------------------------------------------
# CorrectionWord / DpfKey
# ---------------------------------------------------------------------------


def _encode_correction_word(cw: CorrectionWord, vt: ValueType) -> bytes:
    out = wire.len_field(1, encode_block(cw.seed))
    out += wire.bool_field(2, cw.control_left)
    out += wire.bool_field(3, cw.control_right)
    for v in cw.value_correction:
        out += wire.len_field(5, encode_value(vt, v))
    return out


def _decode_correction_word(buf: bytes) -> CorrectionWord:
    seed = 0
    control_left = control_right = False
    value_correction: List = []
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            seed = decode_block(value)
        elif field == 2:
            control_left = bool(value)
        elif field == 3:
            control_right = bool(value)
        elif field == 5:
            value_correction.append(decode_value(value))
    return CorrectionWord(seed, control_left, control_right, value_correction)


def serialize_dpf_key(key: DpfKey, parameters: Sequence[DpfParameters]) -> bytes:
    """DpfKey message bytes. `parameters` supplies the declared value types of
    each hierarchy level's corrections (Values carry their branch but the
    encoder picks uint64-vs-uint128 from the value itself, so only the type
    structure is needed — pass the same parameters used at Create)."""
    tree_to_hierarchy = _output_level_types(parameters, len(key.correction_words))
    out = wire.len_field(1, encode_block(key.seed))
    for i, cw in enumerate(key.correction_words):
        vt = tree_to_hierarchy.get(i, parameters[-1].value_type)
        out += wire.len_field(2, _encode_correction_word(cw, vt))
    out += wire.int32_field(3, key.party)
    for v in key.last_level_value_correction:
        out += wire.len_field(5, encode_value(parameters[-1].value_type, v))
    return out


def _output_level_types(parameters: Sequence[DpfParameters], num_cw: int):
    """cw list index -> value type of the hierarchy level it corrects.

    correction_words[i] belongs to tree level i+1 and carries the value
    correction of the hierarchy level output at tree level i (keygen.py
    _generate_next), so index i maps through tree_to_hierarchy[i]."""
    import dataclasses

    from ..core.params import ParameterValidator

    # Accept RESOLVED parameter lists (validator.parameters): past 88
    # domain bits the resolved default security parameter (40 + bits)
    # exceeds the validator's [0, 128] input range, so re-validating it
    # raised on every deep key. A value above 128 can only BE a resolved
    # default (explicit ones are rejected at Create), so mapping it back
    # to 0 round-trips to the identical resolution.
    v = ParameterValidator([
        dataclasses.replace(p, security_parameter=0.0)
        if p.security_parameter > 128 else p
        for p in parameters
    ])
    return {
        tree_level: parameters[h].value_type
        for tree_level, h in v.tree_to_hierarchy.items()
        if tree_level < num_cw
    }


def parse_dpf_key(buf: bytes) -> DpfKey:
    seed = 0
    correction_words: List[CorrectionWord] = []
    party = 0
    last: List = []
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            seed = decode_block(value)
        elif field == 2:
            correction_words.append(_decode_correction_word(value))
        elif field == 3:
            party = wire.decode_int32(value)
        elif field == 5:
            last.append(decode_value(value))
    return DpfKey(seed, correction_words, party, last)


# ---------------------------------------------------------------------------
# PartialEvaluation / EvaluationContext
# ---------------------------------------------------------------------------


def _encode_partial_evaluation(pe: PartialEvaluation) -> bytes:
    out = wire.len_field(1, encode_block(pe.prefix))
    out += wire.len_field(2, encode_block(pe.seed))
    out += wire.bool_field(3, pe.control_bit)
    return out


def _decode_partial_evaluation(buf: bytes) -> PartialEvaluation:
    prefix = seed = 0
    control_bit = False
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            prefix = decode_block(value)
        elif field == 2:
            seed = decode_block(value)
        elif field == 3:
            control_bit = bool(value)
    return PartialEvaluation(prefix, seed, control_bit)


def serialize_evaluation_context(ctx: EvaluationContext) -> bytes:
    out = b"".join(
        wire.len_field(1, encode_dpf_parameters(p)) for p in ctx.parameters
    )
    out += wire.len_field(2, serialize_dpf_key(ctx.key, ctx.parameters))
    out += wire.int32_field(3, ctx.previous_hierarchy_level)
    for pe in ctx.partial_evaluations:
        out += wire.len_field(4, _encode_partial_evaluation(pe))
    out += wire.int32_field(5, ctx.partial_evaluations_level)
    return out


def parse_evaluation_context(buf: bytes) -> EvaluationContext:
    parameters: List[DpfParameters] = []
    key = None
    previous_hierarchy_level = 0
    partials: List[PartialEvaluation] = []
    partial_evaluations_level = 0
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            parameters.append(decode_dpf_parameters(value))
        elif field == 2:
            key = parse_dpf_key(value)
        elif field == 3:
            previous_hierarchy_level = wire.decode_int32(value)
        elif field == 4:
            partials.append(_decode_partial_evaluation(value))
        elif field == 5:
            partial_evaluations_level = wire.decode_int32(value)
    if key is None:
        raise InvalidArgumentError("`key` is required")
    return EvaluationContext(
        parameters, key, previous_hierarchy_level, partials,
        partial_evaluations_level,
    )


# ---------------------------------------------------------------------------
# DCF (DcfParameters{parameters=1}, DcfKey{key=1})
# ---------------------------------------------------------------------------


def serialize_dcf_parameters(log_domain_size: int, value_type) -> bytes:
    """DcfParameters message: one DpfParameters (field 1) whose
    log_domain_size + value_type fully determine the DCF — the per-level
    parameter list (DpfParameters(i, value_type) for i < n) is derived at
    Create, exactly as DistributedComparisonFunction.create derives it
    (/root/reference/dcf/distributed_comparison_function.cc:56-62)."""
    return wire.len_field(
        1, encode_dpf_parameters(DpfParameters(log_domain_size, value_type))
    )


def parse_dcf_parameters(buf: bytes):
    """-> (log_domain_size, value_type)."""
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            p = decode_dpf_parameters(value)
            return p.log_domain_size, p.value_type
    raise InvalidArgumentError("DcfParameters has no parameters set")


def serialize_dcf_key(dcf_key, parameters: Sequence[DpfParameters]) -> bytes:
    return wire.len_field(1, serialize_dpf_key(dcf_key.key, parameters))


def parse_dcf_key(buf: bytes):
    from ..dcf.dcf import DcfKey

    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            return DcfKey(key=parse_dpf_key(value))
    raise InvalidArgumentError("DcfKey has no key set")


# ---------------------------------------------------------------------------
# MIC gate (Interval, MicParameters, MicKey)
# ---------------------------------------------------------------------------


def encode_interval(lower: int, upper: int) -> bytes:
    return wire.len_field(1, _encode_value_integer(lower)) + wire.len_field(
        2, _encode_value_integer(upper)
    )


def decode_interval(buf: bytes):
    lower = upper = 0
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            lower = _decode_value_integer(value)
        elif field == 2:
            upper = _decode_value_integer(value)
    return lower, upper


def encode_mic_parameters(log_group_size: int, intervals) -> bytes:
    out = wire.int32_field(1, log_group_size)
    for lower, upper in intervals:
        out += wire.len_field(2, encode_interval(lower, upper))
    return out


def decode_mic_parameters(buf: bytes):
    log_group_size = 0
    intervals = []
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            log_group_size = wire.decode_int32(value)
        elif field == 2:
            intervals.append(decode_interval(value))
    return log_group_size, intervals


def serialize_mic_key(mic_key, parameters: Sequence[DpfParameters]) -> bytes:
    out = wire.len_field(1, serialize_dcf_key(mic_key.dcf_key, parameters))
    for share in mic_key.output_mask_shares:
        out += wire.len_field(2, _encode_value_integer(share))
    return out


def parse_mic_key(buf: bytes):
    from ..gates.mic import MicKey

    dcf_key = None
    shares: List[int] = []
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            dcf_key = parse_dcf_key(value)
        elif field == 2:
            shares.append(_decode_value_integer(value))
    if dcf_key is None:
        raise InvalidArgumentError("MicKey has no dcfkey set")
    return MicKey(dcf_key=dcf_key, output_mask_shares=shares)


# ---------------------------------------------------------------------------
# Generic framework gate key (gates/framework.GateKey)
# ---------------------------------------------------------------------------
#
# The natural generalization of the MicKey message: repeated component DCF
# keys (field 1) + repeated mask-share integers (field 2). A one-component
# GateKey therefore serializes BYTE-IDENTICALLY to a MicKey carrying the
# same DCF key and shares — the framework's wire form is a superset of the
# reference's gate message, not a fork (pinned in tests).
#
# Vector-payload component keys (uniform TupleType(Int(w) x t) value types,
# the gate codec) ride field 3 instead: a packed VectorDcfKey message whose
# per-level tuple corrections concatenate into ONE little-endian bytes field
# at their true element width, instead of t nested Value messages per level
# whose per-element proto framing would triple the key. Scalar keys —
# including every 1-element vector gate, which degenerates to a plain
# Int(128) DCF by construction — never take this path, so the MIC-superset
# and byte-identity pins are untouched.
#
# VectorDcfKey layout:
#   field 1: root seed, 16 raw little-endian bytes
#   field 2 (repeated, one per correction word): 17 raw bytes —
#            seed (16, little-endian) + flags (bit 0 control_left,
#            bit 1 control_right)
#   field 3: party varint
#   field 4: element bitsize w varint
#   field 5: packed value corrections — every level's tuple concatenated
#            (correction words in order, then the last level), each element
#            w/8 little-endian bytes; t = len / ((num_cw + 1) * w/8)


def _uniform_tuple_bits(value_type) -> int:
    """Element bitsize of a uniform Int tuple, or 0 when `value_type` is
    not one (the packed VectorDcfKey form applies only when > 0)."""
    if not isinstance(value_type, TupleType) or len(value_type.elements) < 2:
        return 0
    first = value_type.elements[0]
    if not isinstance(first, Int) or first.bitsize not in (32, 64, 128):
        return 0
    if any(e != first for e in value_type.elements[1:]):
        return 0
    return first.bitsize


def _serialize_vector_dcf_key(dcf_key, bits: int) -> bytes:
    key = dcf_key.key
    nbytes = bits // 8
    out = wire.len_field(1, int(key.seed).to_bytes(16, "little"))
    packed = b""
    for cw in key.correction_words:
        flags = int(cw.control_left) | (int(cw.control_right) << 1)
        out += wire.len_field(
            2, int(cw.seed).to_bytes(16, "little") + bytes([flags])
        )
        (corr,) = cw.value_correction
        packed += b"".join(int(c).to_bytes(nbytes, "little") for c in corr)
    out += wire.tag(3, wire.VARINT) + wire.encode_varint(key.party)
    out += wire.tag(4, wire.VARINT) + wire.encode_varint(bits)
    (last,) = key.last_level_value_correction
    packed += b"".join(int(c).to_bytes(nbytes, "little") for c in last)
    out += wire.len_field(5, packed)
    return out


def _parse_vector_dcf_key(buf: bytes):
    from ..core.keys import CorrectionWord, DpfKey
    from ..dcf.dcf import DcfKey

    seed = 0
    cws: List = []
    party = 0
    bits = 0
    packed = b""
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            seed = int.from_bytes(value, "little")
        elif field == 2:
            if len(value) != 17:
                raise InvalidArgumentError(
                    "VectorDcfKey correction word must be 17 bytes"
                )
            cws.append(
                (int.from_bytes(value[:16], "little"), value[16])
            )
        elif field == 3:
            party = int(value)
        elif field == 4:
            bits = int(value)
        elif field == 5:
            packed = value
    if bits not in (32, 64, 128):
        raise InvalidArgumentError(
            f"VectorDcfKey element bitsize {bits} unsupported"
        )
    nbytes = bits // 8
    levels = len(cws) + 1
    if not packed or len(packed) % (levels * nbytes):
        raise InvalidArgumentError(
            "VectorDcfKey packed corrections length does not divide into "
            f"{levels} levels of {nbytes}-byte elements"
        )
    t = len(packed) // (levels * nbytes)
    tuples = []
    for lv in range(levels):
        base = lv * t * nbytes
        tuples.append(
            tuple(
                int.from_bytes(
                    packed[base + e * nbytes : base + (e + 1) * nbytes],
                    "little",
                )
                for e in range(t)
            )
        )
    correction_words = [
        CorrectionWord(s, bool(flags & 1), bool(flags & 2), [tuples[i]])
        for i, (s, flags) in enumerate(cws)
    ]
    return DcfKey(
        key=DpfKey(seed, correction_words, party, [tuples[-1]])
    )


def serialize_gate_key(gate_key, parameters: Sequence[DpfParameters]) -> bytes:
    out = b""
    vec_bits = _uniform_tuple_bits(parameters[-1].value_type)
    for dk in gate_key.dcf_keys:
        if vec_bits:
            out += wire.len_field(3, _serialize_vector_dcf_key(dk, vec_bits))
        else:
            out += wire.len_field(1, serialize_dcf_key(dk, parameters))
    for share in gate_key.mask_shares:
        out += wire.len_field(2, _encode_value_integer(share))
    return out


def parse_gate_key(buf: bytes):
    from ..gates.framework import GateKey

    dcf_keys: List = []
    shares: List[int] = []
    for field, _, value in wire.iter_fields(buf):
        if field == 1:
            dcf_keys.append(parse_dcf_key(value))
        elif field == 2:
            shares.append(_decode_value_integer(value))
        elif field == 3:
            dcf_keys.append(_parse_vector_dcf_key(value))
    if not dcf_keys:
        raise InvalidArgumentError("GateKey has no component DCF keys set")
    return GateKey(dcf_keys=dcf_keys, mask_shares=shares)
