"""Minimal proto3 wire-format primitives (encode + decode).

The framework's keys and evaluation contexts must be byte-compatible with the
reference's protobuf messages
(/root/reference/dpf/distributed_point_function.proto) so that keys generated
here can be evaluated by any other conforming implementation and vice versa —
key interchange between the two non-colluding servers is the library's whole
deployment model. Rather than depending on protoc-generated classes, the
handful of messages involved are encoded/decoded directly against the
(public, stable) protobuf wire format:

* varint        (wire type 0): uint64/int32/bool
* fixed 64-bit  (wire type 1): double
* length-delim  (wire type 2): sub-messages, repeated messages

Encoders write fields in ascending field-number order and omit
default-valued proto3 fields (0 / false / empty), matching protobuf's
canonical C++ serialization, so output is byte-identical to what the
reference's library produces — including for the deterministic ValueType
serialization the reference uses as a dispatch key
(/root/reference/dpf/distributed_point_function.h:574-583).
"""

from __future__ import annotations

import struct
from typing import Iterator, Tuple

from ..utils.errors import InvalidArgumentError

VARINT = 0
FIXED64 = 1
LEN = 2
FIXED32 = 5


def encode_varint(n: int) -> bytes:
    if n < 0:
        raise InvalidArgumentError("varint must be non-negative (pre-wrap int32)")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise InvalidArgumentError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise InvalidArgumentError("varint too long")


def tag(field_number: int, wire_type: int) -> bytes:
    return encode_varint((field_number << 3) | wire_type)


def uint64_field(field_number: int, value: int) -> bytes:
    """Plain proto3 uint64/int32/bool field: omitted when zero."""
    if value == 0:
        return b""
    return tag(field_number, VARINT) + encode_varint(value)


def int32_field(field_number: int, value: int) -> bytes:
    """int32: negative values are sign-extended to 64 bits on the wire."""
    if value < 0:
        value += 1 << 64
    return uint64_field(field_number, value)


def bool_field(field_number: int, value: bool) -> bytes:
    return uint64_field(field_number, 1 if value else 0)


def double_field(field_number: int, value: float) -> bytes:
    if value == 0.0:
        return b""
    return tag(field_number, FIXED64) + struct.pack("<d", value)


def len_field(field_number: int, payload: bytes) -> bytes:
    """Length-delimited field (sub-message). Always emitted, even when empty:
    message presence is meaningful in proto3 (oneofs, message fields)."""
    return tag(field_number, LEN) + encode_varint(len(payload)) + payload


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yields (field_number, wire_type, value); value is int for VARINT /
    FIXED64 / FIXED32 (raw bits) and bytes for LEN."""
    pos = 0
    while pos < len(buf):
        key, pos = decode_varint(buf, pos)
        field_number, wire_type = key >> 3, key & 7
        if field_number == 0:
            raise InvalidArgumentError("invalid field number 0")
        if wire_type == VARINT:
            value, pos = decode_varint(buf, pos)
        elif wire_type == FIXED64:
            if pos + 8 > len(buf):
                raise InvalidArgumentError("truncated fixed64")
            value = int.from_bytes(buf[pos : pos + 8], "little")
            pos += 8
        elif wire_type == FIXED32:
            if pos + 4 > len(buf):
                raise InvalidArgumentError("truncated fixed32")
            value = int.from_bytes(buf[pos : pos + 4], "little")
            pos += 4
        elif wire_type == LEN:
            size, pos = decode_varint(buf, pos)
            if pos + size > len(buf):
                raise InvalidArgumentError("truncated length-delimited field")
            value = buf[pos : pos + size]
            pos += size
        else:
            raise InvalidArgumentError(f"unsupported wire type {wire_type}")
        yield field_number, wire_type, value


def decode_int32(raw: int) -> int:
    """Varint bits -> int32 value (sign extension via 64-bit wrap)."""
    raw &= (1 << 64) - 1
    if raw >= 1 << 63:
        raw -= 1 << 64
    return int(raw)


def decode_double(raw_bits: int) -> float:
    return struct.unpack("<d", raw_bits.to_bytes(8, "little"))[0]
