"""Serving front door (ISSUE 8): continuous batching of asynchronously
arriving small requests into the wide uniform batches the device engines
need, plus a cost-model router that picks host vs device vs kernel mode
per batch — executed through the resilient job supervisor.

    from distributed_point_functions_tpu import serving

    with serving.FrontDoor() as door:
        fut = door.submit(serving.Request.evaluate_at(dpf, [key], points))
        limbs = fut.result(timeout=5)
"""

from . import wire  # noqa: F401
from .autoscale import DEALER_OPS, AutoScaler  # noqa: F401
from .batcher import (  # noqa: F401
    ContinuousBatcher,
    Request,
    ServedFuture,
    WarmCache,
    plan_digest,
)
from .client import (  # noqa: F401
    DpfClient,
    PartyUnavailableError,
    RetryPolicy,
    TwoServerClient,
)
from .fleet import FleetProxy, ReplicaPool  # noqa: F401
from .frontdoor import FrontDoor  # noqa: F401
from .lease import LeaseState, StreamLease  # noqa: F401
from .server import DpfServer  # noqa: F401
from .streaming import (  # noqa: F401
    HeavyHitterStream,
    StreamConfig,
    parse_stream_spec,
)
from .router import (  # noqa: F401
    ANCHORS,
    DISPATCH_SECONDS_PRIOR,
    ENGINE_TABLE,
    CostModel,
    RouteDecision,
    Router,
    Workload,
    engine_table_predictions,
)
