"""Elastic fleet: the stats-driven autoscaler (ISSUE 20).

PR 14 gave each party a :class:`~.fleet.FleetProxy` over a
:class:`~.fleet.ReplicaPool` of server subprocesses — but the replica
count was a boot-time constant, so a deployment had to be provisioned
for its PEAK: a diurnal 4x load swing burns 4x the replica-seconds all
day. :class:`AutoScaler` closes the loop the proxy's aggregated stats
already expose: it polls the fleet's per-op queue depths, in-flight
counts and arrival-rate EWMAs (the ISSUE 20 ``rates`` stats key, fed by
the batcher's adaptive-wait estimator) and drives the pool's
``scale_up`` / ``scale_down`` seams plus the proxy's
``add_replica`` / ``set_retiring`` / ``remove_replica`` membership
seams.

**Signal.** The scaling signal is *backlog per live replica*:

    backlog = sum(queue depth over the plane's ops) + proxy in-flight

A replica-second is wasted when backlog/replica sits near zero; a p95
is blown when it runs away. The thresholds bracket a deadband
(``up_backlog`` strictly above ``down_backlog`` — enforced), and two
dampers keep a noisy or diurnal swing from thrashing:

* **sustain** — a threshold crossing must hold for ``sustain``
  CONSECUTIVE polls before acting (one burst poll is not a trend; any
  in-band poll resets both streaks);
* **cooldown** — after any scale event, no further event until
  ``cooldown`` seconds pass (a just-added replica needs time to absorb
  backlog before the signal is trusted again).

**Scale-up** prefers reviving a stopped pool slot (remembered port: the
replica wins its old rendezvous range back, so warm-tier reuse resumes)
and grows a fresh slot only when all are running.

**Scale-down** is a graceful drain, never a kill: the victim is marked
``retiring`` on the proxy (no NEW requests route to it, in-flight work
finishes), the loop waits — bounded — for its proxy-tracked load to
reach zero, then SIGTERMs it through the pool (the server's own drain
path) and leaves the endpoint retired on the proxy for a cheap revival
later.

**Planes.** The dealer role (``keygen`` — a wire op since PR 13) has a
different load profile from the eval ops: keygen floods are bursty
preprocessing, eval is steady online serving. ``plane`` selects which
ops feed the backlog signal — ``"eval"`` (everything but keygen),
``"dealer"`` (keygen only) or ``"all"`` — so a keygen-only fleet and an
eval fleet each run their own AutoScaler and scale independently.

Env knobs (all through :mod:`..utils.envflags`; see README):
``DPF_TPU_AUTOSCALE_MIN`` / ``MAX`` / ``INTERVAL`` / ``UP_BACKLOG`` /
``DOWN_BACKLOG`` / ``SUSTAIN`` / ``COOLDOWN``.

The control loop runs on the HOST and never touches an accelerator:
``tests/test_dispatch_audit.py`` pins that a full scale-up + drain
cycle adds ZERO device programs.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..utils import envflags
from ..utils import telemetry as _tm
from ..utils.errors import InvalidArgumentError

#: ops that constitute the dealer plane (PR 13's keygen wire op).
DEALER_OPS = ("keygen",)

PLANES = ("eval", "dealer", "all")


class AutoScaler:
    """Stats-driven replica-count control loop for one party's fleet.

    ``proxy`` is the party's :class:`~.fleet.FleetProxy` (polled
    in-process via its ``health()``/``stats()`` accessors); ``pool`` is
    anything with the :class:`~.fleet.ReplicaPool` scaling surface
    (``scale_up() -> (index, port, grew)``, ``scale_down(index)``,
    ``running_indices()``, ``ports``) — the real subprocess pool in
    deployment, a fake in unit tests.

    All mutable control state is owned by ``self._lock``; the worker
    thread is the only writer after ``start()``, but ``stats()`` /
    ``events`` are read from other threads.
    """

    def __init__(
        self,
        proxy,
        pool,
        plane: str = "eval",
        min_replicas: Optional[int] = None,
        max_replicas: Optional[int] = None,
        interval: Optional[float] = None,
        up_backlog: Optional[float] = None,
        down_backlog: Optional[float] = None,
        sustain: Optional[int] = None,
        cooldown: Optional[float] = None,
        drain_timeout: float = 30.0,
        spawn_timeout: float = 180.0,
    ):
        if plane not in PLANES:
            raise InvalidArgumentError(
                f"unknown autoscale plane {plane!r} (one of {PLANES})"
            )
        self.proxy = proxy
        self.pool = pool
        self.plane = plane
        self.min_replicas = (
            envflags.env_int("DPF_TPU_AUTOSCALE_MIN", 1)
            if min_replicas is None else min_replicas
        )
        self.max_replicas = (
            envflags.env_int("DPF_TPU_AUTOSCALE_MAX", 8)
            if max_replicas is None else max_replicas
        )
        self.interval = (
            envflags.env_float("DPF_TPU_AUTOSCALE_INTERVAL", 0.5)
            if interval is None else interval
        )
        self.up_backlog = (
            envflags.env_float("DPF_TPU_AUTOSCALE_UP_BACKLOG", 32.0)
            if up_backlog is None else up_backlog
        )
        self.down_backlog = (
            envflags.env_float("DPF_TPU_AUTOSCALE_DOWN_BACKLOG", 4.0)
            if down_backlog is None else down_backlog
        )
        self.sustain = (
            envflags.env_int("DPF_TPU_AUTOSCALE_SUSTAIN", 3)
            if sustain is None else sustain
        )
        self.cooldown = (
            envflags.env_float("DPF_TPU_AUTOSCALE_COOLDOWN", 5.0)
            if cooldown is None else cooldown
        )
        self.drain_timeout = drain_timeout
        self.spawn_timeout = spawn_timeout
        if self.min_replicas < 1:
            raise InvalidArgumentError("autoscale min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise InvalidArgumentError(
                f"autoscale max_replicas ({self.max_replicas}) < "
                f"min_replicas ({self.min_replicas})"
            )
        if self.sustain < 1:
            raise InvalidArgumentError("autoscale sustain must be >= 1")
        if self.down_backlog >= self.up_backlog:
            # A deadband, not a line: equal thresholds would flap on
            # every poll that lands exactly on them.
            raise InvalidArgumentError(
                f"autoscale down_backlog ({self.down_backlog}) must be "
                f"strictly below up_backlog ({self.up_backlog})"
            )
        self._lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._last_event = 0.0  # perf_counter of the last scale event
        self._polls = 0
        #: scale-event journal — (time, kind, detail) tuples; the test
        #: and bench surface (events() snapshots it).
        self._events: List[tuple] = []
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "AutoScaler":
        if self._thread is not None:
            return self
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"dpf-autoscale-{self.plane}",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, self.drain_timeout + 5.0))
            self._thread = None

    def __enter__(self) -> "AutoScaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- observability -----------------------------------------------------
    def events(self) -> List[tuple]:
        """Snapshot of the scale-event journal:
        ``(seconds, "up"|"down", detail)`` tuples."""
        with self._lock:
            return list(self._events)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "plane": self.plane,
                "polls": self._polls,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "events": len(self._events),
                "ups": sum(1 for e in self._events if e[1] == "up"),
                "downs": sum(1 for e in self._events if e[1] == "down"),
            }

    # -- signal ------------------------------------------------------------
    def _plane_ops(self, ops) -> List[str]:
        if self.plane == "dealer":
            return [op for op in ops if op in DEALER_OPS]
        if self.plane == "eval":
            return [op for op in ops if op not in DEALER_OPS]
        return list(ops)

    def backlog(self) -> float:
        """The scaling signal: plane queue depth + proxy in-flight,
        per LIVE (non-retiring) replica."""
        health = self.proxy.health()
        fleet = health.get("fleet", {})
        live = [
            r for r in fleet.get("replicas", ())
            if r.get("alive") and not r.get("retiring")
        ]
        queues = dict(self.proxy.stats().get("queues") or {})
        backlog = float(sum(
            queues.get(op, 0) for op in self._plane_ops(queues)
        ))
        backlog += float(health.get("inflight", 0))
        return backlog / max(1, len(live))

    # -- control loop ------------------------------------------------------
    def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                self.poll_once()
            except Exception as exc:  # noqa: BLE001 — the loop survives
                # A flapping replica mid-poll (connection refused, a
                # slot that died while draining) must not kill the
                # control loop: log it to telemetry and keep polling.
                _tm.counter("autoscale.poll_errors", op=self.plane)
                with self._lock:
                    self._events.append(
                        (time.perf_counter(), "error",
                         f"{type(exc).__name__}: {exc}")
                    )
            self._stopped.wait(self.interval)

    def poll_once(self) -> Optional[str]:
        """One control-loop iteration — public so tests and benches can
        step the scaler deterministically without the wall-clock thread.
        Returns "up"/"down" when a scale event fired, else None."""
        per_replica = self.backlog()
        running = self.pool.running_indices()
        size = len(running)
        now = time.perf_counter()
        with self._lock:
            self._polls += 1
            if per_replica >= self.up_backlog:
                self._up_streak += 1
                self._down_streak = 0
            elif per_replica <= self.down_backlog:
                self._down_streak += 1
                self._up_streak = 0
            else:
                # In the deadband: both trends are broken.
                self._up_streak = 0
                self._down_streak = 0
            cooled = now - self._last_event >= self.cooldown
            go_up = (
                cooled and size < self.max_replicas
                and self._up_streak >= self.sustain
            )
            go_down = (
                cooled and size > self.min_replicas
                and self._down_streak >= self.sustain
            )
        if _tm.enabled():
            _tm.gauge("autoscale.backlog_per_replica", per_replica,
                      op=self.plane)
            _tm.gauge("autoscale.replicas", size, op=self.plane)
        if go_up:
            self._scale_up(per_replica)
            return "up"
        if go_down:
            self._scale_down(running, per_replica)
            return "down"
        return None

    def _record(self, kind: str, detail: str) -> None:
        with self._lock:
            self._up_streak = 0
            self._down_streak = 0
            self._last_event = time.perf_counter()
            self._events.append((time.perf_counter(), kind, detail))

    def _scale_up(self, per_replica: float) -> None:
        idx, port, grew = self.pool.scale_up(timeout=self.spawn_timeout)
        # Idempotent on the proxy: un-retires a known endpoint (the
        # remembered-port revival) or appends a brand-new one; either
        # way an immediate probe pulls it into the candidate set.
        self.proxy.add_replica("127.0.0.1", port)
        _tm.counter("autoscale.up", op=self.plane)
        self._record(
            "up",
            f"replica{idx}:{port} ({'new' if grew else 'revived'}) at "
            f"backlog/replica {per_replica:.1f}",
        )

    def _scale_down(self, running: List[int], per_replica: float) -> None:
        victim = self._pick_victim(running)
        if victim is None:
            return
        idx, port = victim
        # Graceful drain: no new requests, finish what it holds, THEN
        # SIGTERM (the server's own drain path catches any queue the
        # proxy could not see). The endpoint stays on the proxy in the
        # retired state — the cheap-revival half of scale_up.
        self.proxy.set_retiring("127.0.0.1", port, True)
        t_end = time.perf_counter() + self.drain_timeout
        while time.perf_counter() < t_end and not self._stopped.is_set():
            state = self.proxy.replica_state("127.0.0.1", port)
            if state is None or state["load"] <= 0:
                break
            time.sleep(min(0.05, self.interval))
        self.pool.scale_down(idx, timeout=self.drain_timeout)
        _tm.counter("autoscale.down", op=self.plane)
        self._record(
            "down",
            f"replica{idx}:{port} drained at backlog/replica "
            f"{per_replica:.1f}",
        )

    def _pick_victim(self, running: List[int]):
        """The replica to drain: the live, least-loaded one by the
        proxy's snapshot — evicting the busiest would maximize the
        drain wait and forfeit the most warm state. Ties break toward
        the NEWEST slot (the oldest replica holds the most warm state,
        and LIFO keeps scale-down symmetric with scale-up's
        revive-last-stopped preference)."""
        best = None
        best_load = None
        ports = list(self.pool.ports)
        for i in running:
            port = ports[i] if i < len(ports) else 0
            state = self.proxy.replica_state("127.0.0.1", port)
            if state is None or state["retiring"]:
                continue
            load = (state["load"], state["routed"])
            if best_load is None or load <= best_load:
                best, best_load = (i, port), load
        return best
