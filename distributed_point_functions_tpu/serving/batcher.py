"""Continuous batcher: aggregate small requests into wide device batches.

The device engines win only on wide uniform batches (PERF.md's engine
table), but serving traffic arrives as many small requests — a few keys
or points each — and at ~66 ms per-dispatch RPC latency, dispatching each
request individually hands every workload to the host engine or eats the
latency. This module applies iteration-level continuous batching (the
Orca idea, Yu et al. OSDI 2022, here at FSS-batch rather than model-token
granularity):

* **Compatibility queues** — requests merge only when one device program
  can serve them: the queue key is (op, DPF parameter signature, value
  type, domain, op-specific extras) via :func:`Request.signature`. Keys
  concatenate along the batch axis; evaluation points union (the batched
  entry points evaluate every key at every point, so a merged batch is a
  superset program and each request's answer is a row/column slice).
* **Batch-deadline timers** — a queue flushes when its width reaches
  ``width_target`` OR its oldest request has waited ``max_wait_ms``:
  wide batches when traffic is heavy, bounded latency when it is not.
  With ``adaptive_wait`` the deadline is width-aware (the remaining Orca
  depth, ISSUE 14): a queue whose traffic cannot fill the width target
  within the full window is not going to — waiting the full
  ``max_wait_ms`` buys no batching, only latency — so its effective
  deadline scales with a per-signature ARRIVAL-RATE EWMA (the width a
  full window would collect, projected from each flush's width over its
  actual accumulation time; never below ``_ADAPT_FLOOR`` of
  ``max_wait_ms``, never above it). Rate, not raw width: widths
  measured under an already-shortened window would self-reinforce and
  never let the window grow back when traffic returns.
* **Fair scheduling + priorities** — when several queues are ripe at
  once, flushes are ordered iteration-level fair across *op classes*
  (the Orca scheduling idea at batch granularity): ops are served
  round-robin by least-recently-served, so a flood of one op class —
  e.g. hundreds of per-key gate queues — cannot starve another op's
  lone ripe queue behind its whole backlog. An optional ``priorities``
  map (op -> class, lower serves first) orders classes before fairness
  applies *within* a class; ``fair=False`` restores the FIFO baseline
  (ripeness-scan order), which is also the bench's starvation arm.
* **Admission control** — total queued requests are bounded by
  ``max_queue_depth``; past it, ``submit`` raises
  ``ResourceExhaustedError`` immediately (fail fast beats queue collapse;
  the caller sheds or retries with backoff).
* **Warm cache** — :class:`WarmCache` holds the prepared-state tier
  (``PreparedPirDatabase`` / ``PreparedLevelsPlan`` / ``PreparedKeyBatch``)
  keyed by params signature + content digest, LRU-bounded, so the
  expensive one-time uploads (a PIR database crossing a ~5 MB/s link, the
  hierarchical gather tables) are paid per *content*, not per batch.

The batcher owns one worker thread; flushes run on it, serialized — the
execution layer behind it (ops/supervisor.py robust wrappers) drives one
device. Telemetry: ``serving.submitted`` / ``serving.rejected`` /
``serving.batches`` counters, ``serving.batch_width`` and
``serving.queue_wait_ms`` histograms, a ``serving.queue_depth`` gauge —
the bench's batch-width histogram and the router's feedback loop read
these off the ISSUE 6 bus.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import telemetry as _tm
from ..utils.errors import (
    InternalError,
    InvalidArgumentError,
    ResourceExhaustedError,
)

#: Ops the front door serves — the six bulk entry points plus the
#: generic FSS gate family (ISSUE 9: any gates/framework.MaskedGate —
#: DReLU/ReLU, splines, bit decomposition — served through its shared
#: fused-DCF GatePlan; MIC predates the framework and keeps its own op)
#: plus "keygen", the dealer-offload op (ISSUE 13: batched two-party key
#: generation; same-parameter requests merge into one level-major pass)
#: plus "hh_ingest", the streaming heavy-hitters key-upload op (ISSUE
#: 15: journaled-then-acknowledged window ingestion — its OWN op class
#: in the fair-flush ordering, so a write-heavy ingest flood cannot
#: starve the query ops behind its backlog).
OPS = (
    "full_domain", "evaluate_at", "dcf", "mic", "gate", "pir",
    "hierarchical", "keygen", "hh_ingest",
)


class ServedFuture:
    """One request's pending result. ``result(timeout)`` blocks until the
    batch containing the request completes (or its failure propagates —
    every request in a failed batch gets the batch's exception)."""

    __slots__ = (
        "_event", "_value", "_error", "submitted_at", "completed_at",
        "batch_width", "choice",
    )

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self.submitted_at: float = 0.0
        self.completed_at: float = 0.0
        #: width of the merged batch this request rode (set at flush).
        self.batch_width: int = 0
        #: the routed engine/mode label (set at flush).
        self.choice: str = ""

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("request not served within timeout")
        if self._error is not None:
            raise self._error
        return self._value

    @property
    def latency_seconds(self) -> float:
        """submit -> completion wall time (valid once done)."""
        return max(0.0, self.completed_at - self.submitted_at)

    def _resolve(self, value) -> None:
        self._value = value
        self.completed_at = time.perf_counter()
        self._event.set()

    def _reject(self, exc: BaseException) -> None:
        self._error = exc
        self.completed_at = time.perf_counter()
        self._event.set()


def _digest(*parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        if isinstance(p, np.ndarray):
            h.update(np.ascontiguousarray(p).tobytes())
        else:
            h.update(repr(p).encode())
    return h.hexdigest()[:16]


def _prefix_bytes(prefixes) -> bytes:
    """Canonical bytes of a prefix sequence: int32/int64 arrays, lists
    and tuples of the same values must digest identically, or equal
    plans never merge and the warm cache re-uploads per representation.
    Structured arrays (>64-bit prefix limbs) hash raw — no int() form."""
    if isinstance(prefixes, np.ndarray) and prefixes.dtype.fields:
        return np.ascontiguousarray(prefixes).tobytes()
    return repr([int(x) for x in prefixes]).encode()


def plan_digest(plan) -> str:
    """Content digest of a raw hierarchical plan (list of
    (hierarchy_level, prefixes)) — the compatibility-queue and warm-cache
    key component for hierarchical requests."""
    h = hashlib.sha256()
    for lvl, prefixes in plan:
        h.update(repr(int(lvl)).encode())
        h.update(_prefix_bytes(prefixes))
    return h.hexdigest()[:16]


@dataclasses.dataclass
class Request:
    """One small serving request: an op, its cryptographic object(s), and
    the op-specific work. Build via the classmethods — they validate the
    op-specific fields and keep the signature rules in one place."""

    op: str
    obj: object  # DistributedPointFunction / DCF / MIC gate
    keys: tuple = ()
    points: tuple = ()  # evaluate_at / dcf / mic evaluation points
    plan: Optional[list] = None  # hierarchical (hierarchy_level, prefixes)
    group: int = 16
    db: object = None  # pir: shared database (array or PreparedPirDatabase)
    #: keygen: per hierarchy level, one beta value per alpha (normalized
    #: at construction so same-parameter batches merge by concatenation).
    betas: Optional[list] = None
    #: hh_ingest (ISSUE 15): (parameters, key blobs, batch_id, flush) —
    #: obj is the HeavyHitterStream; the flush callback journals and
    #: acknowledges each batch individually.
    ingest: Optional[tuple] = None
    hierarchy_level: int = -1
    #: multi-tenant QoS token (ISSUE 20): which tenant submitted this
    #: request — "" means untenanted (the wire absent-field default).
    #: Deliberately NOT part of :meth:`signature`: requests from
    #: different tenants still merge into one device batch (splitting
    #: them would forfeit the batching the front door exists for);
    #: the tenant drives admission quotas, flush ordering within an op
    #: class, and per-tenant telemetry only.
    tenant: str = ""
    future: ServedFuture = dataclasses.field(default_factory=ServedFuture)
    #: absolute completion deadline on the ``time.perf_counter`` clock,
    #: or None (unbounded). Set via :meth:`with_deadline`; the RPC server
    #: sets it from the request's remaining ``deadline_ms``. The front
    #: door sheds at admission when it already can't be met, rejects it
    #: at flush if it expired queued, and arms the supervisor's
    #: ``deadline_scope`` with the batch's minimum remaining budget so a
    #: wire deadline bounds device dispatch too (ISSUE 10).
    deadline: Optional[float] = None

    def with_deadline(self, seconds: Optional[float]) -> "Request":
        """Arms this request's completion deadline `seconds` from now
        (None disarms); returns self for construction chaining:
        ``Request.evaluate_at(...).with_deadline(0.25)``."""
        if seconds is None:
            self.deadline = None
        else:
            if seconds <= 0:
                raise InvalidArgumentError(
                    f"deadline must be > 0 seconds, got {seconds!r}"
                )
            self.deadline = time.perf_counter() + float(seconds)
        return self

    def with_tenant(self, tenant: str) -> "Request":
        """Tags this request with a tenant token (construction chaining,
        like :meth:`with_deadline`); "" clears the tag."""
        self.tenant = str(tenant)
        return self

    def remaining(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds of deadline budget left (negative = expired), or None
        when unbounded."""
        if self.deadline is None:
            return None
        return self.deadline - (time.perf_counter() if now is None else now)

    # -- constructors ------------------------------------------------------
    @classmethod
    def full_domain(cls, dpf, keys: Sequence, hierarchy_level: int = -1):
        return cls(
            op="full_domain", obj=dpf, keys=tuple(keys),
            hierarchy_level=hierarchy_level,
        )

    @classmethod
    def evaluate_at(
        cls, dpf, keys: Sequence, points: Sequence[int],
        hierarchy_level: int = -1,
    ):
        return cls(
            op="evaluate_at", obj=dpf, keys=tuple(keys),
            points=tuple(int(p) for p in points),
            hierarchy_level=hierarchy_level,
        )

    @classmethod
    def dcf(cls, dcf, keys: Sequence, xs: Sequence[int]):
        return cls(
            op="dcf", obj=dcf, keys=tuple(keys),
            points=tuple(int(x) for x in xs),
        )

    @classmethod
    def mic(cls, gate, key, xs: Sequence[int]):
        return cls(
            op="mic", obj=gate, keys=(key,),
            points=tuple(int(x) for x in xs),
        )

    @classmethod
    def gate(cls, gate, key, xs: Sequence[int]):
        """Any framework gate (gates/framework.MaskedGate): one party
        key's gate evaluated at many masked inputs — the MIC batching
        shape generalized to the whole family."""
        return cls(
            op="gate", obj=gate, keys=(key,),
            points=tuple(int(x) for x in xs),
        )

    @classmethod
    def pir(cls, dpf, keys: Sequence, db):
        return cls(op="pir", obj=dpf, keys=tuple(keys), db=db)

    @classmethod
    def keygen(cls, dpf, alphas: Sequence[int], betas):
        """Dealer keygen offload (ISSUE 13): K key pairs for `alphas`,
        `betas` per hierarchy level (scalar broadcast or one per alpha;
        normalized per-alpha here so same-parameter requests merge by
        concatenation). Carries no keys — the RESULT is keys.

        Alphas and beta values are FULLY validated here, not at flush:
        keygen requests merge across connections on parameters alone, so
        a deferred error would reject every co-merged request with one
        client's INVALID_ARGUMENT."""
        from ..core import keygen as core_keygen
        from ..utils.errors import InvalidArgumentError as _IAE

        alphas = tuple(int(a) for a in alphas)
        v = dpf.validator
        cols = core_keygen.normalize_beta_cols(
            betas, len(alphas), v.num_hierarchy_levels
        )
        last_lds = v.parameters[-1].log_domain_size
        for a in alphas:
            if a < 0 or (last_lds < 128 and a >= (1 << last_lds)):
                raise _IAE(
                    "`alpha` must be smaller than the output domain size"
                )
        for level, col in enumerate(cols):
            for val in col:
                v.validate_value(val, level)
        return cls(op="keygen", obj=dpf, points=alphas, betas=cols)

    @classmethod
    def hh_ingest(cls, stream, parameters, key_blobs, batch_id: str,
                  flush: bool = False):
        """One client key batch into a heavy-hitter stream's open
        window (ISSUE 15). `key_blobs` are the serialized DpfKey bytes
        exactly as received — the journal records what was acknowledged,
        so the wire bytes ARE the durable form. An empty batch with
        `flush` is a pure window-close control message."""
        return cls(
            op="hh_ingest", obj=stream,
            ingest=(
                tuple(parameters), tuple(bytes(b) for b in key_blobs),
                str(batch_id), bool(flush),
            ),
        )

    @classmethod
    def hierarchical(cls, dpf, keys: Sequence, plan, group: int = 16):
        return cls(
            op="hierarchical", obj=dpf, keys=tuple(keys),
            plan=[(int(h), p) for h, p in plan], group=group,
        )

    # -- batching ----------------------------------------------------------
    def _validator(self):
        if self.op in ("dcf",):
            return self.obj.dpf.validator
        if self.op in ("mic", "gate"):
            return self.obj.dcf.dpf.validator
        return self.obj.validator

    def params_signature(self) -> tuple:
        from ..utils import integrity

        return integrity._params_signature(self._validator())

    def party(self) -> int:
        if self.op == "keygen":
            return -1  # the dealer generates BOTH parties' keys
        k = self.keys[0]
        if self.op == "dcf":
            return k.key.party
        if self.op == "mic":
            return k.dcf_key.key.party
        if self.op == "gate":
            return k.dcf_keys[0].key.party
        return k.party

    def signature(self) -> tuple:
        """The compatibility-queue key: requests with equal signatures can
        merge into one device batch. Params signature covers value type
        and domain per hierarchy level; op-specific extras pin what the
        merged program additionally shares (the PIR database identity,
        the hierarchical plan + group, the MIC key — a MIC batch is one
        key's gate evaluated at many masked inputs)."""
        if self.op not in OPS:
            raise InvalidArgumentError(f"unknown serving op {self.op!r}")
        if self.op == "keygen":
            # No keys and no party: any same-parameter keygen requests
            # merge — the batch is one level-major pass over the
            # concatenated alphas/beta columns.
            return (self.op, self.params_signature())
        if self.op == "hh_ingest":
            # One queue per stream: ingests serialize through the
            # stream's window manager in arrival order, and the op class
            # rides the fair-flush rotation like any other.
            return (self.op, self.obj.config.name)
        if not self.keys:
            raise InvalidArgumentError("request carries no keys")
        # Party rides every signature: a merged KeyBatch must be one
        # party's keys (the KeyBatch.from_keys contract).
        base = (self.op, self.params_signature(), self.party())
        if self.op in ("full_domain", "evaluate_at"):
            return base + (self.hierarchy_level,)
        if self.op == "pir":
            return base + (id(self.db),)
        if self.op == "hierarchical":
            return base + (plan_digest(self.plan), self.group)
        if self.op == "mic":
            key = self.keys[0]
            return base + (
                _digest(key.dcf_key.key.seed, tuple(key.output_mask_shares)),
            )
        if self.op == "gate":
            # One gate + one party key per queue (like MIC): the merged
            # batch is that key's gate at the union of masked inputs.
            # Gate identity = class + the framework's declared public
            # config (MaskedGate.config_signature — the accessor every
            # gate owns, so new gates can't silently under-key); key
            # identity = the component seeds + mask shares.
            key = self.keys[0]
            g = self.obj
            return base + (
                type(g).__name__,
                _digest(g.log_group_size, g.config_signature()),
                _digest(
                    tuple(dk.key.seed for dk in key.dcf_keys),
                    tuple(key.mask_shares),
                ),
            )
        return base  # dcf

    @property
    def width(self) -> int:
        """This request's contribution to the batch-width target: keys
        for the key-merged ops, evaluation points for the gate ops (one
        key by construction), alphas for keygen (keys to produce)."""
        if self.op in ("mic", "gate", "keygen"):
            return len(self.points)
        if self.op == "hh_ingest":
            return max(1, len(self.ingest[1]))  # keys (1 for pure flush)
        return len(self.keys)


class _Queue:
    __slots__ = ("sig", "requests", "width", "oldest", "taken_elapsed")

    def __init__(self, sig):
        self.sig = sig
        self.requests: List[Request] = []
        self.width = 0
        self.oldest = float("inf")
        #: accumulation time at the moment _take_ripe POPPED the queue —
        #: the adaptive-rate denominator. Measured at pop, not at flush:
        #: time spent waiting in pump's pending list behind other
        #: batches is service contention, not arrival-rate evidence, and
        #: counting it would underestimate busy signatures' rates.
        self.taken_elapsed = 0.0


#: adaptive_wait never shrinks a queue's effective deadline below this
#: fraction of ``max_wait_ms`` — light-traffic queues flush early, but a
#: burst arriving just after its first request still gets a window to
#: merge into.
_ADAPT_FLOOR = 0.25

#: adaptive_wait needs this many flush samples for a signature before it
#: trusts the rate EWMA (a single quiet flush must not collapse the
#: window for a queue that was merely unlucky once).
_ADAPT_MIN_SAMPLES = 3

#: bound on the per-signature rate-EWMA table (signatures are
#: client-controlled for the per-key gate ops; LRU-evict past this).
_ADAPT_MAX_SIGS = 512


class ContinuousBatcher:
    """Per-signature compatibility queues + the flush worker.

    ``flush`` is called on the worker thread as ``flush(sig, requests)``
    and must resolve/reject every request's future; an exception it
    raises rejects the whole batch (each future carries it). Use as a
    context manager, or call :meth:`start` / :meth:`stop` explicitly;
    :meth:`pump` flushes ripe queues inline for deterministic tests.

    ``priorities`` maps op -> scheduling class (lower flushes first;
    missing ops are class 0); within a class, ripe queues are served
    round-robin across ops (``fair=True``) so no op class starves behind
    a flood of another. ``adaptive_wait`` scales each queue's batch
    deadline by its flushed-width history (see the module docstring);
    since ISSUE 20 it defaults ON — tenant quotas bound the failure
    mode (one tenant's flood holding every window at full width) that
    kept it opt-in.

    Multi-tenant QoS (ISSUE 20): ``tenant_quotas`` maps tenant token ->
    max queued requests for that tenant (0 / missing = the
    ``tenant_default_quota``, itself 0 = unbounded); past its quota a
    tenant's submit raises ``ResourceExhaustedError`` while other
    tenants keep admitting — admission control per tenant, layered
    INSIDE the global ``max_queue_depth``. ``tenant_priorities`` maps
    tenant token -> scheduling class (lower first, missing = 0): within
    an op class's flush rotation, a higher-priority tenant's ripe queue
    flushes first. Tenants never affect :meth:`Request.signature` —
    cross-tenant requests still merge into one batch.
    """

    def __init__(
        self,
        flush: Callable[[tuple, List[Request]], None],
        max_wait_ms: float = 5.0,
        width_target: int = 64,
        max_queue_depth: int = 1024,
        priorities: Optional[Dict[str, int]] = None,
        fair: bool = True,
        adaptive_wait: bool = True,
        tenant_quotas: Optional[Dict[str, int]] = None,
        tenant_default_quota: int = 0,
        tenant_priorities: Optional[Dict[str, int]] = None,
    ):
        if width_target < 1 or max_queue_depth < 1:
            raise InvalidArgumentError(
                "width_target and max_queue_depth must be >= 1"
            )
        if tenant_default_quota < 0 or any(
            v < 0 for v in (tenant_quotas or {}).values()
        ):
            raise InvalidArgumentError("tenant quotas must be >= 0")
        self._flush = flush
        self.max_wait = max_wait_ms / 1e3
        self.width_target = width_target
        self.max_queue_depth = max_queue_depth
        self.priorities = dict(priorities or {})
        self.fair = fair
        self.adaptive_wait = adaptive_wait
        self.tenant_quotas = dict(tenant_quotas or {})
        self.tenant_default_quota = int(tenant_default_quota)
        self.tenant_priorities = dict(tenant_priorities or {})
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[tuple, _Queue] = collections.OrderedDict()
        self._pending = 0
        #: per-signature EWMA of request ARRIVAL rates (width / actual
        #: accumulation time at flush — adaptive_wait's input),
        #: LRU-bounded; values are (rate_per_second, samples).
        self._rate_ewma: "collections.OrderedDict[tuple, Tuple[float, int]]" = (
            collections.OrderedDict()
        )
        #: per-tenant queued request counts (admission quota input) and
        #: cumulative admission/serving counters — the stats-frame
        #: ``tenants`` section (ISSUE 20). Both owned by self._lock.
        self._tenant_pending: Dict[str, int] = {}
        self._tenant_counters: Dict[str, Dict[str, int]] = {}
        #: fairness clock: op -> sequence number of its last flush.
        self._op_last_served: Dict[str, int] = {}
        self._serve_seq = 0
        self._worker: Optional[threading.Thread] = None
        self._stop = False
        #: the exception that killed the worker thread, once dead. A dead
        #: worker can never flush, so a `ServedFuture.wait()` with no
        #: timeout on anything still queued would block FOREVER — the
        #: worker's last act is rejecting every queued future and pinning
        #: this marker so later submits fail fast too (ISSUE 10 satellite).
        self._dead: Optional[BaseException] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ContinuousBatcher":
        with self._lock:
            if self._worker is not None:
                return self
            self._stop = False
            self._worker = threading.Thread(
                target=self._run, name="dpf-serving-batcher", daemon=True
            )
            self._worker.start()
        return self

    def stop(self) -> None:
        """Flushes everything still queued, then joins the worker."""
        with self._lock:
            self._stop = True
            self._cond.notify_all()
            worker = self._worker
            self._worker = None
        if worker is not None:
            worker.join()
        self.pump(force=True)

    def __enter__(self) -> "ContinuousBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- submission --------------------------------------------------------
    def submit(self, req: Request) -> ServedFuture:
        sig = req.signature()  # validate outside the lock
        width = req.width
        if width < 1:
            raise InvalidArgumentError("request carries no keys/points")
        with self._lock:
            if self._dead is not None:
                _tm.counter("serving.rejected", op=req.op)
                raise InternalError(
                    "serving batcher worker thread died: request rejected "
                    f"(cause: {type(self._dead).__name__}: {self._dead})"
                ) from self._dead
            if self._stop:
                # After stop()'s final drain a queued request would never
                # flush — fail fast like admission control, not a hang.
                _tm.counter("serving.rejected", op=req.op)
                raise ResourceExhaustedError(
                    "serving batcher is stopped: request rejected "
                    "(start() the batcher / front door again to serve)"
                )
            if self._pending >= self.max_queue_depth:
                _tm.counter("serving.rejected", op=req.op)
                raise ResourceExhaustedError(
                    f"serving queue full ({self._pending} pending >= "
                    f"max_queue_depth={self.max_queue_depth}): admission "
                    "control rejected the request — retry with backoff"
                )
            quota = self.tenant_quotas.get(
                req.tenant, self.tenant_default_quota
            )
            tenant_pending = self._tenant_pending.get(req.tenant, 0)
            if quota > 0 and tenant_pending >= quota:
                self._tenant_counters.setdefault(
                    req.tenant, {"admitted": 0, "rejected": 0, "served": 0}
                )["rejected"] += 1
                _tm.counter("serving.rejected", op=req.op)
                if req.tenant:
                    _tm.counter("serving.tenant.rejected", op=req.tenant)
                raise ResourceExhaustedError(
                    f"tenant {req.tenant or '<untenanted>'} over its "
                    f"admission quota ({tenant_pending} pending >= "
                    f"{quota}): retry with backoff — other tenants are "
                    "unaffected"
                )
            q = self._queues.get(sig)
            new_queue = q is None
            if new_queue:
                q = self._queues[sig] = _Queue(sig)
            req.future.submitted_at = time.perf_counter()
            q.requests.append(req)
            q.width += width
            q.oldest = min(q.oldest, req.future.submitted_at)
            self._pending += 1
            self._tenant_pending[req.tenant] = tenant_pending + 1
            self._tenant_counters.setdefault(
                req.tenant, {"admitted": 0, "rejected": 0, "served": 0}
            )["admitted"] += 1
            if _tm.enabled():
                _tm.counter("serving.submitted", op=req.op)
                _tm.gauge("serving.queue_depth", self._pending)
                if req.tenant:
                    _tm.counter("serving.tenant.submitted", op=req.tenant)
            # Wake the worker only when this submit changes what it
            # should do: a NEW queue needs its deadline armed, a queue
            # crossing the width target needs flushing now. A submit
            # into an existing sub-target queue can't move its deadline
            # earlier (q.oldest only ages), so waking would just rescan
            # every queue under the lock on the hot path.
            if new_queue or q.width >= self.width_target:
                self._cond.notify_all()
        return req.future

    def pending(self) -> int:
        with self._lock:
            return self._pending

    def queue_depths(self) -> Dict[str, int]:
        """Queued request count per op — the stats-frame field the fleet
        proxy's least-loaded routing reads (ISSUE 14)."""
        with self._lock:
            out: Dict[str, int] = {}
            for q in self._queues.values():
                if q.requests:
                    op = q.requests[0].op
                    out[op] = out.get(op, 0) + len(q.requests)
            return out

    def arrival_rates(self) -> Dict[str, float]:
        """Per-op arrival-rate EWMAs (requests/second), the SUM over the
        op's signatures — the ``rates`` stats-frame field the autoscaler
        consumes (ISSUE 20). Only signatures past the adaptive-wait
        sample floor contribute: a one-flush rate is noise, and the
        autoscaler must not scale on it any more than the window does.
        Signatures lead with the op name, so the aggregation is a plain
        group-by on the table adaptive_wait already maintains."""
        with self._lock:
            out: Dict[str, float] = {}
            for sig, (rate, n) in self._rate_ewma.items():
                if n < _ADAPT_MIN_SAMPLES:
                    continue
                op = sig[0]
                out[op] = out.get(op, 0.0) + rate
            return out

    def tenant_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant admission/serving counters plus current pending —
        the ``tenants`` stats-frame section (ISSUE 20). Untenanted
        traffic appears under the "" token."""
        with self._lock:
            out = {
                t: dict(c) for t, c in self._tenant_counters.items()
            }
            for t, n in self._tenant_pending.items():
                out.setdefault(
                    t, {"admitted": 0, "rejected": 0, "served": 0}
                )["pending"] = n
            for c in out.values():
                c.setdefault("pending", 0)
            return out

    # -- flushing ----------------------------------------------------------
    def _wait_for(self, sig: tuple) -> float:
        """Effective batch deadline for `sig`, seconds. Caller holds
        self._lock. Width-aware adaptation: a queue whose flushes have
        been running at a fraction of the width target is not going to
        fill — scale its window down proportionally (floored) so light
        traffic stops paying latency for batching it never gets."""
        if not self.adaptive_wait:
            return self.max_wait
        hit = self._rate_ewma.get(sig)
        if hit is None or hit[1] < _ADAPT_MIN_SAMPLES:
            return self.max_wait
        # The width a FULL window would collect at the measured rate —
        # window-invariant, so a shortened window can grow back the
        # moment traffic does.
        projected = hit[0] * self.max_wait
        frac = projected / self.width_target
        return self.max_wait * min(1.0, max(_ADAPT_FLOOR, frac))

    def _take_ripe(self, now: float, force: bool) -> List[_Queue]:
        """Pops every queue that is ripe (width target met, deadline
        passed, or force). Caller holds no lock."""
        ripe: List[_Queue] = []
        with self._lock:
            for sig in list(self._queues):
                q = self._queues[sig]
                if not q.requests:
                    del self._queues[sig]
                    continue
                expired = now - q.oldest >= self._wait_for(sig)
                if force or expired or q.width >= self.width_target:
                    del self._queues[sig]
                    self._pending -= len(q.requests)
                    for r in q.requests:
                        left = self._tenant_pending.get(r.tenant, 1) - 1
                        if left <= 0:
                            self._tenant_pending.pop(r.tenant, None)
                        else:
                            self._tenant_pending[r.tenant] = left
                        self._tenant_counters.setdefault(
                            r.tenant,
                            {"admitted": 0, "rejected": 0, "served": 0},
                        )["served"] += 1
                    q.taken_elapsed = now - q.oldest
                    ripe.append(q)
            if _tm.enabled() and ripe:
                _tm.gauge("serving.queue_depth", self._pending)
        return ripe

    def _tenant_class(self, q: _Queue) -> int:
        """A queue's tenant scheduling class: the BEST (minimum) class
        among its merged requests — a shared batch carrying one
        high-priority tenant's request must not wait behind that
        tenant's class peers. Class 0 (the default) when no tenant
        priorities are configured."""
        if not self.tenant_priorities:
            return 0
        return min(
            self.tenant_priorities.get(r.tenant, 0) for r in q.requests
        )

    def _order_ripe(self, ripe: List[_Queue]) -> List[_Queue]:
        """Iteration-level fair flush order (the Orca scheduling idea at
        batch granularity): priority class first, then round-robin
        across op classes by least-recently-served, oldest queue first
        within an op. Tenant classes (ISSUE 20) layer INSIDE the op
        rotation: among one op's ripe queues, a higher-priority
        tenant's queue flushes first — the op-level starvation guarantee
        is untouched. ``fair=False`` keeps the ripeness-scan (FIFO)
        order within a priority class — the baseline a flood of per-key
        gate queues starves — but explicit ``priorities`` /
        ``tenant_priorities`` maps still apply (an operator who set
        classes gets classes, whichever fairness arm is running)."""
        if len(ripe) <= 1:
            return ripe
        if not self.fair:
            if not self.priorities and not self.tenant_priorities:
                return ripe
            return sorted(  # stable: FIFO within each priority class
                ripe,
                key=lambda q: (
                    self.priorities.get(q.requests[0].op, 0),
                    self._tenant_class(q),
                ),
            )
        by_op: Dict[str, List[_Queue]] = collections.OrderedDict()
        for q in ripe:
            by_op.setdefault(q.requests[0].op, []).append(q)
        for queues in by_op.values():
            queues.sort(key=lambda q: (self._tenant_class(q), q.oldest))
        out: List[_Queue] = []
        with self._lock:
            while by_op:
                op = min(
                    by_op,
                    key=lambda o: (
                        self.priorities.get(o, 0),
                        self._op_last_served.get(o, -1),
                    ),
                )
                out.append(by_op[op].pop(0))
                self._serve_seq += 1
                self._op_last_served[op] = self._serve_seq
                if not by_op[op]:
                    del by_op[op]
        return out

    def _observe_rate(self, sig: tuple, width: int, elapsed: float) -> None:
        rate = width / max(elapsed, 1e-4)
        with self._lock:
            ewma, n = self._rate_ewma.get(sig, (rate, 0))
            self._rate_ewma[sig] = (0.5 * rate + 0.5 * ewma, n + 1)
            self._rate_ewma.move_to_end(sig)
            while len(self._rate_ewma) > _ADAPT_MAX_SIGS:
                self._rate_ewma.popitem(last=False)

    def _run_flush(self, q: _Queue, forced: bool = False) -> None:
        op = q.requests[0].op
        if not forced:
            # Forced drains (shutdown, inline test pumps) are not
            # traffic evidence — their near-zero accumulation time would
            # read as an infinite arrival rate.
            self._observe_rate(q.sig, q.width, q.taken_elapsed)
        if _tm.enabled():
            _tm.counter("serving.batches", op=op)
            _tm.observe("serving.batch_width", q.width, op=op)
            now = time.perf_counter()
            for r in q.requests:
                _tm.observe(
                    "serving.queue_wait_ms",
                    (now - r.future.submitted_at) * 1e3,
                    op=op,
                )
        for r in q.requests:
            r.future.batch_width = q.width
        try:
            self._flush(q.sig, q.requests)
        except BaseException as exc:  # noqa: BLE001 — delivered per future
            for r in q.requests:
                if not r.future.done():
                    r.future._reject(exc)
        # A flush that "succeeds" but forgets a future would hang its
        # caller forever; surface the contract violation instead.
        for r in q.requests:
            if not r.future.done():
                r.future._reject(
                    InvalidArgumentError(
                        "serving flush completed without resolving this "
                        "request (front-door bug)"
                    )
                )

    def pump(self, force: bool = False) -> int:
        """Flushes ripe (or, with force, all) queues inline on the caller
        thread; returns the number of batches flushed. The deterministic
        test/shutdown path — the worker thread does exactly this on a
        timer.

        With ``fair`` (and not ``force``), scheduling is ITERATION-level
        (the Orca granularity): after every flushed batch the ripe set
        is re-scanned and re-ordered, so a request that ripens while a
        long pass of another op's backlog drains waits at most ONE batch
        service — not the remainder of the pass. ``force`` keeps the
        single-scan drain semantics (the shutdown path must terminate
        against concurrent submitters)."""
        flushed = 0
        pending = self._order_ripe(self._take_ripe(time.perf_counter(), force))
        while pending:
            self._run_flush(pending.pop(0), forced=force)
            flushed += 1
            if self.fair and not force and not self._stop:
                fresh = self._take_ripe(time.perf_counter(), False)
                if fresh:
                    pending = self._order_ripe(pending + fresh)
        return flushed

    @property
    def dead(self) -> Optional[BaseException]:
        """The exception that killed the worker, or None while healthy —
        the server's readiness probe reports it."""
        return self._dead

    def _mark_dead(self, exc: BaseException) -> None:
        """The dying worker's cleanup: pin the death marker (new submits
        fail fast), then reject every queued future — nothing else will
        ever flush them, and their waiters may hold no timeout."""
        with self._lock:
            self._dead = exc
            orphans = [
                r for q in self._queues.values() for r in q.requests
            ]
            self._queues.clear()
            self._pending = 0
            self._tenant_pending.clear()
            self._cond.notify_all()
        _tm.counter("serving.worker_death")
        wrapped = InternalError(
            "serving batcher worker thread died mid-service "
            f"(cause: {type(exc).__name__}: {exc})"
        )
        wrapped.__cause__ = exc
        for r in orphans:
            _tm.counter("serving.rejected", op=r.op)
            if not r.future.done():
                r.future._reject(wrapped)

    def _run(self) -> None:
        try:
            self._loop()
        except BaseException as exc:  # noqa: BLE001 — delivered per future
            self._mark_dead(exc)

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
                deadline = None
                now = time.perf_counter()
                ready = False
                for q in self._queues.values():
                    if not q.requests:
                        continue
                    wait = self._wait_for(q.sig)
                    if (
                        q.width >= self.width_target
                        or now - q.oldest >= wait
                    ):
                        ready = True
                        break
                    d = q.oldest + wait
                    deadline = d if deadline is None else min(deadline, d)
                if not ready:
                    timeout = (
                        None if deadline is None
                        else max(0.0, deadline - now)
                    )
                    self._cond.wait(timeout=timeout)
                    if self._stop:
                        return
            self.pump()


# ---------------------------------------------------------------------------
# Warm cache: the prepared-state tier
# ---------------------------------------------------------------------------


class _LRU:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.data: "collections.OrderedDict" = collections.OrderedDict()

    def get(self, key):
        if key in self.data:
            self.data.move_to_end(key)
            return self.data[key]
        return None

    def put(self, key, value):
        self.data[key] = value
        self.data.move_to_end(key)
        while len(self.data) > self.capacity:
            self.data.popitem(last=False)


class WarmCache:
    """LRU cache of the prepared-state tier, keyed by params signature +
    content digest:

    * ``pir_db`` — ``PreparedPirDatabase`` per (params, db identity,
      order, host_levels): the database crosses the host link once per
      content and order, not per query batch.
    * ``levels_plan`` — ``PreparedLevelsPlan`` per (params, plan digest,
      group, mode): the hierarchical gather tables compose + upload once
      and replay across key batches (the documented prepared-replay
      contract).
    * ``key_batch`` — ``PreparedKeyBatch`` per (params, key digest,
      hierarchy level, key_chunk, host_levels): a repeated key set (a
      persistent client, a key batch folded against several databases)
      skips the per-call pack + upload.

    Capacities are entry counts per tier; a PIR database can be ~100 MB,
    so the default keeps few.
    """

    def __init__(self, db_capacity: int = 4, plan_capacity: int = 8,
                 keys_capacity: int = 8):
        self._lock = threading.Lock()
        self._dbs = _LRU(db_capacity)
        self._plans = _LRU(plan_capacity)
        self._keys = _LRU(keys_capacity)

    def inventory(self) -> Dict[str, List[str]]:
        """Digest inventory of the warm tiers — the stats-frame field the
        fleet proxy exposes so an operator can see WHICH replica holds a
        prepared database / plan / key batch hot (ISSUE 14). Digests are
        short hashes of the tier keys (stable within a process; the PIR
        tier's key includes an object id, so cross-replica equality is
        not meaningful there — presence and counts are)."""
        with self._lock:
            return {
                "pir": [_digest(k) for k in self._dbs.data],
                "plans": [_digest(k) for k in self._plans.data],
                "keys": [_digest(k) for k in self._keys.data],
            }

    def _get_or_make(self, lru: _LRU, key, make, op: str):
        with self._lock:
            hit = lru.get(key)
        if hit is not None:
            _tm.counter("serving.cache_hit", op=op)
            return hit
        _tm.counter("serving.cache_miss", op=op)
        value = make()
        with self._lock:
            lru.put(key, value)
        return value

    def pir_db(self, dpf, db, order: str, host_levels=None):
        """The database prepared in ``order`` — pass-through when ``db``
        is already a ``PreparedPirDatabase`` of that order. Keyed by the
        source object's identity, with the source kept alive INSIDE the
        cache entry: id() alone could alias a new database allocated at
        a freed one's address and silently serve stale PIR rows."""
        from ..parallel import sharded

        if isinstance(db, sharded.PreparedPirDatabase) and db.order == order:
            return db
        key = ("pir", id(db), order, host_levels)

        def make():
            src = (
                db.natural_host(dpf)
                if isinstance(db, sharded.PreparedPirDatabase)
                else np.asarray(db)
            )
            prepared = sharded.prepare_pir_database(
                dpf, src, host_levels, order=order
            )
            return (db, prepared)  # db ref pins the id the key encodes

        return self._get_or_make(self._dbs, key, make, "pir")[1]

    def levels_plan(self, dpf, keys, plan, group: int, mode=None):
        """``PreparedLevelsPlan`` for (plan, group, mode) — composed from
        a context over `keys` but replayable across any key batch of the
        same DPF (the prepared-replay contract tools/check_device.py's
        "prepared" extra verifies on-chip)."""
        from ..ops import hierarchical
        from ..utils import integrity

        key = (
            "plan", integrity._params_signature(dpf.validator),
            plan_digest(plan), group, mode,
        )

        def make():
            ctx = hierarchical.BatchedContext.create(dpf, list(keys))
            return hierarchical.prepare_levels_fused(
                ctx, plan, group, mode=mode
            )

        return self._get_or_make(self._plans, key, make, "hierarchical")

    def key_batch(self, dpf, keys, hierarchy_level: int = -1,
                  key_chunk: int = 128, host_levels=None):
        from ..ops import evaluator
        from ..utils import integrity

        digest = _digest(*[
            (
                k.seed, k.party,
                tuple(cw.seed for cw in k.correction_words),
                tuple(int(v) for v in k.last_level_value_correction),
            )
            for k in keys
        ])
        key = (
            "keys", integrity._params_signature(dpf.validator), digest,
            hierarchy_level, key_chunk, host_levels, len(keys),
        )
        return self._get_or_make(
            self._keys, key,
            lambda: evaluator.PreparedKeyBatch(
                dpf, list(keys), hierarchy_level, key_chunk=key_chunk,
                host_levels=host_levels,
            ),
            "full_domain",
        )
