"""The fault-tolerant RPC client + the two-server runtime (ISSUE 10).

:class:`DpfClient` speaks one server; its ``call`` owns the full
fault-tolerance vocabulary:

* **per-attempt timeouts** — every socket read/write is bounded
  (``RetryPolicy.attempt_timeout``); a slow server becomes a retry, not a
  hang;
* **jittered exponential backoff** — retryable failures
  (``UNAVAILABLE``, connection errors, torn frames, attempt timeouts)
  back off ``base_backoff * multiplier**n`` with multiplicative jitter,
  so two retrying clients don't stampede a recovering server;
* **backpressure honored** — ``RESOURCE_EXHAUSTED`` (the server's
  bounded-depth admission shed) is a retry-with-backoff, not an error:
  the server said "later", not "never";
* **reconnect budget** — a lost connection is re-dialed inside the
  attempt (``connect_attempts`` x ``connect_backoff``), which is what
  carries a call across a server SIGKILL + restart; the budget caps it
  so a dead server becomes ``UnavailableError``, not an infinite dial
  loop;
* **fail-fast taxonomy** — ``DEADLINE_EXCEEDED``, ``INVALID_ARGUMENT``,
  ``FAILED_PRECONDITION`` (version mismatch) never retry: retrying
  cannot change the outcome;
* **request-id discipline** — a response whose id doesn't match the
  outstanding request means the stream desynchronized; the connection is
  dropped (and the attempt retried) rather than trusting a mismatched
  answer.

Telemetry (the soak's completeness surface): ``rpc.client.requests`` /
``retries`` / ``reconnects`` / ``attempt_timeouts`` / ``id_mismatch``
counters and the ``rpc.client.backoff_ms`` histogram, all per-op.

:class:`TwoServerClient` composes two clients into the FSS deployment
shape: every op runs against both parties concurrently, and a party that
stays down past its budget raises :class:`PartyUnavailableError` naming
the dead party — reconstruct ops fail fast and attributably instead of
hanging on one answer.
"""

from __future__ import annotations

import dataclasses
import random
import socket
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils import envflags
from ..utils import telemetry as _tm
from ..utils.errors import (
    DpfError,
    FailedPreconditionError,
    InvalidArgumentError,
    UnavailableError,
)
from . import wire


class PartyUnavailableError(UnavailableError):
    """A two-server op failed because one party is down: carries which
    (``party``: 0 or 1) so the caller can page the right replica instead
    of guessing — the partial-failure contract."""

    def __init__(self, message: str, party: int):
        super().__init__(message)
        self.party = party


@dataclasses.dataclass
class RetryPolicy:
    """The client's fault-tolerance knobs (README's knob table).

    ``attempts`` bounds delivered-but-failed tries of one call;
    ``connect_attempts`` x ``connect_backoff`` bounds re-dialing inside
    each attempt (sized so a server restart — seconds of process + jax
    startup — fits one attempt's reconnect loop). ``seed`` pins the
    jitter stream: the chaos soak replays byte-identical schedules."""

    attempts: int = 4
    base_backoff: float = 0.05
    max_backoff: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25
    attempt_timeout: Optional[float] = 30.0
    connect_timeout: float = 5.0
    connect_attempts: int = 60
    connect_backoff: float = 0.25
    seed: Optional[int] = None

    def backoff_seconds(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number `attempt` (1-based), jittered
        multiplicatively in [1-jitter, 1+jitter]."""
        base = min(
            self.max_backoff,
            self.base_backoff * self.multiplier ** (attempt - 1),
        )
        return base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))


class DpfClient:
    """One server's client endpoint. Thread-compatible, not thread-safe:
    one outstanding call at a time (an internal lock enforces it) — run
    one client per worker thread for concurrency, which also gives the
    server's batcher multiple connections to merge across."""

    def __init__(
        self,
        host: str,
        port: int,
        policy: Optional[RetryPolicy] = None,
        max_body: int = wire.DEFAULT_MAX_BODY,
        tenant: Optional[str] = None,
    ):
        self.host = host
        self.port = port
        self.policy = policy or RetryPolicy()
        self.max_body = max_body
        #: ISSUE 20: QoS identity stamped on every request envelope.
        #: None falls back to DPF_TPU_TENANT; "" stays untenanted and
        #: encodes byte-identical to a pre-tenant client.
        self.tenant = (
            tenant
            if tenant is not None
            else envflags.env_str("DPF_TPU_TENANT", "")
        )
        self._rng = random.Random(self.policy.seed)
        self._sock: Optional[socket.socket] = None
        self._next_id = 0
        self._lock = threading.Lock()

    # -- connection --------------------------------------------------------
    def connect(self) -> "DpfClient":
        with self._lock:
            self._ensure_connected(None)
        return self

    def close(self) -> None:
        self._drop()

    def __enter__(self) -> "DpfClient":
        return self.connect()

    def __exit__(self, *exc) -> None:
        self.close()

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _connect_once(self) -> None:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.policy.connect_timeout
        )
        sock.settimeout(self.policy.attempt_timeout)
        try:
            self._next_id += 1
            wire.write_frame(sock, wire.T_HELLO, self._next_id)
            reply = wire.read_frame(
                sock, max_body=self.max_body, check_version=False
            )
        except BaseException:
            sock.close()
            raise
        if reply is None:
            sock.close()
            raise UnavailableError(
                "UNAVAILABLE: server closed the connection during handshake"
            )
        if reply.ftype == wire.T_ERROR:
            code, message = wire.decode_error_body(reply.body)
            sock.close()
            # FAILED_PRECONDITION here is the version-mismatch answer:
            # deterministic, never retried.
            raise wire.exception_for_status(code, message)
        if reply.ftype != wire.T_HELLO_OK:
            sock.close()
            raise wire.FrameError(
                f"handshake answered with frame type {reply.ftype}, "
                "not T_HELLO_OK"
            )
        self._sock = sock

    def _ensure_connected(self, deadline: Optional[float]) -> None:
        """Dials until connected, the reconnect budget runs out, or the
        call deadline passes. FailedPrecondition (version mismatch)
        propagates immediately — redialing can't fix a protocol skew."""
        if self._sock is not None:
            return
        last: Optional[BaseException] = None
        for i in range(1, self.policy.connect_attempts + 1):
            if deadline is not None and time.perf_counter() >= deadline:
                raise UnavailableError(
                    "DEADLINE_EXCEEDED: deadline expired while reconnecting "
                    f"to {self.host}:{self.port} (last: {last})"
                )
            try:
                self._connect_once()
                return
            except (FailedPreconditionError, wire.FrameError):
                raise
            except (DpfError, ConnectionError, OSError) as exc:
                last = exc
                _tm.counter("rpc.client.reconnects")
                if i == self.policy.connect_attempts:
                    break
                pause = self.policy.connect_backoff * (
                    1.0 + self.policy.jitter * (2.0 * self._rng.random() - 1.0)
                )
                if deadline is not None:
                    pause = min(pause, max(0.0, deadline - time.perf_counter()))
                time.sleep(pause)
        raise UnavailableError(
            f"UNAVAILABLE: could not connect to {self.host}:{self.port} "
            f"after {self.policy.connect_attempts} attempts (last: {last})"
        )

    # -- the call machinery ------------------------------------------------
    def call(
        self,
        op: str,
        payload: bytes,
        deadline: Optional[float] = None,
        attempt_timeout: Optional[float] = None,
    ) -> List[np.ndarray]:
        """One op end to end, with retries. `deadline` is the TOTAL
        budget in seconds — it rides the wire as the remaining
        ``deadline_ms`` so the server's admission and dispatch honor it
        too. `attempt_timeout` overrides the policy's per-attempt socket
        bound for this call."""
        with self._lock:
            return self._call_locked(op, payload, deadline, attempt_timeout)

    def _call_locked(
        self,
        op: str,
        payload: bytes,
        deadline: Optional[float],
        attempt_timeout: Optional[float],
    ) -> List[np.ndarray]:
        t_deadline = (
            time.perf_counter() + deadline if deadline is not None else None
        )
        per_attempt = (
            attempt_timeout
            if attempt_timeout is not None
            else self.policy.attempt_timeout
        )
        _tm.counter("rpc.client.requests", op=op)
        last: Optional[BaseException] = None
        with _tm.span("rpc.client.call", op=op):
            for attempt in range(1, self.policy.attempts + 1):
                remaining = None
                if t_deadline is not None:
                    remaining = t_deadline - time.perf_counter()
                    if remaining <= 0:
                        raise UnavailableError(
                            f"DEADLINE_EXCEEDED: {op} call budget exhausted "
                            f"after {attempt - 1} attempts (last: {last})"
                        )
                try:
                    return self._attempt(op, payload, remaining, per_attempt)
                except (FailedPreconditionError,) as exc:
                    raise exc  # protocol skew: deterministic, fail fast
                except (DpfError, ConnectionError, OSError) as exc:
                    retryable, drop = self._classify(exc, op)
                    if drop:
                        self._drop()
                    if not retryable or attempt == self.policy.attempts:
                        raise
                    last = exc
                    _tm.counter("rpc.client.retries", op=op)
                    pause = self.policy.backoff_seconds(attempt, self._rng)
                    if t_deadline is not None:
                        pause = min(
                            pause, max(0.0, t_deadline - time.perf_counter())
                        )
                    _tm.observe("rpc.client.backoff_ms", pause * 1e3, op=op)
                    time.sleep(pause)
        raise AssertionError("unreachable: the retry loop returns or raises")

    def _classify(
        self, exc: BaseException, op: str
    ) -> Tuple[bool, bool]:
        """(retryable, drop_connection) for one attempt failure."""
        if isinstance(exc, socket.timeout):
            # The per-attempt timeout: the server may still answer the
            # stale id later, so the stream is no longer trustworthy.
            _tm.counter("rpc.client.attempt_timeouts", op=op)
            return True, True
        if isinstance(exc, (wire.FrameError, ConnectionError, OSError)):
            return True, True
        status = getattr(exc, "wire_status", None)
        if status is not None:
            # A structured T_ERROR answer: the stream is healthy.
            return status in wire.RETRYABLE_STATUSES, False
        if isinstance(exc, UnavailableError):
            return "DEADLINE_EXCEEDED" not in str(exc), True
        return False, False

    def _attempt(
        self,
        op: str,
        payload: bytes,
        remaining: Optional[float],
        per_attempt: Optional[float],
    ) -> List[np.ndarray]:
        deadline = (
            time.perf_counter() + remaining if remaining is not None else None
        )
        self._ensure_connected(deadline)
        if deadline is not None:
            # Reconnecting spends real budget: recompute so the socket
            # timeout AND the deadline_ms sent on the wire reflect what
            # the caller actually has left, not what it had before the
            # redial loop — otherwise a 10 s call that spent 9 s dialing
            # hands the server a 10 s budget and overruns to ~19 s.
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                raise UnavailableError(
                    "DEADLINE_EXCEEDED: deadline spent reconnecting "
                    "before the attempt could send"
                )
        sock = self._sock
        timeout = per_attempt
        if remaining is not None:
            timeout = (
                min(per_attempt, remaining)
                if per_attempt is not None
                else remaining
            )
        sock.settimeout(timeout)
        self._next_id += 1
        rid = self._next_id
        deadline_ms = (
            max(1, int(remaining * 1e3)) if remaining is not None else 0
        )
        wire.write_frame(
            sock, wire.T_REQUEST, rid,
            wire.encode_request_body(
                op, payload, deadline_ms=deadline_ms, tenant=self.tenant
            ),
        )
        frame = wire.read_frame(sock, max_body=self.max_body)
        if frame is None:
            raise UnavailableError(
                "UNAVAILABLE: server closed the connection before answering"
            )
        if frame.request_id != rid:
            _tm.counter("rpc.client.id_mismatch", op=op)
            raise wire.FrameError(
                f"response carries request id {frame.request_id}, expected "
                f"{rid}: the stream desynchronized — dropping the connection"
            )
        if frame.ftype == wire.T_ERROR:
            code, message = wire.decode_error_body(frame.body)
            raise wire.exception_for_status(code, message)
        if frame.ftype != wire.T_RESPONSE:
            raise wire.FrameError(
                f"request answered with frame type {frame.ftype}"
            )
        return wire.decode_result_arrays(frame.body)

    def _probe(self, ftype: int, ok_type: int, timeout: float) -> dict:
        import json

        with self._lock:
            self._ensure_connected(time.perf_counter() + timeout)
            sock = self._sock
            sock.settimeout(timeout)
            self._next_id += 1
            rid = self._next_id
            try:
                wire.write_frame(sock, ftype, rid)
                frame = wire.read_frame(sock, max_body=self.max_body)
            except (ConnectionError, OSError, wire.FrameError):
                self._drop()
                raise
            if frame is None or frame.ftype != ok_type:
                self._drop()
                raise UnavailableError(
                    "UNAVAILABLE: probe not answered"
                )
            return json.loads(frame.body.decode())

    def health(self, timeout: float = 5.0) -> dict:
        return self._probe(wire.T_HEALTH, wire.T_HEALTH_OK, timeout)

    def stats(self, timeout: float = 5.0) -> dict:
        return self._probe(wire.T_STATS, wire.T_STATS_OK, timeout)

    def wait_ready(self, timeout: float = 60.0, interval: float = 0.2) -> dict:
        """Polls health until the server reports ready — the
        subprocess-orchestration barrier (a restarted server answers
        connections before its front door finishes warming)."""
        t_end = time.perf_counter() + timeout
        last: Optional[BaseException] = None
        while time.perf_counter() < t_end:
            try:
                h = self.health(timeout=min(5.0, timeout))
                if h.get("ready"):
                    return h
                last = UnavailableError(f"server not ready: {h}")
            except (DpfError, ConnectionError, OSError) as exc:
                last = exc
                self._drop()
            time.sleep(interval)
        raise UnavailableError(
            f"UNAVAILABLE: {self.host}:{self.port} not ready within "
            f"{timeout}s (last: {last})"
        )

    # -- typed op surface --------------------------------------------------
    def full_domain(
        self, parameters, keys, hierarchy_level: int = -1,
        deadline: Optional[float] = None, **kw,
    ) -> np.ndarray:
        return self.call(
            "full_domain",
            wire.encode_full_domain(parameters, keys, hierarchy_level),
            deadline=deadline, **kw,
        )[0]

    def evaluate_at(
        self, parameters, keys, points: Sequence[int],
        hierarchy_level: int = -1, deadline: Optional[float] = None, **kw,
    ) -> np.ndarray:
        return self.call(
            "evaluate_at",
            wire.encode_evaluate_at(parameters, keys, points, hierarchy_level),
            deadline=deadline, **kw,
        )[0]

    def dcf(
        self, log_domain_size: int, value_type, keys, xs: Sequence[int],
        deadline: Optional[float] = None, **kw,
    ) -> np.ndarray:
        return self.call(
            "dcf", wire.encode_dcf(log_domain_size, value_type, keys, xs),
            deadline=deadline, **kw,
        )[0]

    def mic(
        self, log_group_size: int, intervals, key, xs: Sequence[int],
        deadline: Optional[float] = None, **kw,
    ) -> np.ndarray:
        return self.call(
            "mic", wire.encode_mic(log_group_size, intervals, key, xs),
            deadline=deadline, **kw,
        )[0]

    def pir(
        self, parameters, keys, db_name: str,
        deadline: Optional[float] = None, **kw,
    ) -> np.ndarray:
        return self.call(
            "pir", wire.encode_pir(parameters, keys, db_name),
            deadline=deadline, **kw,
        )[0]

    def hierarchical(
        self, parameters, keys, plan, group: int = 16,
        deadline: Optional[float] = None, **kw,
    ) -> List[np.ndarray]:
        return self.call(
            "hierarchical",
            wire.encode_hierarchical(parameters, keys, plan, group),
            deadline=deadline, **kw,
        )

    def hh_ingest(
        self, stream: str, parameters, keys, batch_id: str,
        flush: bool = False, deadline: Optional[float] = None, **kw,
    ) -> Tuple[int, bool]:
        """One key batch into a heavy-hitter stream's open window
        (ISSUE 15). The server journals the batch BEFORE acknowledging,
        and `batch_id` is the exactly-once identity: a retry of an
        already-accepted batch (this client's retry budget fires on a
        lost ack, a server restart, or backpressure) is acknowledged
        with its original window generation, never double-counted.
        Returns (window generation, deduped)."""
        arrays = self.call(
            "hh_ingest",
            wire.encode_hh_ingest(
                stream, parameters, keys, batch_id, flush=flush
            ),
            deadline=deadline, **kw,
        )
        out = np.asarray(arrays[0], dtype=np.uint64)
        return int(out[0]), bool(out[1])

    def hh_snapshot(
        self, stream: str, since_generation: int = 0,
        deadline: Optional[float] = None, **kw,
    ) -> dict:
        """The stream's published heavy-hitter view: per published
        window its generation, batch membership, surviving prefixes and
        exact counts (decimal strings), plus the open-window and stats
        fields. `since_generation` is the poller's cursor — only
        windows at or past it return (`published_total` still counts
        them all), so a long-poll loop stays O(new windows) instead of
        re-shipping the stream's whole history every probe."""
        arrays = self.call(
            "hh_snapshot",
            wire.encode_hh_snapshot(stream, since_generation),
            deadline=deadline, **kw,
        )
        return wire.json_from_arrays(arrays)

    def hh_aggregate(
        self, stream: str, generation: int, batch_ids: Sequence[str],
        plan, epoch: int = 0, publish: Optional[dict] = None,
        audit: bool = False, quarantine: Sequence[str] = (),
        deadline: Optional[float] = None, **kw,
    ) -> np.ndarray:
        """One hh_aggregate leg (normally server-to-server — the leader's
        advance worker drives this; exposed here for tooling and the
        chaos soak's zombie-fence probe). `epoch` is the sender's lease
        epoch: in a lease-failover deployment a stale epoch is rejected
        with FAILED_PRECONDITION, which this client never retries."""
        arrays = self.call(
            "hh_aggregate",
            wire.encode_hh_aggregate(
                stream, generation, list(batch_ids), plan,
                epoch=epoch, publish=publish, audit=audit,
                quarantine=quarantine,
            ),
            deadline=deadline, **kw,
        )
        return np.asarray(arrays[0], dtype=np.uint64)

    def keygen(
        self, parameters, alphas: Sequence[int], betas,
        deadline: Optional[float] = None, **kw,
    ) -> tuple:
        """Dealer keygen offload: the server generates K key pairs for
        `alphas`/`betas` (per hierarchy level, scalar or one per alpha)
        through its batched level-major keygen. Returns (keys_0, keys_1)
        as parsed DpfKey lists."""
        arrays = self.call(
            "keygen", wire.encode_keygen(parameters, alphas, betas),
            deadline=deadline, **kw,
        )
        return wire.keygen_keys_from_arrays(arrays)


class TwoServerClient:
    """The FSS deployment shape: one client per non-colluding party,
    every op issued to both concurrently. Outputs are (party0, party1)
    share pairs — reconstruction (XOR for XorWrapper PIR, additive for
    the gates) stays with the caller, who knows the value type.

    Partial failure fails FAST and ATTRIBUTABLY: the moment either
    party's call exhausts its budget, :class:`PartyUnavailableError`
    names it — the caller is never left holding one share and a hang."""

    def __init__(
        self,
        endpoints: Sequence[Tuple[str, int]],
        policy: Optional[RetryPolicy] = None,
        tenant: Optional[str] = None,
    ):
        if len(endpoints) != 2:
            raise InvalidArgumentError(
                "TwoServerClient needs exactly two endpoints"
            )
        self.clients = [
            DpfClient(host, port, policy=policy, tenant=tenant)
            for host, port in endpoints
        ]

    def close(self) -> None:
        for c in self.clients:
            c.close()

    def __enter__(self) -> "TwoServerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def wait_ready(self, timeout: float = 60.0) -> None:
        for c in self.clients:
            c.wait_ready(timeout=timeout)

    def _both(self, thunks) -> list:
        """Runs one thunk per party concurrently; the first party whose
        call fails (after ITS client's whole retry budget) surfaces as
        PartyUnavailableError naming it — IMMEDIATELY, without waiting
        for the surviving party to finish its (possibly long, possibly
        unbounded) call. The survivor's thread is left to drain in the
        background; it holds that client's per-call lock, so a follow-up
        op on this TwoServerClient waits for it rather than corrupting
        the stream."""
        results: list = [None, None]
        errors: list = [None, None]
        done = [False, False]
        cond = threading.Condition()

        def run(i):
            try:
                r = thunks[i]()
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                with cond:
                    errors[i] = exc
                    done[i] = True
                    cond.notify_all()
            else:
                with cond:
                    results[i] = r
                    done[i] = True
                    cond.notify_all()

        threads = [
            threading.Thread(target=run, args=(i,), daemon=True)
            for i in range(2)
        ]
        for t in threads:
            t.start()
        with cond:
            while True:
                for i, exc in enumerate(errors):
                    if exc is not None:
                        c = self.clients[i]
                        raise PartyUnavailableError(
                            f"party {i} ({c.host}:{c.port}) failed: "
                            f"{type(exc).__name__}: {exc}",
                            party=i,
                        ) from exc
                if all(done):
                    return results
                cond.wait(0.05)

    def _pair(self, method: str, key_pair, *args, **kw) -> tuple:
        k0, k1 = key_pair
        return tuple(self._both([
            lambda: getattr(self.clients[0], method)(*_splice(args, k0), **kw),
            lambda: getattr(self.clients[1], method)(*_splice(args, k1), **kw),
        ]))

    # Each op: `key_pair` is ([party0 keys], [party1 keys]) — or a
    # (key0, key1) pair for the single-key MIC — and the return is the
    # (share0, share1) tuple.
    def full_domain(self, parameters, key_pair, hierarchy_level: int = -1,
                    **kw) -> tuple:
        return self._pair(
            "full_domain", key_pair, parameters, None, hierarchy_level, **kw
        )

    def evaluate_at(self, parameters, key_pair, points,
                    hierarchy_level: int = -1, **kw) -> tuple:
        return self._pair(
            "evaluate_at", key_pair, parameters, None, points,
            hierarchy_level, **kw
        )

    def dcf(self, log_domain_size, value_type, key_pair, xs, **kw) -> tuple:
        return self._pair(
            "dcf", key_pair, log_domain_size, value_type, None, xs, **kw
        )

    def mic(self, log_group_size, intervals, key_pair, xs, **kw) -> tuple:
        return self._pair(
            "mic", key_pair, log_group_size, intervals, None, xs, **kw
        )

    def pir(self, parameters, key_pair, db_name: str, **kw) -> tuple:
        return self._pair("pir", key_pair, parameters, None, db_name, **kw)

    def hierarchical(self, parameters, key_pair, plan, group: int = 16,
                     **kw) -> tuple:
        return self._pair(
            "hierarchical", key_pair, parameters, None, plan, group, **kw
        )

    def hh_ingest(
        self, stream: str, parameters, key_pair, batch_id: str,
        flush: bool = False, **kw,
    ) -> tuple:
        """The streaming upload shape (ISSUE 15): one client's key batch
        to BOTH parties concurrently — party 0's share keys to server 0,
        party 1's to server 1, the SAME batch id on both (each party
        journals and dedups independently; window membership converges
        on the ids). Returns the ((gen, deduped), (gen, deduped)) pair.
        A party that stays down past its budget raises
        PartyUnavailableError naming it; re-calling with the same
        batch_id is always safe — the surviving party deduped."""
        k0, k1 = key_pair
        return tuple(self._both([
            lambda: self.clients[0].hh_ingest(
                stream, parameters, k0, batch_id, flush=flush, **kw
            ),
            lambda: self.clients[1].hh_ingest(
                stream, parameters, k1, batch_id, flush=flush, **kw
            ),
        ]))

    def generate_keys_batch(
        self, parameters, alphas: Sequence[int], betas, **kw
    ) -> tuple:
        """Horizontal dealer scale-out (ISSUE 13): the batch SPLITS
        across both servers — each acts as an independent dealer for its
        half (keygen is pure preprocessing; any trusted dealer replica
        can seed any key pair) — and the halves run concurrently behind
        each client's own retry/reconnect/deadline machinery. A dealer
        whose budget exhausts surfaces as PartyUnavailableError naming
        it, like every other op. Returns (keys_0, keys_1) in `alphas`
        order. `betas`: per hierarchy level, scalar or one value per
        alpha."""
        from ..core.keygen import normalize_beta_cols

        alphas = [int(a) for a in alphas]
        k = len(alphas)
        cols = normalize_beta_cols(betas, k)
        if k == 0:
            return [], []
        if k == 1:
            # Too small to split: one dealer serves it whole.
            return self.clients[0].keygen(parameters, alphas, cols, **kw)
        half = (k + 1) // 2
        parts = self._both([
            lambda: self.clients[0].keygen(
                parameters, alphas[:half], [c[:half] for c in cols], **kw
            ),
            lambda: self.clients[1].keygen(
                parameters, alphas[half:], [c[half:] for c in cols], **kw
            ),
        ])
        return (
            parts[0][0] + parts[1][0],
            parts[0][1] + parts[1][1],
        )


def _splice(args: tuple, keys) -> tuple:
    """Replaces the None placeholder in `args` with this party's keys —
    the single seam through which TwoServerClient's op signatures map
    onto DpfClient's."""
    out = list(args)
    out[out.index(None)] = keys
    return tuple(out)
