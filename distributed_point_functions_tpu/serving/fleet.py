"""Fleet tier: replica pools behind a frame-aware front proxy (ISSUE 14).

PR 10 gave each FSS party exactly ONE socket server, so aggregate
throughput was capped by one process's batcher worker and one warm
cache. This module is the party-local fleet tier the ROADMAP's
fleet-serving item asks for: one :class:`FleetProxy` per party owns the
party's listening port and spreads connections across N replica
:class:`~.server.DpfServer` processes, and :class:`ReplicaPool` spawns /
kills / restarts those processes. A deployment is then two proxies (one
per non-colluding party), each fronting its own replica pool — Poplar's
two-server shape, scaled out horizontally behind the SAME wire protocol:
clients speak to a fleet exactly as they speak to a single server.

Routing (per REQUEST, not per connection — the proxy is frame-aware):

* **Affinity first** — each request's :func:`~.wire.routing_digest`
  (the payload fields that feed the replica-side compatibility-queue key
  and warm-cache tiers: parameters / PIR database name / hierarchical
  plan / gate-key blob) is rendezvous-hashed against the replica set, so
  requests that can merge into one batch — and the warm tiers they heat
  (PreparedPirDatabase / PreparedLevelsPlan / PreparedKeyBatch / gate
  keys) — always meet on the same replica. Rendezvous hashing means a
  replica's death re-homes ONLY its own digest range (no global
  reshuffle), and its restart wins the same range back, so warm-tier
  reuse resumes after the re-hash (the ``fleet.affinity_hits`` counter
  makes that visible).
* **Least-loaded spill** — the affinity winner is overridden when its
  load (proxy-tracked in-flight + the health frame's queued count) runs
  ``spill_margin`` past the least-loaded replica's: a hot digest must
  not melt one replica while others idle. With ``affinity=False``
  (``DPF_TPU_FLEET_AFFINITY=0``) every request goes least-loaded.
* **Failover** — an upstream that dies mid-request is marked dead (the
  probe loop revives it when its health frame reports ready again) and
  the client is answered ``UNAVAILABLE``: a *retryable* status, so the
  client's existing retry/reconnect budget (PR 10) carries the call
  across the failover unchanged — the retry lands on a live replica
  because the dead one is already out of the candidate set. The proxy
  never retries on the client's behalf: retry policy belongs to exactly
  one place, and the client already owns it.

Health / stats served by the proxy aggregate the fleet: ``T_HEALTH``
reports ready while ANY replica is ready (plus a per-replica breakdown),
``T_STATS`` merges the replicas' counter bodies (:func:`~.wire
.merge_stats`) and adds a ``fleet`` section (per-replica load, routed
counts, affinity/spill/failover counters).

The chaos seam (``arm`` / ``fired``) is the PR 10 wire-soak fault
vocabulary — ``conn_reset`` / ``garbage_frame`` / ``slow_server``
injected at exactly one response boundary — promoted into the library so
``tools/chaos_soak.py`` drives the real proxy (its ``--wire`` mode is the
single-replica degenerate case) instead of a private copy. Unarmed, the
seam is one ``None`` check per response frame.

Run one party's fleet from the CLI::

    python -m distributed_point_functions_tpu.serving.fleet \\
        --port 9051 --replicas 3 -- --engine host --pir-db demo:12:0

(everything after ``--`` is passed to every replica's server CLI).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal as _signal
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import envflags
from ..utils import telemetry as _tm
from ..utils.errors import (
    DpfError,
    InvalidArgumentError,
    UnavailableError,
)
from . import wire

#: The chaos-seam fault vocabulary (the PR 10 wire-soak kinds).
CHAOS_KINDS = ("conn_reset", "garbage_frame", "slow_server")


def _rendezvous_score(digest: str, replica_key: str) -> int:
    """Highest-random-weight (rendezvous) score of `digest` on one
    replica. Stable across processes and restarts (the replica key is
    host:port), so a restarted replica wins its old digest range back."""
    h = hashlib.sha256(f"{digest}|{replica_key}".encode()).digest()
    return int.from_bytes(h[:8], "little")


class _Replica:
    """One upstream server's routing state. All mutable fields are
    owned by the proxy's lock."""

    __slots__ = (
        "host", "port", "alive", "inflight", "pending", "routed",
        "failures", "epoch", "last_probe", "last_relay", "last_error",
        "health", "stats", "retiring",
    )

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.alive = False      # flipped by the probe loop / failures
        #: ISSUE 20 scale-down: a retiring replica is excluded from
        #: _pick (no NEW requests) but stays probed and counted — the
        #: graceful-drain half of the autoscaler's remove path.
        self.retiring = False
        self.inflight = 0       # proxy-tracked requests outstanding
        self.pending = 0        # the replica's queued count (health frame)
        self.routed = 0         # requests ever routed here
        self.failures = 0       # upstream failures observed here
        #: death epoch: bumped by every request-path _mark_dead so a
        #: probe that was in flight ACROSS the death cannot resurrect
        #: the replica with its stale ready=True.
        self.epoch = 0
        self.last_probe = 0.0   # perf_counter of the last probe attempt
        #: perf_counter of the last relayed-request completion. A stats
        #: poll compares it against last_probe: a cached stats body
        #: predating a completed request must be re-fetched no matter
        #: how young it is (on warm loopback a request + stats poll fit
        #: inside STATS_FRESHNESS, and the pre-request body would hide
        #: counters the poller just caused).
        self.last_relay = 0.0
        self.last_error: Optional[str] = None
        self.health: dict = {}
        self.stats: dict = {}

    @property
    def key(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def load(self) -> int:
        return self.inflight + self.pending


class FleetProxy:
    """One party's frame-aware front door over N replica servers.

    ``endpoints`` is the replica list as (host, port) pairs — in-process
    :class:`~.server.DpfServer` instances for tests, a
    :class:`ReplicaPool`'s subprocesses in deployment. A dead replica is
    routed around (and revived by the probe loop), never dropped
    implicitly, so its rendezvous range is stable across a crash. The
    set IS elastic explicitly (ISSUE 20): :meth:`add_replica` /
    :meth:`set_retiring` / :meth:`remove_replica` are the autoscaler's
    seams — a retiring replica takes no new requests but finishes what
    it holds (graceful drain), and only an explicit remove re-hashes its
    digest range away.

    ``affinity=None`` reads ``DPF_TPU_FLEET_AFFINITY`` (default on).
    ``spill_margin`` is how far past the least-loaded replica the
    affinity winner's load may run before the request spills to the
    least-loaded one instead. Load = proxy-tracked in-flight + the
    replica's queued depth from its health frame; a request this proxy
    routed that is still QUEUED replica-side is counted in both terms,
    so the margin is effectively measured in a mix of requests and
    queue slots — a heuristic knob, not an exact request count.
    """

    def __init__(
        self,
        endpoints: Sequence[Tuple[str, int]],
        host: str = "127.0.0.1",
        port: int = 0,
        affinity: Optional[bool] = None,
        spill_margin: int = 8,
        max_body: int = wire.DEFAULT_MAX_BODY,
        frame_timeout: float = 60.0,
        upstream_timeout: float = 600.0,
        probe_interval: float = 0.25,
    ):
        if not endpoints:
            raise InvalidArgumentError("FleetProxy needs >= 1 replica")
        self.host = host
        self._port = port
        self.affinity = (
            envflags.env_bool("DPF_TPU_FLEET_AFFINITY", True)
            if affinity is None else affinity
        )
        self.spill_margin = spill_margin
        self.max_body = max_body
        self.frame_timeout = frame_timeout
        #: bound on one upstream response wait when the request carries
        #: no deadline (a deadline-bearing request waits deadline+grace).
        self.upstream_timeout = upstream_timeout
        self.probe_interval = probe_interval
        self._lock = threading.Lock()
        self._replicas = [_Replica(h, p) for h, p in endpoints]
        self.counters: Dict[str, int] = {
            "requests": 0, "affinity_hits": 0, "spills": 0,
            "least_loaded": 0, "failovers": 0, "replica_down": 0,
            "upstream_timeouts": 0, "no_replica": 0,
            "replicas_added": 0, "replicas_removed": 0, "retired": 0,
        }
        #: chaos seam (tools/chaos_soak.py): one armed fault fires at the
        #: next request-response boundary. Production traffic never arms.
        self._armed: Optional[str] = None
        self.fired: Dict[str, int] = {k: 0 for k in CHAOS_KINDS}
        #: injected stall length for an armed slow_server fault.
        self.slow_seconds = 3.0
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._stopped = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    @property
    def port(self) -> int:
        return self._port

    def start(self) -> "FleetProxy":
        if self._listener is not None:
            return self
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self.host, self._port))
        listener.listen(128)
        listener.settimeout(0.25)  # poll the stop flag
        self._listener = listener
        self._port = listener.getsockname()[1]
        self._stopped.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="dpf-fleet-probe", daemon=True
        )
        self._probe_thread.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="dpf-fleet-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in (self._accept_thread, self._probe_thread):
            if t is not None:
                t.join(timeout=5)
        self._accept_thread = self._probe_thread = None

    def __enter__(self) -> "FleetProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- chaos seam (tools/chaos_soak.py drives it) ------------------------
    def arm(self, kind: str) -> None:
        """Arms ONE fault at the next request-response boundary (never a
        handshake or a health/stats answer — those are proxy-local)."""
        if kind not in CHAOS_KINDS:
            raise InvalidArgumentError(
                f"unknown chaos kind {kind!r} (one of {CHAOS_KINDS})"
            )
        with self._lock:
            self._armed = kind

    def _take_armed(self) -> Optional[str]:
        with self._lock:
            kind, self._armed = self._armed, None
            if kind is not None:
                self.fired[kind] += 1
            return kind

    # -- routing -----------------------------------------------------------
    def _pick(self, digest: str) -> Optional[_Replica]:
        """One replica for `digest`, or None when the whole fleet is
        down. Affinity = rendezvous winner among LIVE replicas, spilled
        to the least-loaded when the winner runs hot; the winner's
        in-flight count is bumped under the same lock so concurrent
        picks see each other's load."""
        with self._lock:
            alive = [
                r for r in self._replicas if r.alive and not r.retiring
            ]
            if not alive:
                self.counters["no_replica"] += 1
                return None
            least = min(alive, key=lambda r: (r.load, r.routed))
            if self.affinity:
                winner = max(
                    alive, key=lambda r: _rendezvous_score(digest, r.key)
                )
                if winner.load > least.load + self.spill_margin:
                    self.counters["spills"] += 1
                    choice = least
                else:
                    self.counters["affinity_hits"] += 1
                    choice = winner
            else:
                self.counters["least_loaded"] += 1
                choice = least
            self.counters["requests"] += 1
            choice.routed += 1
            choice.inflight += 1
            return choice

    def _release(self, replica: _Replica) -> None:
        with self._lock:
            replica.inflight -= 1
            replica.last_relay = time.perf_counter()

    def _mark_dead(self, replica: _Replica, exc: BaseException) -> None:
        with self._lock:
            was_alive = replica.alive
            replica.alive = False
            replica.epoch += 1  # invalidate any probe in flight
            replica.pending = 0  # its queue died with it
            replica.failures += 1
            replica.last_error = f"{type(exc).__name__}: {exc}"
            if was_alive:
                self.counters["failovers"] += 1
        if was_alive:
            _tm.counter("fleet.failovers")

    # -- elastic membership (ISSUE 20: the autoscaler's seams) -------------
    def add_replica(self, host: str, port: int) -> None:
        """Adds (or un-retires) an upstream endpoint. A new endpoint
        starts dead and joins the candidate set when a probe sees it
        ready (one is fired immediately, so a ready replica serves
        within one round trip, not one probe interval); re-adding a
        known endpoint clears its ``retiring`` flag — the
        scale-up-after-scale-down path, where a remembered-port respawn
        wins its old rendezvous range back."""
        with self._lock:
            replica = None
            for r in self._replicas:
                if r.host == host and r.port == port:
                    r.retiring = False
                    replica = r
                    break
            if replica is None:
                replica = _Replica(host, port)
                self._replicas.append(replica)
                self.counters["replicas_added"] += 1
        _tm.counter("fleet.scale.added")
        self._probe(replica)

    def set_retiring(
        self, host: str, port: int, retiring: bool = True
    ) -> bool:
        """Marks an endpoint retiring (True: excluded from _pick, still
        probed and still finishing its in-flight work — the graceful
        drain) or back in service (False). Returns whether the endpoint
        is known."""
        with self._lock:
            for r in self._replicas:
                if r.host == host and r.port == port:
                    if retiring and not r.retiring:
                        self.counters["retired"] += 1
                    r.retiring = retiring
                    return True
        return False

    def remove_replica(self, host: str, port: int) -> bool:
        """Drops an endpoint from the set — the ONLY operation that
        re-hashes its digest range away. Refuses (returns False) while
        the proxy still tracks in-flight requests on it: retire first,
        wait for :meth:`replica_state`'s load to reach zero, then
        remove."""
        with self._lock:
            for i, r in enumerate(self._replicas):
                if r.host == host and r.port == port:
                    if r.inflight > 0:
                        return False
                    del self._replicas[i]
                    self.counters["replicas_removed"] += 1
                    _tm.counter("fleet.scale.removed")
                    return True
        return False

    def replica_state(self, host: str, port: int) -> Optional[dict]:
        """One endpoint's routing-state snapshot (the autoscaler's
        drained-yet? poll), or None for an unknown endpoint."""
        with self._lock:
            for r in self._replicas:
                if r.host == host and r.port == port:
                    return {
                        "endpoint": r.key, "alive": r.alive,
                        "retiring": r.retiring, "inflight": r.inflight,
                        "pending": r.pending, "load": r.load,
                        "routed": r.routed,
                    }
        return None

    def health(self) -> dict:
        """The T_HEALTH body, in-process — what a socket client would
        see, without the round trip (the co-located autoscaler's poll)."""
        return self._health()

    def stats(self) -> dict:
        """The T_STATS body, in-process (freshness-gated re-probe
        included) — the autoscaler's backlog/rates signal source."""
        return self._stats()

    # -- probing -----------------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stopped.is_set():
            # Snapshot under the lock: the autoscaler adds/removes
            # replicas concurrently, and a probe of a just-removed
            # replica is harmless (its _Replica is unreachable after).
            with self._lock:
                replicas = list(self._replicas)
            for replica in replicas:
                if self._stopped.is_set():
                    return
                self._probe(replica)
            self._stopped.wait(self.probe_interval)

    def _probe(self, replica: _Replica) -> None:
        """One health+stats round trip. Readiness gates aliveness: a
        draining replica (or one whose batcher worker died) reports
        not-ready and is routed around — the DRAIN half of
        drain-and-re-hash; death detection mid-request is synchronous in
        _relay_request and does not wait for this loop. A probe result
        that straddled a request-path death (epoch bumped while the
        round trip was in flight) is DISCARDED — its ready=True predates
        the death and must not resurrect the corpse."""
        with self._lock:
            epoch = replica.epoch
            replica.last_probe = time.perf_counter()
        try:
            sock = socket.create_connection(
                (replica.host, replica.port), timeout=1.0
            )
            try:
                sock.settimeout(2.0)
                wire.write_frame(sock, wire.T_HELLO, 1)
                hello = wire.read_frame(sock, check_version=False)
                if hello is None or hello.ftype != wire.T_HELLO_OK:
                    raise UnavailableError("UNAVAILABLE: bad probe handshake")
                wire.write_frame(sock, wire.T_HEALTH, 2)
                hframe = wire.read_frame(sock)
                wire.write_frame(sock, wire.T_STATS, 3)
                sframe = wire.read_frame(sock)
            finally:
                sock.close()
            if (
                hframe is None or hframe.ftype != wire.T_HEALTH_OK
                or sframe is None or sframe.ftype != wire.T_STATS_OK
            ):
                raise UnavailableError("UNAVAILABLE: probe not answered")
            health = json.loads(hframe.body.decode())
            stats = json.loads(sframe.body.decode())
        except (DpfError, ConnectionError, OSError, ValueError) as exc:
            with self._lock:
                if replica.alive:
                    # Probe-detected death (vs the synchronous
                    # request-path "failovers" counter). Every alive ->
                    # dead TRANSITION bumps the epoch, whichever path
                    # saw it — a slower concurrent probe that read
                    # ready=True before this death must be discarded
                    # (transition-only bumps keep legitimate revives of
                    # an already-dead replica from being discarded).
                    self.counters["replica_down"] += 1
                    replica.epoch += 1
                replica.alive = False
                replica.pending = 0  # its queue died with it
                replica.last_error = f"{type(exc).__name__}: {exc}"
            return
        with self._lock:
            if replica.epoch != epoch:
                return  # a death intervened: this probe's data is stale
            ready = bool(health.get("ready"))
            if replica.alive and not ready:
                self.counters["replica_down"] += 1
                replica.epoch += 1
            replica.alive = ready
            # The replica's QUEUED depth only: its in-flight requests
            # are (for proxy-routed traffic) the same requests this
            # proxy already counts in _Replica.inflight — adding the
            # health frame's inflight on top would double-count each
            # outstanding request and silently compress spill_margin.
            replica.pending = int(health.get("pending", 0))
            replica.health = health
            replica.stats = stats

    # -- socket loops ------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            listener = self._listener
            if listener is None:
                return
            try:
                conn, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                if self._stopped.is_set() or self._listener is None:
                    return
                _tm.counter("fleet.accept_errors")
                time.sleep(0.05)
                continue
            conn.settimeout(self.frame_timeout)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="dpf-fleet-conn", daemon=True,
            ).start()

    def _read_frame_poll(self, sock: socket.socket) -> Optional[wire.Frame]:
        """One client frame, polling the stop flag while IDLE — the
        PR 10 discipline: the 0.5 s poll applies only to the MSG_PEEK
        wait for a frame's first byte; an in-progress frame gets the
        full frame budget, so a stall mid-body is never torn."""
        while True:
            if self._stopped.is_set():
                return None
            sock.settimeout(0.5)
            try:
                first = sock.recv(1, socket.MSG_PEEK)
            except socket.timeout:
                continue
            if not first:
                return None
            sock.settimeout(self.frame_timeout)
            return wire.read_frame(
                sock, max_body=self.max_body, check_version=False
            )

    def _serve_conn(self, sock: socket.socket) -> None:
        upstreams: Dict[str, socket.socket] = {}
        try:
            self._conn_loop(sock, upstreams)
        except (wire.FrameError, ConnectionError, OSError):
            pass  # framing violation or torn connection: drop it
        finally:
            with self._lock:
                self._conns.discard(sock)
            for up in upstreams.values():
                try:
                    up.close()
                except OSError:
                    pass
            try:
                sock.close()
            except OSError:
                pass

    def _conn_loop(
        self, sock: socket.socket, upstreams: Dict[str, socket.socket]
    ) -> None:
        hello = self._read_frame_poll(sock)
        if hello is None:
            return
        if hello.version != wire.PROTO_VERSION or hello.ftype != wire.T_HELLO:
            wire.write_frame(
                sock, wire.T_ERROR, hello.request_id,
                wire.encode_error_body(
                    wire.FAILED_PRECONDITION,
                    f"handshake rejected: got frame type {hello.ftype} "
                    f"version {hello.version}, this fleet proxy speaks "
                    f"T_HELLO version {wire.PROTO_VERSION}",
                ),
            )
            return
        wire.write_frame(
            sock, wire.T_HELLO_OK, hello.request_id,
            json.dumps({
                "version": wire.PROTO_VERSION,
                "fleet": len(self._replicas),
            }).encode(),
        )
        while not self._stopped.is_set():
            frame = self._read_frame_poll(sock)
            if frame is None:
                return
            if frame.version != wire.PROTO_VERSION:
                raise wire.FrameError(
                    f"frame version {frame.version} after a version-"
                    f"{wire.PROTO_VERSION} handshake"
                )
            if frame.ftype == wire.T_HEALTH:
                wire.write_frame(
                    sock, wire.T_HEALTH_OK, frame.request_id,
                    json.dumps(self._health()).encode(),
                )
            elif frame.ftype == wire.T_STATS:
                wire.write_frame(
                    sock, wire.T_STATS_OK, frame.request_id,
                    json.dumps(self._stats()).encode(),
                )
            elif frame.ftype == wire.T_REQUEST:
                self._relay_request(sock, frame, upstreams)
            else:
                raise wire.FrameError(
                    f"unexpected frame type {frame.ftype} from a client"
                )

    # -- request relay -----------------------------------------------------
    def _dial(self, replica: _Replica) -> socket.socket:
        """One upstream connection, handshaken. The connect timeout must
        NOT linger on the socket (the PR 10 chaos-proxy lesson:
        ``create_connection(timeout=)`` leaves its timeout armed, and an
        upstream leg with a 5 s timeout kills any response slower than
        that) — per-request waits arm their own budget."""
        up = socket.create_connection(
            (replica.host, replica.port), timeout=5.0
        )
        try:
            up.settimeout(self.frame_timeout)
            wire.write_frame(up, wire.T_HELLO, 1)
            reply = wire.read_frame(up, check_version=False)
            if reply is None or reply.ftype != wire.T_HELLO_OK:
                raise UnavailableError(
                    "UNAVAILABLE: replica rejected the proxy handshake"
                )
            up.settimeout(None)
            return up
        except BaseException:
            up.close()
            raise

    def _relay_request(
        self,
        sock: socket.socket,
        frame: wire.Frame,
        upstreams: Dict[str, socket.socket],
    ) -> None:
        try:
            # The tenant token (field 4) deliberately does NOT feed the
            # routing digest: QoS is a replica-side scheduling concern,
            # and splitting one batchable family across replicas by
            # tenant would forfeit the merge affinity exists for.
            op, deadline_ms, payload, _ = wire.decode_request_body(frame.body)
            digest = wire.routing_digest(op, payload)
        except DpfError as exc:
            # Undecodable request body: the replica could not serve it
            # either — answer INVALID_ARGUMENT, keep the connection.
            wire.write_frame(
                sock, wire.T_ERROR, frame.request_id,
                wire.encode_error_body(
                    wire.INVALID_ARGUMENT,
                    f"fleet proxy could not route the request: {exc}",
                ),
            )
            return
        replica = self._pick(digest)
        if replica is None:
            wire.write_frame(
                sock, wire.T_ERROR, frame.request_id,
                wire.encode_error_body(
                    wire.UNAVAILABLE,
                    "UNAVAILABLE: no fleet replica is ready — retry",
                ),
            )
            return
        try:
            try:
                reply = self._forward_once(replica, frame, deadline_ms,
                                           upstreams)
            except socket.timeout as exc:
                # A timed-out upstream stream is desynced (the answer
                # may still arrive) and must be dropped — but a slow
                # replica is not a dead one: don't take it out of the
                # candidate set on latency alone.
                self._drop_upstream(upstreams, replica)
                with self._lock:
                    self.counters["upstream_timeouts"] += 1
                raise UnavailableError(
                    f"UNAVAILABLE: replica {replica.key} timed out "
                    "mid-request — retry"
                ) from exc
            except (DpfError, ConnectionError, OSError) as exc:
                self._drop_upstream(upstreams, replica)
                self._mark_dead(replica, exc)
                raise UnavailableError(
                    f"UNAVAILABLE: replica {replica.key} failed "
                    f"mid-request ({type(exc).__name__}) — retry"
                ) from exc
        except UnavailableError as exc:
            # Failover contract: answer a RETRYABLE status and let the
            # client's own retry/reconnect budget carry the call — the
            # next attempt routes around the dead replica.
            _tm.counter("fleet.unavailable_answers", op=op)
            wire.write_frame(
                sock, wire.T_ERROR, frame.request_id,
                wire.encode_error_body(wire.UNAVAILABLE, str(exc)),
            )
            return
        finally:
            self._release(replica)
        _tm.counter("fleet.requests", op=op)
        kind = (
            self._take_armed()
            if reply.ftype in (wire.T_RESPONSE, wire.T_ERROR)
            else None
        )
        if kind == "conn_reset":
            # SO_LINGER(on, 0): close sends RST, not FIN — the client
            # sees a hard reset mid-conversation.
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0),
            )
            raise ConnectionResetError("chaos: injected conn_reset")
        if kind == "garbage_frame":
            sock.settimeout(self.frame_timeout)
            sock.sendall(b"\xde\xad\xbe\xef" * 8)  # not a frame
            raise ConnectionAbortedError("chaos: injected garbage_frame")
        if kind == "slow_server":
            time.sleep(self.slow_seconds)
        sock.settimeout(self.frame_timeout)
        sock.sendall(wire.encode_frame(
            reply.ftype, reply.request_id, reply.body, version=reply.version,
        ))

    def _forward_once(
        self,
        replica: _Replica,
        frame: wire.Frame,
        deadline_ms: int,
        upstreams: Dict[str, socket.socket],
    ) -> wire.Frame:
        """One request over this connection's upstream to `replica` —
        with ONE fresh redial when a CACHED upstream fails before any
        reply bytes arrived: an idle-pooled connection goes stale when
        its replica restarts between requests (the fleet's whole point),
        and declaring the replica dead on a stale socket would bounce a
        healthy restart back to the client as a failover. A failure on a
        FRESH connection (or a second failure) propagates — that is a
        real death, and the caller marks it.

        A reply torn MID-FRAME (FrameError: bytes arrived, then died) is
        never redialed — the replica executed the request, and re-sending
        would run it twice; the client's retry owns that decision. (A
        raw socket error on the reply read can, rarely, hide the same
        partial-reply case and re-execute — acceptable: every wire op is
        pure compute, and the orphaned first execution's result is
        discarded.)"""
        up = upstreams.get(replica.key)
        cached = up is not None
        for attempt in range(2):
            if up is None:
                up = self._dial(replica)
                upstreams[replica.key] = up
            # The request's own deadline bounds the upstream wait (plus
            # the same grace the server's future-wait uses); an
            # unbounded request gets the proxy's backstop.
            up.settimeout(
                deadline_ms / 1e3 + 5.0 if deadline_ms
                else self.upstream_timeout
            )
            try:
                # Forwarded verbatim: the client's request id rides
                # through, so the reply relays without rewriting.
                wire.write_frame(
                    up, wire.T_REQUEST, frame.request_id, frame.body
                )
                reply = wire.read_frame(up, max_body=self.max_body)
            except socket.timeout:
                raise  # the caller's slow-not-dead path
            except wire.FrameError:
                # Reply bytes arrived and then tore: NOT a stale socket.
                self._drop_upstream(upstreams, replica)
                raise
            except (DpfError, ConnectionError, OSError):
                self._drop_upstream(upstreams, replica)
                up = None
                if cached and attempt == 0:
                    continue  # stale pooled socket: one fresh redial
                raise
            if reply is None:
                self._drop_upstream(upstreams, replica)
                up = None
                if cached and attempt == 0:
                    continue  # orderly EOF on a stale pooled socket
                raise UnavailableError(
                    "UNAVAILABLE: replica closed mid-request"
                )
            if reply.request_id != frame.request_id:
                raise wire.FrameError(
                    f"replica answered id {reply.request_id} for "
                    f"request {frame.request_id}: stream desync"
                )
            return reply
        raise UnavailableError("UNAVAILABLE: upstream redial exhausted")

    def _drop_upstream(
        self, upstreams: Dict[str, socket.socket], replica: _Replica
    ) -> None:
        up = upstreams.pop(replica.key, None)
        if up is not None:
            try:
                up.close()
            except OSError:
                pass

    # -- aggregate endpoints ----------------------------------------------
    def _fleet_section(self) -> dict:
        with self._lock:
            return {
                "size": len(self._replicas),
                "affinity": self.affinity,
                "counters": dict(self.counters),
                "replicas": [
                    {
                        "endpoint": r.key, "alive": r.alive,
                        "retiring": r.retiring,
                        "inflight": r.inflight, "pending": r.pending,
                        "routed": r.routed, "failures": r.failures,
                        "last_error": r.last_error,
                    }
                    for r in self._replicas
                ],
            }

    def _health(self) -> dict:
        with self._lock:
            alive = [r for r in self._replicas if r.alive]
            # LIVE replicas only: a dead replica's queue died with it
            # (pending is also zeroed on death), and phantom load here
            # would mislead any operator/autoscaler polling the proxy.
            pending = sum(r.pending for r in alive)
            inflight = sum(r.inflight for r in self._replicas)
        return {
            "status": "serving" if alive else "unavailable",
            "ready": bool(alive) and not self._stopped.is_set(),
            "pending": pending,
            "inflight": inflight,
            "fleet": self._fleet_section(),
            "pid": os.getpid(),
        }

    #: a T_STATS answer re-probes only replicas whose cached body is
    #: older than this (seconds): stats consumers (soaks, operators)
    #: assert on counters they JUST caused, so the cache must be fresher
    #: than the probe loop guarantees — but a stats poll must not sweep
    #: the whole fleet with 3 round trips per replica on every call
    #: (against a dead non-loopback replica each sweep costs the 1 s
    #: connect timeout, serially). Age alone is NOT sufficient: on warm
    #: loopback a relayed request plus the stats poll complete inside
    #: this window, so a body cached moments before the request would be
    #: served back missing the counters the request caused — a cached
    #: body is therefore also stale whenever a relay completed after the
    #: probe that fetched it started (last_relay vs last_probe).
    STATS_FRESHNESS = 0.05

    def _stats(self) -> dict:
        now = time.perf_counter()
        with self._lock:
            replicas = list(self._replicas)
        for replica in replicas:
            with self._lock:
                stale = (
                    now - replica.last_probe > self.STATS_FRESHNESS
                    or replica.last_relay >= replica.last_probe
                )
            if stale:
                self._probe(replica)
        with self._lock:
            # Counters are cumulative observability: a dead replica's
            # LAST-KNOWN body stays in the merge (dropping it would make
            # fleet totals go backwards on every crash; a restart resets
            # the replica's own counters anyway). Its INSTANTANEOUS
            # fields are a different matter — a dead process has no
            # queue, no in-flight work and no live gauges, and reporting
            # its last-seen ones would show an operator/autoscaler
            # backlog that no longer exists — so those are stripped.
            bodies = []
            for r in self._replicas:
                if not r.stats:
                    continue
                body = dict(r.stats)
                if not r.alive:
                    for transient in ("queues", "inflight", "gauges"):
                        body.pop(transient, None)
                bodies.append(body)
        merged = wire.merge_stats(bodies)
        merged["fleet"] = self._fleet_section()
        return merged


# ---------------------------------------------------------------------------
# Replica pool: the subprocess half
# ---------------------------------------------------------------------------


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


class ReplicaPool:
    """N replica ``serving.server`` subprocesses for ONE party.

    Every replica runs the same server CLI arguments (``server_args``)
    plus its own ``--ready-file`` and — when ``journal_base`` is set —
    its own ``--journal-dir``. Ports are ephemeral on first spawn and
    REMEMBERED: :meth:`restart` respawns on the same port, which keeps
    the replica's rendezvous range (and any same-port clients) stable
    across a crash — the fleet analog of the PR 10 same-port server
    restart.

    The pool is elastic (ISSUE 20): :meth:`scale_up` revives a stopped
    slot on its remembered port — or grows a brand-new one — and
    :meth:`scale_down` is the graceful SIGTERM drain. One scaling
    driver at a time (the autoscaler's control loop is single-
    threaded); the internal lock protects the slot lists against the
    concurrent spawn threads of :meth:`start`, not against competing
    scalers.

    ``replicas=None`` reads ``DPF_TPU_FLEET_REPLICAS`` (default 3).
    """

    def __init__(
        self,
        replicas: Optional[int] = None,
        server_args: Sequence[str] = (),
        base_dir: Optional[str] = None,
        platform: str = "cpu",
        journal_base: Optional[str] = None,
        stream_journal_root: Optional[str] = None,
    ):
        if replicas is None:
            replicas = envflags.env_int("DPF_TPU_FLEET_REPLICAS", 3)
        if replicas < 1:
            raise InvalidArgumentError("a replica pool needs >= 1 replica")
        self.n = replicas
        self.server_args = list(server_args)
        self.platform = platform
        self.journal_base = journal_base
        #: ONE directory shared by every replica (ISSUE 16, deliberately
        #: NOT per-replica suffixed like journal_base): fleet-sheltered
        #: streams re-home to a survivor by re-acquiring the per-stream
        #: ownership lease inside this volume and resuming its journals.
        self.stream_journal_root = stream_journal_root
        if base_dir is None:
            import tempfile

            base_dir = tempfile.mkdtemp(prefix="dpf-fleet-")
        self.base_dir = base_dir
        os.makedirs(self.base_dir, exist_ok=True)
        self._lock = threading.Lock()
        self.procs: List[Optional[subprocess.Popen]] = [None] * replicas
        self.ports: List[int] = [0] * replicas
        self._logs: List[str] = [
            os.path.join(self.base_dir, f"replica{i}.log")
            for i in range(replicas)
        ]

    @property
    def endpoints(self) -> List[Tuple[str, int]]:
        with self._lock:
            return [("127.0.0.1", p) for p in self.ports]

    def _ready_file(self, i: int) -> str:
        return os.path.join(self.base_dir, f"ready{i}")

    def spawn(self, i: int, timeout: float = 180.0) -> int:
        """(Re)spawns replica `i` — on its remembered port after a first
        start — and waits for its ready-file handshake. Returns the
        bound port."""
        ready = self._ready_file(i)
        if os.path.exists(ready):
            os.unlink(ready)
        cmd = [
            sys.executable, "-m",
            "distributed_point_functions_tpu.serving.server",
            "--port", str(self.ports[i]),
            "--platform", self.platform,
            "--ready-file", ready,
        ] + self.server_args
        if self.journal_base is not None:
            cmd += ["--journal-dir",
                    os.path.join(self.journal_base, f"replica{i}")]
        if self.stream_journal_root is not None:
            cmd += ["--stream-journal-root", self.stream_journal_root]
        env = dict(os.environ, JAX_PLATFORMS=self.platform)
        with open(self._logs[i], "ab") as log:
            proc = subprocess.Popen(
                cmd, cwd=_repo_root(), env=env, stdout=log, stderr=log
            )
        with self._lock:
            self.procs[i] = proc
        t_end = time.perf_counter() + timeout
        while time.perf_counter() < t_end:
            try:
                with open(ready) as f:
                    port = int(f.read().strip())
            except (OSError, ValueError):
                if proc.poll() is not None:
                    raise UnavailableError(
                        f"UNAVAILABLE: replica {i} exited with "
                        f"{proc.returncode} before ready "
                        f"(log: {self._logs[i]})"
                    )
                time.sleep(0.1)
                continue
            with self._lock:
                self.ports[i] = port
            return port
        # Timing out must not ORPHAN the slow child: it would finish
        # starting later and squat on the remembered port, making every
        # subsequent spawn/restart of this slot fail to bind.
        self.kill(i, _signal.SIGKILL)
        raise UnavailableError(
            f"UNAVAILABLE: replica {i} not ready within {timeout}s "
            f"(killed; log: {self._logs[i]})"
        )

    def start(self, timeout: float = 240.0) -> List[Tuple[str, int]]:
        """Spawns every replica (concurrently — process startup is
        seconds of jax import each) and returns the endpoints."""
        t_end = time.perf_counter() + timeout
        errs: List[BaseException] = []
        threads = []
        for i in range(self.n):
            def _one(i=i):
                try:
                    self.spawn(i, timeout=max(1.0, t_end - time.perf_counter()))
                except BaseException as exc:  # noqa: BLE001 — re-raised below
                    errs.append(exc)
            th = threading.Thread(target=_one, daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=timeout)
        if errs:
            self.stop()
            raise errs[0]
        return self.endpoints

    def kill(
        self, i: int, sig: int = _signal.SIGKILL, wait: float = 20.0
    ) -> None:
        """Hard-kills replica `i` (the chaos arm; SIGTERM drains — with
        the drain wait bounded and escalated, so a wedged drain can
        never block the caller forever)."""
        proc = self.procs[i]
        if proc is not None and proc.poll() is None:
            os.kill(proc.pid, sig)
            try:
                proc.wait(timeout=wait)
            except Exception:  # noqa: BLE001 — escalate a stuck drain
                proc.kill()
                proc.wait()

    def restart(self, i: int, timeout: float = 180.0) -> int:
        """Respawns replica `i` on its original port — its rendezvous
        digest range re-homes back to it once the proxy's probe sees it
        ready."""
        self.kill(i, _signal.SIGKILL)
        return self.spawn(i, timeout=timeout)

    # -- elastic scaling (ISSUE 20) ----------------------------------------
    def running_indices(self) -> List[int]:
        """Slots whose subprocess is currently alive."""
        with self._lock:
            procs = list(self.procs)
        return [
            i for i, p in enumerate(procs)
            if p is not None and p.poll() is None
        ]

    def scale_up(self, timeout: float = 180.0) -> Tuple[int, int, bool]:
        """Brings one more replica up. Prefers respawning a stopped
        slot — its remembered port wins its old rendezvous range back —
        and grows a brand-new ephemeral-port slot only when every slot
        is running. Returns ``(index, port, grew)``; the caller tells
        the proxy either way (:meth:`FleetProxy.add_replica` is
        idempotent: it un-retires a known endpoint, appends a new one).
        """
        with self._lock:
            idx = None
            for i, proc in enumerate(self.procs):
                if proc is None or proc.poll() is not None:
                    idx = i
                    break
            grew = idx is None
            if grew:
                idx = self.n
                self.n += 1
                self.procs.append(None)
                self.ports.append(0)
                self._logs.append(
                    os.path.join(self.base_dir, f"replica{idx}.log")
                )
        port = self.spawn(idx, timeout=timeout)
        return idx, port, grew

    def scale_down(self, i: int, timeout: float = 30.0) -> None:
        """Gracefully stops replica `i`: SIGTERM — the server's drain
        path, which finishes queued work before exiting — with the wait
        bounded and escalated to SIGKILL. The slot and its port are
        remembered, so a later :meth:`scale_up` revives the same
        endpoint."""
        self.kill(i, _signal.SIGTERM, wait=timeout)

    def stop(self) -> None:
        for proc in self.procs:
            if proc is not None and proc.poll() is None:
                proc.terminate()
        for proc in self.procs:
            if proc is not None and proc.poll() is None:
                try:
                    proc.wait(timeout=20)
                except Exception:  # noqa: BLE001 — escalate to SIGKILL
                    proc.kill()

    def __enter__(self) -> "ReplicaPool":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# CLI: one party's pool + proxy
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        usage="python -m distributed_point_functions_tpu.serving.fleet "
              "[options] [-- server args...]",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int,
                    default=envflags.env_int("DPF_TPU_FLEET_PORT", 0),
                    help="the party's public port (0 = ephemeral; env "
                    "default DPF_TPU_FLEET_PORT)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="replica count (default DPF_TPU_FLEET_REPLICAS=3)")
    ap.add_argument("--no-affinity", action="store_true",
                    help="pure least-loaded routing (also "
                    "DPF_TPU_FLEET_AFFINITY=0)")
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--base-dir", default=None,
                    help="ready-file/log directory (default: a tmp dir)")
    ap.add_argument("--journal-base", default=None,
                    help="per-replica journal dirs under this path")
    ap.add_argument("--stream-journal-root", default=None,
                    help="SHARED stream journal volume for fleet-"
                    "sheltered heavy-hitter streams (ISSUE 16): one "
                    "directory for ALL replicas; per-stream ownership "
                    "leases re-home a killed replica's streams to a "
                    "survivor")
    ap.add_argument("--ready-file", default=None,
                    help="write '<port>\\n' here once the proxy listens")
    # ISSUE 20: the elastic fleet. --autoscale starts the stats-driven
    # control loop over this pool+proxy; the plane picks which ops feed
    # its backlog signal, so a keygen-only (dealer) fleet and an eval
    # fleet scale independently. Thresholds/cadence come from the
    # DPF_TPU_AUTOSCALE_* env knobs (see README).
    ap.add_argument("--autoscale", action="store_true",
                    help="scale the replica count from the fleet's "
                    "backlog (DPF_TPU_AUTOSCALE_* knobs)")
    ap.add_argument("--autoscale-plane", default="eval",
                    choices=("eval", "dealer", "all"),
                    help="which ops feed the backlog signal (a dealer "
                    "fleet serves keygen only)")
    args, server_args = ap.parse_known_args(argv)
    if server_args and server_args[0] == "--":
        server_args = server_args[1:]

    pool = ReplicaPool(
        replicas=args.replicas, server_args=server_args,
        base_dir=args.base_dir, platform=args.platform,
        journal_base=args.journal_base,
        stream_journal_root=args.stream_journal_root,
    )
    proxy = None
    scaler = None
    try:
        endpoints = pool.start()
        proxy = FleetProxy(
            endpoints, host=args.host, port=args.port,
            affinity=False if args.no_affinity else None,
        ).start()
        if args.autoscale:
            from .autoscale import AutoScaler

            scaler = AutoScaler(
                proxy, pool, plane=args.autoscale_plane
            ).start()
        print(
            f"dpf-fleet: pid={os.getpid()} proxy {args.host}:{proxy.port} "
            f"over {pool.n} replicas {pool.ports}"
            + (f" (autoscale:{args.autoscale_plane})" if scaler else ""),
            file=sys.stderr, flush=True,
        )
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{proxy.port}\n")
            os.replace(tmp, args.ready_file)
        stop_evt = threading.Event()

        def _sigterm(_signo, _frame):
            print("dpf-fleet: SIGTERM — stopping", file=sys.stderr,
                  flush=True)
            stop_evt.set()

        _signal.signal(_signal.SIGTERM, _sigterm)
        _signal.signal(_signal.SIGINT, _sigterm)
        while not stop_evt.wait(0.25):
            pass
    finally:
        if scaler is not None:
            scaler.stop()
        if proxy is not None:
            proxy.stop()
        pool.stop()
        print("dpf-fleet: stopped", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
