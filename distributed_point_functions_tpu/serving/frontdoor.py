"""The serving front door: batcher + router + supervisor, composed.

One object serves all six bulk entry points behind a submit/await
interface::

    with FrontDoor() as door:
        fut = door.submit(Request.evaluate_at(dpf, [key], points))
        limbs = fut.result(timeout=5)

Per merged batch, the flow is:

1. the **continuous batcher** (serving/batcher.py) aggregated compatible
   small requests into one wide batch;
2. the **cost-model router** (serving/router.py) predicts wall time per
   (engine, mode) candidate from live dispatch latency + throughput
   anchors and picks the cheapest, emitting ``decision(source="router")``
   (an explicit ``engine=`` override skips prediction and records
   ``source="explicit"``);
3. the batch executes **through the PR 7 robust wrappers**
   (ops/supervisor.py) so dispatch deadlines, mode-aware degradation
   chains and chunk journals are inherited, not re-grown — with
   ``robust=False`` the raw entry points run instead (no degradation, but
   the warm-cache prepared tiers — ``PreparedLevelsPlan`` replay,
   ``PreparedKeyBatch`` — become usable, since the chains cannot re-target
   prepared mode-specific tables);
4. the batch's telemetry (captured around the execution only) feeds back:
   measured wall time updates the router's rate EWMA, measured
   ``pipeline.finalize`` spans update its dispatch-latency EWMA, and any
   ``decision(source="degrade")`` records penalize the failed choice
   (``Router.on_degrade``).

Every request's answer is a row/column slice of the merged batch's
result, so results are bit-exact vs calling the entry point directly with
that request's keys/points (pinned by tests/test_serving.py), and the
merged batch launches exactly the device programs the chosen engine would
launch for a direct call (pinned by tests/test_dispatch_audit.py).

The front door never *holds* device results: every op's device rung
already normalizes to host uint32 limb arrays (the robust-wrapper
contract), and slicing is numpy row selection.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import telemetry as _tm
from ..utils.errors import InvalidArgumentError, UnavailableError
from .batcher import ContinuousBatcher, Request, ServedFuture, WarmCache
from .router import RouteDecision, Router, Workload


def _value_meta(validator, hierarchy_level: int) -> Tuple[int, str]:
    """(bits, kind) of the output value type at `hierarchy_level` — the
    router's anchor bucket."""
    from ..ops import evaluator, value_codec

    if hierarchy_level < 0:
        hierarchy_level = validator.num_hierarchy_levels - 1
    vt = validator.parameters[hierarchy_level].value_type
    spec = value_codec.build_spec(
        vt, validator.blocks_needed[hierarchy_level]
    )
    if spec.is_scalar_direct and spec.blocks_needed == 1:
        bits, _ = evaluator._value_kind(vt)
        return bits, ("u128" if bits == 128 else "u64")
    return getattr(vt, "bitsize", 64), "codec"


def _pow2_pad(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def _bucket_target(n: int, chunk: Optional[int] = None, floor: int = 0) -> int:
    """The shape-bucketed axis length `n` pads to (see _pad_keys) — shared
    by the padding itself and the router's device-work model, so the cost
    a device candidate is predicted (and learned) at is the cost of the
    program that actually runs."""
    if n <= 0:
        return n
    if chunk is not None:
        return math.ceil(n / chunk) * chunk
    return max(_pow2_pad(n), _pow2_pad(floor))


def _pad_keys(
    keys: list, bucket: bool, chunk: Optional[int] = None, floor: int = 0
) -> list:
    """Shape bucketing: pads a merged key batch by repeating the last
    key. Merged batches otherwise carry a unique key count per flush,
    and the entry points compile one XLA program PER DISTINCT SHAPE — a
    compile storm locally and, through the tunnel's remote compiler, a
    latency cliff per novel batch width. Padded rows are appended after
    every request's rows, so slicing is unaffected.

    Two regimes: single-program ops (evaluate_at / dcf / hierarchical)
    pad to the next power of two — <= 2x compute on an engine chosen for
    having headroom, zero extra dispatches. Chunked ops (full_domain /
    PIR, `chunk` given) pad to the next key-chunk MULTIPLE — ceil(K/chunk)
    is unchanged, so this never adds a dispatch, and every program is
    exactly the chunk-wide shape of the warm family (a sub-chunk batch
    would otherwise compile at its own width per the chunk_indices
    small-batch exception; a power of two ABOVE the multiple would add
    whole extra chunks = extra ~66 ms dispatches, the one cost the front
    door exists to amortize).

    `floor` (single-program ops only) pads AT LEAST to pow2(floor) — the
    front door passes its width target, so deadline-triggered small
    flushes ride the same wide uniform program the full flushes compile:
    ONE shape per op in steady state, which is also what the device
    engines are fastest at. Padding applies only on the device arm (the
    caller gates `bucket`): the host engine has no program shapes to
    stabilize and would pay the padding as real per-key work."""
    if not bucket or not keys:
        return keys
    target = _bucket_target(len(keys), chunk=chunk, floor=floor)
    return list(keys) + [keys[-1]] * (target - len(keys))


def _pad_points(points: list, bucket: bool, floor: int = 0) -> list:
    """The point-axis twin of :func:`_pad_keys` (merged point unions are
    also unique per flush; `floor` gives the same steady-state
    one-shape-per-op property). Padding repeats point 0; requests slice
    their own column indices, all < the unpadded length."""
    if not bucket or not points:
        return points
    target = _bucket_target(len(points), floor=floor)
    return list(points) + [points[0]] * (target - len(points))


#: serving op -> the degrade-chain op labels its batches execute under
#: (ops/degrade._run_chain's op_name; MIC rides the DCF chain) — the
#: _learn feedback filter. telemetry.capture() is process-global, so a
#: concurrently flushing door/thread's degrade records land in this
#: batch's capture window; penalizing this batch's choice for another
#: op's failure would teach the shared cost model from misattributed
#: events.
_DEGRADE_OPS = {
    "full_domain": ("full_domain_evaluate",),
    "evaluate_at": ("evaluate_at_batch",),
    "dcf": ("dcf.batch_evaluate",),
    "mic": ("dcf.batch_evaluate",),
    "gate": ("dcf.batch_evaluate",),
    "pir": ("pir_query_batch",),
    "hierarchical": ("evaluate_levels_fused",),
    "keygen": ("generate_keys",),
}


def _union(seqs: Sequence[Sequence[int]]) -> Tuple[list, List[np.ndarray]]:
    """Order-preserving union of int sequences + each input's index rows
    into it (the merged-points slicing map)."""
    index: Dict[int, int] = {}
    merged: list = []
    rows = []
    for seq in seqs:
        r = np.empty(len(seq), dtype=np.int64)
        for i, x in enumerate(seq):
            j = index.get(x)
            if j is None:
                j = index[x] = len(merged)
                merged.append(x)
            r[i] = j
        rows.append(r)
    return merged, rows


class FrontDoor:
    """The serving composition. Knobs:

    * ``engine`` — "auto" (the router decides per batch), or "host" /
      "device" to force an engine class (the A/B harness arms; decisions
      are then recorded with ``source="explicit"``).
    * ``mode`` — device execution mode override (None = the router's /
      entry points' choice).
    * ``max_wait_ms`` / ``width_target`` / ``max_queue_depth`` — the
      batcher's deadline, width and admission knobs.
    * ``priorities`` / ``fair`` / ``adaptive_wait`` — the batcher's Orca
      scheduling knobs (ISSUE 14): per-op priority classes, round-robin
      fairness across op classes (default on; ``False`` is the FIFO
      baseline), and width-aware batch-deadline adaptation (default ON
      since ISSUE 20 — tenant quotas bound the flood failure mode that
      kept it opt-in; see README "Fleet deployment").
    * ``tenant_quotas`` / ``tenant_default_quota`` / ``tenant_priorities``
      — the batcher's multi-tenant QoS knobs (ISSUE 20): per-tenant
      admission quotas and scheduling classes, keyed by the wire
      request's tenant token.
    * ``robust`` — execute through ops/supervisor.py (default) vs the raw
      entry points (enables the prepared-plan / prepared-keys warm tiers).
    * ``policy`` / ``pipeline`` — passed through to the execution layer.
    * ``key_chunk`` — chunking for the CHUNKED ops only (full_domain /
      PIR, whose dispatch count scales with keys regardless of merging;
      the batching win there is executor overlap + shape reuse). The
      point-walk ops (evaluate_at / DCF / MIC) and hierarchical advances
      always run their natural one-program-per-batch shape — chunking a
      width-floored merged batch would multiply dispatches by padding,
      the exact cost the front door exists to amortize.
    * ``router`` — a serving.router.Router (shared across doors to pool
      learning; default constructs one, loading ``DPF_TPU_ROUTER_CALIB``).
    """

    def __init__(
        self,
        router: Optional[Router] = None,
        engine: str = "auto",
        mode: Optional[str] = None,
        max_wait_ms: float = 5.0,
        width_target: int = 64,
        max_queue_depth: int = 1024,
        priorities: Optional[Dict[str, int]] = None,
        fair: bool = True,
        adaptive_wait: bool = True,
        tenant_quotas: Optional[Dict[str, int]] = None,
        tenant_default_quota: int = 0,
        tenant_priorities: Optional[Dict[str, int]] = None,
        robust: bool = True,
        policy=None,
        pipeline: Optional[bool] = None,
        key_chunk: Optional[int] = None,
        cache: Optional[WarmCache] = None,
        bucket: bool = True,
        journal_dir: Optional[str] = None,
    ):
        if engine not in ("auto", "host", "device"):
            raise InvalidArgumentError(
                f"engine must be 'auto', 'host' or 'device', got {engine!r}"
            )
        self.router = router or Router()
        self.engine = engine
        self.mode = mode
        self.robust = robust
        self.pipeline = pipeline
        self.key_chunk = key_chunk
        #: shape bucketing (see _pad_keys): pads merged batch axes to
        #: powers of two so flushes reuse compiled programs instead of
        #: compiling one per distinct merged width.
        self.bucket = bucket
        #: directory for full-domain chunk journals (ISSUE 10): robust
        #: full-domain batches journal verified chunks under a
        #: fingerprint-derived file name, so a SIGKILLed server restarted
        #: over the same directory resumes a re-sent job past its
        #: verified chunks. None = no journaling (zero overhead).
        self.journal_dir = journal_dir
        self.cache = cache or WarmCache()
        if policy is None:
            from ..ops import degrade

            policy = degrade.DEFAULT_POLICY
        self.policy = policy
        self.batcher = ContinuousBatcher(
            self._execute,
            max_wait_ms=max_wait_ms,
            width_target=width_target,
            max_queue_depth=max_queue_depth,
            priorities=priorities,
            fair=fair,
            adaptive_wait=adaptive_wait,
            tenant_quotas=tenant_quotas,
            tenant_default_quota=tenant_default_quota,
            tenant_priorities=tenant_priorities,
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FrontDoor":
        self.batcher.start()
        return self

    def stop(self) -> None:
        self.batcher.stop()
        if self.router.calibration:
            try:
                self.router.save_calibration()
            except OSError:
                pass

    def __enter__(self) -> "FrontDoor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- submission --------------------------------------------------------
    def submit(self, request: Request) -> ServedFuture:
        if request.op == "hh_ingest":
            # Streaming ingest (ISSUE 15): admission is the stream's
            # pending-window bound (RESOURCE_EXHAUSTED = backpressure,
            # retried with backoff by the client), not the router's
            # deadline model — an ingest has no engine candidates to
            # cost. The batch id rides along so the retry of an
            # ALREADY-ACCEPTED batch (a lost ack) is acknowledged even
            # under backpressure — never refused for admitted work.
            # Flush-only control messages skip the gate here: whether a
            # flush adds a pending window depends on the open window's
            # contents, which only ingest() can judge (it exempts the
            # empty-window no-op the drain loops send).
            if request.ingest[1]:
                request.obj.check_admission(batch_id=request.ingest[2])
        else:
            self._shed_check(request)
        return self.batcher.submit(request)

    def _shed_check(self, request: Request) -> None:
        """Deadline-aware admission (ISSUE 10 satellite): reject NOW when
        the predicted completion — the batcher's queue-wait bound plus
        the router's cheapest predicted wall for this request alone —
        already exceeds the request's deadline. Richer than bounded depth:
        a doomed request never occupies a queue slot, and the client's
        fail-fast arrives a full queue-wait earlier than the expiry
        would. Prediction uses the single-request workload (its merged
        batch can only be wider, and a wider batch is never cheaper for
        THIS request's rows), the queue bound is ``max_wait`` (a flush
        happens at the latest then), and a cheapest-candidate estimate
        under-promises rather than over-sheds."""
        remaining = request.remaining()
        if remaining is None:
            return
        union = (
            _union([request.points])
            if request.op in ("evaluate_at", "dcf", "mic", "gate")
            else None
        )
        try:
            costs = self.router.model.predict(self._workload([request], union))
        except InvalidArgumentError:
            costs = {}
        if self.engine != "auto":
            forced = {k: v for k, v in costs.items() if k[0] == self.engine}
            costs = forced or costs
        predicted = min(costs.values()) if costs else 0.0
        if self.batcher.max_wait + predicted <= remaining:
            return
        _tm.counter("serving.shed_deadline", op=request.op)
        raise UnavailableError(
            f"DEADLINE_EXCEEDED: {request.op} shed at admission — "
            f"predicted completion {self.batcher.max_wait + predicted:.3f}s "
            f"(queue-wait bound {self.batcher.max_wait:.3f}s + predicted "
            f"wall {predicted:.3f}s) exceeds the {remaining:.3f}s of "
            "deadline budget remaining"
        )

    def serve(
        self, requests: Sequence[Request], timeout: Optional[float] = None
    ) -> list:
        """Submits all, pumps until served (works without the worker
        thread), returns each request's result in order."""
        futures = [self.submit(r) for r in requests]
        if self.batcher._worker is None:
            self.batcher.pump(force=True)
        return [f.result(timeout) for f in futures]

    # -- workload + routing ------------------------------------------------
    def _workload(self, reqs: List[Request], union=None) -> Workload:
        """The router's view of this batch. The device axes carry the
        shape-bucketed sizes the device arm will actually pad to
        (_pad_keys/_pad_points use the same _bucket_target), so a device
        candidate is costed — and its rate learned — at the program that
        runs, while the host is costed at the real request work."""
        r0 = reqs[0]
        v = r0._validator()
        num_keys = sum(len(r.keys) for r in reqs)
        wt = self.batcher.width_target if self.bucket else 0
        if r0.op in ("mic", "gate"):
            # The gate ops' DCF pass runs (components keys) x (sites per
            # input x merged inputs) walks — the axes the DCF anchors are
            # rated in. Every gate (MIC included, a framework gate since
            # ISSUE 9) declares them.
            comps, sites = r0.obj.num_components, r0.obj.num_sites
            merged = len(union[0])
            dev_pts = _bucket_target(merged, floor=wt) if self.bucket else None
            # Vector-payload gates collapse num_components to their real
            # walk count (ONE tuple key) — cost prediction must track the
            # walks that run, not the coefficient count; the widened
            # capture tail is flagged through value_kind.
            elems = getattr(r0.obj, "payload_elems", 1)
            return Workload(
                op=r0.op, num_keys=comps, points=merged * sites,
                value_bits=128,
                value_kind="codec" if elems > 1 else "u128",
                device_points=dev_pts and dev_pts * sites,
            )
        hl = r0.hierarchy_level if r0.op in ("full_domain", "evaluate_at") else -1
        bits, kind = _value_meta(v, hl)
        lds = v.parameters[hl].log_domain_size
        if r0.op == "keygen":
            # Work = keys x tree levels (one level-major AES pass per
            # level). Host-only until a hardware window verifies the
            # device modes (router.UNVERIFIED_MODES), so no bucketed axes.
            return Workload(
                op="keygen",
                num_keys=sum(len(r.points) for r in reqs),
                levels=v.tree_levels_needed,
                log_domain=lds, value_bits=bits, value_kind=kind,
            )
        if r0.op == "hierarchical":
            total = sum(
                max(1, len(np.atleast_1d(np.asarray(p, dtype=object))))
                for _, p in r0.plan
            )
            return Workload(
                op="hierarchical", num_keys=num_keys, levels=len(r0.plan),
                avg_prefixes=max(1, total // max(1, len(r0.plan))),
                group=r0.group, value_bits=bits, value_kind=kind,
                # pow2 only, no width floor (matching _run_hierarchical).
                device_num_keys=(
                    _bucket_target(num_keys) if self.bucket else None
                ),
            )
        points = len(union[0]) if union is not None else 0
        # key_chunk reaches the model for the CHUNKED ops only, at the
        # value execution will use (_run_full_domain / _run_pir): the
        # point-walk ops and hierarchical advances run one program per
        # batch, where a chunk would predict phantom dispatches.
        ck = None
        dev_keys = dev_pts = None
        if r0.op == "full_domain":
            ck = self.key_chunk or 32
            if self.bucket:
                dev_keys = _bucket_target(num_keys, chunk=ck)
        elif r0.op == "pir":
            ck = self.key_chunk or 64
            if self.bucket:
                dev_keys = _bucket_target(num_keys, chunk=ck)
        elif self.bucket:  # evaluate_at / dcf: width-target floors
            dev_keys = _bucket_target(num_keys, floor=wt)
            dev_pts = _bucket_target(points, floor=wt)
        return Workload(
            op=r0.op, num_keys=num_keys, points=points, log_domain=lds,
            value_bits=bits, value_kind=kind, key_chunk=ck,
            device_num_keys=dev_keys, device_points=dev_pts,
        )

    def _route(self, w: Workload) -> RouteDecision:
        if self.engine == "auto":
            return self.router.route(w)
        mode = self.mode
        decision = RouteDecision(
            self.engine, mode if self.engine == "device" else None, 0.0, {}
        )
        _tm.decision(w.op, decision.choice, "explicit", via="serving")
        return decision

    # -- execution ---------------------------------------------------------
    def _execute(self, sig: tuple, reqs: List[Request]) -> None:
        """The batcher's flush callback: route, run, learn, slice."""
        import time

        from ..ops import supervisor as _sv

        # Requests whose deadline expired while queued are rejected
        # before the batch runs — the wire contract promises fail-fast,
        # and running them would spend device time on an answer nobody
        # can use. Survivors' minimum remaining budget arms the
        # supervisor's deadline_scope below.
        now = time.perf_counter()
        live: List[Request] = []
        budget: Optional[float] = None
        for r in reqs:
            remaining = r.remaining(now)
            if remaining is not None and remaining <= 0:
                _tm.counter("serving.shed_deadline", op=r.op)
                r.future._reject(UnavailableError(
                    f"DEADLINE_EXCEEDED: {r.op} request expired while "
                    f"queued ({-remaining:.3f}s past its deadline at flush)"
                ))
                continue
            if remaining is not None:
                budget = remaining if budget is None else min(budget, remaining)
            live.append(r)
        if not live:
            return
        reqs = live
        if reqs[0].op == "hh_ingest":
            # Streaming ingest (ISSUE 15): no routing, no merging — each
            # batch journals and acknowledges individually, in arrival
            # order, and a single bad batch rejects only ITS future (the
            # window manager is the authority on dedup/backpressure).
            self._execute_hh_ingest(reqs)
            return
        # The merged point union is shared by the router's point count
        # and the runner's slicing map — computed once per batch.
        union = (
            _union([r.points for r in reqs])
            if reqs[0].op in ("evaluate_at", "dcf", "mic", "gate")
            else None
        )
        w = self._workload(reqs, union)
        decision = self._route(w)
        with _tm.span("serving.execute", op=w.op, choice=decision.choice):
            with _tm.capture(ring=2048) as tel:
                t0 = time.perf_counter()
                # budget=None passes through (the env default keeps
                # ruling); armed, every per-chunk device wait in this
                # batch is bounded by the batch's tightest remaining wire
                # deadline — the ISSUE 10 propagation: a wire deadline
                # bounds device dispatch, not just the socket wait.
                with _sv.deadline_scope(budget):
                    results = self._run(
                        reqs, decision.engine, decision.mode, union
                    )
                seconds = time.perf_counter() - t0
        self._learn(w, decision, seconds, tel)
        for r, value in zip(reqs, results):
            r.future.choice = decision.choice
            r.future._resolve(value)
            # Per-tenant latency histograms (ISSUE 20): the tenant token
            # rides the telemetry op tag, so the bench's per-tenant p95
            # table and an operator's dashboards read straight off the
            # ISSUE 6 bus. Untenanted traffic stays untagged.
            if r.tenant and _tm.enabled():
                _tm.counter("serving.tenant.served", op=r.tenant)
                _tm.observe(
                    "serving.tenant.latency_ms",
                    r.future.latency_seconds * 1e3,
                    op=r.tenant,
                )

    def _execute_hh_ingest(self, reqs: List[Request]) -> None:
        for r in reqs:
            try:
                parameters, blobs, batch_id, flush = r.ingest
                generation, deduped = r.obj.ingest(
                    parameters, list(blobs), batch_id, flush=flush
                )
                r.future.choice = "host"
                r.future._resolve(
                    np.array([generation, int(deduped)], dtype=np.uint64)
                )
            except BaseException as exc:  # noqa: BLE001 — per-future
                r.future._reject(exc)

    def _learn(self, w: Workload, decision: RouteDecision, seconds, tel) -> None:
        """Feed the measured batch back into the router: rate EWMA,
        dispatch-latency EWMA, and degrade penalties."""
        names = _DEGRADE_OPS.get(w.op, ())
        for d in tel.decision_records(source="degrade"):
            if d.get("name") not in names:
                continue  # another op's concurrent degrade: not ours
            self.router.on_degrade(
                w.op, decision.engine, decision.mode,
                d.get("data", {}).get("reason", ""),
            )
        # Dispatch latency is a property of the process's device link,
        # not of this op — a concurrent batch's finalize spans landing
        # in the window still measure the same quantity.
        lat = tel.latency("span.pipeline.finalize")
        if lat and decision.engine == "device":
            self.router.observe_dispatch(lat["p50"])
        self.router.observe(w, decision.engine, decision.mode, seconds)

    def _run(
        self, reqs: List[Request], engine: str, mode: Optional[str],
        union=None,
    ):
        op = reqs[0].op
        run = getattr(self, f"_run_{op}")
        return run(reqs, engine, mode, union)

    # Each _run_* merges the batch, executes on the chosen engine, and
    # returns one result per request (a row/column slice of the batch
    # result). Device paths go through ops/supervisor.py when
    # self.robust; host paths run the same host-oracle arms the robust
    # chains use as their rung of last resort — identical limb formats.

    def _run_full_domain(self, reqs, engine, mode, union=None):
        from ..ops import degrade, evaluator, supervisor

        dpf, hl = reqs[0].obj, reqs[0].hierarchy_level
        # Bucketing still matters under chunking: a batch smaller than
        # one chunk compiles at its own width (the chunk_indices
        # small-batch exception).
        ck = self.key_chunk or 32
        keys = _pad_keys(
            [k for r in reqs for k in r.keys],
            self.bucket and engine == "device", chunk=ck,
        )
        if engine == "host":
            out = degrade._host_full_domain_limbs(dpf, keys, hl, ck)
        elif self.robust:
            out = supervisor.full_domain_evaluate_robust(
                dpf, keys, hl, key_chunk=ck, policy=self.policy,
                pipeline=self.pipeline, journal_dir=self.journal_dir,
            )
        else:
            prepared = self.cache.key_batch(dpf, keys, hl, key_chunk=ck)
            from ..ops import pipeline as _pl

            chunks = evaluator.full_domain_evaluate_chunks(
                dpf, prepared, hl, pipeline=self.pipeline
            )
            outs = [
                np.asarray(o)[:valid]
                for valid, o in _pl.consume(
                    chunks, lambda item: item, _pl.resolve(self.pipeline),
                    depth=1, op="full_domain_evaluate",
                )
            ]
            out = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
        return self._slice_rows(reqs, out)

    def _run_evaluate_at(self, reqs, engine, mode, union=None):
        from ..ops import degrade, supervisor

        dpf, hl = reqs[0].obj, reqs[0].hierarchy_level
        pad = self.bucket and engine == "device"
        keys = _pad_keys(
            [k for r in reqs for k in r.keys], pad,
            floor=self.batcher.width_target,
        )
        points, rows = union if union is not None else _union(
            [r.points for r in reqs]
        )
        points = _pad_points(points, pad, floor=self.batcher.width_target)
        if engine == "host":
            out = degrade._host_evaluate_at_limbs(dpf, keys, points, hl)
        elif self.robust:
            out = supervisor.evaluate_at_robust(
                dpf, keys, points, hl, policy=self.policy,
                pipeline=self.pipeline, mode=mode,
            )
        else:
            from ..ops import evaluator

            out = evaluator.evaluate_at_batch(
                dpf, keys, points, hl, pipeline=self.pipeline, mode=mode,
            )
        out = np.asarray(out)
        sliced, start = [], 0
        for r, cols in zip(reqs, rows):
            k = len(r.keys)
            sliced.append(out[start : start + k][:, cols])
            start += k
        return sliced

    def _run_dcf(self, reqs, engine, mode, union=None):
        from ..ops import evaluator, supervisor

        dcf = reqs[0].obj
        pad = self.bucket and engine == "device"
        keys = _pad_keys(
            [k for r in reqs for k in r.keys], pad,
            floor=self.batcher.width_target,
        )
        xs, rows = union if union is not None else _union(
            [r.points for r in reqs]
        )
        xs = _pad_points(xs, pad, floor=self.batcher.width_target)
        if engine == "host":
            bits, _ = evaluator._value_kind(dcf.value_type)
            out, _covered = supervisor._dcf_host_limbs(dcf, keys, xs, bits)
        elif self.robust:
            out = supervisor.batch_evaluate_robust(
                dcf, keys, xs, policy=self.policy,
                pipeline=self.pipeline, mode=mode,
            )
        else:
            out = dcf.batch_evaluate(
                keys, xs, pipeline=self.pipeline, mode=mode
            )
        out = np.asarray(out)
        sliced, start = [], 0
        for r, cols in zip(reqs, rows):
            k = len(r.keys)
            sliced.append(out[start : start + k][:, cols])
            start += k
        return sliced

    def _run_mic(self, reqs, engine, mode, union=None):
        """MIC is a framework gate since ISSUE 9 (`mic_batch_eval_robust`
        is an alias of `gate_batch_eval_robust`) — one serving path."""
        return self._run_gate(reqs, engine, mode, union)

    def _run_gate(self, reqs, engine, mode, union=None):
        """Any framework gate (ISSUE 9): the MIC serving shape via the
        shared GatePlan — one fused DCF pass for the merged input union,
        per-request row slices of the [inputs, num_outputs] shares."""
        from ..ops import supervisor

        gate, key = reqs[0].obj, reqs[0].keys[0]
        xs, rows = union if union is not None else _union(
            [r.points for r in reqs]
        )
        xs = _pad_points(
            xs, self.bucket and engine == "device",
            floor=self.batcher.width_target,
        )
        if engine == "host":
            out = gate.batch_eval(key, xs, engine="host")
        elif self.robust:
            out = supervisor.gate_batch_eval_robust(
                gate, key, xs, policy=self.policy,
                pipeline=self.pipeline, mode=mode,
            )
        else:
            out = gate.batch_eval(key, xs, engine="device", mode=mode)
        out = np.asarray(out)
        return [out[cols] for cols in rows]

    def _run_keygen(self, reqs, engine, mode, union=None):
        """Dealer keygen offload (ISSUE 13): merged alphas/beta columns
        run ONE level-major batched keygen pass (the robust chain spot-
        verifies non-oracle rungs against the scalar oracle), and each
        request's slice is answered as serialized key blobs — 2*Kr uint8
        arrays, Kr party-0 then Kr party-1 (`wire.keygen_result_arrays`'
        layout), so the RPC server's generic result-array path carries
        them unchanged. Host engine = the threaded vectorized numpy
        batch (ISSUE 19's production default); device = the
        "jax"/"pallas"/"megakernel" plane-circuit modes
        (staged-for-tunnel)."""
        del union
        from ..ops import keygen_batch, supervisor
        from . import wire

        dpf = reqs[0].obj
        alphas = [a for r in reqs for a in r.points]
        levels = len(reqs[0].betas)
        beta_cols = [
            [b for r in reqs for b in r.betas[level]]
            for level in range(levels)
        ]
        kg_mode = (mode or "jax") if engine == "device" else "numpy-threaded"
        if self.robust:
            keys_0, keys_1 = supervisor.generate_keys_robust(
                dpf, alphas, beta_cols, mode=kg_mode, policy=self.policy,
            )
        else:
            keys_0, keys_1 = keygen_batch.generate_keys_batch(
                dpf, alphas, beta_cols, mode=kg_mode
            )
        blobs = wire.keygen_result_arrays(
            keys_0, keys_1, dpf.validator.parameters
        )
        total = len(alphas)
        results = []
        offset = 0
        for r in reqs:
            kr = len(r.points)
            results.append(
                blobs[offset : offset + kr]
                + blobs[total + offset : total + offset + kr]
            )
            offset += kr
        return results

    def _run_pir(self, reqs, engine, mode, union=None):
        from ..ops import evaluator, supervisor
        from ..parallel import sharded

        dpf, db = reqs[0].obj, reqs[0].db
        ck = self.key_chunk or 64
        keys = _pad_keys(
            [k for r in reqs for k in r.keys],
            self.bucket and engine == "device", chunk=ck,
        )
        v = dpf.validator
        bits, _ = evaluator._value_kind(v.parameters[-1].value_type)
        if engine == "host":
            nat = (
                db.natural_host(dpf)
                if isinstance(db, sharded.PreparedPirDatabase)
                else np.asarray(db)
            )
            out = supervisor._host_pir_fold(dpf, keys, nat, bits)
        else:
            # Mirror pir_query_batch_chunked's order contract: walk/fused
            # consume the natural-order DB, fold/levels the lane order.
            eff = mode or "fold"
            if eff == "megakernel":
                want_order = "megakernel"
            elif eff in ("walk", "fused"):
                want_order = "natural"
            else:
                want_order = "lane"
            pdb = self.cache.pir_db(dpf, db, want_order)
            if self.robust:
                out = supervisor.pir_query_batch_robust(
                    dpf, keys, pdb, key_chunk=ck, policy=self.policy,
                    pipeline=self.pipeline, mode=mode,
                )
            else:
                out = sharded.pir_query_batch_chunked(
                    dpf, keys, pdb, key_chunk=ck, mode=mode or "fold",
                    pipeline=self.pipeline,
                )
        return self._slice_rows(reqs, np.asarray(out))

    def _run_hierarchical(self, reqs, engine, mode, union=None):
        from ..core import host_eval
        from ..ops import evaluator, hierarchical, supervisor

        dpf = reqs[0].obj
        plan, group = reqs[0].plan, reqs[0].group
        # pow2 only, no width floor: hierarchical device compute scales
        # with keys x prefixes, so width-target padding could multiply a
        # 10k-prefix advance many-fold — shape stability is enough here.
        keys = _pad_keys(
            [k for r in reqs for k in r.keys],
            self.bucket and engine == "device",
        )
        ctx = hierarchical.BatchedContext.create(dpf, keys)
        v = dpf.validator
        if engine == "host":
            outs = []
            for h, prefixes in plan:
                bits, _ = evaluator._value_kind(v.parameters[h].value_type)
                ref = hierarchical.evaluate_until_batch(
                    ctx, h, prefixes, engine="host"
                )
                outs.append(host_eval.values_to_limbs(np.asarray(ref), bits))
        elif self.robust:
            outs = supervisor.evaluate_levels_fused_robust(
                ctx, plan, group, policy=self.policy, mode=mode,
                pipeline=self.pipeline,
            )
        else:
            prepared = self.cache.levels_plan(
                dpf, reqs[0].keys, plan, group, mode=mode
            )
            outs = hierarchical.evaluate_levels_fused(
                ctx, prepared, pipeline=self.pipeline
            )
            outs = [np.asarray(o) for o in outs]
        # Per request: the row slice of every plan entry's output.
        results, start = [], 0
        for r in reqs:
            k = len(r.keys)
            results.append([o[start : start + k] for o in outs])
            start += k
        return results

    @staticmethod
    def _slice_rows(reqs, out):
        out = np.asarray(out)
        sliced, start = [], 0
        for r in reqs:
            k = len(r.keys)
            sliced.append(out[start : start + k])
            start += k
        return sliced
