"""Epoch-numbered stream leases (ISSUE 16): the failover primitive.

One small fsync'd file answers "who may drive this stream, and under
which epoch?". The streaming tier uses it twice:

* **role lease** — the aggregation leader TTL-renews it from the advance
  worker; the follower watches and, when the lease expires (the leader
  is dead or wedged), bumps the epoch and takes the leader role. The
  epoch rides every ``hh_aggregate`` request, so a *zombie* ex-leader —
  alive but holding a superseded epoch — is rejected with
  ``FAILED_PRECONDITION`` before anything merges;
* **ownership lease** — streams sheltered behind the fleet proxy share
  one journal volume; the per-stream owner lease inside the stream
  directory guarantees two replicas never advance (or even load) the
  same journals concurrently.

Crash-safety is by construction, not by locking discipline at readers:
every state change lands as a complete-file atomic replace (temp file,
``flush`` + ``fsync``, then ``os.replace``), so a reader sees the old
record or the new record, never a torn one — a mid-write SIGKILL leaves
the previous lease intact, and the TTL (not the file) is what expires
it. Writers serialize through a best-effort ``.lock`` sidecar
(``O_CREAT|O_EXCL``, broken when stale) so a takeover's read-bump-write
is not interleaved with a renewal; the epoch check at the protocol layer
is the real fence, the sidecar just keeps the common case clean.

Epochs only grow. ``try_acquire`` bumps the epoch even when the SAME
owner re-acquires after a crash: a restarted process must fence its own
pre-crash requests exactly like it would fence a rival's.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import time
from typing import Optional

from ..utils import telemetry as _tm
from ..utils.errors import InvalidArgumentError, UnavailableError


@dataclasses.dataclass(frozen=True)
class LeaseState:
    """One decoded lease record. ``deadline`` is a wall-clock instant
    (``time.time()``): both parties of a pair — and every replica of a
    fleet — share the host clock in this repo's deployment shape (the
    soak runs everything on loopback); cross-host deployments would add
    a clock-skew margin to ``ttl``."""

    epoch: int
    owner: str
    deadline: float
    ttl: float

    def expired(self, now: Optional[float] = None) -> bool:
        return (time.time() if now is None else now) >= self.deadline


class StreamLease:
    """The lease file handle for one stream (role or ownership).

    ``owner`` is this process's identity string (stable across renewals,
    distinct between contenders — the server CLI uses ``pid:port``).
    ``ttl`` is the expiry horizon each write buys; holders renew at
    ttl/3 cadence, watchers poll at the same cadence, so a dead holder
    is superseded within ~ttl + one poll tick."""

    #: seconds a .lock sidecar may exist before a contender breaks it —
    #: a crash INSIDE the read-bump-write critical section (microseconds
    #: wide) must not wedge the stream forever.
    STALE_LOCK_SECONDS = 5.0

    def __init__(self, path: str, owner: str, ttl: float = 2.0):
        if ttl <= 0:
            raise InvalidArgumentError(
                f"lease ttl must be > 0, got {ttl}"
            )
        self.path = path
        self.owner = owner
        self.ttl = float(ttl)

    # -- reading -----------------------------------------------------------
    def read(self) -> Optional[LeaseState]:
        """The current lease record, or None when no lease was ever
        granted (or the file is unreadable garbage — treated as absent:
        the atomic-replace writer never leaves a torn file, so garbage
        means a foreign file, and claiming over it is the safe move)."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            rec = json.loads(raw.decode("utf-8"))
            return LeaseState(
                epoch=int(rec["epoch"]),
                owner=str(rec["owner"]),
                deadline=float(rec["deadline"]),
                ttl=float(rec.get("ttl", self.ttl)),
            )
        except (ValueError, KeyError, TypeError):
            return None

    def epoch(self) -> int:
        st = self.read()
        return 0 if st is None else st.epoch

    # -- writing -----------------------------------------------------------
    def _write(self, epoch: int, deadline: float) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps({
                "epoch": int(epoch), "owner": self.owner,
                "deadline": float(deadline), "ttl": self.ttl,
            }, sort_keys=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def _guard(self):
        """The writer-serialization sidecar: O_EXCL create, stale-break.
        Raises UnavailableError (retryable) when contended past its
        budget — callers treat that as "try again next tick"."""
        lock = f"{self.path}.lock"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        deadline = time.time() + 1.0
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                return _LockGuard(lock)
            except OSError as exc:
                if exc.errno != errno.EEXIST:
                    raise
            try:
                age = time.time() - os.path.getmtime(lock)
                if age > self.STALE_LOCK_SECONDS:
                    os.unlink(lock)  # a crash inside the critical section
                    continue
            except OSError:
                continue  # holder finished between stat and unlink
            if time.time() >= deadline:
                raise UnavailableError(
                    f"UNAVAILABLE: lease {self.path} writer lock is "
                    "contended — retry"
                )
            time.sleep(0.005)

    def try_acquire(self) -> Optional[int]:
        """Claims the lease: returns the NEW epoch, or None when a
        different owner holds an unexpired lease. Re-acquisition by the
        same owner (a restart) also bumps the epoch — the restarted
        process's old in-flight requests must be fenced too."""
        with self._guard():
            st = self.read()
            now = time.time()
            if st is not None and st.owner != self.owner and not st.expired(now):
                return None
            epoch = (0 if st is None else st.epoch) + 1
            self._write(epoch, now + self.ttl)
            _tm.counter("lease.acquired")
            return epoch

    def renew(self, epoch: int) -> bool:
        """Extends the deadline iff this owner still holds `epoch`.
        False means the lease moved on (a takeover happened) — the
        caller must stop acting as the holder."""
        with self._guard():
            st = self.read()
            if st is None or st.epoch != epoch or st.owner != self.owner:
                _tm.counter("lease.renew_lost")
                return False
            self._write(epoch, time.time() + self.ttl)
            return True

    def release(self, epoch: int) -> bool:
        """Expires the lease NOW (epoch kept — the next holder still
        bumps past it) iff this owner holds `epoch`. A graceful stop
        hands over in one watcher tick instead of a full TTL."""
        with self._guard():
            st = self.read()
            if st is None or st.epoch != epoch or st.owner != self.owner:
                return False
            self._write(epoch, 0.0)
            return True


class _LockGuard:
    def __init__(self, path: str):
        self._path = path

    def __enter__(self) -> "_LockGuard":
        return self

    def __exit__(self, *exc) -> None:
        try:
            os.unlink(self._path)
        except OSError:
            pass
