"""Cost-model engine router: host vs device vs kernel mode, per batch.

PERF.md's engine table ("Engine-choice results") encodes a sharp
host/device crossover — the ~66 ms per-dispatch RPC latency of this
image's tunnel makes the device lose any workload that arrives as small
requests, while wide uniform batches win by 10-14x — but until ISSUE 8
that knowledge lived in ``DPF_TPU_*`` env vars and bench defaults. This
module turns it into a per-batch decision::

    predicted_seconds(engine, mode) =
        dispatches(workload, mode) * dispatch_seconds(engine)   # latency
      + work_items(workload) / rate(op, engine, mode, kind)     # throughput

* **Dispatch term** — the program count each execution mode provably
  launches (1 per key chunk for the fold/walk shapes, ceil(levels/group)
  per hierarchical advance — the same arithmetic tests/test_dispatch_audit
  pins) times the per-dispatch latency: a live EWMA fed from the telemetry
  bus's ``pipeline.finalize`` spans when the front door has measured any,
  else the cold-start prior (PERF.md: 65.7 ms tiny-jit RPC through the
  tunnel). The host engine has no RPC — its dispatch term is zero.
* **Throughput term** — measured rate anchors from PERF.md's verified
  rows (each entry cites its table row), adjusted online: every served
  batch's measured wall time updates an EWMA of the chosen engine's rate,
  and every supervisor degrade event multiplies a decaying penalty into
  the failed choice's predictions (``on_degrade``) so a flaky kernel mode
  routes around itself.

Modes with **no verified device measurement** (megakernel / walkkernel /
hierkernel / sharded-megakernel — all staged-for-tunnel, ROADMAP) are *not* candidates by
default: routing production traffic on a projection would re-create the
caching-illusion era PERF.md documents. They enter the candidate set only
once a live measurement teaches them (``observe`` / a calibration file
from a hardware window) or when ``include_projections=True`` explicitly
opts into the roofline-ceiling estimates (the ``CHECK_MODE=router``
hardware stage does, to exercise one routed batch per engine class).

Every resolution emits a ``decision(source="router")`` telemetry record
carrying the predicted cost of the chosen candidate AND the alternatives,
so an A/B harness can tell "router mispredicted" from "engine lost".

The anchor table is NOT a second copy of PERF.md's numbers growing apart
from it: tests/test_serving.py pins that routing these anchors reproduces
every winner row of the engine table, and utils/roofline.py's CLI prints
the router's predictions next to the measured anchors so a drift is
visible in the artifact the table is built from.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
from typing import Dict, Optional, Tuple

from ..utils import envflags
from ..utils import telemetry as _tm
from ..utils.errors import InvalidArgumentError

# ---------------------------------------------------------------------------
# Cold-start priors (PERF.md anchors; each entry cites its source row)
# ---------------------------------------------------------------------------

#: Per-dispatch RPC latency prior, seconds (PERF.md "dispatch latency
#: (tiny jit)": 65.7 ms through this image's tunnel; 0.21 ms locally).
DISPATCH_SECONDS_PRIOR = 0.0657

#: EWMA smoothing for online rate/dispatch updates: new = a*x + (1-a)*old.
EWMA_ALPHA = 0.3

#: Derate applied to roofline ceilings when include_projections=True: a
#: staged-for-tunnel kernel mode is predicted at this fraction of its
#: modeled ceiling (the verified Mosaic fold runs ~28% of the VPU
#: roofline, PERF.md MFU table — 0.1 is deliberately pessimistic).
PROJECTION_DERATE = 0.1

#: items/s rate anchors per (op, engine, mode) and value kind. Verified
#: measured rows only (PERF.md "Engine-choice results", re-measured
#: 2026-07-31); kinds missing from an entry fall back to the "u64" rate
#: scaled by 64/bits. Units: full_domain/pir = domain evals/s,
#: evaluate_at/dcf/mic = point evals/s, hierarchical = (prefix x level)
#: advances/s.
ANCHORS: Dict[Tuple[str, str, Optional[str]], Dict[str, float]] = {
    # full-domain 2^20 x 1024 keys u64: 1.06-1.13 G evals/s device
    # (fold/128 + Mosaic row kernels, verified 8/8) vs 72-112 M host.
    ("full_domain", "host", None): {
        "u64": 99.7e6,   # native engine headline (1 thread)
        "u128": 8e6,     # "~8 M evals/s class" table row
        "codec": 30.4e6, # host one-pass IntModN correction rate
    },
    ("full_domain", "device", "fold"): {
        "u64": 1.06e9,
        # XorWrapper<u128> row: 12.7 M evals/s measured AT the dispatch
        # floor (82 ms/expansion incl. ~66 ms RPC); the compute-term
        # anchor backs the dispatch share out: 2^20 / (82-66) ms.
        "u128": 65.5e6,
        "codec": 68.6e6,  # 8-level IntModN<u64> hierarchy row (slabbed fused)
    },
    # batched EvaluateAt 1024 x 4096, 2^32: host VAES walk 5.3-5.9 M pt/s
    # vs 2.0 M pt/s per-level device walk.
    ("evaluate_at", "host", None): {"u64": 5.5e6},
    ("evaluate_at", "device", "walk"): {"u64": 2.0e6},
    # DCF 512 x 512, 2^24: host 1.06-1.25 M cmp/s vs 590 K device walk.
    ("dcf", "host", None): {"u64": 1.15e6, "u128": 0.8e6},
    ("dcf", "device", "walk"): {"u64": 590e3, "u128": 400e3},
    # heavy-hitters 128-level bit hierarchy, 10k prefixes: host ~0.27
    # s/key = 1.28 M prefix-level advances in 0.27 s; device 11.45 s/key
    # (per-level dispatch measurement — the verified device anchor; the
    # grouped fused path's ~0.56 s is a projection until the tunnel).
    ("hierarchical", "host", None): {"u64": 4.7e6},
    ("hierarchical", "device", "fused"): {"u64": 112e3},
    # two-server PIR 2^24 x 64 queries: 21.3 q/s device (in-program inner
    # product, verified) vs 1.5 q/s host — normalized to domain evals/s
    # at the 2^24 config.
    ("pir", "host", None): {"u64": 25.2e6, "u128": 25.2e6},
    ("pir", "device", "fold"): {"u64": 357e6, "u128": 357e6},
    # dealer keygen 1024 keys, depth 20 (PERF.md "Device-side keygen"):
    # vectorized host batch ~9.6 K keys/s u64 = 1.93e5 key-level AES
    # passes/s; u128 pays the exact-int value-correction path. Keygen is
    # mostly single-core numpy (level-major, no native threading), so
    # its rate is NOT host-thread scaled — see _rate.
    ("keygen", "host", None): {
        "u64": 1.93e5, "u128": 1.72e5, "codec": 2.03e5,
    },
}

#: Device modes with NO verified measurement (staged-for-tunnel, ROADMAP):
#: candidates only via a learned rate, a calibration file, or
#: include_projections=True.
UNVERIFIED_MODES: Dict[Tuple[str, str], Tuple[str, ...]] = {
    ("full_domain", "device"): ("megakernel",),
    ("evaluate_at", "device"): ("walkkernel",),
    ("dcf", "device"): ("walkkernel",),
    ("hierarchical", "device"): ("hierkernel",),
    # ISSUE 17: the mesh-sharded megakernel has never run on hardware
    # (the forced-host-device mesh checks bit-exactness, not rate); its
    # projection scales the single-chip VPU ceiling by the Workload's
    # mesh chip count (throughput with 'keys' shards, capacity with
    # 'domain' shards).
    ("pir", "device"): ("megakernel", "sharded-megakernel"),
    # ISSUE 13: device keygen (the plane-space XLA / Mosaic row-kernel
    # modes of ops/keygen_batch.py) has never run on hardware — host
    # wins every keygen batch until a measurement teaches it. ISSUE 19
    # adds the single-program keygen megakernel behind the same gate.
    ("keygen", "device"): ("jax", "pallas", "megakernel"),
}

#: Fallback key chunking for standalone Workloads — the dispatch-count
#: model's denominator, matching what serving EXECUTES when no chunk is
#: given (supervisor.full_domain_evaluate_robust chunks at 32, PIR at
#: 64; point walks run one program per batch). The front door always
#: passes its effective chunk explicitly, so this only binds Workloads
#: built by hand.
_DEFAULT_KEY_CHUNK = {"full_domain": 32, "pir": 64}

_OPS = (
    "full_domain", "evaluate_at", "dcf", "mic", "gate", "pir",
    "hierarchical", "keygen",
)


def _anchor_op(op: str) -> str:
    """The anchor-table op a serving op's rates come from. The gate ops
    (MIC and the ISSUE 9 framework family) ARE batched-DCF passes plus a
    host combine, so they ride the DCF anchors; their Workload carries
    the flattened (components x sites) axes so the work-item count is
    the DCF walks actually executed."""
    return "dcf" if op in ("mic", "gate") else op


@dataclasses.dataclass(frozen=True)
class Workload:
    """The router's view of one merged batch: enough shape to count work
    items and device programs, nothing else. ``value_kind`` buckets the
    rate anchors ("u64" = scalar widths <= 64, "u128", "codec" =
    IntModN/Tuple); ``avg_prefixes``/``levels`` are the hierarchical
    walk's work axes; ``points`` is shared across keys (the batched
    entry-point contract)."""

    op: str
    num_keys: int = 1
    points: int = 0
    log_domain: int = 0
    levels: int = 0
    avg_prefixes: int = 0
    group: int = 16
    value_bits: int = 64
    value_kind: str = "u64"
    key_chunk: Optional[int] = None
    #: (keys, domain) mesh axes of a pod-scale PIR workload (ISSUE 17);
    #: (1, 1) = single-device. Only the "sharded-megakernel" candidate
    #: reads them: its projected rate is the single-chip ceiling times
    #: the chip count (learned rates already embody the mesh they were
    #: measured on and are NOT rescaled).
    mesh_keys: int = 1
    mesh_domain: int = 1
    #: shape-bucketed device axes (the front door's _bucket_target
    #: padding; None = same as the request axes): the device engine runs
    #: THE PADDED PROGRAM, so its cost must be predicted — and its rate
    #: learned — at the padded work, or a small deadline flush poisons
    #: the rate EWMA by the padding factor. The host engine never pads.
    device_num_keys: Optional[int] = None
    device_points: Optional[int] = None

    def _axes(self, engine: Optional[str]) -> Tuple[int, int]:
        if engine == "device":
            return (
                self.device_num_keys or self.num_keys,
                self.device_points or self.points,
            )
        return self.num_keys, self.points

    def work_items(self, engine: Optional[str] = None) -> float:
        """Work items the `engine` actually computes for this batch:
        request-level axes for the host (and for reporting, engine=None),
        the shape-bucketed padded axes for the device."""
        keys, points = self._axes(engine)
        if self.op in ("full_domain", "pir"):
            return float(keys) * float(1 << self.log_domain)
        if self.op in ("evaluate_at", "dcf", "mic", "gate"):
            return float(keys) * float(points)
        if self.op == "hierarchical":
            return (
                float(keys)
                * float(max(1, self.levels))
                * float(max(1, self.avg_prefixes))
            )
        if self.op == "keygen":
            # One level-major AES pass per tree level per key (`levels`
            # carries tree_levels_needed here).
            return float(keys) * float(max(1, self.levels))
        raise InvalidArgumentError(f"unknown router op {self.op!r}")

    def dispatches(self, mode: Optional[str]) -> int:
        """Device programs the mode provably launches for this batch —
        the same counts tests/test_dispatch_audit.py pins (1 per key
        chunk for fold/walk/megakernel shapes; ceil(levels/group) windows
        per hierarchical advance, times key chunks for the hierkernel).
        Counted on the device axes — only the device engine dispatches,
        and chunk-multiple padding never changes the count."""
        keys, _ = self._axes("device")
        if self.op == "keygen":
            if mode == "megakernel":
                # ISSUE 19: the keygen megakernel runs the whole level
                # loop in ONE program per batch (dispatch-audit pin).
                return 1
            # The per-level keygen loop is sequential in tree depth: one
            # fused L/R/value program per level + the final value hash
            # (tests/test_dispatch_audit's keygen pin), independent of
            # the key count.
            return max(1, self.levels)
        ck = self.key_chunk or _DEFAULT_KEY_CHUNK.get(self.op, keys)
        chunks = max(1, math.ceil(keys / max(1, ck)))
        if self.op == "hierarchical":
            windows = max(1, math.ceil(max(1, self.levels) / max(1, self.group)))
            return windows * (chunks if mode == "hierkernel" else 1)
        return chunks


#: The measured engine table (PERF.md "Engine-choice results") as router
#: workloads: (row label, Workload, measured winner). The router pin
#: (tests/test_serving.py) asserts ``route()`` reproduces every winner
#: from the cold-start anchors alone; utils/roofline.py's CLI prints the
#: predictions next to the measured rows.
ENGINE_TABLE = (
    ("full-domain 2^20 x 1024 keys u64",
     # key_chunk=128: the measured headline ran fold/128 (PERF.md).
     Workload(op="full_domain", num_keys=1024, log_domain=20,
              key_chunk=128), "device"),
    ("full-domain 2^20 XorWrapper<u128>, 1 key",
     Workload(op="full_domain", num_keys=1, log_domain=20, value_bits=128,
              value_kind="u128"), "device"),
    ("heavy-hitters 128-level, 10k prefixes, 1 key",
     Workload(op="hierarchical", num_keys=1, levels=128, avg_prefixes=10000),
     "host"),
    ("DCF 512 keys x 512 points, 2^24",
     Workload(op="dcf", num_keys=512, points=512, log_domain=24), "host"),
    ("sparse-histogram experiments (hierarchical, 1 key)",
     Workload(op="hierarchical", num_keys=1, levels=32,
              avg_prefixes=1 << 17), "host"),
    ("batched EvaluateAt 1024 x 4096, 2^32",
     Workload(op="evaluate_at", num_keys=1024, points=4096, log_domain=32),
     "host"),
    ("two-server PIR 2^24 x 64 queries",
     Workload(op="pir", num_keys=64, log_domain=24, value_bits=128,
              value_kind="u128"), "device"),
    ("8-level IntModN<u64> hierarchy, 256 keys",
     Workload(op="full_domain", num_keys=256, log_domain=24,
              value_kind="codec", key_chunk=4), "device"),
)


@dataclasses.dataclass
class RouteDecision:
    """One routing outcome: the chosen (engine, mode), its predicted wall
    seconds, and the full candidate table (label -> predicted seconds)
    the choice was made from."""

    engine: str
    mode: Optional[str]
    predicted_seconds: float
    costs: Dict[str, float]

    @property
    def choice(self) -> str:
        return f"{self.engine}/{self.mode}" if self.mode else self.engine


def _kind_rate(table: Dict[str, float], kind: str, bits: int) -> float:
    """Anchor rate for a value kind, falling back to the u64 rate scaled
    by width (a 128-bit value moves/corrects 2x the limbs)."""
    if kind in table:
        return table[kind]
    return table["u64"] * (64.0 / max(64, bits))


class CostModel:
    """predicted wall seconds per (engine, mode) candidate for a Workload.

    Thread-safe: the front door's batcher thread calls ``predict`` /
    ``observe`` while a monitoring thread may snapshot ``state()``.
    """

    def __init__(
        self,
        dispatch_seconds: float = DISPATCH_SECONDS_PRIOR,
        include_projections: bool = False,
        host_threads: Optional[int] = None,
    ):
        self._lock = threading.Lock()
        self.dispatch_prior = float(dispatch_seconds)
        self.dispatch_ewma: Optional[float] = None
        self.include_projections = include_projections
        self.host_threads = host_threads
        #: learned items/s per (op, engine, mode, kind) — EWMA over
        #: measured batches; overrides the cold-start anchors.
        self.learned: Dict[Tuple[str, str, Optional[str], str], float] = {}
        #: decaying multiplicative penalty per (op, engine, mode): > 1
        #: after a degrade event fed back from the supervisor.
        self.penalty: Dict[Tuple[str, str, Optional[str]], float] = {}

    # -- dispatch term -----------------------------------------------------
    def dispatch_seconds(self, engine: str) -> float:
        if engine == "host":
            return 0.0  # no RPC: the native engine runs in-process
        with self._lock:
            return (
                self.dispatch_ewma
                if self.dispatch_ewma is not None
                else self.dispatch_prior
            )

    def observe_dispatch(self, seconds: float) -> None:
        """Feeds one measured per-dispatch latency (the telemetry bus's
        ``pipeline.finalize`` span p50 is the canonical source)."""
        if seconds <= 0:
            return
        with self._lock:
            if self.dispatch_ewma is None:
                self.dispatch_ewma = float(seconds)
            else:
                self.dispatch_ewma = (
                    EWMA_ALPHA * float(seconds)
                    + (1 - EWMA_ALPHA) * self.dispatch_ewma
                )

    # -- throughput term ---------------------------------------------------
    def _host_speedup(self) -> float:
        from ..utils import roofline

        return roofline.host_thread_speedup(self.host_threads)

    def rate(
        self, op: str, engine: str, mode: Optional[str], kind: str,
        bits: int, n_chips: int = 1,
    ) -> Optional[float]:
        """items/s for a candidate, or None when the candidate has no
        basis (unverified mode with no learned rate and projections off).
        MIC rides the DCF anchors — its gate evaluation IS a DCF batch
        (2m comparison points per input) plus a host combine."""
        anchor_op = _anchor_op(op)
        with self._lock:
            learned = self.learned.get((anchor_op, engine, mode, kind))
        if learned is not None:
            return learned
        table = ANCHORS.get((anchor_op, engine, mode))
        if table is not None:
            rate = _kind_rate(table, kind, bits)
            # ISSUE 19: the host dealer threads its key slices
            # (keygen_batch.host_generate_keys_batch), so keygen now
            # rides the same native-engine thread-speedup model as the
            # evaluation ops.
            if engine == "host":
                rate = rate * self._host_speedup()
            return rate
        if (
            engine == "device"
            and mode in UNVERIFIED_MODES.get((anchor_op, "device"), ())
            and self.include_projections
        ):
            return self._projection_rate(anchor_op, mode, bits, n_chips)
        return None

    def _projection_rate(
        self, op: str, mode: str, bits: int, n_chips: int = 1
    ) -> float:
        """Roofline-ceiling estimate for a staged-for-tunnel kernel mode,
        derated by PROJECTION_DERATE. Explicit opt-in only. `n_chips`
        (mesh-sharded modes only — predict() passes 1 otherwise) scales
        the VPU ceiling by the mesh size: every chip expands its own
        domain slice of its own key shard, and the only cross-chip work
        is the [Kl, lpe] XOR all-gather."""
        from ..utils import roofline

        lpe = max(1, bits // 32)
        ops_per = roofline.hash_ops_per_block()["element_ops_per_block"]
        if op in ("full_domain", "pir"):
            # megakernel: ~3 hashes per leaf (hashes_per_eval at depth).
            return (
                roofline.V5E_VPU_OPS_PER_SEC * max(1, n_chips)
                / (3.0 * ops_per) * PROJECTION_DERATE
            )
        if op in ("evaluate_at", "dcf", "mic", "gate"):
            caps = 33 if op in ("dcf", "mic", "gate") else 1
            f = roofline.walk_hbm_fields(1.0, 32, "walkkernel", lpe, caps)
            return f["walk_vpu_ceiling_points_per_sec"] * PROJECTION_DERATE
        f = roofline.hier_hbm_fields(1.0, "hierkernel", lpe, 2, 32)
        return (
            f["hier_vpu_ceiling_prefix_levels_per_sec"] * PROJECTION_DERATE
        )

    # -- learning ----------------------------------------------------------
    def observe(
        self,
        w: Workload,
        engine: str,
        mode: Optional[str],
        seconds: float,
    ) -> None:
        """Teaches the model one measured batch: the compute-term rate
        EWMA updates from (wall - dispatch share), and a prior degrade
        penalty on this choice decays (the choice is serving again)."""
        if seconds <= 0:
            return
        op = _anchor_op(w.op)
        disp = (
            w.dispatches(mode) * self.dispatch_seconds(engine)
            if engine == "device"
            else 0.0
        )
        compute = max(seconds - disp, seconds * 0.05)
        rate = w.work_items(engine) / compute
        key = (op, engine, mode, w.value_kind)
        with self._lock:
            old = self.learned.get(key)
            self.learned[key] = (
                rate if old is None else EWMA_ALPHA * rate + (1 - EWMA_ALPHA) * old
            )
            pkey = (op, engine, mode)
            if pkey in self.penalty:
                decayed = self.penalty[pkey] ** 0.5
                if decayed <= 1.05:
                    del self.penalty[pkey]
                else:
                    self.penalty[pkey] = decayed

    def on_degrade(
        self, op: str, engine: str, mode: Optional[str], reason: str = ""
    ) -> None:
        """Feedback from a supervisor degrade event: the failed choice's
        predictions are penalized 4x (stacking, capped 256x) until
        successful batches decay it — a flaky kernel mode routes around
        itself without being permanently blacklisted."""
        key = (_anchor_op(op), engine, mode)
        with self._lock:
            self.penalty[key] = min(self.penalty.get(key, 1.0) * 4.0, 256.0)
        _tm.counter("router.degrade_penalty", op=op)

    # -- prediction --------------------------------------------------------
    def candidates(self, op: str) -> Tuple[Tuple[str, Optional[str]], ...]:
        anchor_op = _anchor_op(op)
        out = [("host", None)]
        for (a_op, engine, mode) in ANCHORS:
            if a_op == anchor_op and engine == "device":
                out.append(("device", mode))
        for mode in UNVERIFIED_MODES.get((anchor_op, "device"), ()):
            with self._lock:
                has_learned = any(
                    k[:3] == (anchor_op, "device", mode) for k in self.learned
                )
            if has_learned or self.include_projections:
                out.append(("device", mode))
        return tuple(out)

    def predict(self, w: Workload) -> Dict[Tuple[str, Optional[str]], float]:
        """Candidate -> predicted wall seconds (dispatch + throughput,
        times any degrade penalty)."""
        if w.op not in _OPS:
            raise InvalidArgumentError(
                f"unknown router op {w.op!r} (one of {_OPS})"
            )
        out: Dict[Tuple[str, Optional[str]], float] = {}
        op = _anchor_op(w.op)
        for engine, mode in self.candidates(w.op):
            nc = (
                max(1, w.mesh_keys * w.mesh_domain)
                if mode == "sharded-megakernel"
                else 1
            )
            rate = self.rate(
                w.op, engine, mode, w.value_kind, w.value_bits, n_chips=nc
            )
            if rate is None or rate <= 0:
                continue
            disp = (
                w.dispatches(mode) * self.dispatch_seconds(engine)
                if engine == "device"
                else 0.0
            )
            cost = disp + w.work_items(engine) / rate
            with self._lock:
                cost *= self.penalty.get((op, engine, mode), 1.0)
            out[(engine, mode)] = cost
        return out

    def state(self) -> dict:
        """JSON-serializable calibration state (the DPF_TPU_ROUTER_CALIB
        file format)."""
        with self._lock:
            return {
                "dispatch_ewma": self.dispatch_ewma,
                "learned": {
                    "|".join(str(p) for p in k): v
                    for k, v in self.learned.items()
                },
                "penalty": {
                    "|".join(str(p) for p in k): v
                    for k, v in self.penalty.items()
                },
            }

    def load_state(self, state: dict) -> None:
        def _untuple(s: str) -> tuple:
            parts = s.split("|")
            return tuple(None if p == "None" else p for p in parts)

        with self._lock:
            if state.get("dispatch_ewma"):
                self.dispatch_ewma = float(state["dispatch_ewma"])
            for k, v in (state.get("learned") or {}).items():
                self.learned[_untuple(k)] = float(v)
            for k, v in (state.get("penalty") or {}).items():
                self.penalty[_untuple(k)] = float(v)


class Router:
    """The front door's decision maker: a CostModel plus the telemetry
    emission and calibration-file plumbing.

    ``calibration`` (default: the ``DPF_TPU_ROUTER_CALIB`` env) names a
    JSON file of learned rates / dispatch EWMA / penalties; it is loaded
    at construction and ``save_calibration()`` writes the current state
    back — how a hardware window's measurements persist into the next
    serving process.
    """

    def __init__(
        self,
        model: Optional[CostModel] = None,
        calibration: Optional[str] = None,
    ):
        self.model = model or CostModel()
        self.calibration = (
            calibration
            if calibration is not None
            else envflags.env_str("DPF_TPU_ROUTER_CALIB") or None
        )
        if self.calibration and os.path.exists(self.calibration):
            try:
                with open(self.calibration) as f:
                    self.model.load_state(json.load(f))
            except (OSError, ValueError):
                pass  # a torn calibration file must never block serving

    def save_calibration(self, path: Optional[str] = None) -> None:
        path = path or self.calibration
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.model.state(), f)
        os.replace(tmp, path)

    def route(self, w: Workload) -> RouteDecision:
        """Picks the cheapest candidate and emits the
        ``decision(source="router")`` record with the predicted costs."""
        costs = self.model.predict(w)
        if not costs:
            raise InvalidArgumentError(
                f"no routable candidate for op {w.op!r}"
            )
        (engine, mode), predicted = min(costs.items(), key=lambda kv: kv[1])
        labeled = {
            (f"{e}/{m}" if m else e): round(c, 6) for (e, m), c in costs.items()
        }
        decision = RouteDecision(engine, mode, predicted, labeled)
        _tm.decision(
            w.op,
            decision.choice,
            "router",
            predicted_ms=round(predicted * 1e3, 3),
            costs_ms={k: round(v * 1e3, 3) for k, v in labeled.items()},
            num_keys=w.num_keys,
            work_items=w.work_items(),
        )
        return decision

    def observe(
        self, w: Workload, engine: str, mode: Optional[str], seconds: float
    ) -> None:
        self.model.observe(w, engine, mode, seconds)

    def observe_dispatch(self, seconds: float) -> None:
        self.model.observe_dispatch(seconds)

    def on_degrade(
        self, op: str, engine: str, mode: Optional[str], reason: str = ""
    ) -> None:
        self.model.on_degrade(op, engine, mode, reason)


def engine_table_predictions(
    router: Optional[Router] = None,
) -> list:
    """(label, measured winner, predicted winner, costs) per engine-table
    row — the roofline CLI's "router predictions vs measured anchors"
    section and the router-pin test share this. The default router pins
    host_threads=1: every engine-table host number was measured at the
    reference-parity single thread."""
    router = router or Router(model=CostModel(host_threads=1), calibration="")
    rows = []
    for label, w, measured in ENGINE_TABLE:
        costs = router.model.predict(w)
        (engine, _mode), _ = min(costs.items(), key=lambda kv: kv[1])
        labeled = {
            (f"{e}/{m}" if m else e): c for (e, m), c in costs.items()
        }
        rows.append((label, measured, engine, labeled))
    return rows
