"""The RPC server: the serving front door behind a socket (ISSUE 10).

One :class:`DpfServer` is one FSS party's network face — the deployment
unit Poplar (S&P 2021) runs two of. It owns a listening socket, a
:class:`~.frontdoor.FrontDoor` (continuous batching + cost-model routing
+ the resilient supervisor), and the process-lifetime telemetry collector
its stats endpoint reads. Per connection: a version handshake, then a
serial request loop — concurrency comes from connections (each client
thread holds one), and the batcher merges across them, which is exactly
the traffic shape continuous batching exists for.

Robustness vocabulary served to clients:

* **deadline propagation** — a request's ``deadline_ms`` arms the
  front-door deadline (shed at admission if already unmeetable, rejected
  at flush if expired queued, and the supervisor's ``deadline_scope``
  bounds every device wait by the remaining budget);
* **backpressure** — admission-control rejections
  (``ResourceExhaustedError``, bounded queue depth) travel as
  ``RESOURCE_EXHAUSTED``, the client's retry-with-backoff signal;
* **graceful drain** — SIGTERM (or :meth:`DpfServer.drain`) stops
  accepting, lets in-flight requests finish, flushes the compatibility
  queues, and stops the front door; with ``journal_dir`` set, full-domain
  chunk journals mean even a SIGKILLed server resumes a re-sent job past
  its verified chunks after restart;
* **health / readiness / stats** — ``T_HEALTH`` answers liveness +
  readiness (draining and a dead batcher worker both report not-ready);
  ``T_STATS`` answers the counter snapshot a soak asserts completeness
  against.

Run one party from the CLI (loopback two-server quickstart in the README)::

    python -m distributed_point_functions_tpu.serving.server \
        --port 9051 --journal-dir /tmp/dpf-a --pir-db demo:12:7
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import socket
import sys
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..utils import telemetry as _tm
from ..utils.errors import (
    DpfError,
    InvalidArgumentError,
    UnavailableError,
)
from . import wire
from .batcher import Request
from .frontdoor import FrontDoor


class DpfServer:
    """One party's RPC server over a :class:`FrontDoor`.

    ``door=None`` constructs one from ``**door_kwargs`` (all
    :class:`FrontDoor` knobs pass through — ``engine``, ``journal_dir``,
    ``max_wait_ms``, ...); a provided door is shared, not owned, and is
    still started/stopped with the server (the batcher worker must run
    for the socket loop to ever answer).

    PIR databases never cross the wire: both parties hold replicas by
    construction, so the server holds them in a name registry
    (:meth:`register_db`) and requests name them.
    """

    def __init__(
        self,
        door: Optional[FrontDoor] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_body: int = wire.DEFAULT_MAX_BODY,
        frame_timeout: float = 60.0,
        **door_kwargs,
    ):
        self.door = door if door is not None else FrontDoor(**door_kwargs)
        self.host = host
        self._port = port
        self.max_body = max_body
        #: budget for one in-progress frame (read or write) once its
        #: first byte moved — NOT the idle wait, which polls at 0.5 s.
        #: A peer stalled mid-frame past this is dead: drop it.
        self.frame_timeout = frame_timeout
        self._dbs: Dict[str, np.ndarray] = {}
        #: heavy-hitter streams by name (ISSUE 15) — registered before
        #: start(); the server owns their lifecycle (the leader's advance
        #: worker starts/stops with the socket loop).
        self._streams: Dict[str, object] = {}
        self._objs: "collections.OrderedDict[tuple, object]" = (
            collections.OrderedDict()
        )
        self._objs_lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._inflight = 0
        self._served = 0
        self._inflight_lock = threading.Lock()
        self._draining = False
        self._stopped = threading.Event()
        self._collector = None

    # -- registry ----------------------------------------------------------
    def register_db(self, name: str, db) -> None:
        """Registers a PIR database replica under `name`. One array object
        per name for the server's lifetime — request merging and the warm
        cache both key on the object's identity."""
        self._dbs[name] = np.asarray(db)

    def register_stream(self, stream) -> None:
        """Registers a heavy-hitter stream (ISSUE 15: a
        :class:`~.streaming.HeavyHitterStream`) — its ``hh_ingest`` /
        ``hh_snapshot`` / ``hh_aggregate`` ops become servable, its
        stats ride the stats/health frames, and its lifecycle (journal
        reload, the leader's advance worker) follows the server's."""
        self._streams[stream.config.name] = stream
        if self._listener is not None:
            stream.start()

    def _stream_for(self, name: str):
        stream = self._streams.get(name)
        if stream is None:
            raise InvalidArgumentError(
                f"stream {name!r} is not registered on this server "
                f"(registered: {sorted(self._streams)})"
            )
        return stream

    # -- lifecycle ---------------------------------------------------------
    @property
    def port(self) -> int:
        """The bound port (resolves ``port=0`` after :meth:`start`)."""
        return self._port

    @property
    def ready(self) -> bool:
        """Readiness: accepting connections, not draining, and the
        batcher worker is alive (a dead worker serves nothing)."""
        return (
            self._listener is not None
            and not self._draining
            and not self._stopped.is_set()
            and self.door.batcher.dead is None
        )

    def start(self) -> "DpfServer":
        if self._listener is not None:
            return self
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self.host, self._port))
        listener.listen(64)
        listener.settimeout(0.25)  # poll the stop flag
        self._listener = listener
        self._port = listener.getsockname()[1]
        self.door.start()
        for stream in self._streams.values():
            stream.start()
        self._collector = _tm.attach_collector()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="dpf-rpc-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful drain: stop accepting, let in-flight requests finish
        (bounded by `timeout`), flush the compatibility queues, stop the
        front door. Idempotent; the SIGTERM path."""
        if self._draining:
            return
        self._draining = True
        _tm.counter("rpc.server.drains")
        self._close_listener()
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        # stop() flushes everything still queued and joins the worker —
        # with journaling on, full-domain chunks are already durable (the
        # journal appends per verified chunk DURING execution, which is
        # why even SIGKILL — which never reaches this line — resumes).
        self.door.stop()

    def stop(self, drain_timeout: float = 5.0) -> None:
        self.drain(drain_timeout)
        for stream in self._streams.values():
            stream.stop()
        self._stopped.set()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        if self._collector is not None:
            _tm.detach_collector(self._collector)
            self._collector = None

    def __enter__(self) -> "DpfServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _close_listener(self) -> None:
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None

    # -- socket loops ------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped.is_set() and not self._draining:
            listener = self._listener
            if listener is None:
                return
            try:
                conn, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                # Closed under us (drain/stop — the flags say so) ends
                # the loop; anything else is a transient accept error
                # (ECONNABORTED: client reset mid-handshake; EMFILE
                # under churn) and must NOT permanently stop accepting
                # while `ready` still reports True.
                if (
                    self._stopped.is_set()
                    or self._draining
                    or self._listener is None
                ):
                    return
                _tm.counter("rpc.server.accept_errors")
                time.sleep(0.05)  # EMFILE: don't spin
                continue
            # Replies (and mid-frame reads, via _read_frame_poll) get the
            # frame budget; the idle wait polls the stop flag at 0.5 s.
            conn.settimeout(self.frame_timeout)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="dpf-rpc-conn", daemon=True,
            ).start()

    def _read_frame_poll(self, sock: socket.socket) -> Optional[wire.Frame]:
        """One frame, polling the stop flag while the connection is IDLE.
        The 0.5 s poll applies only to the MSG_PEEK wait for a frame's
        first byte — once a frame starts arriving, the socket switches to
        ``frame_timeout`` for the whole frame (and stays there for the
        handler's reply writes), so a request that stalls mid-frame for
        >0.5 s (slow uplink, GC pause, multi-MB key payload) is NOT torn
        apart by the poll interval: `_recv_exact` discards consumed bytes
        on timeout, and a retry would parse mid-body bytes as a header.
        Returns None on orderly EOF or shutdown. check_version=False:
        version problems are answered with FAILED_PRECONDITION, not a
        silent drop."""
        while True:
            if self._stopped.is_set():
                return None
            sock.settimeout(0.5)
            try:
                first = sock.recv(1, socket.MSG_PEEK)
            except socket.timeout:
                continue
            if not first:
                return None
            sock.settimeout(self.frame_timeout)
            return wire.read_frame(
                sock, max_body=self.max_body, check_version=False
            )

    def _serve_conn(self, sock: socket.socket) -> None:
        try:
            self._conn_loop(sock)
        except (wire.FrameError, ConnectionError, OSError):
            pass  # framing violation or torn connection: drop it
        finally:
            with self._conns_lock:
                self._conns.discard(sock)
            try:
                sock.close()
            except OSError:
                pass

    def _conn_loop(self, sock: socket.socket) -> None:
        # Handshake: the first frame must be a version-matched T_HELLO.
        hello = self._read_frame_poll(sock)
        if hello is None:
            return
        if hello.version != wire.PROTO_VERSION or hello.ftype != wire.T_HELLO:
            _tm.counter("rpc.server.handshake_rejected")
            wire.write_frame(
                sock, wire.T_ERROR, hello.request_id,
                wire.encode_error_body(
                    wire.FAILED_PRECONDITION,
                    f"handshake rejected: got frame type {hello.ftype} "
                    f"version {hello.version}, this server speaks "
                    f"T_HELLO version {wire.PROTO_VERSION}",
                ),
            )
            return
        wire.write_frame(
            sock, wire.T_HELLO_OK, hello.request_id,
            json.dumps({"version": wire.PROTO_VERSION}).encode(),
        )
        while not self._stopped.is_set():
            frame = self._read_frame_poll(sock)
            if frame is None:
                return
            if frame.version != wire.PROTO_VERSION:
                raise wire.FrameError(
                    f"frame version {frame.version} after a version-"
                    f"{wire.PROTO_VERSION} handshake"
                )
            if frame.ftype == wire.T_HEALTH:
                wire.write_frame(
                    sock, wire.T_HEALTH_OK, frame.request_id,
                    json.dumps(self._health()).encode(),
                )
            elif frame.ftype == wire.T_STATS:
                wire.write_frame(
                    sock, wire.T_STATS_OK, frame.request_id,
                    json.dumps(self._stats()).encode(),
                )
            elif frame.ftype == wire.T_REQUEST:
                self._handle_request(sock, frame)
            else:
                raise wire.FrameError(
                    f"unexpected frame type {frame.ftype} from a client"
                )

    # -- endpoints ---------------------------------------------------------
    def _health(self) -> dict:
        dead = self.door.batcher.dead
        with self._inflight_lock:
            inflight, served = self._inflight, self._served
        return {
            "status": "draining" if self._draining else "serving",
            "ready": self.ready,
            "pending": self.door.batcher.pending(),
            # ISSUE 14: the fleet proxy's least-loaded signal — requests
            # being handled right now plus per-op queue depths. New keys
            # in the existing body; pre-fleet clients never read them.
            "inflight": inflight,
            "served": served,
            "queues": self.door.batcher.queue_depths(),
            "worker_dead": (
                f"{type(dead).__name__}: {dead}" if dead else None
            ),
            # ISSUE 15: per-stream window/ingest state
            # (wire.STATS_STREAM_KEYS) — additive keys, old clients
            # never read them.
            "streams": {
                name: s.stats_fields() for name, s in self._streams.items()
            },
            # ISSUE 20: QoS/autoscale signals (wire.STATS_QOS_KEYS) —
            # per-op arrival-rate EWMAs feed the autoscaler's backlog
            # forecast, per-tenant counters its fairness dashboard.
            "rates": self.door.batcher.arrival_rates(),
            "tenants": self.door.batcher.tenant_stats(),
            "pid": os.getpid(),
        }

    def _stats(self) -> dict:
        if self._collector is None:
            return {}
        snap = self._collector.snapshot()
        with self._inflight_lock:
            inflight, served = self._inflight, self._served
        # The counter/aggregate view only: the event ring is an operator
        # debugging surface, not a polling payload. The ISSUE 14 keys
        # (wire.STATS_FLEET_KEYS) are additive: per-op queue depth +
        # in-flight count feed the fleet proxy's routing, the warm-cache
        # digest inventory its affinity observability.
        return {
            "wall_seconds": snap["wall_seconds"],
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "decisions_by_source": snap["decisions_by_source"],
            "integrity_by_kind": snap["integrity_by_kind"],
            "queues": self.door.batcher.queue_depths(),
            "inflight": inflight,
            "served": served,
            "warm": self.door.cache.inventory(),
            "streams": {
                name: s.stats_fields() for name, s in self._streams.items()
            },
            "rates": self.door.batcher.arrival_rates(),
            "tenants": self.door.batcher.tenant_stats(),
        }

    # -- request handling --------------------------------------------------
    def _handle_request(self, sock: socket.socket, frame: wire.Frame) -> None:
        op = "?"
        t0 = time.perf_counter()
        with self._inflight_lock:
            self._inflight += 1
        try:
            # Payload-level garbage (inside a well-framed request) is the
            # client's problem, not the stream's: answer INVALID_ARGUMENT
            # and keep the connection, unlike frame-level garbage which
            # has no resync point and drops it.
            try:
                op, deadline_ms, payload, tenant = wire.decode_request_body(
                    frame.body
                )
                _tm.counter("rpc.server.requests", op=op)
                if tenant:
                    _tm.counter("rpc.server.tenant_requests", op=tenant)
                if self._draining:
                    raise UnavailableError(
                        "UNAVAILABLE: server is draining — retry another "
                        "replica"
                    )
                if op in ("hh_snapshot", "hh_aggregate"):
                    # Streaming reads/exchanges (ISSUE 15) are served by
                    # the window manager directly — no engine choice, no
                    # batch merging; the manager's own lock serializes
                    # window state. They answer on the handler thread
                    # like health/stats, inside the shared error
                    # taxonomy (an incomplete window's UNAVAILABLE is a
                    # client retry signal).
                    arrays = self._serve_stream_op(op, payload)
                    wire.write_frame(
                        sock, wire.T_RESPONSE, frame.request_id,
                        wire.encode_result_arrays(arrays),
                    )
                    _tm.observe(
                        "rpc.server.request_ms",
                        (time.perf_counter() - t0) * 1e3, op=op,
                    )
                    return
                request = self._build_request(op, payload).with_tenant(
                    tenant
                )
            except (DpfError, ConnectionError, OSError):
                raise
            except Exception as exc:
                raise InvalidArgumentError(
                    f"malformed {op} request payload: "
                    f"{type(exc).__name__}: {exc}"
                )
            if deadline_ms:
                request.with_deadline(deadline_ms / 1e3)
            future = self.door.submit(request)
            # The future must resolve: the flush either answers or
            # rejects every request, and an armed deadline rejects at
            # flush. The wait timeout is a backstop for an unarmed
            # request on a wedged path, not the deadline mechanism.
            timeout = (deadline_ms / 1e3 + 5.0) if deadline_ms else None
            try:
                value = future.result(timeout=timeout)
            except TimeoutError:
                raise UnavailableError(
                    f"DEADLINE_EXCEEDED: {op} request not served within "
                    f"its {deadline_ms} ms deadline (+5 s grace)"
                )
            arrays = value if isinstance(value, list) else [np.asarray(value)]
            wire.write_frame(
                sock, wire.T_RESPONSE, frame.request_id,
                wire.encode_result_arrays(arrays),
            )
            _tm.observe(
                "rpc.server.request_ms", (time.perf_counter() - t0) * 1e3,
                op=op,
            )
        except (ConnectionError, OSError, wire.FrameError):
            raise  # the connection itself failed: nothing left to answer
        except BaseException as exc:  # noqa: BLE001 — every failure answers
            code = wire.status_for_exception(exc)
            _tm.counter("rpc.server.errors", op=op)
            _tm.counter(f"rpc.server.status_{code}", op=op)
            wire.write_frame(
                sock, wire.T_ERROR, frame.request_id,
                wire.encode_error_body(code, str(exc)),
            )
            if not isinstance(exc, DpfError):
                raise  # a library bug: answered INTERNAL, but still loud
        finally:
            with self._inflight_lock:
                self._inflight -= 1
                self._served += 1

    #: bound on the crypto-object cache below. The keys are
    #: client-controlled (parameter bytes, interval lists), so an
    #: unbounded dict would let a config-sweeping client grow server
    #: memory forever; LRU keeps the steady-state win (a service serves
    #: few distinct configs) with a hard ceiling.
    MAX_CACHED_OBJS = 128

    def _cached(self, key: tuple, make):
        with self._objs_lock:
            obj = self._objs.get(key)
            if obj is None:
                obj = self._objs[key] = make()
            else:
                self._objs.move_to_end(key)
            while len(self._objs) > self.MAX_CACHED_OBJS:
                self._objs.popitem(last=False)
            return obj

    def _dpf(self, parameters):
        """The DPF for a parameter list, cached by its serialized bytes —
        request merging keys on the validator's params signature, but the
        batcher also requires one OBJECT per logical DPF for the warm
        tiers, and reconstructing per request would defeat both."""
        from ..core.dpf import DistributedPointFunction
        from ..protos import serialization

        key = ("dpf",) + tuple(
            serialization.encode_dpf_parameters(p) for p in parameters
        )
        if len(parameters) > 1:
            make = lambda: DistributedPointFunction.create_incremental(
                list(parameters)
            )
        else:
            make = lambda: DistributedPointFunction.create(parameters[0])
        return self._cached(key, make)

    def _serve_stream_op(self, op: str, payload: bytes):
        """The streaming read/exchange ops (ISSUE 15), answered inline."""
        if op == "hh_snapshot":
            name, since = wire.decode_hh_snapshot(payload)
            stream = self._stream_for(name)
            return wire.json_result_arrays(
                stream.snapshot(since_generation=since)
            )
        stream_name, generation, batch_ids, plan, extras = (
            wire.decode_hh_aggregate(payload)
        )
        stream = self._stream_for(stream_name)
        agg = stream.aggregate(
            generation, batch_ids, plan,
            epoch=extras["epoch"], publish=extras["publish"],
            audit=extras["audit"], quarantine=extras["quarantine"],
        )
        return [np.asarray(agg, dtype=np.uint64)]

    def _build_request(self, op: str, payload: bytes) -> Request:
        if op == "full_domain":
            parameters, keys, hl = wire.decode_full_domain(payload)
            return Request.full_domain(self._dpf(parameters), keys, hl)
        if op == "evaluate_at":
            parameters, keys, points, hl = wire.decode_evaluate_at(payload)
            return Request.evaluate_at(
                self._dpf(parameters), keys, points, hl
            )
        if op == "dcf":
            lds, value_type, keys, xs = wire.decode_dcf(payload)
            from ..dcf.dcf import DistributedComparisonFunction
            from ..protos import serialization

            dcf = self._cached(
                ("dcf", serialization.serialize_dcf_parameters(
                    lds, value_type
                )),
                lambda: DistributedComparisonFunction.create(lds, value_type),
            )
            return Request.dcf(dcf, keys, xs)
        if op == "mic":
            lgs, intervals, key, xs = wire.decode_mic(payload)
            from ..gates.mic import MultipleIntervalContainmentGate

            gate = self._cached(
                ("mic", lgs, tuple(tuple(iv) for iv in intervals)),
                lambda: MultipleIntervalContainmentGate.create(
                    lgs, intervals
                ),
            )
            return Request.mic(gate, key, xs)
        if op == "pir":
            parameters, keys, db_name = wire.decode_pir(payload)
            db = self._dbs.get(db_name)
            if db is None:
                raise InvalidArgumentError(
                    f"PIR database {db_name!r} is not registered on this "
                    f"server (registered: {sorted(self._dbs)})"
                )
            return Request.pir(self._dpf(parameters), keys, db)
        if op == "hierarchical":
            parameters, keys, plan, group = wire.decode_hierarchical(payload)
            return Request.hierarchical(
                self._dpf(parameters), keys, plan, group
            )
        if op == "hh_ingest":
            # Streaming ingestion (ISSUE 15): rides the batcher as its
            # own op class (the fair-flush ordering — an ingest flood
            # cannot starve the query ops), journaled-then-acknowledged
            # inside the flush. Backpressure is checked at submit
            # (FrontDoor -> stream.check_admission): past the pending-
            # window bound the client sees RESOURCE_EXHAUSTED.
            parameters, blobs, stream_name, batch_id, flush = (
                wire.decode_hh_ingest(payload)
            )
            return Request.hh_ingest(
                self._stream_for(stream_name), parameters, blobs, batch_id,
                flush=flush,
            )
        if op == "keygen":
            # Dealer offload (ISSUE 13): this server generates BOTH
            # parties' keys from the client's points/values — the BGI
            # preprocessing-dealer role. The response is the serialized
            # key-blob stream (wire.keygen_result_arrays' layout), which
            # rides the generic result-array path below.
            parameters, alphas, betas = wire.decode_keygen(payload)
            return Request.keygen(self._dpf(parameters), alphas, betas)
        raise InvalidArgumentError(f"unservable op {op!r}")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _parse_pir_db(spec: str):
    """NAME:LOG_DOMAIN:SEED[:WIDTH_WORDS] — a deterministic random
    database both replicas can generate identically from the shared
    spec (the quickstart / soak form; production servers load real
    data through register_db)."""
    parts = spec.split(":")
    if len(parts) not in (3, 4):
        raise argparse.ArgumentTypeError(
            f"--pir-db {spec!r}: want NAME:LOG_DOMAIN:SEED[:WIDTH_WORDS]"
        )
    name, lds, seed = parts[0], int(parts[1]), int(parts[2])
    width = int(parts[3]) if len(parts) == 4 else 4
    rng = np.random.default_rng(seed)
    db = rng.integers(0, 2**32, size=(1 << lds, width), dtype=np.uint32)
    return name, db


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0, help="0 = ephemeral")
    ap.add_argument("--engine", default="auto",
                    choices=("auto", "host", "device"))
    ap.add_argument("--mode", default=None)
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--width-target", type=int, default=64)
    ap.add_argument("--max-queue-depth", type=int, default=1024)
    # Orca scheduling knobs (ISSUE 14): fair round-robin across op
    # classes is the default; --fifo is the starvation baseline arm.
    ap.add_argument("--fifo", action="store_true",
                    help="disable fair cross-op flush ordering (baseline)")
    # ISSUE 20: adaptive wait is the default now that tenant quotas
    # bound its failure mode. --adaptive-wait stays as a no-op so
    # pre-20 launch scripts (and ReplicaPool server_args) keep working.
    ap.add_argument("--adaptive-wait", action="store_true",
                    help="width-aware batch-deadline adaptation "
                    "(default since ISSUE 20; flag kept for "
                    "compatibility)")
    ap.add_argument("--no-adaptive-wait", action="store_true",
                    help="disable width-aware batch-deadline adaptation "
                    "(fixed max-wait baseline)")
    ap.add_argument("--priorities", default=None, metavar="OP=N[,OP=N]",
                    help="op priority classes, lower flushes first "
                    "(e.g. evaluate_at=0,full_domain=1)")
    # ISSUE 20: multi-tenant QoS knobs. Quotas bound a tenant's pending
    # requests (admission control); priorities order flushes within an
    # op class; both key on the wire-envelope tenant token.
    ap.add_argument("--tenant-quotas", default=None,
                    metavar="TENANT=N[,TENANT=N]",
                    help="per-tenant pending-request admission quotas "
                    "(0 = unbounded; e.g. acme=64,probe=8)")
    ap.add_argument("--tenant-default-quota", type=int, default=0,
                    help="admission quota for tenants without an explicit "
                    "--tenant-quotas entry (0 = unbounded)")
    ap.add_argument("--tenant-priorities", default=None,
                    metavar="TENANT=N[,TENANT=N]",
                    help="tenant priority classes, lower flushes first "
                    "within each op class")
    ap.add_argument("--key-chunk", type=int, default=None)
    ap.add_argument("--journal-dir", default=None,
                    help="full-domain chunk-journal directory (crash resume)")
    ap.add_argument("--pir-db", type=_parse_pir_db, action="append",
                    default=[], metavar="NAME:LOG_DOMAIN:SEED[:WIDTH]")
    # Streaming heavy hitters (ISSUE 15). --stream registers a bitwise
    # Int(64) stream; --stream-peer names the OTHER party's endpoint and
    # makes this server the aggregation leader (it drives window
    # advances + publishes); without it the server is the follower
    # (serves hh_aggregate). Streams require --journal-dir: journaled
    # exactly-once window accounting is the tier's contract.
    ap.add_argument("--stream", action="append", default=[],
                    metavar="NAME:BITS:BPL:THRESHOLD:WINDOW"
                    "[:PENDING[:audit]]",
                    help="register a heavy-hitter stream (requires "
                    "--journal-dir or --stream-journal-root)")
    ap.add_argument("--stream-peer", default=None, metavar="HOST:PORT",
                    help="peer party endpoint: this server becomes the "
                    "stream aggregation leader")
    # ISSUE 16: leader failover + fleet-sheltered streams.
    ap.add_argument("--stream-follower-of", default=None,
                    metavar="HOST:PORT",
                    help="peer party endpoint, but boot as the FOLLOWER: "
                    "the failover shape — this server promotes itself by "
                    "lease when the leader's lease expires (requires "
                    "--stream-lease-root)")
    ap.add_argument("--stream-lease-root", default=None, metavar="DIR",
                    help="role-lease directory shared by both parties: "
                    "epoch-numbered TTL-renewed leader lease (failover + "
                    "zombie fencing)")
    ap.add_argument("--stream-lease-ttl", type=float, default=2.0,
                    help="lease TTL seconds (renewed at ttl/3; a dead "
                    "holder is superseded within ~ttl)")
    ap.add_argument("--stream-journal-root", default=None, metavar="DIR",
                    help="SHARED stream journal volume (fleet-sheltered "
                    "streams): replicas arbitrate per-stream ownership "
                    "by lease inside the stream directory, so a replica "
                    "kill re-homes the stream to a survivor resuming "
                    "from the same journals")
    ap.add_argument("--ready-file", default=None,
                    help="write '<port>\\n' here once listening (the "
                    "subprocess-orchestration handshake)")
    ap.add_argument("--platform", default=None, help="cpu/tpu override")
    args = ap.parse_args(argv)

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    try:  # the repo-local persistent compile cache: restarts skip XLA work
        cache = os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
            ".jax_cache",
        )
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:
        pass

    def _parse_class_map(flag: str, text):
        """KEY=N[,KEY=N] maps (--priorities and the tenant knobs share
        the grammar); ap.error exits with the usage message on a bad
        entry."""
        if not text:
            return None
        out = {}
        for part in text.split(","):
            if not part:
                continue
            key, sep, val = part.partition("=")
            bad = not sep
            if not bad:
                try:
                    out[key] = int(val)
                except ValueError:
                    bad = True
            if bad:
                ap.error(
                    f"{flag} entry {part!r}: want KEY=N (e.g. "
                    "evaluate_at=0,full_domain=1)"
                )
        return out

    priorities = _parse_class_map("--priorities", args.priorities)
    tenant_quotas = _parse_class_map("--tenant-quotas", args.tenant_quotas)
    tenant_priorities = _parse_class_map(
        "--tenant-priorities", args.tenant_priorities
    )
    server = DpfServer(
        host=args.host, port=args.port,
        engine=args.engine, mode=args.mode,
        max_wait_ms=args.max_wait_ms, width_target=args.width_target,
        max_queue_depth=args.max_queue_depth, key_chunk=args.key_chunk,
        journal_dir=args.journal_dir,
        fair=not args.fifo, adaptive_wait=not args.no_adaptive_wait,
        priorities=priorities,
        tenant_quotas=tenant_quotas,
        tenant_default_quota=args.tenant_default_quota,
        tenant_priorities=tenant_priorities,
    )
    for name, db in args.pir_db:
        server.register_db(name, db)
    if args.stream:
        from .streaming import HeavyHitterStream, parse_stream_spec

        if args.stream_peer and args.stream_follower_of:
            ap.error("--stream-peer and --stream-follower-of are "
                     "mutually exclusive (leader vs failover-follower)")
        if args.stream_follower_of and not args.stream_lease_root:
            ap.error("--stream-follower-of requires --stream-lease-root "
                     "(the role is arbitrated by lease)")
        if args.stream_journal_root and (
            args.stream_peer or args.stream_follower_of
            or args.stream_lease_root
        ):
            ap.error("--stream-journal-root (fleet-sheltered follower "
                     "replica) excludes --stream-peer/"
                     "--stream-follower-of/--stream-lease-root")
        if not args.journal_dir and not args.stream_journal_root:
            ap.error("--stream requires --journal-dir (durable windows) "
                     "or --stream-journal-root (shared volume)")
        peer_spec = args.stream_peer or args.stream_follower_of
        peer = None
        if peer_spec:
            host_part, _, port_part = peer_spec.rpartition(":")
            peer = (host_part or "127.0.0.1", int(port_part))
        role = "follower" if args.stream_follower_of else None
        owner = f"pid{os.getpid()}:{args.port or 0}"
        for spec in args.stream:
            server.register_stream(HeavyHitterStream(
                parse_stream_spec(spec),
                args.stream_journal_root or args.journal_dir,
                peer=peer,
                role=role,
                lease_dir=args.stream_lease_root,
                lease_ttl=args.stream_lease_ttl,
                owner=owner,
                shared=args.stream_journal_root is not None,
            ))
    server.start()
    print(
        f"dpf-server: pid={os.getpid()} listening on "
        f"{args.host}:{server.port} backend={jax.default_backend()}",
        file=sys.stderr, flush=True,
    )
    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{server.port}\n")
        os.replace(tmp, args.ready_file)

    import signal

    stop_evt = threading.Event()

    def _sigterm(_signo, _frame):
        print("dpf-server: SIGTERM — draining", file=sys.stderr, flush=True)
        stop_evt.set()

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigterm)
    try:
        while not stop_evt.wait(0.25):
            pass
    finally:
        server.stop()
        print("dpf-server: stopped", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
