"""Streaming heavy hitters: crash-safe windowed ingestion as a live
two-server service (ISSUE 15).

Poplar's deployment shape (PAPERS.md: Boneh et al.) is millions of
clients *streaming* key uploads while two non-colluding servers
aggregate. This module is that tier's window manager: arriving key
batches accumulate into rolling **window generations**, each closed
window runs the level-by-level prefix-tree advance (the resumable
``BatchedContext`` the hierarchical journal already checkpoints), counts
reconstruct through a leader→peer aggregate-share exchange (the only
server-to-server communication — two vectors per level, exactly the
batch demo's), survivors prune by threshold, and popular prefixes
publish continuously.

**The durability contract is the point** (the robustness headline — a
write-heavy ingestion service that loses a window of client keys on a
crash, or double-counts them on resume, is worse than no service):

* every accepted ingest batch is journaled — fsync'd into the open
  window generation's :class:`~..ops.supervisor.ChunkJournal` — *before*
  it is acknowledged; a torn tail from a mid-append kill reads as
  "never accepted", which is exactly what the client believes (its ack
  never arrived; the retry re-ingests);
* batches carry a client-chosen **batch id**: a retry of an
  already-journaled batch (the ack lost to a crash) is acknowledged
  with its original generation and never double-counted;
* window advances commit per level through the same verified-chunk
  journal (``ctx_record`` state + reconstructed counts), fingerprinted
  by (stream, generation, membership digest): a resumed window replays
  verified levels, and a generation whose membership no longer matches
  its fingerprint **starts clean instead of merging stale counts**;
* backpressure is explicit: past ``max_pending_windows`` closed-but-
  unpublished windows, ingests are refused with
  ``RESOURCE_EXHAUSTED`` — the PR 10 client retry budget already treats
  that as "later, not never";
* published windows **rotate** their journals (compacted into one
  ``retired.jsonl`` line, then unlinked) so a long-lived server's disk
  does not grow one window-sized file per generation (the PR 10
  fingerprint-journal lesson, applied from day one, with a counter).

Roles: the party whose stream is constructed with a ``peer`` endpoint
is the **aggregation leader** — it drives each window's advance,
fetching the peer party's aggregate share vector per level over the
existing RPC client (``hh_aggregate``), reconstructing counts (the
published output; nothing beyond the protocol's output is revealed),
and publishing. The peer (the **follower**) serves ``hh_aggregate``
from its own journaled window state, fast-forwarding a freshly
restarted window through the request's level trail deterministically.
Window *membership* is the leader's declaration (batch ids); a follower
still missing a batch answers ``UNAVAILABLE`` and the leader retries —
clients upload each batch to both parties, so delivery converges.

Host engine everywhere by default (``engine="host"``: the native AES
advance, zero device programs — pinned); ``engine="device"`` routes each
advance through :func:`~..ops.supervisor.advance_level_robust`, so the
hierkernel window advance stays staged-for-tunnel behind the same mode
plumbing as every kernel since round 5.

**Failover & robustness (ISSUE 16)** — three coupled layers on top:

* **leader failover by lease** (``lease_dir=``): the role is no longer
  fixed at construction — an epoch-numbered TTL-renewed
  :class:`~.lease.StreamLease` file arbitrates it. The leader renews
  from its lease watcher; the follower watches the same file and, when
  the lease expires, bumps the epoch, flips role and drives the advance
  itself. Every ``hh_aggregate`` leg carries the sender's epoch, so a
  *zombie* ex-leader's stale requests are rejected with
  ``FAILED_PRECONDITION`` — fenced, never merged. The one state a
  follower lacks (the published log) is closed two ways: each publish
  record replicates to the follower as a final per-window
  ``hh_aggregate`` leg BEFORE the window's journals rotate, and a
  freshly promoted leader *reconciles* (pulls the peer's published log)
  before its first advance, so a crash between publish and replication
  neither loses nor double-publishes a window — membership is filtered
  against the union of published batch ids at advance time;
* **fleet-sheltered streams** (``shared=True`` / server
  ``--stream-journal-root``): replicas behind the PR 14 FleetProxy share
  one journal volume, and a per-stream *ownership* lease inside the
  stream directory guarantees exactly one replica loads/advances it.
  A replica SIGKILL re-homes the stream to a survivor that acquires the
  lease, reloads the same journals through the existing
  fingerprint/resume machinery, and picks up mid-window — stream
  handoff is journal-directory handoff;
* **malicious-client audit** (``audit=True`` in the config / spec): a
  per-batch share-consistency check before a batch enters window
  membership — both parties reconstruct the batch's level-0 aggregate,
  which for an honest batch of n one-hot keys sums to exactly n with no
  cell above n. A failing batch is quarantined by batch id on BOTH
  parties (durable ``retired.jsonl`` line, ``hh.quarantined`` counter,
  IntegrityEvent), bounding a poisoning client's damage to its own
  rejected batch. (This bounds per-batch mass; full malicious security
  à la Poplar would add the sketching layer on top.)
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.params import DpfParameters
from ..core.value_types import Int
from ..protos import serialization
from ..utils import telemetry as _tm
from ..utils.errors import (
    DataLossError,
    FailedPreconditionError,
    InvalidArgumentError,
    ResourceExhaustedError,
    UnavailableError,
)
from .lease import StreamLease


@dataclasses.dataclass
class StreamConfig:
    """One heavy-hitter stream's public configuration (shared by both
    parties and by clients — the ingest op validates parameters against
    it, so a misconfigured client fails loudly, not with garbage
    counts)."""

    name: str
    parameters: List[DpfParameters]  # the incremental hierarchy
    threshold: int
    #: accepted keys that close the open window (the generation size).
    window_keys: int = 64
    #: closed-but-unpublished windows admitted before ingests are refused
    #: with RESOURCE_EXHAUSTED (the backpressure bound).
    max_pending_windows: int = 2
    group: int = 16
    #: "host" (native AES advance, zero device programs) or "device"
    #: (the robust hierarchical chain; mode= below picks the kernel).
    engine: str = "host"
    #: device advance mode (None = env default; "hierkernel" is the
    #: staged-for-tunnel single-program window advance).
    mode: Optional[str] = None
    #: per-batch share-consistency audit before window membership
    #: (ISSUE 16): a batch whose level-0 aggregate does not reconstruct
    #: to one-hot mass on BOTH parties is quarantined, not counted.
    audit: bool = False

    def __post_init__(self):
        if not self.name or not re.fullmatch(r"[\w.-]+", self.name):
            raise InvalidArgumentError(
                f"stream name {self.name!r} must be a non-empty "
                "filesystem-safe token"
            )
        if not self.parameters:
            raise InvalidArgumentError("a stream needs >= 1 hierarchy level")
        bits = None
        for p in self.parameters:
            if not isinstance(p.value_type, Int) or p.value_type.bitsize > 64:
                raise InvalidArgumentError(
                    "stream levels must use additive Int(<=64) value "
                    "types (counts are share sums mod 2^bits)"
                )
            if bits is not None and p.value_type.bitsize != bits:
                raise InvalidArgumentError(
                    "stream levels must share one value type"
                )
            bits = p.value_type.bitsize
        if self.parameters[-1].log_domain_size > 62:
            raise InvalidArgumentError(
                "stream domains are bounded at 62 bits (uint64 candidate "
                "bookkeeping)"
            )
        if self.threshold < 1 or self.window_keys < 1:
            raise InvalidArgumentError(
                "threshold and window_keys must be >= 1"
            )
        if self.max_pending_windows < 1:
            raise InvalidArgumentError("max_pending_windows must be >= 1")
        if self.engine not in ("host", "device"):
            raise InvalidArgumentError(
                f"engine must be 'host' or 'device', got {self.engine!r}"
            )

    @property
    def value_bits(self) -> int:
        return self.parameters[-1].value_type.bitsize

    @classmethod
    def bitwise(
        cls, name: str, bits: int, bits_per_level: int, threshold: int, **kw
    ) -> "StreamConfig":
        """The heavy-hitters demo shape: `bits`-bit values, one hierarchy
        level per `bits_per_level` bits, Int(64) counts."""
        params = [
            DpfParameters(lds, Int(64))
            for lds in range(bits_per_level, bits + 1, bits_per_level)
        ]
        return cls(name=name, parameters=params, threshold=threshold, **kw)


def parse_stream_spec(spec: str) -> StreamConfig:
    """CLI form
    NAME:BITS:BITS_PER_LEVEL:THRESHOLD:WINDOW_KEYS[:PENDING[:audit]]
    — the deterministic two-terminal quickstart shape (production
    deployments construct StreamConfig directly). The trailing literal
    ``audit`` token switches the per-batch share-consistency audit on."""
    parts = spec.split(":")
    if len(parts) not in (5, 6, 7):
        raise InvalidArgumentError(
            f"--stream {spec!r}: want "
            "NAME:BITS:BITS_PER_LEVEL:THRESHOLD:WINDOW_KEYS"
            "[:PENDING[:audit]]"
        )
    kw = {}
    if len(parts) >= 6:
        kw["max_pending_windows"] = int(parts[5])
    if len(parts) == 7:
        if parts[6] != "audit":
            raise InvalidArgumentError(
                f"--stream {spec!r}: the 7th field must be the literal "
                f"'audit', got {parts[6]!r}"
            )
        kw["audit"] = True
    return StreamConfig.bitwise(
        parts[0], int(parts[1]), int(parts[2]), int(parts[3]),
        window_keys=int(parts[4]), **kw,
    )


class _Window:
    """One ingest generation: the durable unit of window accounting. On
    the leader, generations ARE the advance windows; on the follower they
    are arrival buckets (the leader's membership declaration is what
    defines its windows there)."""

    __slots__ = (
        "generation", "journal", "batch_ids", "keys", "shas", "keys_total",
        "closed", "next_index", "first_ingest_at", "closed_at",
        "advance_started",
    )

    def __init__(self, generation: int, journal):
        self.generation = generation
        self.journal = journal
        self.batch_ids: List[str] = []
        self.keys: Dict[str, list] = {}
        self.shas: Dict[str, str] = {}
        self.keys_total = 0
        self.closed = False
        #: dealer-plane accounting (ISSUE 19): the feed phase (first
        #: ingest -> close) is keygen-bound by design — clients generate
        #: every uploaded key — so the publish record turns that comment
        #: into a measured share. None on crash-recovered windows (the
        #: wall clocks died with the process).
        self.first_ingest_at: Optional[float] = None
        self.closed_at: Optional[float] = None
        self.advance_started: Optional[float] = None
        #: the next ChunkJournal record index — counts every journaled
        #: entry, including quarantined batches the reload skips, so a
        #: live append never collides with a skipped index.
        self.next_index = 0


class _PeerWindow:
    """Follower-side state of one leader-declared window: the resumable
    advance context plus the journaled per-level trail."""

    __slots__ = (
        "generation", "batch_ids", "ctx", "journal", "levels",
        "consumed_logged",
    )

    def __init__(self, generation: int, batch_ids: List[str], ctx, journal):
        self.generation = generation
        self.batch_ids = list(batch_ids)
        self.ctx = ctx
        self.journal = journal
        self.levels: Dict[int, dict] = {}
        #: True once this window's "consumed" retired.jsonl line is
        #: durable — written the moment the FINAL hierarchy level is
        #: served, so a follower restart between serving a window and
        #: the leader's next-generation request cannot orphan its batch
        #: ids (the segment-rotation input).
        self.consumed_logged = False

    @property
    def next_level(self) -> int:
        return self.ctx.previous_hierarchy_level + 1


class HeavyHitterStream:
    """One stream's crash-safe window manager (ISSUE 15).

    ``peer=(host, port)`` makes this party the aggregation **leader**
    (its advance worker drives window publishes against that peer's
    ``hh_aggregate`` endpoint); ``peer=None`` is the **follower**.
    ``journal_dir`` is mandatory — durability is this tier's contract,
    not an option. The manager is thread-safe; the RPC server calls
    :meth:`ingest` from the batcher flush, :meth:`aggregate` /
    :meth:`snapshot` from connection threads."""

    #: seconds the leader's advance worker backs off after a failed
    #: window attempt (peer down mid-restart, etc.) before retrying —
    #: journaled levels replay, so retries are cheap.
    RETRY_SECONDS = 0.5

    def __init__(
        self,
        config: StreamConfig,
        journal_dir: str,
        peer: Optional[Tuple[str, int]] = None,
        peer_policy=None,
        policy=None,
        peer_deadline: float = 30.0,
        lease_dir: Optional[str] = None,
        lease_ttl: float = 2.0,
        role: Optional[str] = None,
        owner: Optional[str] = None,
        shared: bool = False,
    ):
        if not journal_dir:
            raise InvalidArgumentError(
                "a heavy-hitter stream needs a journal_dir — exactly-once "
                "window accounting is the streaming tier's contract"
            )
        self.config = config
        self.dir = os.path.join(journal_dir, f"stream-{config.name}")
        self.peer = tuple(peer) if peer is not None else None
        if role is not None and role not in ("leader", "follower"):
            raise InvalidArgumentError(
                f"stream role must be 'leader' or 'follower', got {role!r}"
            )
        self.role = role if role is not None else (
            "leader" if self.peer is not None else "follower"
        )
        if self.role == "leader" and self.peer is None:
            raise InvalidArgumentError(
                "the aggregation leader needs a peer endpoint"
            )
        if (self.role == "follower" and self.peer is not None
                and not lease_dir):
            raise InvalidArgumentError(
                "a follower with a peer endpoint is the failover shape — "
                "it needs lease_dir to arbitrate the role by lease"
            )
        if shared:
            if self.peer is not None:
                raise InvalidArgumentError(
                    "a fleet-sheltered (shared-journal) stream is a "
                    "follower replica — it cannot also be an aggregation "
                    "leader or failover party (peer=...)"
                )
            if lease_dir:
                raise InvalidArgumentError(
                    "shared-journal streams arbitrate by the per-stream "
                    "ownership lease inside the stream directory; a role "
                    "lease_dir does not apply"
                )
        self._owner_name = owner or f"pid{os.getpid()}-{id(self):x}"
        #: the role lease (leader failover, ISSUE 16); None = the static
        #: PR 15 single-pair shape.
        self._lease = (
            StreamLease(
                os.path.join(lease_dir, f"stream-{config.name}.lease"),
                self._owner_name, ttl=lease_ttl,
            ) if lease_dir else None
        )
        #: the ownership lease (fleet-sheltered shared journals); lives
        #: INSIDE the stream dir so it travels with the journal volume.
        self._owner_lease = (
            StreamLease(
                os.path.join(self.dir, "owner.lease"),
                self._owner_name, ttl=lease_ttl,
            ) if shared else None
        )
        #: False simulates SIGKILL in tests/benchmarks: stop() keeps the
        #: lease so the peer must wait out the TTL like a real crash.
        self.release_on_stop = True
        self._peer_policy = peer_policy
        self._peer_deadline = float(peer_deadline)
        self._policy = policy
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._stop_evt = threading.Event()
        self._loaded = False
        self._dpf_obj = None
        self._party: Optional[int] = None
        self._windows: Dict[int, _Window] = {}
        self._open: Optional[_Window] = None
        self._accepted: Dict[str, int] = {}  # batch id -> ingest generation
        self._consumed: set = set()
        self._peer_windows: Dict[int, _PeerWindow] = {}
        self._published: List[dict] = []
        #: union of batch ids across every published record (own,
        #: replicated, or adopted at reconcile) — the exactly-once spine
        #: the failover advance filters membership against.
        self._published_bids: set = set()
        #: publish records not yet acknowledged by the peer — drained by
        #: the advance loop; a window's journals only matter locally, so
        #: losing this list to a crash is covered by the new leader's
        #: reconcile pull (and by the boot-time rebroadcast from load).
        self._publish_unacked: List[dict] = []
        #: batch ids rejected by the share-consistency audit (durable
        #: via "quarantined" retired.jsonl lines).
        self._quarantined_ids: set = set()
        self._quarantined = 0
        #: quarantine decisions not yet notified to the peer — ride the
        #: next outgoing hh_aggregate leg (idempotent re-sends).
        self._quarantine_unacked: set = set()
        #: batch ids that already passed the audit (in-memory only — a
        #: restart re-audits, which is cheap and deterministic).
        self._audited: set = set()
        self._lease_epoch = 0
        #: True once this leader pulled the peer's published log after
        #: taking the lease — required before the first post-flip
        #: advance (closes the publish-vs-replication crash gap).
        self._reconciled = True
        self._lease_booted = False
        self._lease_thread: Optional[threading.Thread] = None
        #: ownership-lease bookkeeping (shared-journal mode): the held
        #: epoch and a wall-clock horizon below which requests skip the
        #: lease-file read entirely.
        self._owner_epoch = 0
        self._owner_ok_until = 0.0
        self._retired_keys = 0
        self._deduped = 0
        self._backpressure = 0
        self._rotated = 0
        self._client = None
        #: byte offset of retired.jsonl's good prefix when the file ends
        #: in a torn tail (None = clean); the next append truncates to
        #: it first so records never weld onto garbage.
        self._retired_good_bytes: Optional[int] = None
        #: highest generation the orphaned-window disk sweep already
        #: covered (one listdir per generation, not per level request).
        self._swept_below = 0
        self._advance_thread: Optional[threading.Thread] = None
        bits = config.value_bits
        self._count_mask = np.uint64((1 << bits) - 1 if bits < 64
                                     else 0xFFFFFFFFFFFFFFFF)
        #: the configured hierarchy's canonical encoding, computed ONCE —
        #: ingest validation and every journal fingerprint compare
        #: against it on the hot ack path.
        self._config_blobs = [
            serialization.encode_dpf_parameters(p) for p in config.parameters
        ]

    # -- construction helpers ---------------------------------------------
    @property
    def _dpf(self):
        with self._lock:  # reentrant: callers may already hold it
            if self._dpf_obj is None:
                from ..core.dpf import DistributedPointFunction

                params = self.config.parameters
                self._dpf_obj = (
                    DistributedPointFunction.create_incremental(list(params))
                    if len(params) > 1
                    else DistributedPointFunction.create(params[0])
                )
            return self._dpf_obj

    @property
    def validator(self):
        return self._dpf.validator

    def _params_blob(self) -> bytes:
        return b"".join(self._config_blobs)

    def _ingest_fingerprint(self, generation: int) -> str:
        h = hashlib.sha256(b"hh-ingest|")
        h.update(self.config.name.encode())
        h.update(self._params_blob())
        h.update(str(generation).encode())
        return h.hexdigest()

    def _member_digest(self, batch_ids: Sequence[str],
                       shas: Dict[str, str]) -> str:
        h = hashlib.sha256()
        for bid in batch_ids:
            h.update(bid.encode())
            h.update(shas[bid].encode())
        return h.hexdigest()

    def _window_fingerprint(self, generation: int, member_digest: str,
                            kind: str = "window") -> str:
        """`kind` separates the leader's advance journal ("window") from
        the follower's serve journal ("peer"): with lease failover both
        roles can run in ONE process lifetime over ONE directory, and a
        role flip must discard the other role's leftover journal (via
        fingerprint mismatch → clean recompute) instead of replaying a
        trail recorded under different semantics."""
        h = hashlib.sha256(b"hh-window|")
        h.update(kind.encode())
        h.update(self.config.name.encode())
        h.update(self._params_blob())
        h.update(str(generation).encode())
        h.update(member_digest.encode())
        return h.hexdigest()

    def _ingest_path(self, generation: int) -> str:
        return os.path.join(self.dir, f"ingest-g{generation:08d}.journal")

    def _window_path(self, generation: int) -> str:
        return os.path.join(self.dir, f"window-g{generation:08d}.journal")

    # -- durable load ------------------------------------------------------
    def _ensure_loaded(self) -> None:
        """Reload every live journal under the stream directory (caller
        holds the lock). Torn ingest tails are discarded by ChunkJournal
        — those batches were never acknowledged, so the client still owns
        them; retired.jsonl lines keep dedup identity for generations
        whose journals already rotated away."""
        with self._lock:  # reentrant: public callers already hold it
            if self._loaded:
                return
            self._loaded = True
            os.makedirs(self.dir, exist_ok=True)
            from ..ops import supervisor as _sv

            retired_gens: set = set()
            lease_pub_gens: set = set()
            for line in self._read_retired():
                kind = line.get("kind")
                gen = int(line.get("generation", -1))
                for bid in line.get("batch_ids", ()):
                    self._accepted.setdefault(bid, gen)
                if kind == "published" and line.get("lease"):
                    # A lease-mode publish does NOT retire its ingest
                    # segments (its generation numbering is the
                    # PUBLISHER's, which after a role flip is not this
                    # party's segment numbering): the keys stay live
                    # until the segment sweep writes "retired" lines —
                    # which also carry the key accounting.
                    self._published.append(line)
                    self._published_bids.update(line.get("batch_ids", ()))
                    self._consumed.update(line.get("batch_ids", ()))
                    lease_pub_gens.add(gen)
                    continue
                self._retired_keys += int(line.get("keys", 0))
                if kind == "published":
                    self._published.append(line)
                    self._published_bids.update(line.get("batch_ids", ()))
                    retired_gens.add(gen)
                elif kind == "retired":
                    retired_gens.add(gen)
                elif kind == "consumed":
                    self._consumed.update(line.get("batch_ids", ()))
                elif kind == "quarantined":
                    self._quarantined_ids.update(line.get("batch_ids", ()))
            self._published.sort(key=lambda r: int(r["generation"]))
            for gen in lease_pub_gens:
                # Finish the publish-side rotation (the advance/serve
                # journal of a published window is dead weight).
                try:
                    os.unlink(self._window_path(gen))
                except OSError:
                    pass

            gens = []
            for fname in os.listdir(self.dir):
                m = re.fullmatch(r"ingest-g(\d+)\.journal", fname)
                if m:
                    gens.append(int(m.group(1)))
            for gen in sorted(gens):
                if gen in retired_gens:
                    # Rotation crashed between the retired line and the
                    # unlink: finish it now.
                    for path in (
                        self._ingest_path(gen), self._window_path(gen)
                    ):
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
                    continue
                jr = _sv.ChunkJournal(
                    self._ingest_path(gen), self._ingest_fingerprint(gen),
                    op="hh_ingest",
                )
                w = _Window(gen, jr)
                for index in jr.completed_indices():
                    payload = jr.completed(index)
                    w.next_index = max(w.next_index, index + 1)
                    if payload["batch_id"] in self._quarantined_ids:
                        # Audited-out before the crash: the durable
                        # quarantine line outranks the ingest record.
                        continue
                    self._apply_batch(w, payload["batch_id"], [
                        base64.b64decode(b) for b in payload["blobs"]
                    ])
                w.closed = jr.finalized
                self._windows[gen] = w
            live = sorted(self._windows)
            if live:
                # Every generation below the newest is closed (the close
                # decision happened before the next generation opened,
                # even if the crash tore the finalize marker off with
                # the tail).
                for gen in live[:-1]:
                    self._windows[gen].closed = True
                newest = self._windows[live[-1]]
                if not newest.closed:
                    self._open = newest
            next_gen = (live[-1] + 1) if live else (
                (max(retired_gens) + 1) if retired_gens else 0
            )
            if self._open is None:
                self._open = self._new_window(next_gen)
            # Peer acks don't survive a crash and re-sends are
            # idempotent: rebroadcast quarantine ids (and, in lease
            # mode, the published log) once per boot.
            self._quarantine_unacked = set(self._quarantined_ids)
            if self._lease is not None:
                if self.peer is not None:
                    self._publish_unacked = [
                        line for line in self._published
                        if line.get("lease")
                    ]
                # Crash between a lease publish and its segment sweep:
                # finish the sweep now.
                self._sweep_segments_locked()

    def _new_window(self, generation: int) -> _Window:
        from ..ops import supervisor as _sv

        jr = _sv.ChunkJournal(
            self._ingest_path(generation),
            self._ingest_fingerprint(generation), op="hh_ingest",
        )
        w = _Window(generation, jr)
        with self._lock:
            self._windows[generation] = w
        return w

    def _apply_batch(self, w: _Window, batch_id: str,
                     blobs: List[bytes]) -> None:
        keys = [serialization.parse_dpf_key(b) for b in blobs]
        party = keys[0].party
        for k in keys:
            if k.party != party:
                raise InvalidArgumentError(
                    "an ingest batch must carry one party's keys"
                )
        with self._lock:
            if self._party is None:
                self._party = party
            elif party != self._party:
                raise InvalidArgumentError(
                    f"stream {self.config.name!r} holds party "
                    f"{self._party} keys; batch {batch_id!r} carries "
                    f"party {party}"
                )
            if w.first_ingest_at is None:
                w.first_ingest_at = time.monotonic()
            w.batch_ids.append(batch_id)
            w.keys[batch_id] = keys
            w.shas[batch_id] = hashlib.sha256(b"".join(blobs)).hexdigest()
            w.keys_total += len(keys)
            self._accepted[batch_id] = w.generation

    def _retired_path(self) -> str:
        return os.path.join(self.dir, "retired.jsonl")

    def _read_retired(self) -> List[dict]:
        """Loads the good prefix of retired.jsonl and remembers where it
        ends: a crash mid-append leaves a torn tail line, and appending
        after it would WELD the next record onto garbage — one joined
        unparsable line that silently drops every later record (and the
        rotated-generation dedup identity with it) on the following
        reload. The first append after a torn load truncates back to
        the good prefix instead (the ChunkJournal rewrite discipline)."""
        with self._lock:  # reentrant: load/append callers hold it
            out: List[dict] = []
            good_bytes = 0
            try:
                with open(self._retired_path(), "rb") as f:
                    raw = f.read()
            except OSError:
                self._retired_good_bytes = None
                return out
            pos = 0
            while pos < len(raw):
                nl = raw.find(b"\n", pos)
                if nl < 0:
                    break  # unterminated tail: a mid-append kill
                line = raw[pos:nl].strip()
                if line:
                    try:
                        out.append(json.loads(line.decode("utf-8")))
                    except ValueError:
                        break  # torn/corrupt: trust nothing at or after
                pos = nl + 1
                good_bytes = pos
            self._retired_good_bytes = (
                good_bytes if good_bytes < len(raw) else None
            )
            return out

    def _append_retired(self, line: dict) -> None:
        with self._lock:
            self._ensure_loaded()  # the torn-tail offset comes from load
            if self._retired_good_bytes is not None:
                with open(self._retired_path(), "r+b") as f:
                    f.truncate(self._retired_good_bytes)
                self._retired_good_bytes = None
            with open(self._retired_path(), "a") as f:
                f.write(json.dumps(line, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "HeavyHitterStream":
        # Pay the heavy imports (jax via ops/hierarchical) at start, not
        # inside the first window advance — a cold first advance
        # otherwise stalls ~10 s with ingests backing up against the
        # pending-window bound, which reads as spurious backpressure.
        from ..ops import hierarchical  # noqa: F401
        from ..ops import supervisor  # noqa: F401

        with self._lock:
            if self._owner_lease is None:
                self._ensure_loaded()
            # else: fleet-sheltered — journals load lazily on the first
            # request that ACQUIRES the ownership lease; eagerly loading
            # another replica's live journals would race its appends.
            if (
                self._lease is not None
                and not self._lease_booted
                and not self._stop_evt.is_set()
            ):
                self._lease_booted = True
                self._boot_lease_locked()
            drives = self.role == "leader" or (
                self._lease is not None and self.peer is not None
            )
            if (
                drives
                and self._advance_thread is None
                and not self._stop_evt.is_set()
            ):
                t = threading.Thread(
                    target=self._advance_loop,
                    name=f"dpf-hh-advance-{self.config.name}", daemon=True,
                )
                self._advance_thread = t
                t.start()
            if (
                self._lease is not None
                and self._lease_thread is None
                and not self._stop_evt.is_set()
            ):
                lt = threading.Thread(
                    target=self._lease_loop,
                    name=f"dpf-hh-lease-{self.config.name}", daemon=True,
                )
                self._lease_thread = lt
                lt.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        with self._lock:
            self._wake.notify_all()
            t = self._advance_thread
            self._advance_thread = None
            lt = self._lease_thread
            self._lease_thread = None
        for th in (t, lt):
            if th is not None:
                th.join(timeout=15)
        with self._lock:
            release = (
                self._lease is not None
                and self.release_on_stop
                and self.role == "leader"
            )
            epoch = self._lease_epoch
        if release:
            try:
                self._lease.release(epoch)
            except (OSError, UnavailableError):
                pass  # the TTL expires it anyway
        with self._lock:
            if self._client is not None:
                self._client.close()
                self._client = None
            for w in self._windows.values():
                w.journal.close()
            for pw in self._peer_windows.values():
                pw.journal.close()

    def __enter__(self) -> "HeavyHitterStream":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- leader failover by lease (ISSUE 16) -------------------------------
    def _boot_lease_locked(self) -> None:
        """Role arbitration at start. The configured leader CLAIMS the
        lease; a rival's unexpired claim demotes it to follower on the
        spot — so a crashed ex-leader restarted with its original flags
        self-arbitrates into the follower role instead of fighting the
        promoted party. The configured follower just learns the current
        epoch. Claiming always bumps the epoch (even re-claiming our own
        expired lease): a restart must fence its own pre-crash requests
        exactly like a rival's."""
        if self.role == "leader":
            got = None
            try:
                got = self._lease.try_acquire()
            except (OSError, UnavailableError):
                got = None
            if got is not None:
                self._lease_epoch = got
                self._reconciled = False
                return
            st = self._lease.read()
            self.role = "follower"
            self._lease_epoch = max(
                self._lease_epoch, 0 if st is None else st.epoch
            )
            self._reconciled = False
            _tm.counter("streaming.boot_demoted", op=self.config.name)
            from ..utils import integrity

            integrity.emit_event(
                "stream-role-flip",
                f"stream {self.config.name!r} booted as configured "
                f"leader but the lease is held (epoch "
                f"{self._lease_epoch}) — joining as follower",
                "", op=self.config.name,
            )
        else:
            try:
                self._lease_epoch = max(
                    self._lease_epoch, self._lease.epoch()
                )
            except OSError:
                pass

    def _lease_loop(self) -> None:
        """The lease watcher thread (both roles, lease mode only): the
        leader renews at ttl/3 cadence; the follower polls for expiry
        and promotes itself when the leader is dead or wedged."""
        tick = max(0.05, self._lease.ttl / 3.0)
        while not self._stop_evt.is_set():
            try:
                self._lease_tick()
            except Exception:  # noqa: BLE001 — the watcher survives
                _tm.counter("streaming.lease_errors", op=self.config.name)
            self._stop_evt.wait(tick)

    def _lease_tick(self) -> None:
        with self._lock:
            role = self.role
            epoch = self._lease_epoch
        if role == "leader":
            if not self._lease.renew(epoch):
                st = self._lease.read()
                with self._lock:
                    self._demote_locked(
                        epoch if st is None else st.epoch
                    )
            return
        st = self._lease.read()
        if st is None:
            return  # no lease ever granted: wait for the leader's boot
        if st.epoch > epoch:
            with self._lock:
                self._demote_locked(st.epoch)  # learn the newer epoch
        if self.peer is not None and st.expired():
            got = None
            try:
                got = self._lease.try_acquire()
            except (OSError, UnavailableError):
                return
            if got is not None:
                with self._lock:
                    self._promote_locked(got)

    def _promote_locked(self, epoch: int) -> None:
        self._lease_epoch = max(self._lease_epoch, int(epoch))
        if self.role == "leader":
            return
        self.role = "leader"
        self._reconciled = False
        # Follower-side windows belong to the PREVIOUS reign's
        # declarations; a later demotion must rebuild them against the
        # then-leader's membership, never replay these.
        for pw in self._peer_windows.values():
            pw.journal.close()
        self._peer_windows.clear()
        _tm.counter("streaming.promoted", op=self.config.name)
        from ..utils import integrity

        integrity.emit_event(
            "stream-role-flip",
            f"stream {self.config.name!r} follower took the lease at "
            f"epoch {self._lease_epoch} — now the aggregation leader",
            "", op=self.config.name,
        )
        self._wake.notify_all()

    def _demote_locked(self, epoch: int) -> None:
        self._lease_epoch = max(self._lease_epoch, int(epoch))
        if self.role != "leader":
            return
        self.role = "follower"
        self._reconciled = False
        for pw in self._peer_windows.values():
            pw.journal.close()
        self._peer_windows.clear()
        _tm.counter("streaming.demoted", op=self.config.name)
        from ..utils import integrity

        integrity.emit_event(
            "stream-role-flip",
            f"stream {self.config.name!r} leader lost the lease (now "
            f"epoch {self._lease_epoch}) — demoted to follower; "
            "in-flight publishes are fenced by epoch",
            "", op=self.config.name,
        )

    def _relearn_and_demote(self) -> None:
        st = self._lease.read() if self._lease is not None else None
        with self._lock:
            self._demote_locked(
                self._lease_epoch if st is None else st.epoch
            )

    def _reconcile_with_peer(self) -> None:
        """New-leader catch-up, run before the first post-takeover
        advance: pull the peer's published log and adopt every window
        this party missed — the crash gap between the old leader's
        publish and its replication ack. Adoption is idempotent by
        batch-id set, so re-runs (and crossed replication legs) are
        harmless. Raises on an unreachable peer: the advance loop
        retries, which costs nothing — the advance needs the peer for
        level shares anyway."""
        from . import wire

        arrays = self._peer_client().call(
            "hh_snapshot",
            wire.encode_hh_snapshot(self.config.name, 0),
            deadline=self._peer_deadline,
        )
        snap = wire.json_from_arrays(arrays)
        with self._lock:
            for rec in snap.get("published", ()):
                self._apply_replicated_publish_locked(rec)
            self._reconciled = True

    def _apply_replicated_publish_locked(self, record: dict) -> None:
        """Adopts one publish record from the peer (the replication leg
        or the reconcile pull): durable retired.jsonl line, published
        view, exactly-once membership — all idempotent."""
        bids = [str(b) for b in record.get("batch_ids", ())]
        if not bids or all(b in self._published_bids for b in bids):
            return
        line = {
            "kind": "published",
            "generation": int(record.get("generation", -1)),
            "batch_ids": bids,
            "keys": int(record.get("keys", 0)),
            "prefixes": [str(p) for p in record.get("prefixes", ())],
            "counts": [str(c) for c in record.get("counts", ())],
            "lease": True,
        }
        self._append_retired(line)
        self._published.append(line)
        self._published.sort(key=lambda r: int(r["generation"]))
        self._published_bids.update(bids)
        self._consumed.update(bids)
        for bid in bids:
            self._accepted.setdefault(bid, line["generation"])
        pw = self._peer_windows.pop(line["generation"], None)
        if pw is not None:
            pw.journal.unlink()
            self._rotated += 1
        _tm.counter("streaming.publish_replicated", op=self.config.name)
        self._sweep_segments_locked()

    def _peer_notify(self, quarantine: Sequence[str] = (),
                     publish: Optional[dict] = None) -> None:
        """One notification-only hh_aggregate leg (no level trail):
        quarantine ids and/or a publish record for the peer to adopt."""
        from . import wire

        with self._lock:
            epoch = self._lease_epoch
        payload = wire.encode_hh_aggregate(
            self.config.name,
            int(publish["generation"]) if publish else 0,
            [], [],
            epoch=epoch, publish=publish, quarantine=list(quarantine),
        )
        self._peer_client().call(
            "hh_aggregate", payload, deadline=self._peer_deadline
        )

    def _flush_peer_state(self) -> None:
        """Drains un-acked quarantine ids and publish records to the
        peer (ordered, idempotent). Called from the advance loop and at
        publish time; raising is fine — the caller retries."""
        if self.peer is None:
            return
        with self._lock:
            quarantine = sorted(self._quarantine_unacked)
            publishes = list(self._publish_unacked)
        if not quarantine and not publishes:
            return
        if quarantine:
            self._peer_notify(quarantine=quarantine)
            with self._lock:
                self._quarantine_unacked.difference_update(quarantine)
        for line in publishes:
            self._peer_notify(publish=line)
            with self._lock:
                if line in self._publish_unacked:
                    self._publish_unacked.remove(line)

    # -- ingestion ---------------------------------------------------------
    def _pending_locked(self) -> List[_Window]:
        return [
            w for g, w in sorted(self._windows.items()) if w.closed
        ]

    def check_admission(self, batch_id: Optional[str] = None) -> None:
        """Backpressure gate (called by FrontDoor.submit before an
        ingest queues, and again inside :meth:`ingest`): past the
        pending-window bound the server says "later" —
        ``RESOURCE_EXHAUSTED``, the client's retry-with-backoff signal —
        instead of queueing work the advance cannot keep up with.
        A `batch_id` this stream has ALREADY ACCEPTED passes regardless:
        the retry of a lost ack must be acknowledged (the exactly-once
        contract), not refused for work that was already admitted.

        LEADER ONLY. The follower's closed segments retire with the
        LEADER's window progress, and that progress needs every
        membership batch delivered to the follower — a follower that
        refused ingests at its own segment bound would reject exactly
        the deliveries that unblock the pipeline (a real deadlock, found
        by the --stream soak: the leader's pending window stalled
        UNAVAILABLE-incomplete while the follower shed the missing
        batches RESOURCE_EXHAUSTED forever). The follower's backlog is
        bounded transitively: clients upload to both parties in
        lockstep, so the leader's bound throttles them both."""
        if self.role != "leader":
            return
        with self._lock:
            self._ensure_loaded()
            if batch_id and batch_id in self._accepted:
                return  # a dedup ack is always answered
            pending = len(self._pending_locked())
            if pending >= self.config.max_pending_windows:
                self._backpressure += 1
                _tm.counter("streaming.backpressure", op=self.config.name)
                raise ResourceExhaustedError(
                    f"RESOURCE_EXHAUSTED: stream {self.config.name!r} has "
                    f"{pending} pending windows (max_pending_windows="
                    f"{self.config.max_pending_windows}) — ingestion is "
                    "outpacing the window advance; retry with backoff"
                )

    def _check_params(self, parameters: Sequence[DpfParameters]) -> None:
        got = [serialization.encode_dpf_parameters(p) for p in parameters]
        if got != self._config_blobs:
            raise InvalidArgumentError(
                f"ingest parameters do not match stream "
                f"{self.config.name!r}'s configured hierarchy"
            )

    def ingest(
        self,
        parameters: Sequence[DpfParameters],
        key_blobs: Sequence[bytes],
        batch_id: str,
        flush: bool = False,
    ) -> Tuple[int, bool]:
        """One client key batch into the open window. Returns
        (generation, deduped). The batch is journaled — one fsync'd
        ChunkJournal line — BEFORE this returns, so an acknowledged batch
        survives SIGKILL; a batch id seen before is acknowledged with its
        original generation and never re-counted (the client retry after
        a lost ack). ``flush=True`` closes the open window after
        accepting (empty `key_blobs` = a pure window-close control
        message)."""
        self._check_params(parameters)
        if key_blobs and not batch_id:
            raise InvalidArgumentError(
                "a non-empty ingest batch needs a batch_id (the "
                "exactly-once dedup identity)"
            )
        blobs = [bytes(b) for b in key_blobs]
        with self._lock:
            self._ensure_owner_locked()
            self._ensure_loaded()
            if batch_id and batch_id in self._quarantined_ids:
                # The audit's verdict outranks a retry: acknowledge (the
                # client's delivery duty is done) without re-admitting.
                self._deduped += 1
                _tm.counter("streaming.deduped", op=self.config.name)
                return self._accepted.get(batch_id, 0), True
            if batch_id and batch_id in self._accepted:
                self._deduped += 1
                _tm.counter("streaming.deduped", op=self.config.name)
                if flush:
                    self._maybe_close_locked()
                return self._accepted[batch_id], True
            if blobs or (flush and self._open.batch_ids):
                self.check_admission()
            gen = self._open.generation
            if blobs:
                w = self._open
                w.journal.record(
                    w.next_index,
                    {
                        "batch_id": batch_id,
                        "blobs": [
                            base64.b64encode(b).decode("ascii")
                            for b in blobs
                        ],
                    },
                )
                w.next_index += 1
                self._apply_batch(w, batch_id, blobs)
                _tm.counter("streaming.accepted", op=self.config.name)
                if w.keys_total >= self.config.window_keys:
                    self._maybe_close_locked()
            if flush:
                self._maybe_close_locked()
            return gen, False

    def _maybe_close_locked(self) -> None:
        """Closes the open window (finalize = the durable closed marker)
        and opens the next generation. A window with no batches stays
        open — there is nothing to advance."""
        with self._lock:
            w = self._open
            if not w.batch_ids:
                return
            w.journal.finalize()
            w.closed = True
            w.closed_at = time.monotonic()
            _tm.counter("streaming.windows_closed", op=self.config.name)
            self._open = self._new_window(w.generation + 1)
            self._wake.notify_all()

    # -- the advance (leader) ---------------------------------------------
    def _advance_loop(self) -> None:
        """The advance worker. In lease mode it lives for the PROCESS
        (not the role): while follower it idles on the condition, and a
        promotion wakes it — one thread, so two reigns in one process
        can never double-advance."""
        while not self._stop_evt.is_set():
            w = None
            with self._lock:
                if self.role != "leader":
                    if self._lease is None:
                        return  # static follower: nothing to drive, ever
                    self._wake.wait(timeout=0.25)
                    continue
                reconciled = self._reconciled
                w = next(iter(self._pending_locked()), None)
            try:
                if not reconciled:
                    self._reconcile_with_peer()
                self._flush_peer_state()
                if w is None:
                    with self._lock:
                        if self.role == "leader":
                            self._wake.wait(timeout=0.25)
                    continue
                self._advance_window(w)
            except Exception as exc:  # noqa: BLE001 — the worker survives
                _tm.counter("streaming.advance_errors", op=self.config.name)
                from ..utils import integrity

                gen = -1 if w is None else w.generation
                integrity.emit_event(
                    "stream-advance-retry",
                    f"stream {self.config.name!r} window {gen} "
                    f"advance failed ({type(exc).__name__}: {exc}) — "
                    "retrying; journaled levels replay",
                    "",
                    op=self.config.name,
                    generation=gen,
                )
                if (
                    isinstance(exc, FailedPreconditionError)
                    and self._lease is not None
                ):
                    # The peer fenced us: a newer epoch exists. Re-read
                    # the lease and fall in line as follower.
                    self._relearn_and_demote()
                self._stop_evt.wait(self.RETRY_SECONDS)

    def _advance_window(self, w: _Window) -> None:
        """One closed window end to end: level-by-level advance, peer
        exchange, threshold prune, publish, rotate. Every committed level
        is journaled (counts + resumable context state) so a SIGKILL at
        any point resumes without re-walking verified levels — and
        without double-counting: the ingest journal is the membership of
        record, and the window fingerprint binds the state journal to
        exactly that membership."""
        from ..ops import hierarchical
        from ..ops import supervisor as _sv

        cfg = self.config
        v = self._dpf.validator
        w.advance_started = time.monotonic()
        if not w.journal.finalized:
            w.journal.finalize()  # durably close a crash-recovered window
        # Membership of record: the segment's batches MINUS anything the
        # published log already covers (a window the old leader
        # published and we adopted at reconcile) MINUS quarantined ids.
        # In the static PR 15 shape both sets are empty and member ==
        # w.batch_ids, byte for byte.
        with self._lock:
            member = [
                bid for bid in w.batch_ids
                if bid not in self._published_bids
                and bid not in self._quarantined_ids
            ]
        if cfg.audit and member:
            member = self._audit_window(w, member)
        if not member:
            # Nothing left to count: retire the segment (and any stale
            # advance journal) without a publish.
            with self._lock:
                try:
                    os.unlink(self._window_path(w.generation))
                except OSError:
                    pass
                self._sweep_segments_locked()
            return
        if self._lease is not None and not self._lease.renew(
            self._lease_epoch
        ):
            # Zombie self-fence: the lease moved on mid-window — this
            # party must not publish under a superseded epoch.
            self._relearn_and_demote()
            raise FailedPreconditionError(
                f"FAILED_PRECONDITION: stream {self.config.name!r} lease "
                f"epoch {self._lease_epoch} was superseded mid-advance — "
                "this party is no longer the leader"
            )
        keys = [k for bid in member for k in w.keys[bid]]
        ctx = hierarchical.BatchedContext.create(self._dpf, keys)
        jr = _sv.ChunkJournal(
            self._window_path(w.generation),
            self._window_fingerprint(
                w.generation, self._member_digest(member, w.shas)
            ),
            op="hh_window",
        )
        survivors: List[int] = []
        counts_of: Dict[int, int] = {}
        trail: List[Tuple[int, list]] = []
        prefixes: List[int] = []
        try:
            for level in range(v.num_hierarchy_levels):
                prev_lds = (
                    0 if level == 0
                    else v.parameters[level - 1].log_domain_size
                )
                lds = v.parameters[level].log_domain_size
                trail.append((level, list(prefixes)))
                want = [str(p) for p in prefixes]
                stored = jr.completed(level)
                if stored is not None and stored["prefixes"] == want:
                    counts = np.array(
                        [int(c) for c in stored["counts"]], dtype=np.uint64
                    )
                    _sv.ctx_apply(ctx, stored["state"])
                else:
                    own = self._level_shares(ctx, level, prefixes)
                    peer = self._peer_level(w, member, trail)
                    if peer.shape != own.shape:
                        raise DataLossError(
                            f"peer aggregate for window {w.generation} "
                            f"level {level} has {peer.shape[0]} candidates"
                            f", expected {own.shape[0]}"
                        )
                    counts = (own + peer) & self._count_mask
                    jr.record(level, {
                        "prefixes": want,
                        "counts": [str(int(c)) for c in counts],
                        "state": _sv.ctx_record(ctx),
                    })
                cand = hierarchical.candidate_children(
                    prefixes, prev_lds, lds
                )
                keep = np.nonzero(counts >= np.uint64(cfg.threshold))[0]
                survivors = [int(cand[i]) for i in keep]
                counts_of = {int(cand[i]): int(counts[i]) for i in keep}
                prefixes = survivors
                if not prefixes:
                    break
            self._publish(w, jr, member, survivors, counts_of)
        finally:
            jr.close()

    def _publish(self, w: _Window, jr, member: List[str],
                 prefixes: List[int], counts_of: Dict[int, int]) -> None:
        line = {
            "kind": "published",
            "generation": w.generation,
            "batch_ids": list(member),
            "keys": sum(len(w.keys[b]) for b in member),
            "prefixes": [str(p) for p in prefixes],
            "counts": [str(counts_of[p]) for p in prefixes],
        }
        # Dealer-plane share (ISSUE 19): the feed phase (first ingest ->
        # close) is the client keygen bound; the advance phase is this
        # leader's level walk + publish. Recording both walls makes
        # "keygen-bound by design" a measured number on every published
        # window. None on crash-recovered windows (walls died with the
        # process).
        feed = (
            None
            if w.first_ingest_at is None or w.closed_at is None
            else max(0.0, w.closed_at - w.first_ingest_at)
        )
        adv = (
            None
            if w.advance_started is None
            else max(0.0, time.monotonic() - w.advance_started)
        )
        share = (
            None
            if feed is None or adv is None or feed + adv <= 0
            else round(feed / (feed + adv), 4)
        )
        line["keygen"] = {
            "keys": line["keys"],
            "feed_ms": None if feed is None else round(feed * 1e3, 3),
            "advance_ms": None if adv is None else round(adv * 1e3, 3),
            "share": share,
        }
        if share is not None:
            _tm.gauge(
                "streaming.keygen_share", share, op=self.config.name
            )
        if self._lease is not None:
            line["lease"] = True
        # Durability order: the published line lands (fsync) BEFORE the
        # window's journals rotate away — a crash in between re-runs
        # rotation at reload, never the window.
        with self._lock:
            fresh = any(b not in self._published_bids for b in member)
            if fresh:
                if self._lease is not None and not self._lease.renew(
                    self._lease_epoch
                ):
                    # The last fence before the log: a lease stolen
                    # between the window's levels and its publish must
                    # not produce a record the exactly-once spine then
                    # has to fight.
                    st = self._lease.read()
                    self._demote_locked(
                        self._lease_epoch if st is None else st.epoch
                    )
                    raise FailedPreconditionError(
                        f"FAILED_PRECONDITION: stream "
                        f"{self.config.name!r} lease epoch "
                        f"{self._lease_epoch} was superseded at publish "
                        "— record withheld"
                    )
                self._append_retired(line)
                self._published.append(line)
                self._published_bids.update(member)
                self._consumed.update(member)
                if self._lease is not None and self.peer is not None:
                    self._publish_unacked.append(line)
            self._wake.notify_all()
        # Replication is part of the window's ack: the follower holds
        # the publish record BEFORE this leader rotates the journals
        # away (a failure here raises; the advance loop retries and the
        # record rides _publish_unacked).
        self._flush_peer_state()
        jr.finalize()
        with self._lock:
            if self._lease is None:
                self._windows.pop(w.generation, None)
                self._retired_keys += w.keys_total
        jr.unlink()
        with self._lock:
            if self._lease is None:
                w.journal.unlink()
                self._rotated += 2
            else:
                # Lease mode keeps segment accounting in the sweep (a
                # published batch's segment may still hold OTHER live
                # batches after a failover re-partition).
                self._rotated += 1
                self._sweep_segments_locked()
        _tm.counter("streaming.windows_published", op=self.config.name)

    def _peer_client(self):
        with self._lock:
            if self._client is None:
                from .client import DpfClient, RetryPolicy

                policy = self._peer_policy or RetryPolicy(
                    attempts=5, base_backoff=0.1, max_backoff=1.0,
                    attempt_timeout=self._peer_deadline,
                    connect_attempts=40, connect_backoff=0.25, seed=0,
                )
                self._client = DpfClient(
                    self.peer[0], self.peer[1], policy=policy
                )
            return self._client

    def _peer_level(self, w: _Window, member: List[str],
                    trail) -> np.ndarray:
        """The peer party's aggregate share vector for the trail's last
        level — the only server-to-server communication (two vectors per
        level, like the batch demo). The client's retry budget carries
        the call across a peer restart; a still-incomplete peer window
        answers UNAVAILABLE, which lands here as a retry too. The leg
        carries the lease epoch (the zombie fence) and piggybacks any
        un-acked quarantine ids, so a quarantined batch is excluded on
        BOTH parties no later than the window's first level."""
        from . import wire

        with self._lock:
            epoch = self._lease_epoch
            quarantine = sorted(self._quarantine_unacked)
        payload = wire.encode_hh_aggregate(
            self.config.name, w.generation, list(member), trail,
            epoch=epoch, quarantine=quarantine,
        )
        arrays = self._peer_client().call(
            "hh_aggregate", payload, deadline=self._peer_deadline
        )
        if quarantine:
            with self._lock:
                self._quarantine_unacked.difference_update(quarantine)
        return np.asarray(arrays[0], dtype=np.uint64)

    def _level_shares(self, ctx, level: int, prefixes) -> np.ndarray:
        """This party's aggregate share vector for one advance: the
        per-key per-candidate shares summed over keys mod 2^bits. Host
        engine = the native AES advance (zero device programs, pinned);
        device = the robust hierarchical chain with the hierkernel mode
        staged-for-tunnel behind the same plumbing."""
        cfg = self.config
        bits = cfg.value_bits
        if cfg.engine == "host":
            from ..ops import hierarchical

            out = hierarchical.evaluate_until_batch(
                ctx, level, list(prefixes), engine="host"
            )
            vals = np.asarray(out).astype(np.uint64)
        else:
            from ..ops import evaluator
            from ..ops import supervisor as _sv

            kw = {} if self._policy is None else {"policy": self._policy}
            limbs = _sv.advance_level_robust(
                ctx, level, list(prefixes), group=cfg.group, mode=cfg.mode,
                **kw,
            )
            vals = np.asarray(
                evaluator.values_to_numpy(limbs, bits)
            ).astype(np.uint64)
        return vals.sum(axis=0, dtype=np.uint64) & self._count_mask

    # -- the peer exchange (follower) --------------------------------------
    def aggregate(self, generation: int, batch_ids: Sequence[str],
                  plan, *, epoch: int = 0, publish: Optional[dict] = None,
                  quarantine: Sequence[str] = (),
                  audit: bool = False) -> np.ndarray:
        """Serves the leader's per-level aggregate request: assemble this
        party's window from the declared batch-id membership, fast-
        forward through the request's level trail (journaling each
        advanced level), and return the LAST entry's share vector. A
        batch this party has not yet ingested answers UNAVAILABLE (the
        leader retries — the client upload will land); a journaled trail
        that no longer matches starts the window clean.

        ISSUE 16 extensions (all keyword-only — the PR 15 wire shape is
        the default): ``epoch`` is the sender's lease epoch and the
        zombie fence — in lease mode a stale epoch answers
        ``FAILED_PRECONDITION`` before ANY state is touched, and a newer
        one demotes a current leader on the spot. ``quarantine`` applies
        peer quarantine decisions; ``publish`` adopts a replicated
        publish record; ``audit=True`` serves the named batches' level-0
        aggregate from a throwaway context (the share-consistency
        check's follower leg — no window state involved). A leg with no
        level trail is a pure notification and returns an empty
        vector."""
        with self._lock:
            self._ensure_owner_locked()
            self._ensure_loaded()
            if self._lease is not None:
                if epoch > self._lease_epoch:
                    # A newer leader exists: learn its epoch (dropping
                    # leadership if this party still thought it led).
                    self._demote_locked(epoch)
                elif epoch < self._lease_epoch or self.role == "leader":
                    _tm.counter("streaming.fenced", op=self.config.name)
                    raise FailedPreconditionError(
                        f"FAILED_PRECONDITION: stream "
                        f"{self.config.name!r} hh_aggregate carries "
                        f"lease epoch {epoch} but this party is at "
                        f"epoch {self._lease_epoch} — a superseded "
                        "(zombie) leader is fenced, never merged"
                    )
            elif self.role != "follower":
                raise InvalidArgumentError(
                    "hh_aggregate is served by the peer (follower) party"
                )
            for bid in quarantine:
                self._apply_quarantine_locked(
                    str(bid), note=" (peer notification)"
                )
            if publish is not None:
                self._apply_replicated_publish_locked(publish)
            if audit:
                return self._serve_audit_locked(batch_ids)
            if not plan:
                if publish is not None or quarantine:
                    return np.zeros(0, dtype=np.uint64)
                raise InvalidArgumentError(
                    "hh_aggregate needs a level trail"
                )
            missing = [b for b in batch_ids if b not in self._accepted]
            if missing:
                raise UnavailableError(
                    f"UNAVAILABLE: stream {self.config.name!r} window "
                    f"{generation} is missing {len(missing)} ingest "
                    "batches on this party — retry once the client "
                    "uploads land"
                )
            pw = self._peer_windows.get(generation)
            if pw is not None and list(pw.batch_ids) != list(batch_ids):
                if self._lease is None:
                    raise FailedPreconditionError(
                        f"window {generation} membership drifted between "
                        "aggregate requests (leader bug or stale journal)"
                    )
                # Failover redeclaration: a promoted leader legitimately
                # re-partitions membership (adopted publishes and
                # quarantines excluded) — rebuild clean; the fingerprint
                # binds counts to the new membership.
                _tm.counter(
                    "streaming.window_redeclared", op=self.config.name
                )
                pw.journal.unlink()
                self._rotated += 1
                self._peer_windows.pop(generation, None)
                pw = None
            if pw is None:
                pw = self._make_peer_window_locked(generation, batch_ids)
                self._peer_windows[generation] = pw
            result = self._serve_trail_locked(pw, plan)
            # The window that just served is re-fetched: a trail
            # divergence inside _serve_trail_locked replaces the object.
            pw = self._peer_windows[generation]
            if plan[-1][0] == self.validator.num_hierarchy_levels - 1:
                # The FINAL level served: this window's batches are
                # consumed — make that durable NOW, not at the leader's
                # next-generation request, or a follower restart in
                # between orphans the ids (segments would never retire;
                # review catch). The window journal itself stays until
                # retire-below so a leader crash-resume can re-request
                # the final level.
                self._mark_consumed_locked(pw)
                self._sweep_segments_locked()
            self._retire_before_locked(generation)
            return result

    def _make_peer_window_locked(self, generation: int,
                                 batch_ids: Sequence[str]) -> _PeerWindow:
        from ..ops import hierarchical
        from ..ops import supervisor as _sv

        keys, shas = [], {}
        for bid in batch_ids:
            w = self._windows.get(self._accepted[bid])
            if w is None or bid not in w.keys:
                raise FailedPreconditionError(
                    f"batch {bid!r} was already consumed by a retired "
                    "window — the leader is replaying a published "
                    "generation"
                )
            keys.extend(w.keys[bid])
            shas[bid] = w.shas[bid]
        ctx = hierarchical.BatchedContext.create(self._dpf, keys)
        jr = _sv.ChunkJournal(
            self._window_path(generation),
            self._window_fingerprint(
                generation, self._member_digest(list(batch_ids), shas),
                kind="peer",
            ),
            op="hh_peer",
        )
        pw = _PeerWindow(generation, list(batch_ids), ctx, jr)
        # Replay the journaled trail: contiguous levels from 0, context
        # fast-forwarded to the highest replayed level's state.
        for level in jr.completed_indices():
            if level != pw.next_level:
                break
            stored = jr.completed(level)
            pw.levels[level] = {
                "prefixes": stored["prefixes"],
                "agg": np.array(
                    [int(x) for x in stored["agg"]], dtype=np.uint64
                ),
            }
            _sv.ctx_apply(pw.ctx, stored["state"])
        return pw

    def _serve_trail_locked(self, pw: _PeerWindow, plan) -> np.ndarray:
        from ..ops import supervisor as _sv

        for attempt in range(2):
            diverged = False
            for level, prefixes in plan:
                want = [str(int(p)) for p in prefixes]
                have = pw.levels.get(level)
                if have is not None:
                    if have["prefixes"] == want:
                        continue
                    # Stale counts must never merge: start clean.
                    _tm.counter(
                        "streaming.window_reset", op=self.config.name
                    )
                    pw = self._reset_peer_window_locked(pw)
                    diverged = True
                    break
                if level != pw.next_level:
                    raise FailedPreconditionError(
                        f"aggregate trail skips to level {level} but this "
                        f"party's window is at level {pw.next_level}"
                    )
                agg = self._level_shares(pw.ctx, level, prefixes)
                pw.journal.record(level, {
                    "prefixes": want,
                    "agg": [str(int(x)) for x in agg],
                    "state": _sv.ctx_record(pw.ctx),
                })
                pw.levels[level] = {"prefixes": want, "agg": agg}
            if not diverged:
                break
        last_level = plan[-1][0]
        return np.asarray(pw.levels[last_level]["agg"], dtype=np.uint64)

    def _reset_peer_window_locked(self, pw: _PeerWindow) -> _PeerWindow:
        pw.journal.unlink()
        fresh = self._make_peer_window_locked(pw.generation, pw.batch_ids)
        with self._lock:
            self._rotated += 1
            self._peer_windows[pw.generation] = fresh
        return fresh

    def _mark_consumed_locked(self, pw: _PeerWindow) -> None:
        """Durably records a peer window's batch ids as consumed (one
        retired.jsonl line; idempotent across restarts — the loader
        setdefaults)."""
        with self._lock:
            if pw.consumed_logged:
                return
            self._append_retired({
                "kind": "consumed", "generation": pw.generation,
                "batch_ids": list(pw.batch_ids),
            })
            self._consumed.update(pw.batch_ids)
            pw.consumed_logged = True

    def _sweep_segments_locked(self) -> None:
        """Unlinks any closed ingest segment whose batches are all done,
        compacting it into a retired line first. "Done" is role-shape
        dependent: the static follower retires on *consumed* (the final
        level served — the leader publishes right after); in lease mode
        consumption is NOT enough — a leader crash between the final
        level and the publish must leave the keys recoverable for the
        new leader's own advance, so only *published or quarantined*
        batches release a segment."""
        with self._lock:
            for seg_gen, w in sorted(self._windows.items()):
                if not w.closed or not w.batch_ids:
                    continue
                if self._lease is not None:
                    done = all(
                        bid in self._published_bids
                        or bid in self._quarantined_ids
                        for bid in w.batch_ids
                    )
                else:
                    done = all(
                        bid in self._consumed for bid in w.batch_ids
                    )
                if done:
                    self._append_retired({
                        "kind": "retired", "generation": seg_gen,
                        "batch_ids": list(w.batch_ids),
                        "keys": w.keys_total,
                    })
                    self._retired_keys += w.keys_total
                    w.journal.unlink()
                    self._rotated += 1
                    self._windows.pop(seg_gen)

    def _retire_before_locked(self, generation: int) -> None:
        """Rotation, follower side: the leader advances generations in
        order and publishes g before requesting g+1, so a request for
        `generation` retires every earlier peer window — its state
        journal unlinks (including journals ORPHANED on disk by a
        restart: the in-memory map is rebuilt lazily, so files below
        the requested generation are swept by path) — and any closed
        ingest segment whose batches are all consumed compacts into a
        retired line and unlinks too."""
        with self._lock:
            for gen in sorted(
                g for g in self._peer_windows if g < generation
            ):
                pw = self._peer_windows.pop(gen)
                self._mark_consumed_locked(pw)
                pw.journal.unlink()
                self._rotated += 1
            # Orphaned window journals (served before a restart, retired
            # after it): the leader never revisits generations below
            # `generation`, so their files are dead weight — sweep them
            # (once per generation, not per level request).
            if generation <= self._swept_below:
                return
            self._swept_below = generation
            try:
                names = os.listdir(self.dir)
            except OSError:
                names = []
            for fname in names:
                m = re.fullmatch(r"window-g(\d+)\.journal", fname)
                if m and int(m.group(1)) < generation:
                    try:
                        os.unlink(os.path.join(self.dir, fname))
                        self._rotated += 1
                    except OSError:
                        pass
            self._sweep_segments_locked()

    # -- malicious-client share audit (ISSUE 16) ----------------------------
    def _audit_window(self, w: _Window, member: List[str]) -> List[str]:
        """The leader leg of the per-batch share-consistency audit, run
        BEFORE a batch enters window membership. Both parties aggregate
        ONE batch's keys at level 0 with no prefix restriction; for an
        honest batch of n one-hot (beta=1) keys the reconstructed vector
        sums to exactly n with no cell above n. Anything else — a beta≠1
        key, a zero key, a wrapped-negative beta — quarantines the batch
        on both parties (the quarantine id rides the next peer leg; the
        level-0 prefix mass is all this check reveals beyond the
        protocol's output). Returns the surviving member list."""
        from ..ops import hierarchical

        ok: List[str] = []
        for bid in member:
            with self._lock:
                if bid in self._audited:
                    ok.append(bid)
                    continue
                batch_keys = list(w.keys.get(bid, ()))
            if not batch_keys:
                continue
            ctx = hierarchical.BatchedContext.create(self._dpf, batch_keys)
            own = self._level_shares(ctx, 0, [])
            try:
                peer = self._peer_audit(w.generation, bid)
            except FailedPreconditionError:
                # The peer already quarantined this batch and its
                # notification died with a crash (reconcile filtered
                # published/consumed bids out of `member` first, so a
                # failed-precondition here IS the quarantine verdict):
                # adopt it instead of looping a demote cycle.
                with self._lock:
                    self._apply_quarantine_locked(
                        bid, note=" (peer verdict adopted)"
                    )
                continue
            if peer.shape != own.shape:
                raise DataLossError(
                    f"audit share for batch {bid!r} has {peer.shape[0]} "
                    f"candidates, expected {own.shape[0]}"
                )
            counts = (own + peer) & self._count_mask
            n = len(batch_keys)
            total = int(counts.sum(dtype=np.uint64) & self._count_mask)
            if total == n and all(int(c) <= n for c in counts):
                with self._lock:
                    self._audited.add(bid)
                ok.append(bid)
            else:
                with self._lock:
                    self._apply_quarantine_locked(bid, note=(
                        f" (level-0 mass {total} across "
                        f"{int(counts.shape[0])} candidates from {n} "
                        "keys)"
                    ))
        return ok

    def _peer_audit(self, generation: int, bid: str) -> np.ndarray:
        from . import wire

        with self._lock:
            epoch = self._lease_epoch
        payload = wire.encode_hh_aggregate(
            self.config.name, generation, [bid], [],
            epoch=epoch, audit=True,
        )
        arrays = self._peer_client().call(
            "hh_aggregate", payload, deadline=self._peer_deadline
        )
        return np.asarray(arrays[0], dtype=np.uint64)

    def _serve_audit_locked(self, batch_ids: Sequence[str]) -> np.ndarray:
        """The follower leg: the level-0 aggregate share over JUST the
        named batches' keys, from a throwaway context — the audit runs
        before window membership, so no window state is touched."""
        from ..ops import hierarchical

        missing = [b for b in batch_ids if b not in self._accepted]
        if missing:
            raise UnavailableError(
                f"UNAVAILABLE: stream {self.config.name!r} audit is "
                f"missing {len(missing)} ingest batches on this party — "
                "retry once the client uploads land"
            )
        keys: List = []
        for bid in batch_ids:
            w = self._windows.get(self._accepted[bid])
            if w is None or bid not in w.keys:
                raise FailedPreconditionError(
                    f"audit batch {bid!r} was already consumed or "
                    "retired on this party"
                )
            keys.extend(w.keys[bid])
        ctx = hierarchical.BatchedContext.create(self._dpf, keys)
        return self._level_shares(ctx, 0, [])

    def _apply_quarantine_locked(self, bid: str, note: str = "") -> None:
        """Quarantines one batch id: removed from its live segment,
        recorded durably ("quarantined" retired.jsonl line — the reload
        skips the batch's ingest records), counted, and announced. A
        retry of the batch is acknowledged-as-deduped, never
        re-admitted. Idempotent."""
        if bid in self._quarantined_ids:
            return
        gen = self._accepted.get(bid, -1)
        w = self._windows.get(gen)
        n = 0
        if w is not None and bid in w.keys:
            n = len(w.keys.pop(bid))
            w.shas.pop(bid, None)
            if bid in w.batch_ids:
                w.batch_ids.remove(bid)
            w.keys_total -= n
        self._append_retired({
            "kind": "quarantined", "generation": gen,
            "batch_ids": [bid], "keys": n,
        })
        self._accepted.setdefault(bid, gen)
        self._retired_keys += n
        self._quarantined_ids.add(bid)
        self._quarantined += 1
        self._quarantine_unacked.add(bid)
        self._audited.discard(bid)
        _tm.counter("hh.quarantined", op=self.config.name)
        from ..utils import integrity

        integrity.emit_event(
            "stream-batch-quarantined",
            f"stream {self.config.name!r} batch {bid!r} failed the "
            f"share-consistency audit ({n} keys){note} — quarantined "
            "before window membership; honest batches are unaffected",
            "", op=self.config.name,
        )

    # -- fleet-sheltered ownership (ISSUE 16) -------------------------------
    def _owns_now_locked(self) -> bool:
        if self._owner_lease is None:
            return True
        if not self._owner_epoch:
            return False
        if time.time() < self._owner_ok_until:
            return True
        st = self._owner_lease.read()
        return (
            st is not None
            and st.owner == self._owner_name
            and st.epoch == self._owner_epoch
        )

    def _ensure_owner_locked(self) -> None:
        """The shared-journal gate, called before any request touches
        stream state. Holding the ownership lease admits the request
        (renewed at ttl/3 cadence, cached in `_owner_ok_until` so the
        hot path skips the file). Another replica's unexpired lease
        answers UNAVAILABLE — the fleet proxy's routing (and the
        leader's advance retry loop) converge on whichever replica can
        acquire. Acquiring after ANY foreign/newer epoch drops every
        journal-derived structure and reloads the shared volume: stream
        handoff is journal-directory handoff."""
        if self._owner_lease is None:
            return
        now = time.time()
        if self._owner_epoch and now < self._owner_ok_until:
            return
        st = self._owner_lease.read()
        if (
            st is not None
            and self._owner_epoch
            and st.owner == self._owner_name
            and st.epoch == self._owner_epoch
        ):
            # Still my epoch — even if the TTL lapsed, no rival claimed
            # it in between (a claim bumps the epoch), so the in-memory
            # state is valid; just renew.
            if self._owner_lease.renew(self._owner_epoch):
                self._owner_ok_until = now + self._owner_lease.ttl / 3.0
                return
            st = self._owner_lease.read()  # a rival raced the renew
        if (
            st is not None
            and st.owner != self._owner_name
            and not st.expired(now)
        ):
            raise UnavailableError(
                f"UNAVAILABLE: stream {self.config.name!r} is owned by "
                f"replica {st.owner!r} (epoch {st.epoch}) — retry"
            )
        got = self._owner_lease.try_acquire()
        if got is None:
            raise UnavailableError(
                f"UNAVAILABLE: stream {self.config.name!r} ownership is "
                "contended — retry"
            )
        self._owner_epoch = got
        self._owner_ok_until = now + self._owner_lease.ttl / 3.0
        self._reset_state_locked()
        self._ensure_loaded()
        _tm.counter("streaming.rehomed", op=self.config.name)
        from ..utils import integrity

        integrity.emit_event(
            "stream-rehomed",
            f"stream {self.config.name!r} ownership acquired by "
            f"{self._owner_name!r} at epoch {got} — journals reloaded "
            "from the shared volume",
            "", op=self.config.name,
        )

    def _reset_state_locked(self) -> None:
        """Drops every journal-derived structure (process-lifetime
        counters survive) so the next _ensure_loaded() re-reads the
        shared volume — the ownership-handoff reload."""
        for w in self._windows.values():
            w.journal.close()
        for pw in self._peer_windows.values():
            pw.journal.close()
        self._windows = {}
        self._peer_windows = {}
        self._open = None
        self._accepted = {}
        self._consumed = set()
        self._published = []
        self._published_bids = set()
        self._publish_unacked = []
        self._quarantined_ids = set()
        self._quarantine_unacked = set()
        self._audited = set()
        self._party = None
        self._retired_keys = 0
        self._retired_good_bytes = None
        self._swept_below = 0
        self._loaded = False

    # -- observability ------------------------------------------------------
    def snapshot(self, since_generation: int = 0) -> dict:
        """The hh_snapshot read body: published windows (generation,
        membership, heavy-hitter prefixes + exact counts — the
        continuously-published output), the open window, and the stats
        fields. Counts/prefixes travel as decimal strings (JSON keeps
        them exact at any width). `since_generation` bounds the
        published list to generations >= it (the poller's cursor —
        ``published_total`` always counts the whole history), so a
        long-lived stream's snapshot cost tracks NEW windows, not its
        lifetime."""
        with self._lock:
            self._ensure_owner_locked()
            self._ensure_loaded()
            return {
                "stream": self.config.name,
                "role": self.role,
                "lease_epoch": self._epoch_locked(),
                "threshold": self.config.threshold,
                "window_keys": self.config.window_keys,
                "published_total": len(self._published),
                "published": [
                    w for w in self._published
                    if int(w["generation"]) >= since_generation
                ],
                "open": {
                    "generation": self._open.generation,
                    "batches": len(self._open.batch_ids),
                    "keys": self._open.keys_total,
                },
                "pending_windows": len(self._pending_locked()),
                "stats": self.stats_fields(),
            }

    def _epoch_locked(self) -> int:
        """The epoch the stats/snapshot frames report: the role lease's
        in lease mode, the ownership lease's in shared mode, else 0."""
        if self._lease is not None:
            return self._lease_epoch
        return self._owner_epoch

    def stats_fields(self) -> dict:
        """The per-stream block of the server's stats/health frames
        (wire.STATS_STREAM_KEYS). `role`/`lease_epoch`/`quarantined`
        are the ISSUE 16 additions: a poller can tell which party is
        authoritative after a flip, and how many batches the audit
        rejected. A shared-journal replica that does NOT hold the
        ownership lease reports its process counters with zeroed stream
        state — health frames must never load (or fight over) another
        replica's live journals."""
        with self._lock:
            if not self._owns_now_locked():
                return {
                    "role": self.role,
                    "lease_epoch": self._epoch_locked(),
                    "open_generation": 0,
                    "pending_windows": 0,
                    "pending_keys": 0,
                    "accepted_batches": 0,
                    "accepted_keys": 0,
                    "deduped_batches": self._deduped,
                    "backpressure_rejections": self._backpressure,
                    "windows_published": 0,
                    "journals_rotated": self._rotated,
                    "quarantined": self._quarantined,
                }
            self._ensure_loaded()
            pending = self._pending_locked()
            live_keys = sum(w.keys_total for w in self._windows.values())
            return {
                "role": self.role,
                "lease_epoch": self._epoch_locked(),
                "open_generation": self._open.generation,
                "pending_windows": len(pending),
                "pending_keys": sum(w.keys_total for w in pending),
                "accepted_batches": len(self._accepted),
                "accepted_keys": live_keys + self._retired_keys,
                "deduped_batches": self._deduped,
                "backpressure_rejections": self._backpressure,
                "windows_published": len(self._published),
                "journals_rotated": self._rotated,
                # The durable count, not the process counter: a restart
                # reloads its quarantine verdicts and must keep
                # reporting them (the failover soak's both-parties
                # assertion reads this through a crash).
                "quarantined": len(self._quarantined_ids),
            }
