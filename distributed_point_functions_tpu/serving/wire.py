"""Length-prefixed socket framing + op payload codecs for the two-server
RPC boundary (ISSUE 10).

The FSS deployment model is two non-colluding *network* servers (Poplar,
S&P 2021): each holds one key of every pair, and end-to-end reliability is
dominated by the service boundary, not the kernels. This module is that
boundary's wire layer — deliberately dependency-free (sockets + the
existing protobuf-compatible key formats), so a conforming client in any
language needs only the reference's proto definitions plus the 18-byte
frame header below.

Frame layout (all integers little-endian)::

    magic    4 bytes  b"DPF1"
    version  u8       PROTO_VERSION — checked on EVERY frame, pinned by
                      the HELLO handshake
    type     u8       frame type (T_*)
    id       u64      request id; responses echo the request's id
    body_len u32      bytes of body that follow (bounded by max_body)
    body     ...      type-specific payload

Body payloads reuse protos/wire.py's proto3 primitives, and key material
crosses the wire in the byte-compatible protos/serialization messages
(DpfKey / DcfKey / MicKey) — the same blobs the reference library parses.
Request bodies carry an explicit **deadline_ms** (remaining budget, not an
absolute time: the two ends' clocks never need agreement); the server
re-anchors it on receipt and propagates the remainder into the
supervisor's ``deadline_scope`` so a wire deadline bounds device dispatch
too.

Robustness contract (pinned by tests/test_wire.py):

* a frame with a bad magic, a truncated header/body, or a body over
  ``max_body`` raises :class:`FrameError` (a ``DataLossError``) — the
  stream is unrecoverable past it and the connection must be dropped;
* a clean EOF at a frame boundary reads as ``None`` (orderly close);
* a version mismatch is detected on the first frame and answered with
  ``FAILED_PRECONDITION`` before any payload is parsed.
"""

from __future__ import annotations

import dataclasses
import hashlib
import socket
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.params import DpfParameters
from ..protos import serialization
from ..protos import wire as pb
from ..utils.errors import (
    DataLossError,
    DpfError,
    FailedPreconditionError,
    InternalError,
    InvalidArgumentError,
    ResourceExhaustedError,
    UnavailableError,
)

# ---------------------------------------------------------------------------
# Frame header
# ---------------------------------------------------------------------------

MAGIC = b"DPF1"
PROTO_VERSION = 1

_HEADER = struct.Struct("<4sBBQI")
HEADER_BYTES = _HEADER.size  # 18

#: Default body-size bound. Responses carry limb arrays (a full-domain
#: answer at 2^20 x u128 is 16 MiB); requests are key blobs. 64 MiB keeps
#: a garbage length prefix from allocating the machine away while leaving
#: every real payload comfortable headroom.
DEFAULT_MAX_BODY = 64 << 20

# Frame types.
T_HELLO = 1       # client -> server: version handshake
T_HELLO_OK = 2    # server -> client: handshake accepted
T_REQUEST = 3     # client -> server: one op request
T_RESPONSE = 4    # server -> client: the op's result arrays
T_ERROR = 5       # server -> client: structured failure (code + message)
T_HEALTH = 6      # client -> server: health/readiness probe
T_HEALTH_OK = 7   # server -> client: JSON health body
T_STATS = 8       # client -> server: telemetry-counter probe
T_STATS_OK = 9    # server -> client: JSON counters body

FRAME_TYPES = (
    T_HELLO, T_HELLO_OK, T_REQUEST, T_RESPONSE, T_ERROR,
    T_HEALTH, T_HEALTH_OK, T_STATS, T_STATS_OK,
)

# Status codes on T_ERROR frames (the gRPC/absl numbering, matching
# utils/errors.py's absl mirrors).
OK = 0
INVALID_ARGUMENT = 3
DEADLINE_EXCEEDED = 4
RESOURCE_EXHAUSTED = 8
FAILED_PRECONDITION = 9
INTERNAL = 13
UNAVAILABLE = 14
DATA_LOSS = 15

_CODE_TO_ERROR = {
    INVALID_ARGUMENT: InvalidArgumentError,
    DEADLINE_EXCEEDED: UnavailableError,  # message keeps DEADLINE_EXCEEDED
    RESOURCE_EXHAUSTED: ResourceExhaustedError,
    FAILED_PRECONDITION: FailedPreconditionError,
    INTERNAL: InternalError,
    UNAVAILABLE: UnavailableError,
    DATA_LOSS: DataLossError,
}


class FrameError(DataLossError):
    """The byte stream is no longer a valid frame sequence (bad magic,
    truncation mid-frame, oversized body, unknown type). The only safe
    recovery is dropping the connection — framing has no resync point."""


def status_for_exception(exc: BaseException) -> int:
    """Wire status code for a library exception (server-side mapping).
    Deadline expiries travel as UnavailableError with a DEADLINE_EXCEEDED
    prefix (the supervisor's watchdog convention) — give them their own
    code so clients can fail fast instead of retrying a lost cause."""
    if isinstance(exc, UnavailableError):
        if "DEADLINE_EXCEEDED" in str(exc):
            return DEADLINE_EXCEEDED
        return UNAVAILABLE
    if isinstance(exc, ResourceExhaustedError):
        return RESOURCE_EXHAUSTED
    if isinstance(exc, InvalidArgumentError):
        return INVALID_ARGUMENT
    if isinstance(exc, FailedPreconditionError):
        return FAILED_PRECONDITION
    if isinstance(exc, DataLossError):
        return DATA_LOSS
    return INTERNAL


def exception_for_status(code: int, message: str) -> DpfError:
    """Client-side inverse of :func:`status_for_exception`."""
    cls = _CODE_TO_ERROR.get(code, InternalError)
    exc = cls(message)
    exc.wire_status = code  # type: ignore[attr-defined]
    return exc


#: Status codes a client may retry (with backoff). RESOURCE_EXHAUSTED is
#: the server's explicit backpressure signal — admission control said
#: "later", not "never". DEADLINE_EXCEEDED, INVALID_ARGUMENT etc. fail
#: fast: retrying cannot change the outcome.
RETRYABLE_STATUSES = frozenset({UNAVAILABLE, RESOURCE_EXHAUSTED})


@dataclasses.dataclass
class Frame:
    ftype: int
    request_id: int
    body: bytes = b""
    version: int = PROTO_VERSION


def encode_frame(
    ftype: int, request_id: int, body: bytes = b"",
    version: int = PROTO_VERSION,
) -> bytes:
    if ftype not in FRAME_TYPES:
        raise InvalidArgumentError(f"unknown frame type {ftype}")
    return _HEADER.pack(MAGIC, version, ftype, request_id, len(body)) + body


def write_frame(
    sock: socket.socket, ftype: int, request_id: int, body: bytes = b"",
    version: int = PROTO_VERSION,
) -> None:
    sock.sendall(encode_frame(ftype, request_id, body, version=version))


def _recv_exact(sock: socket.socket, n: int, what: str, any_read: bool):
    """Reads exactly n bytes; returns None on clean EOF at offset 0 when
    ``any_read`` is False (frame boundary), raises FrameError on EOF
    mid-way (a torn frame — the peer died or sent garbage lengths)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0 and not any_read:
                return None
            raise FrameError(
                f"connection closed mid-frame while reading {what} "
                f"({got}/{n} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(
    sock: socket.socket, max_body: int = DEFAULT_MAX_BODY,
    check_version: bool = True,
) -> Optional[Frame]:
    """One frame off the socket, or None on orderly EOF. FrameError on
    any framing violation; socket timeouts propagate as socket.timeout
    (the caller's per-attempt timeout seam)."""
    raw = _recv_exact(sock, HEADER_BYTES, "frame header", any_read=False)
    if raw is None:
        return None
    magic, version, ftype, request_id, body_len = _HEADER.unpack(raw)
    if magic != MAGIC:
        raise FrameError(
            f"bad frame magic {magic!r} (expected {MAGIC!r}): peer is not "
            "speaking the DPF wire protocol, or the stream lost sync"
        )
    if ftype not in FRAME_TYPES:
        raise FrameError(f"unknown frame type {ftype}")
    if body_len > max_body:
        raise FrameError(
            f"frame body of {body_len} bytes exceeds the {max_body}-byte "
            "bound (oversized-frame rejection)"
        )
    if check_version and version != PROTO_VERSION:
        raise FrameError(
            f"frame version {version} != supported {PROTO_VERSION}"
        )
    body = b"" if body_len == 0 else _recv_exact(
        sock, body_len, "frame body", any_read=True
    )
    return Frame(ftype=ftype, request_id=request_id, body=body,
                 version=version)


# ---------------------------------------------------------------------------
# Op identifiers
# ---------------------------------------------------------------------------

#: The bulk entry points served over the wire (the generic in-process
#: ``gate`` op needs a per-class config codec and stays in-process; MIC —
#: the reference's own gate message — rides the wire). "keygen" is the
#: dealer-offload op (ISSUE 13): the client ships parameters + points +
#: per-level values, the server runs the batched level-major keygen and
#: answers with both parties' serialized key blobs — dealers scale
#: horizontally behind the existing retry/deadline machinery. The
#: streaming heavy-hitters tier (ISSUE 15) adds three ops: "hh_ingest"
#: (one client key batch into a named stream's open window — journaled
#: before it is acknowledged), "hh_snapshot" (the published
#: heavy-hitter view, a JSON read op) and "hh_aggregate" (the
#: leader-to-peer per-level share exchange that drives a window's
#: prefix-tree advance). Appended LAST: op ids are positional and
#: wire-stable.
WIRE_OPS = (
    "full_domain", "evaluate_at", "dcf", "mic", "pir", "hierarchical",
    "keygen", "hh_ingest", "hh_snapshot", "hh_aggregate",
)

_OP_TO_ID = {name: i + 1 for i, name in enumerate(WIRE_OPS)}
_ID_TO_OP = {i: name for name, i in _OP_TO_ID.items()}


# ---------------------------------------------------------------------------
# Request / response envelope bodies
# ---------------------------------------------------------------------------


def encode_request_body(
    op: str, payload: bytes, deadline_ms: int = 0, tenant: str = ""
) -> bytes:
    """T_REQUEST body: op id (1), deadline_ms remaining (2), payload (3),
    tenant token (4, ISSUE 20 — appended, so pre-tenant decoders skip it
    as an unknown field). deadline_ms=0 means no deadline; tenant=""
    (the absent-field default, like ``hierarchy_level``'s -1) means
    untenanted: old clients simply never emit field 4 and decode to ""."""
    if op not in _OP_TO_ID:
        raise InvalidArgumentError(
            f"op {op!r} is not servable over the wire (one of {WIRE_OPS})"
        )
    if deadline_ms < 0:
        raise InvalidArgumentError("deadline_ms must be >= 0")
    out = pb.uint64_field(1, _OP_TO_ID[op])
    out += pb.uint64_field(2, int(deadline_ms))
    out += pb.len_field(3, payload)
    if tenant:
        out += pb.len_field(4, tenant.encode("utf-8"))
    return out


def decode_request_body(buf: bytes) -> Tuple[str, int, bytes, str]:
    op_id = deadline_ms = 0
    payload = b""
    tenant = b""
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            op_id = value
        elif field == 2:
            deadline_ms = value
        elif field == 3:
            payload = value
        elif field == 4:
            tenant = value
    op = _ID_TO_OP.get(op_id)
    if op is None:
        raise InvalidArgumentError(f"request carries unknown op id {op_id}")
    return op, int(deadline_ms), payload, tenant.decode("utf-8", "replace")


def encode_error_body(code: int, message: str) -> bytes:
    return pb.uint64_field(1, code) + pb.len_field(
        2, message.encode("utf-8", "replace")
    )


def decode_error_body(buf: bytes) -> Tuple[int, str]:
    code = 0
    message = b""
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            code = value
        elif field == 2:
            message = value
    return int(code), message.decode("utf-8", "replace")


# ---------------------------------------------------------------------------
# Arrays (response payloads)
# ---------------------------------------------------------------------------


def _encode_array(a: np.ndarray) -> bytes:
    """Array message: dtype (1), shape packed varints (2), raw
    little-endian bytes (3) for numeric dtypes, repeated value-integers
    (4) for object arrays (the gate ops' exact-int share values)."""
    a = np.asarray(a)
    shape = b"".join(pb.encode_varint(int(d)) for d in a.shape)
    if a.dtype == object:
        out = pb.len_field(1, b"object")
        out += pb.len_field(2, shape)
        for v in a.reshape(-1):
            out += pb.len_field(4, serialization._encode_value_integer(int(v)))
        return out
    data = np.ascontiguousarray(a)
    if data.dtype.byteorder == ">":  # wire format is little-endian
        data = data.astype(data.dtype.newbyteorder("<"))
    out = pb.len_field(1, data.dtype.str.encode("ascii"))
    out += pb.len_field(2, shape)
    out += pb.len_field(3, data.tobytes())
    return out


def _decode_shape(buf: bytes) -> Tuple[int, ...]:
    shape = []
    pos = 0
    while pos < len(buf):
        d, pos = pb.decode_varint(buf, pos)
        shape.append(d)
    return tuple(shape)


def _decode_array(buf: bytes) -> np.ndarray:
    dtype_s = b""
    shape: Tuple[int, ...] = ()
    data = None
    objs: List[int] = []
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            dtype_s = value
        elif field == 2:
            shape = _decode_shape(value)
        elif field == 3:
            data = value
        elif field == 4:
            objs.append(serialization._decode_value_integer(value))
    if dtype_s == b"object":
        out = np.empty(len(objs), dtype=object)
        out[:] = objs
        return out.reshape(shape)
    if data is None:
        raise DataLossError("array message has no data")
    dtype = np.dtype(dtype_s.decode("ascii"))
    expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if len(data) != expect:
        raise DataLossError(
            f"array data is {len(data)} bytes but shape {shape} x "
            f"{dtype} needs {expect}"
        )
    return np.frombuffer(data, dtype=dtype).reshape(shape).copy()


def encode_result_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    """T_RESPONSE body: repeated array messages (field 1) — a single
    array for most ops, one per plan entry for hierarchical."""
    return b"".join(pb.len_field(1, _encode_array(a)) for a in arrays)


def decode_result_arrays(buf: bytes) -> List[np.ndarray]:
    return [
        _decode_array(v) for f, _, v in pb.iter_fields(buf) if f == 1
    ]


# ---------------------------------------------------------------------------
# Op payload codecs
# ---------------------------------------------------------------------------
#
# Every payload that carries DPF keys also carries the full DpfParameters
# list (repeated field 1) — the server reconstructs the cryptographic
# object from parameters alone (pure validator construction; keygen never
# happens server-side), and the key blobs are the byte-compatible
# serialization messages the reference library produces.


def _encode_params(parameters: Sequence[DpfParameters]) -> bytes:
    return b"".join(
        pb.len_field(1, serialization.encode_dpf_parameters(p))
        for p in parameters
    )


def _encode_points(field: int, points: Sequence[int]) -> bytes:
    return b"".join(
        pb.len_field(field, serialization._encode_value_integer(int(x)))
        for x in points
    )


def _int32_field_explicit(field: int, value: int) -> bytes:
    """int32 with EXPLICIT presence — emitted even when 0. The API
    default for hierarchy_level is -1 (last level), so an absent field
    decodes as -1; a client that means level 0 must say so. Plain
    proto3 `int32_field` omits 0, which here would silently flip a
    level-0 request to last-level."""
    if value < 0:
        value += 1 << 64
    return pb.tag(field, pb.VARINT) + pb.encode_varint(value)


def encode_full_domain(
    parameters: Sequence[DpfParameters], keys: Sequence,
    hierarchy_level: int = -1,
) -> bytes:
    out = _encode_params(parameters)
    for k in keys:
        out += pb.len_field(2, serialization.serialize_dpf_key(k, parameters))
    out += _int32_field_explicit(3, hierarchy_level)
    return out


def decode_full_domain(buf: bytes):
    parameters: List[DpfParameters] = []
    keys = []
    hierarchy_level = -1  # absent field = the API default (last level)
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            parameters.append(serialization.decode_dpf_parameters(value))
        elif field == 2:
            keys.append(serialization.parse_dpf_key(value))
        elif field == 3:
            hierarchy_level = pb.decode_int32(value)
    if not parameters or not keys:
        raise InvalidArgumentError("full_domain payload needs params + keys")
    return parameters, keys, hierarchy_level


def encode_evaluate_at(
    parameters: Sequence[DpfParameters], keys: Sequence,
    points: Sequence[int], hierarchy_level: int = -1,
) -> bytes:
    out = encode_full_domain(parameters, keys, hierarchy_level)
    out += _encode_points(4, points)
    return out


def decode_evaluate_at(buf: bytes):
    # evaluate_at extends full_domain's fields with the point list (4).
    parameters, keys, points = [], [], []
    hierarchy_level = -1  # absent field = the API default (last level)
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            parameters.append(serialization.decode_dpf_parameters(value))
        elif field == 2:
            keys.append(serialization.parse_dpf_key(value))
        elif field == 3:
            hierarchy_level = pb.decode_int32(value)
        elif field == 4:
            points.append(serialization._decode_value_integer(value))
    if not parameters or not keys:
        raise InvalidArgumentError("evaluate_at payload needs params + keys")
    return parameters, keys, points, hierarchy_level


def encode_dcf(
    log_domain_size: int, value_type, keys: Sequence, xs: Sequence[int],
) -> bytes:
    """DCF request: the (log_domain_size, value_type) pair reconstructs
    the DistributedComparisonFunction (its per-level DpfParameters are
    derived, the reference's DcfParameters message —
    protos/serialization.serialize_dcf_parameters); keys are DcfKey
    messages against the derived parameter list."""
    parameters = [
        DpfParameters(i, value_type) for i in range(log_domain_size)
    ]
    out = pb.len_field(
        1, serialization.serialize_dcf_parameters(log_domain_size, value_type)
    )
    for k in keys:
        out += pb.len_field(2, serialization.serialize_dcf_key(k, parameters))
    out += _encode_points(3, xs)
    return out


def decode_dcf(buf: bytes):
    log_domain_size = None
    value_type = None
    key_blobs: List[bytes] = []
    xs: List[int] = []
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            log_domain_size, value_type = serialization.parse_dcf_parameters(
                value
            )
        elif field == 2:
            key_blobs.append(value)
        elif field == 3:
            xs.append(serialization._decode_value_integer(value))
    if log_domain_size is None or not key_blobs:
        raise InvalidArgumentError("dcf payload needs parameters + keys")
    keys = [serialization.parse_dcf_key(b) for b in key_blobs]
    return log_domain_size, value_type, keys, xs


def encode_mic(
    log_group_size: int, intervals, key, xs: Sequence[int],
) -> bytes:
    """MIC request: MicParameters (1) + MicKey (2) + masked inputs (3).
    The MicKey message needs the gate's derived DCF parameter list, which
    MicParameters fully determines (log_group_size -> per-level params)."""
    from ..gates.mic import MultipleIntervalContainmentGate

    dcf = MultipleIntervalContainmentGate._create_dcf(log_group_size)
    parameters = dcf.dpf.validator.parameters
    out = pb.len_field(
        1, serialization.encode_mic_parameters(log_group_size, intervals)
    )
    out += pb.len_field(2, serialization.serialize_mic_key(key, parameters))
    out += _encode_points(3, xs)
    return out


def decode_mic(buf: bytes):
    log_group_size = None
    intervals = []
    key = None
    xs: List[int] = []
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            log_group_size, intervals = serialization.decode_mic_parameters(
                value
            )
        elif field == 2:
            key = serialization.parse_mic_key(value)
        elif field == 3:
            xs.append(serialization._decode_value_integer(value))
    if log_group_size is None or key is None:
        raise InvalidArgumentError("mic payload needs parameters + key")
    return log_group_size, intervals, key, xs


def encode_pir(
    parameters: Sequence[DpfParameters], keys: Sequence, db_name: str,
) -> bytes:
    """PIR request: the database never crosses the wire — it is
    registered server-side under a name at deployment (the two servers
    hold replicas by construction); the request names it."""
    out = _encode_params(parameters)
    for k in keys:
        out += pb.len_field(2, serialization.serialize_dpf_key(k, parameters))
    out += pb.len_field(3, db_name.encode("utf-8"))
    return out


def decode_pir(buf: bytes):
    parameters: List[DpfParameters] = []
    keys = []
    db_name = ""
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            parameters.append(serialization.decode_dpf_parameters(value))
        elif field == 2:
            keys.append(serialization.parse_dpf_key(value))
        elif field == 3:
            db_name = value.decode("utf-8")
    if not parameters or not keys or not db_name:
        raise InvalidArgumentError("pir payload needs params + keys + db name")
    return parameters, keys, db_name


def _encode_plan_entry(hierarchy_level: int, prefixes) -> bytes:
    if isinstance(prefixes, np.ndarray) and prefixes.dtype.fields:
        raise InvalidArgumentError(
            "structured prefix arrays are host-internal; send prefixes as "
            "python ints (value-integers carry up to 128 bits)"
        )
    out = pb.int32_field(1, int(hierarchy_level))
    out += _encode_points(2, [int(p) for p in prefixes])
    return out


def _decode_plan_entry(buf: bytes):
    level = 0
    prefixes: List[int] = []
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            level = pb.decode_int32(value)
        elif field == 2:
            prefixes.append(serialization._decode_value_integer(value))
    return level, prefixes


def encode_hierarchical(
    parameters: Sequence[DpfParameters], keys: Sequence, plan,
    group: int = 16,
) -> bytes:
    out = _encode_params(parameters)
    for k in keys:
        out += pb.len_field(2, serialization.serialize_dpf_key(k, parameters))
    for level, prefixes in plan:
        out += pb.len_field(3, _encode_plan_entry(level, prefixes))
    out += pb.uint64_field(4, int(group))
    return out


def decode_hierarchical(buf: bytes):
    parameters: List[DpfParameters] = []
    keys = []
    plan = []
    group = 16
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            parameters.append(serialization.decode_dpf_parameters(value))
        elif field == 2:
            keys.append(serialization.parse_dpf_key(value))
        elif field == 3:
            plan.append(_decode_plan_entry(value))
        elif field == 4:
            group = int(value)
    if not parameters or not keys or not plan:
        raise InvalidArgumentError(
            "hierarchical payload needs params + keys + plan"
        )
    return parameters, keys, plan, group


def encode_keygen(
    parameters: Sequence[DpfParameters],
    alphas: Sequence[int],
    betas,
) -> bytes:
    """Keygen-offload request: the full DpfParameters list (1), K alpha
    points (2), and one level message (3) per hierarchy level carrying
    that level's K beta values (scalar betas broadcast here, so the wire
    form is always explicit per key). The server is a DEALER in the BGI
    preprocessing model — it learns alpha/beta by design; clients that
    must hide them keep keygen local."""
    from ..core.keygen import normalize_beta_cols

    parameters = list(parameters)
    cols = normalize_beta_cols(betas, len(alphas), len(parameters))
    out = _encode_params(parameters)
    out += _encode_points(2, alphas)
    for level, col in enumerate(cols):
        vt = parameters[level].value_type
        body = b"".join(
            pb.len_field(1, serialization.encode_value(vt, v)) for v in col
        )
        out += pb.len_field(3, body)
    return out


def decode_keygen(buf: bytes):
    parameters: List[DpfParameters] = []
    alphas: List[int] = []
    level_blobs: List[bytes] = []
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            parameters.append(serialization.decode_dpf_parameters(value))
        elif field == 2:
            alphas.append(serialization._decode_value_integer(value))
        elif field == 3:
            level_blobs.append(value)
    if not parameters or not alphas:
        raise InvalidArgumentError("keygen payload needs params + alphas")
    if len(level_blobs) != len(parameters):
        raise InvalidArgumentError(
            f"keygen payload needs one beta column per hierarchy level "
            f"({len(parameters)}), got {len(level_blobs)}"
        )
    betas = []
    for level, blob in enumerate(level_blobs):
        col = [
            serialization.decode_value(v)
            for f, _, v in pb.iter_fields(blob)
            if f == 1
        ]
        if len(col) != len(alphas):
            raise InvalidArgumentError(
                f"keygen betas[{level}] carries {len(col)} values for "
                f"{len(alphas)} alphas"
            )
        betas.append(col)
    return parameters, alphas, betas


# ---------------------------------------------------------------------------
# Streaming heavy hitters (ISSUE 15)
# ---------------------------------------------------------------------------


def encode_hh_ingest(
    stream: str,
    parameters: Sequence[DpfParameters],
    keys: Sequence,
    batch_id: str,
    flush: bool = False,
) -> bytes:
    """Key-ingestion request (ISSUE 15): the full DpfParameters list (1,
    the stream's hierarchy — validated against the server's stream
    config), one serialized DpfKey blob per uploaded key (2, the PR 13
    key-batch wire shape — `keys` may be DpfKey objects or pre-serialized
    bytes), the stream name (3), the client-chosen batch id (4, the
    exactly-once dedup identity: a retried batch with the same id is
    acknowledged, never double-counted) and a flush flag (5: close the
    open window after accepting — an EMPTY batch with flush=True is a
    pure window-close control message)."""
    parameters = list(parameters)
    out = _encode_params(parameters)
    for k in keys:
        blob = (
            bytes(k) if isinstance(k, (bytes, bytearray, memoryview))
            else serialization.serialize_dpf_key(k, parameters)
        )
        out += pb.len_field(2, blob)
    out += pb.len_field(3, stream.encode("utf-8"))
    out += pb.len_field(4, batch_id.encode("utf-8"))
    out += pb.uint64_field(5, 1 if flush else 0)
    return out


def decode_hh_ingest(buf: bytes):
    """-> (parameters, key_blobs, stream, batch_id, flush). Key blobs
    stay RAW bytes: the server journals exactly what it acknowledged and
    parses once — re-serialization at the ingest boundary would be a
    byte-identity hazard on the durability path."""
    parameters: List[DpfParameters] = []
    blobs: List[bytes] = []
    stream = ""
    batch_id = ""
    flush = False
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            parameters.append(serialization.decode_dpf_parameters(value))
        elif field == 2:
            blobs.append(value)
        elif field == 3:
            stream = value.decode("utf-8")
        elif field == 4:
            batch_id = value.decode("utf-8")
        elif field == 5:
            flush = bool(value)
    if not parameters or not stream:
        raise InvalidArgumentError(
            "hh_ingest payload needs params + stream name"
        )
    return parameters, blobs, stream, batch_id, flush


def encode_hh_snapshot(stream: str, since_generation: int = 0) -> bytes:
    """Snapshot read request: the stream name (1) and an optional
    published-window cursor (2): only windows with generation >=
    `since_generation` are returned. A long-lived stream publishes
    windows forever — pollers pass their last seen generation + 1 so
    the response stays O(new windows), not O(stream lifetime)."""
    return pb.len_field(1, stream.encode("utf-8")) + pb.uint64_field(
        2, int(since_generation)
    )


def decode_hh_snapshot(buf: bytes) -> Tuple[str, int]:
    stream = ""
    since = 0
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            stream = value.decode("utf-8")
        elif field == 2:
            since = int(value)
    if not stream:
        raise InvalidArgumentError("hh_snapshot payload needs a stream name")
    return stream, since


def encode_hh_aggregate(
    stream: str, generation: int, batch_ids: Sequence[str], plan, *,
    epoch: int = 0, publish: Optional[dict] = None, audit: bool = False,
    quarantine: Sequence[str] = (),
) -> bytes:
    """Leader-to-peer aggregate request: stream (1), window generation
    (2), the window's batch-id membership in leader order (3 — the peer
    assembles ITS OWN share keys for exactly these acknowledged batches;
    sums are order-independent) and the full level trail so far (4, the
    hierarchical plan-entry message: the peer fast-forwards a freshly
    restarted window through every earlier level deterministically). The
    response is the LAST entry's aggregate share vector.

    ISSUE 16 appended fields, all ABSENT in the PR 15 encoding (old
    payloads decode to the old meaning, old decoders skip unknown
    fields): lease epoch (5 — the zombie fence; 0 = no lease), a publish
    record to replicate as JSON (6), the audit flag (7 — serve the
    named batches' level-0 aggregate from a throwaway context), and
    quarantined batch ids to apply (8). A leg with no level trail is a
    pure notification (publish / quarantine / audit only)."""
    import json as _json

    out = pb.len_field(1, stream.encode("utf-8"))
    out += pb.uint64_field(2, int(generation))
    for bid in batch_ids:
        out += pb.len_field(3, bid.encode("utf-8"))
    for level, prefixes in plan:
        out += pb.len_field(4, _encode_plan_entry(level, prefixes))
    if epoch:
        out += pb.uint64_field(5, int(epoch))
    if publish is not None:
        out += pb.len_field(
            6, _json.dumps(publish, sort_keys=True).encode("utf-8")
        )
    if audit:
        out += pb.uint64_field(7, 1)
    for bid in quarantine:
        out += pb.len_field(8, bid.encode("utf-8"))
    return out


def decode_hh_aggregate(buf: bytes):
    """-> (stream, generation, batch_ids, plan, extras) with extras =
    {"epoch", "publish", "audit", "quarantine"} (ISSUE 16 — defaults
    reproduce the PR 15 meaning for old payloads)."""
    import json as _json

    stream = ""
    generation = 0
    batch_ids: List[str] = []
    plan = []
    extras = {
        "epoch": 0, "publish": None, "audit": False, "quarantine": [],
    }
    for field, _, value in pb.iter_fields(buf):
        if field == 1:
            stream = value.decode("utf-8")
        elif field == 2:
            generation = int(value)
        elif field == 3:
            batch_ids.append(value.decode("utf-8"))
        elif field == 4:
            plan.append(_decode_plan_entry(value))
        elif field == 5:
            extras["epoch"] = int(value)
        elif field == 6:
            try:
                extras["publish"] = _json.loads(value.decode("utf-8"))
            except ValueError as exc:
                raise InvalidArgumentError(
                    f"hh_aggregate publish record is not JSON: {exc}"
                ) from exc
        elif field == 7:
            extras["audit"] = bool(int(value))
        elif field == 8:
            extras["quarantine"].append(value.decode("utf-8"))
    if not stream or not (
        plan or extras["publish"] is not None or extras["audit"]
        or extras["quarantine"]
    ):
        raise InvalidArgumentError(
            "hh_aggregate payload needs stream name + level trail "
            "(or an ISSUE 16 notification: publish/audit/quarantine)"
        )
    return stream, generation, batch_ids, plan, extras


def json_result_arrays(body: dict) -> List[np.ndarray]:
    """A JSON body as the generic result-array stream (one uint8 array) —
    the hh_snapshot response form (python ints of any width serialize
    exactly; the client json-parses the bytes back)."""
    import json as _json

    return [
        np.frombuffer(
            _json.dumps(body, sort_keys=True).encode("utf-8"), np.uint8
        ).copy()
    ]


def json_from_arrays(arrays: Sequence[np.ndarray]) -> dict:
    """Inverse of :func:`json_result_arrays`."""
    import json as _json

    if not arrays:
        raise DataLossError("JSON response carries no array")
    return _json.loads(
        np.asarray(arrays[0], dtype=np.uint8).tobytes().decode("utf-8")
    )


# ---------------------------------------------------------------------------
# Fleet routing + stats aggregation (ISSUE 14)
# ---------------------------------------------------------------------------

#: Health/stats body keys added for fleet routing (ISSUE 14), all
#: BACKWARD-COMPATIBLE: new keys in the existing JSON bodies, which old
#: clients simply never read (pinned by the re-encode test in
#: tests/test_wire.py). ``queues`` = per-op queued request counts,
#: ``inflight`` = requests currently being handled, ``served`` = total
#: requests answered this process, ``warm`` = the warm-cache digest
#: inventory per tier (pir/plans/keys).
STATS_FLEET_KEYS = ("queues", "inflight", "served", "warm")

#: Health/stats body keys added for the streaming heavy-hitters tier
#: (ISSUE 15), following the STATS_FLEET_KEYS pattern — new keys in the
#: existing JSON bodies, BACKWARD-COMPATIBLE both directions (old bodies
#: merge fine, old clients never read the new key). ``streams`` maps
#: stream name -> its counters: open window generation, pending window
#: depth (the backpressure bound), keys/batches accepted + deduped,
#: windows published, journals rotated — plus, since ISSUE 16, ``role``
#: / ``lease_epoch`` (which party is authoritative after a failover
#: flip, and under which lease epoch) and ``quarantined`` (batches the
#: share-consistency audit rejected). Old PR 15 bodies simply lack the
#: new fields and merge fine.
STATS_STREAM_KEYS = ("streams",)

#: Health/stats body keys added for the elastic serving plane
#: (ISSUE 20), same additive contract as STATS_FLEET_KEYS /
#: STATS_STREAM_KEYS: new keys in the existing JSON bodies that old
#: consumers never read and old servers simply don't contribute.
#: ``rates`` maps op -> the batcher's arrival-rate EWMA (requests per
#: second — the signal the autoscaler consumes, summed across
#: replicas); ``tenants`` maps tenant token -> its admission/serving
#: counters (pending / admitted / rejected / served, summed across
#: replicas).
STATS_QOS_KEYS = ("rates", "tenants")

#: Per-stream stats fields that aggregate by MAX across replicas (the
#: open generation and the lease epoch are high-water marks, not
#: rates); every other numeric field sums, non-numeric fields (role)
#: keep the first body's.
_STREAM_MAX_FIELDS = frozenset({"open_generation", "lease_epoch"})

#: Request-payload fields, per op, that determine the request's
#: compatibility-queue key and warm-cache identity on the replica — the
#: affinity-routing digest hashes EXACTLY these. Key material is
#: deliberately EXCLUDED for the key-merged ops (full_domain /
#: evaluate_at / dcf / keygen): two clients' different keys must still
#: land on ONE replica and merge into one batch there — routing on
#: (op, parameters, level) keeps every mergeable request together, which
#: also keeps a repeated key set's PreparedKeyBatch tier hot. The gate
#: ops (mic) INCLUDE the key blob: their queues are per-key anyway, so
#: per-key spreading buys load balance without losing any merge. pir
#: adds the database name (the PreparedPirDatabase tier), hierarchical
#: the plan entries + group (the PreparedLevelsPlan tier).
_ROUTING_FIELDS: Dict[str, Tuple[int, ...]] = {
    "full_domain": (1, 3),      # params, hierarchy_level
    "evaluate_at": (1, 3),      # params, hierarchy_level
    "dcf": (1,),                # dcf parameters
    "mic": (1, 2),              # mic parameters, key blob (per-key queues)
    "pir": (1, 3),              # params, db name
    "hierarchical": (1, 3, 4),  # params, plan entries, group
    "keygen": (1,),             # params (any same-parameter batch merges)
    # Streaming ops route on the stream identity: one replica owns a
    # stream's window state (journals + contexts are process-local).
    "hh_ingest": (3,),          # stream name
    "hh_snapshot": (1,),        # stream name
    "hh_aggregate": (1,),       # stream name
}


def routing_digest(op: str, payload: bytes) -> str:
    """Affinity-routing digest of a request payload (ISSUE 14): the
    fleet proxy rendezvous-hashes this against the replica set so
    requests that share a compatibility queue — and therefore a
    warm-cache tier — always meet on the same replica. Computed from the
    raw payload fields (no key parsing, no crypto-object construction):
    the proxy must stay cheap per frame."""
    fields = _ROUTING_FIELDS.get(op)
    if fields is None:
        raise InvalidArgumentError(
            f"op {op!r} has no routing rule (one of {sorted(_ROUTING_FIELDS)})"
        )
    h = hashlib.sha256(op.encode())
    for field, _, value in pb.iter_fields(payload):
        if field not in fields:
            continue
        h.update(struct.pack("<I", field))
        if isinstance(value, int):  # varint/fixed field (hierarchy level…)
            h.update(struct.pack("<Q", value & ((1 << 64) - 1)))
        else:  # length-delimited (params / key / name / plan blobs)
            h.update(struct.pack("<I", len(value)))
            h.update(value)
    return h.hexdigest()[:16]


def merge_stats(bodies: Sequence[dict]) -> dict:
    """Aggregates replica stats bodies (T_STATS_OK JSON) into one fleet
    view: counters / gauges / queue depths / inflight / served SUM
    across replicas, ``wall_seconds`` takes the max (replicas started
    together; the eldest bounds the window), warm inventories
    concatenate. Bodies missing the ISSUE 14 keys (an older server)
    aggregate fine — the keys are additive, both directions."""
    out: dict = {
        "wall_seconds": 0.0, "counters": {}, "gauges": {},
        "decisions_by_source": {}, "integrity_by_kind": {},
        "queues": {}, "inflight": 0, "served": 0,
        "warm": {"pir": [], "plans": [], "keys": []},
        "streams": {}, "rates": {}, "tenants": {},
    }
    for body in bodies:
        out["wall_seconds"] = max(
            out["wall_seconds"], float(body.get("wall_seconds", 0.0))
        )
        for section in ("counters", "decisions_by_source",
                        "integrity_by_kind", "queues"):
            for k, v in (body.get(section) or {}).items():
                out[section][k] = out[section].get(k, 0) + v
        # Gauges are {"last", "max"} dicts; summing across replicas is
        # the fleet reading (aggregate queue depth etc.).
        for k, v in (body.get("gauges") or {}).items():
            prev = out["gauges"].get(k, {"last": 0, "max": 0})
            out["gauges"][k] = {
                "last": prev["last"] + v.get("last", 0),
                "max": prev["max"] + v.get("max", 0),
            }
        out["inflight"] += int(body.get("inflight", 0))
        out["served"] += int(body.get("served", 0))
        for tier, digests in (body.get("warm") or {}).items():
            out["warm"].setdefault(tier, []).extend(digests)
        # Streaming fields (ISSUE 15): per-stream numeric fields sum,
        # except the generation high-water marks which take the max —
        # like the gauges above, a snapshot field is not a rate. Old
        # bodies simply lack the key.
        for name, fields in (body.get("streams") or {}).items():
            agg = out.setdefault("streams", {}).setdefault(name, {})
            for k, v in fields.items():
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    agg.setdefault(k, v)
                elif k in _STREAM_MAX_FIELDS:
                    agg[k] = max(agg.get(k, v), v)
                else:
                    agg[k] = agg.get(k, 0) + v
        # QoS fields (ISSUE 20): arrival-rate EWMAs sum (fleet demand is
        # the sum of replica demand) and per-tenant counters sum. Old
        # bodies simply lack the keys.
        for op_name, rate in (body.get("rates") or {}).items():
            out["rates"][op_name] = out["rates"].get(op_name, 0.0) + rate
        for tenant, fields in (body.get("tenants") or {}).items():
            agg = out["tenants"].setdefault(tenant, {})
            for k, v in fields.items():
                agg[k] = agg.get(k, 0) + v
    return out


def keygen_result_arrays(
    keys_0: Sequence, keys_1: Sequence, parameters: Sequence[DpfParameters]
) -> List[np.ndarray]:
    """Keygen response as the generic result-array stream: 2K uint8 blob
    arrays — K party-0 serialized DpfKey messages, then K party-1 — so
    the response rides `encode_result_arrays` unchanged."""
    return [
        np.frombuffer(
            serialization.serialize_dpf_key(k, list(parameters)), np.uint8
        )
        for k in list(keys_0) + list(keys_1)
    ]


def keygen_keys_from_arrays(arrays: Sequence[np.ndarray]):
    """Inverse of :func:`keygen_result_arrays`: (keys_0, keys_1)."""
    if len(arrays) % 2:
        raise DataLossError(
            f"keygen response carries {len(arrays)} blobs (expected an "
            "even count: K per party)"
        )
    k = len(arrays) // 2
    keys = [
        serialization.parse_dpf_key(np.asarray(a, dtype=np.uint8).tobytes())
        for a in arrays
    ]
    return keys[:k], keys[k:]
