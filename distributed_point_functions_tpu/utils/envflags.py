"""Shared environment-flag parsing.

One canonical parser for the library's boolean env switches
(DPF_TPU_PALLAS, DPF_TPU_FUSE_LAST_HASH, DPF_TPU_INTEGRITY, ...): two
copies could drift and silently make two flags parse differently.
"""

from __future__ import annotations

import os

from .errors import InvalidArgumentError


def env_bool(name: str, default: bool = False) -> bool:
    """Boolean env flag with STRICT parsing: unrecognized values raise
    instead of silently picking a side (a typo in an A/B benchmark flag
    must not measure the same path twice)."""
    env = os.environ.get(name)
    if env is None:
        return default
    low = env.strip().lower()
    if low in ("1", "true", "yes", "on"):
        return True
    if low in ("0", "false", "no", "off", ""):
        return False
    raise InvalidArgumentError(
        f"{name} must be a boolean-ish value, got {env!r}"
    )
