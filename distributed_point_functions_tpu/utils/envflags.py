"""Shared environment-flag parsing.

One canonical parser per flag *type* for the library's env switches
(DPF_TPU_PALLAS, DPF_TPU_FUSE_LAST_HASH, DPF_TPU_INTEGRITY, ...): two
copies could drift and silently make two flags parse differently — a
typo in an A/B benchmark flag must not measure the same path twice.

This is the ONLY module in the library allowed to touch ``os.environ``
directly (enforced by ``tools/dpflint``'s env-discipline checker); every
other module reads flags through these helpers. Parsing is STRICT:
unrecognized values raise ``InvalidArgumentError`` instead of silently
picking a side. Unset — and, for the numeric helpers, blank — values
resolve to the caller's default.
"""

from __future__ import annotations

import os
from typing import Optional

from .errors import InvalidArgumentError


def env_bool(name: str, default: bool = False) -> bool:
    """Boolean env flag with STRICT parsing: unrecognized values raise
    instead of silently picking a side (a typo in an A/B benchmark flag
    must not measure the same path twice)."""
    env = os.environ.get(name)
    if env is None:
        return default
    low = env.strip().lower()
    if low in ("1", "true", "yes", "on"):
        return True
    if low in ("0", "false", "no", "off", ""):
        return False
    raise InvalidArgumentError(
        f"{name} must be a boolean-ish value, got {env!r}"
    )


def env_opt_bool(name: str) -> Optional[bool]:
    """Tri-state boolean: None when the flag is UNSET (callers fall back
    to a platform-dependent default), else the strict env_bool parse —
    an explicitly empty value parses False, matching the historical
    ``if name in os.environ`` call sites."""
    if name not in os.environ:
        return None
    return env_bool(name)


def env_int(name: str, default: int) -> int:
    """Integer env flag: unset/blank -> default, anything unparsable
    raises (strict, like env_bool)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return int(raw.strip())
    except ValueError:
        raise InvalidArgumentError(
            f"{name} must be an integer, got {raw!r}"
        )


def env_float(name: str, default: Optional[float]) -> Optional[float]:
    """Float env flag: unset/blank -> default, anything unparsable
    raises (strict, like env_bool)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw.strip())
    except ValueError:
        raise InvalidArgumentError(
            f"{name} must be a float, got {raw!r}"
        )


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """String env flag (paths, addresses): unset -> default, no parsing.
    Exists so non-envflags modules never touch os.environ directly."""
    return os.environ.get(name, default)
