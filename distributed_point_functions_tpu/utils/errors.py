"""Error types for the framework.

The reference reports failures through absl::Status codes
(/root/reference/dpf/status_macros.h). At a Python API edge the idiomatic
equivalent is exceptions; we keep the same *categories* so tests can assert on
them the way the reference asserts on status codes.
"""


class DpfError(Exception):
    """Base class for all framework errors."""


class InvalidArgumentError(DpfError, ValueError):
    """Mirrors absl::InvalidArgumentError."""


class FailedPreconditionError(DpfError, RuntimeError):
    """Mirrors absl::FailedPreconditionError."""


class UnimplementedError(DpfError, NotImplementedError):
    """Mirrors absl::UnimplementedError."""
