"""Error types for the framework.

The reference reports failures through absl::Status codes
(/root/reference/dpf/status_macros.h). At a Python API edge the idiomatic
equivalent is exceptions; we keep the same *categories* so tests can assert on
them the way the reference asserts on status codes.
"""


class DpfError(Exception):
    """Base class for all framework errors."""


class InvalidArgumentError(DpfError, ValueError):
    """Mirrors absl::InvalidArgumentError."""


class FailedPreconditionError(DpfError, RuntimeError):
    """Mirrors absl::FailedPreconditionError."""


class UnimplementedError(DpfError, NotImplementedError):
    """Mirrors absl::UnimplementedError."""


class InternalError(DpfError, RuntimeError):
    """Mirrors absl::InternalError: an invariant of the library itself is
    broken (dispatch-table misses, self-test failures of the host oracle)."""


class DataLossError(DpfError, RuntimeError):
    """Mirrors absl::DataLossError: unrecoverable loss or corruption of
    data — truncated wire bytes, garbled serialized keys."""


class UnavailableError(DpfError, RuntimeError):
    """Mirrors absl::UnavailableError: a backend or engine is (transiently)
    unreachable; the operation may succeed on retry or on a fallback."""


class ResourceExhaustedError(DpfError, RuntimeError):
    """Mirrors absl::ResourceExhaustedError: out of device memory or a
    similar quota; retrying with a smaller batch may succeed."""


class DataCorruptionError(DataLossError):
    """Silent wrong results detected by runtime integrity checks.

    Raised when a sentinel probe key's device output disagrees with the
    host oracle (utils/integrity.py) — the failure mode PERF.md "Platform
    findings" documents on this image's TPU tunnel, where batched programs
    return garbage in specific lanes with no error signal. Carries the
    diagnostics an operator needs to correlate with a platform bug report:

      key_index  — which row of the batch mismatched (the probe's row)
      lanes      — corrupted output positions (possibly truncated)
      pattern    — human-readable structure of the corruption, e.g.
                   "all corrupted positions have index bit 4 set"
      backend    — the backend level that produced the bad output
    """

    def __init__(
        self,
        message: str,
        *,
        key_index=None,
        lanes=None,
        pattern: str = "",
        backend: str = "",
    ):
        super().__init__(message)
        self.key_index = key_index
        self.lanes = [] if lanes is None else list(lanes)
        self.pattern = pattern
        self.backend = backend
