"""Deterministic fault injection for the runtime integrity layer.

The reference library is tested by differential fuzzing against a scalar
oracle; this repo additionally runs on hardware that has *demonstrably*
corrupted results in production-shaped programs (PERF.md "Platform
findings": a K=64 batched expansion returned garbage in every lane with
bit 4 set while the identical program was bit-exact on XLA:CPU). The
integrity layer (utils/integrity.py) exists to detect that class of
failure at runtime — and a detector that has never seen a fault is
untested code. This module injects faults *deterministically* at the four
seams where real corruption has been observed or is conceivable:

  ``seeds``         — flip a bit of one key's root seed in the prepared
                      device batch (models host-link bit rot / bad DMA).
  ``cw``            — flip a bit of one correction word (same, but level-
                      targeted: corruption surfaces only below that level).
  ``wire``          — truncate or bit-flip serialized key bytes (models a
                      corrupted RPC payload between the two servers).
  ``device_output`` — corrupt evaluated values after the device call,
                      including a replay of the exact upper-16-lane
                      pattern from PERF.md (``pattern="bit4"``).
  ``device_call``   — raise an injected exception instead of running the
                      backend (models UNAVAILABLE / RESOURCE_EXHAUSTED
                      from the runtime, for degradation-policy tests).
  ``chunk_launch``  — raise an injected exception at ONE chunk's launch
                      inside the pipelined executor (ops/pipeline.py):
                      models a failure surfacing mid-pipeline, with other
                      chunks already in flight.
  ``chunk_delay``   — sleep ``delay_launch`` / ``delay_finalize`` seconds
                      at each chunk's launch / finalize stage boundary:
                      an artificial per-chunk dispatch latency + pull
                      cost, so overlap is measurable on CPU where the
                      real ~66 ms tunnel latency does not exist
                      (tests/test_pipeline.py's overlap proxy).
  ``device_hang``   — sleep ``hang_seconds`` at ONE chunk's launch or
                      finalize boundary (``hang_point``): models a
                      *hung* dispatch or pull — the axon-tunnel failure
                      mode where a device call neither returns nor
                      errors. The supervisor's dispatch-deadline
                      watchdog (ops/supervisor.py, DPF_TPU_DEADLINE)
                      must convert it into ``UnavailableError`` within
                      the deadline; without a deadline armed the hook
                      sleeps the full ``hang_seconds`` — exactly the
                      wedged executor ISSUE 7 exists to cure, kept
                      finite so tests terminate.

Faults are scoped by a context manager and never active by default; every
hook is a no-op returning its input unchanged when no plan is armed, so
production paths pay one truthiness check. Plans are plain data — no
randomness — so every test failure replays exactly. ``backends`` /
``modes`` scope a plan to specific fallback-chain rungs (the supervisor's
(mode, backend) chains), ``skip_fires`` delays arming past the first N
matches (how a chaos schedule fails chunk N of a journaled job), and
``max_fires`` bounds the total count of actual firings.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import FrozenSet, Optional

import numpy as np

from .errors import InvalidArgumentError

#: Recognized injection stages (see module docstring).
STAGES = (
    "seeds", "cw", "wire", "device_output", "device_call", "chunk_launch",
    "chunk_delay", "device_hang",
)


@dataclasses.dataclass
class FaultPlan:
    """One deterministic fault. Arm with :func:`inject`.

    ``key_row`` selects the batch row to corrupt (negative = from the end,
    so ``-1`` hits an appended sentinel probe). ``backends`` restricts the
    plan to specific backend levels ("pallas" / "jax" / "numpy"); None
    fires everywhere. ``modes`` restricts it further to specific execution
    modes of a supervisor (mode, backend) rung ("megakernel" /
    "walkkernel" / "hierkernel" / "fold" / "walk" / "fused"); a
    mode-scoped plan NEVER fires at hooks that do not declare a mode
    (backend-only seams, the numpy rung) — it targets exactly the named
    rungs. ``skip_fires`` lets the first N
    matching hook calls pass clean before the plan arms (a mid-job
    failure: chunks 0..N-1 verify and journal, chunk N dies).
    ``max_fires`` bounds how many times the plan actually triggers after
    that (e.g. 1 = corrupt the first armed attempt only, so a retry or a
    fallback level sees clean data).
    """

    stage: str
    # seeds / cw
    bit: int = 0
    key_row: int = -1
    level: int = 0
    # wire
    wire_mode: str = "truncate"  # or "flip"
    wire_arg: int = 1  # bytes to drop (truncate) / byte index (flip)
    # device_output
    pattern: str = "bit4"  # or "lane"
    lane: int = 0
    xor_mask: int = 0xDEADBEEF
    # device_call / chunk_launch
    exception: Optional[BaseException] = None
    # chunk_delay (seconds slept per chunk at each pipeline stage)
    delay_launch: float = 0.0
    delay_finalize: float = 0.0
    # device_hang (seconds one chunk wedges; point "launch" / "finalize")
    hang_seconds: float = 0.0
    hang_point: str = "finalize"
    # scoping
    backends: Optional[FrozenSet[str]] = None
    modes: Optional[FrozenSet[str]] = None
    skip_fires: int = 0
    max_fires: Optional[int] = None
    fires: int = 0

    def __post_init__(self):
        if self.stage not in STAGES:
            raise InvalidArgumentError(
                f"unknown fault stage {self.stage!r}; one of {STAGES}"
            )

    def _matches(
        self, stage: str, backend: Optional[str], mode: Optional[str] = None
    ) -> bool:
        if self.stage != stage:
            return False
        if self.backends is not None and backend is not None:
            if backend not in self.backends:
                return False
        if self.modes is not None:
            # A mode-scoped plan targets exactly the named chain rungs: a
            # hook that declares no mode (backend-only seams, the numpy
            # rung) never matches it — else a plan aimed at a kernel rung
            # would also poison the recovery levels below it.
            if mode is None or mode not in self.modes:
                return False
        limit = (
            None
            if self.max_fires is None
            else self.skip_fires + self.max_fires
        )
        return limit is None or self.fires < limit


_active: list = []


def is_active() -> bool:
    """Fast-path guard for the production hooks."""
    return bool(_active)


@contextlib.contextmanager
def inject(*plans: FaultPlan):
    """Arms `plans` for the dynamic extent of the with-block."""
    _active.extend(plans)
    try:
        yield plans
    finally:
        for p in plans:
            _active.remove(p)


def _take(
    stage: str,
    backend: Optional[str],
    mode: Optional[str] = None,
    pred=None,
) -> Optional[FaultPlan]:
    for plan in _active:
        if not plan._matches(stage, backend, mode):
            continue
        if pred is not None and not pred(plan):
            # Stage-specific scoping (e.g. device_hang's hang_point):
            # a non-matching plan is not consumed.
            continue
        plan.fires += 1
        if plan.fires <= plan.skip_fires:
            # Matched but not yet armed: this call passes clean and the
            # match is consumed (deterministic mid-job scheduling).
            continue
        return plan
    return None


# ---------------------------------------------------------------------------
# Hooks (called from the library's evaluation paths)
# ---------------------------------------------------------------------------


def corrupt_seeds(seeds: np.ndarray, backend: Optional[str] = None) -> np.ndarray:
    """uint32[K, 4] root seeds -> possibly one bit flipped in one row."""
    plan = _take("seeds", backend)
    if plan is None:
        return seeds
    out = np.array(seeds, copy=True)
    row = plan.key_row % out.shape[0]
    out[row, (plan.bit // 32) % 4] ^= np.uint32(1 << (plan.bit % 32))
    return out


def corrupt_cw(cw_seeds: np.ndarray, backend: Optional[str] = None) -> np.ndarray:
    """uint32[K, L, 4] correction-word seeds -> one bit flipped at one
    (row, level)."""
    plan = _take("cw", backend)
    if plan is None:
        return cw_seeds
    out = np.array(cw_seeds, copy=True)
    row = plan.key_row % out.shape[0]
    level = plan.level % max(out.shape[1], 1)
    out[row, level, (plan.bit // 32) % 4] ^= np.uint32(1 << (plan.bit % 32))
    return out


def corrupt_wire(blob: bytes, backend: Optional[str] = None) -> bytes:
    """Serialized key bytes -> truncated or bit-flipped."""
    plan = _take("wire", backend)
    if plan is None:
        return blob
    if plan.wire_mode == "truncate":
        return blob[: max(0, len(blob) - plan.wire_arg)]
    if plan.wire_mode == "flip":
        b = bytearray(blob)
        b[plan.wire_arg % len(b)] ^= 1 << (plan.bit % 8)
        return bytes(b)
    raise InvalidArgumentError(f"unknown wire_mode {plan.wire_mode!r}")


def corrupt_output(values: np.ndarray, backend: Optional[str] = None) -> np.ndarray:
    """uint32[K, positions, lpe] evaluated values -> corrupted copy.

    pattern="bit4" replays the PERF.md platform fault: every position
    whose index has bit 4 set (lanes 16..31 of each packed 32-lane word)
    is XORed with `xor_mask`, in the selected key row. pattern="lane"
    corrupts the single position `lane`.
    """
    plan = _take("device_output", backend)
    if plan is None:
        return values
    out = np.array(values, copy=True)
    row = plan.key_row % out.shape[0]
    if plan.pattern == "bit4":
        idx = np.nonzero((np.arange(out.shape[1]) >> 4) & 1)[0]
    elif plan.pattern == "lane":
        idx = np.array([plan.lane % out.shape[1]])
    else:
        raise InvalidArgumentError(f"unknown output pattern {plan.pattern!r}")
    out[row, idx] ^= np.uint32(plan.xor_mask)
    return out


def maybe_raise(
    stage: str = "device_call",
    backend: Optional[str] = None,
    mode: Optional[str] = None,
) -> None:
    """Raises the armed plan's exception (degradation-policy tests).
    stage "device_call" fires once per rung attempt (ops/degrade.py's
    chain walk passes the rung's mode so mode-scoped plans can fail e.g.
    only the "walkkernel" rung); stage "chunk_launch" fires per chunk
    inside the pipelined executor."""
    plan = _take(stage, backend, mode)
    if plan is not None and plan.exception is not None:
        raise plan.exception


def chunk_delay(point: str, backend: Optional[str] = None) -> None:
    """Sleeps the armed chunk_delay plan's configured seconds at one
    pipeline stage boundary (`point` is "launch" or "finalize") — the
    artificial per-chunk dispatch latency behind the CPU-measurable
    overlap proxy (ops/pipeline.py; ISSUE 2 acceptance). The serial and
    pipelined executors both call this once per chunk per point, so the
    injected cost is identical on the two sides of an A/B."""
    if not _active:
        return
    plan = _take("chunk_delay", backend)
    if plan is None:
        return
    seconds = plan.delay_launch if point == "launch" else plan.delay_finalize
    if seconds > 0:
        time.sleep(seconds)


def device_hang(point: str, backend: Optional[str] = None) -> None:
    """Sleeps the armed device_hang plan's ``hang_seconds`` at one pipeline
    stage boundary when ``hang_point`` matches `point` ("launch" or
    "finalize") — the CPU-testable stand-in for a wedged device dispatch
    or pull (the tunnel failure mode that today blocks forever). The
    supervisor runs this hook *inside* its deadline-watchdog scope
    (ops/pipeline.py launch thunks, finalize waits), so an armed
    DPF_TPU_DEADLINE converts the hang to ``UnavailableError`` while the
    hung sleep finishes out on a daemon thread."""
    if not _active:
        return
    plan = _take(
        "device_hang", backend,
        pred=lambda p: p.hang_point in (point, "any"),
    )
    if plan is not None and plan.hang_seconds > 0:
        time.sleep(plan.hang_seconds)
