"""Runtime integrity layer: sentinel-key verification and backend self-test.

This image's TPU tunnel has *silently corrupted* DPF evaluations in
production-shaped programs (PERF.md "Platform findings": a K=64 batched
expansion returned garbage in every lane with bit 4 set, while the
identical program was bit-exact on XLA:CPU). In a two-server FSS
deployment a silently wrong answer is strictly worse than a crash, so
correctness checking is a *library* capability here, not a bench-script
afterthought:

* **Known-answer self-test** (:func:`ensure_selftest`): the fixed-key
  AES-MMO hash — the single primitive every DPF operation reduces to —
  is checked once per backend against pinned outputs derived from the
  reference-parity numpy oracle. A host mismatch raises
  ``InternalError`` (the library itself is broken); a device mismatch
  raises ``DataCorruptionError`` (the backend miscomputes).
* **Sentinel probe keys** (:func:`make_probe` / :func:`verify_probe_*`):
  batched device calls (``ops/evaluator.full_domain_evaluate`` /
  ``evaluate_at_batch``, the sharded paths in ``parallel/sharded.py``)
  can append one library-generated probe key whose output is recomputed
  on the host oracle (``core/host_eval.py``). Because the probe rides the
  *same program at the same batch shape* as the real keys, it catches
  exactly the shape-dependent corruption the platform has produced. A
  mismatch raises ``DataCorruptionError`` carrying the corrupted lane
  indices and the recognized bit pattern.
* **Structured events** (:func:`add_event_hook`): every integrity verdict
  and every degradation decision (``ops/degrade.py``) emits an
  :class:`IntegrityEvent` through registered hooks and the
  ``distributed_point_functions_tpu.integrity`` logger, so operators can
  see when a server is running degraded. Since ISSUE 6 the hook registry
  is the telemetry bus's locked, exception-isolated
  ``telemetry.HookRegistry`` and every event is also forwarded onto that
  bus (``utils/telemetry.py``: capture()/snapshot(), the JSONL sink, the
  summary table) — ``add_event_hook``/``capture_events`` remain the
  back-compat surface.

Enabled per-call via the ``integrity=`` keyword or process-wide via the
``DPF_TPU_INTEGRITY`` env var (strict boolean parsing; unset = off).
``tools/check_device.py`` is a thin CLI over :func:`run_device_check`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from . import faultinject, telemetry
from .envflags import env_bool as _env_bool
from .envflags import env_str as _env_str
from .errors import (
    DataCorruptionError,
    DataLossError,
    InternalError,
    InvalidArgumentError,
    UnavailableError,
)

_log = logging.getLogger("distributed_point_functions_tpu.integrity")


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------


def enabled(override: Optional[bool] = None) -> bool:
    """Resolves the integrity switch: explicit keyword wins, else the
    DPF_TPU_INTEGRITY env var, else off (verification costs one extra key
    per batch plus one host-oracle probe evaluation per parameter set —
    opt-in, like the reference's optional expensive validations)."""
    if override is not None:
        return bool(override)
    return _env_bool("DPF_TPU_INTEGRITY", default=False)


# ---------------------------------------------------------------------------
# Structured events
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class IntegrityEvent:
    """One integrity / degradation event, as handed to event hooks."""

    kind: str  # "selftest-ok" | "sentinel-ok" | "corruption" | "degrade" |
    #            "retry" | "chunk-halved" | "recovered" | "integrity-skip" |
    #            "engine-downgrade"
    backend: str
    detail: str
    data: dict
    timestamp: float


# The hook registry lives on the telemetry bus (ISSUE 6): locked and
# exception-isolated, because the pipelined executor's finalize worker
# emits events concurrently with hook registration — the old module-level
# list was mutated unlocked and a raising subscriber propagated into the
# executor (pinned by tests/test_telemetry.py).
_hooks = telemetry.HookRegistry(_log)

_EVENT_LEVELS = {
    "corruption": logging.ERROR,
    "degrade": logging.WARNING,
    "retry": logging.WARNING,
    "chunk-halved": logging.WARNING,
    "recovered": logging.WARNING,
    "integrity-skip": logging.INFO,
    "selftest-ok": logging.DEBUG,
    "sentinel-ok": logging.DEBUG,
    # Auto-downgrades that silently pick a different execution engine
    # (e.g. dcf.batch_evaluate's narrow-batch Pallas -> XLA-scan fallback):
    # debug-level, but structured so A/B harnesses can tell "kernel lost"
    # from "kernel never ran".
    "engine-downgrade": logging.DEBUG,
}


def add_event_hook(fn: Callable[[IntegrityEvent], None]) -> Callable:
    """Registers `fn` to receive every IntegrityEvent. Returns `fn`.
    Back-compat shim over the telemetry bus's locked registry."""
    return _hooks.add(fn)


def remove_event_hook(fn: Callable[[IntegrityEvent], None]) -> None:
    _hooks.remove(fn)


@contextlib.contextmanager
def capture_events():
    """Collects events for the with-block (tests / local diagnostics)."""
    events: List[IntegrityEvent] = []
    add_event_hook(events.append)
    try:
        yield events
    finally:
        remove_event_hook(events.append)


def emit_event(kind: str, detail: str, backend: str = "", **data) -> IntegrityEvent:
    ev = IntegrityEvent(
        kind=kind,
        backend=backend or _backend_name(),
        detail=detail,
        data=data,
        timestamp=time.time(),
    )
    _log.log(
        _EVENT_LEVELS.get(kind, logging.INFO),
        "integrity[%s] backend=%s %s",
        ev.kind,
        ev.backend,
        ev.detail,
    )
    # Locked, exception-isolated fan-out (HookRegistry), then the re-home:
    # the same event flows onto the telemetry bus, so sentinel verdicts
    # and engine downgrades share the capture/JSONL/summary surface.
    _hooks.emit(ev)
    telemetry.integrity_event(ev)
    return ev


def _backend_name() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


# ---------------------------------------------------------------------------
# Known-answer self-test of the fixed-key AES hash
# ---------------------------------------------------------------------------

# Pinned MMO-hash outputs of input blocks 0, 1, 2 under the three fixed PRG
# keys (core/constants.py), derived once from the reference-parity numpy
# oracle. tests/test_integrity.py re-runs that oracle against this table: a
# typo here fails the test, a regressed oracle fails the reference-parity
# suite — the pin and the oracle cannot both drift the same way.
_KAT_INPUTS = (0, 1, 2)
_KAT_EXPECTED = {
    "left": (
        0x1B226A1E1F4D7503D49C9C8A136D39D0,
        0x70EBC7088D8E9B41828864D280F226BC,
        0xF04EA01D4790EE9DE964438A6DC65DC9,
    ),
    "right": (
        0x35A2735F59C8B7EB895AAE51D89B5C77,
        0xEBCBF680D47B7D66A39EEEB498855C97,
        0xF7CA2BDCDD590A249B80CC24FEFBB798,
    ),
    "value": (
        0xDC14D7B69CD42EAF1DF275F20B83F793,
        0x6F3FF23243CAEBAF56E843ACF362EF1E,
        0x38A56A06CD06FAA86DEDF36C92FDDF96,
    ),
}

_selftest_done: dict = {}


def _kat_input_limbs() -> np.ndarray:
    from ..core import uint128

    ins = np.zeros((32, 4), np.uint32)  # one packed lane word
    for i, x in enumerate(_KAT_INPUTS):
        ins[i] = uint128.to_limbs(x)
    return ins


def selftest_host() -> None:
    """Fixed-key AES hash KAT on the host oracle; InternalError on drift."""
    from ..core import backend_numpy, uint128

    ins = _kat_input_limbs()[: len(_KAT_INPUTS)]
    prgs = {
        "left": backend_numpy._PRG_LEFT,
        "right": backend_numpy._PRG_RIGHT,
        "value": backend_numpy._PRG_VALUE,
    }
    for name, prg in prgs.items():
        out = prg.evaluate_limbs(ins)
        got = tuple(int(uint128.from_limbs(out[i])) for i in range(len(_KAT_INPUTS)))
        if got != _KAT_EXPECTED[name]:
            raise InternalError(
                f"host-oracle AES self-test failed for PRG key {name!r}: "
                f"got {[hex(g) for g in got]} — the library's own hash "
                "implementation is broken; no verification can be trusted"
            )


def selftest_device() -> None:
    """Fixed-key AES hash KAT through the JAX backend (one tiny program);
    DataCorruptionError on mismatch."""
    import jax.numpy as jnp

    from ..core import uint128
    from ..ops import aes_jax, backend_jax

    planes = aes_jax.pack_to_planes(jnp.asarray(_kat_input_limbs()))
    for name in ("left", "right", "value"):
        hashed = aes_jax.hash_planes(planes, backend_jax._rk(name))
        out = np.asarray(aes_jax.unpack_from_planes(hashed))
        got = tuple(int(uint128.from_limbs(out[i])) for i in range(len(_KAT_INPUTS)))
        if got != _KAT_EXPECTED[name]:
            bad = [i for i, (g, w) in enumerate(zip(got, _KAT_EXPECTED[name])) if g != w]
            raise DataCorruptionError(
                f"device AES self-test failed for PRG key {name!r} on backend "
                f"{_backend_name()!r}: inputs {bad} hash wrong — the backend "
                "miscomputes the core primitive (PERF.md 'Platform findings')",
                lanes=bad,
                backend=_backend_name(),
            )


def ensure_selftest() -> None:
    """One-time (per process per backend) known-answer self-test of the
    fixed-key AES hash: host oracle first, then the active JAX backend.
    Integrity-enabled evaluation paths call this at backend init."""
    name = _backend_name()
    if _selftest_done.get(name):
        return
    selftest_host()
    selftest_device()
    _selftest_done[name] = True
    emit_event("selftest-ok", "fixed-key AES hash KAT passed (host + device)", name)


# ---------------------------------------------------------------------------
# Sentinel probe keys
# ---------------------------------------------------------------------------

# Fixed probe material: deterministic seeds (so the probe key is stable
# across processes) and recognizable alpha/beta nibble patterns.
_PROBE_SEEDS = (
    0x5EA15EA15EA15EA15EA15EA15EA15EA1,
    0xC0FFEEC0FFEEC0FFEEC0FFEEC0FFEE01,
)
_PROBE_ALPHA = 0xA5A5A5A5A5A5A5A5A5A5A5A5A5A5A5A5
_PROBE_BETA = 0xD00DFEEDD00DFEEDD00DFEEDD00DFEED


@dataclasses.dataclass
class SentinelProbe:
    """A probe key plus access to its host-oracle ground truth.

    ``key`` is what rides the device batch (post wire round-trip, so wire
    faults surface); ``pristine`` is the untouched key the oracle
    evaluates. Ground truth is computed lazily: full-domain values are
    cached per parameter set, point evaluations are recomputed per call
    (evaluate_at serves domains far too large to expand)."""

    key: object  # DpfKey (post wire round-trip) — fed to the device
    pristine: object  # DpfKey — fed to the host oracle
    dpf: object
    alpha: int
    hierarchy_level: int
    party: int
    backend: str

    @property
    def expected(self) -> np.ndarray:
        """uint32[domain, lpe] host-oracle limb values (cached)."""
        return _probe_expected(
            self.dpf, self.pristine, self.hierarchy_level, self.party
        )

    def expected_at(self, points) -> np.ndarray:
        """uint32[P, lpe] host-oracle limb values at `points`."""
        from ..core import host_eval

        bits, _ = _scalar_kind(
            self.dpf.validator.parameters[self.hierarchy_level].value_type
        )
        with _faults_suspended():
            raw = host_eval.evaluate_at_host(
                self.dpf, [self.pristine], points, self.hierarchy_level
            )[0]
        return host_eval.values_to_limbs(raw, bits)


def _scalar_kind(value_type) -> Optional[Tuple[int, bool]]:
    from ..core.value_types import Int, XorWrapper

    if isinstance(value_type, Int):
        return value_type.bitsize, False
    if isinstance(value_type, XorWrapper):
        return value_type.bitsize, True
    return None


def _params_signature(validator) -> tuple:
    return tuple(
        (p.log_domain_size, repr(p.value_type)) for p in validator.parameters
    )


_probe_keys: dict = {}
_probe_values: dict = {}
_PROBE_VALUE_CACHE_MAX = 8


@contextlib.contextmanager
def _faults_suspended():
    """Host-oracle ground truth is computed with the fault-injection
    harness suspended: injected faults model *device-side* corruption and
    must not poison the oracle."""
    saved = list(faultinject._active)
    faultinject._active.clear()
    try:
        yield
    finally:
        faultinject._active.extend(saved)


def _probe_pair(dpf):
    """Deterministic probe key pair for `dpf`'s parameter set (cached)."""
    sig = _params_signature(dpf.validator)
    pair = _probe_keys.get(sig)
    if pair is None:
        v = dpf.validator
        last = v.parameters[-1]
        domain = 1 << last.log_domain_size if last.log_domain_size < 128 else 0
        alpha = _PROBE_ALPHA % domain if domain else _PROBE_ALPHA
        betas = []
        for p in v.parameters:
            kind = _scalar_kind(p.value_type)
            assert kind is not None  # callers gate on supports_probe
            bits, _ = kind
            beta = _PROBE_BETA & ((1 << bits) - 1)
            betas.append(beta or 1)
        with _faults_suspended():
            pair = dpf.generate_keys_incremental(alpha, betas, seeds=_PROBE_SEEDS)
        _probe_keys[sig] = (pair, alpha)
    return _probe_keys[sig]


def supports_probe(dpf, hierarchy_level: int) -> bool:
    """Sentinel probes cover scalar Int/XorWrapper outputs (the host bulk
    oracle's scope); codec types evaluate without a probe and emit an
    integrity-skip event. The check spans every hierarchy level's value
    type (the probe key pair needs a beta at each level), so
    `hierarchy_level` does not affect the answer."""
    del hierarchy_level
    return all(
        _scalar_kind(p.value_type) is not None
        for p in dpf.validator.parameters
    )


def _probe_expected(dpf, key, hierarchy_level: int, party: int) -> np.ndarray:
    """Host-oracle full-domain limb values of the probe key (cached)."""
    from ..core import host_eval

    sig = (_params_signature(dpf.validator), hierarchy_level, party)
    vals = _probe_values.get(sig)
    if vals is None:
        v = dpf.validator
        if hierarchy_level < 0:
            hierarchy_level = v.num_hierarchy_levels - 1
        bits, _ = _scalar_kind(v.parameters[hierarchy_level].value_type)
        with _faults_suspended():
            raw = host_eval.full_domain_evaluate_host(
                dpf, [key], hierarchy_level
            )[0]
        vals = host_eval.values_to_limbs(raw, bits)
        if len(_probe_values) >= _PROBE_VALUE_CACHE_MAX:
            _probe_values.pop(next(iter(_probe_values)))
        _probe_values[sig] = vals
    return vals


def setup_probe(
    dpf,
    hierarchy_level: int,
    keys: Sequence,
    override: Optional[bool],
    context: str,
    backend: str = "",
) -> Tuple[Sequence, Optional["SentinelProbe"]]:
    """Integrity-gated probe setup shared by every batched entry point
    (``ops/evaluator``, ``parallel/sharded``): when verification is enabled
    (`override` keyword, else DPF_TPU_INTEGRITY) and the value type is in
    probe scope, runs the one-time self-test and returns
    ``(keys + [probe key], probe)``; otherwise ``(keys, None)``, with an
    integrity-skip event where verification was requested but impossible."""
    if not (enabled(override) and keys):
        return keys, None
    if not supports_probe(dpf, hierarchy_level):
        emit_event(
            "integrity-skip",
            f"{context}: no sentinel probe for codec value types; "
            "output not verified",
        )
        return keys, None
    ensure_selftest()
    probe = make_probe(dpf, hierarchy_level, keys[0].party, backend=backend)
    return list(keys) + [probe.key], probe


def make_probe(dpf, hierarchy_level: int, party: int, backend: str = "") -> SentinelProbe:
    """Builds the sentinel probe for one batched device call.

    The probe key is round-tripped through the serialized wire format on
    every call — the same path a real key takes between the two servers —
    so wire-level corruption (fault stage "wire") is exercised and
    detected: a truncation fails the parse (DataLossError), a bit flip
    that still parses yields values the host oracle comparison rejects.
    """
    from ..protos import serialization

    (pair, alpha) = _probe_pair(dpf)
    key = pair[party]
    blob = serialization.serialize_dpf_key(key, list(dpf.validator.parameters))
    blob = faultinject.corrupt_wire(blob, backend=backend or None)
    try:
        key_rt = serialization.parse_dpf_key(blob)
    except DataLossError:
        raise
    except Exception as e:
        raise DataLossError(
            f"sentinel probe key failed its wire round-trip: {e}"
        ) from e
    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    return SentinelProbe(
        key=key_rt,
        pristine=key,
        dpf=dpf,
        alpha=alpha,
        hierarchy_level=hierarchy_level,
        party=party,
        backend=backend or _backend_name(),
    )


# ---------------------------------------------------------------------------
# Verification + corruption diagnosis
# ---------------------------------------------------------------------------


def diagnose_lanes(bad_idx: np.ndarray, total: int) -> str:
    """Human-readable structure of a corruption pattern.

    Recognizes the index-bit signatures that point at packed-lane lowering
    bugs — e.g. the PERF.md finding, where exactly every position with
    index bit 4 set (lanes 16..31 of each 32-lane word) was garbage.
    """
    bad_idx = np.asarray(bad_idx)
    msg = f"{bad_idx.size}/{total} positions corrupted"
    if bad_idx.size == 0 or total <= 1:
        return msg
    and_mask = int(np.bitwise_and.reduce(bad_idx.astype(np.uint64)))
    and_mask &= (1 << (total - 1).bit_length()) - 1
    for b in range((total - 1).bit_length()):
        if not (and_mask >> b) & 1:
            continue
        with_bit = int(np.count_nonzero((np.arange(total) >> b) & 1))
        if bad_idx.size == with_bit:
            # bad ⊆ {bit b set} (by and_mask) and the counts match, so the
            # sets are equal: the exact packed-lane signature.
            extra = " (the PERF.md upper-16-lane platform signature)" if b == 4 else ""
            return msg + f"; exactly every position with index bit {b} set{extra}"
    bits = [b for b in range((total - 1).bit_length()) if (and_mask >> b) & 1]
    if bits:
        return msg + f"; all corrupted positions have index bit(s) {bits} set"
    head = ", ".join(str(int(i)) for i in bad_idx[:8])
    return msg + f"; first corrupted positions: [{head}]"


def _raise_corruption(
    probe: SentinelProbe, bad: np.ndarray, total: int, context: str, key_index
) -> None:
    pattern = diagnose_lanes(bad, total)
    raise DataCorruptionError(
        f"sentinel verification failed on {context} (backend "
        f"{probe.backend!r}, hierarchy level {probe.hierarchy_level}, "
        f"probe party {probe.party}): device output disagrees with the "
        f"host oracle — {pattern}. Do not trust this backend's outputs "
        "(PERF.md 'Platform findings'); re-run tools/check_device.py and "
        "fall back via ops/degrade.py.",
        key_index=key_index,
        lanes=bad[:64].tolist(),
        pattern=pattern,
        backend=probe.backend,
    )


def _verify_probe_row(
    probe: SentinelProbe,
    want: np.ndarray,
    got_row: np.ndarray,
    context: str,
    key_index,
    ok_detail: str,
) -> None:
    """Shared body of the probe-row checks: shape guard, limb-wise
    comparison, sentinel-ok event or DataCorruptionError diagnosis."""
    got = np.asarray(got_row)
    if got.shape != want.shape:
        raise DataCorruptionError(
            f"sentinel verification failed on {context}: probe row has shape "
            f"{got.shape}, host oracle {want.shape}",
            key_index=key_index,
            backend=probe.backend,
        )
    mism = np.any(got != want, axis=-1)
    if not mism.any():
        emit_event(
            "sentinel-ok",
            f"{context}: probe key verified {ok_detail}",
            probe.backend,
        )
        return
    _raise_corruption(probe, np.nonzero(mism)[0], want.shape[0], context, key_index)


def verify_probe_values(
    probe: SentinelProbe,
    got_row: np.ndarray,
    context: str = "full_domain_evaluate",
    key_index=None,
) -> None:
    """Checks one device-output row (uint32[domain, lpe] limbs) against the
    probe's host-oracle values; raises DataCorruptionError on mismatch."""
    want = probe.expected
    _verify_probe_row(
        probe, want, got_row, context, key_index,
        f"over {want.shape[0]} positions",
    )


def verify_probe_at_points(
    probe: SentinelProbe,
    points: Sequence[int],
    got_row: np.ndarray,
    context: str = "evaluate_at_batch",
    key_index=None,
) -> None:
    """Point-evaluation variant: checks the probe row of an
    evaluate_at-style call (uint32[P, lpe] limbs) against the host oracle
    values at `points`."""
    want = probe.expected_at(points)
    _verify_probe_row(
        probe, want, got_row, context, key_index,
        f"at {want.shape[0]} points",
    )


def verify_probe_fold(
    probe: SentinelProbe,
    got_fold: np.ndarray,
    db_limbs: Optional[np.ndarray] = None,
    context: str = "pir_query_batch",
    key_index=None,
) -> None:
    """Fold variant for PIR-style reductions: the expected probe response
    is the XOR fold of the host-oracle values (AND-masked against
    `db_limbs` when given) — one uint32[lpe] vector per probe."""
    vals = probe.expected
    if db_limbs is not None:
        vals = vals & np.asarray(db_limbs, dtype=np.uint32)
    want = np.bitwise_xor.reduce(vals, axis=0)
    got = np.asarray(got_fold)
    if got.shape == want.shape and np.array_equal(got, want):
        emit_event(
            "sentinel-ok",
            f"{context}: probe fold verified over {vals.shape[0]} positions",
            probe.backend,
        )
        return
    raise DataCorruptionError(
        f"sentinel verification failed on {context} (backend "
        f"{probe.backend!r}): the probe key's folded response "
        f"{np.asarray(got).tolist()} != host-oracle fold {want.tolist()} "
        "— some domain positions were evaluated wrong (the fold cannot "
        "localize lanes; re-run tools/check_device.py for the pattern).",
        key_index=key_index,
        pattern="fold mismatch",
        backend=probe.backend,
    )


# ---------------------------------------------------------------------------
# Whole-backend device check (the library form of tools/check_device.py)
# ---------------------------------------------------------------------------


def run_device_check(
    shapes: Sequence[Tuple[int, int]] = ((64, 20),),
    mode: str = "levels",
    use_pallas: Optional[bool] = None,
    seed: int = 7,
    report: Callable[[str], None] = print,
    selftest: bool = True,
    pipeline: Optional[bool] = None,
) -> int:
    """Verifies the active backend against the host oracle at the given
    (num_keys, log_domain) shapes; returns the total number of mismatched
    keys (0 = all verified). ``tools/check_device.py`` is a thin CLI over
    this function so the CLI and the library cannot drift.

    mode is the execution strategy under test: "levels", "fused", "walk"
    (full_domain_evaluate_chunks), "fold" or "megakernel"
    (full_domain_fold_chunks — "megakernel" is the slab Mosaic kernel,
    CHECK_MODE=megakernel from tools/check_device.py; off-TPU it runs the
    Pallas interpreter, which is only CI-practical at toy shapes), or
    "walkkernel" (the walk megakernel, ISSUE 4: per shape, a
    `evaluate_at_batch(mode="walkkernel")` point batch plus one DCF
    `batch_evaluate(mode="walkkernel")` pass are differential-verified
    against the host oracle — the hardware gate for the single-program
    point-walk family, CHECK_MODE=walkkernel), or "hierkernel" (the
    hierarchical megakernel, ISSUE 5: per shape, a heavy-hitters-shaped
    `evaluate_levels_fused(mode="hierkernel")` multi-window advance is
    verified at EVERY hierarchy level against the host engine —
    CHECK_MODE=hierkernel, the hardware gate for the prefix-window
    family; num_keys drives the key batch, log_domain the level count)
    — the program shapes fail independently on a broken backend — or
    "supervisor" (ISSUE 7: per shape, the first fallback rung is forced
    Unavailable via fault injection and the robust wrapper must recover
    bit-correct through the NEXT rung on-device, with a
    decision(source="degrade") record — CHECK_MODE=supervisor exercises
    one real degrade transition on hardware for the next tunnel window),
    or "keygen" (ISSUE 13: per shape, a device-mode batched keygen —
    pallas on Mosaic platforms, else the plane-space XLA mode — must
    byte-match the scalar oracle on spot rows AND its keys must evaluate
    bit-exact under the HOST engine at alpha and off-alpha points —
    CHECK_MODE=keygen, the hardware gate for device-side dealers), or
    "sharded" (ISSUE 17: per shape, a two-server PIR batch through the
    mesh-sharded slab megakernel — parallel.sharded.
    pir_query_batch_chunked(mode='megakernel', mesh=...) on the
    DPF_TPU_PIR_MESH mesh, else 2 x n/2 over the local devices — must
    reconstruct DB[alpha] against the host oracle AND byte-match the
    single-device megakernel on the same keys — CHECK_MODE=sharded, the
    hardware gate for the pod-scale PIR path).

    `pipeline` (None = DPF_TPU_PIPELINE env / platform default) drives the
    chunk generators through the pipelined executor (ops/pipeline.py) —
    pass both values (CHECK_PIPELINE=0/1 via tools/check_device.py) when
    qualifying a platform, so the overlapped execution shape is
    differential-verified exactly like the serial one: the probe keys ride
    the same programs either way, but buffer donation and the deeper
    in-flight window are pipeline-only behaviors worth checking on
    hardware that has miscomputed shape-dependently before (PERF.md).
    """
    import jax.numpy as jnp

    from ..core.dpf import DistributedPointFunction
    from ..core.host_eval import full_domain_evaluate_host
    from ..core.params import DpfParameters
    from ..core.value_types import Int
    from ..ops import evaluator

    if selftest:
        ensure_selftest()
        report(f"selftest: fixed-key AES KAT OK on backend {_backend_name()!r}")
    rng = np.random.default_rng(seed)
    failures = 0
    if mode == "walkkernel":
        return failures + _run_walkkernel_check(
            shapes, rng, report, pipeline=pipeline
        )
    if mode == "hierkernel":
        return failures + _run_hierkernel_check(
            shapes, rng, report, pipeline=pipeline
        )
    if mode == "supervisor":
        return failures + _run_supervisor_check(
            shapes, rng, report, pipeline=pipeline
        )
    if mode == "router":
        return failures + _run_router_check(
            shapes, rng, report, pipeline=pipeline
        )
    if mode == "keygen":
        return failures + _run_keygen_check(
            shapes, rng, report, pipeline=pipeline
        )
    if mode == "sharded":
        return failures + _run_sharded_check(
            shapes, rng, report, pipeline=pipeline
        )
    for num_keys, lds in shapes:
        dpf = DistributedPointFunction.create(DpfParameters(lds, Int(64)))
        alphas = [int(x) for x in rng.integers(0, 1 << lds, size=num_keys)]
        betas = [[int(x) for x in rng.integers(1, 1000, size=num_keys)]]
        keys, _ = dpf.generate_keys_batch(alphas, betas)
        host = full_domain_evaluate_host(dpf, keys)
        want = np.bitwise_xor.reduce(host, axis=1)
        folds = []
        if mode in ("fold", "megakernel"):
            gen = evaluator.full_domain_fold_chunks(
                dpf, keys, key_chunk=num_keys, use_pallas=use_pallas,
                pipeline=pipeline, mode=mode,
            )
            for valid, fold in gen:
                folds.append(np.asarray(fold)[:valid])
        else:
            for valid, out in evaluator.full_domain_evaluate_chunks(
                dpf, keys, key_chunk=num_keys, mode=mode,
                use_pallas=use_pallas, pipeline=pipeline,
            ):
                folds.append(
                    np.asarray(jnp.bitwise_xor.reduce(out, axis=1))[:valid]
                )
        got = np.concatenate(folds, axis=0)
        got64 = got[:, 0].astype(np.uint64) | (
            got[:, 1].astype(np.uint64) << np.uint64(32)
        )
        bad = int((got64 != want).sum())
        status = "OK" if bad == 0 else f"MISMATCH ({bad}/{num_keys} keys)"
        report(f"keys={num_keys:4d} log_domain={lds:3d} mode={mode}: {status}")
        if bad:
            emit_event(
                "corruption",
                f"device check: {bad}/{num_keys} keys mismatch at "
                f"log_domain={lds} mode={mode}",
                _backend_name(),
                num_keys=num_keys,
                log_domain=lds,
                mode=mode,
            )
        failures += bad
    return failures


def _run_sharded_check(shapes, rng, report, pipeline=None) -> int:
    """CHECK_MODE=sharded body of `run_device_check` (ISSUE 17): the
    mesh-sharded slab-megakernel PIR path on the live backend.

    Per (num_keys, log_domain) shape, a two-server XorWrapper(128) PIR
    batch runs through `parallel.sharded.pir_query_batch_chunked(
    mode='megakernel', mesh=...)` — DB column blocks sharded over the
    'domain' axis, keys over 'keys', one shard_map program per chunk —
    and must (a) reconstruct DB[alpha] for every key pair (the two
    servers' responses XOR to the database row: the host-oracle check,
    with the sentinel probe riding every batch via integrity=True) and
    (b) byte-match the SINGLE-DEVICE megakernel on the same keys and
    database (the degenerate-mesh cross-engine check — the collective,
    the per-shard plans and the column-block layout must be exactly
    invisible in the answers). The mesh comes from DPF_TPU_PIR_MESH when
    set, else 2 x n/2 over the local devices (n/1 when n is odd)."""
    import jax

    from ..core.dpf import DistributedPointFunction
    from ..core.params import DpfParameters
    from ..core.value_types import XorWrapper
    from ..parallel import sharded

    failures = 0
    mesh = sharded.pir_mesh_from_env()
    if mesh is None:
        n = jax.local_device_count()
        k = 2 if n % 2 == 0 and n > 1 else 1
        mesh = sharded.make_mesh(k, n // k)
    d_shards = mesh.shape["domain"]
    # Each domain shard must own whole packed entry words: host_levels >=
    # 5 + log2(domain shards) (plan_megakernel validates the same bound).
    need_hl = 5 + max(0, (d_shards - 1).bit_length())
    for num_keys, lds in shapes:
        if lds < need_hl + 1:
            report(
                f"keys={num_keys:4d} log_domain={lds:3d} mode=sharded: "
                f"SKIP (needs log_domain > {need_hl} for "
                f"{d_shards} domain shards)"
            )
            continue
        dpf = DistributedPointFunction.create(
            DpfParameters(lds, XorWrapper(128))
        )
        domain = 1 << lds
        db = rng.integers(
            0, 1 << 32, size=(domain, 4), dtype=np.uint64
        ).astype(np.uint32)
        alphas = [int(x) for x in rng.integers(0, domain, size=num_keys)]
        beta = (1 << 128) - 1
        pairs = [dpf.generate_keys(a, beta) for a in alphas]
        pdb = sharded.prepare_pir_database(
            dpf, db, host_levels=need_hl, order="megakernel", mesh=mesh
        )
        pdb_one = sharded.prepare_pir_database(
            dpf, db, host_levels=need_hl, order="megakernel"
        )
        res, res_one = [], []
        for party in (0, 1):
            pk = [p[party] for p in pairs]
            res.append(
                sharded.pir_query_batch_chunked(
                    dpf, pk, pdb, key_chunk=num_keys, host_levels=need_hl,
                    mode="megakernel", mesh=mesh, pipeline=pipeline,
                    integrity=True,
                )
            )
            res_one.append(
                sharded.pir_query_batch_chunked(
                    dpf, pk, pdb_one, key_chunk=num_keys,
                    host_levels=need_hl, mode="megakernel",
                    pipeline=pipeline, integrity=True,
                )
            )
        rec = np.bitwise_xor(res[0], res[1])
        want = db[np.asarray(alphas)]
        bad = int((rec != want).any(axis=1).sum())
        bad_eng = int(
            (res[0] != res_one[0]).any(axis=1).sum()
            + (res[1] != res_one[1]).any(axis=1).sum()
        )
        status = (
            "OK" if bad == 0 and bad_eng == 0
            else f"MISMATCH ({bad} keys vs oracle, "
                 f"{bad_eng} vs single-device)"
        )
        report(
            f"keys={num_keys:4d} log_domain={lds:3d} mode=sharded "
            f"mesh={sharded._mesh_desc(mesh)}: {status}"
        )
        if bad or bad_eng:
            emit_event(
                "corruption",
                f"sharded device check: {bad} keys mismatch the oracle, "
                f"{bad_eng} the single-device megakernel at "
                f"log_domain={lds} mesh={sharded._mesh_desc(mesh)}",
                _backend_name(),
                num_keys=num_keys,
                log_domain=lds,
                mode="sharded",
            )
        failures += bad + bad_eng
    return failures


def _run_keygen_check(shapes, rng, report, pipeline=None) -> int:
    """CHECK_MODE=keygen body of `run_device_check` (ISSUE 13): the
    device-side batched dealer on the live backend.

    Per (num_keys, log_domain) shape, a batched keygen runs in the
    platform's device mode ("pallas" on Mosaic platforms — compiled, not
    interpreted — else the plane-space XLA "jax" mode; CHECK_KEYGEN_MODE
    overrides, e.g. "megakernel" to burn in the single-program dealer)
    from pinned seeds, then two independent verdicts:

    1. **Byte-match spot rows** — the first and last key pairs are
       regenerated through the scalar per-key oracle from the same seeds
       and every serialized byte must agree (the wire form IS the
       contract: a dealer whose keys differ anywhere is broken even if
       they happen to evaluate correctly at the probed points).
    2. **Host-engine evaluation** — every generated key pair is
       evaluated under the HOST engine at its alpha and an off-alpha
       point; the parties' shares must reconstruct beta and 0. This
       catches the failure class byte-comparison can't see run on
       hardware: a miscompiled device AES producing self-consistent but
       wrong circuits would fail here against the independent host AES.

    Returns the number of mismatched keys (0 = all verified).
    """
    del pipeline  # keygen's level loop has no chunk executor
    from ..core.dpf import DistributedPointFunction
    from ..core.params import DpfParameters
    from ..core.value_types import Int
    from ..ops import evaluator, keygen_batch
    from ..protos import serialization

    # CHECK_KEYGEN_MODE pins the engine under test (e.g. "megakernel" to
    # burn in the single-program dealer on new hardware); the default
    # stays the platform's device mode.
    mode = _env_str("CHECK_KEYGEN_MODE", None)
    if mode is not None and mode not in keygen_batch.KEYGEN_MODES:
        raise InvalidArgumentError(
            f"CHECK_KEYGEN_MODE must be one of {keygen_batch.KEYGEN_MODES}, "
            f"got {mode!r}"
        )
    if mode is None:
        mode = "pallas" if evaluator._pallas_default() else "jax"
    failures = 0
    for num_keys, lds in shapes:
        dpf = DistributedPointFunction.create(DpfParameters(lds, Int(64)))
        # Byte-draw alphas: rng.integers caps at int64, and deep domains
        # (the >88-bit range the serialization fix covers) must be
        # checkable on hardware.
        alphas = [
            int.from_bytes(rng.bytes(16), "little") % (1 << lds)
            for _ in range(num_keys)
        ]
        betas = [int(x) for x in rng.integers(1, 1000, size=num_keys)]
        seeds = rng.integers(0, 2**32, size=(num_keys, 2, 4), dtype=np.uint32)
        keys_0, keys_1 = keygen_batch.generate_keys_batch(
            dpf, alphas, [betas], mode=mode, seeds=seeds
        )
        bad = 0
        params = dpf.validator.parameters
        for i in sorted({0, num_keys - 1}):
            s = (
                int.from_bytes(seeds[i, 0].tobytes(), "little"),
                int.from_bytes(seeds[i, 1].tobytes(), "little"),
            )
            want_0, want_1 = dpf.generate_keys(alphas[i], betas[i], seeds=s)
            for got, want in ((keys_0[i], want_0), (keys_1[i], want_1)):
                if serialization.serialize_dpf_key(
                    got, params
                ) != serialization.serialize_dpf_key(want, params):
                    bad += 1
        byte_bad = bad
        mask = (1 << 64) - 1
        for i in range(num_keys):
            off = (alphas[i] + 1) % (1 << lds)
            e0 = dpf.evaluate_at(keys_0[i], 0, [alphas[i], off])
            e1 = dpf.evaluate_at(keys_1[i], 0, [alphas[i], off])
            if (e0[0] + e1[0]) & mask != betas[i] or (e0[1] + e1[1]) & mask:
                bad += 1
        status = (
            "OK" if bad == 0
            else f"MISMATCH ({bad} verdicts: {byte_bad} byte, "
            f"{bad - byte_bad} eval)"
        )
        report(
            f"keys={num_keys:4d} log_domain={lds:3d} keygen[{mode}]: {status}"
        )
        if bad:
            emit_event(
                "corruption",
                f"keygen device check: {bad} failed verdicts at "
                f"keys={num_keys} log_domain={lds} mode={mode}",
                _backend_name(),
                num_keys=num_keys,
                log_domain=lds,
                mode=mode,
            )
        failures += bad
    return failures


def _run_router_check(shapes, rng, report, pipeline=None) -> int:
    """CHECK_MODE=router body of `run_device_check` (ISSUE 8): the
    serving front door on the live backend.

    Three layers, per (num_keys, log_domain) shape:

    1. **Model pins** — the router's cold-start anchors must reproduce
       every winner row of the measured engine table
       (serving.router.ENGINE_TABLE): a drifted anchor table is a
       failure even before anything dispatches.
    2. **One real routed batch per engine class** — num_keys single-key
       requests are submitted to a FrontDoor per engine setting ("auto"
       = the router decides with live dispatch latency, then forced
       "device" and "host"), aggregated into one merged batch, executed
       through the supervisor, and every request's sliced answer is
       verified against the host oracle.
    3. **Decision records** — the auto batch must carry a
       ``decision(source="router")`` with predicted costs; the forced
       batches ``source="explicit"``. The live routed choice is
       reported next to the model's cold-start prediction, so a
       hardware window immediately shows whether measured dispatch
       latency moves the crossover.
    """
    from ..core.dpf import DistributedPointFunction
    from ..core.host_eval import full_domain_evaluate_host, values_to_limbs
    from ..core.params import DpfParameters
    from ..core.value_types import Int
    from .. import serving
    from . import telemetry

    failures = 0
    table = serving.engine_table_predictions()
    for label, measured, routed, _costs in table:
        ok = routed == measured
        report(
            f"router pin: {label}: predicted {routed!r} vs measured "
            f"{measured!r} {'OK' if ok else 'MISPREDICTED'}"
        )
        failures += 0 if ok else 1

    for num_keys, lds in shapes:
        dpf = DistributedPointFunction.create(DpfParameters(lds, Int(64)))
        alphas = [int(x) for x in rng.integers(0, 1 << lds, size=num_keys)]
        betas = [[int(x) for x in rng.integers(1, 1000, size=num_keys)]]
        keys, _ = dpf.generate_keys_batch(alphas, betas)
        want = values_to_limbs(full_domain_evaluate_host(dpf, keys), 64)
        router = serving.Router(calibration="")
        for engine in ("auto", "device", "host"):
            with telemetry.capture() as tel:
                with serving.FrontDoor(
                    router=router, engine=engine, max_wait_ms=50,
                    width_target=num_keys, pipeline=pipeline,
                ) as door:
                    futs = [
                        door.submit(serving.Request.full_domain(dpf, [k]))
                        for k in keys
                    ]
                    outs = [f.result(timeout=600) for f in futs]
            bad = sum(
                0 if np.array_equal(np.asarray(outs[i])[0], want[i]) else 1
                for i in range(num_keys)
            )
            src = "router" if engine == "auto" else "explicit"
            decisions = tel.decision_records(source=src, op="full_domain")
            if not decisions:
                bad += 1
                detail = f"no decision(source={src!r}) recorded"
            elif src == "router" and "predicted_ms" not in decisions[0].get(
                "data", {}
            ):
                bad += 1
                detail = "router decision carries no predicted cost"
            else:
                detail = f"chose {decisions[-1]['data'].get('choice')}"
            status = "OK" if bad == 0 else f"MISMATCH ({bad})"
            report(
                f"keys={num_keys:4d} log_domain={lds:3d} mode=router "
                f"engine={engine}: {status} ({detail})"
            )
            failures += bad
    return failures


def _run_supervisor_check(shapes, rng, report, pipeline=None) -> int:
    """CHECK_MODE=supervisor body of `run_device_check` (ISSUE 7): per
    (num_keys, log_domain) shape, the robust full-domain wrapper runs
    with its FIRST fallback rung forced ``UnavailableError`` by a scoped
    fault plan, so the chain must retry, degrade, and serve the batch
    from the next rung — on a real TPU that second rung is still a
    device engine, making this the hardware gate for one real degrade
    transition (retry backoff, rung handoff, sentinel verification on
    the fallback engine, and the decision record) rather than a
    CPU-simulated one."""
    from ..core.dpf import DistributedPointFunction
    from ..core.host_eval import full_domain_evaluate_host, values_to_limbs
    from ..core.params import DpfParameters
    from ..core.value_types import Int
    from ..ops import degrade

    failures = 0
    policy = degrade.DegradationPolicy(backoff_seconds=0.0)
    first_backend = degrade.fallback_chain()[0]
    for num_keys, lds in shapes:
        dpf = DistributedPointFunction.create(DpfParameters(lds, Int(64)))
        alphas = [int(x) for x in rng.integers(0, 1 << lds, size=num_keys)]
        betas = [[int(x) for x in rng.integers(1, 1000, size=num_keys)]]
        keys, _ = dpf.generate_keys_batch(alphas, betas)
        want = values_to_limbs(full_domain_evaluate_host(dpf, keys), 64)
        with telemetry.capture() as tel, capture_events() as events:
            with faultinject.inject(
                faultinject.FaultPlan(
                    stage="device_call",
                    exception=UnavailableError(
                        "UNAVAILABLE: injected supervisor check"
                    ),
                    backends=frozenset({first_backend}),
                )
            ):
                got = degrade.full_domain_evaluate_robust(
                    dpf, keys, policy=policy, pipeline=pipeline,
                )
        snap = tel.snapshot()
        bad = int((got != want).any(axis=(1, 2)).sum())
        degraded = any(e.kind == "degrade" for e in events)
        recorded = snap["decisions_by_source"].get("degrade", 0) >= 1
        ok = bad == 0 and degraded and recorded
        status = "OK" if ok else (
            f"MISMATCH ({bad}/{num_keys} keys)" if bad
            else "NO DEGRADE RECORD"
        )
        report(
            f"keys={num_keys:4d} log_domain={lds:3d} mode=supervisor "
            f"(rung {first_backend!r} forced unavailable): {status}"
        )
        if not ok:
            emit_event(
                "corruption",
                f"supervisor check failed at log_domain={lds}: "
                f"bad={bad}, degrade_event={degraded}, "
                f"decision_recorded={recorded}",
                _backend_name(),
                num_keys=num_keys,
                log_domain=lds,
                mode="supervisor",
            )
            failures += max(bad, 1)
    return failures


def _run_hierkernel_check(shapes, rng, report, pipeline=None) -> int:
    """CHECK_MODE=hierkernel body of `run_device_check`: per
    (num_keys, log_domain) shape, a heavy-hitters-shaped bit-wise
    hierarchy (one level per bit, log_domain levels) is advanced through
    `evaluate_levels_fused(mode="hierkernel")` — the single-program
    prefix-window megakernel, ISSUE 5 — and EVERY hierarchy level's
    outputs are verified per key against the host engine. This is the
    hardware gate for the hier-megakernel family (the real row circuit
    cannot execute through interpret mode in CI time, so only this check
    exercises the Mosaic codegen); off-TPU it runs the Pallas
    interpreter and is CI-practical only at toy shapes. CHECK_HH_GROUP
    sizes the prefix window (levels per pallas_call),
    CHECK_HH_NONZEROS the leaf count."""
    from ..core.dpf import DistributedPointFunction
    from ..core.params import DpfParameters
    from ..core.value_types import Int
    from ..ops import hierarchical

    group = int(os.environ.get("CHECK_HH_GROUP", 16))
    nonzeros = int(os.environ.get("CHECK_HH_NONZEROS", 200))
    failures = 0
    for num_keys, levels in shapes:
        params = [DpfParameters(i + 1, Int(64)) for i in range(levels)]
        dpf = DistributedPointFunction.create_incremental(params)
        keys = [
            dpf.generate_keys_incremental(alpha, [23] * levels)[0]
            for alpha in hierarchical.draw_random_finals(levels, num_keys, rng)
        ]
        plan = hierarchical.bitwise_hierarchy_plan(
            levels, hierarchical.draw_random_finals(levels, nonzeros, rng)
        )
        bc = hierarchical.BatchedContext.create(dpf, keys)
        outs = hierarchical.evaluate_levels_fused(
            bc, plan, group=group, mode="hierkernel", pipeline=pipeline
        )
        bad = 0
        bch = hierarchical.BatchedContext.create(dpf, keys)
        for i, (h, p) in enumerate(plan):
            ref = hierarchical.evaluate_until_batch(bch, h, p, engine="host")
            got = np.asarray(outs[i])
            got64 = got[..., 0].astype(np.uint64) | (
                got[..., 1].astype(np.uint64) << np.uint64(32)
            )
            bad_keys = (got64 != np.asarray(ref).astype(np.uint64)).any(axis=1)
            bad = max(bad, int(bad_keys.sum()))
        status = "OK" if bad == 0 else f"MISMATCH ({bad}/{num_keys} keys)"
        report(
            f"keys={num_keys:4d} levels={levels:3d} mode=hierkernel "
            f"({len(plan[-1][1])} unique deepest prefixes, "
            f"group={group}): {status}"
        )
        if bad:
            emit_event(
                "corruption",
                f"device check: {bad}/{num_keys} keys mismatch on the "
                f"{levels}-level hierkernel advance",
                _backend_name(),
                num_keys=num_keys,
                levels=levels,
                mode="hierkernel",
            )
        failures += bad
    return failures


def _run_walkkernel_check(shapes, rng, report, pipeline=None) -> int:
    """CHECK_MODE=walkkernel body of `run_device_check`: per shape, a
    `evaluate_at_batch(mode="walkkernel")` point batch is verified
    key-by-key against the host oracle (the native engine over every
    point when available, else the reference path over the first 32),
    plus ONE DCF `batch_evaluate(mode="walkkernel")` differential — the
    hardware gate for the single-program point-walk family (the real row
    circuit cannot execute through interpret mode in CI time, so only
    this check exercises the Mosaic codegen)."""
    from .. import native
    from ..core.dpf import DistributedPointFunction
    from ..core.params import DpfParameters
    from ..core.value_types import Int
    from ..dcf import batch as dcf_batch
    from ..dcf.dcf import DistributedComparisonFunction
    from ..ops import evaluator

    failures = 0
    for num_keys, lds in shapes:
        dpf = DistributedPointFunction.create(DpfParameters(lds, Int(64)))
        alphas = [int(x) for x in rng.integers(0, 1 << lds, size=num_keys)]
        betas = [[int(x) for x in rng.integers(1, 1000, size=num_keys)]]
        keys, _ = dpf.generate_keys_batch(alphas, betas)
        num_points = 256
        pts = [alphas[0]] + [
            int(x) for x in rng.integers(0, 1 << lds, size=num_points - 1)
        ]
        dev = evaluator.values_to_numpy(
            evaluator.evaluate_at_batch(
                dpf, keys, pts, key_chunk=num_keys, pipeline=pipeline,
                mode="walkkernel",
            ),
            64,
        ).astype(np.uint64)
        if native.available():
            from ..core.host_eval import evaluate_at_host

            want = evaluate_at_host(
                dpf, keys, np.asarray(pts, dtype=np.uint64)
            ).astype(np.uint64)
            checked = num_points
        else:
            want = np.asarray(
                [dpf.evaluate_at(k, 0, pts[:32]) for k in keys],
                dtype=np.uint64,
            )
            dev = dev[:, :32]
            checked = 32
        bad = int((dev != want).any(axis=1).sum())
        status = "OK" if bad == 0 else f"MISMATCH ({bad}/{num_keys} keys)"
        report(
            f"keys={num_keys:4d} log_domain={lds:3d} mode=walkkernel "
            f"evaluate_at ({checked} pts): {status}"
        )
        if bad:
            emit_event(
                "corruption",
                f"device check: {bad}/{num_keys} keys mismatch at "
                f"log_domain={lds} mode=walkkernel (evaluate_at)",
                _backend_name(),
                num_keys=num_keys,
                log_domain=lds,
                mode="walkkernel",
            )
        failures += bad
    # One DCF pass through the same kernel family (per-depth captures +
    # in-register accumulate are DCF-only code paths).
    lds = min(16, max(l for _, l in shapes))
    dc = DistributedComparisonFunction.create(lds, Int(64))
    ka, _ = dc.generate_keys(int(rng.integers(0, 1 << lds)), 4242)
    xs = [int(x) for x in rng.integers(0, 1 << lds, size=128)]
    dev = evaluator.values_to_numpy(
        dcf_batch.batch_evaluate(dc, [ka], xs, mode="walkkernel"), 64
    )[0].astype(np.uint64)
    want = np.array([dc.evaluate(ka, x) for x in xs[:16]], dtype=np.uint64)
    bad = 0 if np.array_equal(dev[:16], want) else 1
    report(
        f"keys=   1 log_domain={lds:3d} mode=walkkernel dcf (128 pts, "
        f"16 host-checked): {'OK' if bad == 0 else 'MISMATCH'}"
    )
    if bad:
        emit_event(
            "corruption",
            f"device check: DCF walkkernel mismatch at log_domain={lds}",
            _backend_name(),
            log_domain=lds,
            mode="walkkernel",
        )
    return failures + bad
