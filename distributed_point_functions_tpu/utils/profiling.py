"""Profiling/tracing utilities — the Perfetto-facing edge of the
observability layer (utils/telemetry.py owns the in-process bus).

The reference delegates all of this to external tools (SURVEY.md §5: no
in-library tracing; perf work lives in google-benchmark). On TPU the
equivalent is a jax.profiler trace viewable in TensorBoard/Perfetto:
:func:`trace` is the documented capture entry, and while a trace is
active every telemetry span (ops/pipeline.py stage spans, the @traced
entry points) bridges to a ``jax.profiler.TraceAnnotation`` so the
library's own phase structure appears on the timeline.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from . import envflags, telemetry


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None) -> Iterator[None]:
    """Captures a jax.profiler trace into `log_dir` (or $DPF_TPU_PROFILE_DIR).

    No-op when neither is set, so call sites can wrap hot paths
    unconditionally:

        with profiling.trace():
            evaluator.full_domain_evaluate(...)

    While the trace is active, telemetry spans bridge to
    jax.profiler.TraceAnnotation (the ISSUE 6 Perfetto bridge), so the
    pipeline's launch/finalize stages and the bulk entry points appear as
    named regions in the captured timeline.
    """
    log_dir = log_dir or envflags.env_str("DPF_TPU_PROFILE_DIR")
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    telemetry.set_profile_bridge(True)
    try:
        yield
    finally:
        telemetry.set_profile_bridge(False)
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region in the profiler timeline (jax.profiler.TraceAnnotation).

    No-op-safe (ISSUE 6 satellite): returns a null context unless a
    profiler is plausibly attached (DPF_TPU_PROFILE_DIR set or a
    :func:`trace` block active) — the old version imported jax and built
    a TraceAnnotation unconditionally, paying the annotation on every
    call with no profiler to receive it."""
    if not (envflags.env_str("DPF_TPU_PROFILE_DIR") or telemetry._profile_bridge):
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name)


class Stopwatch:
    """Wall-clock phase timing with a one-line report.

    Folded onto the telemetry bus (ISSUE 6 satellite): every lap also
    lands as a completed ``stopwatch.<name>`` span record when a
    collector is active, so ad-hoc phase timings share the
    capture/JSONL/summary surface instead of living only in a local
    report string. Free when telemetry is disabled (one boolean check
    inside observe_span)."""

    def __init__(self) -> None:
        self.phases: list[tuple[str, float]] = []
        self._t0 = time.perf_counter()

    def lap(self, name: str) -> float:
        now = time.perf_counter()
        dt = now - self._t0
        self.phases.append((name, dt))
        self._t0 = now
        telemetry.observe_span(f"stopwatch.{name}", dt)
        return dt

    def report(self) -> str:
        total = sum(dt for _, dt in self.phases)
        parts = ", ".join(f"{n}: {dt:.3f}s" for n, dt in self.phases)
        return f"{parts} (total {total:.3f}s)"
