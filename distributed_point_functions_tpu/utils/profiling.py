"""Profiling/tracing utilities — the observability layer the reference
delegates to external tools (SURVEY.md §5: no in-library tracing; perf work
lives in google-benchmark). On TPU the equivalent is a jax.profiler trace
viewable in TensorBoard/Perfetto, plus named trace annotations around the
framework's phases (keygen, host expansion, device expansion, finalize).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None) -> Iterator[None]:
    """Captures a jax.profiler trace into `log_dir` (or $DPF_TPU_PROFILE_DIR).

    No-op when neither is set, so call sites can wrap hot paths
    unconditionally:

        with profiling.trace():
            evaluator.full_domain_evaluate(...)
    """
    log_dir = log_dir or os.environ.get("DPF_TPU_PROFILE_DIR")
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region in the profiler timeline (jax.profiler.TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class Stopwatch:
    """Wall-clock phase timing with a one-line report; host-side fallback
    when no profiler is attached."""

    def __init__(self) -> None:
        self.phases: list[tuple[str, float]] = []
        self._t0 = time.perf_counter()

    def lap(self, name: str) -> float:
        now = time.perf_counter()
        dt = now - self._t0
        self.phases.append((name, dt))
        self._t0 = now
        return dt

    def report(self) -> str:
        total = sum(dt for _, dt in self.phases)
        parts = ", ".join(f"{n}: {dt:.3f}s" for n, dt in self.phases)
        return f"{parts} (total {total:.3f}s)"
