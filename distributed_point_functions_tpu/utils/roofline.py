"""Roofline / MFU accounting for the bitsliced AES device engine.

VERDICT r4 #4: the repo's perf story relates its rates to ONE reference
CPU core (BASELINE.md), but never to what the TPU silicon itself can do —
"82x one Xeon core" could be 10% or 60% of the chip. This module closes
that gap with exact op accounting:

1. **Gate count** — trace the bitsliced AES-128 MMO hash
   (`ops.aes_jax.hash_planes`, the same circuit the Mosaic row kernels
   compute) with `jax.make_jaxpr` and count the u32 *element* operations
   per AES block. This is exact, not an estimate: the circuit is
   elementwise over [128, W] u32 bit-planes (W lane words = 32 blocks
   each), so every logic gate is one u32 op per lane word.

2. **AES blocks per evaluation** — a full-domain expansion of 2^n leaves
   costs 2*(2^n - 1) tree-node hashes (two child hashes per parent across
   all levels, distributed_point_function.cc's EvaluateSeeds recursion) +
   2^n value-correction hashes: (3*2^n - 2)/2^n ~= 3 hashes per leaf.

3. **VPU peak** — the v5e TensorCore's vector unit is an (8, 128)-lane
   2D SIMD array with 4 independent ALUs at ~940 MHz (public "How to
   Scale Your Model" hardware chapter): 8*128*4*0.94e9 ~= 3.85e12 u32
   elementwise ops/s. The MXU does not participate (no matmuls in this
   workload) — the VPU peak IS the roofline for a bitsliced cipher.

achieved_ops/s = evals/s * hashes_per_eval * ops_per_block, and
MFU = achieved / peak. The same arithmetic inverted gives the ceiling:
the evals/s this chip could reach at 100% VPU utilization.

CLI (writes the PERF.md table):
    python -m distributed_point_functions_tpu.utils.roofline [evals_per_sec]
"""

from __future__ import annotations

import functools
import os

import numpy as np

from . import envflags, errors

# v5e VPU: (8 sublanes, 128 lanes) x 4 ALUs x ~940 MHz. 32-bit ops.
V5E_VPU_OPS_PER_SEC = 8 * 128 * 4 * 0.94e9

# v5e HBM2: 16 GB at ~819 GB/s per chip (public "How to Scale Your Model"
# hardware chapter) — the second wall next to the VPU one. A bitsliced
# cipher is compute-dense, so which wall binds depends on how much plane /
# value state a strategy round-trips through HBM per evaluation.
V5E_HBM_BYTES_PER_SEC = 819e9

# Primitives counted as one u32 element op per output element. Everything
# else in the traced circuit is data movement (reshape/transpose/
# concatenate/slice/broadcast), which XLA largely folds into the compute
# on TPU; it is reported separately, not added to the gate count.
_ELEMENT_PRIMS = {
    "xor", "and", "or", "not", "add", "sub", "mul",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "select_n",
}
_MOVEMENT_PRIMS = {
    "reshape", "transpose", "concatenate", "slice", "broadcast_in_dim",
    "squeeze", "rev", "convert_element_type", "gather", "dynamic_slice",
    "pad",
}


def _count_jaxpr(jaxpr) -> dict:
    """Counts element ops / movement elements over a jaxpr, recursively."""
    counts = {"element_ops": 0, "movement_elems": 0, "other_prims": set()}

    def walk(jx):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            out_elems = sum(
                int(np.prod(v.aval.shape)) if v.aval.shape else 1
                for v in eqn.outvars
            )
            if name in _ELEMENT_PRIMS:
                counts["element_ops"] += out_elems
            elif name in _MOVEMENT_PRIMS:
                counts["movement_elems"] += out_elems
            elif name in ("pjit", "closed_call", "custom_jvp_call"):
                for p in ("jaxpr", "call_jaxpr"):
                    inner = eqn.params.get(p)
                    if inner is not None:
                        walk(inner.jaxpr if hasattr(inner, "jaxpr") else inner)
                        break
            else:
                counts["other_prims"].add(name)
        return counts

    return walk(jaxpr)


@functools.lru_cache(maxsize=4)
def hash_ops_per_block(lane_words: int = 64) -> dict:
    """Exact u32 element-op count of one bitsliced MMO hash, per AES block.

    Traces `hash_planes` on a [128, lane_words] input (32*lane_words
    blocks). The per-block figure is independent of lane_words (the
    circuit is elementwise); the default 64 matches the headline
    program's plane width at 2048-block batches.
    """
    import jax

    from ..ops import aes_jax

    rk = aes_jax.round_key_planes(0x2B7E151628AED2A6ABF7158809CF4F3C)
    blocks = 32 * lane_words

    def one_hash(planes):
        return aes_jax.hash_planes(planes, rk)

    jaxpr = jax.make_jaxpr(one_hash)(
        jax.ShapeDtypeStruct((128, lane_words), np.uint32)
    )
    c = _count_jaxpr(jaxpr.jaxpr)
    return {
        "element_ops_per_block": c["element_ops"] / blocks,
        "movement_elems_per_block": c["movement_elems"] / blocks,
        "uncounted_prims": sorted(c["other_prims"]),
        "lane_words": lane_words,
    }


def hashes_per_eval(log_domain: int) -> float:
    """AES hashes per leaf of a full-domain expansion over 2^log_domain."""
    n = 1 << log_domain
    return (3 * n - 2) / n


def mfu_fields(evals_per_sec: float, log_domain: int) -> dict:
    """The headline-record roofline fields (merged into bench.py's JSON)."""
    ops = hash_ops_per_block()
    per_eval = hashes_per_eval(log_domain) * ops["element_ops_per_block"]
    achieved = evals_per_sec * per_eval
    mfu = achieved / V5E_VPU_OPS_PER_SEC
    ceiling = V5E_VPU_OPS_PER_SEC / per_eval
    return {
        "mfu_estimate": round(mfu, 4),
        "roofline_ceiling_evals_per_sec": round(ceiling),
        "mfu_detail": (
            f"{ops['element_ops_per_block']:.0f} u32 gate-ops/AES-block "
            f"(traced bitsliced circuit) x {hashes_per_eval(log_domain):.2f} "
            f"hashes/eval = {per_eval:.0f} ops/eval; "
            f"{achieved:.3e} ops/s vs v5e VPU peak "
            f"{V5E_VPU_OPS_PER_SEC:.2e} (8x128 lanes x 4 ALUs x 0.94 GHz)"
        ),
    }


def hbm_bytes_per_eval(
    log_domain: int,
    strategy: str = "fold",
    lpe: int = 2,
    keep: int = 2,
    pir: bool = False,
) -> float:
    """Modeled HBM bytes moved per domain evaluation, by strategy.

    A traffic MODEL (counted from the data each strategy provably
    round-trips), not a measurement — labeled as such everywhere it is
    reported. Per leaf, a doubling expansion creates ~2/keep tree nodes
    (16 B of packed seed planes each); the strategies differ in how many
    of those cross HBM:

    * "levels"/"fused"/"fold": every level's child planes are written to
      HBM and read back by the next level (or the value hash) — XLA does
      not keep a full level's planes in VMEM at serving widths. That is
      2 passes x 16 B x 2/keep nodes, plus the hashed planes' write+read
      (2 x 16/keep), plus the value buffer's write+read (2 x 4*lpe; in
      "fold" it sits behind the optimization_barrier, in "fused"/"levels"
      it is the program output).
    * "megakernel": the expansion never leaves VMEM — per-eval traffic is
      the level-h entry seeds amortized over 2^(log_domain - h) leaves
      (~0) plus the output fold (~0); with `pir`, one streaming read of
      the database row (4*lpe B).
    """
    if strategy not in ("levels", "fused", "fold", "megakernel"):
        raise errors.InvalidArgumentError(
            f"no HBM traffic model for strategy {strategy!r} (modeled: "
            "levels/fused/fold/megakernel)"
        )
    if strategy == "megakernel":
        entry = 16.0 * 32 / (1 << log_domain)  # level-5 seeds, amortized
        return entry + (4.0 * lpe if pir else 0.0)
    nodes_per_eval = 2.0 / keep
    planes = 2 * 16.0 * nodes_per_eval  # per-level child write + read
    hashed = 2 * 16.0 / keep  # value-hash planes write + read
    values = 2 * 4.0 * lpe  # value buffer write + consumer read
    db = 4.0 * lpe if pir else 0.0
    return planes + hashed + values + db


def hbm_fields(
    evals_per_sec: float,
    log_domain: int,
    strategy: str = "fold",
    lpe: int = 2,
    keep: int = 2,
    pir: bool = False,
    n_chips: int = 1,
) -> dict:
    """HBM-bandwidth roofline fields for a measured record, next to the
    VPU ones (`mfu_fields`): which wall — VPU arithmetic or HBM traffic —
    the record sits against, per the traffic model above.

    With `n_chips` > 1 (a sharded-megakernel mesh), the PER-EVAL byte
    model is unchanged — sharding the database along `domain` means each
    row is still read from HBM exactly once, on exactly one shard — but
    the aggregate walls scale: the fleet has n_chips HBM pipes and
    n_chips VPUs, so both ceilings multiply by n_chips (the binding wall
    is therefore mesh-invariant) and utilization is measured against the
    aggregate bandwidth. `evals_per_sec` must then be the whole-mesh
    throughput, and the per-chip figures are also emitted so a record can
    be compared against single-chip runs directly.
    """
    if n_chips < 1:
        raise errors.InvalidArgumentError(
            f"`n_chips` must be positive, got {n_chips}"
        )
    bpe = hbm_bytes_per_eval(log_domain, strategy, lpe, keep, pir)
    vpu = mfu_fields(evals_per_sec, log_domain)
    vpu_ceiling = vpu["roofline_ceiling_evals_per_sec"] * n_chips
    if bpe <= 0:
        hbm_ceiling = float("inf")
    else:
        hbm_ceiling = n_chips * V5E_HBM_BYTES_PER_SEC / bpe
    binding = "hbm" if hbm_ceiling < vpu_ceiling else "vpu"
    out = {
        "hbm_bytes_per_eval_model": round(bpe, 2),
        "hbm_bw_utilization_model": (
            round(evals_per_sec * bpe / (n_chips * V5E_HBM_BYTES_PER_SEC), 4)
        ),
        "binding_wall": binding,
    }
    if hbm_ceiling != float("inf"):
        out["hbm_ceiling_evals_per_sec"] = round(hbm_ceiling)
    if n_chips > 1:
        out["roofline_n_chips"] = n_chips
        out["evals_per_sec_per_chip"] = round(evals_per_sec / n_chips)
        if hbm_ceiling != float("inf"):
            out["hbm_ceiling_evals_per_sec_per_chip"] = round(
                hbm_ceiling / n_chips
            )
    return out


def walk_hashes_per_point(levels: int, captures: int = 1) -> float:
    """AES hashes per point-evaluation of a tree walk: one masked child
    hash per level plus one value hash per capture depth (EvaluateAt
    captures once at the leaves; a DCF captures at every output depth —
    pass captures=levels+1 for the dense-capture worst case)."""
    return float(levels + captures)


def walk_hbm_bytes_per_point(
    levels: int, strategy: str = "walk", lpe: int = 2, captures: int = 1
) -> float:
    """Modeled HBM bytes moved per POINT-evaluation of the walk paths
    (evaluate_at_batch / dcf.batch_evaluate / MIC) — the walk twin of
    `hbm_bytes_per_eval`. A traffic MODEL, counted from the data each
    strategy provably round-trips, not a measurement:

    * "walk" — the per-level engines (`walk_levels_pallas_batched` one
      pallas_call per level, or the XLA scan whose per-level carry XLA
      materializes the same way at serving widths): every level writes
      the [K, 128, W] child planes to HBM and reads them back — 16 B per
      point per level, twice — plus the capture's hashed planes
      (write + read per capture) and the [K, P, lpe] value output.
    * "walkkernel" — the walk megakernel: seed planes, control and the
      whole level loop stay in VMEM/registers; per-point traffic is the
      value-row output write (4*lpe B) plus the per-point share of the
      packed path/select masks (levels+captures bits ~= bytes/8, kept in
      the model for honesty at very deep trees).
    """
    if strategy not in ("walk", "walkkernel"):
        raise errors.InvalidArgumentError(
            f"no walk HBM traffic model for strategy {strategy!r} "
            "(modeled: walk/walkkernel)"
        )
    masks = (levels + captures) / 8.0  # packed path + select bits, read once
    if strategy == "walkkernel":
        return 4.0 * lpe + masks
    planes = 2 * 16.0 * levels  # per-level child planes write + read
    hashed = 2 * 16.0 * captures  # value-hash planes write + read
    values = 2 * 4.0 * lpe  # value buffer write + consumer read
    return planes + hashed + values + masks


def walk_hbm_fields(
    points_per_sec: float,
    levels: int,
    strategy: str = "walk",
    lpe: int = 2,
    captures: int = 1,
) -> dict:
    """Roofline fields for a measured point-walk record (the walk twin of
    `hbm_fields`): the HBM traffic model above next to the VPU ceiling at
    the walk's hashes-per-point cost, and which wall binds."""
    ops = hash_ops_per_block()
    per_point = walk_hashes_per_point(levels, captures) * ops[
        "element_ops_per_block"
    ]
    vpu_ceiling = V5E_VPU_OPS_PER_SEC / per_point
    bpe = walk_hbm_bytes_per_point(levels, strategy, lpe, captures)
    hbm_ceiling = V5E_HBM_BYTES_PER_SEC / bpe
    return {
        "walk_hbm_bytes_per_point_model": round(bpe, 2),
        "walk_vpu_ceiling_points_per_sec": round(vpu_ceiling),
        "walk_hbm_ceiling_points_per_sec": round(hbm_ceiling),
        "walk_mfu_estimate": round(
            points_per_sec * per_point / V5E_VPU_OPS_PER_SEC, 4
        ),
        # "walk_"-prefixed like every other key: a record may carry BOTH
        # models (bench.py merges mfu/hbm fields at top level), and the
        # full-domain `hbm_fields` already owns the bare "binding_wall".
        "walk_binding_wall": "hbm" if hbm_ceiling < vpu_ceiling else "vpu",
    }


def hier_hbm_bytes_per_prefix_level(
    strategy: str = "fused",
    lpe: int = 2,
    keep: int = 2,
    group: int = 16,
) -> float:
    """Modeled HBM bytes moved per (prefix x hierarchy-level) advance of
    the heavy-hitters hierarchical walk — the hierarchical twin of
    `hbm_bytes_per_eval` / `walk_hbm_bytes_per_point`. A traffic MODEL,
    counted from the data each strategy provably round-trips, not a
    measurement:

    * "fused" — the grouped fused advance (`evaluate_levels_fused`,
      mode="fused"): per prefix per level the expansion state round-trips
      HBM between the gather and the expand (2 x 16 B of packed seed
      planes for the 2/keep tree nodes), the value-hash planes round-trip
      (2 x 16/keep), the [K, n, lpe] output is written and read (2 x
      4*lpe), and the precomposed index tables stream in (8 B pos + 8 B
      gsel per lane) — ~100 B per prefix-level, ~13 KB per prefix across
      a 128-level hierarchy.
    * "hierkernel" — the hierarchical megakernel: the whole window's
      walk lives in VMEM/vregs; per prefix per level the traffic is the
      value output write (4*lpe*keep B for the full block), the packed
      path/select mask reads (~(1 + keep)/8 B), and the per-window entry
      gather + exit state amortized over `group` levels (2 x (16 + 8) /
      group) — tens of bytes. The hierkernel trades this for ~group/2 x
      more AES compute (every lane walks the whole window), which the
      VPU headroom absorbs; the win it buys is dispatch count, not
      bandwidth — both strategies sit far under either wall.
    """
    if strategy not in ("fused", "hierkernel"):
        raise errors.InvalidArgumentError(
            f"no hierarchical HBM traffic model for strategy {strategy!r} "
            "(modeled: fused/hierkernel)"
        )
    if strategy == "fused":
        planes = 2 * 16.0 * (2.0 / keep)  # gathered state + expansion
        hashed = 2 * 16.0 / keep  # value-hash planes write + read
        values = 2 * 4.0 * lpe  # output write + consumer read
        tables = 8.0 + 8.0  # int64 pos + gsel rows per lane
        return planes + hashed + values + tables
    values = 4.0 * lpe * keep  # value-row block write
    masks = (1.0 + keep) / 8.0  # packed path + select bits, read once
    window = 2 * (16.0 + 8.0) / max(1, group)  # entry gather + exit state
    return values + masks + window


def hier_hbm_fields(
    prefix_levels_per_sec: float,
    strategy: str = "fused",
    lpe: int = 2,
    keep: int = 2,
    group: int = 16,
) -> dict:
    """Roofline fields for a measured hierarchical-advance record (the
    `walk_hbm_fields` twin): the traffic model above next to the VPU
    ceiling at the walk's per-(prefix, level) hash cost — ~2/keep child
    hashes plus 1/keep value hash for "fused"; the hierkernel multiplies
    the child hashes by ~group/2 (every lane walks its whole window) and
    adds a value hash per capture slot."""
    ops = hash_ops_per_block()
    if strategy == "fused":
        hashes = (2.0 + 1.0) / keep
    else:
        hashes = (2.0 / keep) * (max(1, group) / 2.0) + (
            max(1, group) / 2.0
        ) / keep
    per_pl = hashes * ops["element_ops_per_block"]
    vpu_ceiling = V5E_VPU_OPS_PER_SEC / per_pl
    bpe = hier_hbm_bytes_per_prefix_level(strategy, lpe, keep, group)
    hbm_ceiling = V5E_HBM_BYTES_PER_SEC / bpe
    return {
        "hier_hbm_bytes_per_prefix_level_model": round(bpe, 2),
        "hier_vpu_ceiling_prefix_levels_per_sec": round(vpu_ceiling),
        "hier_hbm_ceiling_prefix_levels_per_sec": round(hbm_ceiling),
        "hier_mfu_estimate": round(
            prefix_levels_per_sec * per_pl / V5E_VPU_OPS_PER_SEC, 4
        ),
        "hier_binding_wall": "hbm" if hbm_ceiling < vpu_ceiling else "vpu",
    }


#: Measured single-thread host-engine anchor (PERF.md: 99.7 M evals/s on
#: the full 1024-key headline run; 75-112 M run-to-run on the shared
#: vCPU). The reference-parity default of DPF_TPU_THREADS=1 is what every
#: engine-table host number uses.
HOST_ANCHOR_EVALS_PER_SEC = 99.7e6

#: Parallel efficiency applied per extra host thread. The native pool
#: (native/dpf_native.cc) splits the key batch across workers with
#: bit-identical outputs and no shared mutable state, but the MMO hash is
#: memory-bandwidth-adjacent at the fused-tail rates and this image's
#: vCPUs are shared — model sub-linear scaling rather than promise linear
#: (PERF.md documents 1.5-2x run-to-run swings from tenancy alone).
HOST_THREAD_EFFICIENCY = 0.85


def host_threads_default() -> int:
    """The host engine's worker count: DPF_TPU_THREADS (0 = all hardware
    threads, unset = the reference-parity 1) — the same resolution rule as
    native/dpf_native.cc."""
    try:
        n = envflags.env_int("DPF_TPU_THREADS", 1)
    except errors.InvalidArgumentError:
        return 1
    if n == 0:
        return os.cpu_count() or 1
    return max(1, n)


def host_thread_speedup(threads=None) -> float:
    """Modeled host-engine speedup at `threads` workers (None = the
    DPF_TPU_THREADS resolution above): 1 + efficiency * (n - 1). The
    serving router's host-side predictions scale their single-thread
    anchors by this — the thread knob previously existed only in the
    native engine + bench env, invisible to any cost model."""
    n = host_threads_default() if threads is None else max(1, int(threads))
    return 1.0 + HOST_THREAD_EFFICIENCY * (n - 1)


def host_anchor_evals_per_sec(threads=None) -> float:
    """The host full-domain anchor at `threads` workers (the router's
    cold-start host rate; see HOST_ANCHOR_EVALS_PER_SEC)."""
    return HOST_ANCHOR_EVALS_PER_SEC * host_thread_speedup(threads)


def _native_anchor() -> str:
    """Sanity anchor: the same arithmetic for the AES-NI/VAES host engine.

    One Xeon core at ~3 GHz retiring one 256-bit VAES aesenc per cycle
    (2 blocks/instr, 10 rounds/block) peaks at 3e9 * 2 / 10 = 600 M
    blocks/s. The native engine's measured ~100 M evals/s headline
    (~300 M hashes/s incl. sigma/xor/gather overhead) is ~50% of that
    port-throughput bound — the engine is near the core's AES ceiling,
    so the anchor arithmetic is calibrated, not optimistic.
    """
    return (
        "native host anchor: VAES port bound ~600 M blocks/s/core "
        "(3 GHz x 2 blocks/aesenc / 10 rounds); measured ~300 M hashes/s "
        "~= 50% of bound"
    )


def main(argv) -> int:
    import json

    ops = hash_ops_per_block()
    print("# bitsliced AES MMO hash — traced gate count")
    print(json.dumps(ops, indent=2))
    rows = []
    for rate_name, rate in (
        [("cli_arg", float(argv[0]))]
        if argv
        else [
            ("BASELINE reference (1 core)", 13e6),
            ("host engine (measured best)", 99.7e6),
            ("device XLA bitslice (measured)", 63.8e6),
            ("device Mosaic claim", 1.06e9),
            ("50x target", 50 * 13e6),
        ]
    ):
        f = mfu_fields(rate, 20)
        rows.append((rate_name, rate, f["mfu_estimate"], f["roofline_ceiling_evals_per_sec"]))
    print("\n# MFU at log_domain=20 (3.00 hashes/eval)")
    print(f"{'scenario':38s} {'evals/s':>12s} {'VPU MFU':>8s}")
    for name, rate, mfu, ceil in rows:
        print(f"{name:38s} {rate:12.3e} {mfu:8.2%}")
    print(f"\nroofline ceiling at 100% VPU: {rows[0][3]:.3e} evals/s")
    print(_native_anchor())
    print("\n# HBM-bandwidth roofline (traffic model, v5e ~819 GB/s)")
    print(
        f"{'strategy':14s} {'B/eval':>8s} {'HBM ceiling ev/s':>18s} "
        f"{'binding wall':>13s}"
    )
    vpu_ceiling = mfu_fields(1.0, 20)["roofline_ceiling_evals_per_sec"]
    for strat, pir in (
        ("levels", False), ("fused", False), ("fold", False), ("fold", True),
        ("megakernel", False), ("megakernel", True),
    ):
        bpe = hbm_bytes_per_eval(20, strat, pir=pir)
        ceil = V5E_HBM_BYTES_PER_SEC / bpe if bpe else float("inf")
        name = strat + ("+pir" if pir else "")
        binding = "hbm" if ceil < vpu_ceiling else "vpu"
        ceil_s = f"{ceil:18.3e}" if ceil != float("inf") else f"{'—':>18s}"
        print(f"{name:14s} {bpe:8.2f} {ceil_s} {binding:>13s}")
    print(
        "\n# Point-walk traffic model (per point-eval; 32-level walk, "
        "u64, EvaluateAt captures=1 / DCF captures=33)"
    )
    print(
        f"{'strategy':22s} {'B/pt':>8s} {'HBM ceiling pt/s':>18s} "
        f"{'VPU ceiling pt/s':>18s} {'binding wall':>13s}"
    )
    for strat, caps, label in (
        ("walk", 1, "walk (evaluate_at)"),
        ("walkkernel", 1, "walkkernel (eval_at)"),
        ("walk", 33, "walk (dcf)"),
        ("walkkernel", 33, "walkkernel (dcf)"),
    ):
        f = walk_hbm_fields(1.0, 32, strat, lpe=2, captures=caps)
        print(
            f"{label:22s} {f['walk_hbm_bytes_per_point_model']:8.2f} "
            f"{f['walk_hbm_ceiling_points_per_sec']:18.3e} "
            f"{f['walk_vpu_ceiling_points_per_sec']:18.3e} "
            f"{f['walk_binding_wall']:>13s}"
        )
    print(
        "\n# Hierarchical-advance traffic model (per prefix x level; "
        "u64, keep=2 — the heavy-hitters walk)"
    )
    print(
        f"{'strategy':22s} {'B/pfx-lvl':>10s} {'HBM ceiling':>14s} "
        f"{'VPU ceiling':>14s} {'binding wall':>13s}"
    )
    for strat, grp, label in (
        ("fused", 16, "fused (group=16)"),
        ("hierkernel", 16, "hierkernel (g=16)"),
        ("hierkernel", 32, "hierkernel (g=32)"),
    ):
        f = hier_hbm_fields(1.0, strat, lpe=2, keep=2, group=grp)
        print(
            f"{label:22s} "
            f"{f['hier_hbm_bytes_per_prefix_level_model']:10.2f} "
            f"{f['hier_hbm_ceiling_prefix_levels_per_sec']:14.3e} "
            f"{f['hier_vpu_ceiling_prefix_levels_per_sec']:14.3e} "
            f"{f['hier_binding_wall']:>13s}"
        )
    threads = host_threads_default()
    print(
        f"\n# Host-engine anchor (DPF_TPU_THREADS={threads}): "
        f"{host_anchor_evals_per_sec():.3e} evals/s "
        f"({HOST_ANCHOR_EVALS_PER_SEC:.3e}/thread x "
        f"{host_thread_speedup():.2f} modeled speedup, "
        f"efficiency {HOST_THREAD_EFFICIENCY})"
    )
    print(
        "\n# Router predictions vs measured engine table "
        "(serving/router.py cold-start anchors; ISSUE 8)"
    )
    from ..serving import router as _router

    print(
        f"{'engine-table row':44s} {'measured':>9s} {'routed':>9s} "
        f"{'host_ms':>10s} {'device_ms':>10s}"
    )
    mismatches = 0
    for label, measured, routed, costs in _router.engine_table_predictions():
        host_ms = costs.get("host", float("nan")) * 1e3
        device_ms = min(
            (c for k, c in costs.items() if k.startswith("device")),
            default=float("nan"),
        ) * 1e3
        flag = "" if routed == measured else "  <-- MISPREDICTED"
        mismatches += routed != measured
        print(
            f"{label:44s} {measured:>9s} {routed:>9s} "
            f"{host_ms:10.1f} {device_ms:10.1f}{flag}"
        )
    if mismatches:
        print(
            f"router mispredicts {mismatches} engine-table row(s) — "
            "the anchor table drifted from PERF.md (see "
            "tests/test_serving.py router pins)"
        )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
