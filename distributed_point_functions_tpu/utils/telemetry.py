"""Runtime telemetry bus: spans, counters, and engine-decision records.

ISSUE 6 fuses four previously disconnected observability fragments — the
``IntegrityEvent`` hooks (utils/integrity.py), the Stopwatch/trace helpers
(utils/profiling.py), the test-only program counting of
tests/test_dispatch_audit.py, and bench.py's hand-rolled pipeline A/B —
into one always-on, near-zero-overhead layer that the future cost-model
engine router (ROADMAP "Serving layer") can consume directly:

* **Spans** — nested timed regions (entry point -> chunk -> stage) with a
  parent id. The pipelined chunk executor (ops/pipeline.py) emits one
  ``pipeline.launch`` and one ``pipeline.finalize`` span per chunk, so a
  captured run carries per-stage busy time from which
  :func:`Collector.snapshot` computes a library-side ``pipeline_occupancy``
  figure ((launch busy + finalize busy) / wall clock: > 1 means the
  executor genuinely overlapped stages), replacing bench.py's hand-rolled
  sync-pass A/B as the day-to-day overlap signal.
* **Counters / histograms / gauges** — chunk dispatch counts, H2D/D2H
  bytes, chunk sizes, retry/degrade counts, and stage-latency histograms
  (the measured dispatch latency the router needs instead of
  ``DPF_TPU_*`` knobs). Aggregated in-process; never one event per
  increment.
* **Decision records** — every engine/mode resolution (host vs device vs
  megakernel/walkkernel/hierkernel, env-default fallbacks, degradation
  steps) with a structured ``source``: ``"explicit"`` (caller pinned it),
  ``"env-default"`` (a ``DPF_TPU_*`` knob), ``"pinned-xla"``
  (use_pallas=False vetoed a Mosaic default) or ``"downgrade"`` (the
  resolver fell back, with the reason).
* **Integrity re-home** — every :class:`IntegrityEvent` (sentinel
  verdicts, degradations, engine downgrades) is forwarded onto this bus,
  and the integrity hook registry itself now lives here
  (:class:`HookRegistry`: locked and exception-isolated, fixing the
  unlocked module-list mutation the pipeline's finalize worker raced).

Exporters:

* :func:`capture` — an in-memory ring-buffer collector for a with-block;
  ``snapshot()`` is the test / router surface.
* ``DPF_TPU_TELEMETRY_LOG=<path>`` — a JSONL sink (one event per line,
  line-buffered; an aggregate ``{"kind": "summary"}`` line on close).
  ``tools/tpu_measure.sh`` points every stage at its own artifact file.
* ``DPF_TPU_TELEMETRY=1`` — a process-global ring collector readable via
  the module-level :func:`snapshot` / :func:`summary`.
* ``DPF_TPU_PROFILE_DIR`` — spans bridge to
  ``jax.profiler.TraceAnnotation`` so they appear in Perfetto traces
  (utils/profiling.trace is the capture entry).

Hard constraints (pinned by tests/test_telemetry.py +
tests/test_dispatch_audit.py): the bus adds **zero device programs** —
every measurement is host-side ``perf_counter`` arithmetic or ``.nbytes``
metadata, never a jnp op; with no sink active the fast path is a single
module-global boolean check (``span()`` returns a shared no-op, counters
return immediately, no event objects, no string formatting); and every
subscriber runs under the bus lock discipline with exceptions isolated,
so a raising hook can never corrupt the executor's drain-on-error
semantics.
"""

from __future__ import annotations

import atexit
import contextlib
import dataclasses
import itertools
import json
import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from . import envflags
from .errors import InvalidArgumentError

_log = logging.getLogger("distributed_point_functions_tpu.telemetry")

# ---------------------------------------------------------------------------
# Bus state
# ---------------------------------------------------------------------------

_lock = threading.RLock()
#: Immutable tuple, swapped under _lock; emit paths iterate it lock-free.
_collectors: Tuple["Collector", ...] = ()
_enabled: bool = False
_profile_bridge: bool = False
_ids = itertools.count(1)
_tls = threading.local()


def enabled() -> bool:
    """The guard every instrumentation point checks FIRST. One global
    read; True only while a collector (capture / JSONL / global ring) or
    the profiler bridge is active."""
    return _enabled


def _recompute_enabled() -> None:
    global _enabled
    _enabled = bool(_collectors) or _profile_bridge


def _add_collector(c: "Collector") -> None:
    global _collectors
    with _lock:
        _collectors = _collectors + (c,)
        _recompute_enabled()


def _remove_collector(c: "Collector") -> None:
    global _collectors
    with _lock:
        _collectors = tuple(x for x in _collectors if x is not c)
        _recompute_enabled()


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current_span_id() -> Optional[int]:
    """Span id at the top of THIS thread's stack (None outside any span).
    The pipelined executor captures it on the main thread and passes it as
    the explicit parent of worker-thread finalize spans, so the span tree
    is identical with the pipeline on and off."""
    stack = getattr(_tls, "stack", None)
    return stack[-1].span_id if stack else None


# ---------------------------------------------------------------------------
# Records
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TelemetryRecord:
    """One bus event: a completed span, an engine decision, or a re-homed
    integrity event. Counters/histograms do NOT flow through records —
    they aggregate in-place per collector."""

    kind: str  # "span" | "decision" | "integrity"
    name: str
    t: float  # epoch seconds at record creation (span END time)
    duration: float  # seconds (spans; 0.0 otherwise)
    span_id: int
    parent_id: Optional[int]
    thread: str
    data: dict

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "name": self.name,
            "t": self.t,
            "duration": self.duration,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "thread": self.thread,
            **({"data": self.data} if self.data else {}),
        }


def _emit(rec: TelemetryRecord) -> None:
    """Fans one record out to every collector, exception-isolated: a
    raising sink (full disk, hostile subscriber) must never propagate into
    the executor or mask the record for the other sinks."""
    for c in _collectors:
        try:
            c.add_event(rec)
        except Exception:
            _log.exception("telemetry collector failed")


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


class _NullSpan:
    """Shared no-op context manager returned while the bus is disabled —
    the disabled fast path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "parent_id", "span_id", "_t0", "_ann")

    def __init__(self, name: str, attrs: dict, parent: Optional[int] = None):
        self.name = name
        self.attrs = attrs
        self.parent_id = parent
        self.span_id = 0
        self._t0 = 0.0
        self._ann = None

    def __enter__(self):
        stack = _stack()
        if self.parent_id is None and stack:
            self.parent_id = stack[-1].span_id
        self.span_id = next(_ids)
        stack.append(self)
        if _profile_bridge:
            try:
                import jax

                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        # Pop by identity from the end: resilient to a mis-nested exit
        # (e.g. a generator closed out of order) without corrupting the
        # rest of the stack.
        stack = _stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        if exc_type is not None:
            self.attrs = dict(self.attrs, error=exc_type.__name__)
        if _collectors:
            _emit(
                TelemetryRecord(
                    kind="span",
                    name=self.name,
                    t=time.time(),
                    duration=dur,
                    span_id=self.span_id,
                    parent_id=self.parent_id,
                    thread=threading.current_thread().name,
                    data=self.attrs,
                )
            )
            observe("span." + self.name, dur, op=self.attrs.get("op"))
        return False


def span(name: str, parent: Optional[int] = None, **attrs):
    """A timed region. Disabled -> the shared no-op (zero allocation
    beyond the kwargs dict). ``parent`` overrides the thread-local nesting
    (cross-thread spans, e.g. the pipeline finalize worker)."""
    if not _enabled:
        return _NULL_SPAN
    return _Span(name, attrs, parent)


def set_attrs(**attrs) -> None:
    """Attaches attributes to the current thread's innermost span (no-op
    when disabled or outside any span) — how @traced entry points record
    values only known mid-body (resolved mode, chunk counts)."""
    if not _enabled:
        return
    stack = getattr(_tls, "stack", None)
    if stack:
        stack[-1].attrs.update(attrs)


def traced(name: str, **static_attrs):
    """Decorator form of :func:`span` for non-generator entry points.
    Disabled path: one boolean check, then straight into ``fn``."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return fn(*args, **kwargs)
            with _Span(name, dict(static_attrs)):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def observe_span(name: str, seconds: float, **attrs) -> None:
    """Records an already-measured region as a span event (no TLS push) —
    the bridge for utils/profiling.Stopwatch laps."""
    if not _enabled or not _collectors:
        return
    _emit(
        TelemetryRecord(
            kind="span",
            name=name,
            t=time.time(),
            duration=float(seconds),
            span_id=next(_ids),
            parent_id=current_span_id(),
            thread=threading.current_thread().name,
            data=attrs,
        )
    )
    observe("span." + name, float(seconds), op=attrs.get("op"))


# ---------------------------------------------------------------------------
# Counters / histograms / gauges
# ---------------------------------------------------------------------------

_HIST_SAMPLE_CAP = 65536


class _Hist:
    __slots__ = ("values", "count", "total", "min", "max")

    def __init__(self):
        self.values: List[float] = []
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if len(self.values) < _HIST_SAMPLE_CAP:
            self.values.append(v)

    def merged(self, other: "_Hist") -> "_Hist":
        out = _Hist()
        out.count = self.count + other.count
        out.total = self.total + other.total
        out.min = min(self.min, other.min)
        out.max = max(self.max, other.max)
        out.values = (self.values + other.values)[:_HIST_SAMPLE_CAP]
        return out

    def stats(self) -> dict:
        if not self.count:
            return {}
        vals = sorted(self.values)

        def pct(p):
            return vals[min(len(vals) - 1, int(p * (len(vals) - 1) + 0.5))]

        return {
            "count": self.count,
            "sum": self.total,
            "min": self.min,
            "max": self.max,
            "p50": pct(0.50),
            "p90": pct(0.90),
            "p99": pct(0.99),
        }

    def ewma(self, alpha: float = 0.3) -> float:
        """Exponentially weighted mean over the stored samples in arrival
        order (``values`` appends chronologically) — the router's live
        dispatch-latency estimate, favoring recent observations."""
        out = 0.0
        seen = False
        for v in self.values:
            out = v if not seen else alpha * v + (1 - alpha) * out
            seen = True
        return out


def counter(name: str, value: float = 1, op: Optional[str] = None) -> None:
    """Adds `value` to counter (name, op) in every active collector.
    Counter keys are tuples on the hot path; string labels like
    ``name[op]`` are only formatted at snapshot time."""
    if not _collectors:
        return
    key = (name, op)
    with _lock:
        for c in _collectors:
            c.counters[key] = c.counters.get(key, 0) + value


def observe(name: str, value: float, op: Optional[str] = None) -> None:
    """One histogram observation (stage latency, chunk size)."""
    if not _collectors:
        return
    key = (name, op)
    with _lock:
        for c in _collectors:
            h = c.hists.get(key)
            if h is None:
                h = c.hists[key] = _Hist()
            h.add(value)


def gauge(name: str, value: float, op: Optional[str] = None) -> None:
    """Sets gauge (name, op) to `value`, tracking the max (queue depth)."""
    if not _collectors:
        return
    key = (name, op)
    with _lock:
        for c in _collectors:
            last = c.gauges.get(key)
            c.gauges[key] = (value, value if last is None else max(last[1], value))


def nbytes_of(obj) -> int:
    """Total numpy bytes reachable in `obj` (tuples/lists of arrays, the
    (valid, out) pairs the executor traffics in). Host arrays only — a
    device array's pull is what finalize measures, so only materialized
    numpy counts as D2H traffic. Pure metadata walk, no transfers."""
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, (tuple, list)):
        return sum(nbytes_of(x) for x in obj)
    return 0


# ---------------------------------------------------------------------------
# Decision + integrity records
# ---------------------------------------------------------------------------


def decision(
    op: str, choice: str, source: str, reason: str = "", **attrs
) -> None:
    """One engine/mode resolution: `op` picked `choice` because `source`
    ("explicit" | "env-default" | "pinned-xla" | "downgrade" | "degrade"),
    with a structured `reason` on the fallback paths. The record the
    cost-model router and device A/B harnesses read to tell "kernel lost"
    from "kernel never ran"."""
    if not _collectors:
        return
    data = {"choice": choice, "source": source}
    if reason:
        data["reason"] = reason
    data.update(attrs)
    _emit(
        TelemetryRecord(
            kind="decision",
            name=op,
            t=time.time(),
            duration=0.0,
            span_id=next(_ids),
            parent_id=current_span_id(),
            thread=threading.current_thread().name,
            data=data,
        )
    )
    counter("decisions", 1, op=op)


def integrity_event(ev) -> None:
    """Forwards one utils.integrity.IntegrityEvent onto the bus (the
    re-home: sentinel verdicts, degradations and engine downgrades share
    the capture/JSONL/summary surface with spans and decisions)."""
    if not _collectors:
        return
    data = {"detail": ev.detail, "backend": ev.backend}
    data.update(ev.data)
    _emit(
        TelemetryRecord(
            kind="integrity",
            name=ev.kind,
            t=ev.timestamp,
            duration=0.0,
            span_id=next(_ids),
            parent_id=current_span_id(),
            thread=threading.current_thread().name,
            data=data,
        )
    )
    counter("integrity." + ev.kind, 1)


class HookRegistry:
    """Locked, exception-isolated subscriber registry — the bus-side home
    of the integrity event hooks (utils.integrity.add_event_hook shims
    onto an instance of this). Fixes ISSUE 6's latent thread-safety bug:
    the old module-level list was mutated unlocked while the pipeline
    finalize worker emitted, and a raising subscriber propagated into the
    executor."""

    def __init__(self, logger: Optional[logging.Logger] = None):
        self._lock = threading.Lock()
        self._hooks: List[Callable] = []
        self._logger = logger or _log

    def add(self, fn: Callable) -> Callable:
        with self._lock:
            self._hooks.append(fn)
        return fn

    def remove(self, fn: Callable) -> None:
        with self._lock:
            try:
                self._hooks.remove(fn)
            except ValueError:
                pass  # concurrent double-remove is benign, not an error

    def emit(self, payload) -> None:
        with self._lock:
            hooks = tuple(self._hooks)
        for fn in hooks:
            try:
                fn(payload)
            except Exception:
                # Exception-isolated BY CONTRACT: a raising subscriber on
                # the finalize worker thread must never corrupt the
                # executor's drain-on-error semantics.
                self._logger.exception("event hook failed")


# ---------------------------------------------------------------------------
# Collectors + snapshot
# ---------------------------------------------------------------------------


def _key_label(key: Tuple[str, Optional[str]]) -> str:
    name, op = key
    return f"{name}[{op}]" if op else name


class Collector:
    """One subscriber's aggregate view: a ring of events plus counter /
    histogram / gauge tables. Span aggregates live in the histogram table
    (fed at span exit), so they survive ring overflow."""

    def __init__(self, ring: int = 4096):
        self.events: deque = deque(maxlen=ring)
        self.counters: Dict[Tuple[str, Optional[str]], float] = {}
        self.hists: Dict[Tuple[str, Optional[str]], _Hist] = {}
        self.gauges: Dict[Tuple[str, Optional[str]], Tuple[float, float]] = {}
        self._t0 = time.perf_counter()
        self._t_end: Optional[float] = None

    def add_event(self, rec: TelemetryRecord) -> None:
        # Under the bus lock: snapshot()'s list(self.events) copy can run
        # concurrently (a monitoring thread reading the global ring while
        # the finalize worker emits), and iterating a deque that another
        # thread appends to raises RuntimeError.
        with _lock:
            self.events.append(rec)

    def snapshot(self) -> dict:
        """Aggregated view: wall clock, counters/gauges with formatted
        labels, histogram percentiles (merged across ops AND per-op), the
        ring's event dicts, and the derived router inputs —
        ``dispatch_count``, per-stage busy seconds, and
        ``pipeline_occupancy``."""
        wall = (self._t_end or time.perf_counter()) - self._t0
        with _lock:
            events = list(self.events)
            counters = dict(self.counters)
            hists = dict(self.hists)
            gauges = dict(self.gauges)
        merged: Dict[str, _Hist] = {}
        for (name, _op), h in hists.items():
            if name in merged:
                merged[name] = merged[name].merged(h)
            else:
                merged[name] = h
        histograms = {name: h.stats() for name, h in merged.items()}
        for key, h in hists.items():
            if key[1] is not None:
                histograms[_key_label(key)] = h.stats()
        launch = merged.get("span.pipeline.launch")
        finalize = merged.get("span.pipeline.finalize")
        stage_seconds = {
            "launch": round(launch.total, 6) if launch else 0.0,
            "finalize": round(finalize.total, 6) if finalize else 0.0,
        }
        dispatch_count = int(
            sum(v for (n, _), v in counters.items() if n == "pipeline.chunks_launched")
        )
        occupancy = None
        if dispatch_count and wall > 0:
            occupancy = round(
                (stage_seconds["launch"] + stage_seconds["finalize"]) / wall, 3
            )
        ev_dicts = [r.to_dict() for r in events]
        decisions = [e for e in ev_dicts if e["kind"] == "decision"]
        integrity_evs = [e for e in ev_dicts if e["kind"] == "integrity"]
        # Aggregations the resilience layer reads (ISSUE 7): the chaos
        # harness asserts telemetry completeness by matching the
        # "degrade" integrity-event count against the decision records
        # with source="degrade" — one record per chain-rung transition.
        by_source: Dict[str, int] = {}
        for d in decisions:
            src = d.get("data", {}).get("source", "")
            by_source[src] = by_source.get(src, 0) + 1
        by_kind: Dict[str, int] = {}
        for e in integrity_evs:
            by_kind[e["name"]] = by_kind.get(e["name"], 0) + 1
        return {
            "wall_seconds": wall,
            "counters": {_key_label(k): v for k, v in counters.items()},
            "gauges": {
                _key_label(k): {"last": v[0], "max": v[1]}
                for k, v in gauges.items()
            },
            "histograms": histograms,
            "events": ev_dicts,
            "spans": [e for e in ev_dicts if e["kind"] == "span"],
            "decisions": decisions,
            "integrity": integrity_evs,
            "decisions_by_source": by_source,
            "integrity_by_kind": by_kind,
            "dispatch_count": dispatch_count,
            "stage_seconds": stage_seconds,
            "pipeline_occupancy": occupancy,
        }

    def latency(
        self, name: str, op: Optional[str] = None, alpha: float = 0.3
    ) -> Optional[dict]:
        """Router-facing point lookup (ISSUE 8): percentiles + EWMA of ONE
        histogram — ``latency("span.pipeline.finalize")`` is the measured
        per-dispatch latency — without deriving the whole snapshot (the
        cost model queries this per served batch; ``snapshot()`` copies
        the event ring and merges every histogram). ``op=None`` merges
        across ops; a specific ``op`` reads that key alone. Returns None
        when nothing has been observed."""
        with _lock:
            if op is not None:
                h = self.hists.get((name, op))
            else:
                h = None
                for (n, _o), cand in self.hists.items():
                    if n == name:
                        h = cand if h is None else h.merged(cand)
            if h is None or not h.count:
                return None
            stats = h.stats()
            stats["mean"] = h.total / h.count
            stats["ewma"] = h.ewma(alpha)
            return stats

    def decision_records(
        self, source: Optional[str] = None, op: Optional[str] = None
    ) -> list:
        """Decision records currently in the ring, optionally filtered by
        ``source`` ("router" / "degrade" / "explicit" / ...) and op name —
        the front door's degrade-feedback scan, without the full
        ``snapshot()``."""
        with _lock:
            events = list(self.events)
        out = []
        for rec in events:
            if rec.kind != "decision":
                continue
            if op is not None and rec.name != op:
                continue
            if source is not None and rec.data.get("source") != source:
                continue
            out.append(rec.to_dict())
        return out

    def summary(self) -> str:
        return summary(self.snapshot())


class JsonlSink(Collector):
    """Collector that streams every event as one JSON line
    (DPF_TPU_TELEMETRY_LOG). Line-buffered so tools/tpu_measure.sh stage
    kills still leave a readable artifact; `close()` appends one
    aggregate ``{"kind": "summary", ...}`` line with the counters and
    histogram stats."""

    def __init__(self, path: str):
        super().__init__(ring=1)
        self.path = path
        self._wlock = threading.Lock()
        self._f = open(path, "a", buffering=1)

    def add_event(self, rec: TelemetryRecord) -> None:
        line = json.dumps(rec.to_dict(), default=str)
        with self._wlock:
            self._f.write(line + "\n")

    def close(self) -> None:
        self._t_end = time.perf_counter()
        snap = self.snapshot()
        final = {
            "kind": "summary",
            "wall_seconds": snap["wall_seconds"],
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": snap["histograms"],
            "dispatch_count": snap["dispatch_count"],
            "stage_seconds": snap["stage_seconds"],
            "pipeline_occupancy": snap["pipeline_occupancy"],
        }
        with self._wlock:
            try:
                self._f.write(json.dumps(final, default=str) + "\n")
                self._f.close()
            except ValueError:
                pass  # already closed


def attach_collector(ring: int = 65536) -> "Collector":
    """A long-lived Collector subscribed to the bus until
    :func:`detach_collector` — the RPC server's stats endpoint holds one
    across its whole lifetime (``capture()`` is scoped to a with-block;
    a server's counters must span requests). The caller owns detachment."""
    c = Collector(ring)
    _add_collector(c)
    return c


def detach_collector(c: "Collector") -> None:
    c._t_end = time.perf_counter()
    _remove_collector(c)


@contextlib.contextmanager
def capture(ring: int = 65536):
    """Collects events + metrics for the with-block — the test and router
    surface. Nested captures each get their own aggregates; the wall
    clock freezes at block exit so a later snapshot() reports the
    captured region, not the time since."""
    c = Collector(ring)
    _add_collector(c)
    try:
        yield c
    finally:
        c._t_end = time.perf_counter()
        _remove_collector(c)


# ---------------------------------------------------------------------------
# Env-driven process sinks
# ---------------------------------------------------------------------------

_jsonl: Optional[JsonlSink] = None
_global_ring: Optional[Collector] = None


def configure_from_env() -> None:
    """(Re)applies DPF_TPU_TELEMETRY_LOG (JSONL sink),
    DPF_TPU_TELEMETRY (process-global ring collector) and
    DPF_TPU_PROFILE_DIR (TraceAnnotation bridge). Called at import; tests
    and long-lived servers call it again after changing the environment."""
    global _jsonl, _global_ring, _profile_bridge
    with _lock:
        path = envflags.env_str("DPF_TPU_TELEMETRY_LOG") or None
        if _jsonl is not None and _jsonl.path != path:
            _remove_collector(_jsonl)
            _jsonl.close()
            _jsonl = None
        if path and _jsonl is None:
            try:
                _jsonl = JsonlSink(path)
                _add_collector(_jsonl)
            except OSError:
                _log.exception("cannot open DPF_TPU_TELEMETRY_LOG %r", path)
                _jsonl = None
        try:
            want_ring = envflags.env_bool("DPF_TPU_TELEMETRY", default=False)
        except InvalidArgumentError:
            # Called at import: an unparsable value must not wedge the
            # process — log and leave the ring off (the historical
            # lenient behavior of this one site).
            _log.warning("unparsable DPF_TPU_TELEMETRY value; ring stays off")
            want_ring = False
        if want_ring and _global_ring is None:
            try:
                ring = envflags.env_int("DPF_TPU_TELEMETRY_RING", 4096)
            except InvalidArgumentError:
                _log.warning(
                    "unparsable DPF_TPU_TELEMETRY_RING value; using 4096"
                )
                ring = 4096
            _global_ring = Collector(ring=ring)
            _add_collector(_global_ring)
        elif not want_ring and _global_ring is not None:
            _remove_collector(_global_ring)
            _global_ring = None
        _profile_bridge = bool(envflags.env_str("DPF_TPU_PROFILE_DIR"))
        _recompute_enabled()


def set_profile_bridge(active: bool) -> None:
    """Explicit TraceAnnotation-bridge toggle for profiling.trace() runs
    started with a log_dir argument rather than the env var."""
    global _profile_bridge
    with _lock:
        _profile_bridge = bool(active) or bool(
            envflags.env_str("DPF_TPU_PROFILE_DIR")
        )
        _recompute_enabled()


@atexit.register
def _close_sinks() -> None:
    global _jsonl
    if _jsonl is not None:
        _remove_collector(_jsonl)
        _jsonl.close()
        _jsonl = None


def snapshot() -> Optional[dict]:
    """The process-global ring collector's snapshot (DPF_TPU_TELEMETRY=1),
    or None when no global collector is installed. Scoped measurement
    should use :func:`capture` instead."""
    return _global_ring.snapshot() if _global_ring is not None else None


def dispatch_latency(op: Optional[str] = None) -> Optional[dict]:
    """Measured per-dispatch latency stats (``pipeline.finalize`` span =
    blocking wait on a dispatched program + its pull) from the
    process-global ring collector, or None when no global collector is
    active / nothing dispatched. The serving router's live-latency source
    for long-lived processes (scoped callers use
    ``Collector.latency("span.pipeline.finalize")`` on a capture)."""
    if _global_ring is None:
        return None
    return _global_ring.latency("span.pipeline.finalize", op)


# ---------------------------------------------------------------------------
# Text summary + bench record fields
# ---------------------------------------------------------------------------


def summary(snap: Optional[dict] = None) -> str:
    """One-call text table of a snapshot — wired into tools/check_device.py
    and the bench stderr logs. Pass a Collector.snapshot(); None reads
    the global ring (empty note when telemetry was off)."""
    if snap is None:
        snap = snapshot()
    if not snap:
        return "telemetry: no collector active (set DPF_TPU_TELEMETRY=1 or use capture())"
    lines = [
        f"telemetry: wall {snap['wall_seconds']:.3f}s, "
        f"{snap['dispatch_count']} chunk dispatches"
        + (
            f", pipeline_occupancy {snap['pipeline_occupancy']}"
            if snap.get("pipeline_occupancy") is not None
            else ""
        )
    ]
    span_rows = [
        (name, st)
        for name, st in sorted(snap["histograms"].items())
        if name.startswith("span.") and st
    ]
    if span_rows:
        lines.append(
            f"  {'span':44s} {'count':>6s} {'total_s':>9s} {'p50_ms':>9s} {'max_ms':>9s}"
        )
        for name, st in span_rows:
            lines.append(
                f"  {name[5:]:44s} {st['count']:6d} {st['sum']:9.3f} "
                f"{st['p50'] * 1e3:9.2f} {st['max'] * 1e3:9.2f}"
            )
    cnt = {
        k: v
        for k, v in sorted(snap["counters"].items())
        if not k.startswith("decisions")
    }
    if cnt:
        lines.append("  counters:")
        for k, v in cnt.items():
            lines.append(f"    {k} = {int(v)}")
    if snap["gauges"]:
        lines.append("  gauges:")
        for k, v in sorted(snap["gauges"].items()):
            lines.append(f"    {k} last={v['last']} max={v['max']}")
    if snap["decisions"]:
        lines.append("  decisions:")
        for d in snap["decisions"]:
            data = d.get("data", {})
            extra = f" ({data.get('reason')})" if data.get("reason") else ""
            lines.append(
                f"    {d['name']} -> {data.get('choice')} "
                f"[{data.get('source')}]{extra}"
            )
    if snap["integrity"]:
        kinds: Dict[str, int] = {}
        for e in snap["integrity"]:
            kinds[e["name"]] = kinds.get(e["name"], 0) + 1
        lines.append(
            "  integrity: "
            + ", ".join(f"{k} x{n}" for k, n in sorted(kinds.items()))
        )
    return "\n".join(lines)


def bench_fields(snap: Optional[dict]) -> dict:
    """The provenance fields a bench record gains from a telemetry
    snapshot (ISSUE 6 satellite): measured chunk ``dispatch_count``,
    per-stage busy-time breakdown, ``pipeline_occupancy`` and
    dispatch-latency percentiles (finalize span = the blocking wait on a
    dispatched program + its pull) — exactly the inputs the future
    cost-model router consumes. Empty dict when the run dispatched
    nothing through the executor (host-engine benches)."""
    if not snap or not snap.get("dispatch_count"):
        return {}
    out = {
        "dispatch_count": snap["dispatch_count"],
        "stage_seconds": {
            k: round(v, 4) for k, v in snap["stage_seconds"].items()
        },
    }
    if snap.get("pipeline_occupancy") is not None:
        out["pipeline_occupancy"] = snap["pipeline_occupancy"]
    lat = snap["histograms"].get("span.pipeline.finalize")
    if lat:
        out["dispatch_latency_ms"] = {
            "p50": round(lat["p50"] * 1e3, 3),
            "p90": round(lat["p90"] * 1e3, 3),
            "max": round(lat["max"] * 1e3, 3),
        }
    return out


configure_from_env()
