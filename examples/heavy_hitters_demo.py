"""Two-server private heavy-hitters over the wire format.

The deployment story the reference's experiments/benchmarks gesture at
(BM_HeavyHitters, distributed_point_function_benchmark.cc:306-340; the
Poplar/heavy-hitters literature): N clients each hold a private value;
two non-colluding servers learn WHICH values are held by >= `threshold`
clients — and nothing else about individual clients.

Protocol (semi-honest, additive shares mod 2^64):

1. Every client builds an incremental DPF key pair for the point function
   f(x) = 1 at its value, with one hierarchy level per `bits_per_level`
   bits, and sends one serialized key to each server (the byte-compatible
   wire format — servers parse, never see plaintext values).
2. Level by level, each server batch-evaluates ALL client keys under the
   surviving candidate prefixes (ops/hierarchical.py BatchedContext) and
   sums the per-prefix shares over clients. Server-side evaluation runs
   through the resilient job supervisor's robust wrapper
   (ops/supervisor.evaluate_levels_fused_robust) — the deployment path:
   dispatch deadlines, host-oracle spot checks, and the
   hierkernel -> fused -> jax -> numpy degradation chain come for free,
   instead of calling the raw engine the way a quickstart would.
3. The servers exchange their per-prefix aggregate shares (two uint64
   vectors — the only communication), reconstruct counts, and keep the
   prefixes with count >= threshold for the next level. Individual
   contributions stay hidden inside the aggregates.

Run: python examples/heavy_hitters_demo.py  (CPU; a few seconds)

``HH_MODE`` selects the server-side execution strategy:

* ``fused`` (default) — the grouped fused advance through the robust
  wrapper (one device program per level on hardware).
* ``hierkernel`` — the staged hierarchical megakernel through the same
  wrapper (single-program prefix windows; off-TPU this runs the Pallas
  interpreter and is SLOW — it is the staged-for-tunnel A/B arm).
* ``host`` — the raw native host engine, no supervisor (the pre-ISSUE 9
  quickstart shape, kept as the baseline arm).
"""

import collections
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BITS = 16  # value width
BITS_PER_LEVEL = 2
NUM_CLIENTS = int(os.environ.get("HH_CLIENTS", 120))
THRESHOLD = int(os.environ.get("HH_THRESHOLD", 8))
HH_MODE = os.environ.get("HH_MODE", "fused")


def main() -> int:
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import evaluator, hierarchical, supervisor
    from distributed_point_functions_tpu.protos import serialization as ser

    if HH_MODE not in ("host", "fused", "hierkernel"):
        print(f"unknown HH_MODE {HH_MODE!r} (host|fused|hierkernel)")
        return 2

    rng = np.random.default_rng(2026)

    # --- client values: a few heavy hitters + uniform noise --------------
    heavy = [0xBEEF, 0x1234, 0xC0DE]
    values = []
    for h in heavy:
        values += [h] * (THRESHOLD + int(rng.integers(0, 5)))
    while len(values) < NUM_CLIENTS:
        values.append(int(rng.integers(0, 1 << BITS)))
    rng.shuffle(values)
    values = values[:NUM_CLIENTS]
    true_counts = collections.Counter(values)
    want = sorted(v for v, c in true_counts.items() if c >= THRESHOLD)

    params = [
        DpfParameters(lds, Int(64))
        for lds in range(BITS_PER_LEVEL, BITS + 1, BITS_PER_LEVEL)
    ]
    dpf = DistributedPointFunction.create_incremental(params)
    n_levels = len(params)

    # --- clients: keygen + serialize (one key per server) ----------------
    t0 = time.time()
    wire_a, wire_b = [], []
    for v in values:
        ka, kb = dpf.generate_keys_incremental(v, [1] * n_levels)
        wire_a.append(ser.serialize_dpf_key(ka, params))
        wire_b.append(ser.serialize_dpf_key(kb, params))
    key_bytes = sum(len(b) for b in wire_a)
    print(
        f"# {NUM_CLIENTS} clients: keygen + serialize {time.time() - t0:.2f}s, "
        f"{key_bytes / NUM_CLIENTS:.0f} B/key on the wire"
    )

    # --- servers: parse once, then level-by-level aggregation ------------
    keys_a = [ser.parse_dpf_key(b) for b in wire_a]
    keys_b = [ser.parse_dpf_key(b) for b in wire_b]
    ctx_a = hierarchical.BatchedContext.create(dpf, keys_a)
    ctx_b = hierarchical.BatchedContext.create(dpf, keys_b)

    def server_advance(ctx, level, prefixes) -> np.ndarray:
        """One server's per-candidate shares for one level, as uint64
        [clients, candidates] — through the robust supervisor wrapper
        (HH_MODE fused/hierkernel) or the raw host engine (HH_MODE=host)."""
        if HH_MODE == "host":
            out = hierarchical.evaluate_until_batch(
                ctx, level, prefixes, engine="host"
            )
            return out.astype(np.uint64)
        limbs = supervisor.evaluate_levels_fused_robust(
            ctx, [(level, list(prefixes))], mode=HH_MODE
        )[0]
        return evaluator.values_to_numpy(limbs, 64)

    print(f"# server mode: {HH_MODE}" + (
        "" if HH_MODE == "host" else " (robust supervisor wrapper)"
    ))
    t0 = time.time()
    prefixes = []
    for level in range(n_levels):
        # Each server: shares for every candidate child prefix, summed over
        # clients (the aggregate hides individual contributions).
        agg_a = server_advance(ctx_a, level, prefixes).sum(
            axis=0, dtype=np.uint64
        )
        agg_b = server_advance(ctx_b, level, prefixes).sum(
            axis=0, dtype=np.uint64
        )
        # The only server-to-server exchange: two aggregate vectors.
        counts = (agg_a + agg_b).astype(np.uint64)  # mod 2^64
        n_candidates = counts.shape[0]
        survivors = np.nonzero(counts >= THRESHOLD)[0]
        # Candidate i is (prefix index << bits_per_level) + child — in the
        # batched path outputs are ordered by sorted prefix then leaf.
        if prefixes:
            base = np.repeat(
                np.asarray(prefixes, dtype=np.uint64), 1 << BITS_PER_LEVEL
            )
            child = np.tile(
                np.arange(1 << BITS_PER_LEVEL, dtype=np.uint64),
                len(prefixes),
            )
            cand = (base << np.uint64(BITS_PER_LEVEL)) + child
        else:
            cand = np.arange(n_candidates, dtype=np.uint64)
        prefixes = sorted(int(cand[i]) for i in survivors)
        print(
            f"# level {level}: {n_candidates} candidates -> "
            f"{len(prefixes)} survivors"
        )
        if not prefixes:
            break
    elapsed = time.time() - t0

    got = sorted(prefixes)
    print(f"# aggregation: {elapsed:.2f}s for {n_levels} levels x {NUM_CLIENTS} clients")
    print(f"heavy hitters found: {[hex(v) for v in got]}")
    print(f"expected:            {[hex(v) for v in want]}")
    if got != want:
        print("MISMATCH")
        return 1
    for v in got:
        print(f"  {hex(v)}: true count {true_counts[v]}")
    print("OK: servers learned only the heavy hitters and their counts")
    return 0


if __name__ == "__main__":
    sys.exit(main())
