"""Two-server private heavy-hitters over the wire format.

The deployment story the reference's experiments/benchmarks gesture at
(BM_HeavyHitters, distributed_point_function_benchmark.cc:306-340; the
Poplar/heavy-hitters literature): N clients each hold a private value;
two non-colluding servers learn WHICH values are held by >= `threshold`
clients — and nothing else about individual clients.

Protocol (semi-honest, additive shares mod 2^64):

1. Every client builds an incremental DPF key pair for the point function
   f(x) = 1 at its value, with one hierarchy level per `bits_per_level`
   bits, and sends one serialized key to each server (the byte-compatible
   wire format — servers parse, never see plaintext values).
2. Level by level, each server batch-evaluates ALL client keys under the
   surviving candidate prefixes (ops/hierarchical.py BatchedContext) and
   sums the per-prefix shares over clients. Server-side evaluation runs
   through the resilient job supervisor's robust wrapper
   (ops/supervisor.evaluate_levels_fused_robust) — the deployment path:
   dispatch deadlines, host-oracle spot checks, and the
   hierkernel -> fused -> jax -> numpy degradation chain come for free,
   instead of calling the raw engine the way a quickstart would.
3. The servers exchange their per-prefix aggregate shares (two uint64
   vectors — the only communication), reconstruct counts, and keep the
   prefixes with count >= threshold for the next level. Individual
   contributions stay hidden inside the aggregates.

Run: python examples/heavy_hitters_demo.py  (CPU; a few seconds)

``--serve`` runs the STREAMING deployment shape instead (ISSUE 15): two
real in-process RPC servers on loopback — party 1 the follower, party 0
the aggregation leader driving the window advance against it — with
clients uploading key batches through the ``hh_ingest`` wire op into
rolling window generations (journaled before acknowledgement), windows
closing at ``HH_WINDOW`` keys, popular prefixes publishing continuously,
and the final ``hh_snapshot`` compared per window against the exact
batch oracle.

``HH_MODE`` selects the server-side execution strategy:

* ``fused`` (default) — the grouped fused advance through the robust
  wrapper (one device program per level on hardware).
* ``hierkernel`` — the staged hierarchical megakernel through the same
  wrapper (single-program prefix windows; off-TPU this runs the Pallas
  interpreter and is SLOW — it is the staged-for-tunnel A/B arm).
* ``host`` — the raw native host engine, no supervisor (the pre-ISSUE 9
  quickstart shape, kept as the baseline arm).
"""

import collections
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BITS = 16  # value width
BITS_PER_LEVEL = 2
NUM_CLIENTS = int(os.environ.get("HH_CLIENTS", 120))
THRESHOLD = int(os.environ.get("HH_THRESHOLD", 8))
HH_MODE = os.environ.get("HH_MODE", "fused")


def serve_main() -> int:
    """The streaming tier (ISSUE 15): the same protocol as `main`, but
    as a LIVE two-server service — batched client uploads over the real
    wire, rolling crash-safe window generations, continuous publishes."""
    import collections
    import tempfile

    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction

    window_keys = int(os.environ.get("HH_WINDOW", 48))
    cfg = serving.StreamConfig.bitwise(
        "demo", BITS, BITS_PER_LEVEL, THRESHOLD, window_keys=window_keys,
    )
    dpf = DistributedPointFunction.create_incremental(list(cfg.parameters))
    n_levels = len(cfg.parameters)

    rng = np.random.default_rng(2026)
    heavy = [0xBEEF, 0x1234, 0xC0DE]
    values = []
    for h in heavy:
        values += [h] * (THRESHOLD + int(rng.integers(0, 5)))
    while len(values) < NUM_CLIENTS:
        values.append(int(rng.integers(0, 1 << BITS)))
    rng.shuffle(values)
    values = values[:NUM_CLIENTS]

    tmp = tempfile.mkdtemp(prefix="dpf-hh-serve-")
    follower = serving.DpfServer(engine="host", max_wait_ms=1.0)
    follower.register_stream(
        serving.HeavyHitterStream(cfg, os.path.join(tmp, "party1"))
    )
    follower.start()
    leader = serving.DpfServer(engine="host", max_wait_ms=1.0)
    leader.register_stream(serving.HeavyHitterStream(
        cfg, os.path.join(tmp, "party0"),
        peer=("127.0.0.1", follower.port),
    ))
    leader.start()
    print(f"# two-server streaming pair up: leader :{leader.port} "
          f"(party 0), follower :{follower.port} (party 1); "
          f"window_keys={window_keys}, journals under {tmp}")

    client = serving.TwoServerClient(
        [("127.0.0.1", leader.port), ("127.0.0.1", follower.port)],
        policy=serving.RetryPolicy(
            attempts=8, base_backoff=0.05, max_backoff=0.5, seed=0,
        ),
    )
    batch_size = 4
    batch_values = {}
    t0 = time.time()
    try:
        for start in range(0, len(values), batch_size):
            vals = values[start:start + batch_size]
            bid = f"client-{start // batch_size}"
            batch_values[bid] = vals
            keys0, keys1 = [], []
            for v in vals:
                k0, k1 = dpf.generate_keys_incremental(v, [1] * n_levels)
                keys0.append(k0)
                keys1.append(k1)
            client.hh_ingest("demo", cfg.parameters, (keys0, keys1), bid,
                             deadline=60)
        client.hh_ingest("demo", cfg.parameters, ([], []), "", flush=True,
                         deadline=30)
        print(f"# {len(batch_values)} client batches x {batch_size} keys "
              f"ingested + flushed in {time.time() - t0:.2f}s "
              "(journaled before every ack)")

        deadline = time.time() + 60
        snap = None
        while time.time() < deadline:
            snap = client.clients[0].hh_snapshot("demo", deadline=10)
            done = {b for w in snap["published"] for b in w["batch_ids"]}
            if (
                len(done) == len(batch_values)
                and snap["pending_windows"] == 0
            ):
                break
            time.sleep(0.2)

        ok = True
        for w in snap["published"]:
            vals = [v for b in w["batch_ids"] for v in batch_values[b]]
            cnt = collections.Counter(vals)
            want = {v: c for v, c in cnt.items() if c >= THRESHOLD}
            got = {int(p): int(c) for p, c in zip(w["prefixes"], w["counts"])}
            hot = {hex(k): v for k, v in sorted(got.items())}
            print(f"# window {w['generation']}: {len(w['batch_ids'])} "
                  f"batches, {w['keys']} keys -> {hot}")
            if got != want:
                ok = False
                print(f"MISMATCH vs batch oracle: want {want}")
        seen = sorted(b for w in snap["published"] for b in w["batch_ids"])
        if seen != sorted(batch_values):
            ok = False
            print("MISMATCH: published membership is not exactly-once")
        print(f"# stream stats: {snap['stats']}")
        if not ok:
            return 1
        print("OK: every window's published counts equal its batch oracle "
              "(no lost, no double-counted keys)")
        return 0
    finally:
        client.close()
        leader.stop()
        follower.stop()


def main() -> int:
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import evaluator, hierarchical, supervisor
    from distributed_point_functions_tpu.protos import serialization as ser

    if HH_MODE not in ("host", "fused", "hierkernel"):
        print(f"unknown HH_MODE {HH_MODE!r} (host|fused|hierkernel)")
        return 2

    rng = np.random.default_rng(2026)

    # --- client values: a few heavy hitters + uniform noise --------------
    heavy = [0xBEEF, 0x1234, 0xC0DE]
    values = []
    for h in heavy:
        values += [h] * (THRESHOLD + int(rng.integers(0, 5)))
    while len(values) < NUM_CLIENTS:
        values.append(int(rng.integers(0, 1 << BITS)))
    rng.shuffle(values)
    values = values[:NUM_CLIENTS]
    true_counts = collections.Counter(values)
    want = sorted(v for v, c in true_counts.items() if c >= THRESHOLD)

    params = [
        DpfParameters(lds, Int(64))
        for lds in range(BITS_PER_LEVEL, BITS + 1, BITS_PER_LEVEL)
    ]
    dpf = DistributedPointFunction.create_incremental(params)
    n_levels = len(params)

    # --- clients: keygen + serialize (one key per server) ----------------
    t0 = time.time()
    wire_a, wire_b = [], []
    for v in values:
        ka, kb = dpf.generate_keys_incremental(v, [1] * n_levels)
        wire_a.append(ser.serialize_dpf_key(ka, params))
        wire_b.append(ser.serialize_dpf_key(kb, params))
    key_bytes = sum(len(b) for b in wire_a)
    print(
        f"# {NUM_CLIENTS} clients: keygen + serialize {time.time() - t0:.2f}s, "
        f"{key_bytes / NUM_CLIENTS:.0f} B/key on the wire"
    )

    # --- servers: parse once, then level-by-level aggregation ------------
    keys_a = [ser.parse_dpf_key(b) for b in wire_a]
    keys_b = [ser.parse_dpf_key(b) for b in wire_b]
    ctx_a = hierarchical.BatchedContext.create(dpf, keys_a)
    ctx_b = hierarchical.BatchedContext.create(dpf, keys_b)

    def server_advance(ctx, level, prefixes) -> np.ndarray:
        """One server's per-candidate shares for one level, as uint64
        [clients, candidates] — through the robust supervisor wrapper
        (HH_MODE fused/hierkernel) or the raw host engine (HH_MODE=host)."""
        if HH_MODE == "host":
            out = hierarchical.evaluate_until_batch(
                ctx, level, prefixes, engine="host"
            )
            return out.astype(np.uint64)
        limbs = supervisor.evaluate_levels_fused_robust(
            ctx, [(level, list(prefixes))], mode=HH_MODE
        )[0]
        return evaluator.values_to_numpy(limbs, 64)

    print(f"# server mode: {HH_MODE}" + (
        "" if HH_MODE == "host" else " (robust supervisor wrapper)"
    ))
    t0 = time.time()
    prefixes = []
    for level in range(n_levels):
        # Each server: shares for every candidate child prefix, summed over
        # clients (the aggregate hides individual contributions).
        agg_a = server_advance(ctx_a, level, prefixes).sum(
            axis=0, dtype=np.uint64
        )
        agg_b = server_advance(ctx_b, level, prefixes).sum(
            axis=0, dtype=np.uint64
        )
        # The only server-to-server exchange: two aggregate vectors.
        counts = (agg_a + agg_b).astype(np.uint64)  # mod 2^64
        n_candidates = counts.shape[0]
        survivors = np.nonzero(counts >= THRESHOLD)[0]
        # Candidate i is (prefix index << bits_per_level) + child — the
        # shared candidate<->output-column mapping (sorted prefix, then
        # leaf) the streaming window manager uses too (ISSUE 15).
        cand = hierarchical.candidate_children(
            prefixes, level * BITS_PER_LEVEL, (level + 1) * BITS_PER_LEVEL,
        )
        prefixes = sorted(int(cand[i]) for i in survivors)
        print(
            f"# level {level}: {n_candidates} candidates -> "
            f"{len(prefixes)} survivors"
        )
        if not prefixes:
            break
    elapsed = time.time() - t0

    got = sorted(prefixes)
    print(f"# aggregation: {elapsed:.2f}s for {n_levels} levels x {NUM_CLIENTS} clients")
    print(f"heavy hitters found: {[hex(v) for v in got]}")
    print(f"expected:            {[hex(v) for v in want]}")
    if got != want:
        print("MISMATCH")
        return 1
    for v in got:
        print(f"  {hex(v)}: true count {true_counts[v]}")
    print("OK: servers learned only the heavy hitters and their counts")
    return 0


if __name__ == "__main__":
    if "--serve" in sys.argv[1:]:
        sys.exit(serve_main())
    sys.exit(main())
