"""Two-server PIR, end to end: the reference library's deployment story
(two non-colluding servers each hold one DPF key; the client learns its
record, neither server learns the index) run through this framework's full
stack — keygen, byte-compatible wire format, device/host evaluation, XOR
inner-product reduction.

    python examples/pir_demo.py [--log_domain 16] [--platform cpu]

Roles are separated the way a real deployment separates them: the client
only ever touches alpha and the two serialized key blobs; each "server"
parses its blob and computes its answer independently against its database
copy (prepared once into lane order at setup — `prepare_pir_database`).

With ``--serve`` (ISSUE 10) the same query runs through the REAL network
stack instead of in-process calls: two `serving.DpfServer` instances on
loopback ports (each one party's RPC front door — batching, routing,
robust supervisor), a `serving.TwoServerClient` with retries/deadlines,
and the length-prefixed wire protocol carrying the byte-compatible key
blobs. Production runs each party as its own process/host::

    python -m distributed_point_functions_tpu.serving.server \\
        --port 9051 --pir-db demo:16:0     # terminal 1, party 0
    python -m distributed_point_functions_tpu.serving.server \\
        --port 9052 --pir-db demo:16:0     # terminal 2, party 1
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def serve_mode(args, D, db, dpf, params, alpha):
    """--serve: the two-server query through real sockets (see module
    docstring). Returns the reconstructed record."""
    import time as _time

    from distributed_point_functions_tpu import serving

    servers = [
        serving.DpfServer(max_wait_ms=2.0).start() for _ in range(2)
    ]
    try:
        for s in servers:
            s.register_db("demo", db)
        print(
            "serve: two DpfServers on 127.0.0.1:"
            f"{servers[0].port} / 127.0.0.1:{servers[1].port}"
        )
        keys = dpf.generate_keys(alpha, (1 << 128) - 1)
        with serving.TwoServerClient(
            [("127.0.0.1", s.port) for s in servers]
        ) as client:
            client.wait_ready(timeout=120)
            # Warm pass: compiles + robust-wrapper warm on both parties,
            # so the printed RPC latency is steady-state serving.
            wk = dpf.generate_keys(0, 1)
            client.pir(params, ([wk[0]], [wk[1]]), "demo", deadline=300)
            t0 = _time.perf_counter()
            a0, a1 = client.pir(
                params, ([keys[0]], [keys[1]]), "demo", deadline=60
            )
            dt = _time.perf_counter() - t0
        record = np.asarray(a0)[0] ^ np.asarray(a1)[0]
        print(f"serve: both answers over the wire in {dt:.3f}s "
              "(two RPCs, retries/deadline armed)")
        return record
    finally:
        for s in servers:
            s.stop()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log_domain", type=int, default=16)
    ap.add_argument("--platform", default=None, help="cpu/tpu override")
    ap.add_argument(
        "--serve", action="store_true",
        help="run the query through the real two-server RPC stack "
        "(serving/server.py + serving/client.py) on loopback",
    )
    args = ap.parse_args()
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        # Both knobs on purpose: some environments (this image's
        # sitecustomize) pre-import jax pointing at hardware, making the
        # env var too late — the config update is what actually switches.
        jax.config.update("jax_platforms", args.platform)

    import distributed_point_functions_tpu as D
    from distributed_point_functions_tpu.parallel import sharded
    from distributed_point_functions_tpu.protos import serialization

    domain = 1 << args.log_domain
    params = D.DpfParameters(args.log_domain, D.XorWrapper(128))
    rng = np.random.default_rng(0)

    # ----- setup: both servers hold the same database ---------------------
    db = rng.integers(0, 2**32, size=(domain, 4), dtype=np.uint32)
    dpf = D.DistributedPointFunction.create(params)
    print(f"db: 2^{args.log_domain} x 128-bit records, backend {jax.default_backend()}")

    if args.serve:
        alpha = int(rng.integers(0, domain))
        record = serve_mode(args, D, db, dpf, [params], alpha)
        assert np.array_equal(record, db[alpha]), "reconstruction failed!"
        print(f"client: reconstructed record {alpha} = "
              f"{[hex(int(x)) for x in record]} — matches")
        return

    prepared = [sharded.prepare_pir_database(dpf, db) for _ in range(2)]

    # ----- client: wants record `alpha`, produces two key blobs -----------
    alpha = int(rng.integers(0, domain))
    k0, k1 = dpf.generate_keys(alpha, (1 << 128) - 1)
    blobs = [
        serialization.serialize_dpf_key(k, [params]) for k in (k0, k1)
    ]
    print(f"client: query for index {alpha}; key blobs {len(blobs[0])} B each")

    # ----- servers: parse blob, answer independently ----------------------
    # (One throwaway query per party warms the JIT caches — the party is a
    # static compile-time argument — so the printed latencies reflect
    # steady-state serving, not first-call compilation.)
    for s, warm_key in enumerate(dpf.generate_keys(0, 1)):
        sharded.pir_query_batch_chunked(dpf, [warm_key], prepared[s])
    answers = []
    for s, blob in enumerate(blobs):
        key = serialization.parse_dpf_key(blob)
        t0 = time.perf_counter()
        ans = sharded.pir_query_batch_chunked(dpf, [key], prepared[s])[0]
        answers.append(ans)
        print(f"server {s}: answered in {time.perf_counter() - t0:.3f}s")

    # ----- client: XOR the two answers = the record -----------------------
    record = answers[0] ^ answers[1]
    assert np.array_equal(record, db[alpha]), "reconstruction failed!"
    print(f"client: reconstructed record {alpha} = {[hex(int(x)) for x in record]} — matches")


if __name__ == "__main__":
    main()
