"""Two-server PIR, end to end: the reference library's deployment story
(two non-colluding servers each hold one DPF key; the client learns its
record, neither server learns the index) run through this framework's full
stack — keygen, byte-compatible wire format, device/host evaluation, XOR
inner-product reduction.

    python examples/pir_demo.py [--log_domain 16] [--platform cpu]

Roles are separated the way a real deployment separates them: the client
only ever touches alpha and the two serialized key blobs; each "server"
parses its blob and computes its answer independently against its database
copy (prepared once into lane order at setup — `prepare_pir_database`).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log_domain", type=int, default=16)
    ap.add_argument("--platform", default=None, help="cpu/tpu override")
    args = ap.parse_args()
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        # Both knobs on purpose: some environments (this image's
        # sitecustomize) pre-import jax pointing at hardware, making the
        # env var too late — the config update is what actually switches.
        jax.config.update("jax_platforms", args.platform)

    import distributed_point_functions_tpu as D
    from distributed_point_functions_tpu.parallel import sharded
    from distributed_point_functions_tpu.protos import serialization

    domain = 1 << args.log_domain
    params = D.DpfParameters(args.log_domain, D.XorWrapper(128))
    rng = np.random.default_rng(0)

    # ----- setup: both servers hold the same database ---------------------
    db = rng.integers(0, 2**32, size=(domain, 4), dtype=np.uint32)
    dpf = D.DistributedPointFunction.create(params)
    prepared = [sharded.prepare_pir_database(dpf, db) for _ in range(2)]
    print(f"db: 2^{args.log_domain} x 128-bit records, backend {jax.default_backend()}")

    # ----- client: wants record `alpha`, produces two key blobs -----------
    alpha = int(rng.integers(0, domain))
    k0, k1 = dpf.generate_keys(alpha, (1 << 128) - 1)
    blobs = [
        serialization.serialize_dpf_key(k, [params]) for k in (k0, k1)
    ]
    print(f"client: query for index {alpha}; key blobs {len(blobs[0])} B each")

    # ----- servers: parse blob, answer independently ----------------------
    # (One throwaway query per party warms the JIT caches — the party is a
    # static compile-time argument — so the printed latencies reflect
    # steady-state serving, not first-call compilation.)
    for s, warm_key in enumerate(dpf.generate_keys(0, 1)):
        sharded.pir_query_batch_chunked(dpf, [warm_key], prepared[s])
    answers = []
    for s, blob in enumerate(blobs):
        key = serialization.parse_dpf_key(blob)
        t0 = time.perf_counter()
        ans = sharded.pir_query_batch_chunked(dpf, [key], prepared[s])[0]
        answers.append(ans)
        print(f"server {s}: answered in {time.perf_counter() - t0:.3f}s")

    # ----- client: XOR the two answers = the record -----------------------
    record = answers[0] ^ answers[1]
    assert np.array_equal(record, db[alpha]), "reconstruction failed!"
    print(f"client: reconstructed record {alpha} = {[hex(int(x)) for x in record]} — matches")


if __name__ == "__main__":
    main()
