"""Two-server private inference: a secure ReLU layer over the wire format.

The secure-ML deployment story of the FSS gate family (BCG+ eprint
2020/1392; the preprocessing model of BGI eprint 2018/707): a dealer
(offline phase) knows nothing about the data but hands each of two
non-colluding servers one ReLU gate key per activation; at inference time
the servers see only *masked* activations ``x = x_real + r_in mod N`` —
uniformly random values that leak nothing — and return additive shares
whose sum (minus the output mask) is exactly ``ReLU(x_real)``. One round,
no interaction between the servers.

Flow (roles separated the way a deployment separates them):

1. **Dealer (offline)**: per activation, draw ``r_in`` / ``r_out``, run
   ``ReluGate.gen`` (4 component DCF keys per party — the two-piece
   degree-1 spline), serialize each party's key bundle through the
   byte-compatible wire format (protos/serialization.serialize_gate_key).
2. **Client / previous layer (online)**: mask its real-valued activation
   vector and broadcast the SAME masked vector to both servers.
3. **Servers**: parse their key bundles and evaluate the whole layer in
   ONE fused batched-DCF pass each (gates/framework.bundle_eval — the
   per-activation keys and sites flatten into a single program; under
   ``mode="walkkernel"`` on hardware, a single walk-megakernel program).
4. **Client**: adds the two share vectors, removes the output masks, and
   checks bit-exactness against the plaintext ReLU.

Run: python examples/secure_relu_demo.py  (CPU; a few seconds)
Knobs: RELU_BITS (default 16), RELU_BATCH (default 24).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BITS = int(os.environ.get("RELU_BITS", 16))
BATCH = int(os.environ.get("RELU_BATCH", 24))


def main() -> int:
    from distributed_point_functions_tpu.gates import ReluGate, framework
    from distributed_point_functions_tpu.protos import serialization as ser

    rng = np.random.default_rng(0xAC71)
    gate = ReluGate.create(BITS)
    n = gate.n
    params = gate.dcf.dpf.validator.parameters

    # --- dealer (offline): masks + per-activation key bundles -------------
    t0 = time.time()
    r_ins = [int(r) for r in rng.integers(0, n, size=BATCH)]
    r_outs = [int(r) for r in rng.integers(0, n, size=BATCH)]
    wire_a, wire_b = [], []
    for r_in, r_out in zip(r_ins, r_outs):
        k0, k1 = gate.gen(r_in, [r_out])
        wire_a.append(ser.serialize_gate_key(k0, params))
        wire_b.append(ser.serialize_gate_key(k1, params))
    key_bytes = sum(len(b) for b in wire_a)
    print(
        f"# dealer: {BATCH} ReLU keys ({BITS}-bit fixed point) in "
        f"{time.time() - t0:.2f}s, {key_bytes / BATCH:.0f} B/key on the wire "
        f"({gate.num_components} component DCFs each)"
    )

    # --- client: signed activations, masked once, sent to both servers ----
    x_real = [int(v) for v in rng.integers(-(n // 2), n // 2, size=BATCH)]
    masked = [(gate.signed_lift(v) + r) % n for v, r in zip(x_real, r_ins)]

    # --- servers: parse keys, evaluate the layer in ONE fused pass each ---
    shares = []
    for name, blobs in (("A", wire_a), ("B", wire_b)):
        keys = [ser.parse_gate_key(b) for b in blobs]
        t0 = time.time()
        out = framework.bundle_eval(gate, keys, masked, engine="device")
        print(
            f"# server {name}: {BATCH} activations in {time.time() - t0:.2f}s "
            f"(one fused batched-DCF pass: "
            f"{BATCH * gate.num_components} keys x "
            f"{BATCH * gate.num_sites} sites)"
        )
        shares.append(out)

    # --- client: reconstruct and verify bit-exactly ------------------------
    ok = True
    for b in range(BATCH):
        y = (int(shares[0][b, 0]) + int(shares[1][b, 0]) - r_outs[b]) % n
        want = max(0, x_real[b])
        if gate.to_signed(y) != want:
            ok = False
            print(f"MISMATCH at {b}: got {gate.to_signed(y)}, want {want}")
    sample = ", ".join(
        f"{x_real[b]}->{max(0, x_real[b])}" for b in range(min(6, BATCH))
    )
    print(f"# reconstructed: {sample}, ...")
    if not ok:
        print("MISMATCH")
        return 1
    print(
        "OK: ReLU reconstructed bit-exactly; servers saw only uniformly "
        "masked activations"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
