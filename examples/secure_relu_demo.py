"""Two-server private inference: secure ReLU + sigmoid layers over the
wire format.

The secure-ML deployment story of the FSS gate family (BCG+ eprint
2020/1392; the preprocessing model of BGI eprint 2018/707): a dealer
(offline phase) knows nothing about the data but hands each of two
non-colluding servers one gate key per activation; at inference time
the servers see only *masked* activations ``x = x_real + r_in mod N`` —
uniformly random values that leak nothing — and return additive shares
whose sum (minus the output mask) is exactly the gate function of
``x_real``. One round, no interaction between the servers.

Two layer legs, both on the vector-payload codec (ISSUE 18 — ONE
tuple-payload DCF key per gate instead of one key per shifted
coefficient):

* **ReLU** — the two-piece degree-1 spline, signed fixed point.
* **Sigmoid** — an 8-piece degree-1 chord spline of 1/(1+e^-x) in
  fixed point (outputs carry 2x the fractional bits, the standard
  pre-truncation FSS spline form). The scalar layout would ship 16
  component keys per activation; the vector codec ships one.

Flow (roles separated the way a deployment separates them):

1. **Dealer (offline)**: per activation, draw ``r_in`` / ``r_out``, run
   ``gate.gen``, serialize each party's key bundle through the
   byte-compatible wire format (protos/serialization.serialize_gate_key;
   vector keys ride the packed VectorDcfKey form).
2. **Client / previous layer (online)**: mask its activation vector and
   broadcast the SAME masked vector to both servers.
3. **Servers**: parse their key bundles and evaluate the whole layer in
   ONE fused batched-DCF pass each (gates/framework.bundle_eval — the
   per-activation keys and sites flatten into a single program; under
   ``mode="walkkernel"`` on hardware, a single walk-megakernel program).
4. **Client**: adds the two share vectors, removes the output masks, and
   checks bit-exactness against the exact-int plaintext gate.

Run: python examples/secure_relu_demo.py  (CPU; a few seconds)
Knobs: RELU_BITS (default 16), RELU_BATCH (default 24).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BITS = int(os.environ.get("RELU_BITS", 16))
BATCH = int(os.environ.get("RELU_BATCH", 24))


def run_layer(name, gate, x_raw, plain, rng) -> bool:
    """One secure layer end to end: dealer keys -> wire -> two servers ->
    client reconstruction, checked bit-exactly against ``plain`` (the
    exact-int plaintext outputs, raw mod-N). Returns True on success."""
    from distributed_point_functions_tpu.gates import framework
    from distributed_point_functions_tpu.protos import serialization as ser

    n = gate.n
    params = gate.dcf.dpf.validator.parameters

    # --- dealer (offline): masks + per-activation key bundles -------------
    t0 = time.time()
    r_ins = [int(r) for r in rng.integers(0, n, size=BATCH)]
    r_outs = [int(r) for r in rng.integers(0, n, size=BATCH)]
    wire_a, wire_b = [], []
    for r_in, r_out in zip(r_ins, r_outs):
        k0, k1 = gate.gen(r_in, [r_out])
        wire_a.append(ser.serialize_gate_key(k0, params))
        wire_b.append(ser.serialize_gate_key(k1, params))
    key_bytes = sum(len(b) for b in wire_a)
    print(
        f"# dealer[{name}]: {BATCH} keys ({BITS}-bit fixed point) in "
        f"{time.time() - t0:.2f}s, {key_bytes / BATCH:.0f} B/key on the wire "
        f"({gate.num_components} component DCFs x {gate.payload_elems} "
        f"payload elements each)"
    )

    # --- client: activations masked once, sent to both servers ------------
    masked = [(x + r) % n for x, r in zip(x_raw, r_ins)]
    # The servers learn nothing: each masked value is x_real shifted by an
    # independent uniform r_in, i.e. itself uniform on [0, N).
    spread = len(set(masked))
    print(
        f"# client[{name}]: {BATCH} masked activations "
        f"({spread} distinct values in [0, {n}) — uniform, input-independent)"
    )

    # --- servers: parse keys, evaluate the layer in ONE fused pass each ---
    shares = []
    for server, blobs in (("A", wire_a), ("B", wire_b)):
        keys = [ser.parse_gate_key(b) for b in blobs]
        t0 = time.time()
        out = framework.bundle_eval(gate, keys, masked, engine="device")
        print(
            f"# server {server}[{name}]: {BATCH} activations in "
            f"{time.time() - t0:.2f}s (one fused batched-DCF pass: "
            f"{BATCH * gate.num_components} keys x "
            f"{BATCH * gate.num_sites} sites)"
        )
        shares.append(out)

    # --- client: reconstruct and verify bit-exactly ------------------------
    ok = True
    for b in range(BATCH):
        y = (int(shares[0][b, 0]) + int(shares[1][b, 0]) - r_outs[b]) % n
        if y != plain[b]:
            ok = False
            print(f"MISMATCH[{name}] at {b}: got {y}, want {plain[b]}")
    return ok


def main() -> int:
    from distributed_point_functions_tpu.gates import ReluGate, SigmoidGate

    rng = np.random.default_rng(0xAC71)

    # --- leg 1: ReLU -------------------------------------------------------
    relu = ReluGate.create(BITS)
    n = relu.n
    x_real = [int(v) for v in rng.integers(-(n // 2), n // 2, size=BATCH)]
    x_raw = [relu.signed_lift(v) for v in x_real]
    plain = [relu.plaintext(x) for x in x_raw]
    ok = run_layer("relu", relu, x_raw, plain, rng)
    if ok:
        sample = ", ".join(
            f"{x_real[b]}->{max(0, x_real[b])}" for b in range(min(6, BATCH))
        )
        print(f"# reconstructed[relu]: {sample}, ...")

    # --- leg 2: sigmoid ----------------------------------------------------
    sig = SigmoidGate.create(BITS)
    frac = 1 << 5  # frac_bits=5 default; outputs carry 2*frac_bits
    lim = int(6.0 * frac)
    xs_fixed = [int(v) for v in rng.integers(-lim, lim + 1, size=BATCH)]
    x_raw = [v % n for v in xs_fixed]
    plain = [sig.plaintext(x) for x in x_raw]
    ok2 = run_layer("sigmoid", sig, x_raw, plain, rng)
    if ok2:
        sample = ", ".join(
            f"{v / frac:+.2f}->{sig.plaintext(v % n) / frac**2:.3f}"
            for v in xs_fixed[: min(6, BATCH)]
        )
        print(f"# reconstructed[sigmoid]: {sample}, ...")

    if not (ok and ok2):
        print("MISMATCH")
        return 1
    print(
        "OK: ReLU and sigmoid layers reconstructed bit-exactly; servers "
        "saw only uniformly masked activations"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
