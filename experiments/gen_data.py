"""Generates synthetic sparse-histogram inputs (the reference's CSV fixtures
at /root/reference/experiments/data/ are git-LFS pointers with no content, so
the fixtures are regenerated from their documented distributions,
/root/reference/experiments/README.md:9-14):

1. power law: 90% of nonzeros uniform in the first 10% of the domain
2. power law: 90% of nonzeros uniform in the first 50% of the domain
3. uniform

Usage: python gen_data.py [--log_domain_size 32] [--num_nonzeros 1048576]
       [--out_dir data]
Writes <bits>_<nonzeros>_<count>_<skew>.csv with one bucket id per line
(first column), matching the reference's file naming and format
(synthetic_data_benchmarks.cc:107-133 reads column 0 of each line).
"""

import argparse
import os
import random


def sample_unique(num: int, log_domain: int, skew) -> list:
    """`num` unique bucket ids; skew in {0.1, 0.5, 'uniform'}."""
    rng = random.Random(f"{log_domain}-{num}-{skew}")
    domain = 1 << log_domain
    seen = set()
    if skew == "uniform":
        while len(seen) < num:
            seen.add(rng.randrange(domain))
    else:
        hot = max(int(domain * float(skew)), 1)
        while len(seen) < num:
            if rng.random() < 0.9:
                seen.add(rng.randrange(hot))
            else:
                seen.add(rng.randrange(domain))
    return sorted(seen)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log_domain_size", type=int, default=32)
    ap.add_argument("--num_nonzeros", type=int, default=1 << 20)
    ap.add_argument("--out_dir", default=os.path.join(os.path.dirname(os.path.abspath(__file__)), "data"))
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)
    for skew in ("0.1", "0.5", "uniform"):
        name = (
            f"{args.log_domain_size}_{args.num_nonzeros}_{args.num_nonzeros}_"
            f"{skew}.csv"
        )
        path = os.path.join(args.out_dir, name)
        values = sample_unique(
            args.num_nonzeros, args.log_domain_size,
            skew if skew == "uniform" else float(skew),
        )
        with open(path, "w") as f:
            for v in values:
                f.write(f"{v}\n")
        print(f"wrote {path} ({len(values)} nonzeros)")


if __name__ == "__main__":
    main()
