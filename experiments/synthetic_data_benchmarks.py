"""Sparse-histogram DPF workload: hierarchical vs direct evaluation.

Re-implements the reference experiments binary
(/root/reference/experiments/synthetic_data_benchmarks.cc) against the TPU
framework:

* reads non-zero bucket ids from a CSV (first column),
* hierarchical mode: picks prefix bit lengths so no level's full expansion
  exceeds --max_expansion_factor x nonzeros (ComputeLevelsToEvaluate,
  synthetic_data_benchmarks.cc:139-165), then runs a hierarchical
  evaluation through the batched device path (ops/hierarchical.py),
* direct mode (--only_nonzeros): single-level DPF evaluated at exactly the
  nonzero indices (RunBatchedSinglePointEvaluation, .cc:196-208) through
  the batched device point evaluator.

Reports seconds per key per iteration — comparable to the reference's
tables (experiments/README.md:39-108, the BASELINE.md numbers).

Usage:
  python gen_data.py                       # once, writes data/*.csv
  python synthetic_data_benchmarks.py --input data/32_1048576_1048576_0.1.csv
  python synthetic_data_benchmarks.py --input ... --only_nonzeros
"""

import argparse
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", default="", help="CSV of nonzero bucket ids")
    ap.add_argument("--log_domain_size", type=int, default=20)
    ap.add_argument(
        "--levels_to_evaluate", default="",
        help="comma-separated log domain sizes for hierarchy levels",
    )
    ap.add_argument("--max_expansion_factor", type=int, default=2)
    ap.add_argument("--num_iterations", type=int, default=20)
    ap.add_argument("--only_nonzeros", action="store_true")
    ap.add_argument(
        "--platform", default=None, help="jax platform override (cpu/tpu)"
    )
    ap.add_argument(
        "--engine",
        default="auto",
        choices=("auto", "device", "host"),
        help="evaluation engine: the XLA device path, the native AES-NI "
        "host engine, or auto (host when the backend is cpu — on a CPU the "
        "honest engine is AES-NI, not the TPU bitslice program; PERF.md)",
    )
    return ap.parse_args()


def read_nonzeros(path: str, log_domain_size: int) -> np.ndarray:
    from distributed_point_functions_tpu.core import uint128

    values = []
    with open(path) as f:
        for line_number, line in enumerate(f):
            field = line.split(",")[0].strip()
            if not field:
                raise ValueError(f"Line {line_number} is empty")
            values.append(int(field))
    if log_domain_size < 64:
        arr = np.unique(np.array(values, dtype=np.uint64))
    else:
        # Vectorized hi/lo uint128 arrays — python-int object arrays make
        # the 2^128-domain bookkeeping the bottleneck (core/uint128.py).
        arr = np.unique(uint128.u128_array(values))
    print(f"# read {arr.shape[0]} nonzeros from {len(values)} lines", file=sys.stderr)
    return arr


def compute_prefixes(nonzeros: np.ndarray, log_domain_size: int):
    """prefixes[bits] = unique bit-prefixes of the nonzeros, bits=0..lds.

    Mirrors ComputePrefixes (synthetic_data_benchmarks.cc:84-105).
    """
    from distributed_point_functions_tpu.core import uint128

    prefixes = [np.array([], dtype=nonzeros.dtype)]
    for bits in range(1, log_domain_size + 1):
        shift = log_domain_size - bits
        if nonzeros.dtype == uint128.U128:
            p = np.unique(uint128.u128_rshift(nonzeros, shift))
        else:
            p = np.unique(nonzeros >> np.uint64(shift))
        prefixes.append(p)
    return prefixes


def compute_levels_to_evaluate(
    prefixes, log_domain_size: int, max_expansion_factor: int
):
    """Mirrors ComputeLevelsToEvaluate (synthetic_data_benchmarks.cc:139-165)."""
    num_nonzeros = len(prefixes[-1])
    assert num_nonzeros > 0
    levels = [
        min(
            log_domain_size,
            int(math.log2(num_nonzeros) + math.log2(max_expansion_factor)),
        )
        - 1
    ]
    while levels[-1] < log_domain_size:
        nonzeros_at_last = len(prefixes[levels[-1] + 1])
        levels.append(
            min(
                log_domain_size,
                int(
                    levels[-1]
                    + math.log2(num_nonzeros)
                    + math.log2(max_expansion_factor)
                    - math.log2(nonzeros_at_last)
                ),
            )
        )
    return levels


def main():
    args = parse_args()
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    try:
        cache = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
        )
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import evaluator, hierarchical

    engine = args.engine
    if engine == "auto":
        engine = "host" if jax.default_backend() == "cpu" else "device"
    print(f"# engine: {engine}", file=sys.stderr)

    lds = args.log_domain_size
    if args.input:
        nonzeros = read_nonzeros(args.input, lds)
        prefixes = compute_prefixes(nonzeros, lds)
    else:
        nonzeros = np.arange(4, dtype=np.uint64)
        prefixes = compute_prefixes(nonzeros, lds)
    num_nonzeros = len(prefixes[-1])
    print(f"# nonzeros: {num_nonzeros}", file=sys.stderr)

    if args.levels_to_evaluate:
        levels = [int(x) for x in args.levels_to_evaluate.split(",")]
    elif not args.only_nonzeros and num_nonzeros:
        levels = compute_levels_to_evaluate(
            prefixes, lds, args.max_expansion_factor
        )
    else:
        levels = [lds]
    print(f"# levels to evaluate: {levels}", file=sys.stderr)

    value_bits = 32  # fixed like the reference (element_bitsize = 32)
    rng = np.random.default_rng(0)
    alpha = int(rng.integers(0, 1 << min(lds, 63)))
    prepare_seconds = None  # set by the device-engine hierarchical path
    if args.only_nonzeros:
        dpf = DistributedPointFunction.create(DpfParameters(lds, Int(value_bits)))
        key, _ = dpf.generate_keys(alpha, 1)
        from distributed_point_functions_tpu.core import uint128

        # The host engine consumes U128/uint64 arrays directly; the device
        # batch evaluator takes python ints per point.
        if engine == "host":
            points = nonzeros
        elif nonzeros.dtype == uint128.U128:
            points = uint128.u128_to_ints(nonzeros)
        else:
            points = [int(x) for x in nonzeros]
        t_start = time.perf_counter()
        for i in range(args.num_iterations):
            if engine == "host":
                from distributed_point_functions_tpu.core import host_eval

                out = host_eval.evaluate_at_host(dpf, [key], points)
            else:
                out = evaluator.evaluate_at_batch(dpf, [key], points)
            if i == 0:
                print(f"# outputs: {out.shape}", file=sys.stderr)
        wall = time.perf_counter() - t_start
    else:
        params = [DpfParameters(l, Int(value_bits)) for l in levels]
        dpf = DistributedPointFunction.create_incremental(params)
        key, _ = dpf.generate_keys_incremental(alpha, [1] * len(levels))
        prefixes_to_evaluate = [np.array([], dtype=np.uint64)] + [
            prefixes[levels[i - 1]] for i in range(1, len(levels))
        ]
        # All prefix sets are known upfront (read from the input file), so
        # the grouped fused advance applies — one device program per group
        # of levels instead of ~4 dispatches per level — and since every
        # iteration replays the SAME plan on a fresh context, the
        # key-independent gather tables are composed and uploaded ONCE
        # (hierarchical.prepare_levels_fused; PERF.md "Prepared plans").
        prepared = None
        if engine == "device":
            plan = [
                (level, prefixes_to_evaluate[level])
                for level in range(len(levels))
            ]
            t_prep = time.perf_counter()
            prepared = hierarchical.prepare_levels_fused(
                hierarchical.BatchedContext.create(dpf, [key]), plan
            )
            prepare_seconds = round(time.perf_counter() - t_prep, 4)
            print(
                f"# plan prepared in {prepare_seconds:.2f}s "
                "(once, amortized across iterations)",
                file=sys.stderr,
            )
        t_start = time.perf_counter()
        for i in range(args.num_iterations):
            ctx = hierarchical.BatchedContext.create(dpf, [key])
            if engine == "device":
                outs = hierarchical.evaluate_levels_fused(
                    ctx, prepared, device_output=True
                )
                if i == 0:
                    for level, o in enumerate(outs):
                        print(
                            f"# outputs at level {level} (log_domain "
                            f"{levels[level]}): {o.shape[1]}",
                            file=sys.stderr,
                        )
                jax.block_until_ready(outs[-1])
                continue
            for level in range(len(levels)):
                out = hierarchical.evaluate_until_batch(
                    ctx,
                    level,
                    prefixes_to_evaluate[level],
                    device_output=True,
                    engine=engine,
                )
                if i == 0:
                    n = out[0].shape[1] if isinstance(out, tuple) else out.shape[1]
                    print(
                        f"# outputs at level {level} (log_domain {levels[level]}): {n}",
                        file=sys.stderr,
                    )
            if engine != "host":
                import jax as _jax

                _jax.block_until_ready(out)
        wall = time.perf_counter() - t_start
    per_iter = wall / args.num_iterations
    mode = "direct" if args.only_nonzeros else "hierarchical"
    import json

    print(
        json.dumps(
            {
                "bench": "experiments",
                "mode": mode,
                "input": os.path.basename(args.input) if args.input else "none",
                "log_domain_size": lds,
                "num_nonzeros": num_nonzeros,
                "levels": levels,
                "value": round(per_iter, 4),
                "unit": "s/key/iteration",
                # Methodology marker (r4): device-engine hierarchical runs
                # replay a prepared plan; the one-time table-composition
                # cost is recorded here, NOT in 'value' (it amortizes
                # across key batches in the aggregation workload).
                **(
                    {"prepare_seconds": prepare_seconds}
                    if prepare_seconds is not None
                    else {}
                ),
                "platform": jax.default_backend(),
            }
        )
    )


if __name__ == "__main__":
    main()
