"""Test configuration.

Must run before jax is imported anywhere: forces an 8-device virtual CPU
platform so multi-chip sharding tests (jax.sharding.Mesh over 8 devices) run
without TPU hardware, and enables x64 so uint64 outputs are representable.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
