"""Test configuration.

Tests run on a *virtual 8-device CPU platform* so multi-chip sharding tests
(jax.sharding.Mesh over 8 devices) run without TPU hardware. Two subtleties of
this environment:

* ``sitecustomize`` may pre-import jax with ``JAX_PLATFORMS`` pointing at real
  TPU hardware, so ``os.environ`` changes are too late — the platform must be
  forced via ``jax.config.update``.
* Only one process may hold the TPU claim at a time; tests must never touch
  the TPU backend or they would contend with benchmark runs.

``XLA_FLAGS`` is still read at first backend initialization, so the virtual
device count is set via the environment before any backend is created.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Suppress XLA:CPU AOT-cache feature-set messages: they are emitted at
# ERROR level (cpu_aot_loader.cc) on EVERY persistent-cache load, so level 2
# would not silence them — the cost is that other XLA ERROR logs are hidden
# too. Export TF_CPP_MIN_LOG_LEVEL=0 when diagnosing device-path failures.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the suite's wall time is dominated by XLA
# compiles of the bitsliced AES programs; repeat runs hit the cache.
_cache_dir = os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache")
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    # Cache even tiny programs: the suite's ~200-test tail compiles many
    # sub-second programs whose aggregate recompile cost is minutes on
    # this image's single vCPU.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
except Exception:
    pass

import pytest


def pytest_addoption(parser):
    parser.addoption(
        "--runslow",
        action="store_true",
        default=False,
        help="also run tests marked slow (heavy parametrizations)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavy compile-bound test; excluded unless --runslow"
    )
    config.addinivalue_line(
        "markers",
        "faults: fault-injection / integrity-layer test "
        "(utils/faultinject.py); ci.sh faults runs this subset",
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow; use --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
