"""Test configuration.

Tests run on a *virtual 8-device CPU platform* so multi-chip sharding tests
(jax.sharding.Mesh over 8 devices) run without TPU hardware. Two subtleties of
this environment:

* ``sitecustomize`` may pre-import jax with ``JAX_PLATFORMS`` pointing at real
  TPU hardware, so ``os.environ`` changes are too late — the platform must be
  forced via ``jax.config.update``.
* Only one process may hold the TPU claim at a time; tests must never touch
  the TPU backend or they would contend with benchmark runs.

``XLA_FLAGS`` is still read at first backend initialization, so the virtual
device count is set via the environment before any backend is created.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
