"""Status-matcher analogs for tests — the pytest counterpart of the
reference's gtest matcher layer (IsOk / IsOkAndHolds / StatusIs and the
DPF_ASSERT_OK* macros, /root/reference/dpf/internal/status_matchers.h).

The reference needs matcher classes because absl::StatusOr is a value; in
Python the error model is exceptions (utils/errors.py keeps the absl
*categories*), so the analogs are context managers / asserting callers.
Using these instead of raw pytest.raises pins BOTH the category and, like
the reference's verbatim-message assertions, the message text.

    from matchers import status_is, assert_ok, assert_ok_and_holds

    with status_is("invalid_argument", "`alpha` must be non-negative"):
        dpf.generate_keys(-1, 1)

    keys = assert_ok(dpf.generate_keys, 5, 1)       # DPF_ASSERT_OK_AND_ASSIGN
    # IsOkAndHolds (remember: ONE party's share is pseudorandom — assert on
    # reconstructed values, not a single share):
    assert_ok_and_holds(lambda: (int(a) + int(b)) % 2**64, 99)
"""

import re

import pytest

from distributed_point_functions_tpu.utils import errors

# absl status-code name -> exception category (the reference's StatusIs
# takes absl::StatusCode; this is the exact correspondence).
CATEGORIES = {
    "invalid_argument": errors.InvalidArgumentError,
    "failed_precondition": errors.FailedPreconditionError,
    "unimplemented": errors.UnimplementedError,
}


def status_is(category: str, message_substr: str = None):
    """StatusIs(code, HasSubstr(message)): asserts the raised error's
    category and (optionally) a verbatim message substring. Thin veneer
    over pytest.raises — the point is the absl-code -> category mapping
    and substring (not regex) message semantics."""
    return pytest.raises(
        CATEGORIES[category],
        match=re.escape(message_substr) if message_substr else None,
    )


def assert_ok(fn, *args, **kwargs):
    """DPF_ASSERT_OK_AND_ASSIGN: calls fn and returns its value; any
    framework error fails the test with the status attached."""
    try:
        return fn(*args, **kwargs)
    except errors.DpfError as e:
        pytest.fail(f"expected OK status, got {type(e).__name__}: {e}")


def assert_ok_and_holds(fn, expected, *args, **kwargs):
    """IsOkAndHolds(expected): fn must succeed AND return `expected`."""
    got = assert_ok(fn, *args, **kwargs)
    assert got == expected, f"expected {expected!r}, got {got!r}"
    return got
