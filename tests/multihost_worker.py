"""Worker for the two-process multi-host test (test_sharded.py).

Run as: python multihost_worker.py <process_id> <num_processes> <port> <out.npy>

Each process joins the jax.distributed cluster on 127.0.0.1:<port>, takes
its contiguous slice of a deterministic key batch (seeds fixed, so every
process derives identical keys), evaluates it over its LOCAL (keys, domain)
mesh — the multi-host design of parallel/multihost.py: no cross-process
collectives exist because the DPF math has no cross-key terms — and saves
its share outputs for the parent to verify.
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    pid, n_proc, port, outp = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        sys.argv[4],
    )
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.parallel import multihost, sharded

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=n_proc,
        process_id=pid,
    )
    assert jax.process_count() == n_proc, jax.process_count()

    dpf = DistributedPointFunction.create(DpfParameters(8, Int(16)))
    rng = np.random.default_rng(7)
    num_keys = 5
    alphas = [int(a) for a in rng.integers(0, 256, size=num_keys)]
    seeds = rng.integers(0, 2**32, size=(num_keys, 2, 4), dtype=np.uint32)
    keys_a, _ = dpf.generate_keys_batch(alphas, [[9] * num_keys], seeds=seeds)

    lo, hi = multihost.local_key_slice(num_keys)
    mesh = multihost.local_mesh()  # this process's 2 virtual devices
    out = np.asarray(sharded.sharded_full_domain_evaluate(dpf, keys_a[lo:hi], mesh))
    np.save(outp, out)
    print(
        json.dumps(
            {
                "pid": pid,
                "lo": lo,
                "hi": hi,
                "global_devices": jax.device_count(),
                "local_devices": len(jax.local_devices()),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
