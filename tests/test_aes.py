"""Golden-value and property tests for the numpy AES oracle.

The pinned constants are the cross-implementation compatibility anchors from
the reference's test suite (/root/reference/dpf/aes_128_fixed_key_hash_test.cc
:114-135); matching them proves byte compatibility of the PRG layer.
"""

import hashlib

import numpy as np
import pytest

from distributed_point_functions_tpu.core import constants, uint128
from distributed_point_functions_tpu.core.aes_numpy import (
    Aes128FixedKeyHash,
    SBOX,
    encrypt_blocks,
    expand_key,
)

KEY0 = uint128.make_uint128(0x0000000000000000, 0x0000000000000000)
KEY1 = uint128.make_uint128(0x1111111111111111, 0x1111111111111111)
SEED0 = uint128.make_uint128(0x0123012301230123, 0x0123012301230123)
SEED1 = uint128.make_uint128(0x4567456745674567, 0x4567456745674567)


def test_sbox_spot_values():
    # Standard AES S-box anchors.
    assert SBOX[0x00] == 0x63
    assert SBOX[0x01] == 0x7C
    assert SBOX[0x53] == 0xED
    assert SBOX[0xFF] == 0x16


def test_fips197_vector():
    # FIPS-197 Appendix B: AES-128 single block.
    key = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
    pt = bytes.fromhex("3243f6a8885a308d313198a2e0370734")
    ct = encrypt_blocks(
        np.frombuffer(pt, dtype=np.uint8)[None, :], expand_key(key)
    ).tobytes()
    assert ct.hex() == "3925841d02dc09fbdc118597196a0b32"


def test_fixed_key_hash_golden_values():
    out0 = Aes128FixedKeyHash(KEY0).evaluate([SEED0, SEED1])
    out1 = Aes128FixedKeyHash(KEY1).evaluate([SEED0, SEED1])
    assert out0 == [
        uint128.make_uint128(0x73C2DC14812BE4EF, 0xEAC64D09C8ADF8ED),
        uint128.make_uint128(0xB8F33653A53A8436, 0xAEDF39B62DE91D95),
    ]
    assert out1 == [
        uint128.make_uint128(0x934704AFF58FA233, 0xD3C20D1B9CC18D8F),
        uint128.make_uint128(0x530098817046D284, 0x43E61D3273A04F7C),
    ]


def test_batched_equals_single():
    prg = Aes128FixedKeyHash(KEY1)
    xs = [uint128.make_uint128(i * 7, i * 13 + 1) for i in range(131)]
    batched = prg.evaluate(xs)
    singles = [prg.evaluate_one(x) for x in xs]
    assert batched == singles


def test_prg_keys_derived_from_sha256_of_names():
    for name, value in [
        ("kPrgKeyLeft", constants.PRG_KEY_LEFT),
        ("kPrgKeyRight", constants.PRG_KEY_RIGHT),
        ("kPrgKeyValue", constants.PRG_KEY_VALUE),
    ]:
        digest = hashlib.sha256(
            f"DistributedPointFunction::{name}\n".encode()
        ).digest()[:16]
        assert int.from_bytes(digest, "big") == value
