"""Differential tests: JAX bitsliced AES vs the numpy oracle.

Mirrors the reference's SIMD-vs-OpenSSL strategy
(/root/reference/dpf/internal/aes_128_fixed_key_hash_hwy_test.cc:63-118).
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.core import constants, uint128
from distributed_point_functions_tpu.core.aes_numpy import (
    Aes128FixedKeyHash,
    encrypt_blocks,
    expand_key,
)
from distributed_point_functions_tpu.ops import aes_jax

RNG = np.random.default_rng(0x5EED)


def random_limbs(n):
    return RNG.integers(0, 2**32, size=(n, 4), dtype=np.uint32)


def test_pack_unpack_roundtrip():
    x = random_limbs(96)
    planes = np.asarray(aes_jax.pack_to_planes(x))
    assert planes.shape == (128, 3)
    back = np.asarray(aes_jax.unpack_from_planes(planes))
    np.testing.assert_array_equal(back, x)


def test_pack_plane_semantics():
    # plane b, word w, bit i == bit b of block 32w+i
    x = random_limbs(64)
    planes = np.asarray(aes_jax.pack_to_planes(x))
    for b in [0, 1, 31, 32, 63, 64, 127]:
        for blk in [0, 1, 33, 63]:
            expected = (int(x[blk, b // 32]) >> (b % 32)) & 1
            got = (int(planes[b, blk // 32]) >> (blk % 32)) & 1
            assert got == expected, (b, blk)


def test_pack_bit_mask():
    bits = RNG.integers(0, 2, size=160).astype(bool)
    mask = aes_jax.pack_bit_mask(bits)
    for i in [0, 5, 31, 32, 100, 159]:
        assert ((int(mask[i // 32]) >> (i % 32)) & 1) == int(bits[i])


@pytest.mark.parametrize("n", [32, 256])
def test_encrypt_matches_oracle(n):
    key = constants.PRG_KEY_LEFT
    x = random_limbs(n)
    got = np.asarray(aes_jax.encrypt_blocks_jax(x, key))
    rks = expand_key(uint128.to_bytes(key))
    want = (
        np.ascontiguousarray(encrypt_blocks(x.view(np.uint8).reshape(n, 16), rks))
        .view(np.uint32)
        .reshape(n, 4)
    )
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize(
    "key",
    [constants.PRG_KEY_LEFT, constants.PRG_KEY_RIGHT, constants.PRG_KEY_VALUE],
)
def test_hash_matches_oracle(key):
    x = random_limbs(128)
    got = np.asarray(aes_jax.hash_blocks_jax(x, key))
    want = Aes128FixedKeyHash(key).evaluate_limbs(x)
    np.testing.assert_array_equal(got, want)


def test_hash_with_key_mask():
    """Per-lane key selection == selecting between the two plain hashes."""
    import jax.numpy as jnp

    n = 64
    x = random_limbs(n)
    bits = RNG.integers(0, 2, size=n).astype(bool)
    mask = jnp.asarray(aes_jax.pack_bit_mask(bits))

    rk_l = np.asarray(aes_jax.round_key_planes(constants.PRG_KEY_LEFT))
    rk_r = np.asarray(aes_jax.round_key_planes(constants.PRG_KEY_RIGHT))
    planes = aes_jax.pack_to_planes(jnp.asarray(x))
    out = aes_jax.hash_planes(
        planes, jnp.asarray(rk_l), jnp.asarray(rk_l ^ rk_r), mask
    )
    got = np.asarray(aes_jax.unpack_from_planes(out))

    left = Aes128FixedKeyHash(constants.PRG_KEY_LEFT).evaluate_limbs(x)
    right = Aes128FixedKeyHash(constants.PRG_KEY_RIGHT).evaluate_limbs(x)
    want = np.where(bits[:, None], right, left)
    np.testing.assert_array_equal(got, want)
