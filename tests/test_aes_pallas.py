"""Pallas expansion kernel vs the XLA bitslice (interpreter mode on CPU)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_point_functions_tpu.ops import aes_pallas, backend_jax

RNG = np.random.default_rng(0xBA11A5)


@pytest.mark.parametrize(
    "w,bw",
    [
        (32, 32),
        pytest.param(64, 32, marks=pytest.mark.slow),
        pytest.param(128, 128, marks=pytest.mark.slow),
    ],
)
def test_pallas_expand_matches_xla(w, bw):
    planes = jnp.asarray(RNG.integers(0, 2**32, size=(128, w), dtype=np.uint32))
    control = jnp.asarray(RNG.integers(0, 2**32, size=(w,), dtype=np.uint32))
    cw = jnp.asarray(RNG.integers(0, 2**32, size=(128,), dtype=np.uint32))
    for ccl, ccr in [(0xFFFFFFFF, 0), (0, 0xFFFFFFFF), (0, 0)]:
        want_p, want_c = backend_jax.expand_one_level(
            planes, control, cw, jnp.uint32(ccl), jnp.uint32(ccr)
        )
        got_p, got_c = aes_pallas.expand_one_level_pallas(
            planes, control, cw, jnp.uint32(ccl), jnp.uint32(ccr),
            block_w=bw, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got_p), np.asarray(want_p))
        np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))


def test_rows_circuit_matches_hash_planes():
    """The row-based AES circuit behind the Mosaic kernels (_aes_rows +
    sigma, trace-time round keys, per-lane key select) is bit-equal to the
    XLA reference hash. The pallas_call plumbing itself is validated on
    hardware (tools/check_device.py CHECK_MODE=fold CHECK_PALLAS=1, and
    every bench's host-oracle verification): interpret mode cannot execute
    this circuit in reasonable time on the CI CPU."""
    import jax

    rng = np.random.default_rng(9)
    w = 32
    planes = rng.integers(0, 2**32, size=(128, w), dtype=np.uint32)
    key_mask = rng.integers(0, 2, size=(w,), dtype=np.uint32) * np.uint32(
        0xFFFFFFFF
    )
    with jax.disable_jit():
        x = [jnp.asarray(planes[i]) for i in range(128)]
        sig = [x[64 + q] for q in range(64)] + [
            x[64 + q] ^ x[q] for q in range(64)
        ]
        enc = aes_pallas._aes_rows(
            sig,
            backend_jax._rk_np("left"),
            backend_jax._rk_np("lr_diff"),
            jnp.asarray(key_mask),
        )
        got = np.stack([np.asarray(enc[q] ^ sig[q]) for q in range(128)])
    from distributed_point_functions_tpu.ops import aes_jax

    want = np.asarray(
        aes_jax.hash_planes(
            jnp.asarray(planes),
            backend_jax._rk("left"),
            backend_jax._rk("lr_diff"),
            jnp.asarray(key_mask),
        )
    )
    np.testing.assert_array_equal(got, want)
