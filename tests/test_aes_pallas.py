"""Pallas expansion kernel vs the XLA bitslice (interpreter mode on CPU)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_point_functions_tpu.ops import aes_pallas, backend_jax

RNG = np.random.default_rng(0xBA11A5)


@pytest.mark.parametrize(
    "w,bw",
    [
        (32, 32),
        pytest.param(64, 32, marks=pytest.mark.slow),
        pytest.param(128, 128, marks=pytest.mark.slow),
    ],
)
def test_pallas_expand_matches_xla(w, bw):
    planes = jnp.asarray(RNG.integers(0, 2**32, size=(128, w), dtype=np.uint32))
    control = jnp.asarray(RNG.integers(0, 2**32, size=(w,), dtype=np.uint32))
    cw = jnp.asarray(RNG.integers(0, 2**32, size=(128,), dtype=np.uint32))
    for ccl, ccr in [(0xFFFFFFFF, 0), (0, 0xFFFFFFFF), (0, 0)]:
        want_p, want_c = backend_jax.expand_one_level(
            planes, control, cw, jnp.uint32(ccl), jnp.uint32(ccr)
        )
        got_p, got_c = aes_pallas.expand_one_level_pallas(
            planes, control, cw, jnp.uint32(ccl), jnp.uint32(ccr),
            block_w=bw, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got_p), np.asarray(want_p))
        np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))


class _CheapRows:
    """Stand-in for aes_pallas._aes_rows: shape- and lane-preserving but
    trivially cheap (row rotation + key-mask XOR), so interpret mode can
    execute the batched pallas_call plumbing on the CI CPU. The real AES
    circuit is pinned separately (test_rows_circuit_matches_hash_planes);
    these smokes exist to catch BlockSpec / index-map / grid / padding
    regressions in the three SHIPPING batched entry points, which round 2
    only validated on hardware (VERDICT r2 weak #4)."""

    def __call__(self, rows, rk_base, rk_diff, key_mask):
        out = []
        for p in range(128):
            row = rows[(p + 1) % 128]
            if rk_diff is not None and key_mask is not None:
                row = row ^ key_mask
            out.append(row)
        return out

    @staticmethod
    def np_hash(planes, key_mask):
        """Numpy model of sigma + cheap-'AES' + final XOR for one key:
        planes uint32[128, w], key_mask uint32[w] or None -> uint32[128, w].
        Mirrors the kernel body: sig = (hi, hi^lo); enc = rot1(sig) ^ mask;
        h = enc ^ sig."""
        x = planes
        sig = np.concatenate([x[64:], x[64:] ^ x[:64]], axis=0)
        enc = np.roll(sig, -1, axis=0)
        if key_mask is not None:
            enc = enc ^ key_mask[None, :]
        return enc ^ sig


def _np_expand_child(planes, control, cw, cc_mask, key_mask):
    """Numpy model of one expand child: returns (planes', control')."""
    h = _CheapRows.np_hash(planes, key_mask)
    h = h ^ (cw[:, None] & control[None, :])
    new_control = h[0] ^ (control & cc_mask)
    h[0] = 0
    return h, new_control


@pytest.fixture
def cheap_rows(monkeypatch):
    jax.clear_caches()  # jitted wrappers may hold real-circuit traces
    monkeypatch.setattr(aes_pallas, "_aes_rows", _CheapRows())
    yield
    jax.clear_caches()  # drop cheap-circuit traces before the next test


@pytest.mark.parametrize("k,w,bw", [(3, 32, 32), (2, 96, 64), (1, 37, 32)])
def test_batched_expand_plumbing_interpret(cheap_rows, k, w, bw):
    """expand_one_level_pallas_batched: grid/BlockSpec plumbing incl. the
    children-block-concatenated output layout, the divisor block width
    (w=96, block_w=64 -> bw=48; ADVICE r2 low), and the pad-and-trim route
    for prime-ish widths (w=37 -> padded, halves re-concatenated)."""
    rng = np.random.default_rng(11)
    planes = rng.integers(0, 2**32, size=(k, 128, w), dtype=np.uint32)
    control = rng.integers(0, 2**32, size=(k, w), dtype=np.uint32)
    cw = rng.integers(0, 2**32, size=(k, 128), dtype=np.uint32)
    full = np.uint32(0xFFFFFFFF)
    ccl = (rng.integers(0, 2, size=k, dtype=np.uint32) * full).astype(np.uint32)
    ccr = (rng.integers(0, 2, size=k, dtype=np.uint32) * full).astype(np.uint32)
    got_p, got_c = aes_pallas.expand_one_level_pallas_batched(
        jnp.asarray(planes), jnp.asarray(control), jnp.asarray(cw),
        jnp.asarray(ccl), jnp.asarray(ccr), block_w=bw, interpret=True,
    )
    got_p, got_c = np.asarray(got_p), np.asarray(got_c)
    assert got_p.shape == (k, 128, 2 * w) and got_c.shape == (k, 2 * w)
    zeros = np.zeros(w, np.uint32)
    for i in range(k):
        lp, lc = _np_expand_child(planes[i], control[i], cw[i], ccl[i], zeros)
        rp, rc = _np_expand_child(planes[i], control[i], cw[i], ccr[i], full + zeros)
        np.testing.assert_array_equal(got_p[i, :, :w], lp)
        np.testing.assert_array_equal(got_p[i, :, w:], rp)
        np.testing.assert_array_equal(got_c[i, :w], lc)
        np.testing.assert_array_equal(got_c[i, w:], rc)


@pytest.mark.parametrize("k,w,bw", [(2, 32, 32), (1, 96, 64), (1, 37, 32)])
def test_batched_value_hash_plumbing_interpret(cheap_rows, k, w, bw):
    """hash_value_planes_pallas_batched: fixed-key hash plumbing incl. the
    pad-and-trim route for prime-ish widths."""
    rng = np.random.default_rng(12)
    planes = rng.integers(0, 2**32, size=(k, 128, w), dtype=np.uint32)
    got = np.asarray(
        aes_pallas.hash_value_planes_pallas_batched(
            jnp.asarray(planes), block_w=bw, interpret=True
        )
    )
    assert got.shape == (k, 128, w)
    for i in range(k):
        np.testing.assert_array_equal(got[i], _CheapRows.np_hash(planes[i], None))


@pytest.mark.parametrize(
    "k,w,bw",
    [(1, 37, 32), pytest.param(2, 32, 32, marks=pytest.mark.slow)],
)
def test_fused_expand_hash_matches_composition_interpret(cheap_rows, k, w, bw):
    """expand_and_hash_last_level_pallas_batched == expand kernel followed
    by the value-hash kernel, bit for bit (same stand-in circuit in both
    paths), incl. the pad-and-trim route."""
    rng = np.random.default_rng(14)
    planes = jnp.asarray(rng.integers(0, 2**32, size=(k, 128, w), dtype=np.uint32))
    control = jnp.asarray(rng.integers(0, 2**32, size=(k, w), dtype=np.uint32))
    cw = jnp.asarray(rng.integers(0, 2**32, size=(k, 128), dtype=np.uint32))
    full = np.uint32(0xFFFFFFFF)
    ccl = jnp.asarray(
        (rng.integers(0, 2, size=k, dtype=np.uint32) * full).astype(np.uint32)
    )
    ccr = jnp.asarray(
        (rng.integers(0, 2, size=k, dtype=np.uint32) * full).astype(np.uint32)
    )
    got_h, got_c = aes_pallas.expand_and_hash_last_level_pallas_batched(
        planes, control, cw, ccl, ccr, block_w=bw, interpret=True
    )
    exp_p, exp_c = aes_pallas.expand_one_level_pallas_batched(
        planes, control, cw, ccl, ccr, block_w=bw, interpret=True
    )
    want_h = aes_pallas.hash_value_planes_pallas_batched(
        exp_p, block_w=bw, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got_h), np.asarray(want_h))
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(exp_c))


@pytest.mark.parametrize(
    "k,w,bw,levels",
    [
        pytest.param(2, 32, 32, 3, marks=pytest.mark.slow),
        # w=40 > block_w=32: exercises the lane-word zero-pad + trim
        # (ADVICE r2 medium: P=20000 -> w=625 crashed the shipping path).
        (1, 40, 32, 2),
    ],
)
def test_batched_walk_plumbing_interpret(cheap_rows, k, w, bw, levels):
    """walk_levels_pallas_batched: per-level kernel chain incl. key-tile
    padding and the non-multiple lane-word padding."""
    rng = np.random.default_rng(13)
    planes = rng.integers(0, 2**32, size=(k, 128, w), dtype=np.uint32)
    control = rng.integers(0, 2**32, size=(k, w), dtype=np.uint32)
    path_masks = rng.integers(0, 2**32, size=(levels, w), dtype=np.uint32)
    cw = rng.integers(0, 2**32, size=(k, levels, 128), dtype=np.uint32)
    full = np.uint32(0xFFFFFFFF)
    ccl = (rng.integers(0, 2, size=(k, levels), dtype=np.uint32) * full).astype(np.uint32)
    ccr = (rng.integers(0, 2, size=(k, levels), dtype=np.uint32) * full).astype(np.uint32)
    got_p, got_c = aes_pallas.walk_levels_pallas_batched(
        jnp.asarray(planes), jnp.asarray(control), jnp.asarray(path_masks),
        jnp.asarray(cw), jnp.asarray(ccl), jnp.asarray(ccr),
        block_w=bw, key_tile=2, interpret=True,
    )
    got_p, got_c = np.asarray(got_p), np.asarray(got_c)
    assert got_p.shape == (k, 128, w) and got_c.shape == (k, w)
    for i in range(k):
        p, c = planes[i].copy(), control[i].copy()
        for lv in range(levels):
            mask = path_masks[lv]
            h = _CheapRows.np_hash(p, mask)
            h = h ^ (cw[i, lv][:, None] & c[None, :])
            cc = (ccl[i, lv] & ~mask) | (ccr[i, lv] & mask)
            c = h[0] ^ (c & cc)
            h[0] = 0
            p = h
        np.testing.assert_array_equal(got_p[i], p)
        np.testing.assert_array_equal(got_c[i], c)


def test_rows_circuit_matches_hash_planes():
    """The row-based AES circuit behind the Mosaic kernels (_aes_rows +
    sigma, trace-time round keys, per-lane key select) is bit-equal to the
    XLA reference hash. The pallas_call plumbing itself is validated on
    hardware (tools/check_device.py CHECK_MODE=fold CHECK_PALLAS=1, and
    every bench's host-oracle verification): interpret mode cannot execute
    this circuit in reasonable time on the CI CPU."""
    import jax

    rng = np.random.default_rng(9)
    w = 32
    planes = rng.integers(0, 2**32, size=(128, w), dtype=np.uint32)
    key_mask = rng.integers(0, 2, size=(w,), dtype=np.uint32) * np.uint32(
        0xFFFFFFFF
    )
    with jax.disable_jit():
        x = [jnp.asarray(planes[i]) for i in range(128)]
        sig = [x[64 + q] for q in range(64)] + [
            x[64 + q] ^ x[q] for q in range(64)
        ]
        enc = aes_pallas._aes_rows(
            sig,
            backend_jax._rk_np("left"),
            backend_jax._rk_np("lr_diff"),
            jnp.asarray(key_mask),
        )
        got = np.stack([np.asarray(enc[q] ^ sig[q]) for q in range(128)])
    from distributed_point_functions_tpu.ops import aes_jax

    want = np.asarray(
        aes_jax.hash_planes(
            jnp.asarray(planes),
            backend_jax._rk("left"),
            backend_jax._rk("lr_diff"),
            jnp.asarray(key_mask),
        )
    )
    np.testing.assert_array_equal(got, want)
