"""Pallas expansion kernel vs the XLA bitslice (interpreter mode on CPU)."""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_point_functions_tpu.ops import aes_pallas, backend_jax

RNG = np.random.default_rng(0xBA11A5)


@pytest.mark.parametrize(
    "w,bw",
    [
        (32, 32),
        pytest.param(64, 32, marks=pytest.mark.slow),
        pytest.param(128, 128, marks=pytest.mark.slow),
    ],
)
def test_pallas_expand_matches_xla(w, bw):
    planes = jnp.asarray(RNG.integers(0, 2**32, size=(128, w), dtype=np.uint32))
    control = jnp.asarray(RNG.integers(0, 2**32, size=(w,), dtype=np.uint32))
    cw = jnp.asarray(RNG.integers(0, 2**32, size=(128,), dtype=np.uint32))
    for ccl, ccr in [(0xFFFFFFFF, 0), (0, 0xFFFFFFFF), (0, 0)]:
        want_p, want_c = backend_jax.expand_one_level(
            planes, control, cw, jnp.uint32(ccl), jnp.uint32(ccr)
        )
        got_p, got_c = aes_pallas.expand_one_level_pallas(
            planes, control, cw, jnp.uint32(ccl), jnp.uint32(ccr),
            block_w=bw, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got_p), np.asarray(want_p))
        np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
