"""AutoScaler control-loop units (ISSUE 20).

Pure control-loop behavior against fake proxy/pool objects — the
thresholds, hysteresis (sustain streaks + the deadband), cooldown,
min/max clamps, plane filtering, and the graceful-drain ordering of the
scale-down path. The loop against REAL servers and a REAL proxy lives in
tests/test_fleet.py; the zero-device-programs pin in
tests/test_dispatch_audit.py.
"""

import pytest

from distributed_point_functions_tpu.serving.autoscale import (
    DEALER_OPS,
    AutoScaler,
)
from distributed_point_functions_tpu.utils.errors import InvalidArgumentError


class FakeProxy:
    def __init__(self, ports=(7001,)):
        self.replicas = {
            p: {"alive": True, "retiring": False, "load": 0} for p in ports
        }
        self.queues = {}
        self.inflight = 0
        self.calls = []

    def health(self):
        return {
            "inflight": self.inflight,
            "fleet": {"replicas": [
                {"endpoint": f"127.0.0.1:{p}", "alive": s["alive"],
                 "retiring": s["retiring"]}
                for p, s in self.replicas.items()
            ]},
        }

    def stats(self):
        return {"queues": dict(self.queues)}

    def add_replica(self, host, port):
        self.calls.append(("add", port))
        s = self.replicas.setdefault(
            port, {"alive": True, "retiring": False, "load": 0}
        )
        s["retiring"] = False

    def set_retiring(self, host, port, retiring=True):
        self.calls.append(("retire", port, retiring))
        if port not in self.replicas:
            return False
        self.replicas[port]["retiring"] = retiring
        return True

    def replica_state(self, host, port):
        s = self.replicas.get(port)
        if s is None:
            return None
        return {
            "endpoint": f"127.0.0.1:{port}", "alive": s["alive"],
            "retiring": s["retiring"], "inflight": 0, "pending": 0,
            "load": s["load"], "routed": 0,
        }


class FakePool:
    def __init__(self, proxy, ports=(7001,)):
        self.proxy = proxy
        self.ports = list(ports)
        self.running = set(range(len(self.ports)))
        self.calls = []

    def running_indices(self):
        return sorted(self.running)

    def scale_up(self, timeout=180.0):
        for i in sorted(set(range(len(self.ports))) - self.running):
            self.running.add(i)
            self.calls.append(("up", i, False))
            return i, self.ports[i], False
        i = len(self.ports)
        self.ports.append(7001 + i)
        self.running.add(i)
        self.calls.append(("up", i, True))
        return i, self.ports[i], True

    def scale_down(self, i, timeout=30.0):
        self.calls.append(("down", i))
        self.running.discard(i)
        self.proxy.replicas[self.ports[i]]["alive"] = False


def make(plane="eval", **kw):
    proxy = FakeProxy()
    pool = FakePool(proxy)
    defaults = dict(
        min_replicas=1, max_replicas=4, interval=0.01, up_backlog=10.0,
        down_backlog=1.0, sustain=2, cooldown=0.0, drain_timeout=1.0,
    )
    defaults.update(kw)
    return proxy, pool, AutoScaler(proxy, pool, plane=plane, **defaults)


# ---------------------------------------------------------------------------
# Validation
# ---------------------------------------------------------------------------


def test_validation():
    proxy, pool = FakeProxy(), None
    pool = FakePool(proxy)
    with pytest.raises(InvalidArgumentError, match="plane"):
        AutoScaler(proxy, pool, plane="gpu")
    with pytest.raises(InvalidArgumentError, match="min_replicas"):
        AutoScaler(proxy, pool, min_replicas=0)
    with pytest.raises(InvalidArgumentError, match="max_replicas"):
        AutoScaler(proxy, pool, min_replicas=3, max_replicas=2)
    with pytest.raises(InvalidArgumentError, match="sustain"):
        AutoScaler(proxy, pool, sustain=0)
    with pytest.raises(InvalidArgumentError, match="down_backlog"):
        AutoScaler(proxy, pool, up_backlog=5.0, down_backlog=5.0)


# ---------------------------------------------------------------------------
# Signal
# ---------------------------------------------------------------------------


def test_backlog_is_per_live_replica():
    proxy, pool, sc = make()
    proxy.queues = {"evaluate_at": 12}
    proxy.inflight = 4
    assert sc.backlog() == 16.0  # one live replica
    proxy.replicas[7002] = {"alive": True, "retiring": False, "load": 0}
    assert sc.backlog() == 8.0
    # Retiring replicas don't dilute the signal: their capacity is
    # already leaving.
    proxy.replicas[7002]["retiring"] = True
    assert sc.backlog() == 16.0


def test_plane_filters_ops():
    proxy, pool, _ = make()
    proxy.queues = {"evaluate_at": 6, "keygen": 30}
    _, _, eval_sc = make()
    eval_sc.proxy = proxy
    assert eval_sc.backlog() == 6.0
    _, _, dealer_sc = make(plane="dealer")
    dealer_sc.proxy = proxy
    assert dealer_sc.backlog() == 30.0
    _, _, all_sc = make(plane="all")
    all_sc.proxy = proxy
    assert all_sc.backlog() == 36.0
    assert DEALER_OPS == ("keygen",)


# ---------------------------------------------------------------------------
# Hysteresis: sustain + deadband + cooldown
# ---------------------------------------------------------------------------


def test_sustain_gates_one_burst_poll():
    proxy, pool, sc = make(sustain=3)
    proxy.queues = {"evaluate_at": 100}
    assert sc.poll_once() is None
    assert sc.poll_once() is None
    assert sc.poll_once() == "up"  # third consecutive crossing
    assert len(pool.running_indices()) == 2


def test_deadband_resets_both_streaks():
    proxy, pool, sc = make(sustain=2)
    proxy.queues = {"evaluate_at": 100}
    assert sc.poll_once() is None   # up streak 1
    proxy.queues = {"evaluate_at": 5}  # in the deadband (1 < 5 < 10)
    assert sc.poll_once() is None   # streaks reset
    proxy.queues = {"evaluate_at": 100}
    assert sc.poll_once() is None   # up streak 1 again — no flap
    assert sc.poll_once() == "up"


def test_cooldown_blocks_consecutive_events():
    proxy, pool, sc = make(sustain=1, cooldown=3600.0)
    proxy.queues = {"evaluate_at": 100}
    assert sc.poll_once() == "up"
    assert sc.poll_once() is None  # cooling down despite a hot signal
    assert sc.stats()["ups"] == 1


def test_diurnal_swing_without_thrash():
    """A smooth rise-then-fall produces ONE scale-up and ONE drain-down,
    not a flap per poll — the hysteresis acceptance shape. (max=2 so
    the sustained-hot plateau tops out; in deployment the cooldown
    paces repeat events, which these instant polls bypass.)"""
    proxy, pool, sc = make(sustain=2, cooldown=0.0, max_replicas=2)
    events = []
    for depth in (2, 30, 40, 50, 40, 30, 5, 0, 0, 0, 0):
        proxy.queues = {"evaluate_at": depth}
        ev = sc.poll_once()
        if ev:
            events.append(ev)
    assert events == ["up", "down"], events


# ---------------------------------------------------------------------------
# Clamps and the drain path
# ---------------------------------------------------------------------------


def test_max_replicas_clamps_scale_up():
    proxy, pool, sc = make(sustain=1, max_replicas=2)
    proxy.queues = {"evaluate_at": 1000}
    assert sc.poll_once() == "up"
    assert sc.poll_once() is None  # at max, signal still hot
    assert len(pool.running_indices()) == 2


def test_min_replicas_clamps_scale_down():
    proxy, pool, sc = make(sustain=1)
    proxy.queues = {}
    assert sc.poll_once() is None  # already at min=1
    assert len(pool.running_indices()) == 1


def test_scale_down_retires_before_stopping():
    """The graceful-drain ordering: the proxy excludes the victim from
    routing BEFORE the pool stops it — order observed via the recorded
    seam calls."""
    proxy, pool, sc = make(sustain=1)
    proxy.queues = {"evaluate_at": 1000}
    assert sc.poll_once() == "up"
    proxy.queues = {}
    assert sc.poll_once() == "down"
    retire_i = proxy.calls.index(("retire", 7002, True))
    down_i = pool.calls.index(("down", 1))
    assert retire_i >= 0 and down_i >= 0
    assert ("down", 1) == pool.calls[-1]
    # And the victim stays on the proxy, retired — the cheap revival.
    assert proxy.replicas[7002]["retiring"] is True


def test_scale_down_waits_for_load_to_drain():
    proxy, pool, sc = make(sustain=1, drain_timeout=0.3)
    proxy.queues = {"evaluate_at": 1000}
    assert sc.poll_once() == "up"
    # Pin load on BOTH replicas (load on one only, and the idle one is
    # correctly chosen and drains instantly): the victim's never-
    # draining load bounds the wait at drain_timeout, then the pool
    # SIGTERM (which itself drains) takes over.
    proxy.replicas[7001]["load"] = 5
    proxy.replicas[7002]["load"] = 5
    proxy.queues = {}
    import time

    t0 = time.perf_counter()
    assert sc.poll_once() == "down"
    assert 0.25 <= time.perf_counter() - t0 < 2.0


def test_scale_up_revives_before_growing():
    proxy, pool, sc = make(sustain=1, max_replicas=3)
    proxy.queues = {"evaluate_at": 1000}
    assert sc.poll_once() == "up"
    proxy.queues = {}
    assert sc.poll_once() == "down"
    proxy.queues = {"evaluate_at": 1000}
    assert sc.poll_once() == "up"
    # The stopped slot revived (grew=False) instead of a new slot.
    assert pool.calls[-1] == ("up", 1, False)
    assert proxy.calls[-1] == ("add", 7002)
    assert proxy.replicas[7002]["retiring"] is False


def test_loop_survives_a_poll_error():
    proxy, pool, sc = make(sustain=1)

    calls = {"n": 0}
    real_stats = proxy.stats

    def flaky_stats():
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionResetError("replica flapped mid-poll")
        return real_stats()

    proxy.stats = flaky_stats
    proxy.queues = {"evaluate_at": 1000}
    sc.start()
    try:
        import time

        t_end = time.perf_counter() + 10
        while time.perf_counter() < t_end and not sc.stats()["ups"]:
            time.sleep(0.01)
    finally:
        sc.stop()
    st = sc.stats()
    assert st["ups"] >= 1  # recovered and scaled after the error
    assert any(e[1] == "error" for e in sc.events())
