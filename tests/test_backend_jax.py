"""Differential tests: JAX backend vs the numpy oracle backend, plus full
DPF correctness (share-sum property) through the JAX backend.

Mirrors the reference's SIMD-vs-scalar differential suite
(/root/reference/dpf/internal/evaluate_prg_hwy_test.cc:43-154).
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.core import backend_numpy, uint128
from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
from distributed_point_functions_tpu.core.params import DpfParameters
from distributed_point_functions_tpu.core.value_types import Int
from distributed_point_functions_tpu.ops.backend_jax import JaxBackend

RNG = np.random.default_rng(0xBACD)


def random_limbs(n):
    return RNG.integers(0, 2**32, size=(n, 4), dtype=np.uint32)


def random_cw(levels):
    seeds = random_limbs(levels)
    ccl = RNG.integers(0, 2, size=levels).astype(bool)
    ccr = RNG.integers(0, 2, size=levels).astype(bool)
    return seeds, ccl, ccr


@pytest.mark.parametrize("num_seeds", [1, 2, 33, 101])
@pytest.mark.parametrize("num_levels", [1, 2, 13])
def test_evaluate_seeds_matches_oracle(num_seeds, num_levels):
    seeds = random_limbs(num_seeds)
    control = RNG.integers(0, 2, size=num_seeds).astype(bool)
    paths = np.zeros((num_seeds, 4), dtype=np.uint32)
    paths[:, 0] = RNG.integers(0, 1 << num_levels, size=num_seeds)
    cs, ccl, ccr = random_cw(num_levels)

    want_seeds, want_ctrl = backend_numpy.evaluate_seeds(
        seeds, control, paths, cs, ccl, ccr
    )
    got_seeds, got_ctrl = JaxBackend.evaluate_seeds(
        seeds, control, paths, cs, ccl, ccr
    )
    np.testing.assert_array_equal(got_seeds, want_seeds)
    np.testing.assert_array_equal(got_ctrl, want_ctrl)


def test_evaluate_seeds_long_paths():
    """Paths spanning more than one 32-bit limb."""
    num_seeds, num_levels = 40, 45
    seeds = random_limbs(num_seeds)
    control = RNG.integers(0, 2, size=num_seeds).astype(bool)
    paths = np.zeros((num_seeds, 4), dtype=np.uint32)
    paths[:, 0] = RNG.integers(0, 2**32, size=num_seeds, dtype=np.uint64)
    paths[:, 1] = RNG.integers(0, 1 << (num_levels - 32), size=num_seeds)
    cs, ccl, ccr = random_cw(num_levels)

    want = backend_numpy.evaluate_seeds(seeds, control, paths, cs, ccl, ccr)
    got = JaxBackend.evaluate_seeds(seeds, control, paths, cs, ccl, ccr)
    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_array_equal(got[1], want[1])


@pytest.mark.parametrize("num_seeds", [1, 3, 32])
@pytest.mark.parametrize("num_levels", [1, 2, 6])
def test_expand_seeds_matches_oracle(num_seeds, num_levels):
    seeds = random_limbs(num_seeds)
    control = RNG.integers(0, 2, size=num_seeds).astype(bool)
    cs, ccl, ccr = random_cw(num_levels)

    want_seeds, want_ctrl = backend_numpy.expand_seeds(
        seeds, control, cs, ccl, ccr
    )
    got_seeds, got_ctrl = JaxBackend.expand_seeds(seeds, control, cs, ccl, ccr)
    np.testing.assert_array_equal(got_seeds, want_seeds)
    np.testing.assert_array_equal(got_ctrl, want_ctrl)


@pytest.mark.parametrize("blocks_needed", [1, 3])
def test_hash_expanded_seeds_matches_oracle(blocks_needed):
    seeds = random_limbs(77)
    # Include a seed that exercises carry propagation in seed + j.
    seeds[0] = [0xFFFFFFFF, 0xFFFFFFFF, 0xFFFFFFFF, 0]
    want = backend_numpy.hash_expanded_seeds(seeds, blocks_needed)
    got = JaxBackend.hash_expanded_seeds(seeds, blocks_needed)
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# End-to-end DPF correctness through the JAX backend
# ---------------------------------------------------------------------------


def test_full_domain_share_sum():
    params = DpfParameters(9, Int(64))
    dpf = DistributedPointFunction.create(params, backend=JaxBackend())
    alpha, beta = 42, 987654321
    key_a, key_b = dpf.generate_keys(alpha, beta)
    ctx_a = dpf.create_evaluation_context(key_a)
    ctx_b = dpf.create_evaluation_context(key_b)
    out_a = dpf.evaluate_next([], ctx_a)
    out_b = dpf.evaluate_next([], ctx_b)
    total = (np.array(out_a, dtype=np.uint64) + np.array(out_b, dtype=np.uint64))
    expected = np.zeros(512, dtype=np.uint64)
    expected[alpha] = beta
    np.testing.assert_array_equal(total, expected)


def test_evaluate_at_share_sum():
    params = DpfParameters(32, Int(64))
    dpf = DistributedPointFunction.create(params, backend=JaxBackend())
    alpha, beta = 0xDEADBEEF, 77
    key_a, key_b = dpf.generate_keys(alpha, beta)
    points = [0, 1, alpha, alpha - 1, alpha + 1, 2**32 - 1] + list(
        RNG.integers(0, 2**32, size=50)
    )
    out_a = dpf.evaluate_at(key_a, 0, points)
    out_b = dpf.evaluate_at(key_b, 0, points)
    for p, a, b in zip(points, out_a, out_b):
        expected = beta if p == alpha else 0
        assert (a + b) % 2**64 == expected, p


def test_hierarchical_share_sum():
    params = [
        DpfParameters(5, Int(32)),
        DpfParameters(10, Int(32)),
    ]
    dpf = DistributedPointFunction.create_incremental(params, backend=JaxBackend())
    alpha, betas = 612, [123, 456]
    key_a, key_b = dpf.generate_keys_incremental(alpha, betas)
    ctx_a = dpf.create_evaluation_context(key_a)
    ctx_b = dpf.create_evaluation_context(key_b)

    out_a = dpf.evaluate_next([], ctx_a)
    out_b = dpf.evaluate_next([], ctx_b)
    total = (np.array(out_a, np.uint32) + np.array(out_b, np.uint32)).astype(np.uint32)
    expected = np.zeros(32, dtype=np.uint32)
    expected[alpha >> 5] = betas[0]
    np.testing.assert_array_equal(total, expected)

    prefixes = [alpha >> 5, (alpha >> 5) ^ 1]
    out_a = dpf.evaluate_next(prefixes, ctx_a)
    out_b = dpf.evaluate_next(prefixes, ctx_b)
    total = (np.array(out_a, np.uint32) + np.array(out_b, np.uint32)).astype(np.uint32)
    expected = np.zeros(64, dtype=np.uint32)
    expected[alpha - ((alpha >> 5) << 5)] = betas[1]
    np.testing.assert_array_equal(total, expected)
