"""Dry tests for the round-5 measurement pipeline plumbing.

Covers, without any tunnel or backend initialization (beyond short-lived
killed probe subprocesses):

- bench.py's watcher-journal budget sizing (``_watcher_hint`` — VERDICT
  r4 #2: a dead tunnel must cost minutes, not 25, before the CPU
  fallback);
- the shared single-process TPU claim (tools/tpu_claim.py) and the
  bench.py-vs-measurement-session arbitration dry run (VERDICT r4
  weak #3 / next #3: bench.py must wait, then proceed cleanly, while a
  fake measure session holds the lock);
- tools/run_bench_stage.py's device-record gating (a CPU fallback inside
  a bench script must NOT mark its measurement stage complete).
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")
sys.path.insert(0, TOOLS)

import tpu_claim  # noqa: E402


def _ts(offset_s: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(time.time() + offset_s))


def _journal(tmp_path, lines, state="watching", state_age_s=0.0):
    d = tmp_path / "watch"
    d.mkdir(exist_ok=True)
    (d / "tpu_watch.log").write_text("\n".join(lines) + "\n")
    sp = d / "tpu_watch.state"
    sp.write_text(state + "\n")
    if state_age_s:
        past = time.time() - state_age_s
        os.utime(sp, (past, past))
    return str(d)


def _load_bench(monkeypatch, watch_dir):
    monkeypatch.setenv("BENCH_WATCH_DIR", watch_dir)
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(ROOT, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestWatcherHint:
    def test_continuously_dead(self, tmp_path, monkeypatch):
        lines = [
            f"{_ts(-600 + i * 150)}Z attempt={i + 1} probe down (backend=)"
            for i in range(4)
        ]
        b = _load_bench(monkeypatch, _journal(tmp_path, lines))
        assert b._watcher_hint() == "dead"

    def test_recent_probe_ok_wins(self, tmp_path, monkeypatch):
        lines = [
            f"{_ts(-700)}Z attempt=1 probe down (backend=)",
            f"{_ts(-500)}Z attempt=2 probe down (backend=)",
            f"{_ts(-120)}Z attempt=3 PROBE OK backend=tpu -> tpu_measure.sh",
        ]
        b = _load_bench(monkeypatch, _journal(tmp_path, lines))
        assert b._watcher_hint() == "up"

    def test_measuring_state_means_claimed(self, tmp_path, monkeypatch):
        lines = [f"{_ts(-60)}Z attempt=1 probe down (backend=)"]
        b = _load_bench(monkeypatch, _journal(tmp_path, lines, state="measuring"))
        monkeypatch.delenv("TPU_CLAIM_HELD", raising=False)
        assert b._watcher_hint() == "claimed"

    def test_measuring_inside_own_session_means_up(self, tmp_path, monkeypatch):
        # bench.py running INSIDE the measure session (claim held by an
        # ancestor): the tunnel answered minutes ago — skip the probe.
        lines = [f"{_ts(-60)}Z attempt=1 probe down (backend=)"]
        b = _load_bench(monkeypatch, _journal(tmp_path, lines, state="measuring"))
        monkeypatch.setenv("TPU_CLAIM_HELD", "1")
        assert b._watcher_hint() == "up"

    def test_fresh_done_state_means_up(self, tmp_path, monkeypatch):
        b = _load_bench(monkeypatch, _journal(tmp_path, [], state="done"))
        assert b._watcher_hint() == "up"

    def test_stale_done_state_is_uninformative(self, tmp_path, monkeypatch):
        b = _load_bench(
            monkeypatch, _journal(tmp_path, [], state="done", state_age_s=7200)
        )
        assert b._watcher_hint() is None

    def test_stale_journal_is_uninformative(self, tmp_path, monkeypatch):
        lines = [
            f"{_ts(-7200 + i * 150)}Z attempt={i + 1} probe down (backend=)"
            for i in range(6)
        ]
        b = _load_bench(monkeypatch, _journal(tmp_path, lines))
        assert b._watcher_hint() is None

    def test_too_few_probes_is_uninformative(self, tmp_path, monkeypatch):
        lines = [f"{_ts(-60)}Z attempt=1 probe down (backend=)"]
        b = _load_bench(monkeypatch, _journal(tmp_path, lines))
        assert b._watcher_hint() is None

    def test_skipped_probes_do_not_count(self, tmp_path, monkeypatch):
        # "probe skipped (TPU claim held)" lines are arbitration noise, not
        # evidence of a dead tunnel.
        lines = [
            f"{_ts(-400 + i * 100)}Z attempt={i + 1} probe skipped (TPU claim held)"
            for i in range(4)
        ] + [f"{_ts(-50)}Z attempt=5 probe down (backend=)"]
        b = _load_bench(monkeypatch, _journal(tmp_path, lines))
        assert b._watcher_hint() is None

    def test_missing_journal(self, tmp_path, monkeypatch):
        b = _load_bench(monkeypatch, str(tmp_path / "nope"))
        assert b._watcher_hint() is None

    def test_opt_out(self, tmp_path, monkeypatch):
        lines = [
            f"{_ts(-600 + i * 150)}Z attempt={i + 1} probe down (backend=)"
            for i in range(4)
        ]
        b = _load_bench(monkeypatch, _journal(tmp_path, lines))
        monkeypatch.setenv("BENCH_WATCHER_JOURNAL", "0")
        assert b._watcher_hint() is None


class TestTpuClaim:
    def test_exclusive_and_released(self, tmp_path):
        lock = str(tmp_path / "claim.lock")
        with tpu_claim.hold("a", timeout=0, path=lock):
            with pytest.raises(tpu_claim.ClaimUnavailable) as e:
                with tpu_claim.hold("b", timeout=0.2, poll=0.05, path=lock):
                    pass
            assert '"label": "a"' in str(e.value)
        # Released: immediate re-acquisition succeeds.
        with tpu_claim.hold("c", timeout=0, path=lock):
            pass

    def test_nested_hold_is_noop_under_env(self, tmp_path, monkeypatch):
        lock = str(tmp_path / "claim.lock")
        monkeypatch.setenv("TPU_CLAIM_HELD", "1")
        with tpu_claim.hold("outer-held", timeout=0, path=lock):
            with tpu_claim.hold("inner", timeout=0, path=lock):
                pass

    def test_wait_succeeds_when_holder_releases(self, tmp_path):
        lock = str(tmp_path / "claim.lock")
        env = {**os.environ, "TPU_CLAIM_PATH": lock}
        env.pop("TPU_CLAIM_HELD", None)
        holder = subprocess.Popen(
            [sys.executable, os.path.join(TOOLS, "tpu_claim.py"), "hold", "2"],
            env=env,
        )
        try:
            time.sleep(0.8)  # let the holder acquire
            t0 = time.time()
            with tpu_claim.hold("waiter", timeout=15, poll=0.2, path=lock):
                waited = time.time() - t0
            assert waited < 15
        finally:
            holder.wait(timeout=30)


def _bench_env(watch_dir, lock_path, **extra):
    """Environment for a real bench.py subprocess: tiny CPU config, tight
    probe/device budgets, isolated watcher journal and claim lock."""
    env = dict(os.environ)
    env.pop("TPU_CLAIM_HELD", None)
    env.pop("JAX_PLATFORMS", None)  # the child probes for itself
    env.update(
        BENCH_WATCH_DIR=watch_dir,
        TPU_CLAIM_PATH=lock_path,
        BENCH_CPU_LOG_DOMAIN="8",
        BENCH_CPU_KEYS="4",
        BENCH_CPU_REPS="2",
        BENCH_PROBE_TIMEOUT="3",
        BENCH_PROBE_ATTEMPTS="1",
        BENCH_PROBE_TIMEOUT_DEAD="3",
        BENCH_TPU_TIMEOUT="8",
        BENCH_TPU_TIMEOUT_UNPROBED="8",
        BENCH_TPU_TIMEOUT_DEAD="8",
        BENCH_CPU_TIMEOUT="60",
    )
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _run_bench(env, timeout=240):
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    return json.loads(line), proc.stderr


class TestBenchClaimArbitration:
    """The VERDICT r4 #3 dry test: bench.py vs a fake measurement session."""

    def test_fallback_when_claim_stays_held(self, tmp_path):
        lock = str(tmp_path / "claim.lock")
        watch = _journal(tmp_path, [], state="measuring")
        env = _bench_env(watch, lock, BENCH_CLAIM_WAIT="2")
        holder_env = {**env, "TPU_CLAIM_WAIT": "0"}
        holder = subprocess.Popen(
            [sys.executable, os.path.join(TOOLS, "tpu_claim.py"), "hold", "90"],
            env=holder_env,
        )
        try:
            time.sleep(0.8)
            result, stderr = _run_bench(env)
            # bench.py must NOT have raced the session for the tunnel: no
            # probe, no device subprocess — straight to the host engine.
            assert result["platform"] == "cpu-host-engine"
            assert "claim" in result.get("note", ""), result
            assert result["value"] > 0
            assert len(result["cpu_rep_evals_per_sec"]) == 2
            assert "backend probe" not in stderr  # probe was skipped
        finally:
            holder.kill()
            holder.wait(timeout=30)

    def test_proceeds_when_holder_releases(self, tmp_path):
        lock = str(tmp_path / "claim.lock")
        watch = _journal(tmp_path, [], state="measuring")
        env = _bench_env(watch, lock, BENCH_CLAIM_WAIT="30")
        holder_env = {**env, "TPU_CLAIM_WAIT": "0"}
        holder = subprocess.Popen(
            [sys.executable, os.path.join(TOOLS, "tpu_claim.py"), "hold", "3"],
            env=holder_env,
        )
        try:
            time.sleep(0.8)
            result, _ = _run_bench(env)
            # The claim freed: bench.py acquired it, probed (dead tunnel in
            # this environment -> short timeout), fell back to the host
            # engine WITHOUT the skipped-attempt note.
            assert result["platform"] == "cpu-host-engine"
            assert "note" not in result
            assert result["value"] > 0
        finally:
            holder.wait(timeout=30)

    def test_up_journal_skips_probe(self, tmp_path):
        # A fresh PROBE OK in the journal: bench.py goes straight to the
        # device attempt (no probe subprocess) at the configured budget.
        lines = [
            f"{_ts(-400)}Z attempt=1 probe down (backend=)",
            f"{_ts(-90)}Z attempt=2 PROBE OK backend=tpu -> tpu_measure.sh",
        ]
        watch = _journal(tmp_path, lines)
        lock = str(tmp_path / "claim.lock")
        result, stderr = _run_bench(_bench_env(watch, lock))
        # No tunnel on this box: the attempt dies at its timeout and the
        # host engine reports — but the probe must not have run at all.
        assert result["platform"] == "cpu-host-engine"
        assert "skipping the probe" in stderr
        assert "backend probe" not in stderr

    def test_dead_journal_clamps_budgets(self, tmp_path):
        lines = [
            f"{_ts(-600 + i * 150)}Z attempt={i + 1} probe down (backend=)"
            for i in range(4)
        ]
        watch = _journal(tmp_path, lines)
        lock = str(tmp_path / "claim.lock")
        # Hermetic results fixture: the fallback must attach the latest
        # dated device-platform headline as clearly-labeled context.
        results = tmp_path / "results.json"
        results.write_text(
            json.dumps(
                [
                    {"bench": "full_domain_headline", "platform": "tpu",
                     "value": 123, "unit": "evals/s", "date": "2026-07-30"},
                    {"bench": "full_domain_headline", "platform": "cpu-host-engine",
                     "value": 9, "date": "2026-08-01"},
                ]
            )
        )
        env = _bench_env(watch, lock, BENCH_RESULTS_PATH=str(results))
        t0 = time.time()
        result, stderr = _run_bench(env)
        elapsed = time.time() - t0
        assert result["platform"] == "cpu-host-engine"
        assert "continuously down" in stderr
        onchip = result.get("last_onchip_headline_record")
        assert onchip == {
            "bench": "full_domain_headline",
            "platform": "tpu",
            "value": 123,
            "unit": "evals/s",
            "date": "2026-07-30",
        }
        # One short probe + one short device attempt + the tiny CPU run:
        # far under the old 600s-probe + 900s-device ordeal. Generous bound
        # for a loaded box; the configured budgets sum to ~11s + startup.
        assert elapsed < 120, elapsed


class TestLatestOnchipHeadline:
    def _lookup(self, tmp_path, monkeypatch, records):
        path = tmp_path / "results.json"
        path.write_text(json.dumps(records))
        monkeypatch.setenv("BENCH_RESULTS_PATH", str(path))
        b = _load_bench(monkeypatch, str(tmp_path))
        return b._latest_onchip_headline()

    def test_picks_latest_device_record(self, tmp_path, monkeypatch):
        got = self._lookup(
            tmp_path,
            monkeypatch,
            [
                {"bench": "full_domain_headline", "platform": "tpu",
                 "value": 1, "date": "2026-07-29"},
                {"bench": "full_domain_headline@tpu", "platform": "tpu",
                 "value": 2, "date": "2026-07-31",
                 "config": {"vs_baseline": 4.5}},
            ],
        )
        assert got["value"] == 2 and got["vs_baseline"] == 4.5

    def test_ignores_cpu_errors_and_ab_variants(self, tmp_path, monkeypatch):
        got = self._lookup(
            tmp_path,
            monkeypatch,
            [
                {"bench": "full_domain_headline", "platform": "cpu-host-engine",
                 "value": 1, "date": "2026-08-01"},
                {"bench": "full_domain_headline", "platform": "tpu",
                 "error": "timeout", "date": "2026-08-01"},
                {"bench": "full_domain_headline_fused_hash", "platform": "tpu",
                 "value": 7, "date": "2026-08-01"},
            ],
        )
        assert got is None

    def test_null_config_survives(self, tmp_path, monkeypatch):
        got = self._lookup(
            tmp_path,
            monkeypatch,
            [
                {"bench": "full_domain_headline", "platform": "tpu",
                 "value": 3, "date": "2026-07-31", "config": None},
            ],
        )
        assert got["value"] == 3 and "vs_baseline" not in got

    def test_missing_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BENCH_RESULTS_PATH", str(tmp_path / "nope.json"))
        b = _load_bench(monkeypatch, str(tmp_path))
        assert b._latest_onchip_headline() is None


class TestRunBenchStage:
    def _stage(self, tmp_path, script_body, suffix=None):
        bench_dir = tmp_path / "benchdir"
        bench_dir.mkdir(exist_ok=True)
        (bench_dir / "fake_bench.py").write_text(script_body)
        env = dict(os.environ)
        env["BENCH_STAGE_DIR"] = str(bench_dir)
        args = [
            sys.executable,
            os.path.join(TOOLS, "run_bench_stage.py"),
            "fake_bench.py",
        ]
        if suffix:
            args.append(f"RECORD_SUFFIX={suffix}")
        proc = subprocess.run(args, env=env, capture_output=True, text=True, timeout=60)
        results_path = bench_dir / "results.json"
        stored = json.loads(results_path.read_text()) if results_path.exists() else []
        return proc.returncode, stored

    def test_device_record_completes_stage(self, tmp_path):
        rc, stored = self._stage(
            tmp_path,
            'import json; print(json.dumps({"bench": "x", "value": 1, "platform": "tpu"}))',
        )
        assert rc == 0
        assert stored and stored[0]["bench"] == "x"
        assert stored[0]["date"]  # dated by the stage runner if absent

    def test_cpu_fallback_does_not_complete_stage(self, tmp_path):
        rc, stored = self._stage(
            tmp_path,
            'import json; print(json.dumps({"bench": "x", "value": 1, "platform": "cpu-host-engine"}))',
        )
        assert rc == 2
        assert stored  # the record is still merged (it is a real CPU record)

    def test_error_record_does_not_complete_stage(self, tmp_path):
        rc, _ = self._stage(
            tmp_path,
            'import json; print(json.dumps({"bench": "x", "error": "boom", "platform": "tpu"}))',
        )
        assert rc == 2

    def test_smoke_record_does_not_complete_stage(self, tmp_path):
        rc, _ = self._stage(
            tmp_path,
            'import json; print(json.dumps({"bench": "x", "value": 1, "platform": "tpu", "smoke": True}))',
        )
        assert rc == 2

    def test_crash_is_rc1(self, tmp_path):
        rc, stored = self._stage(tmp_path, "raise SystemExit(9)")
        assert rc == 1
        assert not stored

    def test_record_suffix_isolates_ab_variants(self, tmp_path):
        rc, stored = self._stage(
            tmp_path,
            'import json; print(json.dumps({"bench": "x", "value": 1, "platform": "tpu"}))',
            suffix="_fused",
        )
        assert rc == 0
        assert stored[0]["bench"] == "x_fused"
