"""DCF correctness: share-sum property (f(x) = beta iff x < alpha),
exhaustive over small domains, plus fused batch kernel vs host path.

Mirrors the reference's exhaustive alpha x evaluation-point suite
(/root/reference/dcf/distributed_comparison_function_test.cc:93-176).
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.core.value_types import Int, IntModN, XorWrapper
from distributed_point_functions_tpu.dcf.dcf import DistributedComparisonFunction

RNG = np.random.default_rng(0xDCF)


@pytest.mark.parametrize("log_domain", [1, 2, 4])
def test_dcf_exhaustive_small_domain(log_domain):
    vt = Int(64)
    dcf = DistributedComparisonFunction.create(log_domain, vt)
    domain = 1 << log_domain
    beta = 123456789
    for alpha in range(domain):
        ka, kb = dcf.generate_keys(alpha, beta)
        for x in range(domain):
            a = dcf.evaluate(ka, x)
            b = dcf.evaluate(kb, x)
            expected = beta if x < alpha else 0
            assert (a + b) % 2**64 == expected, (alpha, x)


def test_dcf_64bit_domain_spot_checks():
    vt = Int(32)
    dcf = DistributedComparisonFunction.create(64, vt)
    alpha = 0x123456789ABCDEF0
    beta = 4242
    ka, kb = dcf.generate_keys(alpha, beta)
    for x in [0, alpha - 1, alpha, alpha + 1, 2**64 - 1, alpha ^ (1 << 40)]:
        a, b = dcf.evaluate(ka, x), dcf.evaluate(kb, x)
        expected = beta if x < alpha else 0
        assert (a + b) % 2**32 == expected, hex(x)


def test_dcf_intmodn():
    mod = (1 << 30) + 7
    vt = IntModN(32, mod)
    dcf = DistributedComparisonFunction.create(6, vt)
    alpha, beta = 40, 999
    ka, kb = dcf.generate_keys(alpha, beta)
    for x in [0, 39, 40, 41, 63]:
        a, b = dcf.evaluate(ka, x), dcf.evaluate(kb, x)
        expected = beta if x < alpha else 0
        assert (a + b) % mod == expected, x


@pytest.mark.parametrize(
    "bits", [64, pytest.param(32, marks=pytest.mark.slow)]
)
def test_batch_evaluate_matches_host(bits):
    from distributed_point_functions_tpu.ops import evaluator

    dcf = DistributedComparisonFunction.create(12, Int(bits))
    alphas = [0, 1, 3000, 4095]
    beta = 777
    keys_a, keys_b = [], []
    for alpha in alphas:
        ka, kb = dcf.generate_keys(alpha, beta)
        keys_a.append(ka)
        keys_b.append(kb)
    xs = [0, 1, 2, 2999, 3000, 3001, 4094, 4095] + [
        int(x) for x in RNG.integers(0, 4096, size=8)
    ]
    got_a = evaluator.values_to_numpy(dcf.batch_evaluate(keys_a, xs), bits)
    got_b = evaluator.values_to_numpy(dcf.batch_evaluate(keys_b, xs), bits)
    mod = 1 << bits
    for ki, alpha in enumerate(alphas):
        # fused kernel matches the reference-parity host loop
        for j in [0, 3, 11]:
            want = dcf.evaluate(keys_a[ki], xs[j])
            assert int(got_a[ki, j]) == want % mod, (ki, j)
        # and the share-sum property holds everywhere
        for j, x in enumerate(xs):
            expected = beta if x < alpha else 0
            assert (int(got_a[ki, j]) + int(got_b[ki, j])) % mod == expected, (
                alpha,
                x,
            )


@pytest.mark.parametrize("bits,xor", [(64, False), (32, True)])
def test_dcf_batch_pallas_driver_matches_xla_driver(monkeypatch, bits, xor):
    """Plumbing smoke for the Mosaic DCF driver (_dcf_batch_pallas_jit):
    both drivers run with IDENTICAL cheap stand-in circuits (the real AES
    is pinned elsewhere; interpret mode cannot execute it on the CI CPU),
    so any per-level capture / correction-indexing / walk-interleave
    divergence shows as an output mismatch. Values are meaningless; only
    driver equality is asserted."""
    import jax
    import jax.numpy as jnp

    from distributed_point_functions_tpu.dcf import batch as dcf_batch
    from distributed_point_functions_tpu.ops import aes_jax, aes_pallas

    vt = XorWrapper(bits) if xor else Int(bits)
    dcf = DistributedComparisonFunction.create(10, vt)
    keys = []
    for alpha in [17, 900]:
        ka, _ = dcf.generate_keys(alpha, 4242)
        keys.append(ka)
    xs = [0, 16, 17, 18, 511, 1023] + [
        int(x) for x in RNG.integers(0, 1024, size=10)
    ]

    def cheap_hash_planes(planes, rk_base, rk_diff=None, key_mask=None):
        sig = aes_jax.sigma_planes(planes)
        enc = jnp.roll(sig, -1, axis=0)
        if rk_diff is not None and key_mask is not None:
            enc = enc ^ key_mask[None, :]
        return enc ^ sig

    def cheap_rows(rows, rk_base, rk_diff, key_mask):
        out = []
        for p in range(128):
            row = rows[(p + 1) % 128]
            if rk_diff is not None and key_mask is not None:
                row = row ^ key_mask
            out.append(row)
        return out

    jax.clear_caches()
    monkeypatch.setattr(aes_jax, "hash_planes", cheap_hash_planes)
    monkeypatch.setattr(aes_pallas, "_aes_rows", cheap_rows)
    try:
        a = dcf_batch.batch_evaluate(dcf, keys, xs, use_pallas=False)
        b = dcf_batch.batch_evaluate(
            dcf, keys, xs, use_pallas=True, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        jax.clear_caches()  # drop cheap-circuit traces


@pytest.mark.slow  # XOR-group device coverage also lives in
# test_batch_evaluate_host_wide_groups[xor128]; this adds the
# dcf.batch_evaluate API shape for XorWrapper
def test_batch_evaluate_xor_group():
    from distributed_point_functions_tpu.ops import evaluator

    dcf = DistributedComparisonFunction.create(6, XorWrapper(128))
    alpha, beta = 40, (1 << 127) | 0xABC
    ka, kb = dcf.generate_keys(alpha, beta)
    xs = list(range(0, 64, 7)) + [39, 40, 41]
    va = evaluator.values_to_numpy(dcf.batch_evaluate([ka], xs), 128)
    vb = evaluator.values_to_numpy(dcf.batch_evaluate([kb], xs), 128)
    for j, x in enumerate(xs):
        expected = beta if x < alpha else 0
        assert int(va[0, j]) ^ int(vb[0, j]) == expected, x


def test_dcf_rejects_bad_inputs():
    from distributed_point_functions_tpu.utils.errors import InvalidArgumentError

    with pytest.raises(InvalidArgumentError):
        DistributedComparisonFunction.create(0, Int(32))
    dcf = DistributedComparisonFunction.create(4, Int(32))
    with pytest.raises(InvalidArgumentError):
        dcf.generate_keys(16, 1)
    ka, _ = dcf.generate_keys(3, 1)
    with pytest.raises(InvalidArgumentError):
        dcf.evaluate(ka, 16)


def test_batched_dcf_keygen_matches_sequential():
    """generate_keys_batch is bit-exact with sequential generate_keys given
    the same seeds."""
    dcf = DistributedComparisonFunction.create(6, Int(32))
    rng = np.random.default_rng(5)
    alphas = [int(a) for a in rng.integers(0, 64, size=4)]
    betas = [int(b) for b in rng.integers(1, 100, size=4)]
    seeds = rng.integers(0, 2**32, size=(4, 2, 4), dtype=np.uint32)
    ka_b, kb_b = dcf.generate_keys_batch(alphas, betas, seeds=seeds)
    for i in range(4):
        s = (
            int.from_bytes(seeds[i, 0].tobytes(), "little"),
            int.from_bytes(seeds[i, 1].tobytes(), "little"),
        )
        ka, kb = dcf.generate_keys(alphas[i], betas[i], seeds=s)
        assert ka == ka_b[i] and kb == kb_b[i]
    from distributed_point_functions_tpu.utils.errors import InvalidArgumentError

    with pytest.raises(InvalidArgumentError, match="single value or one per alpha"):
        dcf.generate_keys_batch([1, 2], [3, 4, 5])
    # a tuple beta that is itself a valid value broadcasts
    from distributed_point_functions_tpu.core.value_types import TupleType

    dcf_t = DistributedComparisonFunction.create(4, TupleType(Int(32), Int(32)))
    ka, kb = dcf_t.generate_keys_batch([5, 6], (7, 9))
    assert len(ka) == 2


@pytest.mark.slow
def test_batch_evaluate_host_matches_device():
    import numpy as np
    import pytest

    from distributed_point_functions_tpu import native
    from distributed_point_functions_tpu.dcf import batch as dcf_batch
    from distributed_point_functions_tpu.dcf.dcf import (
        DistributedComparisonFunction,
    )
    from distributed_point_functions_tpu.core.value_types import Int

    if not native.available():
        pytest.skip("native engine unavailable")
    rng = np.random.default_rng(0x0DCF)
    for vt in (Int(16), Int(64)):
        dcf = DistributedComparisonFunction.create(9, vt)
        alphas = [7, 300, 511]
        keys_a, keys_b = [], []
        for a in alphas:
            ka, kb = dcf.generate_keys(a, 5)
            keys_a.append(ka)
            keys_b.append(kb)
        xs = [int(x) for x in rng.integers(0, 512, size=25)] + [0, 511]
        # One batched call per party (not per key): same coverage, and the
        # device program compiles/dispatches once per shape.
        for keys in (keys_a, keys_b):
            host = dcf_batch.batch_evaluate_host(dcf, keys, xs)
            dev = np.asarray(dcf_batch.batch_evaluate(dcf, keys, xs))
            dev64 = dev[..., 0].astype(np.uint64)
            if dev.shape[-1] > 1:
                dev64 |= dev[..., 1].astype(np.uint64) << np.uint64(32)
            mask = np.uint64((1 << vt.bitsize) - 1)
            np.testing.assert_array_equal(host & mask, dev64 & mask)


@pytest.mark.parametrize(
    "case",
    ["xor128"]
    + [
        pytest.param(c, marks=pytest.mark.slow)
        for c in ("int128", "xor16", "xor64")
    ],
)
def test_batch_evaluate_host_wide_groups(case):
    """The wide native kernel (XOR groups, 128-bit values) vs the device
    path and the share-sum property. Fast cases cover the two distinct
    kernel paths (XOR group, additive 128-bit); narrower XOR widths are
    slow-marked."""
    import numpy as np
    import pytest

    from distributed_point_functions_tpu import native
    from distributed_point_functions_tpu.dcf import batch as dcf_batch
    from distributed_point_functions_tpu.dcf.dcf import (
        DistributedComparisonFunction,
    )
    from distributed_point_functions_tpu.core.value_types import Int, XorWrapper

    if not native.available():
        pytest.skip("native engine unavailable")

    def to_int(limbs_or_wide):
        a = np.asarray(limbs_or_wide)
        if a.dtype == np.uint64 and a.ndim == 1:  # packed u64 values
            return a.astype(object)
        if a.dtype == np.uint64:  # wide (lo, hi) pairs
            return a[..., 0].astype(object) | (
                a[..., 1].astype(object) << 64
            )
        out = np.zeros(a.shape[:-1], dtype=object)
        for l in range(a.shape[-1]):
            out |= a[..., l].astype(object) << (32 * l)
        return out

    rng = np.random.default_rng(0x1DCF)
    cases = {
        "xor16": (XorWrapper(16), 0xABCD),
        "xor64": (XorWrapper(64), (1 << 64) - 3),
        "xor128": (XorWrapper(128), (1 << 128) - 1),
        "int128": (Int(128), (1 << 100) + 17),
    }
    for vt, beta in [cases[case]]:
        dcf = DistributedComparisonFunction.create(8, vt)
        alpha = 113
        ka, kb = dcf.generate_keys(alpha, beta)
        xs = [int(x) for x in rng.integers(0, 256, size=17)] + [0, alpha, 255]
        got_a = to_int(dcf_batch.batch_evaluate_host(dcf, [ka], xs)[0])
        got_b = to_int(dcf_batch.batch_evaluate_host(dcf, [kb], xs)[0])
        dev_a = to_int(dcf_batch.batch_evaluate(dcf, [ka], xs)[0])
        np.testing.assert_array_equal(got_a, dev_a)
        bits = vt.bitsize
        for j, x in enumerate(xs):
            if isinstance(vt, XorWrapper):
                total = int(got_a[j]) ^ int(got_b[j])
            else:
                total = (int(got_a[j]) + int(got_b[j])) % (1 << bits)
            want = beta if x < alpha else 0
            assert total == want, (vt, x)
