"""Dispatch audit: warm calls of the main device entry points must execute
a PINNED number of device programs — every program is a separate dispatch
(~66 ms latency through this image's TPU tunnel), so an unnoticed eager
op or an extra per-level launch is a real regression even when CPU timing
can't see it.

Round-5 rework (ADVICE r4, medium): the old audit hooked
`jax._src.dispatch.apply_primitive`, which in jax 0.9.0 only sees
slice/gather-style eager ops — eager adds, concatenates, un-jitted vmaps
and jnp's internally-jitted ops all take the C++ pjit fastpath and were
invisible. This version counts at the EXECUTION level: the fixture
disables the C++ fastpath (`_get_fastpath_data -> None`) so every program
execution — jitted or eager, warm or cold — flows through
`pxla.ExecuteReplicated.__call__`, where it is counted. A positive
control (a warm eager add must count exactly 1) makes the fixture skip
loudly if a jax upgrade reroutes execution instead of passing vacuously.

The stronger counter immediately earned its keep: it found the
per-prefix block selection in `evaluate_until_batch` running as ~7 eager
programs per advance (bounds ops + gather + broadcasts of a fancy-index
on device arrays) that the old audit certified as zero — now jitted
(`_select_block_outputs_jit`) and pinned here at 1.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
from distributed_point_functions_tpu.core.params import DpfParameters
from distributed_point_functions_tpu.core.value_types import Int
from distributed_point_functions_tpu.dcf import batch as dcf_batch
from distributed_point_functions_tpu.dcf.dcf import DistributedComparisonFunction
from distributed_point_functions_tpu.ops import evaluator, hierarchical
from distributed_point_functions_tpu.parallel import sharded


@pytest.fixture
def program_counter(monkeypatch):
    import jax
    import jax.numpy as jnp

    try:
        from jax._src import pjit as pjit_mod
        from jax._src.interpreters import pxla

        orig_call = pxla.ExecuteReplicated.__call__
    except (ImportError, AttributeError):
        pytest.skip("jax internals moved; program-execution hook unavailable")
    if getattr(pjit_mod, "_get_fastpath_data", None) is None:
        pytest.skip("jax internals moved; program-execution hook unavailable")

    monkeypatch.setattr(pjit_mod, "_get_fastpath_data", lambda *a, **k: None)
    counts = {"programs": 0}

    def spy(self, *args):
        counts["programs"] += 1
        return orig_call(self, *args)

    monkeypatch.setattr(pxla.ExecuteReplicated, "__call__", spy)
    # Entries cached by the C++ fastpath BEFORE the patch would bypass the
    # spy; flush them so every execution goes through the Python path.
    jax.clear_caches()

    # Positive control (ADVICE r4): a warm eager op must be counted, else
    # the hook is ineffective on this jax version and the audit would pass
    # vacuously — skip loudly instead.
    x = jnp.arange(64, dtype=jnp.uint32).reshape(8, 8)
    jax.block_until_ready(x + x)
    counts["programs"] = 0
    jax.block_until_ready(x + x)
    if counts["programs"] != 1:
        pytest.skip(
            f"program hook counted {counts['programs']} for a warm eager "
            "add (expected 1); jax execution path changed — fix the fixture"
        )
    counts["programs"] = 0
    yield counts
    # Executables compiled while the fastpath was disabled stay cached
    # without fastpath data; drop them so later tests re-cache normally.
    jax.clear_caches()


def _assert_programs(counts, fn, name, budget):
    fn()  # warm: compiles + constant uploads are allowed
    counts["programs"] = 0
    fn()
    got = counts["programs"]
    assert 1 <= got <= budget, (
        f"{name}: {got} device programs per warm call (pinned budget "
        f"{budget}). Each program is its own ~66 ms dispatch through the "
        "tunnel. A count over budget means an eager op or an extra launch "
        "crept in — move it inside a jitted program (PERF.md dispatch "
        "audit); 0 means the counting hook broke."
    )


def test_full_domain_chunks_program_budget(program_counter):
    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9], [[1, 2]])

    # levels mode: pack + split + one program per level group + finalize.
    _assert_programs(
        program_counter,
        lambda: list(evaluator.full_domain_evaluate_chunks(dpf, keys, mode="levels")),
        "full_domain_evaluate_chunks[levels]",
        budget=7,
    )
    # fused / fold: ONE program per chunk (the headline shape).
    _assert_programs(
        program_counter,
        lambda: list(evaluator.full_domain_evaluate_chunks(dpf, keys, mode="fused")),
        "full_domain_evaluate_chunks[fused]",
        budget=1,
    )
    _assert_programs(
        program_counter,
        lambda: list(evaluator.full_domain_fold_chunks(dpf, keys)),
        "full_domain_fold_chunks",
        budget=1,
    )


@pytest.mark.slow
def test_evaluate_at_and_dcf_program_budget(program_counter):
    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9], [[1, 2]])
    pts = [int(x) for x in np.random.default_rng(1).integers(0, 1 << 10, 64)]
    _assert_programs(
        program_counter,
        lambda: evaluator.evaluate_at_batch(dpf, keys, pts),
        "evaluate_at_batch",
        budget=1,
    )

    dc = DistributedComparisonFunction.create(8, Int(64))
    dk, _ = dc.generate_keys_batch([100, 200], [7, 9])
    xs = [int(x) for x in np.random.default_rng(2).integers(0, 1 << 8, 48)]
    _assert_programs(
        program_counter,
        lambda: dcf_batch.batch_evaluate(dc, dk, xs, use_pallas=False),
        "dcf.batch_evaluate",
        budget=1,
    )


def test_pipelined_chunked_paths_program_budget(program_counter):
    """ISSUE 2: the pipelined executor (ops/pipeline.py) must never change
    the device program count — overlap reorders dispatches in time, it
    must not ADD any (an executor-introduced eager op would multiply by
    the chunk count). Budgets are pinned per warm call with pipeline OFF
    and ON on a 2-chunk run of every fast-tier rewired entry point; the
    slow tier pins DCF and chunked PIR."""
    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9, 100, 731], [[1, 2, 3, 4]])
    pts = [int(x) for x in np.random.default_rng(1).integers(0, 1 << 10, 64)]

    for pipe in (False, True):
        tag = f"[pipeline={'on' if pipe else 'off'}]"
        # levels: (pack + split + 4 expand + finalize) = 7 per chunk.
        _assert_programs(
            program_counter,
            lambda: list(
                evaluator.full_domain_evaluate_chunks(
                    dpf, keys, key_chunk=2, mode="levels", pipeline=pipe
                )
            ),
            f"full_domain_evaluate_chunks[levels,2chunks]{tag}",
            budget=14,
        )
        # fused / fold: ONE program per chunk, pipelined or not.
        _assert_programs(
            program_counter,
            lambda: list(
                evaluator.full_domain_evaluate_chunks(
                    dpf, keys, key_chunk=2, mode="fused", pipeline=pipe
                )
            ),
            f"full_domain_evaluate_chunks[fused,2chunks]{tag}",
            budget=2,
        )
        _assert_programs(
            program_counter,
            lambda: list(
                evaluator.full_domain_fold_chunks(
                    dpf, keys, key_chunk=2, pipeline=pipe
                )
            ),
            f"full_domain_fold_chunks[2chunks]{tag}",
            budget=2,
        )
        # evaluate_at: one walk program per key chunk; the worker-thread
        # pulls are transfers, never programs.
        _assert_programs(
            program_counter,
            lambda: evaluator.evaluate_at_batch(
                dpf, keys, pts, key_chunk=2, pipeline=pipe
            ),
            f"evaluate_at_batch[2chunks]{tag}",
            budget=2,
        )


def test_telemetry_enabled_program_budget(program_counter):
    """ISSUE 6: the telemetry bus must add ZERO device programs — every
    measurement is host-side perf_counter arithmetic / .nbytes metadata,
    never a jnp op. Same shapes and budgets as the pipelined-path audit
    above (compile reuse), but with a capture collector active, the
    integrity event stream re-homed through the bus, and spans enabled on
    every chunk."""
    from distributed_point_functions_tpu.utils import telemetry

    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9, 100, 731], [[1, 2, 3, 4]])

    for pipe in (False, True):
        tag = f"[telemetry,pipeline={'on' if pipe else 'off'}]"

        def run_fold():
            with telemetry.capture() as tel:
                list(
                    evaluator.full_domain_fold_chunks(
                        dpf, keys, key_chunk=2, pipeline=pipe
                    )
                )
            assert tel.snapshot()["dispatch_count"] == 2

        def run_levels():
            with telemetry.capture():
                list(
                    evaluator.full_domain_evaluate_chunks(
                        dpf, keys, key_chunk=2, mode="levels", pipeline=pipe
                    )
                )

        # Identical budgets to the telemetry-off audit above: the bus
        # observed both chunks without dispatching anything of its own.
        _assert_programs(
            program_counter, run_fold,
            f"full_domain_fold_chunks{tag}", budget=2,
        )
        _assert_programs(
            program_counter, run_levels,
            f"full_domain_evaluate_chunks[levels]{tag}", budget=14,
        )


def test_megakernel_program_budget(program_counter, monkeypatch):
    """ISSUE 3: mode='megakernel' is EXACTLY one device program per chunk
    — pack + the slab pallas_call + the fold-width reduction are one jit —
    with the pipelined executor on AND off (overlap must never add
    programs). The cheap `_aes_rows` stand-in keeps the kernel's XLA-CPU
    compile tractable (the real row circuit is hardware-only, PERF.md);
    the program COUNT is circuit-independent."""
    import jax

    from distributed_point_functions_tpu.ops import aes_pallas
    from test_aes_pallas import _CheapRows

    jax.clear_caches()
    monkeypatch.setattr(aes_pallas, "_aes_rows", _CheapRows())
    dpf = DistributedPointFunction.create(DpfParameters(8, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9, 100, 201], [[1, 2, 3, 4]])

    def run(pipe):
        return list(
            evaluator.full_domain_fold_chunks(
                dpf, keys, key_chunk=2, mode="megakernel", pipeline=pipe
            )
        )

    try:
        for pipe in (False, True):
            run(pipe)  # warm: compiles allowed
            program_counter["programs"] = 0
            run(pipe)
            got = program_counter["programs"]
            assert got == 2, (
                f"mode='megakernel'[pipeline={pipe}]: {got} device programs "
                "for 2 chunks (pinned at EXACTLY 1 per chunk — the whole "
                "point of the megakernel is one fused program per chunk)"
            )
    finally:
        jax.clear_caches()  # drop cheap-circuit traces


def test_sharded_megakernel_program_budget(program_counter, monkeypatch):
    """ISSUE 17: the mesh-sharded megakernel PIR path is EXACTLY one
    device program per key chunk — pack + per-shard slab fold + the XOR
    all-gather are ONE jitted shard_map program, and every per-chunk host
    input lands shard-direct via device_put onto its NamedSharding (a
    transfer, never a program) — with the pipelined executor on AND off.
    Cheap `_aes_rows` stand-in (the count is circuit-independent); the
    2x4 mesh rides the forced 8-device CPU platform."""
    import jax

    from distributed_point_functions_tpu.core.value_types import XorWrapper
    from distributed_point_functions_tpu.ops import aes_pallas
    from test_aes_pallas import _CheapRows

    jax.clear_caches()
    sharded.build_sharded_megakernel_step.cache_clear()
    monkeypatch.setattr(aes_pallas, "_aes_rows", _CheapRows())
    lds, hl = 9, 8
    dpf = DistributedPointFunction.create(DpfParameters(lds, XorWrapper(128)))
    db = np.random.default_rng(7).integers(
        0, 2**32, size=(1 << lds, 4), dtype=np.uint64
    ).astype(np.uint32)
    keys = [
        dpf.generate_keys(a, (1 << 128) - 1)[0] for a in (3, 77, 500, 129)
    ]
    mesh = sharded.make_mesh(2, 4)
    pdb = sharded.prepare_pir_database(
        dpf, db, host_levels=hl, order="megakernel", mesh=mesh
    )

    def run(pipe):
        return sharded.pir_query_batch_chunked(
            dpf, keys, pdb, key_chunk=2, host_levels=hl, mode="megakernel",
            mesh=mesh, integrity=False, pipeline=pipe,
        )

    try:
        for pipe in (False, True):
            run(pipe)  # warm: compiles + constant uploads are allowed
            program_counter["programs"] = 0
            run(pipe)
            got = program_counter["programs"]
            assert got == 2, (
                f"sharded megakernel[pipeline={pipe}]: {got} device "
                "programs for 2 chunks (pinned at EXACTLY 1 shard_map "
                "program per key chunk — an eager reshard of a sharded "
                "input lowers to ~7 programs each, the round-5 audit "
                "lesson)"
            )
    finally:
        jax.clear_caches()  # drop cheap-circuit traces
        sharded.build_sharded_megakernel_step.cache_clear()


@pytest.mark.slow
def test_walkkernel_program_budget(program_counter, monkeypatch):
    """ISSUE 4: mode='walkkernel' is EXACTLY one device program per chunk
    on all three point-walk entry points — evaluate_at_batch, DCF
    batch_evaluate, and MIC batch_eval (which rides the DCF path) — with
    the pipelined executor on AND off. The whole point of the walk
    megakernel is collapsing the per-level dispatch train (one program
    per tree level, 20-128 levels) into one program per chunk; the cheap
    `_aes_rows` stand-in keeps the interpret compile tractable — the
    program COUNT is circuit-independent."""
    import jax

    from distributed_point_functions_tpu.gates.mic import (
        MultipleIntervalContainmentGate,
    )
    from distributed_point_functions_tpu.ops import aes_pallas
    from test_aes_pallas import _CheapRows

    jax.clear_caches()
    monkeypatch.setattr(aes_pallas, "_aes_rows", _CheapRows())
    try:
        dpf = DistributedPointFunction.create(DpfParameters(6, Int(64)))
        keys, _ = dpf.generate_keys_batch([5, 9, 30, 51], [[1, 2, 3, 4]])
        pts = [int(x) for x in np.random.default_rng(1).integers(0, 1 << 6, 48)]
        dc = DistributedComparisonFunction.create(4, Int(64))
        dk, _ = dc.generate_keys_batch([7, 9, 3, 1], [4, 5, 6, 7])
        xs = [int(x) for x in np.random.default_rng(2).integers(0, 1 << 4, 24)]
        gate = MultipleIntervalContainmentGate.create(3, [(1, 5)])
        mk, _ = gate.gen(2, [3])

        for pipe in (False, True):
            tag = f"[pipeline={'on' if pipe else 'off'}]"
            for name, fn, want in (
                (
                    f"evaluate_at_batch[walkkernel,2chunks]{tag}",
                    lambda: evaluator.evaluate_at_batch(
                        dpf, keys, pts, key_chunk=2, pipeline=pipe,
                        mode="walkkernel",
                    ),
                    2,
                ),
                (
                    f"dcf.batch_evaluate[walkkernel,2chunks]{tag}",
                    lambda: dcf_batch.batch_evaluate(
                        dc, dk, xs, key_chunk=2, pipeline=pipe,
                        mode="walkkernel",
                    ),
                    2,
                ),
                (
                    f"mic.batch_eval[walkkernel]{tag}",
                    lambda: gate.batch_eval(
                        mk, [0, 4, 7], mode="walkkernel", pipeline=pipe
                    ),
                    1,
                ),
            ):
                fn()  # warm: compiles + constant uploads are allowed
                program_counter["programs"] = 0
                fn()
                got = program_counter["programs"]
                assert got == want, (
                    f"{name}: {got} device programs (pinned at EXACTLY "
                    f"{want} — one per chunk; the walk megakernel exists to "
                    "collapse the per-level dispatch train)"
                )
    finally:
        jax.clear_caches()  # drop cheap-circuit traces


def test_gate_family_program_budget(program_counter):
    """ISSUE 9 acceptance pin: every framework gate's batch_eval flattens
    to the SAME single fused batched-DCF pass MIC uses — EXACTLY one
    device program per key chunk in walk mode (here: one chunk = one
    program per call, multi-component keys included), the vector-payload
    codec keeps that pin (ONE tuple-payload key -> ONE program, no
    per-coefficient dispatches), and serving a vector gate through the
    front door launches exactly the programs the direct robust call
    launches (routing, GatePlan combine, and slicing are all
    host-side)."""
    from distributed_point_functions_tpu import gates, serving
    from distributed_point_functions_tpu.ops import supervisor

    relu = gates.ReluGate.create(6, payload="vector")
    rk, _ = relu.gen(11, [3])
    relu_s = gates.ReluGate.create(6, payload="scalar")
    rk_s, _ = relu_s.gen(11, [3])
    bits = gates.BitDecompositionGate.create(6)
    bk, _ = bits.gen(45, [0] * 6)
    xs = [0, 9, 32, 63]

    for name, gate, key, want in (
        ("relu.batch_eval[vector: 1 tuple-payload key]", relu, rk, 1),
        ("relu.batch_eval[scalar: 4 components]", relu_s, rk_s, 1),
        ("bitdecomp.batch_eval[6 components]", bits, bk, 1),
    ):
        fn = lambda: gate.batch_eval(key, xs, mode="walk")  # noqa: B023
        fn()  # warm: compiles allowed
        program_counter["programs"] = 0
        fn()
        got = program_counter["programs"]
        assert got == want, (
            f"{name}: {got} device programs (pinned at EXACTLY {want} — "
            "the framework exists so every gate is ONE fused DCF pass)"
        )

    def direct():
        supervisor.gate_batch_eval_robust(relu, rk, xs, pipeline=False)

    direct()  # warm: compiles + spot-check oracle caches
    program_counter["programs"] = 0
    direct()
    direct_count = program_counter["programs"]
    assert direct_count >= 1

    def door_pass():
        door = serving.FrontDoor(
            engine="device", max_wait_ms=1e6, width_target=4,
            pipeline=False,
        )
        with door:
            futs = [
                door.submit(serving.Request.gate(relu, rk, [x])) for x in xs
            ]
            door.batcher.pump(force=True)
            for f in futs:
                f.result(120)

    door_pass()  # warm
    program_counter["programs"] = 0
    door_pass()
    assert program_counter["programs"] == direct_count, (
        f"front door launched {program_counter['programs']} device "
        f"programs vs {direct_count} for the direct robust gate call — "
        "serving must add zero dispatches"
    )


@pytest.mark.slow
def test_hierkernel_program_budget(program_counter, monkeypatch):
    """ISSUE 5: mode='hierkernel' is EXACTLY ceil(levels / W) device
    programs per key chunk for a 128-level heavy-hitters advance — one
    program per prefix window (the entry gather, the hier megakernel
    pallas_call and every per-level output selection are one jit) — with
    the pipelined executor on AND off. W = group = 8 here, so the whole
    128-level hierarchy is 16 window programs per chunk where the
    grouped fused path runs ~16 and the per-level path ~1000+; the cheap
    `_aes_rows` stand-in keeps the interpret compile tractable (2 window
    shapes: the depth-0-capture first window + the shape-uniform rest) —
    the program COUNT is circuit-independent."""
    import jax

    from distributed_point_functions_tpu.ops import aes_pallas
    from test_aes_pallas import _CheapRows
    from test_hierkernel import _bitwise_plan

    jax.clear_caches()
    monkeypatch.setattr(aes_pallas, "_aes_rows", _CheapRows())
    try:
        levels, group = 128, 8
        params = [DpfParameters(i + 1, Int(64)) for i in range(levels)]
        dpf = DistributedPointFunction.create_incremental(params)
        keys = [
            dpf.generate_keys_incremental(a, [23] * levels)[0]
            for a in (1, 3 << 120, 5, 1 << 127)
        ]
        plan = _bitwise_plan(levels, 2, np.random.default_rng(2))
        proto = hierarchical.BatchedContext.create(dpf, keys)
        prepared = hierarchical.prepare_levels_fused(
            proto, plan, group=group, mode="hierkernel"
        )
        n_windows = len(prepared.hier_windows)
        assert n_windows == -(-levels // group)  # ceil(levels / W)

        def run(pipe):
            bc = hierarchical.BatchedContext.create(dpf, keys)
            hierarchical.evaluate_levels_fused(
                bc, prepared, key_chunk=2, pipeline=pipe
            )

        for pipe in (False, True):
            run(pipe)  # warm: compiles + constant uploads are allowed
            program_counter["programs"] = 0
            run(pipe)
            got = program_counter["programs"]
            want = 2 * n_windows  # 4 keys in 2 chunks
            assert got == want, (
                f"mode='hierkernel'[pipeline={pipe}]: {got} device programs "
                f"for 2 chunks of a {levels}-level advance (pinned at "
                f"EXACTLY ceil(levels/W) = {n_windows} per chunk — the "
                "whole point of the hier megakernel is one program per "
                "prefix window)"
            )
    finally:
        jax.clear_caches()  # drop cheap-circuit traces


@pytest.mark.slow
def test_pipelined_dcf_and_pir_program_budget(program_counter):
    """Slow-tier half of the ISSUE 2 pipelined budgets: DCF batch walk and
    single-device chunked PIR (fold mode), pipeline OFF and ON."""
    from distributed_point_functions_tpu.core.value_types import XorWrapper

    dc = DistributedComparisonFunction.create(8, Int(64))
    dk, _ = dc.generate_keys_batch([100, 200, 55, 9], [7, 9, 3, 1])
    xs = [int(x) for x in np.random.default_rng(2).integers(0, 1 << 8, 48)]

    rng = np.random.default_rng(7)
    lds = 10
    dpfx = DistributedPointFunction.create(DpfParameters(lds, XorWrapper(128)))
    db = rng.integers(0, 2**32, size=(1 << lds, 4), dtype=np.uint32)
    pir_keys = [dpfx.generate_keys(a, (1 << 128) - 1)[0] for a in (3, 77, 500)]
    pdb = sharded.prepare_pir_database(dpfx, db, order="lane")

    for pipe in (False, True):
        tag = f"[pipeline={'on' if pipe else 'off'}]"
        _assert_programs(
            program_counter,
            lambda: dcf_batch.batch_evaluate(
                dc, dk, xs, use_pallas=False, key_chunk=2, pipeline=pipe
            ),
            f"dcf.batch_evaluate[2chunks]{tag}",
            budget=2,
        )
        # fold mode: one in-program inner product per chunk (2 chunks of
        # 2 for 3 keys, last padded).
        _assert_programs(
            program_counter,
            lambda: sharded.pir_query_batch_chunked(
                dpfx, pir_keys, pdb, key_chunk=2, mode="fold", pipeline=pipe
            ),
            f"pir_query_batch_chunked[fold,2chunks]{tag}",
            budget=2,
        )


def test_hierarchical_paths_program_budget(program_counter):
    params = [DpfParameters(d, Int(32)) for d in (3, 6, 9)]
    dpf = DistributedPointFunction.create_incremental(params)
    key, _ = dpf.generate_keys_incremental(77, [5, 6, 7])

    # 3-advance walk over (3, 6, 9): first advance is 5 programs (pack +
    # split + expand + finalize + reorder); each later advance is gather +
    # pack + split + 3 per-level expands + finalize + reorder + the jitted
    # block selection = 9. Total 23. The round-4 version of this walk ran
    # 36 — the eager fancy-index tail + an eager entry-state cast the old
    # audit couldn't see.
    def walk():
        bc = hierarchical.BatchedContext.create(dpf, [key])
        hierarchical.evaluate_until_batch(bc, 0, device_output=True)
        hierarchical.evaluate_until_batch(bc, 1, list(range(8)), device_output=True)
        hierarchical.evaluate_until_batch(bc, 2, list(range(16)), device_output=True)

    _assert_programs(program_counter, walk, "evaluate_until_batch", budget=23)

    levels = 6
    paramsf = [DpfParameters(i + 1, Int(64)) for i in range(levels)]
    dpff = DistributedPointFunction.create_incremental(paramsf)
    kf, _ = dpff.generate_keys_incremental(11, [7] * levels)
    finals = sorted({int(x) for x in np.random.default_rng(5).integers(0, 64, 20)})
    pres = [
        sorted({f >> (levels - (i + 1)) for f in finals})
        for i in range(levels)
    ]
    plan = [(0, [])] + [(i, pres[i - 1]) for i in range(1, levels)]
    prepared = hierarchical.prepare_levels_fused(
        hierarchical.BatchedContext.create(dpff, [kf]), plan, 4
    )

    # Grouped fused advance at group=4 over 6 plan entries: two unrolled
    # advance programs + one scan chunk = 3 programs TOTAL for the whole
    # hierarchy (vs ~9/advance on the per-level path) — the heavy-hitters
    # latency shape.
    def fused():
        bc = hierarchical.BatchedContext.create(dpff, [kf])
        hierarchical.evaluate_levels_fused(
            bc, prepared, device_output=True, use_pallas=False
        )

    _assert_programs(
        program_counter, fused, "evaluate_levels_fused[prepared]", budget=3
    )


@pytest.mark.slow
def test_sharded_walk_program_budget(program_counter):
    # Mesh-sharded 3-advance walk on the virtual 2x4 mesh: entry pad
    # (out-sharded to the step layout) + shard_map step + fused trim per
    # advance, plus gather + block-selection on the later advances = 13,
    # with ZERO eager reshards. The round-5 audit found 87 before the
    # entry/trim/reshard fusions — eager slices of SHARDED arrays lower to
    # ~7 programs each, so this path regresses catastrophically if the
    # trims or pads leave the jitted programs.
    mesh = sharded.make_mesh(2, 4)
    params = [DpfParameters(d, Int(64)) for d in (4, 8, 12)]
    dpf = DistributedPointFunction.create_incremental(params)
    key, _ = dpf.generate_keys_incremental(0xABC, [5, 6, 7])

    def walk():
        bc = hierarchical.BatchedContext.create(dpf, [key])
        hierarchical.evaluate_until_batch(bc, 0, mesh=mesh, device_output=True)
        hierarchical.evaluate_until_batch(
            bc, 1, list(range(16)), mesh=mesh, device_output=True
        )
        hierarchical.evaluate_until_batch(
            bc, 2, list(range(64)), mesh=mesh, device_output=True
        )

    _assert_programs(
        program_counter, walk, "evaluate_until_batch[mesh 2x4]", budget=13
    )


@pytest.mark.slow
def test_sharded_pir_program_budget(program_counter):
    # One query batch = ONE device program: host inputs are device_put
    # straight onto their shards (transfers, not programs). Before the
    # round-5 fix the shard_map call resharded all six inputs eagerly
    # (7 programs per batch).
    from distributed_point_functions_tpu.core.value_types import XorWrapper

    rng = np.random.default_rng(7)
    lds = 10
    dpf = DistributedPointFunction.create(DpfParameters(lds, XorWrapper(128)))
    db = rng.integers(0, 2**32, size=(1 << lds, 4), dtype=np.uint32)
    keys = []
    for a in (3, 77, 500):
        k0, _ = dpf.generate_keys(a, (1 << 128) - 1)
        keys.append(k0)
    mesh = sharded.make_mesh(2, 4)

    _assert_programs(
        program_counter,
        lambda: np.asarray(sharded.pir_query_batch(dpf, keys, db, mesh)),
        "pir_query_batch[mesh 2x4]",
        budget=1,
    )


def test_serving_frontdoor_adds_zero_programs(program_counter):
    """ISSUE 8 acceptance pin: serving N single-key requests through the
    front door launches EXACTLY the device programs a direct call of the
    chosen engine launches for the merged batch — routing, batching,
    telemetry capture and per-request slicing are all host-side. Counted
    against the identical supervisor wrapper call (same keys, chunking,
    verification policy)."""
    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.ops import supervisor

    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9, 44, 77], [[1, 2, 3, 4]])

    def direct():
        supervisor.full_domain_evaluate_robust(
            dpf, list(keys), key_chunk=2, pipeline=False
        )

    direct()  # warm: compiles + probe caches
    program_counter["programs"] = 0
    direct()
    direct_count = program_counter["programs"]
    assert direct_count >= 1

    def door_pass():
        door = serving.FrontDoor(
            engine="device", max_wait_ms=1e6, width_target=4, key_chunk=2,
            pipeline=False,
        )
        door.serve(
            [serving.Request.full_domain(dpf, [k]) for k in keys],
            timeout=120,
        )

    door_pass()  # warm
    program_counter["programs"] = 0
    door_pass()
    assert program_counter["programs"] == direct_count, (
        f"front door launched {program_counter['programs']} device "
        f"programs vs {direct_count} for the direct merged call — "
        "routing must add zero dispatches"
    )


def test_keygen_batch_program_budget(program_counter, monkeypatch):
    """ISSUE 13 pin: jax-mode batched keygen launches EXACTLY
    tree_levels_needed device programs per warm batch — one fused
    expansion per level step plus the final value hash — independent of
    the key count, with the pipeline env on AND off (keygen's level loop
    has no chunk executor; the pin proves none sneaks in)."""
    from distributed_point_functions_tpu.ops import keygen_batch

    rng = np.random.default_rng(5)
    dpf = DistributedPointFunction.create(DpfParameters(8, Int(64)))
    expected = dpf.validator.tree_levels_needed
    alphas = [3, 70, 201]
    betas = [[5, 9, 40]]
    seeds = rng.integers(0, 2**32, size=(3, 2, 4), dtype=np.uint32)

    for pipeline_env in ("0", "1"):
        monkeypatch.setenv("DPF_TPU_PIPELINE", pipeline_env)
        run = lambda: keygen_batch.generate_keys_batch(
            dpf, alphas, betas, mode="jax", seeds=seeds
        )
        run()  # warm: compiles allowed
        program_counter["programs"] = 0
        run()
        got = program_counter["programs"]
        assert got == expected, (
            f"jax-mode keygen ran {got} device programs for a "
            f"{expected}-tree-level batch with DPF_TPU_PIPELINE="
            f"{pipeline_env} (pinned: one per level step + the final "
            "value hash)"
        )


@pytest.mark.slow  # ~40 s interpret-mode XLA-CPU compile per pipeline arm
def test_keygen_megakernel_program_budget(program_counter, monkeypatch):
    """ISSUE 19 pin: megakernel-mode batched keygen launches EXACTLY ONE
    device program per warm batch — the whole level loop + CW algebra +
    value hashes are one pallas_call inside one jit; pack/unpack stay
    host-side — independent of depth and key count, with the pipeline
    env on AND off. Cheap `_aes_rows` stand-in keeps the interpreted
    kernel's XLA-CPU compile tractable; the program COUNT is
    circuit-independent."""
    import jax

    from distributed_point_functions_tpu.ops import aes_pallas, keygen_batch
    from test_aes_pallas import _CheapRows

    jax.clear_caches()
    keygen_batch._keygen_megakernel_jit.cache_clear()
    monkeypatch.setattr(aes_pallas, "_aes_rows", _CheapRows())
    try:
        rng = np.random.default_rng(6)
        # Shallow tree on purpose: the interpreted kernel's XLA-CPU
        # compile scales with the unrolled level loop, and the program
        # COUNT is depth-independent.
        dpf = DistributedPointFunction.create(DpfParameters(5, Int(64)))
        alphas = [3, 17, 29]
        betas = [[5, 9, 40]]
        seeds = rng.integers(0, 2**32, size=(3, 2, 4), dtype=np.uint32)

        for pipeline_env in ("0", "1"):
            monkeypatch.setenv("DPF_TPU_PIPELINE", pipeline_env)
            run = lambda: keygen_batch.generate_keys_batch(
                dpf, alphas, betas, mode="megakernel", seeds=seeds,
                interpret=True,
            )
            run()  # warm: compiles allowed
            program_counter["programs"] = 0
            run()
            got = program_counter["programs"]
            assert got == 1, (
                f"megakernel keygen ran {got} device programs per warm "
                f"batch with DPF_TPU_PIPELINE={pipeline_env} (pinned: "
                "ONE — the single-program dealer)"
            )
    finally:
        keygen_batch._keygen_megakernel_jit.cache_clear()
        jax.clear_caches()


def test_keygen_threaded_runs_zero_device_programs(program_counter):
    """ISSUE 19 pin: the production-default threaded host dealer is pure
    numpy at ANY worker count — a warm threaded batch launches ZERO
    device programs (the thread pool shards the host batch; nothing
    touches a device)."""
    from distributed_point_functions_tpu.ops import keygen_batch

    rng = np.random.default_rng(7)
    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    alphas = [5, 9, 44, 77]
    betas = [[1, 2, 3, 4]]
    seeds = rng.integers(0, 2**32, size=(4, 2, 4), dtype=np.uint32)

    def run(threads):
        return keygen_batch.generate_keys_batch(
            dpf, alphas, betas, mode="numpy-threaded", seeds=seeds,
            threads=threads,
        )

    for threads in (1, 2):
        run(threads)  # warm (object caches)
        program_counter["programs"] = 0
        run(threads)
        assert program_counter["programs"] == 0, (
            f"threaded keygen at {threads} workers launched "
            f"{program_counter['programs']} device programs — the host "
            "dealer must launch none"
        )


def test_serving_keygen_runs_zero_device_programs(program_counter):
    """ISSUE 13 acceptance pin: the keygen-offload serving path routes
    to the host batched dealer (device keygen modes are unverified,
    router.UNVERIFIED_MODES), so a served keygen batch launches ZERO
    device programs — the wire op costs nothing beyond the batched
    path's own pinned budget, and the host batch's budget is zero."""
    from distributed_point_functions_tpu import serving

    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))

    def door_pass():
        # width_target == the merged alpha count: the flush fires on
        # width, not the (deliberately huge) batch deadline.
        door = serving.FrontDoor(max_wait_ms=1e6, width_target=3)
        with door:
            out = door.serve(
                [
                    serving.Request.keygen(dpf, [5, 9], [[1, 2]]),
                    serving.Request.keygen(dpf, [44], [7]),
                ],
                timeout=120,
            )
        assert len(out[0]) == 4 and len(out[1]) == 2  # 2*K blobs each

    door_pass()  # warm (object caches)
    program_counter["programs"] = 0
    door_pass()
    assert program_counter["programs"] == 0, (
        f"served keygen launched {program_counter['programs']} device "
        "programs — the host dealer path must launch none"
    )


def test_serving_wire_adds_zero_programs(program_counter):
    """ISSUE 10 acceptance pin: the SOCKET boundary — framing, the
    server's request decode/reconstruct, deadline plumbing, response
    encode — adds zero device programs over the in-process front door.
    Four concurrent client threads land the same merged 4-key batch the
    in-process reference serves (same lds-10 chunk-2 family as the
    ISSUE 8 pin: no new compiles), and the warm program counts must be
    EQUAL."""
    import threading

    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.ops import supervisor

    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9, 44, 77], [[1, 2, 3, 4]])
    params = [DpfParameters(10, Int(64))]

    def direct():
        supervisor.full_domain_evaluate_robust(
            dpf, list(keys), key_chunk=2, pipeline=False
        )

    direct()  # warm
    program_counter["programs"] = 0
    direct()
    direct_count = program_counter["programs"]
    assert direct_count >= 1

    with serving.DpfServer(
        engine="device", max_wait_ms=10_000.0, width_target=4, key_chunk=2,
        pipeline=False,
    ) as srv:
        def wire_pass():
            # One key per client connection; the width target of 4
            # flushes them as ONE merged batch — the same program
            # profile as the direct merged call.
            def one(k):
                cli = serving.DpfClient("127.0.0.1", srv.port)
                try:
                    cli.full_domain(params, [k], deadline=300)
                finally:
                    cli.close()

            threads = [
                threading.Thread(target=one, args=(k,)) for k in keys
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        wire_pass()  # warm (serialization caches, server object caches)
        program_counter["programs"] = 0
        wire_pass()
        assert program_counter["programs"] == direct_count, (
            f"the wire boundary launched {program_counter['programs']} "
            f"device programs vs {direct_count} for the direct merged "
            "call — framing and the server loop must add zero dispatches"
        )


def test_fleet_adds_zero_programs(program_counter):
    """ISSUE 14 acceptance pin — the front door's zero-overhead pin
    extended to the FLEET tier: a proxy + N replicas launch EXACTLY the
    device programs N direct servers do. Affinity routing is what makes
    this hold: the four single-key requests share a routing digest, so
    they all land on ONE replica and merge into the same 4-key batch a
    single server would run — the proxy never splits a mergeable batch
    across replicas (which would multiply programs), and relay/routing
    are pure host work."""
    import threading

    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.ops import supervisor

    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9, 44, 77], [[1, 2, 3, 4]])
    params = [DpfParameters(10, Int(64))]

    def direct():
        supervisor.full_domain_evaluate_robust(
            dpf, list(keys), key_chunk=2, pipeline=False
        )

    direct()  # warm: compiles + probe caches
    program_counter["programs"] = 0
    direct()
    direct_count = program_counter["programs"]
    assert direct_count >= 1

    replicas = [
        serving.DpfServer(
            engine="device", max_wait_ms=10_000.0, width_target=4,
            key_chunk=2, pipeline=False,
        ).start()
        for _ in range(2)
    ]
    proxy = serving.FleetProxy(
        [("127.0.0.1", s.port) for s in replicas]
    ).start()
    try:
        ready = serving.DpfClient("127.0.0.1", proxy.port)
        ready.wait_ready(timeout=60)
        ready.close()

        def fleet_pass():
            # One key per client connection; the width target of 4 and
            # the shared routing digest flush them as ONE merged batch
            # on ONE replica.
            def one(k):
                cli = serving.DpfClient("127.0.0.1", proxy.port)
                try:
                    cli.full_domain(params, [k], deadline=300)
                finally:
                    cli.close()

            threads = [
                threading.Thread(target=one, args=(k,)) for k in keys
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        fleet_pass()  # warm (server object caches on the serving replica)
        program_counter["programs"] = 0
        fleet_pass()
        assert program_counter["programs"] == direct_count, (
            f"the fleet tier launched {program_counter['programs']} "
            f"device programs vs {direct_count} for the direct merged "
            "call — affinity must keep a mergeable batch on one replica "
            "and the proxy must add zero dispatches"
        )
    finally:
        proxy.stop()
        for s in replicas:
            s.stop()


def test_autoscaler_loop_adds_zero_programs(program_counter):
    """ISSUE 20 acceptance pin: the autoscaler control plane is pure host
    work — stats/health polling over the wire, the backlog signal,
    streak/cooldown bookkeeping, victim picking, and a full scale-up +
    drain-down + revive cycle through the proxy membership seams launch
    ZERO device programs. Elasticity must never cost a dispatch."""
    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.serving.autoscale import AutoScaler

    class _InProcessPool:
        def __init__(self):
            self.servers = [
                serving.DpfServer(engine="host", max_wait_ms=1.0).start()
            ]
            self.ports = [self.servers[0].port]

        def running_indices(self):
            return [i for i, s in enumerate(self.servers) if s is not None]

        def scale_up(self, timeout=180.0):
            for i, s in enumerate(self.servers):
                if s is None:
                    srv = serving.DpfServer(
                        engine="host", max_wait_ms=1.0, port=self.ports[i],
                    ).start()
                    self.servers[i] = srv
                    return i, srv.port, False
            srv = serving.DpfServer(engine="host", max_wait_ms=1.0).start()
            self.servers.append(srv)
            self.ports.append(srv.port)
            return len(self.servers) - 1, srv.port, True

        def scale_down(self, i, timeout=30.0):
            s, self.servers[i] = self.servers[i], None
            if s is not None:
                s.stop()

        def stop(self):
            for s in self.servers:
                if s is not None:
                    s.stop()

    pool = _InProcessPool()
    proxy = serving.FleetProxy(
        [("127.0.0.1", pool.ports[0])], probe_interval=60.0,
    ).start()
    try:
        ready = serving.DpfClient("127.0.0.1", proxy.port)
        ready.wait_ready(timeout=60)
        ready.close()
        sc = AutoScaler(
            proxy, pool, plane="eval", min_replicas=1, max_replicas=2,
            up_backlog=10.0, down_backlog=1.0, sustain=1, cooldown=0.0,
            drain_timeout=10.0,
        )
        program_counter["programs"] = 0
        # The real stats-path signal: wire polls of /stats + /health.
        for _ in range(3):
            assert sc.backlog() == 0.0
        # Forced signal drives a full up -> drain-down -> revive cycle
        # (only the signal is stubbed; the membership plumbing is real).
        sc.backlog = lambda: 50.0
        assert sc.poll_once() == "up"
        sc.backlog = lambda: 0.0
        assert sc.poll_once() == "down"
        sc.backlog = lambda: 50.0
        assert sc.poll_once() == "up"
        assert sc.stats()["ups"] == 2 and sc.stats()["downs"] == 1
        assert program_counter["programs"] == 0, (
            f"the autoscaler control loop launched "
            f"{program_counter['programs']} device programs across polls "
            "and a full scale cycle — elasticity must be pure host work"
        )
    finally:
        proxy.stop()
        pool.stop()


def test_tenant_tagged_requests_add_zero_programs(program_counter):
    """ISSUE 20 acceptance pin: tenant tokens on the wire — decode, QoS
    admission (quotas + priority classing), per-tenant telemetry, and
    cross-tenant batch merging — add ZERO device programs over the
    untenanted wire path. Four clients under TWO tenants land the same
    merged 4-key batch (tenant is excluded from the merge signature), so
    the warm program count must EQUAL the direct merged call's."""
    import threading

    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.ops import supervisor

    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9, 44, 77], [[1, 2, 3, 4]])
    params = [DpfParameters(10, Int(64))]

    def direct():
        supervisor.full_domain_evaluate_robust(
            dpf, list(keys), key_chunk=2, pipeline=False
        )

    direct()  # warm
    program_counter["programs"] = 0
    direct()
    direct_count = program_counter["programs"]
    assert direct_count >= 1

    with serving.DpfServer(
        engine="device", max_wait_ms=10_000.0, width_target=4, key_chunk=2,
        pipeline=False, tenant_quotas={"acme": 8, "zeta": 8},
        tenant_priorities={"acme": 1},
    ) as srv:
        def wire_pass():
            # Two tenants, one key per client; the shared signature
            # merges all four into ONE batch despite the tenant split.
            def one(k, tenant):
                cli = serving.DpfClient("127.0.0.1", srv.port, tenant=tenant)
                try:
                    cli.full_domain(params, [k], deadline=300)
                finally:
                    cli.close()

            tenants = ["acme", "acme", "zeta", "zeta"]
            threads = [
                threading.Thread(target=one, args=(k, t))
                for k, t in zip(keys, tenants)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        wire_pass()  # warm (serialization caches, server object caches)
        program_counter["programs"] = 0
        wire_pass()
        assert program_counter["programs"] == direct_count, (
            f"tenant-tagged requests launched "
            f"{program_counter['programs']} device programs vs "
            f"{direct_count} for the direct merged call — the QoS plane "
            "must add zero dispatches"
        )
        h = srv._health()
        assert h["tenants"]["acme"]["served"] >= 2  # the tag rode the wire


def test_streaming_adds_zero_programs(program_counter, tmp_path):
    """ISSUE 15 acceptance pin: the streaming heavy-hitters tier on the
    host route — ingest journaling, window close, the leader's full
    level-by-level advance with the peer exchange, threshold prune,
    publish, rotation — launches ZERO device programs. The host-engine
    advance is the native AES path end to end; hierkernel stays
    staged-for-tunnel behind the stream's mode plumbing."""
    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.protos import serialization as ser

    cfg = serving.StreamConfig.bitwise(
        "audit", 6, 2, threshold=2, window_keys=4
    )
    dpf = DistributedPointFunction.create_incremental(list(cfg.parameters))
    n = len(cfg.parameters)

    follower = serving.HeavyHitterStream(cfg, str(tmp_path / "f"))
    leader = serving.HeavyHitterStream(
        cfg, str(tmp_path / "l"), peer=("127.0.0.1", 1),
    )
    leader._peer_level = lambda w, member, trail: follower.aggregate(
        w.generation, list(member), trail
    )
    program_counter["programs"] = 0
    for i, vals in enumerate([[9, 9], [40, 9]]):
        b0, b1 = [], []
        for v in vals:
            k0, k1 = dpf.generate_keys_incremental(v, [1] * n)
            b0.append(ser.serialize_dpf_key(k0, cfg.parameters))
            b1.append(ser.serialize_dpf_key(k1, cfg.parameters))
        leader.ingest(cfg.parameters, b0, f"b-{i}")
        follower.ingest(cfg.parameters, b1, f"b-{i}")
    leader.ingest(cfg.parameters, [], "", flush=True)
    with leader._lock:
        pending = list(leader._pending_locked())
    for w in pending:
        leader._advance_window(w)
    snap = leader.snapshot()
    assert snap["published"], "the window must publish"
    assert program_counter["programs"] == 0, (
        f"the streaming host route launched {program_counter['programs']} "
        "device programs — ingest/advance/publish must be pure host work"
    )
    leader.stop()
    follower.stop()
