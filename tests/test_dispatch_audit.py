"""Dispatch audit: warm calls of the main device entry points must run
ZERO eager primitives.

Eager ops between jit calls (slices, un-jitted vmaps, pads) each dispatch
their own tiny device program. CPU timing hides them, but through this
image's ~66 ms-dispatch tunnel they dominate: r4 found ~127 slice
dispatches (~8 s pure latency) inside one fused heavy-hitters call and
~18 per hierarchical level-advance (PERF.md "Round 4"). This test pins
the audit result so a refactor can't silently reintroduce a storm.

The counter hooks jax's internal eager-execution entry point; if a jax
upgrade moves it, the test skips rather than fails.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
from distributed_point_functions_tpu.core.params import DpfParameters
from distributed_point_functions_tpu.core.value_types import Int
from distributed_point_functions_tpu.dcf import batch as dcf_batch
from distributed_point_functions_tpu.dcf.dcf import DistributedComparisonFunction
from distributed_point_functions_tpu.ops import evaluator, hierarchical


@pytest.fixture
def eager_counter(monkeypatch):
    try:
        import jax._src.dispatch as dispatch_mod

        orig = dispatch_mod.apply_primitive
    except (ImportError, AttributeError):
        pytest.skip("jax internal apply_primitive moved; audit hook unavailable")
    counts = {"eager": 0}

    def spy(prim, *args, **kwargs):
        counts["eager"] += 1
        return orig(prim, *args, **kwargs)

    monkeypatch.setattr(dispatch_mod, "apply_primitive", spy)
    return counts


def _assert_no_eager(counts, fn, name):
    fn()  # warm: compiles + constant uploads are allowed
    counts["eager"] = 0
    fn()
    assert counts["eager"] == 0, (
        f"{name}: {counts['eager']} eager primitive dispatches in a warm "
        "call — each is a separate device program (~66 ms latency on the "
        "real link); move the op inside a jitted program (see PERF.md "
        "'Round 4' dispatch audit)"
    )


def test_full_domain_chunks_no_eager_dispatch(eager_counter):
    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9], [[1, 2]])

    for mode in ("levels", "fused"):
        _assert_no_eager(
            eager_counter,
            lambda: list(
                evaluator.full_domain_evaluate_chunks(dpf, keys, mode=mode)
            ),
            f"full_domain_evaluate_chunks[{mode}]",
        )
    _assert_no_eager(
        eager_counter,
        lambda: list(evaluator.full_domain_fold_chunks(dpf, keys)),
        "full_domain_fold_chunks",
    )


@pytest.mark.slow
def test_evaluate_at_and_dcf_no_eager_dispatch(eager_counter):
    dpf = DistributedPointFunction.create(DpfParameters(10, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 9], [[1, 2]])
    pts = [int(x) for x in np.random.default_rng(1).integers(0, 1 << 10, 64)]
    _assert_no_eager(
        eager_counter,
        lambda: evaluator.evaluate_at_batch(dpf, keys, pts),
        "evaluate_at_batch",
    )

    dc = DistributedComparisonFunction.create(8, Int(64))
    dk, _ = dc.generate_keys_batch([100, 200], [7, 9])
    xs = [int(x) for x in np.random.default_rng(2).integers(0, 1 << 8, 48)]
    _assert_no_eager(
        eager_counter,
        lambda: dcf_batch.batch_evaluate(dc, dk, xs, use_pallas=False),
        "dcf.batch_evaluate",
    )


def test_hierarchical_paths_no_eager_dispatch(eager_counter):
    params = [DpfParameters(d, Int(32)) for d in (3, 6, 9)]
    dpf = DistributedPointFunction.create_incremental(params)
    key, _ = dpf.generate_keys_incremental(77, [5, 6, 7])

    def walk():
        bc = hierarchical.BatchedContext.create(dpf, [key])
        hierarchical.evaluate_until_batch(bc, 0, device_output=True)
        hierarchical.evaluate_until_batch(
            bc, 1, list(range(8)), device_output=True
        )
        hierarchical.evaluate_until_batch(
            bc, 2, list(range(16)), device_output=True
        )

    _assert_no_eager(eager_counter, walk, "evaluate_until_batch")

    levels = 6
    paramsf = [DpfParameters(i + 1, Int(64)) for i in range(levels)]
    dpff = DistributedPointFunction.create_incremental(paramsf)
    kf, _ = dpff.generate_keys_incremental(11, [7] * levels)
    finals = sorted({int(x) for x in np.random.default_rng(5).integers(0, 64, 20)})
    pres = [
        sorted({f >> (levels - (i + 1)) for f in finals})
        for i in range(levels)
    ]
    plan = [(0, [])] + [(i, pres[i - 1]) for i in range(1, levels)]
    prepared = hierarchical.prepare_levels_fused(
        hierarchical.BatchedContext.create(dpff, [kf]), plan, 4
    )

    def fused():
        bc = hierarchical.BatchedContext.create(dpff, [kf])
        hierarchical.evaluate_levels_fused(
            bc, prepared, device_output=True, use_pallas=False
        )

    _assert_no_eager(eager_counter, fused, "evaluate_levels_fused[prepared]")
