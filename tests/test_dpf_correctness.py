"""Share-sum correctness property tests for the core DPF engine.

The workhorse acceptance property from the reference test suite
(/root/reference/dpf/distributed_point_function_test.cc:334-462): evaluating
*both* keys and summing must give beta at alpha (or a prefix of alpha) and
zero everywhere else, across value types, hierarchies, and evaluation modes.
"""

import copy
import random

import pytest

from distributed_point_functions_tpu import (
    DistributedPointFunction,
    DpfParameters,
    Int,
    IntModN,
    InvalidArgumentError,
    TupleType,
    XorWrapper,
)

RNG = random.Random(0xDF0)


def make_dpf(params):
    return DistributedPointFunction.create_incremental(params)


def combine(vt, a, b):
    return vt.add(a, b)


def check_share_sum(vt, shares0, shares1, alpha_index, beta, domain_iter):
    for x, (a, b) in zip(domain_iter, zip(shares0, shares1)):
        total = vt.add(a, b)
        expected = beta if x == alpha_index else vt.zero()
        assert total == expected, (x, total, expected)


@pytest.mark.parametrize("bitsize", [8, 16, 32, 64, 128])
@pytest.mark.parametrize("log_domain", [0, 1, 5, 10])
def test_regular_dpf_full_domain(bitsize, log_domain):
    vt = Int(bitsize)
    dpf = make_dpf([DpfParameters(log_domain, vt)])
    alpha = RNG.randrange(1 << log_domain)
    beta = RNG.randrange(1 << bitsize)
    k0, k1 = dpf.generate_keys(alpha, beta)
    ctx0, ctx1 = dpf.create_evaluation_context(k0), dpf.create_evaluation_context(k1)
    e0, e1 = dpf.evaluate_next([], ctx0), dpf.evaluate_next([], ctx1)
    assert len(e0) == 1 << log_domain
    check_share_sum(vt, e0, e1, alpha, beta, range(1 << log_domain))


@pytest.mark.parametrize(
    "vt",
    [
        Int(8),
        Int(128),
        XorWrapper(64),
        XorWrapper(128),
        IntModN(32, 4294967291),  # 2**32 - 5
        IntModN(64, 18446744073709551557),  # 2**64 - 59
        TupleType(Int(32), Int(32)),
        TupleType(Int(8), Int(16), Int(8)),
        TupleType(Int(64), TupleType(Int(32), Int(32))),
        TupleType(Int(32), IntModN(32, 4294967291)),
        TupleType(IntModN(32, 4294967291), IntModN(32, 4294967291)),
    ],
    ids=str,
)
def test_value_types_full_domain_and_points(vt):
    log_domain = 7
    dpf = make_dpf([DpfParameters(log_domain, vt)])
    alpha = 93
    beta = random_value(vt)
    k0, k1 = dpf.generate_keys(alpha, beta)
    ctx0, ctx1 = dpf.create_evaluation_context(k0), dpf.create_evaluation_context(k1)
    e0, e1 = dpf.evaluate_next([], ctx0), dpf.evaluate_next([], ctx1)
    check_share_sum(vt, e0, e1, alpha, beta, range(1 << log_domain))

    points = [RNG.randrange(1 << log_domain) for _ in range(20)] + [alpha]
    a0 = dpf.evaluate_at(k0, 0, points)
    a1 = dpf.evaluate_at(k1, 0, points)
    check_share_sum(vt, a0, a1, alpha, beta, points)


def random_value(vt):
    if isinstance(vt, Int):
        return RNG.randrange(1 << vt.bitsize)
    if isinstance(vt, XorWrapper):
        return RNG.randrange(1 << vt.bitsize)
    if isinstance(vt, IntModN):
        return RNG.randrange(vt.modulus)
    if isinstance(vt, TupleType):
        return tuple(random_value(e) for e in vt.elements)
    raise TypeError(vt)


@pytest.mark.parametrize("level_step", [1, 2, 3, 5, 7])
def test_incremental_hierarchy_prefixes(level_step):
    # Step 7 extends the ceiling so it still yields a real 2-level
    # hierarchy ([7, 14]) like the reference's level_step matrix.
    log_domains = list(
        range(level_step, max(10, 2 * level_step) + 1, level_step)
    )
    params = [DpfParameters(ld, Int(64)) for ld in log_domains]
    dpf = make_dpf(params)
    alpha = RNG.randrange(1 << log_domains[-1])
    betas = [RNG.randrange(1 << 64) for _ in params]
    k0, k1 = dpf.generate_keys_incremental(alpha, betas)
    ctx0, ctx1 = dpf.create_evaluation_context(k0), dpf.create_evaluation_context(k1)

    vt = Int(64)
    prefixes = []
    for level, ld in enumerate(log_domains):
        e0 = dpf.evaluate_until(level, prefixes, ctx0)
        e1 = dpf.evaluate_until(level, prefixes, ctx1)
        alpha_prefix = alpha >> (log_domains[-1] - ld)
        # Reconstruct absolute indices for the evaluated prefixes.
        if prefixes:
            step = ld - log_domains[level - 1]
            indices = [
                (p << step) | j for p in prefixes for j in range(1 << step)
            ]
        else:
            indices = list(range(1 << ld))
        check_share_sum(vt, e0, e1, alpha_prefix, betas[level], indices)
        # Keep the path containing alpha plus a decoy prefix.
        decoy = (alpha_prefix + 1) % (1 << ld)
        prefixes = sorted({alpha_prefix, decoy})


def test_evaluate_at_all_hierarchy_levels_with_ctx():
    params = [DpfParameters(ld, Int(32)) for ld in (4, 8, 12)]
    dpf = make_dpf(params)
    alpha = 0xABC
    betas = [5, 6, 7]
    k0, k1 = dpf.generate_keys_incremental(alpha, betas)
    vt = Int(32)
    # Without a context: each call starts from the key seed.
    for level, ld in enumerate((4, 8, 12)):
        alpha_prefix = alpha >> (12 - ld)
        points = [alpha_prefix, (alpha_prefix + 2) % (1 << ld)]
        a0 = dpf.evaluate_at(k0, level, points)
        a1 = dpf.evaluate_at(k1, level, points)
        check_share_sum(vt, a0, a1, alpha_prefix, betas[level], points)
    # With a context: partial evaluations are saved and reused per level.
    ctx0, ctx1 = dpf.create_evaluation_context(k0), dpf.create_evaluation_context(k1)
    for level, ld in enumerate((4, 8, 12)):
        alpha_prefix = alpha >> (12 - ld)
        points = [alpha_prefix, (alpha_prefix + 2) % (1 << ld)]
        a0 = dpf.evaluate_at(k0, level, points, ctx=ctx0)
        a1 = dpf.evaluate_at(k1, level, points, ctx=ctx1)
        check_share_sum(vt, a0, a1, alpha_prefix, betas[level], points)
        assert ctx0.previous_hierarchy_level == level


def test_128_bit_domain_point_eval():
    vt = Int(64)
    dpf = make_dpf([DpfParameters(128, vt)])
    alpha = (1 << 127) + 12345
    beta = 42
    k0, k1 = dpf.generate_keys(alpha, beta)
    points = [alpha, 0, (1 << 128) - 1, alpha ^ 1]
    a0 = dpf.evaluate_at(k0, 0, points)
    a1 = dpf.evaluate_at(k1, 0, points)
    check_share_sum(vt, a0, a1, alpha, beta, points)


def test_keygen_validation_errors():
    dpf = make_dpf([DpfParameters(5, Int(32))])
    with pytest.raises(InvalidArgumentError, match="smaller than the output domain"):
        dpf.generate_keys(32, 1)
    with pytest.raises(InvalidArgumentError, match="too large"):
        dpf.generate_keys(3, 1 << 32)
    with pytest.raises(InvalidArgumentError, match="same size as `parameters`"):
        dpf.generate_keys_incremental(3, [1, 2])


def test_context_lifecycle_errors():
    dpf = make_dpf([DpfParameters(3, Int(32)), DpfParameters(6, Int(32))])
    k0, _ = dpf.generate_keys_incremental(5, [1, 2])
    # Hierarchy-level bounds (EvaluationFailsIfHierarchyLevelNegative /
    # ...TooLarge).
    fresh = dpf.create_evaluation_context(k0)
    with pytest.raises(InvalidArgumentError):
        dpf.evaluate_until(-1, [], fresh)
    with pytest.raises(InvalidArgumentError):
        dpf.evaluate_until(2, [], fresh)
    ctx = dpf.create_evaluation_context(k0)
    with pytest.raises(InvalidArgumentError, match="must be empty"):
        dpf.evaluate_until(0, [1], ctx)
    dpf.evaluate_until(0, [], ctx)
    # Prefixes are domain indices at the PREVIOUS level (3 bits: 0..7) —
    # EvaluationFailsIfPrefixOutOfRange.
    with pytest.raises(InvalidArgumentError, match="out of range"):
        dpf.evaluate_until(1, [8], ctx)
    with pytest.raises(InvalidArgumentError, match="greater than"):
        dpf.evaluate_until(0, [0], ctx)
    dpf.evaluate_until(1, [0, 1], ctx)
    with pytest.raises(InvalidArgumentError, match="fully evaluated"):
        dpf.evaluate_until(1, [0], ctx)


def test_context_duplicate_prefix_with_mismatching_state():
    """FailsIfDuplicatePrefixInCtx (distributed_point_function_test.cc): a
    context whose partial_evaluations hold the same prefix twice with
    DIFFERENT seed/control state is corrupt and must be rejected; an exact
    duplicate is tolerated (the reference dedupes silently)."""
    dpf = make_dpf([DpfParameters(w, Int(32)) for w in (3, 6, 9)])
    k0, _ = dpf.generate_keys_incremental(5, [1, 2, 3])
    ctx = dpf.create_evaluation_context(k0)
    dpf.evaluate_until(0, [], ctx)
    # The partial-evaluation cache fills on the first prefixed call
    # (mirroring ExpandAndUpdateContext's laziness).
    dpf.evaluate_until(1, [0, 1, 2], ctx)
    assert ctx.partial_evaluations


    # Exact duplicate: harmless — and the deduped evaluation must return
    # exactly what the untampered context returns.
    query = [int(ctx.partial_evaluations[0].prefix)]
    want = dpf.evaluate_until(2, query, copy.deepcopy(ctx))
    benign = copy.deepcopy(ctx)
    benign.partial_evaluations.append(
        copy.deepcopy(benign.partial_evaluations[0])
    )
    got = dpf.evaluate_until(2, query, benign)
    assert list(got) == list(want)

    # Same prefix, different seed: corrupt.
    bad = copy.deepcopy(ctx)
    clone = copy.deepcopy(bad.partial_evaluations[0])
    clone.seed ^= 1
    bad.partial_evaluations.append(clone)
    with pytest.raises(InvalidArgumentError, match="Duplicate prefix"):
        dpf.evaluate_until(2, [bad.partial_evaluations[0].prefix], bad)


def test_context_prefix_not_present():
    """FailsIfPrefixNotPresentInCtx: asking for a prefix whose parent state
    was never stored (here: removed) must fail with the reference's
    message, not silently expand garbage."""
    dpf = make_dpf([DpfParameters(w, Int(32)) for w in (3, 6, 9)])
    k0, _ = dpf.generate_keys_incremental(5, [1, 2, 3])
    ctx = dpf.create_evaluation_context(k0)
    dpf.evaluate_until(0, [], ctx)
    # Int(32) packs 4 elements/block, so partial evaluations are stored
    # per TREE index: the 3-bit level's 8 prefixes collapse to tree
    # entries {0, 1} (prefix >> 2).
    dpf.evaluate_until(1, list(range(8)), ctx)
    assert [p.prefix for p in ctx.partial_evaluations] == [0, 1]
    del ctx.partial_evaluations[1]  # drop tree entry 1
    # Level-1 domain prefix 32's ancestry: level-0 prefix 32 >> 3 = 4,
    # tree index 4 >> 2 = 1 — exactly the deleted entry.
    with pytest.raises(InvalidArgumentError, match="not present"):
        dpf.evaluate_until(2, [32], ctx)


def test_maximum_output_domain_129_levels():
    """The reference's MaximumOutputDomainSize suite: a 129-level hierarchy
    with log domains 0..128, alpha spanning the full 128 bits, evaluated at
    a sample of levels via prefixes around alpha
    (/root/reference/dpf/distributed_point_function_test.cc:879-897)."""
    params = [DpfParameters(i, Int(64)) for i in range(129)]
    dpf = DistributedPointFunction.create_incremental(params)
    alpha = (23 << 64) | 42
    beta = 1234567
    ka, kb = dpf.generate_keys_incremental(alpha, [beta] * 129)

    ctx_a = dpf.create_evaluation_context(ka)
    ctx_b = dpf.create_evaluation_context(kb)
    previous = -1
    levels = list(range(0, 129, 7)) + [128]  # level_step 7, as the suite does
    for level in levels:
        if previous < 0:
            prefixes = []
        else:
            prev_lds = params[previous].log_domain_size
            prefix = alpha >> (128 - prev_lds)
            # alpha's prefix plus a couple of cold neighbours
            prefixes = sorted(
                {prefix, prefix ^ 1 if prev_lds > 0 else prefix, 0}
            )
        va = dpf.evaluate_until(level, prefixes, ctx_a)
        vb = dpf.evaluate_until(level, prefixes, ctx_b)
        lds = params[level].log_domain_size
        alpha_prefix = alpha >> (128 - lds) if lds < 128 else alpha
        outputs_per_prefix = (
            len(va) // max(len(prefixes), 1) if prefixes else len(va)
        )
        # reconstruct and locate the nonzero
        hits = 0
        for j, (a, b) in enumerate(zip(va, vb)):
            total = (a + b) % (1 << 64)
            if prefixes:
                p = prefixes[j // outputs_per_prefix]
                idx = (p << (lds - params[previous].log_domain_size)) + (
                    j % outputs_per_prefix
                )
            else:
                idx = j
            if idx == alpha_prefix:
                assert total == beta, (level, idx)
                hits += 1
            else:
                assert total == 0, (level, idx)
        # alpha's prefix must have been covered at every evaluated level
        assert hits == 1, level
        previous = level
