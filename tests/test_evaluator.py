"""Tests for the batched device evaluators (ops/evaluator.py) against the
host path and the share-sum property."""

import numpy as np
import pytest

from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
from distributed_point_functions_tpu.core.params import DpfParameters
from distributed_point_functions_tpu.core.value_types import Int, XorWrapper
from distributed_point_functions_tpu.ops import evaluator


def test_batched_keygen_matches_sequential():
    """generate_keys_batch is bit-exact with K sequential generate_keys calls
    given the same seeds (level-major vectorization changes no math)."""
    rng = np.random.default_rng(42)
    params = [DpfParameters(3, Int(128)), DpfParameters(10, Int(32))]
    dpf = DistributedPointFunction.create_incremental(params)
    k = 6
    alphas = [int(a) for a in rng.integers(0, 1 << 10, size=k)]
    betas = [
        [int(b) for b in rng.integers(1, 100, size=k)],
        [int(b) for b in rng.integers(1, 100, size=k)],
    ]
    seeds = rng.integers(0, 2**32, size=(k, 2, 4), dtype=np.uint32)
    ka_batch, kb_batch = dpf.generate_keys_batch(alphas, betas, seeds=seeds)
    for i in range(k):
        s = (
            int.from_bytes(seeds[i, 0].tobytes(), "little"),
            int.from_bytes(seeds[i, 1].tobytes(), "little"),
        )
        ka, kb = dpf.generate_keys_incremental(
            alphas[i], [betas[0][i], betas[1][i]], seeds=s
        )
        assert ka == ka_batch[i]
        assert kb == kb_batch[i]


def test_batched_keygen_broadcast_beta_and_validation():
    dpf = DistributedPointFunction.create(DpfParameters(8, Int(64)))
    keys_a, keys_b = dpf.generate_keys_batch([1, 2, 3], [5])
    assert len(keys_a) == len(keys_b) == 3
    with pytest.raises(Exception, match="same size as `parameters`"):
        dpf.generate_keys_batch([1], [5, 6])
    with pytest.raises(Exception, match="smaller than the output domain"):
        dpf.generate_keys_batch([1 << 9], [5])

RNG = np.random.default_rng(0xEA1)


def make_keys(dpf, alphas, betas):
    keys_a, keys_b = [], []
    for alpha, beta in zip(alphas, betas):
        ka, kb = dpf.generate_keys(alpha, beta)
        keys_a.append(ka)
        keys_b.append(kb)
    return keys_a, keys_b


@pytest.mark.parametrize(
    "bits,log_domain", [(8, 6), (32, 8), (64, 9), (128, 7)]
)
def test_full_domain_share_sum(bits, log_domain):
    dpf = DistributedPointFunction.create(DpfParameters(log_domain, Int(bits)))
    domain = 1 << log_domain
    k = 5
    alphas = RNG.integers(0, domain, size=k)
    betas = [int(b) for b in RNG.integers(1, 2 ** min(bits, 63), size=k)]
    keys_a, keys_b = make_keys(dpf, [int(a) for a in alphas], betas)

    out_a = evaluator.full_domain_evaluate(dpf, keys_a, key_chunk=3)
    out_b = evaluator.full_domain_evaluate(dpf, keys_b, key_chunk=3)
    va = evaluator.values_to_numpy(out_a, bits)
    vb = evaluator.values_to_numpy(out_b, bits)
    assert va.shape == (k, domain)
    mod = 1 << bits
    for i in range(k):
        total = (va[i].astype(object) + vb[i].astype(object)) % mod
        expected = np.zeros(domain, dtype=object)
        expected[alphas[i]] = betas[i]
        assert (total == expected).all(), f"key {i}"


def test_full_domain_matches_host_path():
    dpf = DistributedPointFunction.create(DpfParameters(8, Int(64)))
    ka, _ = dpf.generate_keys(200, 31337)
    got = evaluator.values_to_numpy(
        evaluator.full_domain_evaluate(dpf, [ka]), 64
    )[0]
    ctx = dpf.create_evaluation_context(ka)
    want = np.array(dpf.evaluate_next([], ctx), dtype=np.uint64)
    np.testing.assert_array_equal(got, want)


def test_full_domain_xor_group():
    dpf = DistributedPointFunction.create(DpfParameters(6, XorWrapper(128)))
    alpha, beta = 33, (1 << 100) | 0xFFEE
    ka, kb = dpf.generate_keys(alpha, beta)
    va = evaluator.values_to_numpy(evaluator.full_domain_evaluate(dpf, [ka]), 128)
    vb = evaluator.values_to_numpy(evaluator.full_domain_evaluate(dpf, [kb]), 128)
    total = va[0] ^ vb[0]
    expected = np.zeros(64, dtype=object)
    expected[alpha] = beta
    assert (total == expected).all()


@pytest.mark.slow
def test_full_domain_host_levels_split():
    """Different host/device level splits give identical results."""
    dpf = DistributedPointFunction.create(DpfParameters(8, Int(32)))
    ka, _ = dpf.generate_keys(200, 99)
    base = evaluator.full_domain_evaluate(dpf, [ka], host_levels=5)
    # hl=0 exercises the all-device lane-pad path; hl=9 exceeds the tree
    # depth (stop_level=6 for lds=8/Int32) and exercises the clamp.
    for hl in [0, 9]:
        other = evaluator.full_domain_evaluate(dpf, [ka], host_levels=hl)
        np.testing.assert_array_equal(base, other)


@pytest.mark.parametrize(
    "bits", [64, pytest.param(32, marks=pytest.mark.slow)]
)
def test_evaluate_at_batch_matches_host(bits):
    dpf = DistributedPointFunction.create(DpfParameters(24, Int(bits)))
    k, p = 3, 40
    alphas = [int(a) for a in RNG.integers(0, 2**24, size=k)]
    betas = [int(b) for b in RNG.integers(1, 2 ** min(bits, 63), size=k)]
    keys_a, keys_b = make_keys(dpf, alphas, betas)
    points = [int(x) for x in RNG.integers(0, 2**24, size=p)]
    points[0] = alphas[0]
    points[1] = alphas[min(1, k - 1)]

    got_a = evaluator.values_to_numpy(
        evaluator.evaluate_at_batch(dpf, keys_a, points), bits
    )
    got_b = evaluator.values_to_numpy(
        evaluator.evaluate_at_batch(dpf, keys_b, points), bits
    )
    mod = 1 << bits
    for i in range(k):
        want = dpf.evaluate_at(keys_a[i], 0, points)
        np.testing.assert_array_equal(
            got_a[i].astype(object), np.array([w % mod for w in want], dtype=object)
        )
        for j, pt in enumerate(points):
            expected = betas[i] if pt == alphas[i] else 0
            assert (int(got_a[i][j]) + int(got_b[i][j])) % mod == expected


@pytest.mark.parametrize(
    "params,alpha",
    [
        # ADVICE r1 repro: level 0 (Int(128), epb=1) forces tree height 3;
        # level 1 (Int(32), epb=4) stops at a tree level where only
        # 2^(lds - level) < epb elements per block are addressable.
        ([DpfParameters(3, Int(128)), DpfParameters(4, Int(32))], 13),
        pytest.param(
            [DpfParameters(2, Int(64)), DpfParameters(5, Int(8))], 21,
            marks=pytest.mark.slow,
        ),
        pytest.param(
            [DpfParameters(4, Int(32)), DpfParameters(8, Int(32)),
             DpfParameters(12, Int(64))], 3071,
            marks=pytest.mark.slow,
        ),
    ],
)
def test_full_domain_incremental_matches_host(params, alpha):
    """Device full_domain_evaluate == host evaluate_until at EVERY hierarchy
    level of an incremental DPF (catches partial-block trimming)."""
    dpf = DistributedPointFunction.create_incremental(params)
    betas = [int(b) for b in RNG.integers(1, 100, size=len(params))]
    ka, kb = dpf.generate_keys_incremental(alpha, betas)
    for level, p in enumerate(params):
        bits = p.value_type.bitsize
        got = evaluator.values_to_numpy(
            evaluator.full_domain_evaluate(dpf, [ka], hierarchy_level=level),
            bits,
        )[0]
        ctx = dpf.create_evaluation_context(ka)
        want = dpf.evaluate_until(level, [], ctx)
        np.testing.assert_array_equal(
            got.astype(object), np.array(want, dtype=object)
        )
        # and the share-sum property at this level
        got_b = evaluator.values_to_numpy(
            evaluator.full_domain_evaluate(dpf, [kb], hierarchy_level=level),
            bits,
        )[0]
        total = (got.astype(object) + got_b.astype(object)) % (1 << bits)
        expected = np.zeros(1 << p.log_domain_size, dtype=object)
        expected[alpha >> (params[-1].log_domain_size - p.log_domain_size)] = betas[level]
        assert (total == expected).all(), f"level {level}"


@pytest.mark.slow
def test_evaluate_at_batch_incremental_intermediate_level():
    """evaluate_at_batch at an intermediate hierarchy level == host path."""
    params = [DpfParameters(3, Int(128)), DpfParameters(4, Int(32))]
    dpf = DistributedPointFunction.create_incremental(params)
    ka, _ = dpf.generate_keys_incremental(13, [7, 9])
    for level, bits in [(0, 128), (1, 32)]:
        points = list(range(1 << params[level].log_domain_size))
        got = evaluator.values_to_numpy(
            evaluator.evaluate_at_batch(dpf, [ka], points, hierarchy_level=level),
            bits,
        )[0]
        want = dpf.evaluate_at(ka, level, points)
        np.testing.assert_array_equal(
            got.astype(object), np.array(want, dtype=object)
        )


@pytest.mark.slow
def test_evaluate_at_batch_large_domain_128():
    dpf = DistributedPointFunction.create(DpfParameters(128, Int(64)))
    alpha = (1 << 127) | 12345
    ka, kb = dpf.generate_keys(alpha, 5)
    points = [alpha, alpha ^ 1, 0, (1 << 128) - 1]
    va = evaluator.values_to_numpy(evaluator.evaluate_at_batch(dpf, [ka], points), 64)
    vb = evaluator.values_to_numpy(evaluator.evaluate_at_batch(dpf, [kb], points), 64)
    total = (va[0].astype(object) + vb[0].astype(object)) % 2**64
    assert list(total) == [5, 0, 0, 0]


def test_lane_order_output_with_lane_order_map():
    """leaf_order=False + lane_order_map reconstructs the leaf-order output
    (the PIR pre-permuted-database pairing) on the scalar fast path."""
    dpf = DistributedPointFunction.create(DpfParameters(8, Int(64)))
    ka, _ = dpf.generate_keys(113, 777)
    leaf = None
    for valid, out in evaluator.full_domain_evaluate_chunks(dpf, [ka]):
        leaf = np.asarray(out)[:valid]
    lane = None
    for valid, out in evaluator.full_domain_evaluate_chunks(
        dpf, [ka], leaf_order=False
    ):
        lane = np.asarray(out)[:valid]
    m = evaluator.lane_order_map(dpf)
    assert lane.shape[1] == m.shape[0]
    ok = m >= 0
    rebuilt = np.zeros_like(leaf)
    rebuilt[:, m[ok]] = lane[:, ok]
    np.testing.assert_array_equal(rebuilt, leaf)


def test_lane_order_output_codec_path():
    """Same pairing on the codec (IntModN) path, which uses
    _finalize_batch_codec_jit's reorder flag."""
    from distributed_point_functions_tpu.core.value_types import IntModN

    n = (1 << 32) - 5
    dpf = DistributedPointFunction.create(DpfParameters(6, IntModN(32, n)))
    ka, _ = dpf.generate_keys(33, 12345)
    leaf = lane = None
    for valid, out in evaluator.full_domain_evaluate_chunks(dpf, [ka]):
        leaf = np.asarray(out)[:valid]
    for valid, out in evaluator.full_domain_evaluate_chunks(
        dpf, [ka], leaf_order=False
    ):
        lane = np.asarray(out)[:valid]
    m = evaluator.lane_order_map(dpf)
    ok = m >= 0
    rebuilt = np.zeros_like(leaf)
    rebuilt[:, m[ok]] = lane[:, ok]
    np.testing.assert_array_equal(rebuilt, leaf)


@pytest.mark.parametrize(
    "which",
    ["scalar", "tuple"]
    + [pytest.param(w, marks=pytest.mark.slow) for w in ("packed", "xor", "modn")],
)
def test_walk_mode_matches_levels_mode(which):
    """mode='walk' (single-program leaf-path walk) and mode='fused'
    (single-program doubling expansion) are bit-identical to the default
    per-level doubling expansion across packing regimes and value types,
    including the padded last chunk. The fast cases cover the scalar and
    codec program families; the remaining packing regimes are slow-marked."""
    from distributed_point_functions_tpu.core.value_types import IntModN, TupleType

    rng = np.random.default_rng(0xA11C)
    cases = {
        "scalar": (DpfParameters(8, Int(64)), 5),   # scalar, 2 elements/block
        "packed": (DpfParameters(7, Int(16)), 3),   # deep packing (8 epb)
        "xor": (DpfParameters(6, XorWrapper(128)), 4),  # XOR group, 1 epb
        "modn": (DpfParameters(5, IntModN(64, (1 << 64) - 59)), 3),  # codec scalar
        "tuple": (DpfParameters(5, TupleType(Int(32), Int(32))), 3),  # codec tuple
    }
    for params, num_keys in [cases[which]]:
        dpf = DistributedPointFunction.create(params)
        lds = params.log_domain_size
        alphas = [int(a) for a in rng.integers(0, 1 << lds, size=num_keys)]
        if isinstance(params.value_type, TupleType):
            betas = [[(7, 9)] * num_keys]
        else:
            betas = [[int(b) for b in rng.integers(1, 100, size=num_keys)]]
        keys, _ = dpf.generate_keys_batch(alphas, betas)

        def collect(mode):
            outs = []
            for valid, out in evaluator.full_domain_evaluate_chunks(
                dpf, keys, key_chunk=2, mode=mode
            ):
                if isinstance(out, tuple):
                    outs.append(tuple(np.asarray(o)[:valid] for o in out))
                else:
                    outs.append(np.asarray(out)[:valid])
            if isinstance(outs[0], tuple):
                return tuple(
                    np.concatenate([o[c] for o in outs]) for c in range(len(outs[0]))
                )
            return np.concatenate(outs)

        got_levels = collect("levels")
        got_walk = collect("walk")
        got_fused = collect("fused")
        if isinstance(got_levels, tuple):
            for a, b in zip(got_levels, got_walk):
                np.testing.assert_array_equal(a, b)
            for a, b in zip(got_levels, got_fused):
                np.testing.assert_array_equal(a, b)
        else:
            np.testing.assert_array_equal(got_levels, got_walk)
            np.testing.assert_array_equal(got_levels, got_fused)

    with pytest.raises(ValueError, match="mode must be"):
        list(
            evaluator.full_domain_evaluate_chunks(
                DistributedPointFunction.create(DpfParameters(4, Int(64))),
                [],
                mode="bogus",
            )
        )


def test_walk_path_masks_matches_sharded_leaf_masks():
    """The host word-wise walk-mask builder (evaluator._walk_path_masks) and
    the device lane-wise builder (sharded._leaf_path_masks) are independent
    implementations of the same leaf->path-bit mapping; pin them equal."""
    import jax.numpy as jnp

    from distributed_point_functions_tpu.parallel import sharded

    for num_levels in (1, 3, 5, 6, 9, 11):
        host = evaluator._walk_path_masks(num_levels)
        lanes = max(32, 1 << num_levels)
        dev = np.asarray(
            sharded._leaf_path_masks(jnp.uint32(0), lanes, num_levels)
        )
        np.testing.assert_array_equal(host, dev, err_msg=str(num_levels))


def test_fused_lane_slab_pieces_match_unslabbed():
    """lane_slab splits a fused chunk into leaf-contiguous pieces whose
    concatenation is bit-identical to the unslabbed expansion (the shape
    that keeps every dispatch under a platform's safe program size)."""
    dpf = DistributedPointFunction.create(DpfParameters(9, Int(64)))
    keys, _ = dpf.generate_keys_batch([5, 300, 511], [[9, 8, 7]])
    plain = []
    for v, out in evaluator.full_domain_evaluate_chunks(
        dpf, keys, key_chunk=2, mode="fused"
    ):
        plain.append(np.asarray(out)[:v])
    plain = np.concatenate(plain)
    rows, cur = [], None
    for v, out in evaluator.full_domain_evaluate_chunks(
        dpf, keys, key_chunk=2, mode="fused", host_levels=6, lane_slab=32
    ):
        a = np.asarray(out)
        cur = a if cur is None else np.concatenate([cur, a], axis=1)
        if cur.shape[1] == plain.shape[1]:
            rows.append(cur[:v])
            cur = None
    assert cur is None  # pieces covered each chunk's domain exactly
    np.testing.assert_array_equal(plain, np.concatenate(rows))
    # plan_slabs sizes under the budget and rejects misuse
    h, s = evaluator.plan_slabs(dpf, key_chunk=2, max_out_bytes=1 << 14)
    assert s is None or (s % 32 == 0 and s >= 32)
    with pytest.raises(ValueError, match="lane_slab requires"):
        list(
            evaluator.full_domain_evaluate_chunks(
                dpf, keys, mode="levels", lane_slab=32
            )
        )
    with pytest.raises(ValueError, match="multiple of 32"):
        list(
            evaluator.full_domain_evaluate_chunks(
                dpf, keys, mode="fused", lane_slab=17
            )
        )


@pytest.mark.slow
def test_fused_lane_slab_codec_non_pow2_epb_exact_partition():
    """Regression (ADVICE r2): with lane_slab and a codec value type whose
    elements_per_block is NOT a power of two (Tuple<u32,u8> -> epb=3), the
    pieces must still partition the domain exactly — keep_per_block is
    2^(lds - stop_level), so m_lanes * 2^device_levels * keep == 2^lds and
    no piece overshoots (guarded by an assert in the slab loop)."""
    from distributed_point_functions_tpu.core.value_types import TupleType

    t = TupleType([Int(32), Int(8)])
    dpf = DistributedPointFunction.create(DpfParameters(12, t))
    assert t.elements_per_block() == 3
    keys, _ = dpf.generate_keys_batch([5, 4000], [[(7, 3), (9, 1)]])

    def run(lane_slab, host_levels):
        per_piece = []
        for v, out in evaluator.full_domain_evaluate_chunks(
            dpf, keys, mode="fused", lane_slab=lane_slab,
            host_levels=host_levels,
        ):
            per_piece.append(tuple(np.asarray(o) for o in out))
        return [
            np.concatenate([p[c] for p in per_piece], axis=1)
            for c in range(len(per_piece[0]))
        ]

    sliced = run(32, 6)  # 2 pieces per chunk
    plain = run(None, None)
    assert sliced[0].shape[1] == 1 << 12  # pieces cover the domain exactly
    for a, b in zip(sliced, plain):
        np.testing.assert_array_equal(a, b)


def test_fused_auto_slab_protects_by_default(monkeypatch):
    """With DPF_TPU_MAX_PROGRAM_BYTES set and no explicit sizing, fused
    mode auto-slabs programs over the budget (opt-in protection on
    platforms that miscompute oversized programs) and the pieces
    reassemble bit-exactly; budget 0 / unset disables it."""
    dpf = DistributedPointFunction.create(DpfParameters(9, Int(64)))
    keys, _ = dpf.generate_keys_batch([5], [[9]])
    monkeypatch.setenv("DPF_TPU_MAX_PROGRAM_BYTES", str(1 << 11))
    pieces = list(
        evaluator.full_domain_evaluate_chunks(dpf, keys, key_chunk=1, mode="fused")
    )
    assert len(pieces) > 1
    full = np.concatenate([np.asarray(o) for _, o in pieces], axis=1)
    monkeypatch.setenv("DPF_TPU_MAX_PROGRAM_BYTES", "0")
    ((v0, out0),) = list(
        evaluator.full_domain_evaluate_chunks(dpf, keys, key_chunk=1, mode="fused")
    )
    assert v0 == 1
    np.testing.assert_array_equal(full, np.asarray(out0))


@pytest.mark.slow
def test_full_domain_fold_chunks_matches_values_fold():
    """The in-program XOR fold (full_domain_fold_chunks — values
    materialized behind an optimization_barrier and consumed in-program,
    tiny output) equals folding the full value output, for additive and
    XOR groups, including the padded last chunk."""
    for vt, betas in ((Int(64), [9, 8, 7]), (XorWrapper(128), [9, 8, 7])):
        dpf = DistributedPointFunction.create(DpfParameters(9, vt))
        keys, _ = dpf.generate_keys_batch([5, 77, 300], [betas])
        vals = evaluator.full_domain_evaluate(dpf, keys)
        want = np.bitwise_xor.reduce(vals, axis=1)
        got = []
        for valid, fold in evaluator.full_domain_fold_chunks(
            dpf, keys, key_chunk=2
        ):
            got.append(np.asarray(fold)[:valid])
        np.testing.assert_array_equal(np.concatenate(got), want)
    # codec types and tiny domains are rejected, not silently mis-folded
    dpf_small = DistributedPointFunction.create(DpfParameters(3, Int(64)))
    ks, _ = dpf_small.generate_keys_batch([1], [[2]])
    with pytest.raises(NotImplementedError, match="depth >= 5"):
        list(evaluator.full_domain_fold_chunks(dpf_small, ks))
    from distributed_point_functions_tpu.core.value_types import IntModN

    dpf_modn = DistributedPointFunction.create(
        DpfParameters(9, IntModN(64, (1 << 64) - 59))
    )
    km, _ = dpf_modn.generate_keys_batch([1], [[2]])
    with pytest.raises(NotImplementedError, match="scalar Int/XorWrapper"):
        list(evaluator.full_domain_fold_chunks(dpf_modn, km))
