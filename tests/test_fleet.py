"""Fleet-tier pins over real loopback sockets (ISSUE 14).

All service tests run in-process ``DpfServer`` replicas with
``engine="host"`` behind the REAL :class:`FleetProxy` — the full
frame-relay / affinity-routing / failover path with zero XLA programs and
zero new compiles (the wire-suite budget discipline; the
zero-added-device-programs pin lives in tests/test_dispatch_audit.py).
The routing-digest and stats-merge units are pure wire-format tests.
"""

import time

import numpy as np
import pytest

from distributed_point_functions_tpu import serving
from distributed_point_functions_tpu.core import host_eval
from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
from distributed_point_functions_tpu.core.params import DpfParameters
from distributed_point_functions_tpu.core.value_types import Int
from distributed_point_functions_tpu.serving import wire
from distributed_point_functions_tpu.serving.fleet import _rendezvous_score
from distributed_point_functions_tpu.utils import telemetry
from distributed_point_functions_tpu.utils.errors import UnavailableError

PARAMS = [DpfParameters(8, Int(64))]
FAST = serving.RetryPolicy(
    attempts=4, base_backoff=0.01, max_backoff=0.05, connect_attempts=3,
    connect_backoff=0.05, attempt_timeout=10.0, seed=0,
)


def _wait_until(pred, timeout=30.0, interval=0.02, msg="condition"):
    """Deflake primitive (ISSUE 20): poll an observable predicate with a
    bounded deadline instead of sleeping a guessed duration — loopback
    timing under CI load is exactly what the guessed durations lost to.
    Returns the first truthy pred() value."""
    t_end = time.perf_counter() + timeout
    while True:
        out = pred()
        if out:
            return out
        if time.perf_counter() >= t_end:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(interval)


def _probe_all(proxy):
    """One synchronous probe sweep; returns the per-replica aliveness."""
    with proxy._lock:
        replicas = list(proxy._replicas)
    for r in replicas:
        proxy._probe(r)
    with proxy._lock:
        return [r.alive for r in replicas]


def _rendezvous_owner(proxy, digest) -> str:
    """The endpoint affinity will route `digest` to when every replica
    is alive — deterministic owner identification, instead of inferring
    the owner from routed counts that a client retry can skew."""
    with proxy._lock:
        keys = [r.key for r in proxy._replicas]
    return max(keys, key=lambda k: _rendezvous_score(digest, k))


@pytest.fixture(scope="module")
def dpf():
    return DistributedPointFunction.create(PARAMS[0])


@pytest.fixture(scope="module")
def keys(dpf):
    return dpf.generate_keys_batch([3, 70, 201], [[5, 9, 40]])


@pytest.fixture()
def fleet():
    """Two in-process host-engine replicas behind a FleetProxy. The
    probe interval is long: tests that want request-path death detection
    must not race the probe loop; tests that want the probe call
    proxy._probe themselves.

    Both replicas are probed into the candidate set BEFORE the fixture
    yields: the proxy reports ready while ANY replica is alive, so a
    request sent in the half-alive window routes wherever happens to be
    up — the loopback-timing flake that made the affinity/failover pins
    fail under CI load while passing in isolation."""
    servers = [
        serving.DpfServer(engine="host", max_wait_ms=1.0).start()
        for _ in range(2)
    ]
    proxy = serving.FleetProxy(
        [("127.0.0.1", s.port) for s in servers], probe_interval=60.0,
    ).start()
    _wait_until(
        lambda: all(_probe_all(proxy)),
        msg="both replicas alive in the proxy's candidate set",
    )
    yield servers, proxy
    proxy.stop()
    for s in servers:
        s.stop()


@pytest.fixture()
def client(fleet):
    _, proxy = fleet
    c = serving.DpfClient("127.0.0.1", proxy.port, policy=FAST)
    c.wait_ready(timeout=30)
    yield c
    c.close()


# ---------------------------------------------------------------------------
# Routing digest (pure wire-format)
# ---------------------------------------------------------------------------


def test_routing_digest_key_independent_for_merged_ops(dpf, keys):
    """Two clients' DIFFERENT keys for the same parameters must share a
    digest — they can merge into one replica batch, and splitting them
    across replicas would forfeit exactly the batching the front door
    exists for."""
    k0s, k1s = keys
    a = wire.routing_digest(
        "evaluate_at", wire.encode_evaluate_at(PARAMS, [k0s[0]], [1, 2])
    )
    b = wire.routing_digest(
        "evaluate_at", wire.encode_evaluate_at(PARAMS, [k1s[2]], [7])
    )
    assert a == b
    # ... but a different hierarchy level is a different program family.
    c = wire.routing_digest(
        "evaluate_at", wire.encode_evaluate_at(PARAMS, [k0s[0]], [1], 0)
    )
    assert c != a
    # ... and a different op never collides by construction.
    d = wire.routing_digest(
        "full_domain", wire.encode_full_domain(PARAMS, [k0s[0]])
    )
    assert d != a


def test_routing_digest_pir_keys_on_database(dpf, keys):
    """PIR requests route on the database name (the PreparedPirDatabase
    warm tier), not on key material."""
    k0s, _ = keys
    a = wire.routing_digest("pir", wire.encode_pir(PARAMS, [k0s[0]], "db-a"))
    b = wire.routing_digest("pir", wire.encode_pir(PARAMS, [k0s[1]], "db-a"))
    c = wire.routing_digest("pir", wire.encode_pir(PARAMS, [k0s[0]], "db-b"))
    assert a == b and a != c


def test_routing_digest_mic_keys_per_key(dpf):
    """Gate requests route per key (their compatibility queues are
    per-key anyway, so spreading keys buys load balance for free)."""
    from distributed_point_functions_tpu.gates.mic import (
        MultipleIntervalContainmentGate,
    )

    gate = MultipleIntervalContainmentGate.create(6, [(2, 10), (20, 40)])
    ka, _ = gate.gen(5, [3, 7])
    kb, _ = gate.gen(9, [1, 2])
    a = wire.routing_digest("mic", wire.encode_mic(6, gate.intervals, ka, [1]))
    b = wire.routing_digest("mic", wire.encode_mic(6, gate.intervals, kb, [1]))
    assert a != b


def test_rendezvous_rehash_is_minimal():
    """The rendezvous property the failover design leans on: removing
    one replica re-homes ONLY the digests it owned — every other
    digest's winner is unchanged (no global reshuffle on death)."""
    replicas = [f"127.0.0.1:{9000 + i}" for i in range(4)]
    digests = [f"digest-{i:03d}" for i in range(200)]

    def winner(pool, d):
        return max(pool, key=lambda r: _rendezvous_score(d, r))

    before = {d: winner(replicas, d) for d in digests}
    dead = replicas[1]
    survivors = [r for r in replicas if r != dead]
    for d in digests:
        after = winner(survivors, d)
        if before[d] == dead:
            assert after != dead
        else:
            assert after == before[d], "unrelated digest re-homed"


# ---------------------------------------------------------------------------
# Stats merge (the backward-compat satellite)
# ---------------------------------------------------------------------------


def test_merge_stats_sums_and_tolerates_old_bodies():
    """A pre-fleet stats body (no ISSUE 14 keys) merges with a new one:
    the new keys are additive in both directions — old clients ignore
    them, old servers simply don't contribute."""
    old_body = {
        "wall_seconds": 10.0,
        "counters": {"rpc.server.requests[dcf]": 3},
        "gauges": {"serving.queue_depth": {"last": 2, "max": 5}},
        "decisions_by_source": {"router": 1},
        "integrity_by_kind": {},
    }
    new_body = {
        "wall_seconds": 12.0,
        "counters": {"rpc.server.requests[dcf]": 4},
        "gauges": {"serving.queue_depth": {"last": 1, "max": 2}},
        "decisions_by_source": {"router": 2},
        "integrity_by_kind": {},
        "queues": {"dcf": 6},
        "inflight": 2,
        "served": 40,
        "warm": {"pir": ["abc"], "plans": [], "keys": ["def"]},
    }
    merged = wire.merge_stats([old_body, new_body])
    assert merged["wall_seconds"] == 12.0
    assert merged["counters"]["rpc.server.requests[dcf]"] == 7
    assert merged["gauges"]["serving.queue_depth"] == {"last": 3, "max": 7}
    assert merged["queues"] == {"dcf": 6}
    assert merged["inflight"] == 2 and merged["served"] == 40
    assert merged["warm"]["pir"] == ["abc"]


def test_stats_body_new_keys_are_additive():
    """The ISSUE 14 stats keys ride the EXISTING JSON body — re-encoding
    a body without them is byte-stable, and a consumer reading only the
    pre-fleet keys sees identical values with or without them."""
    import json

    base = {"wall_seconds": 1.0, "counters": {"x": 1}, "gauges": {}}
    extended = dict(
        base, queues={"dcf": 1}, inflight=0, served=9,
        warm={"pir": [], "plans": [], "keys": []},
    )
    assert set(wire.STATS_FLEET_KEYS) == set(extended) - set(base)
    # An old consumer's view of the extended body == the base body.
    old_view = {k: extended[k] for k in base}
    assert old_view == base
    # And re-encode stability: the base body round-trips byte-identical.
    blob = json.dumps(base, sort_keys=True).encode()
    assert json.dumps(json.loads(blob), sort_keys=True).encode() == blob


# ---------------------------------------------------------------------------
# End-to-end over loopback
# ---------------------------------------------------------------------------


def test_fleet_bit_exact_and_aggregated_probes(fleet, client, dpf, keys):
    k0s, _ = keys
    pts = [0, 3, 70, 201, 255]
    got = client.evaluate_at(PARAMS, list(k0s), pts, deadline=30)
    want = host_eval.values_to_limbs(
        host_eval.evaluate_at_host(dpf, list(k0s), pts, 0), 64
    )
    assert np.array_equal(got, want)
    h = client.health()
    assert h["ready"] and h["fleet"]["size"] == 2
    st = client.stats()
    # The merged replica counters + the fleet routing section. The
    # pre-ISSUE 20 form of this assertion was order-flaky: on a warm
    # process the whole request + poll fits inside STATS_FRESHNESS of
    # the fixture's setup probes, and the proxy served back the cached
    # PRE-request body. The proxy now re-probes any replica whose cache
    # predates its last relayed completion, so counters a caller just
    # caused are always visible.
    assert st["fleet"]["counters"]["requests"] >= 1
    assert sum(
        v for k, v in st["counters"].items()
        if k.startswith("rpc.server.requests")
    ) >= 1
    # The ISSUE 14 stats fields arrive through the proxy too.
    for key in wire.STATS_FLEET_KEYS:
        assert key in st, key


def test_affinity_keeps_a_family_on_one_replica(fleet, client, dpf, keys):
    """Same-parameter requests share a routing digest, so they all land
    on ONE replica — where they can merge into one batch and share its
    warm tiers. The other replica serves nothing. The owner is computed
    from the rendezvous hash (not inferred from counts), and the counts
    are lower-bounded (a client retry may add a routed request) — the
    deflaked form of the PR 17/18/19 exact-count pin."""
    _, proxy = fleet
    k0s, _ = keys
    digest = wire.routing_digest(
        "evaluate_at", wire.encode_evaluate_at(PARAMS, [k0s[0]], [1, 2])
    )
    owner_key = _rendezvous_owner(proxy, digest)
    for _ in range(6):
        client.evaluate_at(PARAMS, [k0s[0]], [1, 2], deadline=30)
    st = client.stats()
    by_key = {r["endpoint"]: r["routed"] for r in st["fleet"]["replicas"]}
    assert by_key[owner_key] >= 6, by_key
    assert sum(v for k, v in by_key.items() if k != owner_key) == 0, by_key
    assert st["fleet"]["counters"]["affinity_hits"] >= 6


def test_failover_rides_the_client_retry_budget(fleet, client, dpf, keys):
    """The pinned client-failover contract: a replica killed under a
    warm digest range costs the caller ZERO visible errors — the proxy
    answers UNAVAILABLE (retryable), the client's existing retry budget
    carries the call, and the retry lands on the surviving replica
    because the dead one left the candidate set synchronously."""
    servers, proxy = fleet
    k0s, _ = keys
    pts = [0, 3, 70]
    want = host_eval.values_to_limbs(
        host_eval.evaluate_at_host(dpf, [k0s[0]], pts, 0), 64
    )
    got = client.evaluate_at(PARAMS, [k0s[0]], pts, deadline=30)
    assert np.array_equal(got, want)
    # The digest owner is computed, not inferred from routed counts (a
    # retry in the warm-up request would have made the inference pick
    # the wrong replica and the kill a no-op — one of the flake modes).
    digest = wire.routing_digest(
        "evaluate_at", wire.encode_evaluate_at(PARAMS, [k0s[0]], pts)
    )
    owner_key = _rendezvous_owner(proxy, digest)
    owner = next(s for s in servers if owner_key.endswith(f":{s.port}"))
    owner.stop()
    with telemetry.capture() as cap:
        got = client.evaluate_at(PARAMS, [k0s[0]], pts, deadline=30)
    assert np.array_equal(got, want)  # zero caller-visible errors
    snap = cap.snapshot()
    retries = sum(
        v for k, v in snap["counters"].items()
        if k.startswith("rpc.client.retries")
    )
    assert retries >= 1
    # No reconnect-budget walk: the proxy stayed up, so the client never
    # had to redial — a counter assertion instead of the wall-clock
    # bound (dt < 5) that lost to CI load.
    reconnects = sum(
        v for k, v in snap["counters"].items()
        if k.startswith("rpc.client.reconnects")
    )
    assert reconnects == 0, snap["counters"]
    st = client.stats()
    assert st["fleet"]["counters"]["failovers"] >= 1
    dead = [r for r in st["fleet"]["replicas"] if r["endpoint"] == owner_key]
    assert dead[0]["alive"] is False


def test_probe_revives_a_restarted_replica_and_affinity_rehomes(
    fleet, client, dpf, keys
):
    """Drain + re-hash, both directions: a dead replica's digest range
    re-homes to the survivor; a replica revived ON THE SAME PORT wins
    its range back (rendezvous keys on host:port), so warm-tier reuse
    resumes — the counter the fleet soak also asserts."""
    servers, proxy = fleet
    k0s, _ = keys
    client.evaluate_at(PARAMS, [k0s[0]], [1], deadline=30)
    digest = wire.routing_digest(
        "evaluate_at", wire.encode_evaluate_at(PARAMS, [k0s[0]], [1])
    )
    owner_key = _rendezvous_owner(proxy, digest)
    owner_i = next(
        i for i, s in enumerate(servers) if owner_key.endswith(f":{s.port}")
    )
    port = servers[owner_i].port
    servers[owner_i].stop()
    # Probe until the death is OBSERVED (one sweep can race the
    # listener teardown on a loaded machine — the flake).
    _wait_until(
        lambda: not dict(
            zip([r.key for r in proxy._replicas], _probe_all(proxy))
        )[owner_key],
        msg="the probe loop observing the owner's death",
    )
    # Re-hash: the survivor owns the digest now.
    client.evaluate_at(PARAMS, [k0s[0]], [1], deadline=30)
    st = client.stats()
    by_key = {r["endpoint"]: r for r in st["fleet"]["replicas"]}
    assert by_key[owner_key]["alive"] is False
    survivor_routed = sum(
        r["routed"] for r in st["fleet"]["replicas"]
        if r["endpoint"] != owner_key
    )
    assert survivor_routed >= 1
    # Revive on the SAME port: the range re-homes back.
    servers[owner_i] = serving.DpfServer(
        engine="host", max_wait_ms=1.0, port=port,
    ).start()
    _wait_until(
        lambda: all(_probe_all(proxy)),
        msg="the revived replica re-entering the candidate set",
    )
    base = {r.key: r.routed for r in proxy._replicas}[owner_key]
    for _ in range(3):
        client.evaluate_at(PARAMS, [k0s[0]], [1], deadline=30)
    st = client.stats()
    # Lower-bounded, not exact: a client retry adds a routed request.
    assert {
        r["endpoint"]: r["routed"] for r in st["fleet"]["replicas"]
    }[owner_key] >= base + 3


def test_whole_fleet_down_is_unavailable_not_a_hang(dpf, keys):
    k0s, _ = keys
    srv = serving.DpfServer(engine="host", max_wait_ms=1.0).start()
    proxy = serving.FleetProxy(
        [("127.0.0.1", srv.port)], probe_interval=60.0,
    ).start()
    cli = serving.DpfClient("127.0.0.1", proxy.port, policy=FAST)
    cli.wait_ready(timeout=30)
    srv.stop()
    t0 = time.perf_counter()
    with pytest.raises(UnavailableError):
        cli.evaluate_at(PARAMS, [k0s[0]], [1], deadline=10)
    assert time.perf_counter() - t0 < 8  # bounded by the retry budget
    st = cli.stats()
    assert st["fleet"]["counters"]["no_replica"] >= 1
    cli.close()
    proxy.stop()
    srv.stop()


def test_spill_overrides_a_hot_affinity_winner(fleet, dpf, keys):
    """A hot digest must not melt one replica while the other idles:
    when the winner's load runs spill_margin past the least-loaded, the
    request spills (counted)."""
    _, proxy = fleet
    k0s, _ = keys
    for r in proxy._replicas:  # deterministic: don't race the probe loop
        proxy._probe(r)
    # Make the rendezvous winner for this digest look overloaded.
    digest = wire.routing_digest(
        "evaluate_at", wire.encode_evaluate_at(PARAMS, [k0s[0]], [1])
    )
    winner = max(
        proxy._replicas, key=lambda r: _rendezvous_score(digest, r.key)
    )
    with proxy._lock:
        winner.pending = proxy.spill_margin + 5
    picked = proxy._pick(digest)
    try:
        assert picked is not winner
        assert proxy.counters["spills"] == 1
    finally:
        proxy._release(picked)
        with proxy._lock:
            winner.pending = 0


# ---------------------------------------------------------------------------
# Elastic membership (ISSUE 20: the autoscaler's seams)
# ---------------------------------------------------------------------------


def test_retiring_replica_takes_no_new_requests(fleet, client, dpf, keys):
    """The graceful-drain half of scale-down: a retiring replica leaves
    the candidate set (new requests route to the survivor) without being
    marked dead — and un-retiring wins its digest range straight back."""
    _, proxy = fleet
    k0s, _ = keys
    digest = wire.routing_digest(
        "evaluate_at", wire.encode_evaluate_at(PARAMS, [k0s[0]], [1])
    )
    owner_key = _rendezvous_owner(proxy, digest)
    host, port = owner_key.split(":")
    assert proxy.set_retiring(host, int(port), True)
    client.evaluate_at(PARAMS, [k0s[0]], [1], deadline=30)
    st = client.stats()
    by_key = {r["endpoint"]: r for r in st["fleet"]["replicas"]}
    assert by_key[owner_key]["retiring"] is True
    assert by_key[owner_key]["alive"] is True  # drained, not dead
    assert by_key[owner_key]["routed"] == 0
    assert proxy.set_retiring(host, int(port), False)
    base = by_key[owner_key]["routed"]
    client.evaluate_at(PARAMS, [k0s[0]], [1], deadline=30)
    st = client.stats()
    by_key = {r["endpoint"]: r for r in st["fleet"]["replicas"]}
    assert by_key[owner_key]["routed"] >= base + 1


def test_add_and_remove_replica_resize_the_candidate_set(dpf, keys):
    """add_replica pulls a new endpoint into the fleet within one probe;
    remove_replica is refused while the proxy tracks in-flight work on
    it and re-hashes the range away once drained."""
    k0s, _ = keys
    a = serving.DpfServer(engine="host", max_wait_ms=1.0).start()
    proxy = serving.FleetProxy(
        [("127.0.0.1", a.port)], probe_interval=60.0,
    ).start()
    b = None
    try:
        _wait_until(lambda: all(_probe_all(proxy)), msg="replica a alive")
        assert proxy._health()["fleet"]["size"] == 1
        b = serving.DpfServer(engine="host", max_wait_ms=1.0).start()
        proxy.add_replica("127.0.0.1", b.port)  # probes immediately
        h = proxy.health()
        assert h["fleet"]["size"] == 2
        assert all(r["alive"] for r in h["fleet"]["replicas"])
        assert proxy.counters["replicas_added"] == 1
        # Refusal while in-flight: simulate one tracked request.
        with proxy._lock:
            rb = next(r for r in proxy._replicas if r.port == b.port)
            rb.inflight += 1
        assert proxy.remove_replica("127.0.0.1", b.port) is False
        with proxy._lock:
            rb.inflight -= 1
        assert proxy.remove_replica("127.0.0.1", b.port) is True
        assert proxy.health()["fleet"]["size"] == 1
        assert proxy.remove_replica("127.0.0.1", b.port) is False  # unknown
    finally:
        proxy.stop()
        a.stop()
        if b is not None:
            b.stop()


def test_autoscaler_in_process_scale_up_and_drain_down(dpf, keys):
    """The full ISSUE 20 loop against real servers and a real proxy: a
    forced-high backlog signal adds a replica (which serves), a
    forced-low signal drains one down gracefully (zero caller-visible
    errors), and the next scale-up revives the SAME remembered port so
    the rendezvous range comes home. Only the SIGNAL is stubbed — the
    stats-path signal itself is asserted separately at zero load."""
    from distributed_point_functions_tpu.serving.autoscale import AutoScaler

    class _InProcessPool:
        """ReplicaPool's scaling surface over in-process DpfServers."""

        def __init__(self):
            self.servers = [
                serving.DpfServer(engine="host", max_wait_ms=1.0).start()
            ]
            self.ports = [self.servers[0].port]

        def running_indices(self):
            return [
                i for i, s in enumerate(self.servers) if s is not None
            ]

        def scale_up(self, timeout=180.0):
            for i, s in enumerate(self.servers):
                if s is None:
                    srv = serving.DpfServer(
                        engine="host", max_wait_ms=1.0, port=self.ports[i],
                    ).start()
                    self.servers[i] = srv
                    return i, srv.port, False
            srv = serving.DpfServer(engine="host", max_wait_ms=1.0).start()
            self.servers.append(srv)
            self.ports.append(srv.port)
            return len(self.servers) - 1, srv.port, True

        def scale_down(self, i, timeout=30.0):
            s, self.servers[i] = self.servers[i], None
            if s is not None:
                s.stop()  # the in-process stand-in for SIGTERM drain

        def stop(self):
            for s in self.servers:
                if s is not None:
                    s.stop()

    k0s, _ = keys
    pool = _InProcessPool()
    proxy = serving.FleetProxy(
        [("127.0.0.1", pool.ports[0])], probe_interval=60.0,
    ).start()
    cli = serving.DpfClient("127.0.0.1", proxy.port, policy=FAST)
    try:
        _wait_until(lambda: all(_probe_all(proxy)), msg="seed replica alive")
        cli.wait_ready(timeout=30)
        sc = AutoScaler(
            proxy, pool, plane="eval", min_replicas=1, max_replicas=2,
            up_backlog=10.0, down_backlog=1.0, sustain=1, cooldown=0.0,
            drain_timeout=10.0,
        )
        # The real stats-path signal at zero load.
        assert sc.backlog() == 0.0
        # Scale-up: forced-high signal, one poll (sustain=1).
        sc.backlog = lambda: 50.0
        assert sc.poll_once() == "up"
        assert len(pool.running_indices()) == 2
        _wait_until(lambda: all(_probe_all(proxy)), msg="grown fleet alive")
        assert proxy.health()["fleet"]["size"] == 2
        cli.evaluate_at(PARAMS, [k0s[0]], [1], deadline=30)
        # Drain-down: forced-low signal; zero caller-visible errors after.
        sc.backlog = lambda: 0.0
        assert sc.poll_once() == "down"
        assert len(pool.running_indices()) == 1
        cli.evaluate_at(PARAMS, [k0s[0]], [1], deadline=30)
        retired_ports = [
            r.port for r in proxy._replicas if r.retiring
        ]
        assert len(retired_ports) == 1
        # Scale-up again: the remembered port revives (rendezvous range
        # comes home) and the proxy un-retires it.
        sc.backlog = lambda: 50.0
        assert sc.poll_once() == "up"
        assert len(pool.running_indices()) == 2
        assert retired_ports[0] in pool.ports
        assert not any(r.retiring for r in proxy._replicas)
        _wait_until(lambda: all(_probe_all(proxy)), msg="revived fleet alive")
        cli.evaluate_at(PARAMS, [k0s[0]], [1], deadline=30)
        assert sc.stats()["ups"] == 2 and sc.stats()["downs"] == 1
    finally:
        cli.close()
        proxy.stop()
        pool.stop()
