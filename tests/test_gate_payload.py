"""Vector-payload gate codec: bit-exactness, wire pins, and the perf
acceptance (ISSUE 18).

The vector codec packs a gate's m(d+1) spline coefficients into ONE DCF
key with a uniform Int(w) tuple value type (w = the narrowest of
{32, 64, 128} that holds the group), evaluated in ONE batched-DCF pass.
This suite pins it against three oracles:

* the scalar-flattened layout (one DCF key per shifted coefficient),
* the exact-integer plaintext gate function,
* the serialized wire bytes (packed VectorDcfKey form, and the
  1-element degeneration that must stay byte-identical to scalar).

Device-engine coverage rides the cheap ReLU shape (log_group_size=6,
w=32); the wide sigmoid/tanh gates are exercised through the host AES
engine and the per-point evaluator so the matrix stays inside the fast
tier.
"""

import hashlib

import numpy as np
import pytest

from distributed_point_functions_tpu import gates, serving
from distributed_point_functions_tpu.gates import framework
from distributed_point_functions_tpu.protos import serialization as ser


def _params(gate):
    return gate.dcf.dpf.validator.parameters


def _reconstruct(gate, k0, k1, x, r_out, engine):
    e0 = gate.batch_eval(k0, [x], engine=engine)
    e1 = gate.batch_eval(k1, [x], engine=engine)
    return gate.to_signed((int(e0[0][0]) + int(e1[0][0]) - r_out) % gate.n)


# ---------------------------------------------------------------------------
# Bit-exactness: vector vs scalar oracle vs exact-int plaintext
# ---------------------------------------------------------------------------


def test_relu_vector_edge_matrix_host():
    """The PR 9 edge matrix on both payload arms: r_in at the wrap
    points, x_real at the interval endpoints, both parties contributing.
    Vector and scalar reconstructions must equal the exact-int plaintext
    gate for every cell."""
    n = 1 << 6
    gv = gates.ReluGate.create(6, payload="vector")
    gs = gates.ReluGate.create(6, payload="scalar")
    assert gv.num_components == 1 and gs.num_components == 4
    r_out = 5
    for r_in in (0, 1, n // 2, n - 1):
        kv0, kv1 = gv.gen(r_in, [r_out])
        ks0, ks1 = gs.gen(r_in, [r_out])
        for xr in (-(n // 2), -(n // 2) + 1, -1, 0, 1, n // 2 - 1):
            x = (gv.signed_lift(xr) + r_in) % n
            want = max(0, xr)
            got_v = _reconstruct(gv, kv0, kv1, x, r_out, "host")
            got_s = _reconstruct(gs, ks0, ks1, x, r_out, "host")
            assert got_v == want, (r_in, xr, got_v)
            assert got_s == want, (r_in, xr, got_s)


def test_relu_vector_device_engine():
    """Device engine (the jax batched walk with the tuple capture tail)
    agrees with the host engine and the per-point evaluator on the
    vector arm."""
    n = 1 << 6
    gv = gates.ReluGate.create(6, payload="vector")
    r_in, r_out = 13, 7
    k0, k1 = gv.gen(r_in, [r_out])
    xs = [(gv.signed_lift(xr) + r_in) % n for xr in (-5, 0, 11)]
    dev0 = gv.batch_eval(k0, xs, engine="device")
    host0 = gv.batch_eval(k0, xs, engine="host")
    assert np.array_equal(np.asarray(dev0), np.asarray(host0))
    for x, row in zip(xs, dev0):
        assert list(gv.eval(k0, x)) == [int(v) for v in row]
    dev1 = gv.batch_eval(k1, xs, engine="device")
    for xr, r0, r1 in zip((-5, 0, 11), dev0, dev1):
        got = gv.to_signed((int(r0[0]) + int(r1[0]) - r_out) % n)
        assert got == max(0, xr)


@pytest.mark.parametrize("cls", [gates.SigmoidGate, gates.TanhGate])
def test_wide_spline_vector_bit_exact(cls):
    """8-piece degree-1 sigmoid/tanh on the vector codec: ONE component
    key whose reconstruction equals both the scalar oracle and the
    exact-int plaintext spline, across parties and the wrap mask. The
    point set hits every piece's interval endpoints (raw mod-N domain —
    negative fixed-point inputs ride two's complement)."""
    gv = cls.create(12, payload="vector")
    gs = cls.create(12, payload="scalar")
    assert gv.num_components == 1 and gs.num_components == 16
    n = gv.n
    r_out = 3
    endpoints = sorted({e for pq in gv.intervals for e in pq})
    for r_in in (0, n - 1):
        kv0, kv1 = gv.gen(r_in, [r_out])
        ks0, ks1 = gs.gen(r_in, [r_out])
        for x_raw in endpoints:
            x = (x_raw + r_in) % n
            want = gv.plaintext(x_raw)
            assert gs.plaintext(x_raw) == want
            e0 = gv.batch_eval(kv0, [x], engine="host")
            e1 = gv.batch_eval(kv1, [x], engine="host")
            got_v = (int(e0[0][0]) + int(e1[0][0]) - r_out) % n
            s0 = gs.batch_eval(ks0, [x], engine="host")
            s1 = gs.batch_eval(ks1, [x], engine="host")
            got_s = (int(s0[0][0]) + int(s1[0][0]) - r_out) % n
            assert got_v == want, (r_in, x_raw)
            assert got_s == want, (r_in, x_raw)


def test_vector_bundle_eval():
    """bundle_eval fuses B tuple-payload keys into one pass and each
    bundle element still reconstructs exactly."""
    n = 1 << 6
    gv = gates.ReluGate.create(6, payload="vector")
    r_ins, r_out = [3, 40, 63], 9
    pairs = [gv.gen(r, [r_out]) for r in r_ins]
    xrs = [-7, 0, 20]
    xs = [(gv.signed_lift(xr) + r) % n for xr, r in zip(xrs, r_ins)]
    out0 = framework.bundle_eval(gv, [p[0] for p in pairs], xs, engine="host")
    out1 = framework.bundle_eval(gv, [p[1] for p in pairs], xs, engine="host")
    for xr, r0, r1 in zip(xrs, out0, out1):
        got = gv.to_signed((int(r0[0]) + int(r1[0]) - r_out) % n)
        assert got == max(0, xr)


# ---------------------------------------------------------------------------
# Wire pins
# ---------------------------------------------------------------------------


def test_one_element_vector_key_byte_identical_to_scalar():
    """A 1-element vector gate degenerates to a scalar Int(128) DCF by
    construction, so its serialized GateKey must be BYTE-IDENTICAL to
    the scalar arm's — the packed VectorDcfKey form only ever applies to
    true tuples (the MIC-superset wire pin survives the codec)."""
    gv = gates.SplineGate.create(6, [(0, 31)], [[5]], payload="vector")
    gs = gates.SplineGate.create(6, [(0, 31)], [[5]], payload="scalar")
    assert gv.num_components == 1 and gs.num_components == 1
    kv = gv.gen(3, [9], prng=gates.CounterRng(b"pin"), dcf_seeds=[(1, 2)])
    ks = gs.gen(3, [9], prng=gates.CounterRng(b"pin"), dcf_seeds=[(1, 2)])
    for v, s in zip(kv, ks):
        assert ser.serialize_gate_key(v, _params(gv)) == ser.serialize_gate_key(
            s, _params(gs)
        )


def test_vector_gate_golden_digest():
    """gen() on the vector arm with an injected CounterRng + pinned DCF
    seeds is deterministic and its serialized fingerprint is pinned —
    the vector twin of the scalar golden in test_gates_framework.py.
    Changes only if the tuple keygen algebra or the packed wire format
    changes; regenerate deliberately."""
    gate = gates.ReluGate.create(8, payload="vector")
    seeds = [(0x1111111122222222, 0x3333333344444444)]

    def make():
        return gate.gen(
            77, [5], prng=gates.CounterRng(seed=b"relu-golden"),
            dcf_seeds=seeds,
        )

    k0_a, k1_a = make()
    k0_b, k1_b = make()
    assert k0_a == k0_b and k1_a == k1_b
    blob = ser.serialize_gate_key(k0_a, _params(gate))
    assert hashlib.sha256(blob).hexdigest() == (
        "15bb02fda75426a610e78068677656e448fce6d69cb46c292e4fe8608f8feead"
    )
    n = gate.n
    for xr in (-100, -1, 0, 1, 100):
        x = (gate.signed_lift(xr) + 77) % n
        e0 = gate.eval(k0_a, x)
        e1 = gate.eval(k1_a, x)
        assert gate.to_signed((e0[0] + e1[0] - 5) % n) == max(0, xr)


def test_packed_vector_key_roundtrip():
    """The packed VectorDcfKey wire form round-trips field-exactly and
    the parsed key evaluates identically to the original."""
    gv = gates.SigmoidGate.create(12, payload="vector")
    k0, _ = gv.gen(7, [3])
    blob = ser.serialize_gate_key(k0, _params(gv))
    back = ser.parse_gate_key(blob)
    assert back.mask_shares == k0.mask_shares
    a, b = back.dcf_keys[0].key, k0.dcf_keys[0].key
    assert (a.seed, a.party) == (b.seed, b.party)
    assert a.last_level_value_correction == b.last_level_value_correction
    assert len(a.correction_words) == len(b.correction_words)
    for ca, cb in zip(a.correction_words, b.correction_words):
        assert (ca.seed, ca.control_left, ca.control_right,
                ca.value_correction) == (
            cb.seed, cb.control_left, cb.control_right, cb.value_correction)
    for x in (0, 1, 2048, 4095):
        assert gv.eval(back, x) == gv.eval(k0, x)


# ---------------------------------------------------------------------------
# Merge safety
# ---------------------------------------------------------------------------


def test_scalar_vector_requests_never_merge():
    """A scalar-payload gate batch and a vector-payload gate batch land
    in DIFFERENT batcher queues: merging them would hand one program a
    mix of Int(128) scalar keys and Int(w)-tuple keys."""
    gv = gates.ReluGate.create(6, payload="vector")
    gs = gates.ReluGate.create(6, payload="scalar")
    kv, _ = gv.gen(11, [3])
    ks, _ = gs.gen(11, [3])
    sig_v = serving.Request.gate(gv, kv, [5]).signature()
    sig_s = serving.Request.gate(gs, ks, [5]).signature()
    assert sig_v != sig_s
    # same-config requests on the same arm DO share a queue
    assert sig_v == serving.Request.gate(gv, kv, [9]).signature()


# ---------------------------------------------------------------------------
# Perf acceptance: >= 8x key bytes AND >= 8x DCF walks (8-piece sigmoid)
# ---------------------------------------------------------------------------


def test_sigmoid_key_bytes_and_walks_drop_8x():
    """The ISSUE 18 acceptance: for an 8-piece degree-1 sigmoid spline,
    serialized key bytes and DCF walks per gate eval both drop >= 8x on
    the vector arm, bit-exact across arms (bit-exactness is pinned by
    test_wide_spline_vector_bit_exact)."""
    gv = gates.SigmoidGate.create(12, payload="vector")
    gs = gates.SigmoidGate.create(12, payload="scalar")
    kv, _ = gv.gen(7, [3])
    ks, _ = gs.gen(7, [3])

    bytes_v = len(ser.serialize_gate_key(kv, _params(gv)))
    bytes_s = len(ser.serialize_gate_key(ks, _params(gs)))
    assert bytes_s >= 8 * bytes_v, (bytes_s, bytes_v)

    def count_walks(gate, key):
        walks = []
        orig = gate.dcf.batch_evaluate

        def spy(keys, points, **kw):
            walks.append(len(keys) * len(points))
            return orig(keys, points, **kw)

        gate.dcf.batch_evaluate = spy
        try:
            gate.batch_eval(key, [100], engine="host")
        finally:
            gate.dcf.batch_evaluate = orig
        assert len(walks) == 1, "gate eval must be ONE batched-DCF pass"
        return walks[0]

    walks_v = count_walks(gv, kv)
    walks_s = count_walks(gs, ks)
    assert walks_s >= 8 * walks_v, (walks_s, walks_v)
