"""FSS gate framework (ISSUE 9): the shared mod-N edge-case suite every
gate runs through once, plus framework plumbing (wire format, robust
wrapper, serving, bundle eval).

The edge matrix is parameterized ONCE over the family instead of
per-gate copies: wraparound input masks (r_in at 0, 1, 2^n-1, the sign
boundary), boundary inputs at interval endpoints, BOTH parties, and
exact-Python-int plaintext oracles. Each gate family compiles exactly one
XLA program (shapes are constant across masks/parties: the mask only
changes key *values*), and everything runs the walk-mode device path or
pure host arithmetic — ZERO pallas interpret configs, per the walkkernel
compile-budget lesson (the kernel path itself is covered by the MIC
walkkernel differentials in test_mic_gate.py; every gate flattens through
the same GatePlan onto the same program family, pinned by
test_dispatch_audit.py::test_gate_family_program_budget).
"""

import numpy as np
import pytest

from distributed_point_functions_tpu import gates
from distributed_point_functions_tpu.gates import framework
from distributed_point_functions_tpu.utils.errors import InvalidArgumentError

RNG = np.random.default_rng(0x6A7E)


# ---------------------------------------------------------------------------
# The family matrix: (name, log_group_size, make_gate, oracle, out_modulus)
# ---------------------------------------------------------------------------
# oracle(gate, x_real) -> the exact plaintext outputs (Python ints).


def _mic_oracle(gate, xr):
    return [1 if p <= xr <= q else 0 for p, q in gate.intervals]


def _drelu_oracle(gate, xr):
    return [1 if xr < gate.n // 2 else 0]


def _spline_oracle(gate, xr):
    n = gate.n
    y = 0
    for (p, q), cs in zip(gate.intervals, gate.coefficients):
        if p <= xr <= q:
            y = (y + sum(c * pow(xr, j, n) for j, c in enumerate(cs))) % n
    return [y]


def _bits_oracle(gate, xr):
    return [(xr >> j) & 1 for j in range(gate.log_group_size)]


LG = 6  # one group size across the family: shapes shared where K matches
N = 1 << LG

FAMILY = [
    # MIC: intervals hitting 0, the sign boundary, n-1, and a singleton.
    (
        "mic",
        lambda: gates.MultipleIntervalContainmentGate.create(
            LG, [(0, N // 4), (N // 4 + 1, N // 2), (7, 7)]
        ),
        _mic_oracle,
        None,  # mod n outputs
    ),
    ("drelu", lambda: gates.DReluGate.create(LG), _drelu_oracle, None),
    (
        "relu",
        lambda: gates.ReluGate.create(LG),
        lambda g, xr: [max(0, g.to_signed(xr)) % g.n],
        None,
    ),
    (
        "spline",
        lambda: gates.SplineGate.create(
            LG,
            [(0, 9), (10, N // 2 - 1), (N // 2, N - 1)],
            [[3, 1, 2], [7, 0, 1], [1, 5, 0]],
        ),
        _spline_oracle,
        None,
    ),
    (
        "bitdecomp",
        lambda: gates.BitDecompositionGate.create(LG),
        _bits_oracle,
        2,  # boolean output shares
    ),
]

#: wraparound masks: zero, minimal, maximal (full wrap), both sides of
#: the sign boundary.
EDGE_MASKS = (0, 1, N - 1, N // 2, N // 2 - 1)

#: boundary x_real values: domain ends, the sign boundary (the DReLU/
#: ReLU knot from both sides), and a spline/MIC knot. Exactly 5 so the
#: widest site count (MIC/spline: 5 x 6 sites = 30 points) stays within
#: one 32-point pad — every K=1 family (MIC + DReLU) and every K-matched
#: pair below shares ONE compiled XLA program per party (the compile-
#: budget discipline; the wraparound masks shift every knot's
#: neighborhood through the points anyway).
EDGE_INPUTS = (0, 9, N // 2 - 1, N // 2, N - 1)


def _reconstruct(gate, out0, out1, r_outs, out_mod):
    n = gate.n
    vals = []
    for j in range(gate.num_outputs):
        mod = out_mod or n
        vals.append((int(out0[j]) + int(out1[j]) - int(r_outs[j])) % mod)
    return vals


def _r_outs(gate, out_mod):
    hi = out_mod or gate.n
    return [int(r) for r in RNG.integers(0, hi, size=gate.num_outputs)]


@pytest.mark.parametrize("name,make,oracle,out_mod", FAMILY, ids=[f[0] for f in FAMILY])
def test_gate_mod_n_edges_both_parties(name, make, oracle, out_mod):
    """The shared edge suite: every wraparound mask x boundary input,
    both parties' batch_eval (ONE fused device pass per party per mask —
    constant shapes, one XLA compile per gate family) recombined against
    the exact-int plaintext oracle."""
    gate = make()
    n = gate.n
    for r_in in EDGE_MASKS:
        r_outs = _r_outs(gate, out_mod)
        k0, k1 = gate.gen(r_in, r_outs)
        xs = [(xr + r_in) % n for xr in EDGE_INPUTS]
        out0 = gate.batch_eval(k0, xs)
        out1 = gate.batch_eval(k1, xs)
        assert out0.shape == (len(xs), gate.num_outputs)
        for xi, xr in enumerate(EDGE_INPUTS):
            got = _reconstruct(gate, out0[xi], out1[xi], r_outs, out_mod)
            want = [int(v) % (out_mod or n) for v in oracle(gate, xr)]
            assert got == want, (name, r_in, xr, got, want)


@pytest.mark.parametrize(
    "name,make,oracle,out_mod", FAMILY[1:], ids=[f[0] for f in FAMILY[1:]]
)
def test_gate_eval_matches_batch_eval(name, make, oracle, out_mod):
    """The per-point host path (reference-parity DCF walks, pure Python
    ints) agrees with the fused batch path share for share — the
    framework's two eval templates cannot drift. One wraparound mask, a
    few inputs, both parties. (MIC's own suite pins this already.)"""
    gate = make()
    n = gate.n
    r_in = n - 1
    r_outs = _r_outs(gate, out_mod)
    k0, k1 = gate.gen(r_in, r_outs)
    xs = [0, 5, n - 1]
    for key in (k0, k1):
        batch = gate.batch_eval(key, xs)
        for xi, x in enumerate(xs):
            single = gate.eval(key, x)
            assert [int(v) for v in batch[xi]] == [int(v) for v in single], (
                name, x,
            )


def test_gate_host_engine_matches_device():
    """engine='host' (native AES-NI wide kernel) produces bit-identical
    shares to the device pass for a multi-component gate."""
    from distributed_point_functions_tpu import native

    if not native.available():
        pytest.skip("native engine unavailable")
    gate = gates.ReluGate.create(LG)
    k0, k1 = gate.gen(17, [5])
    xs = [0, 13, 31, 32, 63]
    for key in (k0, k1):
        dev = gate.batch_eval(key, xs)
        host = gate.batch_eval(key, xs, engine="host")
        assert (dev == host).all()


def test_gate_robust_wrapper_matches_direct():
    """supervisor.gate_batch_eval_robust == direct batch_eval for a
    framework gate (the generic form of the MIC wrapper: same GatePlan
    flatten, the DCF chain + host-oracle spot checks underneath)."""
    from distributed_point_functions_tpu.ops import supervisor

    gate = gates.BitDecompositionGate.create(LG)
    r_outs = [int(b) for b in RNG.integers(0, 2, size=LG)]
    k0, k1 = gate.gen(N - 1, r_outs)
    xs = [0, 9, 32, 63]
    for key in (k0, k1):
        direct = gate.batch_eval(key, xs)
        robust = supervisor.gate_batch_eval_robust(gate, key, xs)
        assert (direct == robust).all()
    # reconstruction sanity on the robust outputs
    r0 = supervisor.gate_batch_eval_robust(gate, k0, xs)
    r1 = supervisor.gate_batch_eval_robust(gate, k1, xs)
    for xi, x in enumerate(xs):
        xr = (x - (N - 1)) % N
        bits = gates.BitDecompositionGate.reconstruct_bits(r0[xi], r1[xi], r_outs)
        assert bits == [(xr >> j) & 1 for j in range(LG)]


def test_bundle_eval_one_key_per_input():
    """bundle_eval: per-activation keys and inputs in ONE fused pass
    agree with per-key batch_eval calls (the secure-ML layer shape)."""
    gate = gates.ReluGate.create(LG)
    n = gate.n
    b = 4
    keys0, keys1, r_ins, r_outs = [], [], [], []
    for _ in range(b):
        ri = int(RNG.integers(0, n))
        ro = int(RNG.integers(0, n))
        k0, k1 = gate.gen(ri, [ro])
        keys0.append(k0)
        keys1.append(k1)
        r_ins.append(ri)
        r_outs.append(ro)
    x_real = [int(v) for v in RNG.integers(-(n // 2), n // 2, size=b)]
    xs = [(gate.signed_lift(v) + ri) % n for v, ri in zip(x_real, r_ins)]
    o0 = framework.bundle_eval(gate, keys0, xs)
    o1 = framework.bundle_eval(gate, keys1, xs)
    for i in range(b):
        per_key = gate.batch_eval(keys0[i], [xs[i]])  # shares the K=4 family
        assert int(per_key[0, 0]) == int(o0[i, 0])
        got = gate.to_signed((int(o0[i, 0]) + int(o1[i, 0]) - r_outs[i]) % n)
        assert got == max(0, x_real[i]), (i, got)
    with pytest.raises(InvalidArgumentError):
        framework.bundle_eval(gate, keys0, xs[:-1])


def test_gate_key_wire_roundtrip_and_mic_superset():
    """serialize_gate_key/parse_gate_key round-trips a multi-component
    key, and a one-component GateKey serializes BYTE-IDENTICALLY to the
    MicKey message carrying the same material — the framework wire form
    is a superset of the reference's gate proto, not a fork."""
    from distributed_point_functions_tpu.protos import serialization as ser

    # The FAMILY spline config: its (K=9, 32-point) program family is
    # already compiled by the edge suite — zero new programs here.
    gate = FAMILY[3][1]()
    params = gate.dcf.dpf.validator.parameters
    k0, _ = gate.gen(3, [7])
    blob = ser.serialize_gate_key(k0, params)
    back = ser.parse_gate_key(blob)
    assert len(back.dcf_keys) == gate.num_components
    assert back.mask_shares == k0.mask_shares
    assert [dk.key for dk in back.dcf_keys] == [dk.key for dk in k0.dcf_keys]
    # parsed keys still evaluate
    assert (gate.batch_eval(back, [0, 9]) == gate.batch_eval(k0, [0, 9])).all()

    mic = gates.MultipleIntervalContainmentGate.create(5, [(1, 5)])
    mk, _ = mic.gen(2, [3])
    as_gate = gates.GateKey([mk.dcf_key], list(mk.output_mask_shares))
    mparams = mic.dcf.dpf.validator.parameters
    assert ser.serialize_gate_key(as_gate, mparams) == ser.serialize_mic_key(
        mk, mparams
    )
    with pytest.raises(InvalidArgumentError):
        ser.parse_gate_key(b"")


def test_gate_gen_deterministic_golden():
    """gen() with an injected CounterRng + pinned component DCF seeds is
    fully deterministic for a multi-component gate, and the serialized
    key fingerprint is pinned — the keygen-algebra guard the MIC golden
    test provides, extended to the framework's multi-key form."""
    import hashlib

    # Pinned on the scalar-flattened layout (one DCF key per shifted
    # coefficient); the vector codec has its own pins in
    # tests/test_gate_payload.py.
    gate = gates.ReluGate.create(8, payload="scalar")
    seeds = [
        (0x1111111122222222 + i, 0x3333333344444444 + i)
        for i in range(gate.num_components)
    ]

    def make():
        return gate.gen(
            77, [5], prng=gates.CounterRng(seed=b"relu-golden"),
            dcf_seeds=seeds,
        )

    k0_a, k1_a = make()
    k0_b, k1_b = make()
    assert k0_a == k0_b and k1_a == k1_b, "gen must be deterministic"
    from distributed_point_functions_tpu.protos import serialization as ser

    blob = ser.serialize_gate_key(k0_a, gate.dcf.dpf.validator.parameters)
    digest = hashlib.sha256(blob).hexdigest()
    # Pinned fingerprint: changes only if the keygen algebra (shifted-
    # coefficient expansion, share draw order) or the wire format changes
    # — both must be deliberate (regenerate after verifying the change).
    assert digest == (
        "502c5a0d36cc1a0ab4f562ebe5064730f81ea9883dfbc123c9f17d1b651082d5"
    ), digest
    # shares still reconstruct
    n = gate.n
    for xr in (-100, -1, 0, 1, 100):
        x = (gate.signed_lift(xr) + 77) % n
        e0 = gate.eval(k0_a, x)
        e1 = gate.eval(k1_a, x)
        assert gate.to_signed((e0[0] + e1[0] - 5) % n) == max(0, xr)


def test_gate_validation():
    with pytest.raises(InvalidArgumentError):
        gates.SplineGate.create(6, [], [])
    with pytest.raises(InvalidArgumentError):
        gates.SplineGate.create(6, [(5, 3)], [[1]])
    with pytest.raises(InvalidArgumentError):
        gates.SplineGate.create(6, [(0, 64)], [[1]])
    with pytest.raises(InvalidArgumentError):
        gates.SplineGate.create(6, [(0, 3)], [[1], [2]])
    with pytest.raises(InvalidArgumentError):  # ragged degrees
        gates.SplineGate.create(6, [(0, 3), (4, 7)], [[1, 2], [1]])
    with pytest.raises(InvalidArgumentError):  # DCF needs a real domain
        gates.DReluGate.create(1)
    with pytest.raises(InvalidArgumentError):
        gates.BitDecompositionGate.create(0)
    gate = gates.DReluGate.create(6)
    with pytest.raises(InvalidArgumentError):  # input mask out of group
        gate.gen(64, [0])
    with pytest.raises(InvalidArgumentError):  # output mask out of group
        gate.gen(0, [64])
    with pytest.raises(InvalidArgumentError):  # r_outs count
        gate.gen(0, [0, 1])
    bd = gates.BitDecompositionGate.create(4)
    with pytest.raises(InvalidArgumentError):  # boolean masks only
        bd.gen(0, [2, 0, 0, 0])
    k0, _ = gate.gen(0, [0])
    with pytest.raises(InvalidArgumentError):  # masked input out of group
        gate.batch_eval(k0, [64])
    with pytest.raises(InvalidArgumentError):  # seeds-per-component check
        gates.ReluGate.create(6, payload="scalar").gen(
            0, [0], dcf_seeds=[(1, 2)]
        )
    with pytest.raises(InvalidArgumentError):  # vector: ONE component key
        gates.ReluGate.create(6, payload="vector").gen(
            0, [0], dcf_seeds=[(1, 2), (3, 4)]
        )


def test_gate_serving_roundtrip():
    """The serving front door's "gate" op: requests merge into one fused
    pass, answers slice bit-exactly vs direct batch_eval, on the auto,
    host, and device arms (the MIC serving shape generalized)."""
    from distributed_point_functions_tpu import serving

    gate = gates.ReluGate.create(LG)
    n = gate.n
    k0, _ = gate.gen(11, [3])
    xs = [0, 5, 31, 32, 63, 40]
    want = gate.batch_eval(k0, xs)
    for engine in ("auto", "host", "device"):
        door = serving.FrontDoor(
            engine=engine, max_wait_ms=1e6, width_target=4, bucket=False
        )
        with door:
            futs = [
                door.submit(serving.Request.gate(gate, k0, [x])) for x in xs
            ]
            door.batcher.pump(force=True)
            got = [f.result(60) for f in futs]
        for xi in range(len(xs)):
            assert (np.asarray(got[xi][0]) == want[xi]).all(), (engine, xi)
    # queue keying: same gate+key merge, different keys do not
    k0b, _ = gate.gen(12, [4])
    ra = serving.Request.gate(gate, k0, [1])
    rb = serving.Request.gate(gate, k0, [2])
    rc = serving.Request.gate(gate, k0b, [3])
    assert ra.signature() == rb.signature()
    assert ra.signature() != rc.signature()
    # router model: the gate workload rides the DCF anchors with the
    # flattened (components x sites) axes
    w = serving.Workload(
        op="gate", num_keys=gate.num_components, points=len(xs) * gate.num_sites,
        value_bits=128, value_kind="u128",
    )
    costs = serving.CostModel().predict(w)
    assert ("host", None) in costs and ("device", "walk") in costs
